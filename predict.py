#!/usr/bin/env python
"""Reference-compatible inference entrypoint (SURVEY.md §2 component 2, §3.2).

Loads a checkpoint saved by train.py (model hyperparams + featurization
config + Normalizer state ride inside it, like the reference's checkpoint
``args``), runs the forward pass over a directory of CIFs, denormalizes,
and writes ``test_results.csv`` rows of ``id, target, prediction``.

Usage:
    python predict.py CKPT_DIR DATA_DIR [--device=...] [--out csv]
"""

from __future__ import annotations

import argparse
import csv
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("ckpt_dir", help="checkpoint directory written by train.py")
    p.add_argument("root_dir", help="dataset dir: {id}.cif + id_prop.csv")
    p.add_argument("--device", choices=["auto", "cpu", "tpu"], default="auto")
    p.add_argument("--best", action="store_true",
                   help="load the best checkpoint instead of the latest")
    p.add_argument("-b", "--batch-size", type=int, default=256)
    p.add_argument("--out", default="test_results.csv")
    p.add_argument("--synthetic", type=int, default=0,
                   help="predict on N synthetic structures (smoke runs)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        # env var alone is not honored under the axon TPU tunnel
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from cgnn_tpu.config import DataConfig, ModelConfig, build_model
    from cgnn_tpu.data.dataset import (
        load_cif_directory,
        load_synthetic,
        load_trajectory,
    )
    from cgnn_tpu.data.graph import batch_iterator
    from cgnn_tpu.train import CheckpointManager, Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import capacities_for
    from cgnn_tpu.train.step import make_predict_step

    mgr = CheckpointManager(args.ckpt_dir)
    tag = "best" if args.best else "latest"
    if not mgr.exists(tag):
        print(f"no '{tag}' checkpoint under {args.ckpt_dir}", file=sys.stderr)
        return 2

    meta = mgr.read_meta(tag)
    model_cfg = ModelConfig.from_meta(meta["model"])
    data_cfg = DataConfig.from_meta(meta["data"])
    task = meta.get("task", "regression")
    force_task = task == "force"
    model = build_model(model_cfg, data_cfg, task)

    if args.synthetic:
        if force_task:
            graphs = load_trajectory(args.synthetic, data_cfg.featurize_config())
        else:
            graphs = load_synthetic(args.synthetic, data_cfg.featurize_config())
    else:
        from cgnn_tpu.data.trajectory import is_trajectory_path

        if force_task and is_trajectory_path(args.root_dir):
            from cgnn_tpu.data.trajectory import load_trajectory_root

            graphs = [
                g
                for grp in load_trajectory_root(
                    args.root_dir, data_cfg.featurize_config())
                for g in grp
            ]
        else:
            graphs = load_cif_directory(
                args.root_dir, data_cfg.featurize_config(),
                keep_geometry=force_task,
            )
    # pack the way the model expects (dense slot layout rides in the
    # checkpoint meta; see data/graph.py pack_graphs)
    layout_m = model_cfg.dense_m or None
    node_cap, edge_cap = capacities_for(graphs, args.batch_size,
                                        dense_m=layout_m)

    # take the example from the iterator (respects capacities; a direct
    # pack_graphs of an oversize head batch would fail)
    example = next(batch_iterator(graphs, args.batch_size, node_cap, edge_cap,
                                  dense_m=layout_m, in_cap=0))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.identity(model_cfg.num_targets), rng=jax.random.key(0),
    )
    state = mgr.restore_for_inference(state, tag)

    if force_task:
        from cgnn_tpu.train.force_step import make_force_predict_step

        predict_step = jax.jit(make_force_predict_step())
    else:
        predict_step = jax.jit(make_predict_step())
    rows = []
    force_ids: list[str] = []
    force_arrays: list[np.ndarray] = []
    idx = 0
    # in_cap=0: inference has no backward; skip transpose-slot packing
    for batch in batch_iterator(graphs, args.batch_size, node_cap, edge_cap,
                                dense_m=layout_m, in_cap=0):
        out = jax.device_get(predict_step(state, batch))
        if force_task:
            energies, forces = (np.asarray(out[0]), np.asarray(out[1]))
            preds = energies[:, None]
            node_graph = np.asarray(batch.node_graph)
            node_mask = np.asarray(batch.node_mask) > 0
        else:
            preds = np.asarray(out)
        n_real = int(np.asarray(batch.graph_mask).sum())
        for k in range(n_real):
            g = graphs[idx]
            rows.append(
                [g.cif_id]
                + [f"{t:.6f}" for t in np.atleast_1d(g.target)]
                + [f"{p:.6f}" for p in preds[k]]
            )
            if force_task:
                force_ids.append(g.cif_id)
                force_arrays.append(forces[(node_graph == k) & node_mask])
            idx += 1
    with open(args.out, "w", newline="") as f:
        csv.writer(f).writerows(rows)
    print(f"wrote {len(rows)} predictions to {args.out}")
    if force_task:
        np.savez(
            args.out + ".forces.npz",
            ids=np.array(force_ids),
            **{f"forces_{i}": f for i, f in enumerate(force_arrays)},
        )
        print(f"wrote per-atom forces to {args.out}.forces.npz")
    mgr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
