#!/usr/bin/env python
"""Reference-compatible inference entrypoint (SURVEY.md §2 component 2, §3.2).

Loads a checkpoint saved by train.py (model hyperparams + featurization
config + Normalizer state ride inside it, like the reference's checkpoint
``args``), runs the forward pass over a directory of CIFs, denormalizes,
and writes ``test_results.csv`` rows of ``id, target, prediction``.

Usage:
    python predict.py CKPT_DIR DATA_DIR [--device=...] [--out csv]
"""

from __future__ import annotations

import argparse
import csv
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("ckpt_dir", help="checkpoint directory written by train.py")
    p.add_argument("root_dir", nargs="?", default=None,
                   help="dataset dir: {id}.cif + id_prop.csv (optional "
                        "with --cache / --synthetic)")
    p.add_argument("--device", choices=["auto", "cpu", "tpu"], default="auto")
    p.add_argument("--best", action="store_true",
                   help="load the best checkpoint instead of the latest")
    p.add_argument("-b", "--batch-size", type=int, default=256)
    p.add_argument("--out", default="test_results.csv")
    p.add_argument("--synthetic", type=int, default=0,
                   help="predict on N synthetic structures (smoke runs)")
    p.add_argument("--cache", type=str, default="",
                   help="featurized graph cache (data/cache.py) to predict "
                        "from instead of parsing CIFs")
    p.add_argument("--packing", choices=["snug", "ladder"], default="snug",
                   help="snug = fill-to-capacity batches (train.py's "
                        "default; >=0.97 padding efficiency)")
    p.add_argument("--buckets", type=int, default=0,
                   help="legacy per-size-class capacity derivation (use 3 "
                        "for MP-scale mixed sizes); default packs into the "
                        "serving shape ladder instead (--rungs)")
    p.add_argument("--rungs", type=int, default=2,
                   help="serving shape-ladder depth (serve.shapes): the "
                        "compile count is pinned at this many programs, "
                        "shared with an online server via the persistent "
                        "compile cache")
    p.add_argument("--pack-workers", type=int, default=None,
                   help="host pack pipeline threads (data/pipeline.py) "
                        "overlapping packing with device dispatch; 0 packs "
                        "serially on the main thread (default: 4 on an "
                        "accelerator backend, 0 on CPU — overlap threads "
                        "only steal cores from a CPU 'device')")
    p.add_argument("--wire", choices=["auto", "raw", "featurized"],
                   default="auto",
                   help="wire format of the ladder path (ISSUE 11): "
                        "'raw' stages (positions, lattice, species) and "
                        "the compiled program runs the periodic neighbor "
                        "search + featurization itself (~100x fewer "
                        "staged bytes, near-zero host work; structures "
                        "outside the raw rung caps ride the featurized "
                        "path); 'auto' engages on accelerator backends "
                        "— on CPU the host IS the device, so moving the "
                        "search 'on device' buys nothing")
    p.add_argument("--compact", choices=["auto", "on", "off"],
                   default="auto",
                   help="stage raw CompactBatch forms (~12x fewer host and "
                        "H2D bytes; data/compact.py) and expand on device; "
                        "'auto' engages on accelerator backends when the "
                        "dataset probes stageable, falling back to "
                        "full-fidelity staging otherwise")
    p.add_argument("--devices", default="auto", metavar="{auto,N}",
                   help="device-parallel dispatch (serve/devices.py): "
                        "distribute over this many local devices. 'auto' "
                        "= all devices on accelerator backends, one on "
                        "CPU (host 'devices' share the same cores); an "
                        "integer forces")
    p.add_argument("--engine", choices=["auto", "mesh", "threads"],
                   default="auto",
                   help="multi-device execution layer (ISSUE 10): 'mesh' "
                        "(the auto default with >1 device) stacks batches "
                        "N-at-a-time and ONE sharded jitted dispatch "
                        "covers all devices; 'threads' keeps the ISSUE-5 "
                        "per-device replica round-robin (the A/B leg)")
    p.add_argument("--compile-cache", type=str, default="/tmp/jax_cache",
                   metavar="DIR", help="persistent XLA compile cache "
                                       "('' disables)")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        # env var alone is not honored under the axon TPU tunnel
        jax.config.update("jax_platforms", "cpu")
    if args.compile_cache:
        try:
            jax.config.update("jax_compilation_cache_dir", args.compile_cache)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
        except Exception as e:  # noqa: BLE001 — cache is best-effort
            print(f"compilation cache unavailable: {e}", file=sys.stderr)
    from cgnn_tpu.train import CheckpointManager

    mgr = CheckpointManager(args.ckpt_dir)
    try:
        # single exit path: every return below (incl. early argument/data
        # errors) flows through the finally, so the manager's finalizer
        # thread and orbax handles are always closed
        return _run(args, mgr)
    finally:
        mgr.close()


def _probe_compact(args, graphs, data_cfg, layout_m, edge_dtype):
    """CompactSpec for this dataset, or None (full-fidelity staging):
    --compact off, a CPU backend under 'auto' (the device IS the host —
    nothing to save, re-expansion to pay), COO layout, or a dataset the
    probe rejects (continuous atom features / stale cache) all fall back
    loudly-but-gracefully."""
    import sys

    import jax

    if args.compact == "off" or layout_m is None:
        return None
    if args.compact == "auto" and jax.default_backend() == "cpu":
        return None
    from cgnn_tpu.data.compact import CompactSpec, CompactUnsupported

    try:
        return CompactSpec.build(
            graphs, data_cfg.featurize_config().gdf(), dense_m=layout_m,
            edge_dtype=edge_dtype,
        )
    except CompactUnsupported as e:
        print(f"compact staging unavailable ({e}); using full-fidelity "
              f"packing", file=sys.stderr)
        return None


def _run(args, mgr) -> int:
    import jax
    import numpy as np

    from cgnn_tpu.config import DataConfig, ModelConfig, build_model
    from cgnn_tpu.data.dataset import (
        load_cif_directory,
        load_synthetic,
        load_trajectory,
    )
    from cgnn_tpu.data.graph import batch_iterator
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.infer import run_fast_inference
    from cgnn_tpu.train.loop import capacities_for

    from cgnn_tpu.serve.devices import resolve_devices

    if args.pack_workers is None:
        args.pack_workers = 4 if jax.default_backend() != "cpu" else 0
    devices = resolve_devices(args.devices)
    tag = "best" if args.best else "latest"
    if not mgr.exists(tag):
        print(f"no '{tag}' checkpoint under {args.ckpt_dir}", file=sys.stderr)
        return 2

    meta = mgr.read_meta(tag)
    model_cfg = ModelConfig.from_meta(meta["model"])
    data_cfg = DataConfig.from_meta(meta["data"])
    task = meta.get("task", "regression")
    force_task = task == "force"
    # arbitrary inference inputs: widen training-set-derived bounds
    # (ModelConfig.for_arbitrary_inputs — the cgconv window contract)
    model_cfg = model_cfg.for_arbitrary_inputs()
    model = build_model(model_cfg, data_cfg, task)

    if args.cache and not os.path.exists(args.cache):
        print(f"--cache {args.cache} does not exist", file=sys.stderr)
        return 2
    # raw wire wants geometry kept at featurize time (the graphs convert
    # back to wire form via raw_from_graph); CPU 'auto' stays featurized
    # — the host IS the device (the compact/pack-workers rule)
    want_raw = (args.wire == "raw"
                or (args.wire == "auto" and jax.default_backend() != "cpu"))
    if args.cache:
        from cgnn_tpu.data.cache import load_graph_cache

        graphs = load_graph_cache(args.cache)
        print(f"loaded {len(graphs)} graphs from {args.cache}")
    elif args.synthetic:
        if force_task:
            graphs = load_trajectory(args.synthetic, data_cfg.featurize_config())
        else:
            graphs = load_synthetic(args.synthetic,
                                    data_cfg.featurize_config(),
                                    keep_geometry=want_raw)
    else:
        if not args.root_dir:
            print("DATA_DIR, --cache, or --synthetic is required",
                  file=sys.stderr)
            return 2
        from cgnn_tpu.data.trajectory import is_trajectory_path

        if force_task and is_trajectory_path(args.root_dir):
            from cgnn_tpu.data.trajectory import load_trajectory_root

            graphs = [
                g
                for grp in load_trajectory_root(
                    args.root_dir, data_cfg.featurize_config())
                for g in grp
            ]
        else:
            graphs = load_cif_directory(
                args.root_dir, data_cfg.featurize_config(),
                keep_geometry=force_task or want_raw,
            )
    # pack the way the model expects (dense slot layout rides in the
    # checkpoint meta; see data/graph.py pack_graphs)
    layout_m = model_cfg.dense_m or None
    snug = args.packing == "snug"
    edge_dtype = (jax.numpy.bfloat16 if model_cfg.dtype == "bfloat16"
                  else np.float32)
    node_cap, edge_cap = capacities_for(graphs, args.batch_size,
                                        dense_m=layout_m, snug=snug)

    # take the example from the iterator (respects capacities; a direct
    # pack_graphs of an oversize head batch would fail)
    example = next(batch_iterator(graphs, args.batch_size, node_cap, edge_cap,
                                  dense_m=layout_m, in_cap=0, snug=snug,
                                  edge_dtype=edge_dtype))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.identity(model_cfg.num_targets), rng=jax.random.key(0),
    )
    state = mgr.restore_for_inference(state, tag)

    rows = []
    force_ids: list[str] = []
    force_arrays: list[np.ndarray] = []
    if force_task:
        from cgnn_tpu.train.force_step import make_force_predict_step

        predict_step = jax.jit(make_force_predict_step())
        idx = 0
        # per-atom force extraction needs host-side node bookkeeping per
        # batch; force datasets are small, so this path keeps the simple
        # fetch-per-batch loop
        for batch in batch_iterator(graphs, args.batch_size, node_cap,
                                    edge_cap, dense_m=layout_m, in_cap=0,
                                    snug=snug, edge_dtype=edge_dtype):
            out = jax.tree_util.tree_map(  # true copies (GC-ALIAS)
                np.array, jax.device_get(predict_step(state, batch)))
            energies, forces = (np.asarray(out[0]), np.asarray(out[1]))
            node_graph = np.asarray(batch.node_graph)
            node_mask = np.asarray(batch.node_mask) > 0
            n_real = int(np.asarray(batch.graph_mask).sum())
            for k in range(n_real):
                g = graphs[idx]
                rows.append(
                    [g.cif_id]
                    + [f"{t:.6f}" for t in np.atleast_1d(g.target)]
                    + [f"{energies[k]:.6f}"]
                )
                force_ids.append(g.cif_id)
                force_arrays.append(forces[(node_graph == k) & node_mask])
                idx += 1
    elif args.buckets >= 1:
        # legacy path (any EXPLICIT --buckets, including 1): per-size-
        # class snug capacities derived from THIS dataset (fresh compiles
        # per run); the unset default (0) takes the shape ladder below
        preds, rate = run_fast_inference(
            state, graphs, args.batch_size, buckets=args.buckets,
            dense_m=layout_m, snug=snug, edge_dtype=edge_dtype,
            compact=_probe_compact(args, graphs, data_cfg, layout_m,
                                   edge_dtype),
            pack_workers=args.pack_workers, devices=devices,
            engine=args.engine,
        )
        print(f"inference throughput: {rate:.0f} structures/sec "
              f"(dispatch-pipelined, single fetch per bucket, "
              f"{len(devices)} device(s), {args.engine} engine)")
    else:
        # default: pack into the serving shape ladder (serve.shapes) —
        # compile count pinned at --rungs, and shared with an online
        # server through the persistent XLA compile cache. Compact-staged
        # by default: batches cross the link in raw form (~12x smaller)
        # and the ladder's packers run on --pack-workers threads.
        from cgnn_tpu.serve.shapes import plan_shape_set

        raw_spec = None
        if want_raw and layout_m is not None and not force_task:
            from cgnn_tpu.data.rawbatch import RawUnsupported, plan_raw_spec

            fcfg = data_cfg.featurize_config()
            try:
                raw_spec = plan_raw_spec(graphs, fcfg.gdf(), fcfg.radius,
                                         layout_m)
            except RawUnsupported as e:
                print(f"raw wire unavailable ({e}); featurized wire",
                      file=sys.stderr)
        shape_set = plan_shape_set(
            graphs, args.batch_size, rungs=args.rungs, dense_m=layout_m,
            edge_dtype=edge_dtype, num_targets=model_cfg.num_targets,
            compact=_probe_compact(args, graphs, data_cfg, layout_m,
                                   edge_dtype),
            raw=raw_spec,
        )
        if raw_spec is not None:
            # raw wire (ISSUE 11): structures stage as (positions,
            # lattice, species) and the compiled program builds the
            # graph; anything outside the raw rung caps rides the
            # featurized ladder, rows merged back in input order
            from cgnn_tpu.data.rawbatch import raw_from_graph
            from cgnn_tpu.train.infer import run_raw_inference

            raws = [raw_from_graph(g) for g in graphs]
            raw_idx = [i for i, r in enumerate(raws)
                       if r is not None and shape_set.admits_raw(r)]
            admitted = set(raw_idx)
            feat_idx = [i for i in range(len(graphs))
                        if i not in admitted]
            by_id = {id(raws[i]): graphs[i] for i in raw_idx}
            preds = np.zeros((len(graphs), model_cfg.num_targets),
                             np.float32)
            rate = 0.0
            if raw_idx:
                rp, rate = run_raw_inference(
                    state, [raws[i] for i in raw_idx], shape_set,
                    devices=devices, engine=args.engine,
                    raw_fallback=lambda rs: by_id[id(rs)],
                )
                preds[raw_idx] = rp
            if feat_idx:
                fpreds, _ = run_fast_inference(
                    state, [graphs[i] for i in feat_idx],
                    args.batch_size, shape_set=shape_set,
                    pack_workers=args.pack_workers, devices=devices,
                    engine=args.engine,
                )
                preds[feat_idx] = fpreds
            print(f"inference throughput: {rate:.0f} structures/sec "
                  f"(raw wire, in-program neighbor search, "
                  f"{len(raw_idx)}/{len(graphs)} structures raw-staged, "
                  f"{len(shape_set)}-rung ladder, {len(devices)} "
                  f"device(s), {args.engine} engine)")
        else:
            preds, rate = run_fast_inference(
                state, graphs, args.batch_size, shape_set=shape_set,
                pack_workers=args.pack_workers, devices=devices,
                engine=args.engine,
            )
            print(f"inference throughput: {rate:.0f} structures/sec "
                  f"(dispatch-pipelined, {len(shape_set)}-rung shape "
                  f"ladder, "
                  f"{'compact' if shape_set.compact else 'full'}-staged, "
                  f"{args.pack_workers} pack workers, "
                  f"{len(devices)} device(s), {args.engine} engine)")
    if not force_task:
        for g, p in zip(graphs, preds):
            rows.append(
                [g.cif_id]
                + [f"{t:.6f}" for t in np.atleast_1d(g.target)]
                + [f"{v:.6f}" for v in p]
            )
    with open(args.out, "w", newline="") as f:
        csv.writer(f).writerows(rows)
    print(f"wrote {len(rows)} predictions to {args.out}")
    if force_task:
        np.savez(
            args.out + ".forces.npz",
            ids=np.array(force_ids),
            **{f"forces_{i}": f for i, f in enumerate(force_arrays)},
        )
        print(f"wrote per-atom forces to {args.out}.forces.npz")
    return 0


if __name__ == "__main__":
    sys.exit(main())
