#!/usr/bin/env python
"""Continual-learning trainer entrypoint (cgnn_tpu.continual; ISSUE 18).

Tails a label journal (the fleet router's ``--journal`` JSONL, or a
single replica's), fine-tunes from the newest committed checkpoint on
the labeled replay set, and commits versioned CANDIDATE saves into the
shared checkpoint directory on a doubly-gated cadence (at least
``--min-new-labels`` new joins AND ``--min-interval`` seconds apart).
Nothing here promotes: the fleet's canary gate (``fleet.py --canary``)
decides which candidates ever serve, and gated reload watchers hold
every replica until it does.

Run it BESIDE the serving fleet, against the same checkpoint dir:

    python fleet.py CKPT --journal /tmp/labels.jsonl --canary &
    python continual.py CKPT --journal /tmp/labels.jsonl
"""

from __future__ import annotations

import argparse
import os
import sys
import threading


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("ckpt_dir",
                   help="shared checkpoint directory (must hold a "
                        "committed save with model meta — the "
                        "fine-tune starting point)")
    p.add_argument("--journal", required=True, metavar="PATH",
                   help="label journal JSONL to tail (the fleet "
                        "router's --journal file)")
    p.add_argument("--min-new-labels", type=int, default=64,
                   help="newly joined labels required per round")
    p.add_argument("--min-interval", type=float, default=5.0,
                   help="min seconds between committed candidates")
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--epochs-per-round", type=int, default=2,
                   help="fine-tune epochs over the replay set per round")
    p.add_argument("--lr", type=float, default=0.01,
                   help="fine-tune learning rate")
    p.add_argument("--max-replay", type=int, default=4096,
                   help="newest labeled records replayed per round")
    p.add_argument("--max-rounds", type=int, default=0,
                   help="exit after this many committed rounds "
                        "(0 = run until SIGTERM)")
    p.add_argument("--poll-interval", type=float, default=1.0,
                   help="journal poll cadence (seconds)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--device", choices=["auto", "cpu", "tpu"],
                   default="auto")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"

    from cgnn_tpu.continual import ContinualTrainer
    from cgnn_tpu.resilience.preempt import PreemptionHandler

    trainer = ContinualTrainer(
        args.ckpt_dir,
        journal_path=args.journal,
        min_new_labels=args.min_new_labels,
        min_interval_s=args.min_interval,
        batch_size=args.batch_size,
        epochs_per_round=args.epochs_per_round,
        lr=args.lr,
        max_replay=args.max_replay,
        max_rounds=args.max_rounds,
        seed=args.seed,
    )
    # SIGTERM/SIGINT -> finish the in-flight round, then exit clean
    # (the same preempt plumbing train.py uses)
    stop = threading.Event()
    handler = PreemptionHandler(
        log_fn=print,
        action="finishing the in-flight round, then exiting",
    )
    handler.add_callback(stop.set)
    handler.install()
    print(f"continual: tailing {args.journal} -> {args.ckpt_dir} "
          f"(>= {args.min_new_labels} labels AND >= "
          f"{args.min_interval:g}s between commits)")
    try:
        trainer.run(poll_interval_s=args.poll_interval, stop=stop)
    finally:
        handler.uninstall()
        trainer.close()
    s = trainer.stats()
    print(f"continual: exiting — {s['rounds']} rounds, "
          f"{len(s['commits'])} commits "
          f"({', '.join(s['commits']) or 'none'}), "
          f"{s['labels_trained']} labels trained, "
          f"{s['divergence_rollbacks']} divergence rollbacks")
    return 0


if __name__ == "__main__":
    sys.exit(main())
