#!/usr/bin/env python
"""Reference-compatible training entrypoint with ``--device={cpu,tpu}``.

Flag surface mirrors the reference lineage's ``main.py``/``train.py``
(SURVEY.md §2 component 1, §5 config system): same names where known
(``--task``, ``--n-conv``, ``--atom-fea-len``, ``--max-num-nbr``,
``--radius``, ``--resume``, ``--lr-milestones`` in epochs, ...), plus the
TPU-native additions: ``--device``, ``--data-parallel``, ``--bf16``,
``--aggregation``, and ``--synthetic N`` (offline stand-in for MP/OC20
downloads, SURVEY.md §7 phase 0).

Usage:
    python train.py DATA_DIR [flags]         # {id}.cif + id_prop.csv layout
    python train.py --synthetic 1000 [flags] # packaged synthetic dataset
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("root_dir", nargs="?", default=None,
                   help="dataset dir: {id}.cif files + id_prop.csv")
    p.add_argument("--synthetic", type=int, default=0, metavar="N",
                   help="train on N synthetic crystals instead of root_dir")
    p.add_argument("--synthetic-oc20", type=int, default=0, metavar="N",
                   help="train on N synthetic OC20-like catalyst slabs "
                        "(50-200+ atom graphs; BASELINE config #4)")
    p.add_argument("--task",
                   choices=["regression", "classification", "force"],
                   default="regression",
                   help="'force' trains the differentiable force field on "
                        "energy+force labels (BASELINE config #5)")
    p.add_argument("--device", choices=["auto", "cpu", "tpu"], default="auto",
                   help="accelerator (reference flag; 'auto' uses what jax finds)")
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--start-epoch", type=int, default=0)
    p.add_argument("-b", "--batch-size", type=int, default=256)
    p.add_argument("--lr", "--learning-rate", type=float, default=0.01, dest="lr")
    p.add_argument("--lr-milestones", type=int, nargs="*", default=[100],
                   help="epochs at which lr decays by 10x (torch MultiStepLR)")
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=0.0)
    p.add_argument("--optim", choices=["SGD", "Adam", "AdamW"], default="SGD")
    p.add_argument("--print-freq", type=int, default=10)
    p.add_argument("--resume", type=str, default="",
                   help="checkpoint dir to resume from, or 'auto': resume "
                        "from --ckpt-dir when a valid checkpoint exists, "
                        "start fresh otherwise (the requeue-after-"
                        "preemption mode; see README Fault tolerance)")
    p.add_argument("--train-ratio", type=float, default=0.8)
    p.add_argument("--val-ratio", type=float, default=0.1)
    # model hyperparams (reference names)
    p.add_argument("--atom-fea-len", type=int, default=64)
    p.add_argument("--h-fea-len", type=int, default=128)
    p.add_argument("--n-conv", type=int, default=3)
    p.add_argument("--n-h", type=int, default=1)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--num-classes", type=int, default=2)
    p.add_argument("--multi-task-head", action="store_true",
                   help="per-task MLP heads over the shared trunk for "
                        "multi-column targets (BASELINE config #3)")
    # featurization (reference names)
    p.add_argument("--max-num-nbr", type=int, default=12)
    p.add_argument("--radius", type=float, default=8.0)
    p.add_argument("--dmin", type=float, default=0.0)
    p.add_argument("--step", type=float, default=0.2)
    # input pipeline
    p.add_argument("--cache", type=str, default="",
                   help="graph cache (.npz): loaded if present, else written "
                        "after featurization (see cgnn_tpu.data.preprocess)")
    p.add_argument("-j", "--workers", type=int, default=0,
                   help="featurization worker processes (0 = all cores)")
    # runtime
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--ckpt-dir", type=str, default="checkpoints")
    # fault tolerance (cgnn_tpu.resilience; README "Fault tolerance")
    p.add_argument("--keep-ckpts", type=int, default=3, metavar="K",
                   help="checkpoint retention: newest K versioned saves "
                        "plus the best-pointer target (0 keeps all)")
    p.add_argument("--guard", choices=["off", "skip", "rollback"],
                   default="skip",
                   help="divergence guard. 'skip' (default): non-finite "
                        "updates are skipped ON DEVICE (jnp.where select; "
                        "trajectory bit-identical when nothing fires). "
                        "'rollback' additionally restores the last good "
                        "checkpoint with an LR cut when >= "
                        "--guard-max-skips steps of one epoch were "
                        "skipped. 'off' disables both")
    p.add_argument("--guard-max-skips", type=int, default=3, metavar="K",
                   help="skipped steps per epoch that count as divergence "
                        "(--guard rollback)")
    p.add_argument("--guard-lr-cut", type=float, default=0.5,
                   help="LR multiplier applied per rollback")
    p.add_argument("--guard-max-rollbacks", type=int, default=3,
                   help="rollback budget before the run fails for real")
    p.add_argument("--no-preempt-handler", action="store_true",
                   help="do not trap SIGTERM/SIGINT for graceful "
                        "checkpoint-and-resume (exit code 75)")
    # observability (SURVEY.md §5; cgnn_tpu.observe)
    p.add_argument("--telemetry", choices=["off", "epoch", "step"],
                   default="epoch",
                   help="telemetry level (cgnn_tpu.observe). 'epoch' "
                        "(default, zero per-step overhead): epoch records "
                        "in metrics.jsonl + host span trace (trace.json, "
                        "open in Perfetto) + run manifest (manifest.json) "
                        "+ padding/HBM/dispatch gauges. 'step' adds "
                        "per-step loss/grad-norm/NaN streaming from "
                        "INSIDE the epoch scan (async host callback; scan "
                        "trajectory unchanged) and in-graph grad-health "
                        "metrics. 'off' writes nothing")
    p.add_argument("--log-dir", type=str, default="",
                   help="metrics dir (metrics.jsonl + TensorBoard when "
                        "available); default: <ckpt-dir>/logs")
    p.add_argument("--live-metrics", type=float, default=0.0, metavar="SECS",
                   help="append a live registry snapshot (counters, "
                        "gauges, rolling-window quantiles) to "
                        "metrics_live.jsonl in the log dir every SECS "
                        "seconds, so a multi-hour run is scrapeable "
                        "MID-FLIGHT instead of only at exit (0 disables; "
                        "needs --telemetry != off). SIGUSR2 additionally "
                        "captures a bounded on-demand jax.profiler trace "
                        "into the log dir at any time")
    p.add_argument("--profile", type=int, default=0, metavar="N",
                   help="trace N post-compile steps of the first epoch with "
                        "jax.profiler (xprof/perfetto trace in the log dir)")
    p.add_argument("--debug-nans", action="store_true",
                   help="fail fast with a traceback at the first NaN")
    p.add_argument("--check-invariants", action="store_true",
                   help="validate every packed batch's GraphBatch "
                        "invariants (sorted centers, mask/slot consistency, "
                        "dense ownership, transpose completeness) host-side "
                        "before it reaches the step; ~free vs device time, "
                        "on by default in the test suite")
    p.add_argument("--node-cap", type=int, default=0, help="0 = auto")
    p.add_argument("--edge-cap", type=int, default=0, help="0 = auto")
    p.add_argument("--buckets", type=int, default=1,
                   help="size-class buckets for batching (>1 compiles one "
                        "step per bucket; better padding on mixed-size data)")
    p.add_argument("--packing", choices=["snug", "ladder"], default="snug",
                   help="'snug': fill-to-capacity packing with exact "
                        "batch-count-balanced capacities (~0.99 padding "
                        "efficiency); 'ladder': close batches at "
                        "--batch-size graphs with geometric-ladder "
                        "capacities (round-2 behavior)")
    p.add_argument("--pack-once", action="store_true",
                   help="pack training batches once and shuffle batch order "
                        "across epochs (large cached datasets: per-epoch "
                        "host packing would starve the device)")
    p.add_argument("--device-resident", action="store_true",
                   help="stage packed batches into HBM once and reuse the "
                        "device buffers every epoch (implies --pack-once; "
                        "dataset batches must fit in HBM)")
    p.add_argument("--scan-epochs", action="store_true",
                   help="fold each epoch into one lax.scan dispatch per "
                        "bucket shape (implies --device-resident; maximal "
                        "throughput on high-latency links). DEFAULT when "
                        "--device-resident is set: randomized chunk "
                        "scheduling (r3) brought multi-bucket convergence "
                        "within seed noise of the per-step loop "
                        "(scripts/scan_convergence.py)")
    p.add_argument("--no-scan-epochs", action="store_true",
                   help="keep the per-step loop under --device-resident")
    p.add_argument("--chunk-steps", type=int, default=2, metavar="C",
                   help="scan-driver mean chunk granularity (steps folded "
                        "per dispatch; lengths drawn from {C/2, C, 2C}). "
                        "Small on purpose: coarse chunks create long "
                        "same-shape runs that cost multi-bucket val "
                        "accuracy (~35%% MAE at MP-146k with C=8 vs C=2, "
                        "PERF.md 6e); dispatch count itself is ~free")
    # force task (BASELINE config #5)
    p.add_argument("--energy-weight", type=float, default=1.0,
                   help="w_e in L = w_e*MSE(E) + w_f*MSE(F)")
    p.add_argument("--force-weight", type=float, default=10.0,
                   help="w_f in L = w_e*MSE(E) + w_f*MSE(F)")
    p.add_argument("--md-atoms", type=int, default=8,
                   help="atoms per frame for --synthetic MD trajectories")
    p.add_argument("--md-jitter", type=float, default=0.08,
                   help="per-frame Cartesian jitter (Å) for synthetic MD")
    # TPU-native additions
    p.add_argument("--data-parallel", action="store_true",
                   help="shard batches over all visible devices (DP over ICI)")
    p.add_argument("--graph-shards", type=int, default=1, metavar="G",
                   help="shard every batch's edge axis over a G-way 'graph' "
                        "mesh axis (edge-sharded message passing — the "
                        "long-context analog for graphs too large for one "
                        "chip; composes with --data-parallel as a 2-D mesh)")
    p.add_argument("--bf16", action="store_true",
                   help="bfloat16 compute on the MXU (f32 params/stats)")
    p.add_argument("--aggregation", choices=["xla", "sort", "pallas"],
                   default=None, help="edge-aggregation backend (flat COO "
                                      "layout only)")
    p.add_argument("--fused-epilogue", choices=["off", "xla", "pallas"],
                   default="off",
                   help="fuse the BN1->gate->mask->sum chain into one "
                        "custom-VJP op (dense layout only). MEASURED "
                        "SLOWER than the default unfused path on v5e — "
                        "the custom-VJP boundary forfeits XLA's producer/"
                        "consumer fusion (PERF.md 6b); kept for "
                        "reproduction/experiments")
    p.add_argument("--cgconv-impl", choices=["off", "xla", "pallas"],
                   default="off",
                   help="WHOLE-conv fused kernel (ops/pallas_cgconv.py): "
                        "gather+fc_full+BN+gate+sum as one custom-VJP op, "
                        "v_j/z never in HBM; 'xla' = structured jnp twin, "
                        "'pallas' = blocked TPU kernels (dense layout "
                        "only; A/B via bench.py --ab cgconv, verdict in "
                        "PERF.md)")
    p.add_argument("--compact-staging", choices=["auto", "on", "off"],
                   default="auto",
                   help="stage batches in raw form (atom vocabulary index "
                        "+ scalar distance, ~12x fewer bytes) and rebuild "
                        "features inside the jitted scan body "
                        "(data/compact.py). Requires --scan-epochs + dense "
                        "layout, energy/classification tasks, single "
                        "device. auto = on when supported")
    p.add_argument("--compile-cache", type=str, default="/tmp/jax_cache",
                   metavar="DIR",
                   help="persistent XLA compilation cache directory "
                        "('' disables); scan-program compiles become disk "
                        "hits on re-runs")
    p.add_argument("--layout", choices=["auto", "dense", "coo"], default="auto",
                   help="edge batch layout: 'dense' (node-major slots, "
                        "scatter-free aggregation — ~2x faster on TPU; "
                        "composes with --graph-shards via node-strip "
                        "sharding) or 'coo' (flat edge list). Default: "
                        "dense unless --aggregation overrides the backend")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.chunk_steps < 1:
        print(f"--chunk-steps must be >= 1, got {args.chunk_steps}",
              file=sys.stderr)
        return 2
    if args.device == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if args.device == "cpu":
        # env var alone is not honored under the axon TPU tunnel
        jax.config.update("jax_platforms", "cpu")
    # multi-host (ISSUE 10): the CGNN_TPU_COORDINATOR/_NUM_PROCESSES/
    # _PROCESS_ID env triple turns this process into one controller of a
    # jax.distributed run — must init BEFORE anything touches a backend
    from cgnn_tpu.parallel import dist

    dist.initialize_from_env(log_fn=print)
    if args.compile_cache:
        try:
            jax.config.update("jax_compilation_cache_dir", args.compile_cache)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
        except Exception as e:  # noqa: BLE001 — cache is best-effort
            print(f"compilation cache unavailable: {e}", file=sys.stderr)
    import numpy as np

    from cgnn_tpu.config import DataConfig, ModelConfig, build_model
    from cgnn_tpu.data.dataset import (
        load_cif_directory,
        load_synthetic,
        load_synthetic_oc20,
        load_trajectory,
        train_val_test_split,
    )
    from cgnn_tpu.train import (
        CheckpointManager,
        Normalizer,
        create_train_state,
        make_optimizer,
    )
    from cgnn_tpu.train.loop import capacities_for, evaluate, fit

    if args.debug_nans:
        from cgnn_tpu.observe import enable_debug_nans

        enable_debug_nans()
    if args.check_invariants:
        from cgnn_tpu.data import invariants

        invariants.enable()

    devices = jax.devices()
    if args.device == "tpu" and devices[0].platform not in ("tpu", "axon"):
        print(f"--device=tpu requested but jax found {devices[0].platform}",
              file=sys.stderr)
        return 2
    print(f"devices: {devices}")

    from cgnn_tpu.observe import Telemetry
    from cgnn_tpu.resilience import PreemptionHandler, faultinject

    log_dir = args.log_dir or os.path.join(args.ckpt_dir, "logs")
    telemetry = Telemetry(args.telemetry, log_dir)

    # the live observability plane (ISSUE 6), training flavor: a
    # periodic metrics_live.jsonl appender over the export registry
    # (scrape a run mid-flight by file), and SIGUSR2 -> one bounded
    # on-demand device-profile capture — both host-side only, so the
    # trajectory stays bit-identical with the plane on or off
    live_writer = None
    if args.live_metrics > 0 and telemetry.enabled:
        from cgnn_tpu.observe import LiveMetricsWriter, MetricsRegistry

        # window matched to the telemetry retention (15 min), NOT the
        # serving 60 s default: training observes epoch_time_s once per
        # epoch, and a 60 s window would report an empty series on
        # nearly every tick of a run with multi-minute epochs
        live_writer = LiveMetricsWriter(
            MetricsRegistry(
                window_s=telemetry.series_window_s
            ).attach_telemetry(telemetry),
            os.path.join(log_dir, "metrics_live.jsonl"),
            interval_s=args.live_metrics,
        ).start()
    profiler = None
    if telemetry.enabled:
        from cgnn_tpu.observe import ProfileCapture, install_sigusr2

        profiler = ProfileCapture(log_dir, spans=telemetry.spans)
        install_sigusr2(profiler, log_fn=print)

    # SIGTERM/SIGINT -> checkpoint at the next epoch/chunk boundary and
    # exit resumable (75); a second signal kills immediately
    preempt = None
    if not args.no_preempt_handler:
        preempt = PreemptionHandler.installed(log_fn=print)
    fault_plan = faultinject.plan()
    if fault_plan is not None:
        print(f"FAULT INJECTION ACTIVE: {fault_plan.describe()}",
              file=sys.stderr)

    if (args.device_resident and not args.no_scan_epochs
            and not args.profile):
        # scan dispatch is the device-resident default since r3 (see
        # --scan-epochs help; composes with --graph-shards since r5);
        # --no-scan-epochs restores the per-step loop. Not auto-applied
        # for per-step profiling, which scan cannot provide — that keeps
        # the per-step loop rather than erroring on a flag the user
        # never passed.
        args.scan_epochs = True
    if args.scan_epochs and args.no_scan_epochs:
        print("--scan-epochs and --no-scan-epochs are contradictory",
              file=sys.stderr)
        return 2

    data_cfg = DataConfig(
        radius=args.radius, max_num_nbr=args.max_num_nbr,
        dmin=args.dmin, step=args.step,
    )
    t0 = time.perf_counter()
    # trajectory grouping for the force task's leak-aware split (frames of
    # one MD trajectory are time-autocorrelated; data/trajectory.py)
    traj_groups = None
    if args.cache and os.path.exists(args.cache):
        from cgnn_tpu.data.cache import load_graph_cache

        with telemetry.span("load_cache", path=args.cache):
            graphs = load_graph_cache(args.cache)
        print(f"loaded {len(graphs)} graphs from {args.cache} "
              f"in {time.perf_counter() - t0:.1f}s")
        if args.task == "force":
            from cgnn_tpu.data.trajectory import regroup_by_trajectory

            if any(g.forces is None or g.positions is None for g in graphs):
                print(f"cache {args.cache} lacks force labels/geometry; "
                      f"refeaturize from the trajectory files",
                      file=sys.stderr)
                return 2
            traj_groups = regroup_by_trajectory(graphs)
    elif args.synthetic_oc20:
        graphs = load_synthetic_oc20(
            args.synthetic_oc20, data_cfg.featurize_config(), seed=args.seed
        )
    elif args.synthetic:
        if args.task == "force":
            graphs = load_trajectory(
                args.synthetic, data_cfg.featurize_config(), seed=args.seed,
                num_atoms=args.md_atoms, jitter=args.md_jitter,
            )
            # one trajectory -> the same contiguous-block split policy as
            # on-disk trajectories (frames are per-frame i.i.d. jitters
            # here, but the split policy should not depend on that detail)
            traj_groups = [graphs]
        else:
            graphs = load_synthetic(args.synthetic, data_cfg.featurize_config(),
                                    seed=args.seed)
    elif args.task == "force":
        from cgnn_tpu.data.trajectory import (
            is_trajectory_path,
            load_trajectory_root,
        )

        if not args.root_dir or not is_trajectory_path(args.root_dir):
            print("--task force needs --synthetic N or an on-disk trajectory "
                  "dataset: a .npz file or a directory of them, one file per "
                  "trajectory (key conventions: cgnn_tpu/data/trajectory.py; "
                  "MD17/sGDML R/z/E/F files load unchanged)",
                  file=sys.stderr)
            return 2
        traj_groups = load_trajectory_root(
            args.root_dir, data_cfg.featurize_config()
        )
        graphs = [g for grp in traj_groups for g in grp]
        print(f"loaded {len(traj_groups)} trajectories "
              f"({len(graphs)} frames) from {args.root_dir}")
    elif args.root_dir:
        if args.workers != 1:
            from cgnn_tpu.data.cache import featurize_directory_parallel

            with telemetry.span("featurize", root=args.root_dir):
                graphs, failures = featurize_directory_parallel(
                    args.root_dir, data_cfg.featurize_config(),
                    workers=args.workers or None,
                )
            for cif_id, err in failures[:10]:
                print(f"skipped {cif_id}: {err}", file=sys.stderr)
        else:
            with telemetry.span("featurize", root=args.root_dir):
                graphs = load_cif_directory(
                    args.root_dir, data_cfg.featurize_config())
    else:
        print("either DATA_DIR or --synthetic N is required", file=sys.stderr)
        return 2
    if not (args.cache and os.path.exists(args.cache)):
        print(f"featurized {len(graphs)} structures "
              f"in {time.perf_counter() - t0:.1f}s")
        if args.cache:
            from cgnn_tpu.data.cache import save_graph_cache

            save_graph_cache(graphs, args.cache)
            print(f"wrote cache {args.cache}")

    if traj_groups is not None:
        from cgnn_tpu.data.trajectory import split_trajectory_groups

        train_g, val_g, test_g = split_trajectory_groups(
            traj_groups, args.train_ratio, args.val_ratio, seed=args.seed
        )
        print(f"trajectory-aware split: {len(train_g)}/{len(val_g)}/"
              f"{len(test_g)} frames over {len(traj_groups)} trajectories")
    else:
        train_g, val_g, test_g = train_val_test_split(
            graphs, args.train_ratio, args.val_ratio, seed=args.seed
        )
    if dist.active():
        # multi-host DP: per-host data slicing (the loader side of
        # ISSUE 10). Every process runs the identical split above
        # (same seed, same data), then takes its disjoint strided
        # shard; the global batch is the union across hosts and the
        # cross-host grad allreduce lives in the shard_map step.
        if not args.data_parallel:
            print("multi-host run (jax.distributed) requires "
                  "--data-parallel: without the global-mesh step there "
                  "is no cross-host gradient reduction and the hosts "
                  "would silently train divergent models",
                  file=sys.stderr)
            return 2
        if args.scan_epochs or args.device_resident or args.pack_once:
            print("multi-host DP runs the per-step loop; drop "
                  "--scan-epochs/--device-resident/--pack-once",
                  file=sys.stderr)
            return 2
        train_g = dist.host_shard(train_g)
        val_g = dist.host_shard(val_g)
        print(f"multi-host: process {dist.process_index()}/"
              f"{dist.process_count()} trains {len(train_g)} / "
              f"validates {len(val_g)} structures (strided host shard); "
              f"test eval runs the full split on every host")
    num_targets = int(train_g[0].target.shape[0])
    classification = args.task == "classification"
    force_task = args.task == "force"

    # dense slot layout: scatter-free aggregation (see data/graph.py); the
    # flat COO layout remains for edge-sharded meshes and explicit
    # aggregation-backend experiments. Default for ALL tasks incl. force
    # since r4: gather_transpose moved to linear_call so the second-order
    # force differentiation composes (ops/segment.py), parity is pinned to
    # training-step gradients (tests/test_forces.py), and the bench
    # measures dense at 1.59x COO on the force workload (BENCH r4).
    dense_ok = args.aggregation is None
    if args.layout == "dense" and not dense_ok:
        print("--layout dense is incompatible with --aggregation",
              file=sys.stderr)
        return 2
    use_dense = dense_ok if args.layout == "auto" else args.layout == "dense"
    dense_m = args.max_num_nbr if use_dense else 0
    if args.fused_epilogue != "off" and (
        not use_dense or force_task or args.graph_shards > 1
    ):
        print("--fused-epilogue requires the dense layout with BatchNorm "
              "and no graph sharding (not --layout coo / --task force / "
              "--graph-shards)", file=sys.stderr)
        return 2
    if args.cgconv_impl != "off" and (
        not use_dense or force_task or args.graph_shards > 1
        or args.fused_epilogue != "off"
    ):
        print("--cgconv-impl (the whole-conv fused kernel) requires the "
              "dense layout with BatchNorm, no graph sharding, and no "
              "--fused-epilogue (it subsumes it)", file=sys.stderr)
        return 2
    cgconv_window = 0
    if args.cgconv_impl != "off":
        # the in-kernel gather's neighbor-window bound comes from the
        # REAL dataset (an undersized bound would silently zero
        # out-of-window neighbors — ops/pallas_cgconv.py contract)
        from cgnn_tpu.ops.pallas_cgconv import window_width

        cgconv_window = window_width(max(g.num_nodes for g in graphs))

    model_cfg = ModelConfig(
        atom_fea_len=args.atom_fea_len, n_conv=args.n_conv,
        h_fea_len=args.h_fea_len, n_h=args.n_h, num_targets=num_targets,
        classification=classification, num_classes=args.num_classes,
        dropout=args.dropout, dtype="bfloat16" if args.bf16 else "float32",
        aggregation=args.aggregation, multi_task_head=args.multi_task_head,
        dense_m=dense_m,
        fused_epilogue="" if args.fused_epilogue == "off"
        else args.fused_epilogue,
        cgconv_impl="" if args.cgconv_impl == "off" else args.cgconv_impl,
        cgconv_window=cgconv_window,
    )
    graph_shards = max(1, args.graph_shards)
    if graph_shards > 1:
        if force_task:
            print("--graph-shards is not supported for --task force",
                  file=sys.stderr)
            return 2
        if len(devices) < graph_shards:
            print(f"--graph-shards {graph_shards} requested but only "
                  f"{len(devices)} device(s) visible", file=sys.stderr)
            return 2
        if args.data_parallel and len(devices) % graph_shards:
            stranded = len(devices) % graph_shards
            print(f"warning: {len(devices)} devices not divisible by "
                  f"--graph-shards {graph_shards}; {stranded} device(s) "
                  f"idle", file=sys.stderr)
    model = build_model(model_cfg, data_cfg, args.task)

    if classification:
        normalizer = Normalizer.identity(num_targets)
    else:
        normalizer = Normalizer.fit(
            np.stack([g.target for g in train_g]),
            np.stack([
                g.target_mask if g.target_mask is not None
                else np.ones_like(g.target) for g in train_g
            ]),
        )

    layout_m = dense_m or None
    snug = args.packing == "snug"
    # bf16 compute reads edge features (the largest staged tensor) straight
    # from bf16 storage: halves their HBM footprint and per-step bytes
    edge_dtype = jax.numpy.bfloat16 if args.bf16 else np.float32
    node_cap, edge_cap = capacities_for(train_g, args.batch_size,
                                        dense_m=layout_m, snug=snug)
    node_cap = args.node_cap or node_cap
    if layout_m and args.edge_cap:
        print(f"warning: --edge-cap {args.edge_cap} ignored by the dense "
              f"layout (edge capacity is node_cap * max_num_nbr = "
              f"{node_cap * dense_m}); use --layout coo to honor it",
              file=sys.stderr)
    edge_cap = (node_cap * dense_m) if layout_m else (args.edge_cap or edge_cap)
    # real batch count (capacity-filled batches split early, so
    # len//batch_size undercounts and milestones would decay too early)
    from cgnn_tpu.data.graph import batch_iterator, count_batches

    steps_per_epoch = max(1, count_batches(
        train_g, args.batch_size, node_cap, edge_cap, snug=snug
    ))
    tx = make_optimizer(
        optim=args.optim.lower(), lr=args.lr, momentum=args.momentum,
        weight_decay=args.weight_decay,
        lr_milestones=[m * steps_per_epoch for m in args.lr_milestones],
    )

    # the iterator respects capacities (direct pack_graphs of an oversize
    # head batch would die with an opaque broadcast error)
    example = next(batch_iterator(train_g, args.batch_size, node_cap, edge_cap,
                                  dense_m=layout_m, snug=snug,
                                  edge_dtype=edge_dtype))
    with telemetry.span("state_init"):
        state = create_train_state(model, example, tx, normalizer,
                                   rng=jax.random.key(args.seed))

    ckpt = CheckpointManager(args.ckpt_dir, telemetry=telemetry,
                             keep=args.keep_ckpts)
    start_epoch = args.start_epoch
    resume_meta = None
    if args.resume:
        from cgnn_tpu.train.checkpoint import CheckpointRestoreError

        auto = args.resume == "auto"
        resume_dir = args.ckpt_dir if auto else args.resume
        resume_mgr = ckpt if os.path.abspath(resume_dir) == ckpt.directory \
            else CheckpointManager(resume_dir)
        if auto and not resume_mgr.exists():
            print(f"--resume auto: no checkpoint under {resume_dir}; "
                  f"starting fresh")
        else:
            try:
                state, meta = resume_mgr.restore(state)
            except CheckpointRestoreError as e:
                print(f"cannot resume from {resume_dir}: {e}",
                      file=sys.stderr)
                if auto:
                    # checkpoints exist but none restored: refusing to
                    # "start fresh" on top of them — that would retrain
                    # from epoch 0 over (and eventually rotate out) a
                    # run's remains; a human should inspect or remove
                    # the directory
                    print("--resume auto: checkpoint directory is "
                          "non-empty but unrestorable; inspect or remove "
                          f"{resume_dir} to start fresh", file=sys.stderr)
                return 2
            if "epoch" not in meta:
                # refusing to guess: silently computing start_epoch = 0
                # would retrain over (and eventually rotate out) the
                # checkpoint the user asked to resume from
                print(f"checkpoint meta under {resume_dir} lacks 'epoch' "
                      f"({meta!r}) — cannot determine the resume point; "
                      f"aborting instead of restarting at epoch 0",
                      file=sys.stderr)
                return 2
            start_epoch = int(meta["epoch"]) + 1
            resume_meta = meta
            print(f"resumed from {resume_dir} at epoch {start_epoch}")

    meta_base = {"model": model_cfg.to_meta(), "data": data_cfg.to_meta(),
                 "task": args.task}
    sel_key = "force_mae" if force_task else (
        "correct" if classification else "mae")

    guard_enabled = args.guard != "off"
    monitor = None
    if args.guard == "rollback":
        from cgnn_tpu.resilience import DivergenceMonitor

        monitor = DivergenceMonitor(
            ckpt, max_skips=args.guard_max_skips, lr_cut=args.guard_lr_cut,
            max_rollbacks=args.guard_max_rollbacks, log_fn=print,
        )
        if resume_meta is not None:
            # resumed: reapply any persisted LR cut / rollback budget —
            # otherwise every preemption requeue restarts at the
            # full-strength LR that caused the divergence with a fresh
            # retry budget (an unbounded diverge->rollback->preempt loop)
            state = monitor.resume_from_meta(state, resume_meta)
    resilience_kw = {
        "guard": guard_enabled, "monitor": monitor, "preempt": preempt,
    }

    _skip_noted = [False]

    def save_cb(s, e, m, b):
        if not dist.is_coordinator():
            # multi-host: checkpoint commits are PROCESS-0-ONLY — two
            # hosts writing the same versioned-save sequence into one
            # shared directory would race the commit protocol. The
            # state is replicated (post-pmean), so process 0's save IS
            # everyone's save; non-zero hosts pick it up via restore /
            # the coordinated hot-reload path (parallel/dist.py).
            if not _skip_noted[0]:
                _skip_noted[0] = True
                print(f"multi-host: process {dist.process_index()} "
                      f"skips checkpoint commits (process 0 is the "
                      f"single committer)")
            return
        extra = monitor.meta() if monitor is not None else {}
        ckpt.save(
            s, dict(meta_base, epoch=e, best_mae=m.get(sel_key, -1.0),
                    **extra),
            is_best=b,
        )

    # run manifest: config + device/mesh inventory + git SHA, written once
    telemetry.write_manifest(
        vars(args),
        task=args.task,
        mesh_shape={
            "data": (len(devices) // graph_shards
                     if args.data_parallel else 1),
            "graph": graph_shards,
        },
    )
    log_epoch_metrics = telemetry.write_epoch

    step_overrides = {}
    eval_step_fn = None
    if force_task:
        from cgnn_tpu.train.force_step import (
            make_force_eval_step,
            make_force_train_step,
        )

        eval_step_fn = make_force_eval_step(args.energy_weight, args.force_weight)
        step_overrides = {"best_metric": "force_mae"}

    if graph_shards > 1 or (args.data_parallel and len(devices) > 1):
        if args.compact_staging == "on":
            print("--compact-staging on is not yet supported with "
                  "--data-parallel/--graph-shards (full staging only); "
                  "drop the flag or use auto", file=sys.stderr)
            return 2
        from cgnn_tpu.parallel import fit_data_parallel
        from cgnn_tpu.parallel.mesh import make_2d_mesh

        mesh = None
        fit_state = state
        if graph_shards > 1 and args.profile:
            print("--profile is not supported with --graph-shards "
                  "(edge-sharded meshes)", file=sys.stderr)
            return 2
        if graph_shards > 1 and args.buckets > 1 and not use_dense:
            print("--buckets with --graph-shards requires the dense layout "
                  "(drop --layout coo)", file=sys.stderr)
            return 2
        if graph_shards > 1:
            # edge-sharded model: same params, psum over 'graph' per conv;
            # the plain `state` keeps the single-device apply_fn for the
            # final test evaluation and checkpointing
            sharded_model = build_model(
                model_cfg, data_cfg, args.task, edge_axis_name="graph"
            )
            fit_state = state.replace(apply_fn=sharded_model.apply)
            mesh = make_2d_mesh(
                graph_shards,
                data_shards=(len(devices) // graph_shards
                             if args.data_parallel else 1),
            )
        if force_task:
            step_overrides |= {
                "train_step_fn": make_force_train_step(
                    args.energy_weight, args.force_weight, axis_name="data",
                    grad_health=telemetry.step_level,
                ),
                "eval_step_fn": make_force_eval_step(
                    args.energy_weight, args.force_weight, axis_name="data"
                ),
            }
        fit_state, result = fit_data_parallel(
            fit_state, train_g, val_g, epochs=args.epochs,
            batch_size=args.batch_size,
            node_cap=node_cap, edge_cap=edge_cap, classification=classification,
            seed=args.seed, print_freq=args.print_freq,
            on_epoch_end=save_cb, start_epoch=start_epoch,
            on_epoch_metrics=log_epoch_metrics, mesh=mesh,
            pack_once=args.pack_once, device_resident=args.device_resident,
            dense_m=layout_m, buckets=args.buckets, snug=snug,
            scan_epochs=args.scan_epochs, profile_steps=args.profile,
            profile_dir=log_dir, edge_dtype=edge_dtype,
            chunk_steps=args.chunk_steps, telemetry=telemetry,
            **resilience_kw, **step_overrides,
        )
        state = fit_state.replace(apply_fn=state.apply_fn)
        if dist.active():
            # post-fit the state is replicated over the GLOBAL mesh;
            # pull host-local copies so the single-device test eval and
            # any further checkpointing run without the mesh
            state = dist.localize(state)
    else:
        if force_task:
            step_overrides |= {
                "train_step_fn": make_force_train_step(
                    args.energy_weight, args.force_weight,
                    grad_health=telemetry.step_level,
                ),
                "eval_step_fn": eval_step_fn,
            }
        compact_ok = (args.scan_epochs and layout_m is not None
                      and not force_task)
        if args.compact_staging == "on" and not compact_ok:
            print("--compact-staging on requires --scan-epochs, the dense "
                  "layout, and a non-force task", file=sys.stderr)
            return 2
        if args.compact_staging != "off" and compact_ok:
            from cgnn_tpu.data.compact import CompactSpec, CompactUnsupported

            try:
                step_overrides["compact"] = CompactSpec.build(
                    train_g + val_g + test_g,
                    data_cfg.featurize_config().gdf(),
                    dense_m=layout_m, edge_dtype=edge_dtype,
                )
                print("compact staging: on (raw atoms+distances staged; "
                      "features rebuilt on device)")
            except CompactUnsupported as e:
                if args.compact_staging == "on":
                    raise
                print(f"compact staging unavailable ({e}); using full "
                      f"staging", file=sys.stderr)
        state, result = fit(
            state, train_g, val_g, epochs=args.epochs, batch_size=args.batch_size,
            node_cap=node_cap, edge_cap=edge_cap, classification=classification,
            seed=args.seed, print_freq=args.print_freq,
            on_epoch_end=save_cb, start_epoch=start_epoch,
            buckets=args.buckets, on_epoch_metrics=log_epoch_metrics,
            profile_steps=args.profile, profile_dir=log_dir,
            pack_once=args.pack_once, device_resident=args.device_resident,
            dense_m=layout_m, scan_epochs=args.scan_epochs, snug=snug,
            edge_dtype=edge_dtype, chunk_steps=args.chunk_steps,
            telemetry=telemetry,
            **resilience_kw, **step_overrides,
        )

    if result.get("preempted"):
        # the loop already saved a resumable checkpoint at the boundary;
        # surface any failed save LOUDLY (a silent one would strand the
        # requeue), flush telemetry, and exit with the resumable code
        from cgnn_tpu.resilience.preempt import resumable_exit

        ckpt.close()
        if live_writer is not None:
            live_writer.stop()
        if profiler is not None:
            # exiting mid-capture segfaults in the profiler backend
            profiler.wait_idle()
        telemetry.sample_hbm("preempted")
        telemetry.close()
        return resumable_exit(print)

    with telemetry.span("test_eval"):
        test_m = evaluate(state, test_g, args.batch_size, node_cap, edge_cap,
                          classification, eval_step_fn=eval_step_fn,
                          dense_m=layout_m, snug=snug, edge_dtype=edge_dtype)
    print(f"** test {sel_key}: {test_m.get(sel_key, float('nan')):.4f} "
          f"(best val: {result['best']:.4f})")
    if force_task:
        print(f"** test energy mae: {test_m.get('mae', float('nan')):.4f}")
    for t in range(num_targets):
        if f"mae_task{t}" in test_m:
            print(f"** test mae task {t}: {test_m[f'mae_task{t}']:.4f}")

    if classification:
        # full classification metric set (reference surfaces AUC/F1 too);
        # needs raw per-structure scores, so run a predict pass on the host
        from cgnn_tpu.data.graph import batch_iterator as _biter
        from cgnn_tpu.train.metrics import class_eval
        from cgnn_tpu.train.step import make_predict_step

        pstep = jax.jit(make_predict_step())
        scores, labels = [], []
        idx = 0
        # in_cap=0: forward-only pass needs no transpose slots, and packing
        # them would both cost host time and compile a new In shape
        for b in _biter(test_g, args.batch_size, node_cap, edge_cap,
                        dense_m=layout_m, in_cap=0, snug=snug,
                        edge_dtype=edge_dtype):
            out = np.array(jax.device_get(pstep(state, b)))  # copy: GC-ALIAS
            n_real = int(np.asarray(b.graph_mask).sum())
            scores.append(out[:n_real])
            labels.extend(
                int(test_g[idx + k].target[0]) for k in range(n_real)
            )
            idx += n_real
        cls = class_eval(np.concatenate(scores), np.array(labels))
        test_m = dict(test_m, **cls)
        print("** test " + "  ".join(
            f"{k} {v:.4f}" for k, v in cls.items() if v == v))

    telemetry.write_scalars(args.epochs, test_m, prefix="test")
    telemetry.sample_hbm("end_of_run")
    if live_writer is not None:
        live_writer.stop()
    if profiler is not None:
        # exiting mid-capture segfaults in the profiler backend
        profiler.wait_idle()
    telemetry.close()  # flushes gauges/counters; exports trace.json
    ckpt.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
