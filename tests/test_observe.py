"""Telemetry subsystem tests (cgnn_tpu.observe).

The load-bearing guarantees, pinned:

- metrics.jsonl schema round-trips (epoch records, step records, events);
- the span trace is valid Chrome-trace JSON with consistent nesting;
- the run manifest carries config + device inventory;
- the in-scan step stream delivers per-step records from INSIDE the
  whole-epoch ``lax.scan`` whose weighted sum reconciles exactly with the
  epoch aggregates, and the scan trajectory (final params, per-epoch
  losses) is BIT-IDENTICAL with step telemetry on vs off;
- telemetry off is a true no-op: no callback is staged into the compiled
  HLO (off/epoch levels), while step level stages exactly the tap.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cgnn_tpu.data.dataset import (
    FeaturizeConfig,
    load_synthetic,
    train_val_test_split,
)
from cgnn_tpu.data.graph import PaddingStats, pack_graphs
from cgnn_tpu.models import CrystalGraphConvNet
from cgnn_tpu.observe import (
    MetricsLogger,
    SpanTracer,
    StepStream,
    Telemetry,
    hbm_gauges,
    padding_gauges,
    read_jsonl,
    write_manifest,
)
from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
from cgnn_tpu.train.loop import capacities_for, fit
from cgnn_tpu.train.step import make_train_step


@pytest.fixture(scope="module")
def tiny_dataset():
    graphs = load_synthetic(60, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=3, max_atoms=6)
    return train_val_test_split(graphs, 0.7, 0.15, seed=0)


class TestMetricsLogger:
    def test_schema_round_trip(self, tmp_path):
        log = MetricsLogger(str(tmp_path), use_clu=False)
        log.write(0, {"loss": 1.5, "mae": 0.25, "nan": float("nan")},
                  prefix="train")
        log.event("step", {"phase": "train", "step": 3, "loss": 0.5})
        log.event("hbm", {"device": "d0", "bytes_in_use": 123})
        log.close()
        recs = read_jsonl(str(tmp_path / "metrics.jsonl"))
        assert len(recs) == 3
        epoch = recs[0]
        assert epoch["step"] == 0 and epoch["train/loss"] == 1.5
        assert "train/nan" not in epoch  # NaNs dropped, as before
        assert recs[1]["event"] == "step" and recs[1]["loss"] == 0.5
        assert recs[2]["event"] == "hbm" and recs[2]["bytes_in_use"] == 123
        assert all("time" in r for r in recs)

    def test_append_and_thread_safety_smoke(self, tmp_path):
        import threading

        log = MetricsLogger(str(tmp_path), use_clu=False)

        def writer(i):
            for j in range(50):
                log.event("step", {"phase": "t", "step": i * 50 + j})

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        log.close()
        recs = read_jsonl(str(tmp_path / "metrics.jsonl"))
        assert len(recs) == 200  # no torn/interleaved lines


class TestSpans:
    def test_trace_json_valid_and_nested(self, tmp_path):
        tracer = SpanTracer()
        with tracer.span("outer", kind="test"):
            with tracer.span("inner", epoch=0):
                pass
            with tracer.span("inner", epoch=1):
                pass
        path = tracer.export(str(tmp_path / "trace.json"))
        doc = json.load(open(path))
        events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert len(events) == 3
        by_name = {}
        for e in events:
            by_name.setdefault(e["name"], []).append(e)
            # chrome trace required fields
            assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
        outer, = by_name["outer"]
        for inner in by_name["inner"]:
            # inner spans nest inside outer's interval, one level deeper
            assert inner["ts"] >= outer["ts"]
            assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"]
            assert inner["args"]["depth"] == outer["args"]["depth"] + 1
        assert by_name["inner"][0]["args"]["epoch"] == 0


class TestManifest:
    def test_write_manifest(self, tmp_path):
        path = write_manifest(str(tmp_path), {"batch_size": 32, "lr": 0.01},
                              task="regression",
                              mesh_shape={"data": 1, "graph": 1})
        m = json.load(open(path))
        assert m["config"]["batch_size"] == 32
        assert m["device_count"] == len(jax.devices())
        assert m["devices"][0]["platform"] == "cpu"
        assert m["task"] == "regression"
        assert m["mesh_shape"] == {"data": 1, "graph": 1}
        # this repo is a git checkout, so the SHA must be present here
        assert len(m.get("git_sha", "")) == 40


class TestGauges:
    def test_padding_gauges_per_bucket(self, tiny_dataset):
        from cgnn_tpu.data.graph import bucketed_batch_iterator

        train_g, _, _ = tiny_dataset
        stats = PaddingStats()
        batches = list(stats.wrap(bucketed_batch_iterator(train_g, 8, 2)))
        assert len(batches) >= 2
        gauges = padding_gauges(stats)
        buckets = [g for g in gauges if g["bucket"] != "overall"]
        overall = [g for g in gauges if g["bucket"] == "overall"]
        assert len(buckets) == len(stats.shapes) and len(overall) == 1
        for g in buckets:
            assert 0.0 < g["node_efficiency"] <= 1.0
            assert 0.0 < g["edge_efficiency"] <= 1.0
        assert sum(g["batches"] for g in buckets) == stats.batches
        # per-bucket accumulators reconcile with the overall figures
        tot_real = sum(stats.per_shape[s][0] for s in stats.per_shape)
        assert tot_real == stats.real_nodes

    def test_hbm_gauges_cpu_fallback(self):
        recs = hbm_gauges()
        assert len(recs) == len(jax.devices())
        # CPU test mesh: neither memory_stats nor the kind table applies
        assert all(r["source"] in ("memory_stats", "table", "unknown")
                   for r in recs)


class TestStepStream:
    def test_tap_inside_jit_and_scan(self, tmp_path):
        log = MetricsLogger(str(tmp_path), use_clu=False)
        stream = StepStream(log)

        def body(carry, x):
            metrics = {"loss_sum": x * 2.0, "count": jnp.float32(4.0)}
            stream.tap(metrics, "train", step=carry)
            return carry + 1, metrics["loss_sum"]

        @jax.jit
        def run(carry, xs):
            return jax.lax.scan(body, carry, xs)

        xs = jnp.arange(5, dtype=jnp.float32)
        run(jnp.int32(0), xs)
        jax.effects_barrier()
        recs = stream.records("train")
        assert len(recs) == 5
        by_step = {r["step"]: r for r in recs}
        # derived per-step mean: loss_sum / count
        assert by_step[2]["loss"] == pytest.approx(2 * 2.0 / 4.0)
        assert by_step[0]["count"] == 4.0
        log.close()
        file_steps = [r for r in read_jsonl(log.path)
                      if r.get("event") == "step"]
        assert len(file_steps) == 5

    def test_muted_drops_records(self):
        stream = StepStream(None)

        @jax.jit
        def f(x):
            stream.tap({"loss_sum": x, "count": jnp.float32(1.0)}, "train",
                       step=jnp.int32(1))
            return x + 1

        with stream.muted():
            f(jnp.float32(3.0))
            jax.effects_barrier()
        assert stream.records() == []
        f(jnp.float32(3.0))
        jax.effects_barrier()
        assert len(stream.records()) == 1


def _fresh_state(train_g, node_cap, edge_cap):
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=24)
    tx = make_optimizer(optim="adam", lr=0.01)
    normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
    example = pack_graphs(train_g[:8], node_cap, edge_cap, 8)
    return create_train_state(model, example, tx, normalizer,
                              rng=jax.random.key(0))


class TestScanParityAndNoOp:
    def _run(self, tiny_dataset, tmp_path, level, epochs=3):
        train_g, val_g, _ = tiny_dataset
        node_cap, edge_cap = capacities_for(train_g, 8)
        state = _fresh_state(train_g, node_cap, edge_cap)
        telemetry = Telemetry(level, str(tmp_path / level))
        state, result = fit(
            state, train_g, val_g, epochs=epochs, batch_size=8,
            node_cap=node_cap, edge_cap=edge_cap, print_freq=0, seed=11,
            scan_epochs=True, log_fn=lambda *a: None, telemetry=telemetry,
        )
        telemetry.close()
        params = jax.tree_util.tree_map(np.asarray, state.params)
        return params, result, telemetry

    def test_scan_trajectory_bit_identical_with_step_telemetry(
            self, tiny_dataset, tmp_path):
        """The acceptance criterion: --telemetry step on the scan path
        must not move the trajectory AT ALL (the tap only reads metric
        scalars; grad-health metrics are extra outputs)."""
        p_off, r_off, _ = self._run(tiny_dataset, tmp_path, "off")
        p_step, r_step, t_step = self._run(tiny_dataset, tmp_path, "step")
        for a, b in zip(jax.tree_util.tree_leaves(p_off),
                        jax.tree_util.tree_leaves(p_step)):
            assert np.array_equal(a, b)  # bitwise
        for h_off, h_step in zip(r_off["history"], r_step["history"]):
            assert h_off["train"]["loss"] == h_step["train"]["loss"]
            assert h_off["val"]["mae"] == h_step["val"]["mae"]

        # per-step records streamed from inside the scan reconcile with
        # the epoch aggregates exactly (same (sum, count) arithmetic)
        recs = read_jsonl(os.path.join(str(tmp_path / "step"),
                                       "metrics.jsonl"))
        steps = [r for r in recs
                 if r.get("event") == "step" and r["phase"] == "train"]
        total_steps = sum(h["train"]["steps"] for h in r_step["history"])
        assert len(steps) == total_steps
        w_stream = sum(r["loss"] * r["count"] for r in steps)
        c_stream = sum(r["count"] for r in steps)
        w_epoch = sum(h["train"]["loss"] * h["train"]["count"]
                      for h in r_step["history"])
        assert w_stream / c_stream == pytest.approx(
            w_epoch / c_stream, rel=1e-5)
        # grad health rode along every step record
        assert all("grad_norm" in r and "nonfinite_grads" in r
                   for r in steps)
        assert all(r["nonfinite_grads"] == 0.0 for r in steps)
        # optimizer step numbers are the in-graph counter: a contiguous
        # 1..N run regardless of callback arrival order
        assert sorted(r["step"] for r in steps) == list(
            range(1, total_steps + 1))
        # eval records streamed too
        assert any(r.get("event") == "step" and r["phase"] == "eval"
                   for r in recs)

    def test_epoch_level_writes_epochs_and_summary_but_no_steps(
            self, tiny_dataset, tmp_path):
        _, _, _ = self._run(tiny_dataset, tmp_path, "epoch", epochs=1)
        recs = read_jsonl(os.path.join(str(tmp_path / "epoch"),
                                       "metrics.jsonl"))
        assert not any(r.get("event") == "step" for r in recs)
        summaries = [r for r in recs if r.get("event") == "run_summary"]
        assert len(summaries) == 1
        assert summaries[0]["counters"]["scan_steps"] > 0
        assert summaries[0]["gauges"]["scan_dispatch_share"] == 1.0
        paddings = [r for r in recs if r.get("event") == "padding"]
        assert any(p["bucket"] == "overall" for p in paddings)
        # trace exported with the epoch spans
        trace = json.load(open(os.path.join(str(tmp_path / "epoch"),
                                            "trace.json")))
        names = {e["name"] for e in trace["traceEvents"]}
        assert {"pack", "stage_scan_stacks", "epoch"} <= names

    def test_off_level_stages_no_callback_into_hlo(self, tiny_dataset,
                                                   tmp_path):
        """--telemetry off/epoch is a true no-op: the compiled step HLO
        contains no host callback; step level stages exactly the tap."""
        train_g, _, _ = tiny_dataset
        node_cap, edge_cap = capacities_for(train_g, 8)
        state = _fresh_state(train_g, node_cap, edge_cap)
        batch = pack_graphs(train_g[:8], node_cap, edge_cap, 8)

        plain = jax.jit(make_train_step())
        text_off = plain.lower(state, batch).as_text()
        assert "callback" not in text_off.lower()

        stream = StepStream(None)
        tapped = jax.jit(stream.wrap_train(make_train_step()))
        text_step = tapped.lower(state, batch).as_text()
        assert "callback" in text_step.lower()

        # and through the driver: telemetry below step level stages none
        from cgnn_tpu.train.loop import ScanEpochDriver
        from cgnn_tpu.train.step import make_eval_step

        batches = [batch]
        drv = ScanEpochDriver(
            make_train_step(), make_eval_step(), batches, [],
            np.random.default_rng(0),
            telemetry=Telemetry("epoch", str(tmp_path / "drv")),
        )
        assert drv._tap is None
        key = next(iter(drv._train_groups))
        fn = drv._scan_fn(drv._train_scans, (key, 1), drv._train_body, True)
        text_scan = fn.lower(
            state, drv._train_groups[key],
            jnp.zeros(1, jnp.int32),
        ).as_text()
        assert "callback" not in text_scan.lower()


class TestGradHealth:
    def test_metrics_present_and_finite(self, tiny_dataset):
        train_g, _, _ = tiny_dataset
        node_cap, edge_cap = capacities_for(train_g, 8)
        state = _fresh_state(train_g, node_cap, edge_cap)
        batch = pack_graphs(train_g[:8], node_cap, edge_cap, 8)
        step = jax.jit(make_train_step(grad_health=True))
        state, metrics = step(state, batch)
        for k in ("grad_norm_sum", "update_norm_sum", "nonfinite_grads_sum",
                  "nonfinite_loss_sum"):
            assert k in metrics
        assert float(metrics["grad_norm_sum"]) > 0.0
        assert float(metrics["update_norm_sum"]) > 0.0
        assert float(metrics["nonfinite_grads_sum"]) == 0.0
        assert float(metrics["nonfinite_loss_sum"]) == 0.0

    def test_nan_onset_is_counted(self, tiny_dataset):
        """Poisoned inputs surface as nonfinite grad/loss counts — the
        signal that used to be invisible inside the epoch scan."""
        import dataclasses

        train_g, _, _ = tiny_dataset
        node_cap, edge_cap = capacities_for(train_g, 8)
        state = _fresh_state(train_g, node_cap, edge_cap)
        batch = pack_graphs(train_g[:8], node_cap, edge_cap, 8)
        bad = dataclasses.replace(
            batch, targets=np.full_like(batch.targets, np.nan)
        )
        step = jax.jit(make_train_step(grad_health=True))
        _, metrics = step(state, bad)
        assert float(metrics["nonfinite_loss_sum"]) == 1.0
        assert float(metrics["nonfinite_grads_sum"]) > 0.0


class TestLoaderTelemetry:
    def test_prefetch_counters(self, tmp_path):
        from cgnn_tpu.data.loader import prefetch_to_device

        telemetry = Telemetry("epoch", str(tmp_path))
        batches = [jnp.ones(4) * i for i in range(5)]
        out = list(prefetch_to_device(iter(batches), telemetry=telemetry))
        assert len(out) == 5
        counters = telemetry.counters()
        assert counters.get("loader_put_s", 0.0) >= 0.0
        assert "loader_wait_s" in counters
        telemetry.close()


class TestDataParallelStepStream:
    @pytest.mark.skipif(not hasattr(jax, "shard_map"),
                        reason="jax.shard_map unavailable (pre-existing "
                               "seed gap in this jax build; runs in CI)")
    def test_dp_per_step_loop_streams(self, tiny_dataset, tmp_path):
        """The PR-1 known gap, closed (ISSUE 3): the DP PER-STEP loop
        (scan_epochs=False) now emits per-step stream records — the tap
        rides an outer jit around the shard_map step, carrying the
        replicated post-psum metric sums (one record per step, not one
        per device)."""
        from cgnn_tpu.parallel import fit_data_parallel
        from cgnn_tpu.parallel.mesh import make_mesh
        from cgnn_tpu.train.loop import capacities_for

        train, val, _ = tiny_dataset
        telemetry = Telemetry("step", str(tmp_path), use_clu=False)
        nc, ec = capacities_for(train, 4)
        state = _fresh_state(train, nc, ec)
        fit_data_parallel(
            state, train, val, epochs=1, batch_size=4,
            node_cap=nc, edge_cap=ec, mesh=make_mesh(2),
            print_freq=0, log_fn=lambda *a, **k: None,
            telemetry=telemetry, scan_epochs=False,
        )
        recs = telemetry.stream.records("train")
        assert recs, "DP per-step loop emitted no stream records"
        n_steps = max(r["step"] for r in recs)
        # one record per optimizer step (not per device)
        assert len(recs) == len({r["step"] for r in recs})
        assert all("loss" in r for r in recs)
        telemetry.close()
        events = [r for r in read_jsonl(str(tmp_path / "metrics.jsonl"))
                  if r.get("event") == "step" and r.get("phase") == "train"]
        assert len(events) >= n_steps
