"""Test configuration: run JAX on 8 virtual CPU devices (SURVEY.md §4.5).

Must run before jax is imported anywhere — pytest imports conftest first.
The real TPU chip is exercised separately by bench.py and the driver's
compile checks, not by the unit suite.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
