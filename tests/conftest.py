"""Test configuration: run JAX on 8 virtual CPU devices (SURVEY.md §4.5).

Must run before jax is imported anywhere — pytest imports conftest first.
The real TPU chip is exercised separately by bench.py and the driver's
compile checks, not by the unit suite.
"""

import os

# force CPU: the surrounding environment pins JAX_PLATFORMS=axon (the real
# TPU tunnel), but the unit suite runs on 8 virtual CPU devices by design
os.environ["JAX_PLATFORMS"] = "cpu"
# float64 support for the double-precision oracle parity harness
os.environ.setdefault("JAX_ENABLE_X64", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# jax may already have been imported by a pytest plugin, in which case the
# env vars above were read too late — force the settings through jax.config
# too (honoring an explicit env opt-out, e.g. JAX_ENABLE_X64=0 pytest).
import jax  # noqa: E402

if os.environ.get("JAX_ENABLE_X64", "1").lower() not in ("0", "false"):
    jax.config.update("jax_enable_x64", True)
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# validate every iterator-produced GraphBatch in the whole suite
# (SURVEY.md §5 sanitizers; the --check-invariants flag, forced on here)
from cgnn_tpu.data import invariants  # noqa: E402

invariants.enable()

# jax 0.4.37 (this container) predates pltpu.force_tpu_interpret_mode —
# the reason every pallas interpret-mode test was among the pre-existing
# seed failures. Emulate it FOR THE TEST SUITE ONLY by forcing
# interpret=True through pallas_call while the context is active; newer
# jax (CI) keeps the real context manager. Library code never depends on
# this shim (ops/pallas_cgconv.py threads its own interpret flag).
from jax.experimental import pallas as _pl  # noqa: E402
from jax.experimental.pallas import tpu as _pltpu  # noqa: E402

if not hasattr(_pltpu, "force_tpu_interpret_mode"):
    import contextlib as _contextlib
    import functools as _functools

    @_contextlib.contextmanager
    def _force_tpu_interpret_mode():
        orig = _pl.pallas_call

        @_functools.wraps(orig)
        def interpreted(*args, **kwargs):
            kwargs["interpret"] = True
            return orig(*args, **kwargs)

        _pl.pallas_call = interpreted
        try:
            yield
        finally:
            _pl.pallas_call = orig

    _pltpu.force_tpu_interpret_mode = _force_tpu_interpret_mode
