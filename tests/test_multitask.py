"""Multi-task head end-to-end (BASELINE config #3): masked multi-column CSV
-> CIF directory -> MultiTaskHead model -> per-task MAE metrics."""

import csv
import os

import numpy as np
import pytest

from cgnn_tpu.config import DataConfig, ModelConfig
from cgnn_tpu.data.cif import write_cif_file
from cgnn_tpu.data.dataset import FeaturizeConfig, load_cif_directory
from cgnn_tpu.data.graph import batch_iterator, capacities_for
from cgnn_tpu.data.synthetic import random_structure, synthetic_target


@pytest.fixture(scope="module")
def multitask_dir(tmp_path_factory):
    """24 CIFs + id_prop.csv with 3 target columns, ~25% cells empty."""
    root = tmp_path_factory.mktemp("mtdata")
    rng = np.random.default_rng(11)
    rows = []
    for i in range(24):
        s = random_structure(rng, 3, 9)
        cid = f"mt-{i:03d}"
        write_cif_file(s, os.path.join(root, cid + ".cif"), cid)
        # three correlated-but-distinct targets (fake E_f / gap / modulus)
        base = synthetic_target(s)
        t = [base, 2.0 * base + 0.5, -0.7 * base + float(s.num_atoms) / 10.0]
        cells = [f"{v:.6f}" if rng.uniform() > 0.25 else "" for v in t]
        # guarantee at least one label per row
        if all(c == "" for c in cells):
            cells[0] = f"{t[0]:.6f}"
        rows.append([cid] + cells)
    with open(os.path.join(root, "id_prop.csv"), "w", newline="") as f:
        csv.writer(f).writerows(rows)
    return str(root)


def test_masked_multicolumn_csv_loads(multitask_dir):
    graphs = load_cif_directory(
        multitask_dir, FeaturizeConfig(radius=6.0, max_num_nbr=10)
    )
    assert len(graphs) == 24
    for g in graphs:
        assert g.target.shape == (3,)
        assert g.target_mask.shape == (3,)
    masks = np.stack([g.target_mask for g in graphs])
    assert 0 < masks.mean() < 1  # some labels genuinely missing
    assert (masks.sum(axis=1) >= 1).all()


def test_multitask_head_trains_with_per_task_metrics(multitask_dir):
    import jax

    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import evaluate, fit

    graphs = load_cif_directory(
        multitask_dir, FeaturizeConfig(radius=6.0, max_num_nbr=10)
    )
    train_g, val_g = graphs[:20], graphs[20:]
    cfg = ModelConfig(
        atom_fea_len=32, n_conv=2, h_fea_len=32, num_targets=3,
        multi_task_head=True,
    )
    model = cfg.build()
    # the head really is per-task stacks, not a shared fc_out
    nc, ec = capacities_for(graphs, 8)
    example = next(batch_iterator(train_g, 8, nc, ec))
    variables = model.init(jax.random.key(0), example)
    head_params = variables["params"].get("head", variables["params"])
    assert any("task2_out" in k for k in head_params)

    norm = Normalizer.fit(
        np.stack([g.target for g in train_g]),
        np.stack([g.target_mask for g in train_g]),
    )
    state = create_train_state(
        model, example, make_optimizer(optim="adam", lr=3e-3), norm,
        rng=jax.random.key(1),
    )
    state, res = fit(
        state, train_g, val_g, epochs=10, batch_size=8,
        node_cap=nc, edge_cap=ec, print_freq=0, log_fn=lambda *_: None,
    )
    m = evaluate(state, val_g, 8, nc, ec)
    for t in range(3):
        assert f"mae_task{t}" in m
        assert np.isfinite(m[f"mae_task{t}"])
    losses = [h["train"]["loss"] for h in res["history"]]
    assert losses[-1] < 0.7 * losses[0]


def test_multitask_meta_roundtrip():
    cfg = ModelConfig(num_targets=3, multi_task_head=True)
    back = ModelConfig.from_meta(cfg.to_meta())
    assert back.multi_task_head is True
    assert back.num_targets == 3
