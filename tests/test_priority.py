"""Priority-class serving (ISSUE 19): the flush-cut (class, tier, form)
triple, padding-slack backfill, WFQ tenant fairness, scavenger
starvation-freedom, and deadline-feasibility admission at the router.

Batcher tests drive ``poll`` with a fake clock (the synchronously
testable core); the server-level test checks the backfill accounting
(padding fill share, per-class responses) survives the real worker
thread with zero post-warmup recompiles; router tests use fake
transports + probed ReplicaStates so the feasibility gate is exercised
without sockets.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from cgnn_tpu.config import DataConfig, ModelConfig, build_model
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.fleet.replica import ReplicaState
from cgnn_tpu.fleet.router import FleetRouter
from cgnn_tpu.observe.slo import SLOEngine, SLOObjective
from cgnn_tpu.serve.batcher import (
    CLASSES,
    DEFAULT_CLASS,
    MALFORMED,
    MicroBatcher,
    Request,
    ServeRejection,
    parse_kv_spec,
)
from cgnn_tpu.serve.server import InferenceServer
from cgnn_tpu.serve.shapes import BatchShape, ShapeSet, plan_shape_set

CFG = FeaturizeConfig(radius=5.0, max_num_nbr=8)


@pytest.fixture(scope="module")
def graphs():
    return load_synthetic(48, CFG, seed=11, max_atoms=8)


@pytest.fixture(scope="module")
def shape_set(graphs):
    return plan_shape_set(graphs, 8, rungs=2)


@pytest.fixture(scope="module")
def model_state(graphs, shape_set):
    from cgnn_tpu.train import (
        Normalizer,
        create_train_state,
        make_optimizer,
    )

    model_cfg = ModelConfig(atom_fea_len=8, n_conv=1, h_fea_len=16)
    model = build_model(model_cfg, DataConfig(radius=5.0, max_num_nbr=8))
    state = create_train_state(
        model, shape_set.pack([graphs[0]]), make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(7),
    )
    return model_cfg, state


def _tiny_shape_set() -> ShapeSet:
    return ShapeSet([BatchShape(4, 64, 512), BatchShape(8, 128, 1024)])


def _request(graph, now=0.0, deadline=None, klass=DEFAULT_CLASS,
             tenant="", form="feat", precision="f32") -> Request:
    return Request(graph=graph, enqueued=now, deadline=deadline,
                   klass=klass, tenant=tenant, form=form,
                   precision=precision)


# ------------------------------------------------------ flush-cut triple


class TestClassCut:
    def test_unknown_class_is_malformed(self, graphs):
        b = MicroBatcher(_tiny_shape_set(), clock=lambda: 0.0)
        with pytest.raises(ServeRejection) as e:
            b.offer(_request(graphs[0], klass="vip"))
        assert e.value.reason == MALFORMED
        assert b.depth == 0

    def test_head_class_preempts_lower_class_fifo_order(self, graphs):
        """A scavenger arriving FIRST does not hold the head of the
        queue: the flush is cut for the highest class present."""
        b = MicroBatcher(_tiny_shape_set(), max_wait_ms=1000.0,
                         backfill=False, clock=lambda: 0.0)
        b.offer(_request(graphs[0], now=0.0, klass="scavenger"))
        for g in graphs[1:9]:
            b.offer(_request(g, now=0.0, klass="interactive"))
        flush = b.poll(now=0.0)
        assert flush is not None and flush.reason == "shape_full"
        assert flush.klass == "interactive"
        assert all(r.klass == "interactive" for r in flush.requests)
        assert b.depth == 1  # the scavenger is still queued, not dropped

    def test_cut_key_is_class_tier_form_triple(self, graphs):
        """Within the head class a tier/form change is a batch boundary
        (one program per flush) — but a LOWER class sharing the head's
        (tier, form) is NOT a boundary: it backfills instead."""
        b = MicroBatcher(_tiny_shape_set(), max_wait_ms=1000.0,
                         clock=lambda: 0.0)
        b.offer(_request(graphs[0], klass="interactive", precision="f32"))
        b.offer(_request(graphs[1], klass="interactive", precision="bf16"))
        b.offer(_request(graphs[2], klass="scavenger", precision="f32"))
        flush = b.poll(now=1000.0)  # way past every wait budget
        assert flush.reason == "tier_boundary"
        assert flush.klass == "interactive"
        assert flush.precision == "f32"
        # the f32 scavenger rode the head's slack; the bf16 interactive
        # request starts the NEXT batch
        assert [r.precision for r in flush.requests] == ["f32", "f32"]
        assert flush.requests[1].klass == "scavenger"
        assert flush.requests[1].backfilled
        assert b.depth == 1

    def test_default_class_single_tenant_keeps_legacy_fifo(self, graphs):
        """No classes, no tenants -> the legacy batcher behavior
        exactly (WFQ degenerates to FIFO, aging to flush-on-deadline)."""
        b = MicroBatcher(_tiny_shape_set(), max_wait_ms=50.0,
                         clock=lambda: 0.0)
        b.offer(_request(graphs[0], now=0.0))
        assert b.poll(now=0.049) is None
        flush = b.poll(now=0.051)
        assert flush.reason == "deadline"
        assert [r.graph for r in flush.requests] == [graphs[0]]

    def test_class_wait_override_validation(self, graphs):
        with pytest.raises(ValueError, match="unknown priority class"):
            MicroBatcher(_tiny_shape_set(),
                         class_max_wait_ms={"vip": 1.0})
        with pytest.raises(ValueError, match="must be > 0"):
            MicroBatcher(_tiny_shape_set(), wfq_weights={"t": 0.0})

    def test_parse_kv_spec_grammar(self):
        assert parse_kv_spec("") == {}
        assert parse_kv_spec("interactive=50,batch=200") == {
            "interactive": 50.0, "batch": 200.0}
        with pytest.raises(ValueError, match="malformed spec entry"):
            parse_kv_spec("interactive")


# ------------------------------------------------------------- backfill


class TestBackfill:
    def test_backfill_fills_slack_same_shape_same_time(self, graphs):
        """Backfill converts padding into goodput: the rung chosen for
        the head prefix is unchanged, the flush fires at the same poll
        time as with backfill off, and the scavengers ride marked."""
        clk = [0.0]
        mk = lambda on: MicroBatcher(  # noqa: E731 — two twin batchers
            _tiny_shape_set(), max_wait_ms=50.0, backfill=on,
            clock=lambda: clk[0])
        on, off = mk(True), mk(False)
        for b in (on, off):
            b.offer(_request(graphs[0], now=0.0, klass="interactive"))
            b.offer(_request(graphs[1], now=0.0, klass="scavenger"))
            b.offer(_request(graphs[2], now=0.0, klass="scavenger"))
            assert b.poll(now=0.049) is None  # neither fires early
        f_on, f_off = on.poll(now=0.051), off.poll(now=0.051)
        assert f_on.reason == f_off.reason == "deadline"
        assert f_on.klass == f_off.klass == "interactive"
        # same head -> same rung; backfill never upgrades the shape
        assert f_on.shape == f_off.shape
        assert len(f_off.requests) == 1 and f_off.n_backfilled == 0
        assert len(f_on.requests) == 3 and f_on.n_backfilled == 2
        assert [r.backfilled for r in f_on.requests] == [
            False, True, True]
        assert f_on.slack_slots == f_on.shape.graph_cap - 1
        assert on.backfilled_total == 2
        assert on.slack_total == f_on.slack_slots
        assert on.depth == 0 and off.depth == 2

    def test_backfill_requires_matching_tier_and_form(self, graphs):
        """A lower-class request in a different (tier, form) cannot ride
        — the flush runs ONE program."""
        b = MicroBatcher(_tiny_shape_set(), max_wait_ms=50.0,
                         clock=lambda: 0.0)
        b.offer(_request(graphs[0], now=0.0, klass="interactive"))
        b.offer(_request(graphs[1], now=0.0, klass="scavenger",
                         precision="bf16"))
        flush = b.poll(now=0.051)
        assert len(flush.requests) == 1 and flush.n_backfilled == 0
        assert b.depth == 1

    def test_backfill_skips_expired_and_nonfitting(self, graphs):
        """An expired candidate never rides (the client gave up); a
        too-big candidate stays queued while smaller ones still fit."""
        small = sorted(graphs, key=lambda g: g.num_nodes)
        b = MicroBatcher(_tiny_shape_set(), max_wait_ms=50.0,
                         clock=lambda: 0.0)
        b.offer(_request(small[0], now=0.0, klass="interactive"))
        b.offer(_request(small[1], now=0.0, klass="scavenger",
                         deadline=0.01))  # expired by flush time
        b.offer(_request(small[2], now=0.0, klass="scavenger"))
        flush = b.poll(now=0.051)
        assert flush.n_backfilled == 1
        assert flush.requests[1].graph is small[2]
        assert [r.graph for r in flush.expired] == [small[1]]

    def test_backfill_prefers_higher_class_among_lower(self, graphs):
        """batch outranks scavenger for the same slack."""
        b = MicroBatcher(_tiny_shape_set(), max_wait_ms=50.0,
                         clock=lambda: 0.0)
        b.offer(_request(graphs[0], now=0.0, klass="interactive"))
        b.offer(_request(graphs[1], now=0.0, klass="scavenger"))
        b.offer(_request(graphs[2], now=0.0, klass="batch"))
        flush = b.poll(now=0.051)
        ridden = [r.klass for r in flush.requests[1:]]
        assert ridden[0] == "batch"


# ------------------------------------------------- fairness / starvation


def _wfq_shares(graphs, weights, backlogs, rounds=24):
    """Serve ``rounds`` shape-full flushes while every tenant stays
    individually backlogged (its queue refilled to ``backlogs[t]``
    before each cut) -> served counts per tenant. WFQ's share contract
    only binds while a tenant HAS work queued; a tenant limited by its
    own arrival rate keeps its shortfall, it is not owed credit."""
    b = MicroBatcher(_tiny_shape_set(), max_queue=512,
                     max_wait_ms=1000.0, backfill=False,
                     wfq_weights=weights, clock=lambda: 0.0)
    gi = iter(graphs * 200)
    queued = {t: 0 for t in backlogs}
    served = {t: 0 for t in backlogs}
    for _ in range(rounds):
        for t, depth in backlogs.items():
            while queued[t] < depth:
                b.offer(_request(next(gi), now=0.0, tenant=t))
                queued[t] += 1
        flush = b.poll(now=0.0)
        assert flush is not None and flush.reason == "shape_full"
        for r in flush.requests:
            served[r.tenant] += 1
            queued[r.tenant] -= 1
    return served


class TestFairness:
    def test_wfq_share_converges_to_weights(self, graphs):
        """Tenants weighted 2:1, both backlogged, converge to a 2:1
        served share (cost 1 per request)."""
        served = _wfq_shares(graphs, {"a": 2.0, "b": 1.0},
                             {"a": 12, "b": 12})
        ratio = served["a"] / max(served["b"], 1)
        assert 1.8 <= ratio <= 2.2, served

    def test_unweighted_tenants_share_equally(self, graphs):
        """A tenant with a 3x deeper backlog gets no more than its
        weight's share — under FIFO it would take ~3x."""
        served = _wfq_shares(graphs, {}, {"x": 18, "y": 6})
        ratio = served["x"] / max(served["y"], 1)
        assert 0.8 <= ratio <= 1.25, served

    def test_scavenger_starvation_freedom_under_interactive_load(
            self, graphs):
        """Sustained interactive saturation cannot pin a scavenger
        forever: once it ages past its own class wait budget it gets
        its OWN flush (aging, not backfill — a different form here
        blocks riding along)."""
        b = MicroBatcher(_tiny_shape_set(), max_wait_ms=10.0,
                         clock=lambda: 0.0)  # scavenger budget: 160 ms
        b.offer(_request(graphs[0], now=0.0, klass="scavenger",
                         precision="bf16"))
        gi = iter(graphs[1:] * 20)
        now = 0.0
        saw_scavenger = None
        for step in range(40):
            now = step * 0.005
            while b.depth < 12:  # interactive firehose
                b.offer(_request(next(gi), now=now, klass="interactive"))
            flush = b.poll(now=now)
            if flush and flush.klass == "scavenger":
                saw_scavenger = (now, flush)
                break
        assert saw_scavenger is not None, "scavenger starved"
        at, flush = saw_scavenger
        # it fired via aging once overdue — not before its own budget,
        # not unboundedly later
        assert b.class_wait["scavenger"] <= at <= 2 * b.class_wait[
            "scavenger"]
        assert flush.requests[0].graph is graphs[0]
        assert flush.reason == "deadline"

    def test_backfill_never_delays_interactive_flush(self, graphs):
        """With a scavenger backlog present, the interactive deadline
        flush still fires exactly at max_wait — backfill runs AFTER the
        fire decision."""
        b = MicroBatcher(_tiny_shape_set(), max_wait_ms=50.0,
                         clock=lambda: 0.0)
        for g in graphs[1:4]:
            b.offer(_request(g, now=0.0, klass="scavenger"))
        b.offer(_request(graphs[0], now=0.02, klass="interactive"))
        assert b.poll(now=0.069) is None  # 49 ms: under the budget
        flush = b.poll(now=0.071)  # 51 ms: fires, carrying scavengers
        assert flush.reason == "deadline"
        assert flush.klass == "interactive"
        assert flush.n_backfilled > 0


# ----------------------------------------------------- server end to end


class TestServerPriority:
    def test_mixed_class_serving_accounts_backfill(
            self, graphs, shape_set, model_state):
        _, state = model_state
        server = InferenceServer(
            state, shape_set, cache_size=0, max_wait_ms=10.0,
            log_fn=lambda *a, **k: None)
        server.warm(graphs[0])
        server.start()
        futs = [server.submit(g, klass="scavenger")
                for g in graphs[1:4]]
        futs.append(server.submit(graphs[4], klass="interactive"))
        for f in futs:
            assert f.result(timeout=30.0).prediction is not None
        assert server.drain(timeout_s=30.0)
        stats = server.stats()
        pr = stats["priority"]
        assert pr["backfill"] is True
        assert pr["responses_by_class"]["interactive"] == 1
        assert pr["responses_by_class"]["scavenger"] == 3
        assert pr["backfilled_responses"] >= 1
        assert pr["padding_fill_share"] > 0.0
        assert stats["recompiles_after_warm"] == 0
        # the per-class latency family made it to the scrape
        text = server.registry.prometheus_text()
        assert 'serve_class_latency_ms_hist' in text
        assert 'class="interactive"' in text
        assert "serve_padding_fill_share" in text

    def test_unknown_class_rejected_at_submit(self, graphs, shape_set,
                                              model_state):
        _, state = model_state
        server = InferenceServer(
            state, shape_set, cache_size=0, max_wait_ms=10.0,
            log_fn=lambda *a, **k: None)
        with pytest.raises(ServeRejection) as e:
            server.submit(graphs[0], klass="vip")
        assert e.value.reason == MALFORMED
        assert server.counts["reject_malformed"] == 1


# --------------------------------------------- feasibility at the router


def _probed_replica(rid: int, *, p99_ms=None, queue_depth=0.0
                    ) -> ReplicaState:
    r = ReplicaState(rid, f"http://127.0.0.1:{9100 + rid}")
    r.note_probe(ready=True, queue_depth=queue_depth, p99_ms=p99_ms)
    return r


def _counting_transport(calls):
    def transport(replica, body, timeout_s):
        calls.append(replica.rid)
        return 200, {"param_version": "v1", "prediction": [0.0],
                     "latency_ms": 1.0}
    return transport


def _router(replicas, transport, **kw):
    kw.setdefault("backoff_ms", 1.0)
    kw.setdefault("log_fn", lambda *a: None)
    return FleetRouter(replicas, transport=transport, **kw)


class TestFeasibilityAdmission:
    def test_p99_floor_above_deadline_sheds_504(self):
        calls = []
        router = _router([_probed_replica(0, p99_ms=500.0)],
                         _counting_transport(calls))
        status, payload, meta = router.dispatch({"graph": {}},
                                                timeout_ms=100.0)
        assert status == 504
        assert payload["reason"] == "infeasible_deadline"
        assert payload["retry_after_s"] >= 1.0
        assert meta["retry_after_s"] == payload["retry_after_s"]
        assert calls == []  # never crossed a process boundary
        assert router.counts["fleet_infeasible_deadline"] == 1

    def test_queue_congestion_sheds_429_with_drain_hint(self):
        calls = []
        # floor (50 ms) fits the deadline; the queue does not:
        # est = 50 * (1 + 80/8) = 550 ms > 100 ms
        router = _router(
            [_probed_replica(0, p99_ms=50.0, queue_depth=80.0)],
            _counting_transport(calls))
        status, payload, _ = router.dispatch({"graph": {}},
                                             timeout_ms=100.0)
        assert status == 429
        assert payload["reason"] == "infeasible_queue"
        assert calls == []
        assert router.counts["fleet_infeasible_queue"] == 1

    def test_retry_after_scales_with_measured_congestion(self):
        """The PR bugfix: Retry-After reflects the queue drain estimate,
        not just breaker cooldowns (none are open here)."""
        router = _router(
            [_probed_replica(0, p99_ms=2000.0, queue_depth=40.0)],
            _counting_transport([]))
        # est = 2000 * (1 + 40/8) = 12 s
        assert router._retry_after_s() == pytest.approx(12.0)
        idle = _router([_probed_replica(0, p99_ms=100.0)],
                       _counting_transport([]))
        assert idle._retry_after_s() == 1.0  # clamped floor

    def test_cold_fleet_admits_without_p99(self):
        """Feasibility is an optimisation on a warmed fleet, not a gate
        that sheds a cold start."""
        calls = []
        router = _router([_probed_replica(0)],  # no p99 sample yet
                         _counting_transport(calls))
        status, _, _ = router.dispatch({"graph": {}}, timeout_ms=100.0)
        assert status == 200 and calls == [0]

    def test_best_replica_feasible_admits(self):
        """One saturated replica does not shed while a sibling can
        still make the deadline."""
        calls = []
        router = _router(
            [_probed_replica(0, p99_ms=50.0, queue_depth=500.0),
             _probed_replica(1, p99_ms=50.0, queue_depth=0.0)],
            _counting_transport(calls))
        status, _, _ = router.dispatch({"graph": {}}, timeout_ms=200.0)
        assert status == 200 and calls == [1]

    def test_gate_respects_flag_and_margin(self):
        calls = []
        off = _router([_probed_replica(0, p99_ms=500.0)],
                      _counting_transport(calls), feasibility=False)
        assert off.dispatch({"graph": {}}, timeout_ms=100.0)[0] == 200
        roomy = _router([_probed_replica(0, p99_ms=500.0)],
                        _counting_transport(calls),
                        feasibility_margin=10.0)
        assert roomy.dispatch({"graph": {}}, timeout_ms=100.0)[0] == 200
        with pytest.raises(ValueError, match="feasibility_margin"):
            _router([_probed_replica(0)], _counting_transport([]),
                    feasibility_margin=0.0)

    def test_class_label_counted_through_router(self):
        router = _router([_probed_replica(0)], _counting_transport([]))
        status, _, _ = router.dispatch(
            {"graph": {}, "class": "scavenger"}, timeout_ms=1000.0)
        assert status == 200
        assert router.counts["fleet_class_scavenger_requests"] == 1
        assert router.counts["fleet_class_scavenger_answered"] == 1


# ------------------------------------------------------- class-scoped SLO


class TestClassScopedSLO:
    def test_objective_sees_only_its_class(self):
        eng = SLOEngine(
            [SLOObjective("lat_interactive", target=0.9,
                          latency_threshold_ms=100.0, window_s=60.0,
                          klass="interactive"),
             SLOObjective("lat_all", target=0.9,
                          latency_threshold_ms=100.0, window_s=60.0)],
            clock=lambda: 0.0)
        # a slow scavenger answer must not burn the interactive budget
        eng.record(True, 5000.0, now=1.0, klass="scavenger")
        assert eng.burn_rate("lat_interactive", 60.0, now=1.0) == 0.0
        assert eng.burn_rate("lat_all", 60.0, now=1.0) > 0.0
        eng.record(True, 5000.0, now=2.0, klass="interactive")
        assert eng.burn_rate("lat_interactive", 60.0, now=2.0) > 0.0

    def test_classes_are_stable_wire_strings(self):
        assert CLASSES == ("interactive", "batch", "scavenger")
        assert DEFAULT_CLASS == "interactive"
