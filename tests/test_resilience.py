"""Fault-tolerance tests (ISSUE 2; cgnn_tpu.resilience).

The load-bearing guarantees, pinned:

- a crash at ANY point of a checkpoint save (fault-injected at the
  finalizer's crash points) leaves every previously committed save
  restorable — the temp-dir + atomic-rename protocol;
- corruption of the newest save (data garble, truncation, meta damage)
  makes restore FALL BACK to the previous valid save, with a report of
  what was skipped and why;
- the in-graph divergence guard is bit-identical to the unguarded body
  when no fault fires (like the telemetry tap), and an injected NaN
  batch is skipped exactly — the faulted run equals a run that never saw
  that batch, bit for bit;
- preemption requests stop training at the epoch boundary (chunk
  boundary under the epoch scan) with a resumable checkpoint, and the
  resumed run reaches the same epoch count as an uninterrupted one;
- the divergence monitor rolls back to the last good checkpoint with an
  LR cut, bounded by its retry budget;
- the prefetch producer thread exits when the consumer abandons the
  iterator mid-epoch.
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import jax

from cgnn_tpu.data.dataset import (
    FeaturizeConfig,
    load_synthetic,
    train_val_test_split,
)
from cgnn_tpu.data.graph import batch_iterator, pack_graphs
from cgnn_tpu.data.loader import prefetch_to_device
from cgnn_tpu.models import CrystalGraphConvNet
from cgnn_tpu.resilience import (
    DivergenceError,
    DivergenceMonitor,
    IntegrityError,
    PreemptionHandler,
    faultinject,
    guard_step,
    tree_manifest,
    verify_tree,
)
from cgnn_tpu.train import (
    CheckpointManager,
    Normalizer,
    create_train_state,
    make_optimizer,
)
from cgnn_tpu.train.checkpoint import CheckpointRestoreError
from cgnn_tpu.train.loop import capacities_for, fit
from cgnn_tpu.train.step import make_train_step


@pytest.fixture(scope="module")
def tiny_dataset():
    graphs = load_synthetic(60, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=3, max_atoms=6)
    return train_val_test_split(graphs, 0.7, 0.15, seed=0)


@pytest.fixture(autouse=True)
def _clear_fault_plan():
    yield
    faultinject.set_plan(None)


def _caps(train_g):
    return capacities_for(train_g, 16)


def _fresh_state(train_g, node_cap, edge_cap, seed=1, optim="adam"):
    """A new state with its OWN normalizer/optimizer buffers: the train
    steps donate the state argument, so sharing arrays across states
    would poison later runs with deleted buffers."""
    # small on purpose: these tests pin mechanics (bit-identity, skip
    # selects, restores), not learning, and compile time dominates
    model = CrystalGraphConvNet(atom_fea_len=8, n_conv=1, h_fea_len=16)
    tx = make_optimizer(optim=optim, lr=0.01)
    norm = Normalizer.fit(np.stack([g.target for g in train_g]))
    example = pack_graphs(train_g[:16], node_cap, edge_cap, 16)
    return create_train_state(model, example, tx, norm,
                              rng=jax.random.key(seed))


def _leaves(tree):
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(tree)]


def _assert_trees_equal(a, b):
    for x, y in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(x, y)


class TestIntegrity:
    def test_manifest_round_trip_and_bit_flip(self):
        tree = {"a": np.arange(12, dtype=np.float32).reshape(3, 4),
                "b": {"c": np.ones(5, dtype=np.int32)}}
        m = tree_manifest(tree)
        verify_tree(tree, m)  # clean tree verifies
        flipped = {"a": tree["a"].copy(), "b": {"c": tree["b"]["c"].copy()}}
        flipped["a"][1, 2] += 1.0
        with pytest.raises(IntegrityError, match="crc32"):
            verify_tree(flipped, m)
        with pytest.raises(IntegrityError, match="shape"):
            verify_tree({"a": tree["a"][:2], "b": tree["b"]}, m)
        with pytest.raises(IntegrityError, match="leaf set"):
            verify_tree({"a": tree["a"]}, m)

    def test_typed_and_raw_trees_share_paths(self):
        """The manifest must verify a raw orbax round trip of a TYPED
        tree (optax namedtuples deserialize as plain dicts)."""
        import collections

        Point = collections.namedtuple("Point", ["x", "y"])
        typed = {"p": Point(np.ones(2), np.zeros(3))}
        raw = {"p": {"x": np.ones(2), "y": np.zeros(3)}}
        verify_tree(raw, tree_manifest(typed))


class TestCrashSafeCheckpoint:
    def test_versioned_commit_and_round_trip(self, tiny_dataset, tmp_path):
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        state = _fresh_state(train_g, nc, ec)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(state, {"epoch": 0, "task": "regression"}, is_best=True)
        mgr.save(state, {"epoch": 1, "task": "regression"})
        mgr.wait()
        names = sorted(n for n in os.listdir(tmp_path)
                       if n.startswith("ckpt-"))
        assert names == ["ckpt-00000000", "ckpt-00000001"]
        for n in names:  # committed = meta + manifest inside the save dir
            assert os.path.exists(tmp_path / n / "meta.json")
            assert os.path.exists(tmp_path / n / "MANIFEST.json")
        assert mgr.exists("latest") and mgr.exists("best")
        assert mgr.exists("previous")
        assert mgr.read_meta()["epoch"] == 1
        assert mgr.read_meta("best")["epoch"] == 0

        restored, meta = mgr.restore(
            _fresh_state(train_g, nc, ec, seed=9))
        assert meta["epoch"] == 1
        _assert_trees_equal(restored.params, state.params)
        inf = mgr.restore_for_inference(
            _fresh_state(train_g, nc, ec, seed=9), "best")
        _assert_trees_equal(inf.params, state.params)
        mgr.close()

    @pytest.mark.parametrize("crash_at", ["after_write", "before_commit"])
    def test_crash_mid_save_previous_still_restorable(
            self, tiny_dataset, tmp_path, crash_at):
        """The kill-9-mid-save guarantee: a crash before the atomic
        commit leaves an uncommitted temp dir that restore never sees;
        the previous checkpoint stays the resume point."""
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        state = _fresh_state(train_g, nc, ec)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(state, {"epoch": 0})
        mgr.wait()
        faultinject.set_plan(
            faultinject.FaultPlan.parse(f"crash={crash_at}:1"))
        mgr.save(state, {"epoch": 1})
        with pytest.raises(faultinject.InjectedCrash):
            mgr.wait()
        faultinject.set_plan(None)
        # crash state on disk: epoch-1's temp never committed
        assert any(n.startswith(".tmp-") for n in os.listdir(tmp_path))
        mgr2 = CheckpointManager(str(tmp_path))  # post-crash process
        restored, meta = mgr2.restore(_fresh_state(train_g, nc, ec, seed=9))
        assert meta["epoch"] == 0
        _assert_trees_equal(restored.params, state.params)
        # the stale temp is swept by the first SAVE (writers own the
        # directory; a mere reader like predict.py must never delete a
        # live trainer's in-progress temp) and the resumed run commits
        mgr2.save(restored, {"epoch": 1})
        mgr2.wait()
        assert not any(n.startswith(".tmp-") for n in os.listdir(tmp_path))
        assert mgr2.read_meta()["epoch"] == 1
        mgr.close()
        mgr2.close()

    @pytest.mark.parametrize("mode", ["garble", "truncate", "meta"])
    def test_corrupt_latest_falls_back_with_report(
            self, tiny_dataset, tmp_path, mode):
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        s0 = _fresh_state(train_g, nc, ec, seed=0)
        s1 = _fresh_state(train_g, nc, ec, seed=1)
        mgr = CheckpointManager(str(tmp_path), log_fn=lambda m: None)
        mgr.save(s0, {"epoch": 0})
        mgr.save(s1, {"epoch": 1})
        mgr.wait()
        faultinject.corrupt_checkpoint(
            str(tmp_path / "ckpt-00000001"), mode=mode)
        restored, meta = mgr.restore(_fresh_state(train_g, nc, ec, seed=9))
        assert meta["epoch"] == 0  # fell back to the previous valid save
        _assert_trees_equal(restored.params, s0.params)
        assert mgr.last_restore_report  # the skip was reported
        assert "ckpt-00000001" in mgr.last_restore_report[0]
        mgr.close()

    def test_all_candidates_corrupt_raises(self, tiny_dataset, tmp_path):
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        mgr = CheckpointManager(str(tmp_path), log_fn=lambda m: None)
        mgr.save(_fresh_state(train_g, nc, ec), {"epoch": 0})
        mgr.wait()
        faultinject.corrupt_checkpoint(
            str(tmp_path / "ckpt-00000000"), mode="truncate")
        with pytest.raises(CheckpointRestoreError):
            mgr.restore(_fresh_state(train_g, nc, ec, seed=9))
        mgr.close()

    def test_retention_keeps_k_plus_best(self, tiny_dataset, tmp_path):
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        state = _fresh_state(train_g, nc, ec)
        mgr = CheckpointManager(str(tmp_path), keep=2)
        mgr.save(state, {"epoch": 0}, is_best=True)
        for e in range(1, 5):
            mgr.save(state, {"epoch": e})
        mgr.wait()
        names = sorted(n for n in os.listdir(tmp_path)
                       if n.startswith("ckpt-"))
        # newest two plus the best-pointer target survive
        assert names == ["ckpt-00000000", "ckpt-00000003", "ckpt-00000004"]
        assert mgr.read_meta("best")["epoch"] == 0
        mgr.close()

    def test_legacy_tag_layout_still_restores(self, tiny_dataset, tmp_path):
        """Pre-ISSUE-2 checkpoints (orbax tag dirs + meta-<tag>.json)
        remain readable as the fallback chain's last resort."""
        import orbax.checkpoint as ocp

        from cgnn_tpu.train.checkpoint import _state_pytree

        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        state = _fresh_state(train_g, nc, ec)
        tree = jax.device_get(_state_pytree(state))
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(str(tmp_path / "latest"), tree)
        with open(tmp_path / "meta-latest.json", "w") as f:
            json.dump({"epoch": 7}, f)
        mgr = CheckpointManager(str(tmp_path))
        assert mgr.exists("latest")
        restored, meta = mgr.restore(_fresh_state(train_g, nc, ec, seed=9))
        assert meta["epoch"] == 7
        _assert_trees_equal(restored.params, state.params)
        mgr.close()

    def test_legacy_missing_meta_refuses_blind_resume(
            self, tiny_dataset, tmp_path):
        """A legacy checkpoint with no meta must NOT restore silently
        (train.py used to compute start_epoch = 0 and retrain over it)."""
        import orbax.checkpoint as ocp

        from cgnn_tpu.train.checkpoint import _state_pytree

        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        state = _fresh_state(train_g, nc, ec)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(str(tmp_path / "latest"),
                       jax.device_get(_state_pytree(state)))
        mgr = CheckpointManager(str(tmp_path), log_fn=lambda m: None)
        with pytest.raises(CheckpointRestoreError, match="resume blind"):
            mgr.restore(_fresh_state(train_g, nc, ec, seed=9))
        mgr.close()


class TestDivergenceGuard:
    def test_guard_noop_is_bit_identical(self, tiny_dataset):
        """No fault -> the guarded trajectory equals the unguarded one
        bit for bit, per-step loop and whole-epoch scan alike (the same
        pin the telemetry tap carries)."""
        train_g, val_g, _ = tiny_dataset
        nc, ec = _caps(train_g)

        def run(guard, scan):
            state, result = fit(
                _fresh_state(train_g, nc, ec), train_g, val_g, epochs=2,
                batch_size=16, node_cap=nc, edge_cap=ec, print_freq=0,
                seed=4, scan_epochs=scan, guard=guard,
                log_fn=lambda *a: None,
            )
            return state, result

        for scan in (False, True):
            s_off, r_off = run(False, scan)
            s_on, r_on = run(True, scan)
            _assert_trees_equal(s_off.params, s_on.params)
            for h0, h1 in zip(r_off["history"], r_on["history"]):
                assert h1["train"]["loss"] == h0["train"]["loss"]
                assert h1["train"]["guard_skipped"] == 0.0

    def test_nan_batch_skip_equals_manual_skip_bit_exact(self, tiny_dataset):
        """A NaN batch under the guard leaves the state EXACTLY as if the
        batch had never been dispatched: same params, same step count."""
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        batches = list(batch_iterator(train_g, 16, nc, ec))
        assert len(batches) >= 3
        step = jax.jit(guard_step(make_train_step()), donate_argnums=0)
        j = 1
        faulted = [
            faultinject.poison_nan(b) if i == j else b
            for i, b in enumerate(batches)
        ]
        s1 = _fresh_state(train_g, nc, ec, seed=2)
        skips = 0.0
        for b in faulted:
            s1, m = step(s1, b)
            skips += float(np.asarray(m["guard_skipped_sum"]))
        s2 = _fresh_state(train_g, nc, ec, seed=2)
        for i, b in enumerate(batches):
            if i == j:
                continue
            s2, _ = step(s2, b)
        assert skips == 1.0
        assert int(np.asarray(s1.step)) == int(np.asarray(s2.step))
        _assert_trees_equal(s1.params, s2.params)
        _assert_trees_equal(s1.opt_state, s2.opt_state)

    def test_scan_nan_batch_skipped_and_counted(self, tiny_dataset):
        """The acceptance fault: a NaN batch injected mid-scan. The
        staged batch replays every epoch; the guard skips it every epoch,
        losses stay finite, and the skip count reaches telemetry via the
        epoch aggregates."""
        train_g, val_g, _ = tiny_dataset
        nc, ec = _caps(train_g)
        faultinject.set_plan(faultinject.FaultPlan.parse("nan_batch=1"))
        state, result = fit(
            _fresh_state(train_g, nc, ec), train_g, val_g, epochs=2,
            batch_size=16, node_cap=nc, edge_cap=ec, print_freq=0, seed=4,
            scan_epochs=True, guard=True, log_fn=lambda *a: None,
        )
        faultinject.set_plan(None)
        for h in result["history"]:
            assert np.isfinite(h["train"]["loss"])
            assert h["train"]["guard_skipped"] * h["train"]["steps"] == 1.0
        assert all(np.isfinite(x).all() for x in _leaves(state.params))

        # control: without the guard the same fault reaches the params
        faultinject.set_plan(faultinject.FaultPlan.parse("nan_batch=1"))
        state_n, _ = fit(
            _fresh_state(train_g, nc, ec), train_g, val_g, epochs=2,
            batch_size=16, node_cap=nc, edge_cap=ec, print_freq=0, seed=4,
            scan_epochs=True, guard=False, log_fn=lambda *a: None,
        )
        faultinject.set_plan(None)
        assert not all(np.isfinite(x).all() for x in _leaves(state_n.params))


class TestPreemption:
    def test_handler_latches_real_sigterm(self):
        hits = []
        handler = PreemptionHandler(log_fn=hits.append).install()
        try:
            assert not handler.requested
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.time() + 2
            while not handler.requested and time.time() < deadline:
                time.sleep(0.01)
            assert handler.requested
            assert hits and "SIGTERM" in hits[0]
        finally:
            handler.uninstall()

    def test_epoch_boundary_preempt_then_resume_full_count(
            self, tiny_dataset, tmp_path):
        """The acceptance cycle, in-process: preempt after epoch 1, save
        at the boundary, resume with the checkpoint's epoch and reach the
        same epoch count as an uninterrupted run."""
        train_g, val_g, _ = tiny_dataset
        nc, ec = _caps(train_g)
        ckpt = CheckpointManager(str(tmp_path))
        save_cb = lambda s, e, m, b: ckpt.save(s, {"epoch": e}, is_best=b)  # noqa: E731
        pre = PreemptionHandler(log_fn=lambda m: None)

        def request_at_1(epoch, tm, vm):
            if epoch == 1:
                pre.request()

        _, result = fit(
            _fresh_state(train_g, nc, ec), train_g, val_g, epochs=5,
            batch_size=16, node_cap=nc, edge_cap=ec, print_freq=0, seed=4,
            on_epoch_end=save_cb, on_epoch_metrics=request_at_1,
            preempt=pre, log_fn=lambda *a: None,
        )
        assert result["preempted"] is True
        assert [h["epoch"] for h in result["history"]] == [0, 1]
        ckpt.wait()
        meta = ckpt.read_meta()
        assert meta["epoch"] == 1

        resumed, meta2 = ckpt.restore(
            _fresh_state(train_g, nc, ec, seed=9))
        _, r2 = fit(
            resumed, train_g, val_g, epochs=5, batch_size=16,
            node_cap=nc, edge_cap=ec, print_freq=0, seed=4,
            start_epoch=meta2["epoch"] + 1, log_fn=lambda *a: None,
        )
        assert [h["epoch"] for h in r2["history"]] == [2, 3, 4]
        assert "preempted" not in r2
        ckpt.close()

    def test_scan_driver_aborts_at_chunk_boundary(self, tiny_dataset):
        """A request arriving MID-epoch stops the scan driver at the next
        chunk boundary: fewer steps dispatched, ``aborted`` set."""
        from cgnn_tpu.train.loop import ScanEpochDriver
        from cgnn_tpu.train.step import make_eval_step

        class RequestAfterPolls:
            """Looks requested from the (n+1)-th poll on — a signal that
            lands while the n-th chunk is in flight."""

            def __init__(self, n):
                self.polls, self.n = 0, n

            @property
            def requested(self):
                self.polls += 1
                return self.polls > self.n

        train_g, val_g, _ = tiny_dataset
        nc, ec = _caps(train_g)
        batches = list(batch_iterator(train_g, 8, nc, ec))
        vbatches = list(batch_iterator(val_g, 8, nc, ec, in_cap=0))
        assert len(batches) >= 4
        drv = ScanEpochDriver(
            make_train_step(), make_eval_step(), batches, vbatches,
            np.random.default_rng(7), preempt=RequestAfterPolls(1),
        )
        state = _fresh_state(train_g, nc, ec, seed=2)
        state, train_m, val_m = drv.run_epoch_pair(state, first=True)
        assert drv.aborted
        # exactly one chunk (chunk_steps=2 -> 2 steps) ran before the
        # boundary check fired; eval was skipped outright
        assert train_m["steps"] == drv.chunk_steps
        assert train_m["steps"] < len(batches)
        assert val_m == {"count": 0.0, "steps": 0}

        # a request landing during EVAL must NOT mark the (completed)
        # train epoch aborted — the caller would otherwise checkpoint it
        # under epoch-1 and retrain the whole epoch on resume
        n_train_chunks = -(-len(batches) // 2)  # single bucket, chunk 2
        drv2 = ScanEpochDriver(
            make_train_step(), make_eval_step(), batches, vbatches,
            np.random.default_rng(7),
            preempt=RequestAfterPolls(n_train_chunks),
        )
        state2 = _fresh_state(train_g, nc, ec, seed=2)
        state2, train_m2, val_m2 = drv2.run_epoch_pair(state2, first=True)
        assert not drv2.aborted
        assert train_m2["steps"] == len(batches)  # full train epoch
        assert val_m2["steps"] < len(vbatches)  # eval cut short

    def test_fit_scan_preempted_mid_epoch_saves_last_completed(
            self, tiny_dataset, tmp_path):
        """fit() handling of a chunk-boundary abort: the partial epoch's
        state is checkpointed under the last COMPLETED epoch, so resume
        redoes the interrupted epoch instead of skipping its tail."""
        train_g, val_g, _ = tiny_dataset
        nc, ec = _caps(train_g)
        ckpt = CheckpointManager(str(tmp_path))
        saved_epochs = []

        def save_cb(s, e, m, b):
            saved_epochs.append(e)
            ckpt.save(s, {"epoch": e}, is_best=b)

        pre = PreemptionHandler(log_fn=lambda m: None)
        pre.request()  # lands before epoch 0's first chunk
        _, result = fit(
            _fresh_state(train_g, nc, ec), train_g, val_g, epochs=4,
            batch_size=16, node_cap=nc, edge_cap=ec, print_freq=0, seed=4,
            scan_epochs=True, on_epoch_end=save_cb, preempt=pre,
            log_fn=lambda *a: None,
        )
        assert result["preempted"] is True
        assert result["history"] == []  # no epoch completed
        assert saved_epochs == [-1]  # resume restarts at epoch 0
        ckpt.wait()
        assert ckpt.read_meta()["epoch"] == -1
        ckpt.close()


class TestDivergenceMonitor:
    def test_rollback_lr_cut_and_bounded_retries(
            self, tiny_dataset, tmp_path):
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        state = _fresh_state(train_g, nc, ec)
        ckpt = CheckpointManager(str(tmp_path))
        ckpt.save(state, {"epoch": 0})
        ckpt.wait()
        mon = DivergenceMonitor(ckpt, max_skips=2, lr_cut=0.5,
                                max_rollbacks=2, log_fn=lambda m: None)
        bad = {"loss": 1.0, "guard_skipped": 0.5, "steps": 4}  # 2 skips
        good = {"loss": 1.0, "guard_skipped": 0.0, "steps": 4}

        s0, rolled = mon.observe(state, 0, good)
        assert not rolled and s0 is state

        s1, rolled = mon.observe(state, 1, bad)
        assert rolled and mon.rollbacks == 1 and mon.lr_scale == 0.5
        _assert_trees_equal(s1.params, state.params)  # restored weights
        # the cut tx halves the update for identical grads, with the
        # optimizer STATE structure untouched (checkpoint compatibility)
        g = jax.tree_util.tree_map(np.ones_like, state.params)
        u_base, _ = state.tx.update(g, state.tx.init(state.params),
                                    state.params)
        u_cut, _ = s1.tx.update(g, s1.tx.init(s1.params), s1.params)
        for a, b in zip(_leaves(u_base), _leaves(u_cut)):
            np.testing.assert_allclose(b, a * 0.5, rtol=1e-6)
        assert (jax.tree_util.tree_structure(s1.opt_state)
                == jax.tree_util.tree_structure(state.opt_state))

        s2, rolled = mon.observe(s1, 2, bad)
        assert rolled and mon.lr_scale == 0.25
        with pytest.raises(DivergenceError):
            mon.observe(s2, 3, bad)
        ckpt.close()

    def test_progress_survives_requeue_via_meta(self, tiny_dataset, tmp_path):
        """The LR cut and rollback budget persist through checkpoint
        meta: a preemption requeue must NOT restart at the full-strength
        LR that caused the divergence with a fresh retry budget."""
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        state = _fresh_state(train_g, nc, ec)
        ckpt = CheckpointManager(str(tmp_path))
        ckpt.save(state, {"epoch": 0})
        ckpt.wait()
        mon = DivergenceMonitor(ckpt, max_skips=2, lr_cut=0.5,
                                max_rollbacks=3, log_fn=lambda m: None)
        rolled_state, _ = mon.observe(
            state, 1, {"loss": 1.0, "guard_skipped": 0.5, "steps": 4})
        saved_meta = {"epoch": 1, **mon.meta()}
        assert saved_meta["guard_lr_scale"] == 0.5
        assert saved_meta["guard_rollbacks"] == 1

        # "new process": fresh monitor + fresh state, resumed from meta
        mon2 = DivergenceMonitor(ckpt, max_skips=2, lr_cut=0.5,
                                 max_rollbacks=3, log_fn=lambda m: None)
        state2 = _fresh_state(train_g, nc, ec, seed=9)
        state2 = mon2.resume_from_meta(state2, saved_meta)
        assert mon2.lr_scale == 0.5 and mon2.rollbacks == 1
        g = jax.tree_util.tree_map(np.ones_like, state2.params)
        u_base, _ = state.tx.update(g, state.tx.init(state.params),
                                    state.params)
        u_res, _ = state2.tx.update(g, state2.tx.init(state2.params),
                                    state2.params)
        for a, b in zip(_leaves(u_base), _leaves(u_res)):
            np.testing.assert_allclose(b, a * 0.5, rtol=1e-6)
        # no cut recorded -> state untouched
        state3 = _fresh_state(train_g, nc, ec, seed=3)
        mon3 = DivergenceMonitor(ckpt, log_fn=lambda m: None)
        assert mon3.resume_from_meta(state3, {"epoch": 0}) is state3
        ckpt.close()

    def test_nonfinite_loss_triggers_and_no_ckpt_continues(
            self, tiny_dataset, tmp_path):
        train_g, _, _ = tiny_dataset
        nc, ec = _caps(train_g)
        state = _fresh_state(train_g, nc, ec)
        ckpt = CheckpointManager(str(tmp_path / "empty"))
        mon = DivergenceMonitor(ckpt, log_fn=lambda m: None)
        nan_epoch = {"loss": float("nan"), "steps": 4}
        # divergence before any checkpoint exists: log and continue
        s, rolled = mon.observe(state, 0, nan_epoch)
        assert not rolled and s is state and mon.rollbacks == 0
        ckpt.save(state, {"epoch": 0})
        ckpt.wait()
        _, rolled = mon.observe(state, 1, nan_epoch)
        assert rolled and mon.rollbacks == 1
        ckpt.close()


class TestLoaderShutdown:
    @staticmethod
    def _alive_producers():
        return [t for t in threading.enumerate()
                if t.name == "cgnn-prefetch" and t.is_alive()]

    def test_producer_exits_when_consumer_abandons(self):
        """The epoch-abandonment fix: a consumer that stops mid-epoch
        (exception in the train loop) must not leave the producer
        blocked forever on a full queue."""
        batches = [np.zeros((4, 4)) for _ in range(64)]
        it = prefetch_to_device(iter(batches), size=2,
                                device_put=lambda x: x)
        next(it)
        assert self._alive_producers()
        it.close()  # what an exception in the consumer does via GC
        deadline = time.time() + 5
        while self._alive_producers() and time.time() < deadline:
            time.sleep(0.02)
        assert not self._alive_producers(), \
            "prefetch producer still alive after the consumer left"

    def test_normal_path_and_error_propagation_unchanged(self):
        batches = [np.full((2, 2), i) for i in range(16)]
        out = list(prefetch_to_device(iter(batches), size=2,
                                      device_put=lambda x: x))
        assert len(out) == 16
        np.testing.assert_array_equal(out[7], batches[7])

        def exploding():
            yield np.zeros(3)
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            list(prefetch_to_device(exploding(), device_put=lambda x: x))

    def test_injected_loader_exception_propagates(self, tiny_dataset):
        """faultinject.loader_exc surfaces through the prefetch thread
        to the consumer (and the producer still shuts down)."""
        faultinject.set_plan(faultinject.FaultPlan.parse("loader_exc=3"))
        batches = [np.zeros(2) for _ in range(8)]
        wrapped = faultinject.poison_batches(iter(batches))
        with pytest.raises(faultinject.InjectedLoaderError):
            list(prefetch_to_device(wrapped, device_put=lambda x: x))
        faultinject.set_plan(None)
        assert not self._alive_producers()


class TestFaultPlan:
    def test_parse_and_describe(self):
        p = faultinject.FaultPlan.parse(
            "nan_batch=5;sigterm_epoch=1;crash=after_write:2:exit")
        assert p.nan_batch == 5 and p.sigterm_epoch == 1
        assert p.crash_point == "after_write" and p.crash_hit == 2
        assert p.crash_exit is True
        desc = p.describe()
        assert "after_write" in desc and "os._exit" in desc

    def test_unknown_key_rejected(self):
        with pytest.raises(ValueError, match="unknown fault key"):
            faultinject.FaultPlan.parse("chaos_monkey=1")

    def test_no_plan_is_a_passthrough(self):
        faultinject.set_plan(None)
        batches = [np.zeros(1)]
        out = list(faultinject.poison_batches(iter(batches)))
        assert len(out) == 1 and out[0] is batches[0]  # unwrapped passthrough
        faultinject.crash_point("after_write")  # no-op
        faultinject.maybe_sigterm(0)  # no-op
