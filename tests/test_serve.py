"""Online serving subsystem tests (cgnn_tpu.serve; ISSUE 3).

The load-bearing guarantees, pinned:

- micro-batch flush fires on shape-full AND on the deadline, never on a
  shape outside the warm set;
- admission control: oversize and queue-full reject with typed errors,
  per-request deadlines expire with TIMEOUT, a draining batcher rejects
  new work but answers what it accepted (SIGTERM drain, zero drops);
- hot reload is atomic: a swap landing mid-batch leaves the in-flight
  batch on its old params (version recorded per response); an
  integrity-failed checkpoint is skipped with a logged report and the
  old params keep serving;
- the served numbers equal the offline predict path's, and repeated
  queries hit the LRU cache without drifting.
"""

import threading
import time

import numpy as np
import pytest

import jax

from cgnn_tpu.config import DataConfig, ModelConfig, build_model
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.observe import Telemetry
from cgnn_tpu.resilience import faultinject
from cgnn_tpu.resilience.preempt import PreemptionHandler
from cgnn_tpu.serve import (
    MALFORMED,
    OVERSIZE,
    QUEUE_FULL,
    SHUTDOWN,
    TIMEOUT,
    BatchShape,
    InferenceServer,
    MicroBatcher,
    Request,
    ResultCache,
    ServeRejection,
    ShapeSet,
    plan_shape_set,
    structure_fingerprint,
)
from cgnn_tpu.serve.reload import CheckpointWatcher
from cgnn_tpu.train import (
    CheckpointManager,
    Normalizer,
    create_train_state,
    make_optimizer,
)
from cgnn_tpu.train.step import make_predict_step

CFG = FeaturizeConfig(radius=5.0, max_num_nbr=8)


@pytest.fixture(scope="module")
def graphs():
    return load_synthetic(48, CFG, seed=11, max_atoms=8)


@pytest.fixture(scope="module")
def shape_set(graphs):
    return plan_shape_set(graphs, 8, rungs=2)


@pytest.fixture(scope="module")
def model_state(graphs, shape_set):
    model_cfg = ModelConfig(atom_fea_len=8, n_conv=1, h_fea_len=16)
    model = build_model(model_cfg, DataConfig(radius=5.0, max_num_nbr=8))
    state = create_train_state(
        model, shape_set.pack([graphs[0]]), make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(7),
    )
    return model_cfg, state


def _request(graph, now=0.0, deadline=None):
    return Request(graph=graph, enqueued=now, deadline=deadline)


# ---------------------------------------------------------------- shapes


class TestShapePlanner:
    def test_ladder_properties(self, graphs):
        ss = plan_shape_set(graphs, 16, rungs=3)
        assert len(ss) == 3
        caps = [(s.graph_cap, s.node_cap, s.edge_cap) for s in ss]
        assert caps == sorted(caps)
        for s in ss:
            assert s.node_cap % 8 == 0
            # every admitted graph fits every rung (deadline flushes can
            # land a lone large structure in the smallest rung)
            assert all(
                s.fits(1, *ss.graph_counts(g)) for g in graphs
            )

    def test_shape_for_picks_smallest(self, graphs):
        ss = plan_shape_set(graphs, 16, rungs=3)
        small = ss.shapes[0]
        assert ss.shape_for(1, 8, 16) == small
        assert ss.shape_for(10**9, 1, 1) is None

    def test_dense_invariant(self, graphs):
        ss = plan_shape_set(graphs, 16, rungs=2, dense_m=8)
        for s in ss:
            assert s.edge_cap == s.node_cap * 8

    def test_pack_round_trip(self, graphs, shape_set):
        batch = shape_set.pack(graphs[:3])
        assert int(np.asarray(batch.graph_mask).sum()) == 3
        shapes = {(s.node_cap,) for s in shape_set}
        assert (batch.nodes.shape[0],) in shapes


# --------------------------------------------------------------- batcher


def _tiny_shape_set():
    # graph_cap 4 so shape-full is easy to hit; node/edge caps generous
    return ShapeSet([BatchShape(4, 64, 512), BatchShape(8, 128, 1024)])


class TestMicroBatcher:
    def test_flush_on_shape_full(self, graphs):
        clk = [0.0]
        b = MicroBatcher(_tiny_shape_set(), max_queue=64, max_wait_ms=1000.0,
                         clock=lambda: clk[0])
        for g in graphs[:8]:
            b.offer(_request(g))
        flush = b.poll(now=0.0)  # way before the deadline
        assert flush is not None and flush.reason == "shape_full"
        assert len(flush.requests) == 8  # fits the LARGEST rung (cap 8)
        assert flush.shape.graph_cap == 8
        assert b.depth == 0

    def test_flush_on_deadline(self, graphs):
        b = MicroBatcher(_tiny_shape_set(), max_queue=64, max_wait_ms=50.0,
                         clock=lambda: 0.0)
        b.offer(_request(graphs[0], now=0.0))
        assert b.poll(now=0.0) is None  # neither full nor waited
        assert b.poll(now=0.049) is None
        flush = b.poll(now=0.051)
        assert flush is not None and flush.reason == "deadline"
        assert len(flush.requests) == 1
        assert flush.shape is not None  # smallest rung
        assert flush.shape.graph_cap == 4

    def test_oversize_rejected(self, graphs):
        ss = ShapeSet([BatchShape(4, 8, 16)])  # nothing real fits
        b = MicroBatcher(ss)
        big = max(graphs, key=lambda g: g.num_nodes)
        with pytest.raises(ServeRejection) as e:
            b.offer(_request(big))
        assert e.value.reason == OVERSIZE
        assert "largest compiled shape" in str(e.value)
        assert b.depth == 0

    def test_backpressure_queue_full(self, graphs):
        b = MicroBatcher(_tiny_shape_set(), max_queue=4, max_wait_ms=1000.0)
        for g in graphs[:4]:
            b.offer(_request(g, now=time.monotonic()))
        with pytest.raises(ServeRejection) as e:
            b.offer(_request(graphs[4], now=time.monotonic()))
        assert e.value.reason == QUEUE_FULL

    def test_timeout_expiry_delivered(self, graphs):
        b = MicroBatcher(_tiny_shape_set(), max_queue=64, max_wait_ms=50.0,
                         clock=lambda: 0.0)
        b.offer(_request(graphs[0], now=0.0, deadline=0.01))
        b.offer(_request(graphs[1], now=0.0, deadline=99.0))
        flush = b.poll(now=0.06)  # past the head's deadline AND max_wait
        assert flush.reason == "deadline"
        assert [r.graph for r in flush.requests] == [graphs[1]]
        assert [r.graph for r in flush.expired] == [graphs[0]]

    def test_expiry_alone_flushes_without_batch(self, graphs):
        b = MicroBatcher(_tiny_shape_set(), max_queue=64, max_wait_ms=500.0,
                         clock=lambda: 0.0)
        b.offer(_request(graphs[0], now=0.0, deadline=0.01))
        flush = b.poll(now=0.02)  # expired, but max_wait not reached
        assert flush is not None
        assert not flush.requests and len(flush.expired) == 1

    def test_drain_rejects_new_flushes_old(self, graphs):
        b = MicroBatcher(_tiny_shape_set(), max_queue=64, max_wait_ms=1000.0,
                         clock=lambda: 0.0)
        b.offer(_request(graphs[0], now=0.0))
        b.close()
        with pytest.raises(ServeRejection) as e:
            b.offer(_request(graphs[1], now=0.0))
        assert e.value.reason == SHUTDOWN
        flush = b.poll(now=0.0)
        assert flush.reason == "drain" and len(flush.requests) == 1
        assert b.next_flush() is None  # closed + empty -> worker exits


# ----------------------------------------------------------------- cache


class TestResultCache:
    def test_lru_eviction_and_hits(self):
        c = ResultCache(capacity=2)
        c.put("a", 1)
        c.put("b", 2)
        assert c.get("a") == 1  # refreshes 'a'
        c.put("c", 3)  # evicts 'b' (least recent)
        assert c.get("b") is None
        assert c.get("a") == 1 and c.get("c") == 3
        s = c.stats()
        assert s["hits"] == 3 and s["misses"] == 1

    def test_fingerprint_content_keyed(self, graphs):
        a, b = graphs[0], graphs[1]
        assert structure_fingerprint(a) == structure_fingerprint(a)
        assert structure_fingerprint(a) != structure_fingerprint(b)


# ---------------------------------------------------------------- server


def _make_server(model_state, shape_set, **kw):
    _, state = model_state
    kw.setdefault("max_wait_ms", 5.0)
    kw.setdefault("log_fn", lambda *a, **k: None)
    return InferenceServer(state, shape_set, **kw)


class TestInferenceServer:
    def test_end_to_end_matches_offline(self, graphs, shape_set,
                                        model_state):
        _, state = model_state
        server = _make_server(model_state, shape_set, cache_size=0)
        server.warm(graphs[0])
        server.start()
        futs = [server.submit(g) for g in graphs[:20]]
        got = np.stack([f.result(timeout=30.0).prediction for f in futs])
        # offline reference: one singleton batch per graph (eval is
        # batch-composition independent up to float assoc; loose tol)
        pstep = jax.jit(make_predict_step())
        want = np.stack([
            np.asarray(pstep(state, shape_set.pack([g])))[0]
            for g in graphs[:20]
        ])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        assert server.drain(timeout_s=30.0)
        stats = server.stats()
        assert stats["counts"]["responses"] == 20
        assert stats["recompiles_after_warm"] == 0

    def test_cache_hit_serves_same_row(self, graphs, shape_set,
                                       model_state):
        server = _make_server(model_state, shape_set, cache_size=16)
        server.warm(graphs[0])
        server.start()
        first = server.predict(graphs[3], timeout_ms=30000)
        second = server.predict(graphs[3], timeout_ms=30000)
        assert not first.cached and second.cached
        np.testing.assert_array_equal(first.prediction, second.prediction)
        assert second.param_version == first.param_version
        assert server.drain(timeout_s=30.0)
        assert server.counts["cache_hits"] == 1

    def test_serving_telemetry_flows(self, graphs, shape_set, model_state,
                                     tmp_path):
        telemetry = Telemetry(level="epoch", log_dir=str(tmp_path),
                              use_clu=False)
        server = _make_server(model_state, shape_set, cache_size=0,
                              telemetry=telemetry)
        server.warm(graphs[0])
        server.start()
        for g in graphs[:6]:
            server.predict(g, timeout_ms=30000)
        assert server.drain(timeout_s=30.0)
        q = telemetry.series_quantiles("serve_latency_ms")
        assert q and q["count"] >= 1 and q["p99"] >= q["p50"] > 0
        counters = telemetry.counters()
        assert counters["serve_responses"] == 6
        assert counters["serve_requests"] == 6
        # warmup dispatches must not count as served work
        assert counters.get("serve_warm", 0) == 0
        telemetry.close()
        from cgnn_tpu.observe import read_jsonl

        recs = read_jsonl(str(tmp_path / "metrics.jsonl"))
        summary = [r for r in recs if r.get("event") == "run_summary"]
        assert len(summary) == 1
        assert "serve_latency_ms_p99" in summary[0]["gauges"]

    def test_sigterm_drain_zero_drops(self, graphs, shape_set, model_state):
        server = _make_server(model_state, shape_set, cache_size=0,
                              max_wait_ms=200.0, default_timeout_ms=None)
        server.warm(graphs[0])
        server.start()
        # queue a burst, then latch the preemption signal mid-queue: the
        # resilience callback path must kick the drain without polling
        futs = [server.submit(g) for g in graphs[:12]]
        handler = PreemptionHandler(log_fn=lambda *a: None)
        handler.add_callback(server.begin_drain)
        handler.request()  # the signal handler path, minus the signal
        with pytest.raises(ServeRejection) as e:
            server.submit(graphs[0])
        assert e.value.reason == SHUTDOWN
        assert server.drain(timeout_s=30.0)
        # zero drops: every accepted request got a real answer
        preds = [f.result(timeout=1.0) for f in futs]
        assert all(p.prediction.shape == preds[0].prediction.shape
                   for p in preds)
        assert server.counts["responses"] == 12

    def test_malformed_structure_rejected_at_admission(self, graphs,
                                                       shape_set,
                                                       model_state):
        """A request with the wrong feature width or out-of-range
        connectivity must fail ALONE (400) at admission — packed, it
        would fail every co-batched request or trace a fresh shape."""
        import dataclasses

        server = _make_server(model_state, shape_set, cache_size=0)
        server.warm(graphs[0])
        g = graphs[0]
        bad_width = dataclasses.replace(
            g, atom_fea=np.zeros((g.num_nodes, g.atom_fea.shape[1] + 3),
                                 np.float32))
        with pytest.raises(ServeRejection) as e:
            server.submit(bad_width)
        assert e.value.reason == MALFORMED and "atom_fea" in str(e.value)
        bad_index = dataclasses.replace(
            g, centers=np.full_like(g.centers, g.num_nodes + 7))
        with pytest.raises(ServeRejection) as e:
            server.submit(bad_index)
        assert e.value.reason == MALFORMED and "centers" in str(e.value)
        assert server.counts["reject_malformed"] == 2
        assert server.batcher.depth == 0  # nothing poisoned the queue

    def test_worker_timeout_rejection(self, graphs, shape_set, model_state):
        server = _make_server(model_state, shape_set, cache_size=0,
                              max_wait_ms=30.0)
        server.warm(graphs[0])
        # no worker running: the request's deadline passes while queued
        fut = server.submit(graphs[0], timeout_ms=1.0)
        time.sleep(0.05)
        flush = server.batcher.poll()
        assert flush is not None and flush.expired
        server._process(flush)
        with pytest.raises(ServeRejection) as e:
            fut.result(timeout=1.0)
        assert e.value.reason == TIMEOUT
        assert server.counts["reject_timeout"] == 1


# ------------------------------------------------------------ hot reload


def _save_state(mgr, state, model_cfg, nudge=0.0):
    params = state.params
    if nudge:
        params = jax.tree_util.tree_map(
            lambda x: (np.asarray(x) + nudge).astype(np.asarray(x).dtype)
            if np.issubdtype(np.asarray(x).dtype, np.floating) else x,
            params,
        )
    mgr.save(state.replace(params=params),
             {"model": model_cfg.to_meta(),
              "data": DataConfig(radius=5.0, max_num_nbr=8).to_meta(),
              "task": "regression", "epoch": 0})
    mgr.wait()


class TestHotReload:
    def test_swap_mid_batch_is_atomic(self, graphs, shape_set, model_state,
                                      tmp_path):
        model_cfg, state = model_state
        mgr = CheckpointManager(str(tmp_path / "ckpt"),
                                log_fn=lambda m: None)
        _save_state(mgr, state, model_cfg)
        v1 = mgr.newest_committed()
        _, boot = model_state
        real = jax.jit(make_predict_step())
        swap_during_call = {"armed": False, "watcher": None}

        def spy_predict(s, batch):
            if swap_during_call["armed"]:
                swap_during_call["armed"] = False
                # the reload lands while this batch is IN FLIGHT
                assert swap_during_call["watcher"].poll_once()
            return real(s, batch)

        server = InferenceServer(
            boot, shape_set, predict_step=spy_predict, version=v1,
            cache_size=16, max_wait_ms=5.0, log_fn=lambda *a: None,
        )
        watcher = server.attach_watcher(mgr, poll_interval_s=3600)
        swap_during_call["watcher"] = watcher

        # commit v2 with different params, then serve one request with
        # the swap firing mid-predict
        _save_state(mgr, state, model_cfg, nudge=0.25)
        v2 = mgr.newest_committed()
        assert v2 != v1
        server.start()
        swap_during_call["armed"] = True
        r_old = server.predict(graphs[0], timeout_ms=30000)
        # in-flight batch finished on the OLD params
        assert r_old.param_version == v1
        # cache was cleared by the swap: the same structure re-serves
        # fresh on the new params, and the numbers actually moved
        r_new = server.predict(graphs[0], timeout_ms=30000)
        assert not r_new.cached
        assert r_new.param_version == v2
        assert not np.allclose(r_old.prediction, r_new.prediction)
        assert server.drain(timeout_s=30.0)
        mgr.close()

    def test_integrity_failed_checkpoint_skipped(self, graphs, shape_set,
                                                 model_state, tmp_path):
        model_cfg, state = model_state
        logs: list[str] = []
        mgr = CheckpointManager(str(tmp_path / "ckpt2"),
                                log_fn=logs.append)
        _save_state(mgr, state, model_cfg)
        v1 = mgr.newest_committed()
        from cgnn_tpu.serve.reload import ParamStore

        store = ParamStore(state, v1)
        watcher = CheckpointWatcher(mgr, store, state,
                                    log_fn=logs.append)
        # commit v2, then corrupt its payload (crc catches it)
        _save_state(mgr, state, model_cfg, nudge=0.5)
        v2 = mgr.newest_committed()
        faultinject.corrupt_checkpoint(str(tmp_path / "ckpt2" / v2),
                                       mode="garble")
        assert not watcher.poll_once()
        assert watcher.skips == 1 and store.version == v1
        assert any("SKIPPING" in m and v2 in m for m in logs)
        # the bad save is remembered, not retried in a loop
        assert not watcher.poll_once()
        assert watcher.skips == 1
        # a full restore through the chain falls back PAST the corrupt
        # v2 — and reports what it actually loaded (the serving version
        # label must be the restored save, not newest_committed)
        mgr.restore_for_inference(state, "latest")
        assert mgr.last_restored == v1
        # the next GOOD save supersedes it
        _save_state(mgr, state, model_cfg, nudge=1.0)
        assert watcher.poll_once()
        assert store.version == mgr.newest_committed() != v2
        mgr.close()

    def test_watcher_noop_without_new_save(self, model_state, tmp_path):
        model_cfg, state = model_state
        mgr = CheckpointManager(str(tmp_path / "ckpt3"),
                                log_fn=lambda m: None)
        _save_state(mgr, state, model_cfg)
        from cgnn_tpu.serve.reload import ParamStore

        store = ParamStore(state, mgr.newest_committed())
        watcher = CheckpointWatcher(mgr, store, state,
                                    log_fn=lambda m: None)
        assert not watcher.poll_once()
        assert watcher.swaps == 0
        mgr.close()

    def test_coordinator_failed_restore_retries_next_round(
            self, model_state, tmp_path):
        """Under a coordinator a failed restore must NOT be poisoned
        into ``_skipped``: the peers already swapped past the shared
        barrier, so a transient failure (fs lag on a blob) must retry
        next round — else this host serves stale params forever while
        reporting nothing (the PR-10 review fix, previously unpinned)."""
        model_cfg, state = model_state
        mgr = CheckpointManager(str(tmp_path / "ckptc"),
                                log_fn=lambda m: None)
        _save_state(mgr, state, model_cfg)
        v1 = mgr.newest_committed()
        _save_state(mgr, state, model_cfg, nudge=0.5)
        v2 = mgr.newest_committed()
        assert v2 != v1
        from cgnn_tpu.serve.reload import ParamStore

        store = ParamStore(state, v1)
        calls = {"n": 0}
        real_restore = mgr.restore_for_inference

        def flaky_restore(template, name):
            calls["n"] += 1
            if calls["n"] == 1:
                raise IOError("transient fs lag on a blob")
            return real_restore(template, name)

        mgr.restore_for_inference = flaky_restore
        watcher = CheckpointWatcher(
            mgr, store, state,
            coordinator=lambda newest: newest,  # every host agrees
            log_fn=lambda m: None,
        )
        assert not watcher.poll_once()
        assert watcher.skips == 1
        assert v2 not in watcher._skipped  # NOT remembered as bad
        # next coordinated round: the retry succeeds and the host
        # converges with its peers
        assert watcher.poll_once()
        assert store.version == v2

        # CONTRAST: the single-host watcher (no coordinator) remembers
        # the failure and never hot-retries it
        store2 = ParamStore(state, v1)
        calls["n"] = 0
        solo = CheckpointWatcher(mgr, store2, state,
                                 log_fn=lambda m: None)
        assert not solo.poll_once()
        assert v2 in solo._skipped
        assert not solo.poll_once()  # no retry
        assert calls["n"] == 1 and store2.version == v1
        mgr.close()


# ----------------------------------------------------- concurrent load


def test_concurrent_load_zero_drops(graphs, shape_set, model_state):
    """64 concurrent in-process clients, every request answered (the
    acceptance-criteria concurrency floor; ~2 s on CPU)."""
    server = _make_server(model_state, shape_set, cache_size=0,
                          max_queue=4096, default_timeout_ms=60000.0)
    server.warm(graphs[0])
    server.start()
    answered = []
    lock = threading.Lock()

    def client(ci):
        rng = np.random.default_rng(ci)
        for _ in range(5):
            g = graphs[int(rng.integers(len(graphs)))]
            r = server.predict(g, timeout_ms=60000)
            with lock:
                answered.append(r)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(64)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert server.drain(timeout_s=60.0)
    assert len(answered) == 64 * 5
    assert server.stats()["recompiles_after_warm"] == 0


# ----------------------------------------------------- compact serving


class TestCompactServing:
    """ISSUE 4: serving stages the raw CompactBatch form when it can —
    ~12x fewer host/H2D bytes per flush — expands on device, and falls
    back to warmed full-fidelity packing (never a recompile) for
    requests that cannot stage compactly."""

    @pytest.fixture(scope="class")
    def dense_parts(self):
        from cgnn_tpu.data.compact import CompactSpec

        cfg = FeaturizeConfig(radius=5.0, max_num_nbr=8)
        graphs = load_synthetic(48, cfg, seed=21, max_atoms=8)
        spec = CompactSpec.build(graphs, cfg.gdf(), dense_m=8)
        ss = plan_shape_set(graphs, 8, rungs=2, dense_m=8, compact=spec)
        model_cfg = ModelConfig(atom_fea_len=8, n_conv=1, h_fea_len=16,
                                dense_m=8)
        model = build_model(model_cfg, DataConfig(radius=5.0, max_num_nbr=8))
        state = create_train_state(
            model, ss.pack_full([graphs[0]]), make_optimizer(),
            Normalizer.fit(np.stack([g.target for g in graphs])),
            rng=jax.random.key(7),
        )
        return graphs, ss, state

    def _server(self, ss, state, **kw):
        kw.setdefault("cache_size", 0)
        kw.setdefault("log_fn", lambda *a, **k: None)
        return InferenceServer(state, ss, **kw)

    def test_compact_serving_matches_full_fidelity(self, dense_parts):
        graphs, ss, state = dense_parts
        compact_srv = self._server(ss, state)
        compact_srv.warm(graphs[0])
        compact_srv.start()
        futs = [compact_srv.submit(g, timeout_ms=30000)
                for g in graphs[:16]]
        got = np.stack([f.result(30.0).prediction for f in futs])
        assert compact_srv.drain(timeout_s=30.0)
        # every flush actually took the compact path
        assert compact_srv.counts.get("pack_compact", 0) >= 1
        assert compact_srv.counts.get("pack_full", 0) == 0

        full_ss = ShapeSet(list(ss.shapes), dense_m=8,
                           num_targets=ss.num_targets)
        full_srv = self._server(full_ss, state)
        full_srv.warm(graphs[0])
        full_srv.start()
        futs = [full_srv.submit(g, timeout_ms=30000) for g in graphs[:16]]
        want = np.stack([f.result(30.0).prediction for f in futs])
        assert full_srv.drain(timeout_s=30.0)
        # same answers up to the <=1 ulp on-device edge re-expansion
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_non_compactable_falls_back_without_recompile(self,
                                                          dense_parts):
        import dataclasses

        graphs, ss, state = dense_parts
        server = self._server(ss, state, max_wait_ms=20.0)
        server.warm(graphs[0])
        server.start()
        # wire-format request: featurized arrays, no raw distances
        bare = dataclasses.replace(graphs[1], distances=None)
        futs = [server.submit(g, timeout_ms=30000)
                for g in (graphs[0], bare, graphs[2])]
        rows = [f.result(30.0) for f in futs]
        assert all(np.isfinite(r.prediction).all() for r in rows)
        # a compactable-only burst afterwards still goes compact
        futs = [server.submit(g, timeout_ms=30000) for g in graphs[3:9]]
        for f in futs:
            f.result(30.0)
        assert server.drain(timeout_s=30.0)
        assert server.counts.get("pack_full", 0) >= 1
        assert server.counts.get("pack_compact", 0) >= 1
        # the fallback program was warmed: NOTHING recompiled under load
        assert server.stats()["recompiles_after_warm"] == 0
        # the bare graph's answer equals its full-featured twin's
        bare_row = rows[1].prediction
        direct = server_predict_reference(state, ss, graphs[1])
        np.testing.assert_allclose(bare_row, direct, rtol=1e-5, atol=1e-5)

    def test_pack_pipeline_telemetry_series(self, dense_parts, tmp_path):
        graphs, ss, state = dense_parts
        telemetry = Telemetry(level="epoch", log_dir=str(tmp_path),
                              use_clu=False)
        server = self._server(ss, state, telemetry=telemetry,
                              pack_workers=2)
        server.warm(graphs[0])
        server.start()
        for g in graphs[:8]:
            server.predict(g, timeout_ms=30000)
        assert server.drain(timeout_s=30.0)
        # the satellite's observability contract: pack time and
        # dispatch-side pipeline wait are value SERIES, so run_summary
        # carries p50/p95/p99 through the existing quantile machinery
        assert telemetry.series_quantiles("serve_pack_s")["count"] >= 1
        assert telemetry.series_quantiles("pipeline_wait_s")["count"] >= 1
        ingest = server.stats()["ingest"]
        assert ingest["compact"] and ingest["pack_workers"] == 2
        telemetry.close()
        from cgnn_tpu.observe import read_jsonl

        recs = read_jsonl(str(tmp_path / "metrics.jsonl"))
        summary = [r for r in recs if r.get("event") == "run_summary"]
        assert len(summary) == 1
        gauges = summary[0]["gauges"]
        assert "serve_pack_s_p99" in gauges
        assert "pipeline_wait_s_p99" in gauges

    def test_inline_pack_workers_zero_still_serves_compact(self,
                                                           dense_parts):
        graphs, ss, state = dense_parts
        server = self._server(ss, state, pack_workers=0)
        server.warm(graphs[0])
        server.start()
        futs = [server.submit(g, timeout_ms=30000) for g in graphs[:6]]
        for f in futs:
            assert np.isfinite(f.result(30.0).prediction).all()
        assert server.drain(timeout_s=30.0)
        assert server.counts.get("pack_compact", 0) >= 1


# ------------------------------------------------- device-parallel serving


class TestDeviceParallelServing:
    """ISSUE 5: the DeviceSet dispatch layer — replicated programs and
    params across N devices, least-loaded routing, per-device windows —
    with the load-bearing invariants pinned: distribution (every device
    serves), the compile pin (programs trace once; executables build per
    device at warmup and NEVER after), and hot-swap atomicity across
    replicas (no response's param_version disagrees with the params that
    computed it, under concurrent load spanning the swap)."""

    N_DEV = 4

    def _devices(self):
        import jax as _jax

        return _jax.devices()[: self.N_DEV]

    def test_resolve_devices_semantics(self):
        from cgnn_tpu.serve.devices import resolve_devices

        # the PR-4 device-awareness lesson: CPU 'devices' share the
        # host's cores, so auto stays single-device on this backend
        assert len(resolve_devices("auto")) == 1
        assert len(resolve_devices(3)) == 3
        assert len(resolve_devices("8")) == 8
        with pytest.raises(ValueError):
            resolve_devices(99)  # silent clamp would fake a dryrun
        with pytest.raises(ValueError):
            resolve_devices(0)

    def test_multidev_distribution_parity_and_compile_pin(
            self, graphs, shape_set, model_state):
        _, state = model_state
        server = _make_server(model_state, shape_set, cache_size=0,
                              pack_workers=1, devices=self._devices(),
                              engine="threads")
        server.warm(graphs[0])
        # the compile pin, N-device form: one executable per (traced
        # program, device), all built AT WARMUP
        assert server._jit_cache_size() == len(shape_set) * self.N_DEV
        server.start()
        futs = [server.submit(g, timeout_ms=30000)
                for _ in range(3) for g in graphs[:24]]
        res = [f.result(30.0) for f in futs]
        assert server.drain(timeout_s=30.0)
        # zero drops, zero recompiles, and every device answered
        assert len(res) == 72
        assert server.stats()["recompiles_after_warm"] == 0
        assert server._jit_cache_size() == len(shape_set) * self.N_DEV
        assert {r.device_id for r in res} == set(range(self.N_DEV))
        dev_stats = server.stats()["devices"]
        assert [d["dispatches"] for d in dev_stats].count(0) == 0
        assert sum(d["dispatches"] for d in dev_stats) == \
            server.counts["batches"]
        # parity: the answers equal the offline single-device reference
        pstep = jax.jit(make_predict_step())
        by_graph = {}
        for g in graphs[:24]:
            by_graph[id(g)] = np.asarray(
                pstep(state, shape_set.pack([g])))[0]
        for fut_graphs, r in zip(
                [g for _ in range(3) for g in graphs[:24]], res):
            np.testing.assert_allclose(
                r.prediction, by_graph[id(fut_graphs)],
                rtol=1e-4, atol=1e-5)

    def test_multidev_hot_swap_atomic_under_concurrent_load(
            self, graphs, shape_set, model_state, tmp_path):
        """The ISSUE-3 cache-revalidation race, per-device: under load
        spanning a swap, every response must carry numbers computed by
        the params its ``param_version`` names — on whichever replica it
        dispatched. A torn replica set (some devices old, some new,
        under one version) fails the numeric check immediately."""
        model_cfg, state = model_state
        mgr = CheckpointManager(str(tmp_path / "mdckpt"),
                                log_fn=lambda m: None)
        _save_state(mgr, state, model_cfg)
        v1 = mgr.newest_committed()
        server = _make_server(model_state, shape_set, cache_size=0,
                              pack_workers=1, devices=self._devices(),
                              engine="threads",
                              version=v1, default_timeout_ms=60000.0,
                              max_queue=4096)
        server.warm(graphs[0])
        watcher = server.attach_watcher(mgr, poll_interval_s=3600)
        _save_state(mgr, state, model_cfg, nudge=0.5)
        v2 = mgr.newest_committed()
        server.start()

        results = []
        lock = threading.Lock()
        stop = threading.Event()

        def client(ci):
            rng = np.random.default_rng(ci)
            while not stop.is_set():
                g = graphs[int(rng.integers(24))]
                r = server.predict(g, timeout_ms=60000)
                with lock:
                    results.append((id(g), r))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        # let v1 traffic flow, swap mid-load, let v2 traffic flow
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= 40:
                    break
            time.sleep(0.01)
        assert watcher.poll_once()  # the swap lands under live load
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= 120:
                    break
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert server.drain(timeout_s=60.0)
        assert server.stats()["recompiles_after_warm"] == 0

        # per-version references (batch-composition independent to tol)
        pstep = jax.jit(make_predict_step())

        def nudged(s):
            return s.replace(params=jax.tree_util.tree_map(
                lambda x: (np.asarray(x) + 0.5).astype(
                    np.asarray(x).dtype)
                if np.issubdtype(np.asarray(x).dtype, np.floating) else x,
                s.params))

        refs = {}
        for g in graphs[:24]:
            refs[(id(g), v1)] = np.asarray(
                pstep(state, shape_set.pack([g])))[0]
            refs[(id(g), v2)] = np.asarray(
                pstep(nudged(state), shape_set.pack([g])))[0]
        seen_versions = set()
        for gid, r in results:
            assert r.param_version in (v1, v2)
            seen_versions.add(r.param_version)
            # THE atomicity pin: the numbers match the version label
            np.testing.assert_allclose(
                r.prediction, refs[(gid, r.param_version)],
                rtol=1e-4, atol=1e-4,
                err_msg=f"response labeled {r.param_version} (device "
                        f"{r.device_id}) disagrees with those params")
        assert seen_versions == {v1, v2}  # load really spanned the swap
        mgr.close()

    def test_multidev_device_gauges_in_run_summary(
            self, graphs, shape_set, model_state, tmp_path):
        telemetry = Telemetry(level="epoch", log_dir=str(tmp_path),
                              use_clu=False)
        server = _make_server(model_state, shape_set, cache_size=0,
                              pack_workers=1, devices=self._devices(),
                              engine="threads", telemetry=telemetry)
        server.warm(graphs[0])
        server.start()
        futs = [server.submit(g, timeout_ms=30000)
                for _ in range(3) for g in graphs[:24]]
        for f in futs:
            f.result(30.0)
        assert server.drain(timeout_s=30.0)
        telemetry.close()
        from cgnn_tpu.observe import read_jsonl

        recs = read_jsonl(str(tmp_path / "metrics.jsonl"))
        summary = [r for r in recs if r.get("event") == "run_summary"]
        assert len(summary) == 1
        gauges = summary[0]["gauges"]
        assert gauges["device_count"] == self.N_DEV
        assert gauges["devices_active"] == self.N_DEV
        assert 0 < gauges["device_dispatch_min_share"]
        assert gauges["device_dispatch_max_share"] < 1
        for i in range(self.N_DEV):
            assert gauges[f"device{i}_dispatches"] >= 1


def server_predict_reference(state, ss, graph):
    """Offline reference for one graph through the set's compact path."""
    from cgnn_tpu.train.step import make_predict_step as _mps

    step = jax.jit(_mps(ss.expander()))
    out = np.asarray(step(state, ss.pack([graph])))
    return out[0]


# ------------------------------------------- live observability (ISSUE 6)


class TestRequestTracing:
    """Per-request tracing propagation: trace ids minted at admission
    appear on the response, the span stream, and the cache-hit fast
    path; co-batched requests carry distinct ids under one flush id;
    and the plane changes NO served number (bit-exact on vs off)."""

    def test_trace_id_on_response_spans_and_cache_hit(
            self, graphs, shape_set, model_state, tmp_path):
        import json

        telemetry = Telemetry(level="epoch", log_dir=str(tmp_path),
                              use_clu=False)
        server = _make_server(model_state, shape_set, cache_size=16,
                              telemetry=telemetry)
        server.warm(graphs[0])
        server.start()
        first = server.predict(graphs[2], timeout_ms=30000,
                               trace_id="client-supplied-42")
        hit = server.predict(graphs[2], timeout_ms=30000)
        minted = server.predict(graphs[3], timeout_ms=30000)
        assert server.drain(timeout_s=30.0)
        # response: the inbound id is honored verbatim; absent one, the
        # server mints req-<prefix>-<seq>; the cache hit gets its OWN id
        assert first.trace_id == "client-supplied-42"
        assert not first.cached and hit.cached
        assert hit.trace_id and hit.trace_id != first.trace_id
        assert minted.trace_id.startswith("req-")
        # stage stamps: full journey on a computed result, the two-stop
        # journey on a cache hit, and monotone ordering throughout
        assert set(first.stamps) == {"queued", "packed", "dispatched",
                                     "fetched", "replied"}
        s = first.stamps
        assert (s["queued"] <= s["packed"] <= s["dispatched"]
                <= s["fetched"] <= s["replied"])
        assert set(hit.stamps) == {"queued", "replied"}
        assert first.flush_id and hit.flush_id == ""
        telemetry.close()
        doc = json.load(open(tmp_path / "trace.json"))
        reqs = {e["args"].get("trace_id"): e for e in doc["traceEvents"]
                if e["name"] == "serve.request"}
        # every journey (incl. the cache hit) is a span carrying its id
        assert "client-supplied-42" in reqs
        assert hit.trace_id in reqs and reqs[hit.trace_id]["args"]["cached"]
        assert minted.trace_id in reqs
        # the flush-level hops join to the request via flush_id
        packs = [e for e in doc["traceEvents"] if e["name"] == "serve.pack"]
        dispatches = [e for e in doc["traceEvents"]
                      if e["name"] == "serve.dispatch"]
        fid = reqs["client-supplied-42"]["args"]["flush_id"]
        assert any(e["args"]["flush_id"] == fid
                   and "client-supplied-42" in e["args"]["trace_ids"]
                   for e in packs)
        assert any(e["args"]["flush_id"] == fid for e in dispatches)

    def test_cobatched_requests_distinct_ids_shared_flush(
            self, graphs, shape_set, model_state):
        # a large max_wait lets one deadline flush coalesce the burst
        server = _make_server(model_state, shape_set, cache_size=0,
                              max_wait_ms=150.0)
        server.warm(graphs[0])
        server.start()
        futs = [server.submit(g, timeout_ms=30000) for g in graphs[:6]]
        results = [f.result(timeout=30.0) for f in futs]
        assert server.drain(timeout_s=30.0)
        ids = [r.trace_id for r in results]
        assert len(set(ids)) == len(ids)  # DISTINCT per request
        flushes = {r.flush_id for r in results}
        assert len(flushes) == 1  # ONE shared flush/batch id
        (fid,) = flushes
        assert fid.startswith("flush-")
        # co-batched => identical flush-level stamps, distinct queued
        packed = {r.stamps["packed"] for r in results}
        dispatched = {r.stamps["dispatched"] for r in results}
        assert len(packed) == 1 and len(dispatched) == 1

    def test_served_numbers_bit_exact_plane_on_vs_off(
            self, graphs, shape_set, model_state, tmp_path):
        """The PR-1 invariant, serving flavor: the full plane (tracing +
        registry + rolling series) must not move ONE BIT of any served
        value."""
        def run(telemetry):
            server = _make_server(model_state, shape_set, cache_size=0,
                                  telemetry=telemetry)
            server.warm(graphs[0])
            server.start()
            futs = [server.submit(g, timeout_ms=30000)
                    for g in graphs[:16]]
            preds = [f.result(timeout=30.0).prediction for f in futs]
            assert server.drain(timeout_s=30.0)
            return np.stack(preds)

        off = run(Telemetry.disabled())
        on_t = Telemetry(level="epoch", log_dir=str(tmp_path),
                         use_clu=False)
        on = run(on_t)
        on_t.close()
        np.testing.assert_array_equal(off, on)  # bitwise

    def test_stats_rolling_window_and_inflight(self, graphs, shape_set,
                                               model_state):
        server = _make_server(model_state, shape_set, cache_size=0)
        server.warm(graphs[0])
        server.start()
        for g in graphs[:8]:
            server.predict(g, timeout_ms=30000)
        stats = server.stats()
        rolling = stats["rolling"]
        assert rolling["window_s"] == server.rolling_window_s
        assert rolling["latency_ms"]["count"] >= 8
        assert rolling["latency_ms"]["p99"] >= rolling["latency_ms"]["p50"]
        assert rolling["device_inflight"] == [0]
        assert server.drain(timeout_s=30.0)

    def test_metrics_endpoint_families(self, graphs, shape_set,
                                       model_state):
        """GET /metrics renders the registry with the three required
        families present whatever the telemetry level (here: off)."""
        from cgnn_tpu.observe import parse_prometheus_text

        server = _make_server(model_state, shape_set, cache_size=0)
        server.warm(graphs[0])
        server.start()
        for g in graphs[:4]:
            server.predict(g, timeout_ms=30000)
        text = server.registry.prometheus_text()
        assert server.drain(timeout_s=30.0)
        fams = parse_prometheus_text(text)
        for prefix in ("cgnn_serve_", "cgnn_device", "cgnn_pipeline_"):
            assert any(f.startswith(prefix) for f in fams), (prefix, fams)
        assert fams["cgnn_serve_responses_total"]["samples"][0][1] == 4.0
        lat = fams["cgnn_serve_latency_ms"]
        assert any('quantile="0.99"' in n for n, _ in lat["samples"])

    def test_profile_endpoint_gate_and_artifact(self, graphs, shape_set,
                                                model_state, tmp_path):
        from cgnn_tpu.observe import ProfileBusy

        server = _make_server(model_state, shape_set, cache_size=0)
        server.warm(graphs[0])
        server.start()
        profiler = server.enable_profiling(str(tmp_path))
        rec = profiler.capture(0.2)
        assert rec["bytes"] > 0
        assert profiler._gate.acquire(blocking=False)
        try:
            with pytest.raises(ProfileBusy):
                profiler.capture(0.1)
        finally:
            profiler._gate.release()
        # profiling staged nothing: the compile pin survives a capture
        n0 = server._jit_cache_size()
        server.predict(graphs[0], timeout_ms=30000)
        assert server._jit_cache_size() == n0
        assert server.drain(timeout_s=30.0)
        assert server.stats()["recompiles_after_warm"] == 0


# ------------------------- cross-process observability layer (ISSUE 15)


class TestCrossProcessTraceLayer:
    """The fleet-facing half of the plane: the always-on span ring
    behind GET /trace, inbound X-Trace-Parent adoption, and the flight
    recorder's request ring — all host-side. Pinned: served numbers are
    BIT-EXACT and the post-warmup compile count stays zero with the
    whole layer on vs fully off (the ISSUE-15 acceptance pin)."""

    def test_bit_exact_and_zero_recompiles_with_layer_on(
            self, graphs, shape_set, model_state, tmp_path):
        from cgnn_tpu.observe import FlightRecorder

        off = _make_server(model_state, shape_set, cache_size=0,
                           trace_ring=0)
        on = _make_server(model_state, shape_set, cache_size=0,
                          trace_ring=4096)
        on.attach_flight_recorder(FlightRecorder(
            str(tmp_path / "fr"), role="replica", registry=on.registry,
            tracer=on.tracer, log_fn=lambda *a, **k: None))
        for server in (off, on):
            server.warm(graphs[0])
            server.start()
        assert off.tracer is None and on.tracer is not None
        for i, g in enumerate(graphs[:6]):
            a = off.predict(g, timeout_ms=30000)
            b = on.predict(g, timeout_ms=30000,
                           trace_parent=f"att-pin-{i:06x}")
            np.testing.assert_array_equal(a.prediction, b.prediction)
        assert off.drain(timeout_s=30.0) and on.drain(timeout_s=30.0)
        assert off.stats()["recompiles_after_warm"] == 0
        assert on.stats()["recompiles_after_warm"] == 0
        # the layer actually recorded what it promised while staying
        # out of the compute: spans in the ring, requests in the ring
        assert len(on.flightrec.recent_requests()) == 6
        assert all(r["status"] == "ok"
                   for r in on.flightrec.recent_requests())

    def test_trace_window_adopts_inbound_parent(self, graphs,
                                                shape_set, model_state):
        server = _make_server(model_state, shape_set, cache_size=16,
                              trace_ring=4096)
        server.warm(graphs[0])
        server.start()
        server.predict(graphs[1], timeout_ms=30000,
                       trace_id="joined-1",
                       trace_parent="att-up-000001")
        # the cache-hit fast path must carry the parent too (a hedged
        # retry answered from cache still nests under its attempt)
        hit = server.predict(graphs[1], timeout_ms=30000,
                             trace_id="joined-2",
                             trace_parent="att-up-000002")
        orphan = server.predict(graphs[2], timeout_ms=30000)
        assert hit.cached
        assert server.drain(timeout_s=30.0)
        w = server.trace_window()
        assert w["role"] == "replica" and w["dropped"] == 0
        reqs = {e["args"].get("trace_id"): e["args"]
                for e in w["events"] if e["name"] == "serve.request"}
        assert reqs["joined-1"]["parent"] == "att-up-000001"
        assert reqs["joined-2"]["parent"] == "att-up-000002"
        # no inbound context -> the span roots its own tree (no
        # invented parent key at all)
        assert "parent" not in reqs[orphan.trace_id]
        # flush-level hops landed in the SAME ring (the joiner nests
        # them by flush_id/trace_ids)
        assert any(e["name"] == "serve.dispatch" for e in w["events"])

    def test_window_since_and_telemetry_coexistence(
            self, graphs, shape_set, model_state, tmp_path):
        # both sinks on: the telemetry tracer (trace.json at close) AND
        # the serving ring must each hold the request span
        telemetry = Telemetry(level="epoch", log_dir=str(tmp_path),
                              use_clu=False)
        server = _make_server(model_state, shape_set, cache_size=0,
                              telemetry=telemetry, trace_ring=4096)
        server.warm(graphs[0])
        server.start()
        server.predict(graphs[0], timeout_ms=30000, trace_id="both-1")
        assert server.drain(timeout_s=30.0)
        ring_ids = {e["args"].get("trace_id")
                    for e in server.trace_window()["events"]
                    if e["name"] == "serve.request"}
        tel_ids = {e["args"].get("trace_id")
                   for e in telemetry.spans.events
                   if e["name"] == "serve.request"}
        assert "both-1" in ring_ids and "both-1" in tel_ids
        telemetry.close()
        # a since cut in the future filters everything out
        import time as _time

        assert server.trace_window(
            since_s=_time.time() + 60.0)["events"] == []


# ------------------------------------------------- precision tiers (ISSUE 9)


class TestPrecisionServing:
    """serve/quantize.py through the serving path: every tier is a warm
    program (compile pin), requests pick tiers per call, the batcher
    cuts flushes at tier boundaries, the cache is tier-keyed, and hot
    reload rebuilds every tier under one version without retracing."""

    def _tier_server(self, model_state, shape_set, **kw):
        model_cfg, state = model_state
        model = build_model(model_cfg, DataConfig(radius=5.0, max_num_nbr=8))
        kw.setdefault("log_fn", lambda *a, **k: None)
        kw.setdefault("max_wait_ms", 5.0)
        return InferenceServer(
            state, shape_set, precisions=("f32", "bf16", "int8"),
            model=model, **kw,
        )

    def test_mixed_tier_traffic_compile_pin(self, graphs, shape_set,
                                            model_state):
        server = self._tier_server(model_state, shape_set, cache_size=0)
        compiled = server.warm(graphs[0])
        # rungs x tiers (one staging form: no compact spec), one device
        assert compiled == len(shape_set) * 3
        server.start()
        n0 = server._jit_cache_size()
        futs = [
            (tier, server.submit(graphs[i % len(graphs)], timeout_ms=30000,
                                 precision=tier))
            for i, tier in enumerate(
                ["f32", "bf16", "int8", "int8", "f32", "bf16"] * 4)
        ]
        for tier, fut in futs:
            res = fut.result(timeout=60.0)
            assert res.precision == tier
        assert server._jit_cache_size() == n0
        assert server.drain(timeout_s=30.0)
        assert server.stats()["recompiles_after_warm"] == 0
        assert server.stats()["counts"]["responses"] == len(futs)

    def test_tier_predictions_differ_but_agree(self, graphs, shape_set,
                                               model_state):
        server = self._tier_server(model_state, shape_set, cache_size=0)
        server.warm(graphs[0])
        server.start()
        res = {t: server.predict(graphs[1], timeout_ms=30000, precision=t)
               for t in ("f32", "bf16", "int8")}
        f32 = res["f32"].prediction
        for tier in ("bf16", "int8"):
            got = res[tier].prediction
            assert not np.array_equal(got, f32)  # a REAL low-precision run
            np.testing.assert_allclose(got, f32, rtol=0.05, atol=0.05)
        assert server.drain(timeout_s=30.0)

    def test_batcher_cuts_flush_at_tier_boundary(self, graphs, shape_set):
        clk = [0.0]
        b = MicroBatcher(shape_set, max_wait_ms=5.0, clock=lambda: clk[0])
        for tier in ("f32", "f32", "bf16", "bf16", "int8"):
            r = _request(graphs[0], now=clk[0])
            r.precision = tier
            b.offer(r)
        clk[0] += 1.0  # all past the batching deadline
        flushes = []
        while True:
            f = b.poll()
            if f is None or not f.requests:
                break
            flushes.append((f.precision, len(f.requests)))
        assert flushes == [("f32", 2), ("bf16", 2), ("int8", 1)]

    def test_unknown_tier_rejected_at_admission(self, graphs, shape_set,
                                                model_state):
        server = self._tier_server(model_state, shape_set, cache_size=0)
        server.warm(graphs[0])
        server.start()
        with pytest.raises(ServeRejection, match="precision"):
            server.submit(graphs[0], precision="fp4")
        # a plain-f32 server rejects non-f32 tiers too (never warmed)
        assert server.drain(timeout_s=30.0)

    def test_tier_keyed_cache_isolation(self, graphs, shape_set,
                                        model_state):
        server = self._tier_server(model_state, shape_set, cache_size=64)
        server.warm(graphs[0])
        server.start()
        r_f32 = server.predict(graphs[2], timeout_ms=30000)
        r_int8 = server.predict(graphs[2], timeout_ms=30000,
                                precision="int8")
        # the int8 request must NOT be answered from the f32 cache row
        assert not r_int8.cached
        assert not np.array_equal(r_int8.prediction, r_f32.prediction)
        # same-tier repeats DO hit, each tier its own row
        assert server.predict(graphs[2], timeout_ms=30000).cached
        r_int8_2 = server.predict(graphs[2], timeout_ms=30000,
                                  precision="int8")
        assert r_int8_2.cached
        np.testing.assert_array_equal(r_int8_2.prediction,
                                      r_int8.prediction)
        assert server.drain(timeout_s=30.0)

    def test_hot_swap_rebuilds_every_tier_without_retrace(
            self, graphs, shape_set, model_state, tmp_path):
        model_cfg, state = model_state
        mgr = CheckpointManager(str(tmp_path / "ckpt"),
                                log_fn=lambda m: None)
        _save_state(mgr, state, model_cfg)
        v1 = mgr.newest_committed()
        server = self._tier_server(model_state, shape_set, cache_size=0,
                                   version=v1)
        server.warm(graphs[0])
        server.start()
        before = {t: server.predict(graphs[3], timeout_ms=30000,
                                    precision=t)
                  for t in ("f32", "int8")}
        n0 = server._jit_cache_size()
        watcher = server.attach_watcher(mgr, poll_interval_s=3600)
        _save_state(mgr, state, model_cfg, nudge=0.25)
        assert watcher.poll_once()
        after = {t: server.predict(graphs[3], timeout_ms=30000,
                                   precision=t)
                 for t in ("f32", "int8")}
        for tier in ("f32", "int8"):
            assert before[tier].param_version == v1
            assert after[tier].param_version == mgr.newest_committed()
            # every tier's numbers moved with the swap (quantized
            # variants really were re-derived from the new params)
            assert not np.allclose(before[tier].prediction,
                                   after[tier].prediction)
        # the swap reused the warmed programs: no retrace, no recompile
        assert server._jit_cache_size() == n0
        assert server.stats()["recompiles_after_warm"] == 0
        assert server.drain(timeout_s=30.0)
        mgr.close()


# ------------------------------- readiness + back-off hints (ISSUE 14)


def _http_get(url: str):
    import json as _json
    import urllib.error
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=10.0) as resp:
            return resp.status, _json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, _json.loads(e.read()), dict(e.headers or {})


def _http_post(url: str, body: dict):
    import json as _json
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url, data=_json.dumps(body, allow_nan=False).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30.0) as resp:
            return resp.status, _json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, _json.loads(e.read()), dict(e.headers or {})


def _graph_body(g):
    return {"graph": {
        "atom_fea": g.atom_fea.tolist(),
        "edge_fea": g.edge_fea.tolist(),
        "centers": g.centers.tolist(),
        "neighbors": g.neighbors.tolist(),
    }, "timeout_ms": 30000}


class TestReadinessAndBackoff:
    """The ISSUE-14 satellites: /healthz readiness vs liveness and the
    Retry-After back-off hints on 429/503."""

    def _bind(self, server):
        import threading as _threading

        from cgnn_tpu.serve.http import make_http_server

        httpd = make_http_server(server, port=0)
        port = httpd.server_address[1]
        t = _threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="test-http-listener")
        t.start()
        return httpd, f"http://127.0.0.1:{port}"

    def test_healthz_ready_only_after_warm(self, graphs, shape_set,
                                           model_state):
        server = _make_server(model_state, shape_set, cache_size=0)
        httpd, base = self._bind(server)
        try:
            # live but NOT ready: the shape set has not compiled
            status, payload, headers = _http_get(base + "/healthz")
            assert status == 503
            assert payload["ok"] and not payload["ready"]
            assert not payload["warmed"] and not payload["draining"]
            assert int(headers["Retry-After"]) >= 1
            # /predict refuses with the same back-off hint: admitting
            # would eat traffic into cold-compile latency
            status, payload, headers = _http_post(
                base + "/predict", _graph_body(graphs[0]))
            assert status == 503 and payload["reason"] == SHUTDOWN
            assert "Retry-After" in headers
            server.warm(graphs[0])
            server.start()
            status, payload, _ = _http_get(base + "/healthz")
            assert status == 200 and payload["ready"] and payload["warmed"]
            assert payload["param_version"]
            # draining flips readiness back off (while staying alive)
            server.begin_drain()
            status, payload, headers = _http_get(base + "/healthz")
            assert status == 503
            assert payload["ok"] and payload["draining"]
            assert not payload["ready"]
            assert "Retry-After" in headers
        finally:
            httpd.shutdown()
            httpd.server_close()
            server.drain(timeout_s=30.0)

    def test_queue_full_and_draining_carry_retry_after(self, graphs,
                                                       shape_set,
                                                       model_state):
        server = _make_server(model_state, shape_set, cache_size=0,
                              max_queue=1)
        server.warm(graphs[0])  # worker NOT started: the queue fills
        server.submit(graphs[0])
        httpd, base = self._bind(server)
        try:
            status, payload, headers = _http_post(
                base + "/predict", _graph_body(graphs[1]))
            assert status == 429 and payload["reason"] == QUEUE_FULL
            assert int(headers["Retry-After"]) >= 1
            server.begin_drain()
            status, payload, headers = _http_post(
                base + "/predict", _graph_body(graphs[1]))
            assert status == 503 and payload["reason"] == SHUTDOWN
            assert int(headers["Retry-After"]) >= 1
        finally:
            httpd.shutdown()
            httpd.server_close()
            server.drain(timeout_s=30.0)


# -------------------------------- serve-side fault points (ISSUE 14)


class TestServeFaultPoints:
    def test_dispatch_exception_fails_flush_alone(self, graphs,
                                                  shape_set,
                                                  model_state):
        """The chaos substrate: an injected dispatch exception fails
        its flush (futures get the typed error) and the server KEEPS
        serving — the fleet router's retry-on-500 path upstream."""
        server = _make_server(model_state, shape_set, cache_size=0)
        server.warm(graphs[0])
        server.start()
        faultinject.set_plan(faultinject.FaultPlan(dispatch_exc=0))
        try:
            with pytest.raises(faultinject.InjectedDispatchError):
                server.predict(graphs[0], timeout_ms=30000)
            # the NEXT flush is healthy: one injected failure must not
            # wedge or poison the worker
            res = server.predict(graphs[1], timeout_ms=30000)
            assert res.prediction is not None
        finally:
            faultinject.set_plan(None)
        assert server.drain(timeout_s=30.0)
