"""predict.py fast path (train/infer.py): bucketed pipelined inference
must return predictions in input order, identical to the naive
batch-at-a-time loop (eval mode is batch-composition-independent)."""

import jax
import numpy as np

from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
from cgnn_tpu.data.graph import batch_iterator, capacities_for
from cgnn_tpu.models import CrystalGraphConvNet
from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
from cgnn_tpu.train.infer import run_fast_inference
from cgnn_tpu.train.step import make_predict_step

CFG = FeaturizeConfig(radius=6.0, max_num_nbr=12)


def test_fast_inference_order_and_values():
    graphs = load_synthetic_mp(160, CFG, seed=5)
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=32,
                                dense_m=12)
    nc, ec = capacities_for(graphs, 32, dense_m=12, snug=True)
    example = next(batch_iterator(graphs, 32, nc, ec, dense_m=12, in_cap=0,
                                  snug=True))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(3),
    )

    # reference: naive single-bucket ladder loop, fetch per batch
    pstep = jax.jit(make_predict_step())
    nc_l, ec_l = capacities_for(graphs, 32, dense_m=12)
    want = []
    for b in batch_iterator(graphs, 32, nc_l, ec_l, dense_m=12, in_cap=0):
        out = np.asarray(jax.device_get(pstep(state, b)))
        want.append(out[: int(np.asarray(b.graph_mask).sum())])
    want = np.concatenate(want)

    got, rate = run_fast_inference(state, graphs, 32, buckets=3, dense_m=12,
                                   snug=True)
    assert rate > 0
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def _tiny_state(graphs, batch_size=32, seed=3, dense_m=12):
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=32,
                                dense_m=dense_m)
    nc, ec = capacities_for(graphs, batch_size, dense_m=dense_m, snug=True)
    example = next(batch_iterator(graphs, batch_size, nc, ec, dense_m=dense_m,
                                  in_cap=0, snug=True))
    return create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(seed),
    )


def test_fast_inference_bit_exact_vs_naive_fetch_per_batch():
    """The pipelining + single-stacked-fetch machinery must be a pure
    I/O optimization: identical batches through the identical step give
    BIT-identical outputs vs a naive fetch-per-batch loop — including
    the ragged final batch (157 % 32 != 0) and the multi-bucket
    input-order restoration."""
    graphs = load_synthetic_mp(157, CFG, seed=9)
    state = _tiny_state(graphs)
    pstep = jax.jit(make_predict_step())

    for buckets in (1, 3):
        # naive reference: same bucket partition, same capacities, same
        # packed batches — but one synchronous device_get per batch
        from cgnn_tpu.data.graph import assign_size_buckets

        bucket_of = assign_size_buckets(graphs, buckets)
        want = np.zeros((len(graphs), 1), np.float32)
        for b in range(int(bucket_of.max()) + 1):
            idxs = np.nonzero(bucket_of == b)[0]
            sub = [graphs[int(i)] for i in idxs]
            nc, ec = capacities_for(sub, 32, dense_m=12, snug=True)
            ptr = 0
            for batch in batch_iterator(sub, 32, nc, ec, dense_m=12,
                                        in_cap=0, snug=True):
                out = np.asarray(jax.device_get(pstep(state, batch)))
                n_real = int(np.asarray(batch.graph_mask).sum())
                want[idxs[ptr : ptr + n_real]] = out[:n_real]
                ptr += n_real
            assert ptr == len(sub)  # ragged tail fully consumed

        got, _ = run_fast_inference(state, graphs, 32, buckets=buckets,
                                    dense_m=12, snug=True,
                                    predict_step=pstep)
        np.testing.assert_array_equal(got, want)


def test_fast_inference_shape_set_pins_compiles():
    """The injected (predict_step, shape_set) pair: output parity with
    the capacity-derived path, and the jit cache-miss counter pinned at
    len(shape_set) across repeated datasets — offline predict reuses the
    serving ladder instead of compiling per dataset."""
    from cgnn_tpu.serve.shapes import plan_shape_set

    graphs = load_synthetic_mp(150, CFG, seed=7)
    state = _tiny_state(graphs)
    shape_set = plan_shape_set(graphs, 32, rungs=2, dense_m=12)
    pstep = jax.jit(make_predict_step())

    # warm every rung once (what serve.InferenceServer.warm does): the
    # compile count is then pinned at exactly len(shape_set)
    for shape in shape_set:
        np.asarray(pstep(state, shape_set.pack([graphs[0]], shape=shape)))
    assert pstep._cache_size() == len(shape_set)

    got, rate = run_fast_inference(state, graphs, 32,
                                   predict_step=pstep, shape_set=shape_set)
    assert rate > 0
    assert pstep._cache_size() == len(shape_set)  # zero fresh traces

    # reference via the default bucketed path (different packing, same
    # math up to float association)
    want, _ = run_fast_inference(state, graphs, 32, dense_m=12, snug=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    # a second, differently-sized dataset through the SAME shapes: the
    # counter must not move — this is what "offline predict reuses the
    # serving shapes" buys over per-dataset capacity derivation
    more = load_synthetic_mp(40, CFG, seed=8)
    run_fast_inference(state, more, 32, predict_step=pstep,
                       shape_set=shape_set)
    assert pstep._cache_size() == len(shape_set)

    # oversize structures are rejected with a pointed error
    tiny_set = plan_shape_set(graphs[:4], 2, rungs=1, dense_m=12)
    huge = max(graphs, key=lambda g: g.num_nodes)
    if not tiny_set.admits(huge):
        with np.testing.assert_raises(ValueError):
            run_fast_inference(state, [huge], 2, shape_set=tiny_set)


def test_fast_inference_parallel_pipeline_bit_exact_vs_serial():
    """The parallel pack pipeline must be a pure scheduling optimization:
    identical inputs through pack_workers=0 and pack_workers=3 give
    BIT-identical outputs — ragged tail (157 graphs), multi-rung ladder,
    multi-bucket legacy path, input-order restoration, with and without
    compact staging."""
    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.serve.shapes import plan_shape_set

    graphs = load_synthetic_mp(157, CFG, seed=9)
    state = _tiny_state(graphs)
    spec = CompactSpec.build(graphs, CFG.gdf(), dense_m=12)

    # serving-ladder path, compact-staged (predict.py's default)
    ladder = plan_shape_set(graphs, 32, rungs=2, dense_m=12, compact=spec)
    pstep = jax.jit(make_predict_step(make_expander(spec)))
    serial, _ = run_fast_inference(state, graphs, 32, shape_set=ladder,
                                   predict_step=pstep, pack_workers=0)
    parallel, _ = run_fast_inference(state, graphs, 32, shape_set=ladder,
                                     predict_step=pstep, pack_workers=3)
    np.testing.assert_array_equal(serial, parallel)

    # ladder path, full-fidelity staging
    ladder_full = plan_shape_set(graphs, 32, rungs=2, dense_m=12)
    fserial, _ = run_fast_inference(state, graphs, 32,
                                    shape_set=ladder_full,
                                    predict_step=pstep, pack_workers=0)
    fparallel, _ = run_fast_inference(state, graphs, 32,
                                      shape_set=ladder_full,
                                      predict_step=pstep, pack_workers=3)
    np.testing.assert_array_equal(fserial, fparallel)

    # legacy bucketed path (multi-bucket order restoration under the pool)
    for buckets in (1, 3):
        bserial, _ = run_fast_inference(state, graphs, 32, buckets=buckets,
                                        dense_m=12, snug=True,
                                        predict_step=pstep, pack_workers=0)
        bparallel, _ = run_fast_inference(state, graphs, 32,
                                          buckets=buckets, dense_m=12,
                                          snug=True, predict_step=pstep,
                                          pack_workers=3)
        np.testing.assert_array_equal(bserial, bparallel)


def test_fast_inference_compact_staging_matches_full():
    """Compact staging is an I/O-layout change, not a numerics change:
    predictions over compact-staged batches must match full-fidelity
    staging to edge-feature roundoff (the <=1 ulp jnp.exp/np.exp
    difference; same bound test_compact pins for training)."""
    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.serve.shapes import plan_shape_set

    graphs = load_synthetic_mp(96, CFG, seed=12)
    state = _tiny_state(graphs)
    spec = CompactSpec.build(graphs, CFG.gdf(), dense_m=12)
    pstep = jax.jit(make_predict_step(make_expander(spec)))

    ladder = plan_shape_set(graphs, 32, rungs=2, dense_m=12, compact=spec)
    ladder_full = plan_shape_set(graphs, 32, rungs=2, dense_m=12)
    got, _ = run_fast_inference(state, graphs, 32, shape_set=ladder,
                                predict_step=pstep, pack_workers=2)
    want, _ = run_fast_inference(state, graphs, 32, shape_set=ladder_full,
                                 predict_step=pstep, pack_workers=0)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    # the bucketed path accepts a spec directly (no shape set)
    got_b, _ = run_fast_inference(state, graphs, 32, buckets=2, dense_m=12,
                                  snug=True, predict_step=pstep,
                                  compact=spec, pack_workers=2)
    want_b, _ = run_fast_inference(state, graphs, 32, buckets=2, dense_m=12,
                                   snug=True, predict_step=pstep,
                                   pack_workers=0)
    np.testing.assert_allclose(got_b, want_b, rtol=1e-5, atol=1e-5)


def test_fast_inference_compact_ladder_pins_compiles():
    """Compact staging keeps the ladder's compile pin: warming each
    rung's compact program once leaves the jit cache at len(shape_set),
    and a full pipelined run adds NOTHING — the parallel packers and the
    buffer pool never perturb traced shapes."""
    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.serve.shapes import plan_shape_set

    graphs = load_synthetic_mp(120, CFG, seed=13)
    state = _tiny_state(graphs)
    spec = CompactSpec.build(graphs, CFG.gdf(), dense_m=12)
    ladder = plan_shape_set(graphs, 32, rungs=2, dense_m=12, compact=spec)
    pstep = jax.jit(make_predict_step(make_expander(spec)))

    for shape in ladder:
        np.asarray(pstep(state, ladder.pack([graphs[0]], shape=shape)))
    assert pstep._cache_size() == len(ladder)

    run_fast_inference(state, graphs, 32, shape_set=ladder,
                       predict_step=pstep, pack_workers=3)
    assert pstep._cache_size() == len(ladder)  # zero fresh traces


def test_fast_inference_multidev_bit_exact_vs_single():
    """ISSUE 5: round-robining the windowed dispatch across the 8
    virtual devices is a pure placement change — identical batches
    through the identical program give BIT-identical outputs vs the
    single-device loop, across the ladder+compact path and the legacy
    multi-bucket full-fidelity path (ragged 157-graph tail, input-order
    restoration), with and without the parallel pack pipeline. (The
    full-fidelity LADDER form is covered per-device by the serve warmup
    tests and the buffer-fence stress below.)"""
    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.serve.shapes import plan_shape_set

    devices = jax.devices()
    assert len(devices) == 8  # conftest forces the 8-device CPU mesh
    graphs = load_synthetic_mp(157, CFG, seed=9)
    state = _tiny_state(graphs)
    spec = CompactSpec.build(graphs, CFG.gdf(), dense_m=12)
    ladder = plan_shape_set(graphs, 32, rungs=2, dense_m=12, compact=spec)
    pstep = jax.jit(make_predict_step(make_expander(spec)))

    single, _ = run_fast_inference(state, graphs, 32, shape_set=ladder,
                                   predict_step=pstep, pack_workers=0)
    multi, _ = run_fast_inference(state, graphs, 32, shape_set=ladder,
                                  predict_step=pstep, pack_workers=3,
                                  devices=devices)
    np.testing.assert_array_equal(single, multi)

    # legacy multi-bucket path: full-fidelity packing, input-order
    # restoration across buckets under the round-robin
    bsingle, _ = run_fast_inference(state, graphs, 32, buckets=3,
                                    dense_m=12, snug=True,
                                    predict_step=pstep)
    bmulti, _ = run_fast_inference(state, graphs, 32, buckets=3,
                                   dense_m=12, snug=True,
                                   predict_step=pstep, devices=devices)
    np.testing.assert_array_equal(bsingle, bmulti)


def test_fast_inference_multidev_trace_count_independent_of_devices():
    """The ISSUE-5 compile pin: the number of TRACED programs is
    len(shape_set) x staging forms, independent of the device count (the
    jit trace cache keys on abstract values, not devices); XLA builds
    one executable per (program, device) at the first multidev pass and
    a second pass adds NOTHING."""
    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.serve.shapes import plan_shape_set

    devices = jax.devices()
    graphs = load_synthetic_mp(157, CFG, seed=9)
    state = _tiny_state(graphs)
    spec = CompactSpec.build(graphs, CFG.gdf(), dense_m=12)
    ladder = plan_shape_set(graphs, 32, rungs=2, dense_m=12, compact=spec)
    base = make_predict_step(make_expander(spec))

    def counting_jit():
        traces = [0]

        def counting_body(state, batch):
            traces[0] += 1  # runs once per TRACE, never per execution
            return base(state, batch)

        return jax.jit(counting_body), traces

    p1, t1 = counting_jit()
    want, _ = run_fast_inference(state, graphs, 32, shape_set=ladder,
                                 predict_step=p1)
    p8, t8 = counting_jit()
    got, _ = run_fast_inference(state, graphs, 32, shape_set=ladder,
                                predict_step=p8, devices=devices)
    # THE pin: the 8-device run traces exactly what the single-device
    # run traces (one program per dispatched shape — never per device)
    assert t8[0] == t1[0] >= 1
    assert t8[0] <= len(ladder)
    executables = p8._cache_size()
    assert executables <= t8[0] * len(devices)
    # a second full pass must add neither traces nor executables
    again, _ = run_fast_inference(state, graphs, 32, shape_set=ladder,
                                  predict_step=p8, devices=devices)
    assert t8[0] == t1[0]
    assert p8._cache_size() == executables
    np.testing.assert_array_equal(want, got)
    np.testing.assert_array_equal(got, again)


def test_fast_inference_multidev_buffer_fence_per_device(monkeypatch):
    """The per-device buffer-release contract under stress: shrink the
    in-flight window to 2 so pooled compact staging buffers recycle
    constantly across 8 devices and 3 packer threads — any release
    before the owning device's fence proved its dispatch done would
    corrupt an in-flight batch and break bit-exactness. A spy pool
    verifies recycling actually engaged (the contract was exercised,
    not vacuously passed)."""
    import cgnn_tpu.train.infer as infer_mod
    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.data.pipeline import BufferPool
    from cgnn_tpu.serve.shapes import plan_shape_set

    graphs = load_synthetic_mp(157, CFG, seed=9)
    state = _tiny_state(graphs, batch_size=8)
    spec = CompactSpec.build(graphs, CFG.gdf(), dense_m=12)
    ladder = plan_shape_set(graphs, 8, rungs=2, dense_m=12, compact=spec)
    pstep = jax.jit(make_predict_step(make_expander(spec)))

    want, _ = run_fast_inference(state, graphs, 8, shape_set=ladder,
                                 predict_step=pstep, pack_workers=0)

    pools = []
    real_pool = BufferPool

    def spy_pool(*a, **k):
        pools.append(real_pool(*a, **k))
        return pools[-1]

    # window 2 + 4 devices over ~20 batches: every device's fence fires
    # repeatedly, so released buffers are re-acquired while other
    # devices' dispatches are still in flight. engine="threads": the
    # pooled-buffer recycle contract belongs to the per-device engine —
    # the mesh engine (the multi-device default since ISSUE 10) packs
    # fresh stacks and never touches the pool
    monkeypatch.setattr(infer_mod, "_WINDOW", 2)
    monkeypatch.setattr(infer_mod, "BufferPool", spy_pool)
    got, _ = run_fast_inference(state, graphs, 8, shape_set=ladder,
                                predict_step=pstep, pack_workers=3,
                                devices=jax.devices()[:4],
                                engine="threads")
    np.testing.assert_array_equal(want, got)
    assert pools and pools[0].reused > 0  # buffers really recycled


def test_fast_inference_single_bucket_small():
    graphs = load_synthetic_mp(20, CFG, seed=6)
    model = CrystalGraphConvNet(atom_fea_len=8, n_conv=1, h_fea_len=16,
                                dense_m=12)
    nc, ec = capacities_for(graphs, 8, dense_m=12, snug=True)
    example = next(batch_iterator(graphs, 8, nc, ec, dense_m=12, in_cap=0,
                                  snug=True))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(0),
    )
    preds, _ = run_fast_inference(state, graphs, 8, dense_m=12)
    assert preds.shape == (20, 1)
    assert np.isfinite(preds).all()
