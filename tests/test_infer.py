"""predict.py fast path (train/infer.py): bucketed pipelined inference
must return predictions in input order, identical to the naive
batch-at-a-time loop (eval mode is batch-composition-independent)."""

import jax
import numpy as np

from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
from cgnn_tpu.data.graph import batch_iterator, capacities_for
from cgnn_tpu.models import CrystalGraphConvNet
from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
from cgnn_tpu.train.infer import run_fast_inference
from cgnn_tpu.train.step import make_predict_step

CFG = FeaturizeConfig(radius=6.0, max_num_nbr=12)


def test_fast_inference_order_and_values():
    graphs = load_synthetic_mp(160, CFG, seed=5)
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=32,
                                dense_m=12)
    nc, ec = capacities_for(graphs, 32, dense_m=12, snug=True)
    example = next(batch_iterator(graphs, 32, nc, ec, dense_m=12, in_cap=0,
                                  snug=True))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(3),
    )

    # reference: naive single-bucket ladder loop, fetch per batch
    pstep = jax.jit(make_predict_step())
    nc_l, ec_l = capacities_for(graphs, 32, dense_m=12)
    want = []
    for b in batch_iterator(graphs, 32, nc_l, ec_l, dense_m=12, in_cap=0):
        out = np.asarray(jax.device_get(pstep(state, b)))
        want.append(out[: int(np.asarray(b.graph_mask).sum())])
    want = np.concatenate(want)

    got, rate = run_fast_inference(state, graphs, 32, buckets=3, dense_m=12,
                                   snug=True)
    assert rate > 0
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_fast_inference_single_bucket_small():
    graphs = load_synthetic_mp(20, CFG, seed=6)
    model = CrystalGraphConvNet(atom_fea_len=8, n_conv=1, h_fea_len=16,
                                dense_m=12)
    nc, ec = capacities_for(graphs, 8, dense_m=12, snug=True)
    example = next(batch_iterator(graphs, 8, nc, ec, dense_m=12, in_cap=0,
                                  snug=True))
    state = create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(0),
    )
    preds, _ = run_fast_inference(state, graphs, 8, dense_m=12)
    assert preds.shape == (20, 1)
    assert np.isfinite(preds).all()
