"""On-disk trajectory datasets (BASELINE config #5's file-based half).

VERDICT r3 next-step #3: the force task's front door. Covers both npz key
conventions, the gas-phase vacuum-box featurization, leak-aware splitting,
and the train.py -> predict.py cycle from disk (subprocess).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from cgnn_tpu.data.dataset import FeaturizeConfig
from cgnn_tpu.data.trajectory import (
    is_trajectory_path,
    load_trajectory_npz,
    load_trajectory_root,
    regroup_by_trajectory,
    save_trajectory_npz,
    split_trajectory_groups,
    trajectory_graphs,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "md")


def test_native_npz_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    pos = rng.normal(size=(5, 4, 3)).astype(np.float32)
    Z = np.array([1, 6, 8, 8], np.int32)
    E = rng.normal(size=5).astype(np.float32)
    F = rng.normal(size=(5, 4, 3)).astype(np.float32)
    lat = np.diag([9.0, 9.0, 9.0]).astype(np.float32)
    p = str(tmp_path / "t.npz")
    save_trajectory_npz(p, pos, Z, E, F, lattice=lat)
    d = load_trajectory_npz(p)
    np.testing.assert_allclose(d["positions"], pos, rtol=1e-6)
    np.testing.assert_array_equal(d["numbers"], Z)
    np.testing.assert_allclose(d["energy"], E, rtol=1e-6)
    np.testing.assert_allclose(d["forces"], F, rtol=1e-6)
    assert d["lattice"].shape == (5, 3, 3)  # [3,3] broadcast to per-frame


def test_md17_convention_fixture_loads():
    d = load_trajectory_npz(os.path.join(FIXTURES, "lj-md17.npz"))
    t, n = d["positions"].shape[:2]
    assert (t, n) == (26, 6)
    assert d["energy"].shape == (26,)  # sGDML [T,1] flattened
    assert d["forces"].shape == (26, 6, 3)
    assert d["lattice"] is None


def test_bad_npz_rejected(tmp_path):
    p = str(tmp_path / "bad.npz")
    np.savez(p, stuff=np.zeros(3))
    with pytest.raises(ValueError, match="unrecognized trajectory keys"):
        load_trajectory_npz(p)
    p2 = str(tmp_path / "bad2.npz")
    np.savez(p2, positions=np.zeros((4, 3, 3)), numbers=np.zeros(3),
             energy=np.zeros(4), forces=np.zeros((4, 2, 3)))
    with pytest.raises(ValueError, match="forces shape"):
        load_trajectory_npz(p2)
    p3 = str(tmp_path / "bad3.npz")
    np.savez(p3, positions=np.zeros((4, 3, 3)), numbers=np.zeros(3),
             energy=np.zeros(2), forces=np.zeros((4, 3, 3)))
    with pytest.raises(ValueError, match="energies for 4 frames"):
        load_trajectory_npz(p3)


def test_vacuum_box_is_exactly_open_boundary():
    """Gas-phase featurization: every edge must connect atoms directly
    (offset 0) with the plain Cartesian distance — no periodic-image
    contamination from the synthesized box."""
    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = trajectory_graphs(os.path.join(FIXTURES, "lj-md17.npz"), cfg)
    assert len(graphs) == 26
    g = graphs[0]
    assert g.forces is not None and g.positions is not None
    np.testing.assert_array_equal(g.offsets, 0)
    direct = np.linalg.norm(
        g.positions[g.neighbors] - g.positions[g.centers], axis=1
    )
    np.testing.assert_allclose(g.distances, direct, rtol=1e-5, atol=1e-5)
    assert g.distances.max() <= 6.0 + 1e-6


def test_periodic_fixture_keeps_lattice_and_forces():
    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = trajectory_graphs(
        os.path.join(FIXTURES, "lj-periodic.npz"), cfg
    )
    assert len(graphs) == 30
    g = graphs[3]
    assert g.cif_id == "lj-periodic/00003"
    assert g.lattice is not None and g.forces.shape == (8, 3)
    # periodic neighbors exist (nonzero image offsets somewhere)
    assert np.abs(np.concatenate([h.offsets for h in graphs])).max() >= 1


def test_load_root_groups_by_file():
    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    groups = load_trajectory_root(FIXTURES, cfg)
    assert len(groups) == 3
    stems = {grp[0].cif_id.rsplit("/", 1)[0] for grp in groups}
    assert stems == {"lj-md17", "lj-periodic", "lj-periodic-b"}


def test_split_by_trajectory_is_atomic():
    """>= 3 trajectories: no trajectory spans two splits; none empty."""
    groups = [[f"a/{i}" for i in range(50)], [f"b/{i}" for i in range(20)],
              [f"c/{i}" for i in range(20)], [f"d/{i}" for i in range(10)]]
    train, val, test = split_trajectory_groups(groups, 0.6, 0.2, seed=3)
    assert train and val and test
    assert len(train) + len(val) + len(test) == 100
    for split in (train, val, test):
        stems = {x.split("/")[0] for x in split}
        for other in (train, val, test):
            if other is not split:
                assert not (stems & {x.split("/")[0] for x in other})


def test_split_zero_val_ratio_gets_no_trajectory():
    """val_ratio=0 must not lose a whole trajectory to val (advisor r4)."""
    groups = [[f"{c}/{i}" for i in range(20)] for c in "abcde"]
    train, val, test = split_trajectory_groups(groups, 0.8, 0.0, seed=1)
    assert val == []
    assert len(train) + len(test) == 100
    assert test  # test quota is 0.2 > 0, so it is still seeded


def test_split_warns_on_large_ratio_deviation():
    """Very unequal trajectories: realized fractions can be a whole
    trajectory off the quota — that must come with a warning."""
    groups = [[f"big/{i}" for i in range(70)], [f"m/{i}" for i in range(10)],
              [f"s/{i}" for i in range(10)], [f"t/{i}" for i in range(10)]]
    with pytest.warns(UserWarning, match="deviates from requested"):
        split_trajectory_groups(groups, 0.34, 0.33, seed=0)


def test_split_contiguous_for_few_trajectories():
    """1-2 trajectories: contiguous time blocks, train = prefix."""
    grp = [f"a/{i:03d}" for i in range(100)]
    train, val, test = split_trajectory_groups([grp], 0.8, 0.1, seed=0)
    assert train == grp[:80] and val == grp[80:90] and test == grp[90:]


def test_regroup_by_trajectory_from_ids():
    class G:
        def __init__(self, cid):
            self.cif_id = cid

    gs = [G("a/1"), G("b/1"), G("a/2"), G("b/2"), G("b/3")]
    groups = regroup_by_trajectory(gs)
    assert sorted(len(g) for g in groups) == [2, 3]
    assert regroup_by_trajectory([G("noslash")]) is None


def test_is_trajectory_path(tmp_path):
    assert is_trajectory_path(FIXTURES)
    assert is_trajectory_path(os.path.join(FIXTURES, "lj-md17.npz"))
    assert not is_trajectory_path(str(tmp_path))  # empty dir
    assert not is_trajectory_path(str(tmp_path / "missing.npz"))


def test_force_data_parallel_cli(tmp_path):
    """--task force --data-parallel over virtual devices: the composite
    loss's nested differentiation under shard_map, dense default layout,
    driven exactly as a user would from the CLI."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    })
    p = subprocess.run(
        [sys.executable, "train.py", FIXTURES, "--task", "force",
         "--device", "cpu", "--epochs", "1", "--optim", "Adam", "-b", "8",
         "--radius", "5", "--data-parallel",
         "--ckpt-dir", str(tmp_path / "ckpt"), "--print-freq", "0"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "dp x2" in p.stdout, p.stdout
    assert "force_mae" in p.stdout


def test_force_train_predict_from_disk_cli(tmp_path):
    """Config #5 end to end FROM DISK: train.py on the fixture trajectory
    directory, then predict.py on one fixture file -> CSV + forces npz.
    Closes the VERDICT r3 'partial' (train.py used to refuse any on-disk
    force dataset)."""
    ckpt = str(tmp_path / "ckpt")
    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""})
    p1 = subprocess.run(
        [sys.executable, "train.py", FIXTURES, "--task", "force",
         "--device", "cpu", "--epochs", "2", "--optim", "Adam", "-b", "16",
         "--radius", "5", "--ckpt-dir", ckpt, "--print-freq", "0"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert p1.returncode == 0, p1.stderr[-2000:]
    assert "loaded 3 trajectories (76 frames)" in p1.stdout
    assert "trajectory-aware split" in p1.stdout
    assert "** test force_mae" in p1.stdout.replace(":", "")

    out_csv = str(tmp_path / "preds.csv")
    p2 = subprocess.run(
        [sys.executable, "predict.py", ckpt,
         os.path.join(FIXTURES, "lj-md17.npz"),
         "--device", "cpu", "-b", "16", "--out", out_csv],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert p2.returncode == 0, p2.stderr[-2000:]
    rows = open(out_csv).read().strip().splitlines()
    assert len(rows) == 26
    cid, target, pred = rows[0].split(",")
    assert cid == "lj-md17/00000"
    float(target), float(pred)
    forces = np.load(out_csv + ".forces.npz")
    assert forces["forces_0"].shape == (6, 3)
