"""Oracle parity harness (SURVEY.md §4.3): JAX model vs torch-CPU CGCNN.

Identical weights, identical graphs -> forward and gradients must agree.
Structures are chosen so every atom has >= max_num_nbr neighbors in radius
(small cells + periodic images guarantee it), so the oracle's dense [N, M]
layout and our flat COO layout describe the same edge set and the batch
contains no padding — making train-mode BatchNorm statistics comparable too.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch

from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.data.graph import pack_graphs
from cgnn_tpu.models import CrystalGraphConvNet
from tests.oracle.torch_cgcnn import TorchCGCNN, variables_from_torch

ATOM_FEA_LEN = 24
N_CONV = 2
H_FEA_LEN = 32
N_H = 2
MAX_NBR = 12


# function scope: the train-mode test mutates the torch oracle's running
# stats in place, so sharing one oracle across tests would be order-dependent
@pytest.fixture()
def setup():
    cfg = FeaturizeConfig(radius=8.0, max_num_nbr=MAX_NBR)
    graphs = load_synthetic(4, cfg, seed=11, max_atoms=8)
    # dense-layout precondition: every atom saturates max_num_nbr
    for g in graphs:
        counts = np.bincount(g.centers, minlength=g.num_nodes)
        assert np.all(counts == MAX_NBR), "test structures must be fully coordinated"

    total_nodes = sum(g.num_nodes for g in graphs)
    total_edges = sum(g.num_edges for g in graphs)
    batch = pack_graphs(graphs, total_nodes, total_edges, len(graphs))

    # oracle inputs: dense [N, M] from the same flat edge list
    nbr_idx = np.asarray(batch.centers).reshape(total_nodes, MAX_NBR)
    assert np.all(nbr_idx == np.arange(total_nodes)[:, None]), "edges sorted by center"
    nbr_fea_idx = np.asarray(batch.neighbors).reshape(total_nodes, MAX_NBR)
    nbr_fea = np.asarray(batch.edges).reshape(total_nodes, MAX_NBR, -1)
    crystal_atom_idx = []
    off = 0
    for g in graphs:
        crystal_atom_idx.append(torch.arange(off, off + g.num_nodes))
        off += g.num_nodes

    torch.manual_seed(0)
    oracle = TorchCGCNN(
        orig_atom_fea_len=batch.nodes.shape[1],
        nbr_fea_len=nbr_fea.shape[-1],
        atom_fea_len=ATOM_FEA_LEN,
        n_conv=N_CONV,
        h_fea_len=H_FEA_LEN,
        n_h=N_H,
    ).double()

    model = CrystalGraphConvNet(
        atom_fea_len=ATOM_FEA_LEN, n_conv=N_CONV, h_fea_len=H_FEA_LEN, n_h=N_H,
        dtype=jnp.float64,
    )
    variables = variables_from_torch(oracle, model.init(jax.random.key(0), batch))
    t_inputs = (
        torch.from_numpy(np.asarray(batch.nodes, np.float64)),
        torch.from_numpy(nbr_fea.astype(np.float64)),
        torch.from_numpy(nbr_fea_idx.astype(np.int64)),
        crystal_atom_idx,
    )
    return graphs, batch, oracle, model, variables, t_inputs


class TestOracleParity:
    def test_forward_eval(self, setup):
        graphs, batch, oracle, model, variables, t_inputs = setup
        oracle.eval()
        with torch.no_grad():
            ref = oracle(*t_inputs).numpy()
        out = np.asarray(model.apply(variables, batch))
        np.testing.assert_allclose(out[: len(graphs)], ref, rtol=1e-9, atol=1e-9)

    def test_forward_train_batchstats(self, setup):
        graphs, batch, oracle, model, variables, t_inputs = setup
        oracle.train()
        ref = oracle(*t_inputs).detach().numpy()
        out, updated = model.apply(
            variables, batch, train=True, mutable=["batch_stats"]
        )
        np.testing.assert_allclose(
            np.asarray(out)[: len(graphs)], ref, rtol=1e-8, atol=1e-8
        )
        # running stats updated identically (torch mutated oracle in-place)
        for i, conv in enumerate(oracle.convs):
            for bn_name, bn in (("bn1", conv.bn1), ("bn2", conv.bn2)):
                got = updated["batch_stats"][f"conv_{i}"][bn_name]
                np.testing.assert_allclose(
                    got["mean"], bn.running_mean.numpy(), rtol=1e-8, atol=1e-10
                )
                np.testing.assert_allclose(
                    got["var"], bn.running_var.numpy(), rtol=1e-8, atol=1e-10
                )

    def test_gradient_parity(self, setup):
        graphs, batch, oracle, model, variables, t_inputs = setup
        targets = np.linspace(-1.0, 1.0, len(graphs))

        oracle.train()
        oracle.zero_grad()
        ref_out = oracle(*t_inputs)
        loss = ((ref_out[:, 0] - torch.from_numpy(targets)) ** 2).mean()
        loss.backward()

        def loss_fn(params):
            out, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                batch, train=True, mutable=["batch_stats"],
            )
            err = out[: len(graphs), 0] - jnp.array(targets)
            return jnp.mean(err**2)

        grads = jax.grad(loss_fn)(variables["params"])
        pairs = [
            (grads["embedding"]["kernel"], oracle.embedding.weight.grad.numpy().T),
            (grads["conv_0"]["fc_full"]["kernel"], oracle.convs[0].fc_full.weight.grad.numpy().T),
            (grads["conv_0"]["bn1"]["scale"], oracle.convs[0].bn1.weight.grad.numpy()),
            (grads["fc_out"]["bias"], oracle.fc_out.bias.grad.numpy()),
        ]
        for got, ref in pairs:
            np.testing.assert_allclose(got, ref, rtol=1e-7, atol=1e-9)


class TestMaskedOracleParity:
    """Under-coordinated structures (VERDICT r2 #4): the masked oracle's
    dense [N, M] padding slots vs the framework's flat COO edges must be
    numerically identical — forward, train-mode BN statistics included."""

    def _build(self):
        from cgnn_tpu.data.dataset import load_synthetic_mp

        # radius 4.0: ~2/3 of atoms under-coordinated in the MP-like
        # distribution (radius 6 saturates max_num_nbr, masking nothing)
        cfg = FeaturizeConfig(radius=4.0, max_num_nbr=MAX_NBR)
        graphs = load_synthetic_mp(6, cfg, seed=7)
        counts = np.concatenate([
            np.bincount(g.centers, minlength=g.num_nodes) for g in graphs
        ])
        assert counts.min() < MAX_NBR, "need under-coordination to test"

        total_n = sum(g.num_nodes for g in graphs)
        total_e = sum(g.num_edges for g in graphs)
        batch = pack_graphs(graphs, total_n, total_e, len(graphs))

        # dense [N, M] views with padding mask (shared helper, offset here)
        from cgnn_tpu.data.graph import dense_neighbor_views

        gdim = graphs[0].edge_fea.shape[1]
        nbr = np.zeros((total_n, MAX_NBR, gdim))
        idx = np.tile(np.arange(total_n)[:, None], (1, MAX_NBR))
        mask = np.zeros((total_n, MAX_NBR))
        crystal_atom_idx, off = [], 0
        for g in graphs:
            gn, gi, gm = dense_neighbor_views(g, MAX_NBR)
            sl = slice(off, off + g.num_nodes)
            nbr[sl], mask[sl] = gn, gm
            # self-loop padding keeps each node's own (offset) index
            idx[sl] = gi + off
            crystal_atom_idx.append(torch.arange(off, off + g.num_nodes))
            off += g.num_nodes

        torch.manual_seed(1)
        oracle = TorchCGCNN(
            orig_atom_fea_len=batch.nodes.shape[1], nbr_fea_len=gdim,
            atom_fea_len=ATOM_FEA_LEN, n_conv=N_CONV,
            h_fea_len=H_FEA_LEN, n_h=N_H,
        ).double()
        model = CrystalGraphConvNet(
            atom_fea_len=ATOM_FEA_LEN, n_conv=N_CONV, h_fea_len=H_FEA_LEN,
            n_h=N_H, dtype=jnp.float64,
        )
        variables = variables_from_torch(
            oracle, model.init(jax.random.key(0), batch))
        t_inputs = (
            torch.from_numpy(np.asarray(batch.nodes, np.float64)),
            torch.from_numpy(nbr),
            torch.from_numpy(idx.astype(np.int64)),
            crystal_atom_idx,
        )
        return batch, oracle, model, variables, t_inputs, torch.from_numpy(mask)

    def test_forward_train_masked(self):
        batch, oracle, model, variables, t_inputs, mask = self._build()
        oracle.train()
        t_out = oracle(*t_inputs[:3], t_inputs[3], nbr_mask=mask)
        j_out, mutated = model.apply(
            variables, batch, train=True, mutable=["batch_stats"],
        )
        np.testing.assert_allclose(
            np.asarray(j_out)[: t_out.shape[0]],
            t_out.detach().numpy(), atol=1e-8,
        )
        # BN1 running stats updated from MASKED moments must agree
        for i in range(N_CONV):
            np.testing.assert_allclose(
                np.asarray(mutated["batch_stats"][f"conv_{i}"]["bn1"]["mean"]),
                oracle.convs[i].bn1.running_mean.detach().numpy(), atol=1e-8,
            )
            np.testing.assert_allclose(
                np.asarray(mutated["batch_stats"][f"conv_{i}"]["bn1"]["var"]),
                oracle.convs[i].bn1.running_var.detach().numpy(), atol=1e-8,
            )

    def test_forward_eval_masked(self):
        batch, oracle, model, variables, t_inputs, mask = self._build()
        oracle.eval()
        with torch.no_grad():
            t_out = oracle(*t_inputs[:3], t_inputs[3], nbr_mask=mask)
        j_out = model.apply(variables, batch, train=False)
        np.testing.assert_allclose(
            np.asarray(j_out)[: t_out.shape[0]],
            t_out.numpy(), atol=1e-8,
        )
