"""Multi-bucket batching + OC20 large-graph regime (BASELINE config #4,
SURVEY.md §5 long-context analog)."""

import numpy as np

from cgnn_tpu.data.dataset import (
    FeaturizeConfig,
    load_synthetic,
    load_synthetic_oc20,
)
from cgnn_tpu.data.graph import (
    PaddingStats,
    batch_iterator,
    bucketed_batch_iterator,
    capacities_for,
    count_batches,
)

CFG = FeaturizeConfig(radius=5.0, max_num_nbr=10)


def _mixed_graphs():
    """Bimodal size mix: small MP-like crystals + large OC20-like slabs."""
    small = load_synthetic(24, CFG, seed=0, max_atoms=8)
    big = load_synthetic_oc20(8, CFG, seed=1)
    return small + big


def test_dense_layout_preserves_edge_set_and_invariants():
    """Dense slot packing: node n owns slots [n*M, (n+1)*M); the flat-COO
    invariants (sorted centers, masked padding) still hold, and the
    (center, neighbor, feature) edge multiset is exactly the flat one's."""
    graphs = _mixed_graphs()
    m = CFG.max_num_nbr
    nc, ec = capacities_for(graphs, 8, dense_m=m)
    assert ec == nc * m
    # same node_cap and non-binding flat edge_cap -> identical batch splits
    flat = list(batch_iterator(graphs, 8, nc, nc * m))
    dense = list(batch_iterator(graphs, 8, nc, ec, dense_m=m))
    assert len(flat) == len(dense)
    for fb, db in zip(flat, dense):
        c = np.asarray(db.centers)
        assert (np.diff(c) >= 0).all()  # sortedness invariant
        assert (c == np.arange(ec) // m).all()  # dense slot ownership
        mask = np.asarray(db.edge_mask) > 0
        # real edges per node never exceed M, and the edge multiset matches
        def key(b, sel):
            flat_edges = np.asarray(b.flat_edges)
            return sorted(
                zip(
                    np.asarray(b.centers)[sel].tolist(),
                    np.asarray(b.neighbors)[sel].tolist(),
                    flat_edges[sel].sum(axis=1).round(5).tolist(),
                )
            )
        assert key(db, mask) == key(fb, np.asarray(fb.edge_mask) > 0)
        # masked padding slots are self-loops on their owning node
        assert (np.asarray(db.neighbors)[~mask] == c[~mask]).all()


def test_dense_model_matches_flat_model():
    """Same graphs, same params: the dense-layout model must reproduce the
    flat-COO model's outputs and gradients (layout is not semantics)."""
    import jax
    import jax.numpy as jnp

    from cgnn_tpu.models import CrystalGraphConvNet

    graphs = load_synthetic(12, CFG, seed=3)
    m = CFG.max_num_nbr
    fnc, fec = capacities_for(graphs, 12)
    dnc, dec = capacities_for(graphs, 12, dense_m=m)
    fb = next(batch_iterator(graphs, 12, fnc, fec))
    db = next(batch_iterator(graphs, 12, dnc, dec, dense_m=m))
    flat_model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=24)
    dense_model = CrystalGraphConvNet(
        atom_fea_len=16, n_conv=2, h_fea_len=24, dense_m=m
    )
    variables = flat_model.init(jax.random.key(0), fb)

    out_f = flat_model.apply(variables, fb)
    out_d = dense_model.apply(variables, db)
    np.testing.assert_allclose(
        np.asarray(out_f), np.asarray(out_d), rtol=1e-5, atol=1e-5
    )

    def loss(params, model, batch):
        out, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            batch, train=True, mutable=["batch_stats"],
        )
        return jnp.sum(out ** 2)

    gf = jax.grad(loss)(variables["params"], flat_model, fb)
    gd = jax.grad(loss)(variables["params"], dense_model, db)
    for a, b in zip(jax.tree_util.tree_leaves(gf), jax.tree_util.tree_leaves(gd)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_transpose_slots_invariants():
    """The (two-tier) transpose is exact: every real edge slot appears
    exactly once across tier-1 in_slots + the overflow COO, each in the
    row/entry of the node it references; padding entries are masked."""
    graphs = _mixed_graphs()
    m = CFG.max_num_nbr
    nc, ec = capacities_for(graphs, 8, dense_m=m)
    for b in batch_iterator(graphs, 8, nc, ec, dense_m=m):
        assert b.in_slots is not None and b.in_mask is not None
        assert b.in_slots.shape == (nc * m,)  # stored flat (pack_graphs)
        assert b.in_mask.shape == (nc, m)
        real = np.nonzero(np.asarray(b.edge_mask) > 0)[0]
        listed = np.asarray(b.in_slots).reshape(nc, m)[
            np.asarray(b.in_mask) > 0]
        rows, _ = np.nonzero(np.asarray(b.in_mask) > 0)
        over = np.asarray(b.over_mask) > 0
        listed = np.concatenate([listed, np.asarray(b.over_slots)[over]])
        rows = np.concatenate([rows, np.asarray(b.over_nodes)[over]])
        assert sorted(listed.tolist()) == sorted(real.tolist())
        np.testing.assert_array_equal(
            np.asarray(b.neighbors)[listed], rows
        )
        # overflow list is node-sorted (the scatter's unchecked promise)
        assert np.all(np.diff(np.asarray(b.over_nodes)) >= 0)


def test_transpose_backward_matches_plain_gather():
    """The scatter-free gather backward (gather_transpose) must produce the
    same gradients as autodiff through the plain gather."""
    import jax
    import jax.numpy as jnp

    from cgnn_tpu.models import CrystalGraphConvNet

    graphs = load_synthetic(12, CFG, seed=5)
    m = CFG.max_num_nbr
    nc, ec = capacities_for(graphs, 12, dense_m=m)
    db = next(batch_iterator(graphs, 12, nc, ec, dense_m=m))
    stripped = db.replace(in_slots=None, in_mask=None)
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=24,
                                dense_m=m)
    variables = model.init(jax.random.key(0), stripped)

    def loss(params, batch):
        out, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            batch, train=True, mutable=["batch_stats"],
        )
        return jnp.sum(out ** 2)

    g_plain = jax.grad(loss)(variables["params"], stripped)
    g_transpose = jax.grad(loss)(variables["params"], db)
    # f32 reassociation tolerance: the linear_call transpose (r4) builds a
    # slightly different accumulation graph than custom_vjp did; semantic
    # exactness is pinned separately in f64 (max |diff| 2.8e-14 on this
    # exact setup) so 5e-6 absolute here is pure roundoff headroom
    for a, b in zip(
        jax.tree_util.tree_leaves(g_plain),
        jax.tree_util.tree_leaves(g_transpose),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=5e-6
        )


def test_over_cap_overrun_splits_batch_instead_of_dying():
    """A 3-sigma shuffle-tail over_cap overrun must split the offending
    batch (same compiled shape) with a warning, not abort the run; a
    single unsplittable graph still raises. (advisor r3; the recovery is
    caught BY TYPE — TransposeOverflowError — not by message text.)"""
    import warnings

    import pytest

    from cgnn_tpu.data.graph import CrystalGraph, TransposeOverflowError

    def star_graph(n, cid):
        # every node sends 2 edges to node 0 -> in-degree(0) = 2n, far
        # above dense_m=2, forcing (2n - 2) overflow entries per graph
        centers = np.repeat(np.arange(n, dtype=np.int32), 2)
        neighbors = np.zeros(2 * n, np.int32)
        return CrystalGraph(
            atom_fea=np.ones((n, 4), np.float32),
            edge_fea=np.ones((2 * n, 3), np.float32),
            centers=centers,
            neighbors=neighbors,
            target=np.zeros(1, np.float32),
            cif_id=cid,
        )

    graphs = [star_graph(5, "s0"), star_graph(5, "s1")]
    # each graph overflows 8 entries; over_cap=8 fits one graph per batch
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        batches = list(batch_iterator(
            graphs, 2, node_cap=16, edge_cap=32, dense_m=2, over_cap=8
        ))
    assert len(batches) == 2  # split in half, same capacities
    assert any("splitting it in half" in str(w.message) for w in caught)
    for b in batches:
        assert np.shape(b.nodes) == (16, 4)
        assert int((np.asarray(b.over_mask) > 0).sum()) == 8
    # an unsplittable single graph re-raises the typed error
    with pytest.raises(TransposeOverflowError):
        list(batch_iterator(
            [star_graph(8, "big")], 1, node_cap=16, edge_cap=32,
            dense_m=2, over_cap=8,
        ))


def test_transpose_in_cap_overflow_raises():
    from cgnn_tpu.data.graph import pack_graphs

    graphs = load_synthetic(4, CFG, seed=0, max_atoms=8)
    m = CFG.max_num_nbr
    nc, ec = capacities_for(graphs, 4, dense_m=m)
    try:
        pack_graphs(graphs, nc, ec, 4, dense_m=m, in_cap=1)
    except ValueError as e:
        assert "in-degree" in str(e)
    else:
        raise AssertionError("expected in_cap overflow to raise")


def test_oc20_graphs_are_large():
    graphs = load_synthetic_oc20(8, CFG, seed=0)
    sizes = [g.num_nodes for g in graphs]
    assert min(sizes) >= 20
    assert max(sizes) >= 50  # the large-graph regime config #4 targets


def test_count_batches_matches_iterator():
    graphs = _mixed_graphs()
    nc, ec = capacities_for(graphs, 8)
    n = sum(1 for _ in batch_iterator(graphs, 8, nc, ec))
    assert count_batches(graphs, 8, nc, ec) == n
    # and the naive len//batch_size estimate is indeed wrong here
    assert n >= len(graphs) // 8


def test_bucketed_iterator_yields_every_graph_once():
    graphs = _mixed_graphs()
    for shuffle in (False, True):
        ids = []
        for batch in bucketed_batch_iterator(
            graphs, 8, 3, shuffle=shuffle, rng=np.random.default_rng(0)
        ):
            node_graph = np.asarray(batch.node_graph)
            node_mask = np.asarray(batch.node_mask) > 0
            for k in range(int(np.asarray(batch.graph_mask).sum())):
                ids.append(int(((node_graph == k) & node_mask).sum()))
        assert len(ids) == len(graphs)
        assert sorted(ids) == sorted(g.num_nodes for g in graphs)


def test_bucketed_iterator_bounds_compiled_shapes():
    graphs = _mixed_graphs()
    stats = PaddingStats()
    for _ in bucketed_batch_iterator(graphs, 8, 3, stats=stats):
        pass
    assert 1 <= len(stats.shapes) <= 3


def test_buckets_beat_single_capacity_on_bimodal_mix():
    graphs = _mixed_graphs()
    nc, ec = capacities_for(graphs, 8)
    single = PaddingStats()
    for b in single.wrap(batch_iterator(graphs, 8, nc, ec)):
        pass
    multi = PaddingStats()
    for _ in bucketed_batch_iterator(graphs, 8, 3, stats=multi):
        pass
    assert multi.node_efficiency > single.node_efficiency


def test_oc20_trains_end_to_end_with_buckets():
    """Slab graphs pack, batch with buckets, and loss decreases."""
    import jax

    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import fit

    graphs = load_synthetic_oc20(32, CFG, seed=2)
    train_g, val_g = graphs[:28], graphs[28:]
    norm = Normalizer.fit(np.stack([g.target for g in train_g]))
    model = CrystalGraphConvNet(atom_fea_len=32, n_conv=2, h_fea_len=32)
    nc, ec = capacities_for(train_g, 8)
    example = next(batch_iterator(train_g, 8, nc, ec))
    state = create_train_state(
        model, example, make_optimizer(optim="adam", lr=3e-3), norm,
        rng=jax.random.key(0),
    )
    state, res = fit(
        state, train_g, val_g, epochs=8, batch_size=8, buckets=2,
        print_freq=0, log_fn=lambda *_: None,
    )
    losses = [h["train"]["loss"] for h in res["history"]]
    assert losses[-1] < 0.5 * losses[0]


def test_snug_packing_efficiency_and_coverage():
    """Fill-to-capacity packing (VERDICT r2 #2): >=0.95 slot efficiency on
    the MP-like distribution, every graph packed exactly once, compiled
    shape count unchanged, count_batches in sync."""
    from cgnn_tpu.data.dataset import load_synthetic_mp
    from cgnn_tpu.data.graph import (
        PaddingStats,
        batch_iterator,
        bucketed_batch_iterator,
        capacities_for,
        count_batches,
    )

    graphs = load_synthetic_mp(512, FeaturizeConfig(radius=5.0), seed=0)
    stats = PaddingStats()
    batches = list(bucketed_batch_iterator(
        graphs, 64, 3, shuffle=True, rng=np.random.default_rng(1),
        dense_m=12, snug=True, stats=stats,
    ))
    assert stats.node_efficiency >= 0.95
    assert len(stats.shapes) <= 3
    packed = sum(int(np.asarray(b.graph_mask).sum()) for b in batches)
    assert packed == len(graphs)
    for b in batches:
        # mask consistency: real edges only on real nodes
        em = np.asarray(b.edge_mask).reshape(b.node_capacity, 12)
        nm = np.asarray(b.node_mask)
        assert not np.any(em.max(axis=1) > nm)

    nc, ec = capacities_for(graphs, 64, dense_m=12, snug=True)
    n = count_batches(graphs, 64, nc, ec, snug=True)
    assert n == len(list(batch_iterator(graphs, 64, nc, ec, dense_m=12,
                                        snug=True)))


def test_per_bucket_in_cap_tracks_bucket_skew():
    """per_bucket_in_cap (forced single-tier): the bucket containing the
    skewed hub graph gets a LARGER transpose capacity than the other
    bucket, which must stay below the dataset-wide cap — the point of the
    flag (one adsorbate-style outlier must not inflate every bucket)."""
    from cgnn_tpu.data.graph import bucketed_batch_iterator, in_degree_cap

    cfg = FeaturizeConfig(radius=5.0, max_num_nbr=8)
    graphs = load_synthetic(64, cfg, seed=2, max_atoms=6)
    # skew the LARGEST graph (lands in the top size bucket): a hub node
    # listed as neighbor by every edge -> in-degree = num_edges
    hub = max(graphs, key=lambda g: g.num_nodes)
    hub.neighbors = np.zeros_like(hub.neighbors)
    hub._max_in_degree = None
    global_cap = in_degree_cap(graphs)
    batches = list(bucketed_batch_iterator(
        graphs, 8, 2, dense_m=8, snug=True, per_bucket_in_cap=True,
    ))
    caps = {b.in_mask.shape[1] for b in batches}
    assert len(caps) == 2, caps
    assert max(caps) == global_cap  # hub bucket pays its own skew
    assert min(caps) < global_cap  # ...and the other bucket does not


def test_two_tier_transpose_backward_matches_plain_gather():
    """Two-tier (tier-1 [N, M] + overflow COO) gather_transpose gradients
    == plain-gather gradients through a full CGConv-like masked consumer,
    on graphs whose in-degree exceeds dense_m (overflow populated)."""
    import jax
    import jax.numpy as jnp

    from cgnn_tpu.data.dataset import load_synthetic_mp
    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.ops.segment import gather, gather_transpose

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(64, cfg, seed=3)
    nc, ec = capacities_for(graphs, 32, dense_m=12, snug=True)
    b = next(batch_iterator(graphs, 32, nc, ec, dense_m=12, snug=True))
    assert b.over_slots is not None
    assert int(np.asarray(b.over_mask).sum()) > 0, "no overflow exercised"

    nodes = jnp.asarray(
        np.random.default_rng(0).normal(size=(b.node_capacity, 16))
    ).astype(jnp.float32)
    emask = jnp.asarray(b.edge_mask)

    def loss_two_tier(n):
        v_j = gather_transpose(
            n, jnp.asarray(b.neighbors), jnp.asarray(b.in_slots),
            jnp.asarray(b.in_mask), jnp.asarray(b.over_slots),
            jnp.asarray(b.over_nodes), jnp.asarray(b.over_mask),
        )
        return ((v_j * emask[:, None]) ** 2).sum()

    def loss_plain(n):
        v_j = gather(n, jnp.asarray(b.neighbors))
        return ((v_j * emask[:, None]) ** 2).sum()

    g1 = jax.grad(loss_two_tier)(nodes)
    g2 = jax.grad(loss_plain)(nodes)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-5)


def test_bf16_edge_storage_packs_validates_and_trains():
    """edge_dtype=bfloat16 (train.py --bf16): packs, passes the invariant
    checker, and one train step runs with finite loss."""
    import jax

    from cgnn_tpu.data import invariants
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_train_step

    graphs = load_synthetic(16, CFG, seed=9, max_atoms=6)
    m = CFG.max_num_nbr
    nc, ec = capacities_for(graphs, 8, dense_m=m, snug=True)
    b = next(batch_iterator(graphs, 8, nc, ec, dense_m=m, snug=True,
                            edge_dtype=jax.numpy.bfloat16))
    assert b.edges.dtype == jax.numpy.bfloat16
    invariants.check_batch(b, dense_m=m)

    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                dtype=jax.numpy.bfloat16, dense_m=m)
    state = create_train_state(
        model, b, make_optimizer(optim="sgd", lr=0.01),
        Normalizer.fit(np.stack([g.target for g in graphs])),
    )
    state, metrics = jax.jit(make_train_step())(state, b)
    assert np.isfinite(float(metrics["loss_sum"]))
