"""One fleet cache (ISSUE 20): consistent-hash ring + cache contracts.

Host-side only — no jax, no model. Pins the three load-bearing
properties of the partitioned result cache:

- the ring is DETERMINISTIC across process restarts and rebalances
  INCREMENTALLY (only a removed replica's arcs re-own);
- ``ResultCache`` keeps its LRU/versioning semantics — hit-time
  ``param_version`` revalidation stays the correctness boundary no
  matter who routed the request;
- the coalescing plumbing (``RequestFuture.add_done_callback``,
  ``ResultCache.snapshot``) delivers exactly-once / tear-free reads.
"""

from __future__ import annotations

import threading

import pytest

from cgnn_tpu.fleet.cachering import CacheRing, _point
from cgnn_tpu.serve.batcher import RequestFuture
from cgnn_tpu.serve.cache import ResultCache

KEYS = [f"key-{i:04d}" for i in range(256)]


# ------------------------------------------------------------------ ring


class TestCacheRing:
    def test_deterministic_across_instances(self):
        # a restarted router process rebuilds the IDENTICAL ring: vnode
        # points derive only from (rid, index), never object identity
        a = CacheRing([0, 1, 2])
        b = CacheRing([2, 0, 1])  # insertion order must not matter
        assert [a.owner(k) for k in KEYS] == [b.owner(k) for k in KEYS]

    def test_incremental_rebalance_on_remove(self):
        ring = CacheRing([0, 1, 2])
        before = {k: ring.owner(k) for k in KEYS}
        ring.remove(1)
        after = {k: ring.owner(k) for k in KEYS}
        for k in KEYS:
            if before[k] != 1:
                # only the removed replica's arcs re-own
                assert after[k] == before[k]
            else:
                assert after[k] in (0, 2)

    def test_re_add_restores_exact_mapping(self):
        # crash + restart of one replica is a remove + add: the ring
        # must restore the ORIGINAL ownership bit-exactly (the smoke
        # leg's re-ownership assertion rides this)
        ring = CacheRing([0, 1, 2])
        before = {k: ring.owner(k) for k in KEYS}
        ring.remove(1)
        ring.add(1)
        assert {k: ring.owner(k) for k in KEYS} == before

    def test_alive_walk_skips_dead_owner(self):
        ring = CacheRing([0, 1, 2])
        owned_by_1 = [k for k in KEYS if ring.owner(k) == 1]
        assert owned_by_1  # 256 keys over 3 replicas: all own some
        for k in owned_by_1:
            fallback = ring.owner(k, alive={0, 2})
            assert fallback in (0, 2)
            # the fallback is the deterministic ring successor: the
            # same down-set always yields the same stand-in owner
            assert fallback == ring.owner(k, alive={0, 2})
        # keys NOT owned by the dead replica keep their owner
        for k in KEYS:
            if ring.owner(k) != 1:
                assert ring.owner(k, alive={0, 2}) == ring.owner(k)

    def test_empty_and_no_alive(self):
        assert CacheRing().owner("anything") is None
        ring = CacheRing([0, 1])
        assert ring.owner("k", alive=set()) is None
        assert ring.owner("k", alive={7}) is None

    def test_vnodes_validation(self):
        with pytest.raises(ValueError):
            CacheRing(vnodes=0)

    def test_membership(self):
        ring = CacheRing([3, 1])
        assert ring.members() == [1, 3]
        assert 1 in ring and 2 not in ring and len(ring) == 2
        ring.add(1)  # idempotent
        assert len(ring) == 2
        ring.remove(9)  # idempotent
        assert ring.members() == [1, 3]

    def test_arc_shares_roughly_balanced(self):
        s = CacheRing([0, 1, 2]).stats()
        assert s["points"] == 3 * s["vnodes"]
        shares = list(s["arc_share"].values())
        assert abs(sum(shares) - 1.0) < 1e-6
        # 64 vnodes/replica keeps the imbalance modest
        assert all(0.15 < x < 0.55 for x in shares)

    def test_point_is_stable(self):
        # the hash function is part of the cross-restart contract: a
        # changed _point() would silently re-own the whole keyspace on
        # a rolling upgrade. Pin one value.
        assert _point("0:0") == _point("0:0")
        assert _point("0:0") != _point("0:1")


# ----------------------------------------------------------------- cache


class TestResultCacheContracts:
    def test_capacity_one_eviction_order(self):
        c = ResultCache(capacity=1)
        c.put("a", ("row-a", "v1"))
        c.put("b", ("row-b", "v1"))  # evicts 'a'
        assert c.get("a") is None
        assert c.get("b") == ("row-b", "v1")
        assert c.snapshot() == (1, 1, 1, 1)  # hits, misses, size, cap

    def test_version_revalidation_races_put_after_swap(self):
        # a peer-fill or flush carrying PRE-swap params must never be
        # served post-swap: the cache stores (row, version) verbatim
        # and the CALLER revalidates at hit time — so a stale put stays
        # visible as stale, and a fresh put then serves
        c = ResultCache(capacity=4)
        c.put("k", ("row-old", "v1"))
        current = "v2"  # the param swap lands
        row = c.get("k")
        assert row == ("row-old", "v1")
        assert row[1] != current  # caller rejects -> recompute path
        c.put("k", ("row-new", "v2"))
        row = c.get("k")
        assert row == ("row-new", "v2") and row[1] == current

    def test_snapshot_is_tear_free_under_hammer(self):
        # hits + misses must equal total lookups at quiesce, and any
        # mid-flight snapshot must satisfy the same bookkeeping over
        # its OWN counters (the /metrics scrape reads this)
        c = ResultCache(capacity=8)
        n_threads, n_ops = 8, 500
        stop = threading.Event()
        snaps = []

        def hammer(seed: int):
            for i in range(n_ops):
                k = f"k{(seed * 7 + i) % 32}"
                if c.get(k) is None:
                    c.put(k, (i, "v"))

        def scraper():
            while not stop.is_set():
                snaps.append(c.snapshot())

        ts = [threading.Thread(target=hammer, args=(s,))
              for s in range(n_threads)]
        sc = threading.Thread(target=scraper)
        sc.start()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        stop.set()
        sc.join()
        hits, misses, size, capacity = c.snapshot()
        assert hits + misses == n_threads * n_ops
        assert size <= capacity == 8
        for h, m, sz, cap in snaps:
            assert 0 <= h + m <= n_threads * n_ops and sz <= cap


# ------------------------------------------------- coalescing primitives


class TestFutureCallbacks:
    def test_callback_fires_exactly_once_on_result(self):
        f = RequestFuture()
        fired = []
        f.add_done_callback(fired.append)
        f.set_result("x")
        f.set_result("y")  # idempotent set must not re-fire
        assert fired == [f]

    def test_callback_after_done_fires_immediately(self):
        f = RequestFuture()
        f.set_result("x")
        fired = []
        f.add_done_callback(fired.append)
        assert fired == [f]

    def test_callback_fires_on_error_too(self):
        # single-flight followers must hear about leader FAILURE as
        # loudly as success, or they hang until their own deadline
        f = RequestFuture()
        fired = []
        f.add_done_callback(fired.append)
        f.set_error(RuntimeError("boom"))
        assert fired == [f]

    def test_concurrent_add_and_set_deliver_exactly_once(self):
        for _ in range(50):
            f = RequestFuture()
            fired = []
            barrier = threading.Barrier(2)

            def setter():
                barrier.wait()
                f.set_result("x")

            def adder():
                barrier.wait()
                f.add_done_callback(fired.append)

            ts = [threading.Thread(target=setter),
                  threading.Thread(target=adder)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert fired == [f]
