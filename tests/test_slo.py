"""Metrics-truth layer tests (ISSUE 16).

The load-bearing guarantees, pinned:

- mergeable histograms: merge is associative AND commutative on the
  integer bucket counts, refuses mismatched bucket layouts, and —
  the fleet identity — merging N per-process scrapes through a REAL
  registry render -> parse cycle is bit-identical to one histogram
  fed the pooled raw observations;
- the shared exposition parser round-trips histogram families and
  REJECTS invalid ones (non-monotone cumulative counts, missing +Inf);
- the embedded time-series store: a coarse tier is exactly the fold of
  its fine-tier buckets, and memory is bounded by construction
  (per-tier ring eviction + series-cap dropping, both observable);
- the SLO engine: error-budget accounting, the multi-window burn-rate
  condition, and the alert state machine inactive -> pending ->
  firing -> resolved, including the pending clear on a blip and the
  RE-ARM (a second burst fires again, fire_count increments), with
  fire/resolve hooks invoked outside the lock.
"""

import math

import pytest

from cgnn_tpu.observe.export import MetricsRegistry, parse_prometheus_text
from cgnn_tpu.observe.hist import (
    LATENCY_MS_BOUNDS,
    Histogram,
    log_bounds,
    merge_snapshot_maps,
    quantile_from_snapshot,
    snapshot_exposition_lines,
    snapshots_from_family,
)
from cgnn_tpu.observe.slo import (
    BurnRateRule,
    SLOEngine,
    SLOObjective,
    default_rules,
)
from cgnn_tpu.observe.tsdb import TimeSeriesStore, TsdbCollector

# dyadic values: float sums are EXACT in any addition order, so the
# associativity/commutativity asserts below can demand bit equality
# on sums, not just counts
_DYADIC = [0.25, 0.5, 1.5, 2.0, 12.0, 100.5, 7000.0, 1.0e9]


def _hist_of(values, bounds=LATENCY_MS_BOUNDS) -> Histogram:
    h = Histogram(bounds)
    for v in values:
        h.observe(v)
    return h


class TestHistogramMerge:
    def test_merge_commutative(self):
        a = _hist_of(_DYADIC[:4])
        b = _hist_of(_DYADIC[4:])
        assert a.merge(b).snapshot() == b.merge(a).snapshot()

    def test_merge_associative(self):
        a = _hist_of(_DYADIC[:3])
        b = _hist_of(_DYADIC[3:6])
        c = _hist_of(_DYADIC[6:])
        left = a.merge(b).merge(c)
        right = a.merge(b.merge(c))
        assert left.snapshot() == right.snapshot()

    def test_merge_equals_pooled(self):
        parts = [_hist_of(_DYADIC[i::3]) for i in range(3)]
        merged = Histogram.merge_all(parts)
        pooled = _hist_of(_DYADIC)
        assert merged.snapshot() == pooled.snapshot()

    def test_merge_refuses_mismatched_bounds(self):
        a = Histogram(log_bounds(0.1, 100.0, 6))
        b = Histogram(log_bounds(0.1, 100.0, 3))
        with pytest.raises(ValueError, match="different bounds"):
            a.merge(b)

    def test_inputs_untouched(self):
        a = _hist_of([1.0])
        b = _hist_of([2.0])
        a.merge(b)
        assert a.count == 1 and b.count == 1

    def test_fleet_merge_via_real_render_parse_cycle(self):
        """The ISSUE-16 acceptance identity, host-side: N per-process
        registries render REAL expositions, the shared parser
        reconstructs each scrape, the fleet merge folds them — and the
        result is bit-identical (counts AND sums) to one histogram fed
        every raw observation."""
        per_replica = [_DYADIC[i::3] for i in range(3)]
        scraped_maps = []
        for values in per_replica:
            reg = MetricsRegistry(namespace="cgnn")
            h = _hist_of(values)
            reg.add_provider(
                "serve",
                lambda h=h: {"histograms": {"lat_ms_hist": h.snapshot()}})
            fams = parse_prometheus_text(reg.prometheus_text())
            assert fams["cgnn_lat_ms_hist"]["type"] == "histogram"
            scraped_maps.append(fams["cgnn_lat_ms_hist"]["histogram"])
        merged = merge_snapshot_maps(scraped_maps)
        pooled = _hist_of(_DYADIC).snapshot()
        assert merged[""] == pooled

    def test_labels_preserved_through_merge(self):
        maps = [
            {'{rung="0"}': _hist_of([1.0]).snapshot(),
             '{rung="1"}': _hist_of([8.0]).snapshot()},
            {'{rung="0"}': _hist_of([2.0]).snapshot()},
        ]
        merged = merge_snapshot_maps(maps)
        assert merged['{rung="0"}']["count"] == 2
        assert merged['{rung="1"}']["count"] == 1  # never cross-rung


class TestExpositionRoundTrip:
    def test_snapshot_exposition_round_trip_exact(self):
        snap = _hist_of(_DYADIC).snapshot()
        lines = ["# TYPE lat_ms_hist histogram"]
        lines += snapshot_exposition_lines("lat_ms_hist", snap)
        fams = parse_prometheus_text("\n".join(lines) + "\n")
        back = fams["lat_ms_hist"]["histogram"][""]
        assert back == snap  # bounds, counts, count, AND float sum

    def test_monotonicity_violation_rejected(self):
        text = (
            "# TYPE bad_hist histogram\n"
            'bad_hist_bucket{le="1.0"} 5\n'
            'bad_hist_bucket{le="2.0"} 3\n'
            'bad_hist_bucket{le="+Inf"} 3\n'
            "bad_hist_sum 4.0\n"
            "bad_hist_count 3\n"
        )
        with pytest.raises(ValueError, match="decrease"):
            parse_prometheus_text(text)

    def test_missing_inf_bucket_rejected(self):
        fam = {"samples": [('h_bucket{le="1.0"}', 2.0), ("h_count", 2.0),
                           ("h_sum", 1.0)]}
        with pytest.raises(ValueError, match=r"\+Inf"):
            snapshots_from_family(fam)

    def test_inf_bucket_count_mismatch_rejected(self):
        fam = {"samples": [('h_bucket{le="1.0"}', 2.0),
                           ('h_bucket{le="+Inf"}', 2.0),
                           ("h_count", 5.0), ("h_sum", 1.0)]}
        with pytest.raises(ValueError, match="_count"):
            snapshots_from_family(fam)

    def test_quantile_from_snapshot(self):
        h = Histogram(log_bounds(1.0, 1000.0, 3))
        for _ in range(100):
            h.observe(50.0)
        p50 = quantile_from_snapshot(h.snapshot(), 0.5)
        # bucket resolution: within one log-spaced bucket of the truth
        assert 50.0 / (10 ** (1 / 3)) <= p50 <= 50.0 * (10 ** (1 / 3))
        assert math.isnan(quantile_from_snapshot(
            Histogram(log_bounds(1.0, 10.0, 2)).snapshot(), 0.5))


class TestTimeSeriesStore:
    RES = (("10s", 10.0), ("1m", 60.0))

    def test_coarse_tier_is_fold_of_fine_tier(self):
        store = TimeSeriesStore(self.RES, clock=lambda: 0.0)
        for i in range(12):  # two 1m buckets, twelve 10s buckets
            store.observe("lat", float(i + 1), now=i * 10.0)
        fine = store.query("lat", "10s")
        coarse = store.query("lat", "1m")
        assert len(fine) == 12 and len(coarse) == 2
        for cb in coarse:
            members = [b for b in fine
                       if cb["t"] <= b["t"] < cb["t"] + 60.0]
            assert cb["count"] == sum(b["count"] for b in members)
            assert cb["sum"] == sum(b["sum"] for b in members)
            assert cb["min"] == min(b["min"] for b in members)
            assert cb["max"] == max(b["max"] for b in members)

    def test_ring_bound_evicts_oldest(self):
        store = TimeSeriesStore(self.RES, points_per_tier=5)
        for i in range(20):
            store.observe("x", 1.0, now=i * 10.0)
        ring = store.query("x", "10s")
        assert len(ring) == 5
        assert ring[0]["t"] == 150.0  # 0..140 evicted, newest kept

    def test_series_cap_drops_novel_names(self):
        store = TimeSeriesStore(self.RES, max_series=2)
        store.observe("a", 1.0, now=0.0)
        store.observe("b", 1.0, now=0.0)
        store.observe("c", 1.0, now=0.0)  # past the cap: dropped
        store.observe("a", 2.0, now=1.0)  # existing names still fold
        assert store.query("c", "10s") == []
        assert store.stats()["dropped_series"] == 1
        assert store.query("a", "10s")[0]["count"] == 2

    def test_unknown_resolution_raises_unknown_name_empty(self):
        store = TimeSeriesStore(self.RES)
        with pytest.raises(KeyError, match="unknown resolution"):
            store.query("x", "5m")
        assert store.query("never-seen", "10s") == []

    def test_nan_points_skipped(self):
        store = TimeSeriesStore(self.RES)
        store.observe("x", float("nan"), now=0.0)
        assert store.query("x", "10s") == []

    def test_append_snapshot_fans_out(self):
        store = TimeSeriesStore(self.RES)
        h = _hist_of([1.0, 2.0, 4.0])
        n = store.append_snapshot({
            "counters": {"served_total": 7},
            "gauges": {"queue_depth": 3.0},
            "series": {"lat_ms": {"p50": 1.0, "p95": 2.0, "p99": 4.0}},
            "histograms": {"lat_ms_hist": h.snapshot()},
        }, now=0.0)
        names = store.names()
        assert {"served_total", "queue_depth", "lat_ms_p50", "lat_ms_p99",
                "lat_ms_hist_count", "lat_ms_hist_sum",
                "lat_ms_hist_p99"} <= set(names)
        assert n == 8  # 1 counter + 1 gauge + 3 quantiles + 3 hist
        assert store.query("lat_ms_hist_count", "10s")[0]["last"] == 3.0

    def test_collector_tick_and_broken_hook_survival(self):
        reg = MetricsRegistry()
        reg.add_provider("p", lambda: {"gauges": {"g": 1.0}})
        store = TimeSeriesStore(self.RES)
        collector = TsdbCollector(reg, store, interval_s=0.1)
        calls = []
        collector.add_on_tick(lambda: calls.append(1))

        def broken():
            raise RuntimeError("hook down")

        collector.add_on_tick(broken)
        assert collector.tick_once() >= 1
        assert collector.tick_once() >= 1  # broken hook swallowed
        assert len(calls) == 2 and collector.ticks == 2
        assert "g" in store.names()

    def test_bad_bounds_rejected(self):
        with pytest.raises(ValueError):
            TimeSeriesStore(self.RES, points_per_tier=0)
        with pytest.raises(ValueError):
            TimeSeriesStore((("10s", 10.0), ("10s", 20.0)))


class TestSLOEngine:
    OBJ = SLOObjective("avail", target=0.9, window_s=60.0)
    RULE = BurnRateRule(fast_s=2.0, slow_s=8.0, factor=2.0, for_s=1.0)

    def _engine(self, **kw):
        fired, resolved = [], []
        eng = SLOEngine((self.OBJ,), rules=(self.RULE,),
                        on_fire=fired.append, on_resolve=resolved.append,
                        **kw)
        return eng, fired, resolved

    def test_lifecycle_fire_resolve_and_rearm(self):
        eng, fired, resolved = self._engine()
        for t in range(30):  # clean baseline
            eng.record(True, 1.0, now=float(t))
        assert eng.evaluate(now=30.0) == []
        for t in range(31, 36):  # the burst: all-bad seconds
            for _ in range(5):
                eng.record(False, 0.0, now=float(t))
        made = eng.evaluate(now=33.0)
        assert [m["to"] for m in made] == ["pending"]
        assert not fired  # for_s hold not yet served
        made = eng.evaluate(now=34.5)
        assert [m["to"] for m in made] == ["firing"]
        assert len(fired) == 1 and fired[0]["objective"] == "avail"
        assert fired[0]["burn_fast"] > 2.0
        assert eng.firing()[0]["fire_count"] == 1
        # recovery: clean traffic ages the burst out of both windows
        for t in range(40, 60):
            eng.record(True, 1.0, now=float(t))
        made = eng.evaluate(now=55.0)
        assert [m["to"] for m in made] == ["resolved"]
        assert len(resolved) == 1 and not eng.firing()
        # RE-ARM: a second burst walks resolved -> pending -> firing
        for t in range(60, 65):
            for _ in range(5):
                eng.record(False, 0.0, now=float(t))
        eng.evaluate(now=62.0)
        made = eng.evaluate(now=63.5)
        assert [m["to"] for m in made] == ["firing"]
        assert len(fired) == 2
        assert eng.firing()[0]["fire_count"] == 2

    def test_pending_clears_on_blip(self):
        eng, fired, _ = self._engine()
        for _ in range(5):
            eng.record(False, 0.0, now=10.0)
        made = eng.evaluate(now=10.5)
        assert [m["to"] for m in made] == ["pending"]
        for t in range(11, 25):  # blip over before the for_s hold fires
            for _ in range(20):
                eng.record(True, 1.0, now=float(t))
        made = eng.evaluate(now=24.0)
        assert [m["to"] for m in made] == ["inactive"]
        assert not fired

    def test_multiwindow_condition_needs_both(self):
        # a spike too short for the SLOW window must not fire: 2 bad
        # in a long-good history exceeds the fast burn only
        eng, fired, _ = self._engine()
        for t in range(50):
            for _ in range(10):
                eng.record(True, 1.0, now=float(t))
        for _ in range(4):
            eng.record(False, 0.0, now=50.0)
        made = eng.evaluate(now=50.5)
        burn_fast = eng.burn_rate("avail", 2.0, now=50.5)
        burn_slow = eng.burn_rate("avail", 8.0, now=50.5)
        assert burn_fast > 2.0 > burn_slow
        assert made == [] and not fired

    def test_budget_accounting(self):
        eng, _, _ = self._engine()
        for i in range(100):
            eng.record(i >= 5, 1.0, now=30.0)  # 5 bad of 100
        b = eng.budget("avail", now=30.0)
        assert b["total"] == 100 and b["bad"] == 5
        assert b["allowed"] == pytest.approx(10.0)
        assert b["remaining_frac"] == pytest.approx(0.5)

    def test_note_status_5xx_burns(self):
        eng, _, _ = self._engine()
        eng.note_status(500, now=10.0)
        eng.note_status(503, now=10.0)
        eng.note_status(429, now=10.0)  # shedding is NOT budget burn
        eng.note_status(200, now=10.0)
        b = eng.budget("avail", now=10.0)
        assert b["total"] == 4 and b["bad"] == 2

    def test_latency_objective(self):
        obj = SLOObjective("lat", target=0.9, latency_threshold_ms=100.0)
        assert obj.good(True, 50.0)
        assert not obj.good(True, 150.0)  # slow success burns
        assert not obj.good(False, 50.0)
        assert not obj.good(True, None)

    def test_gauges_and_state_views(self):
        eng, _, _ = self._engine(clock=lambda: 30.0)
        eng.record(True, 1.0, now=29.0)
        g = eng.gauges()
        assert g["slo_alerts_firing"] == 0.0
        assert g["slo_avail_budget_remaining"] == 1.0
        st = eng.state(now=30.0)
        assert st["events"] == 1
        assert self.RULE.key in st["objectives"]["avail"]["rules"]

    def test_constructor_validation(self):
        with pytest.raises(ValueError, match="at least one"):
            SLOEngine(())
        with pytest.raises(ValueError, match="duplicate"):
            SLOEngine((self.OBJ, self.OBJ))
        with pytest.raises(ValueError, match="target"):
            SLOObjective("x", target=1.0)
        with pytest.raises(ValueError, match="fast_s"):
            BurnRateRule(fast_s=8.0, slow_s=2.0, factor=2.0)

    def test_default_rules_scale_with_window(self):
        rules = default_rules(3600.0)
        assert len(rules) == 2
        assert rules[0].fast_s == pytest.approx(300.0)
        assert rules[0].slow_s == rules[1].slow_s == 3600.0
        assert rules[0].factor > rules[1].factor
