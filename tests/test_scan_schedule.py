"""Scan-driver schedule statistics (VERDICT r4 weak #2 -> r5 item 4).

The r4 forensic (PERF.md 6e) found that chunk GRANULARITY — long
same-shape step runs from coarse chunks — cost ~35% multi-bucket val MAE
at MP-146k; chunk_steps=2 with randomized lengths and weighted-random
group picks recovers the per-step loop's convergence. Nothing cheaper
than a 146k re-run guarded that property. These tests pin it host-side
in milliseconds: they extract the driver's realized step sequence (the
scan bodies are stubbed; only scheduling runs) at the group sizes of the
at-scale regime (~85 batches/shape, where the original regression was
visible) and assert the same-shape run-length distribution stays in the
chunk-2 family. A scheduler change reintroducing chunk-8-style runs
(measured here: mean 5.7, p95 20 vs chunk-2's mean 2.8, p95 8) fails
immediately.
"""

import numpy as np
import pytest

from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
from cgnn_tpu.data.graph import pack_graphs
from cgnn_tpu.train.loop import ScanEpochDriver

CFG = FeaturizeConfig(radius=6.0, max_num_nbr=12)
EPOCHS = 10


@pytest.fixture(scope="module")
def batches():
    """Three shape groups at at-scale group sizes (85/80/90 batches):
    replicated tiny packed batches — the scheduler sees only shapes."""
    graphs = load_synthetic_mp(48, CFG, seed=0)

    def mk(sub, nc):
        return pack_graphs(sub, nc, nc * 12, len(sub), dense_m=12)

    b0 = mk(graphs[:16], 600)
    b1 = mk(graphs[16:32], 800)
    b2 = mk(graphs[32:], 1000)
    return [b0] * 85 + [b1] * 80 + [b2] * 90


def realized_schedule(batches, chunk_steps, epochs=EPOCHS, seed=0):
    """[(group_key, chunk_len)] over ``epochs`` driven epochs, with the
    jitted scan bodies stubbed out (host-side scheduling only)."""
    drv = ScanEpochDriver(
        lambda s, b: (s, {}), lambda s, b: {}, batches, [],
        np.random.default_rng(seed), chunk_steps=chunk_steps,
    )
    seq: list = []

    def fake_scan_fn(cache, key, body, train):
        # the driver's cache key is (shape_key, chunk_len) — record the
        # SHAPE key and the realized length separately, else runs of one
        # shape split wherever the drawn length changes
        shape_key, length = key

        def fn(state, stacked, perm):
            assert int(np.shape(perm)[0]) == length
            seq.append((shape_key, length))
            return state, {}

        return fn

    drv._scan_fn = fake_scan_fn
    epoch_bounds = []
    for _ in range(epochs):
        drv._drive(None, drv._train_groups, {}, None, train=True,
                   first=False)
        epoch_bounds.append(len(seq))
    return seq, epoch_bounds


def run_lengths(seq):
    steps = [k for k, ln in seq for _ in range(ln)]
    runs, cur, n = [], None, 0
    for s in steps:
        if s == cur:
            n += 1
        else:
            if n:
                runs.append(n)
            cur, n = s, 1
    runs.append(n)
    return np.array(runs)


def test_chunk2_run_length_distribution(batches):
    """The property whose violation cost 35% val MAE: with the default
    chunk_steps=2, same-shape runs must track the per-step weighted
    interleave (measured family: mean ~2.8, p95 8), far from the chunk-8
    family (mean ~5.7, p95 20)."""
    seq, _ = realized_schedule(batches, chunk_steps=2)
    runs = run_lengths(seq)
    assert runs.mean() <= 3.5, f"mean same-shape run {runs.mean():.2f}"
    assert np.percentile(runs, 95) <= 10, f"p95 run {np.percentile(runs, 95)}"
    assert runs.max() <= 24, f"max run {runs.max()}"


def test_chunk_lengths_bounded_for_compile_keys(batches):
    """Dispatch lengths must stay in the bounded set {1..c/2, c, 2c} so
    distinct compiled scan programs stay O(1) per group."""
    for c in (2, 4):
        seq, _ = realized_schedule(batches, chunk_steps=c)
        lengths = {ln for _, ln in seq}
        assert max(lengths) <= 2 * c
        allowed = set(range(1, max(2, c // 2 + 1))) | {c, 2 * c}
        assert lengths <= allowed, f"c={c}: unexpected lengths {lengths - allowed}"


def test_every_batch_scheduled_once_per_epoch(batches):
    """Coverage invariant: each epoch dispatches each group's every batch
    exactly once (chunks partition the permutation)."""
    seq, bounds = realized_schedule(batches, chunk_steps=2, epochs=4)
    sizes = {85, 80, 90}
    start = 0
    for end in bounds:
        per_group: dict = {}
        for key, ln in seq[start:end]:
            per_group[key] = per_group.get(key, 0) + ln
        assert sorted(per_group.values()) == sorted(sizes)
        start = end


def test_coarse_chunks_would_fail_the_guard(batches):
    """Self-check that the thresholds bite: chunk-8 scheduling violates
    the distribution test (this is the regression the guard exists for)."""
    seq, _ = realized_schedule(batches, chunk_steps=8)
    runs = run_lengths(seq)
    assert runs.mean() > 3.5 and np.percentile(runs, 95) > 10


def test_chunk_steps_flag_reaches_driver(batches):
    drv = ScanEpochDriver(lambda s, b: (s, {}), lambda s, b: {},
                          batches[:3], [], np.random.default_rng(0),
                          chunk_steps=4)
    assert drv.chunk_steps == 4
    with pytest.raises(ValueError):
        ScanEpochDriver(lambda s, b: (s, {}), lambda s, b: {}, batches[:3],
                        [], np.random.default_rng(0), chunk_steps=0)
