"""Cross-process observability layer tests (ISSUE 15).

The load-bearing guarantees, pinned host-side and deterministically —
the live-fleet legs (kill -9 -> breaker trip -> bundle with a joined
cross-process trace) run in scripts/fleet_smoke.sh:

- trace context: span ids are process-unique, the X-Trace-Parent wire
  format round-trips, malformed values parse to empty (never raise on
  the request path);
- span windows: `/trace` payloads carry the drop count and retained
  bounds, so a joiner can mark truncation instead of silently
  rendering a partial tree;
- the joiner: N process windows -> ONE Chrome-trace doc with per-
  process metadata, rebased timestamps, flow arrows on span parents,
  and a per-trace index that marks cross-process + incomplete chains;
- the flight recorder: bounded ring, rate-limited triggers, a bundle
  dir holding manifest/requests/metrics/trace, the 5xx burst trigger
  firing once per plateau;
- JSON logging: one parseable line per call carrying role + pid + the
  contextvar-bound trace id.
"""

import json
import os
import threading
import time

from cgnn_tpu.observe import flightrec, log, trace_join, tracectx
from cgnn_tpu.observe.export import MetricsRegistry
from cgnn_tpu.observe.spans import SpanTracer

# ------------------------------------------------------------ tracectx


class TestTraceContext:
    def test_span_ids_unique(self):
        ids = {tracectx.mint_span_id("att") for _ in range(1000)}
        assert len(ids) == 1000

    def test_parent_round_trip(self):
        sid = tracectx.mint_span_id("att")
        header = tracectx.format_parent("flt-ab-000001", sid)
        assert tracectx.parse_parent(header) == ("flt-ab-000001", sid)

    def test_trace_id_with_slashes_survives(self):
        # trace ids are client-controlled (X-Request-Id); the span id
        # owns the LAST '/' so a slashed trace id still round-trips
        header = tracectx.format_parent("client/run/7", "att-1-2")
        assert tracectx.parse_parent(header) == ("client/run/7", "att-1-2")

    def test_malformed_parses_empty_never_raises(self):
        for bad in (None, "", "/", "no-separator", 42, "a/" , "/b"):
            assert tracectx.parse_parent(bad) == ("", "")


# ---------------------------------------------------------- span window


class TestSpanWindow:
    def test_window_carries_drop_count_and_bounds(self):
        tr = SpanTracer(process_name="w", max_events=4)
        for i in range(7):  # 3 evictions
            tr.complete(f"s{i}", 0.0, 0.001)
        w = tr.window()
        assert w["dropped"] == 3 and w["max_events"] == 4
        assert len(w["events"]) == 4
        assert w["begin_us"] <= w["end_us"]
        assert w["pid"] == os.getpid() and w["t0_unix"] > 0

    def test_since_filters_by_wall_clock(self):
        tr = SpanTracer(process_name="w")
        t0 = SpanTracer.now_s()
        tr.complete("old", t0 - 10.0, t0 - 9.0)
        tr.complete("new", t0, t0 + 0.001)
        w = tr.window(since_s=time.time() - 5.0)
        names = [e["name"] for e in w["events"]]
        assert names == ["new"]
        # no filter -> both retained
        assert len(tr.window()["events"]) == 2


# -------------------------------------------------------------- joiner


def _fleet_windows(drop_replica=False):
    """A router ring + one replica ring holding a retried request:
    two fleet.attempt spans (replica 0 failed, replica 1 answered)
    and the replica-side serve.request nested under attempt 2."""
    router = SpanTracer(process_name="router")
    replica = SpanTracer(process_name="replica1")
    t = SpanTracer.now_s()
    root = tracectx.mint_span_id("req")
    att1 = tracectx.mint_span_id("att")
    att2 = tracectx.mint_span_id("att")
    router.complete("fleet.attempt", t, t + 0.01, trace_id="tid-1",
                    span_id=att1, parent=root, replica=0,
                    outcome="transport_errors", status=0)
    router.complete("fleet.attempt", t + 0.02, t + 0.05,
                    trace_id="tid-1", span_id=att2, parent=root,
                    replica=1, outcome="answered", status=200)
    router.complete("fleet.request", t, t + 0.05, trace_id="tid-1",
                    span_id=root, status=200, attempts=2)
    replica.complete("serve.request", t + 0.025, t + 0.045,
                     trace_id="tid-1", parent=att2, flush_id="f-1")
    replica.complete("serve.dispatch", t + 0.03, t + 0.04,
                     flush_id="f-1", trace_ids=["tid-1"])
    wr = router.window()
    wr["role"] = "router"
    wp = replica.window()
    wp["role"] = "replica"
    wp["pid"] = os.getpid() + 1  # two tracers, one test process: give
    #                              the replica window its own pid
    if drop_replica:
        wp["dropped"] = 5
    return wr, wp


class TestTraceJoin:
    def test_joined_doc_is_one_cross_process_tree(self):
        doc = trace_join.join_windows(list(_fleet_windows()))
        names = {e.get("name") for e in doc["traceEvents"]}
        assert {"fleet.request", "fleet.attempt", "serve.request",
                "process_name"} <= names
        # two processes, metadata naming both roles
        meta = [e for e in doc["traceEvents"]
                if e.get("name") == "process_name"]
        labels = {e["args"]["name"] for e in meta}
        assert any("router" in x for x in labels)
        assert any("replica" in x for x in labels)
        # the per-trace index: one request spanning BOTH pids, rooted,
        # complete (no ring dropped anything)
        t = doc["traces"]["tid-1"]
        assert len(t["pids"]) == 2
        assert t["rooted"] and t["complete"]
        # flow arrows connect the attempt span to the replica's
        # serve.request (the parent edge the propagation carried)
        flows = [e for e in doc["traceEvents"] if e.get("ph") in "sf"]
        assert any(e["ph"] == "s" for e in flows)
        assert any(e["ph"] == "f" for e in flows)
        assert doc["incomplete_processes"] == []

    def test_cross_process_index_finds_retried_request(self):
        doc = trace_join.join_windows(list(_fleet_windows()))
        assert trace_join.cross_process_traces(doc) == ["tid-1"]
        # a stricter bar than the data holds -> empty, not a crash
        assert trace_join.cross_process_traces(doc, min_spans=3) == []

    def test_truncated_ring_marks_chains_incomplete(self):
        doc = trace_join.join_windows(list(
            _fleet_windows(drop_replica=True)))
        assert len(doc["incomplete_processes"]) == 1
        t = doc["traces"]["tid-1"]
        assert t["rooted"] and not t["complete"]

    def test_timestamps_rebase_onto_shared_anchor(self):
        wr, wp = _fleet_windows()
        wp["t0_unix"] = wr["t0_unix"] + 3.0  # replica booted 3 s later
        doc = trace_join.join_windows([wr, wp])
        by_pid = {}
        for e in doc["traceEvents"]:
            if e.get("ph") == "X":
                by_pid.setdefault(e["pid"], []).append(e["ts"])
        a, b = sorted(by_pid)
        # the later process's events land ~3e6 us after the anchor
        assert min(by_pid[b]) - min(by_pid[a]) > 2.5e6
        assert doc["t0_unix"] == wr["t0_unix"]

    def test_empty_and_missing_windows_degrade(self):
        doc = trace_join.join_windows([])
        assert doc["traceEvents"] == [] and doc["traces"] == {}
        doc = trace_join.join_windows([None, {"events": [],
                                              "t0_unix": 1.0}])
        assert doc["traces"] == {}

    def test_write_joined_is_loadable_json(self, tmp_path):
        path = str(tmp_path / "joined" / "trace.json")
        doc = trace_join.write_joined(path, list(_fleet_windows()))
        on_disk = json.load(open(path))
        assert on_disk["traces"].keys() == doc["traces"].keys()
        assert any(e.get("name") == "serve.request"
                   for e in on_disk["traceEvents"])


# ----------------------------------------------------- flight recorder


def _recorder(tmp_path, **kw):
    kw.setdefault("role", "replica")
    kw.setdefault("min_interval_s", 0.0)
    kw.setdefault("log_fn", lambda *a, **k: None)
    return flightrec.FlightRecorder(str(tmp_path / "flightrec"), **kw)


class TestFlightRecorder:
    def test_ring_is_bounded(self, tmp_path):
        fr = _recorder(tmp_path, ring=8)
        for i in range(20):
            fr.note_request({"trace_id": f"t{i}", "status": "ok"})
        ring = fr.recent_requests()
        assert len(ring) == 8
        assert ring[-1]["trace_id"] == "t19"  # newest retained

    def test_trigger_writes_correlated_bundle(self, tmp_path):
        tracer = SpanTracer(process_name="replica")
        tracer.complete("serve.request", 0.0, 0.01, trace_id="t1")
        registry = MetricsRegistry()
        registry.add_provider("serve", lambda: {
            "counters": {"serve_requests": 3.0}})
        fr = _recorder(tmp_path, registry=registry, tracer=tracer,
                       manifest={"param_version": "ckpt-7"})
        fr.note_request({"trace_id": "t1", "status": "ok",
                         "param_version": "ckpt-7"})
        bundle = fr.trigger("breaker_trip", "replica1 ejected",
                            wait=True)
        assert bundle and os.path.isdir(bundle)
        # pid in the dir name: replicas sharing one flightrec dir and
        # firing in the same second must land in DISTINCT bundles
        assert f"-p{os.getpid()}-" in os.path.basename(bundle)
        files = set(os.listdir(bundle))
        assert {"manifest.json", "requests.jsonl", "metrics.json",
                "trace.json"} <= files
        manifest = json.load(open(os.path.join(bundle, "manifest.json")))
        assert manifest["reason"] == "breaker_trip"
        assert manifest["param_version"] == "ckpt-7"
        assert manifest["triggers"] == {"breaker_trip": 1}
        rows = [json.loads(ln) for ln in
                open(os.path.join(bundle, "requests.jsonl"))]
        assert rows[0]["trace_id"] == "t1"
        metrics = json.load(open(os.path.join(bundle, "metrics.json")))
        assert metrics["counters"]["serve_requests"] == 3.0
        doc = json.load(open(os.path.join(bundle, "trace.json")))
        assert "t1" in doc["traces"]

    def test_rate_limit_and_bundle_cap(self, tmp_path):
        clk = [0.0]
        fr = _recorder(tmp_path, min_interval_s=30.0, max_bundles=2,
                       clock=lambda: clk[0])
        assert fr.trigger("a", wait=True) is not None
        # inside the quiet interval: counted, not dumped
        assert fr.trigger("a", wait=True) is None
        clk[0] += 31.0
        assert fr.trigger("b", wait=True) is not None
        clk[0] += 31.0
        # bundle budget spent: an incident storm cannot fill the disk
        assert fr.trigger("c", wait=True) is None
        s = fr.stats()
        assert s["bundles"] == 2 and s["suppressed"] == 2
        assert s["triggers"] == {"a": 2, "b": 1, "c": 1}

    def test_force_trigger_bypasses_rate_limit_and_cap(self, tmp_path):
        """The drain-force-exit contract: the process is about to
        os._exit, and the final bundle must not be suppressed because
        the wedge's own 5xx burst dumped moments earlier."""
        clk = [0.0]
        fr = _recorder(tmp_path, min_interval_s=30.0, max_bundles=1,
                       clock=lambda: clk[0])
        assert fr.trigger("5xx_burst", wait=True) is not None
        # an ordinary trigger inside the quiet window: suppressed
        assert fr.trigger("breaker_trip", wait=True) is None
        b = fr.trigger("drain_force_exit", wait=True, force=True)
        assert b is not None and os.path.isdir(b)
        assert os.path.exists(os.path.join(b, "manifest.json"))
        assert fr.stats()["bundles"] == 2  # cap of 1 bypassed too

    def test_5xx_burst_fires_once_per_plateau(self, tmp_path):
        clk = [0.0]
        fr = _recorder(tmp_path, burst_threshold=5, burst_window_s=10.0,
                       min_interval_s=0.0, clock=lambda: clk[0])
        for _ in range(4):
            fr.note_status(500)
        assert fr.stats()["triggers"] == {}  # below threshold
        fr.note_status(502)
        fr.wait_idle()
        assert fr.stats()["triggers"] == {"5xx_burst": 1}
        # the plateau continues: no re-fire while armed
        for _ in range(10):
            fr.note_status(500)
        fr.wait_idle()
        assert fr.stats()["triggers"]["5xx_burst"] == 1
        # window drains + a fresh burst -> re-arms and fires again
        clk[0] += 20.0
        fr.note_status(500)  # evicts the stale window, re-arms
        for _ in range(5):
            fr.note_status(500)
        fr.wait_idle()
        assert fr.stats()["triggers"]["5xx_burst"] == 2

    def test_2xx_and_4xx_never_feed_the_burst(self, tmp_path):
        fr = _recorder(tmp_path, burst_threshold=2)
        for s in (200, 200, 404, 429, 413):
            fr.note_status(s)
        assert fr.stats()["triggers"] == {}

    def test_snapshot_is_the_peer_pull_surface(self, tmp_path):
        fr = _recorder(tmp_path, manifest={"port": 8441})
        fr.note_request({"trace_id": "t9", "status": "ok"})
        snap = fr.snapshot()
        assert snap["role"] == "replica" and snap["pid"] == os.getpid()
        assert snap["requests"][0]["trace_id"] == "t9"
        assert snap["manifest"]["port"] == 8441
        json.dumps(snap)  # wire-serializable as-is


# -------------------------------------------------------- JSON logging


class TestJsonLogging:
    def test_line_schema_and_trace_binding(self):
        import io

        buf = io.StringIO()
        jlog = log.json_log_fn("router", stream=buf)
        jlog("fleet: routing on", "http://x:1")
        with log.bind_trace("flt-1-000001"):
            jlog("retrying on replica2")
        jlog("drained")
        lines = [json.loads(ln) for ln in
                 buf.getvalue().strip().splitlines()]
        assert [ln["trace_id"] for ln in lines] == ["", "flt-1-000001",
                                                    ""]
        assert lines[0]["msg"] == "fleet: routing on http://x:1"
        assert all(ln["role"] == "router" and ln["pid"] == os.getpid()
                   for ln in lines)

    def test_binding_is_per_context_not_global(self):
        seen = {}

        def worker():
            with log.bind_trace("other-thread"):
                time.sleep(0.05)
                seen["worker"] = log.current_trace_id()

        t = threading.Thread(target=worker, name="log-bind-test")
        with log.bind_trace("main-thread"):
            t.start()
            time.sleep(0.01)
            seen["main"] = log.current_trace_id()
        t.join()
        assert seen == {"main": "main-thread", "worker": "other-thread"}

    def test_stdlib_handler_idempotent(self):
        import io
        import logging

        buf = io.StringIO()
        log.setup_json_logging("trainer", stream=buf)
        logger = log.setup_json_logging("trainer", stream=buf)
        logger.info("epoch 3 done")
        lines = buf.getvalue().strip().splitlines()
        assert len(lines) == 1  # re-setup did NOT stack a second handler
        rec = json.loads(lines[0])
        assert rec["role"] == "trainer" and rec["level"] == "info"
        assert rec["msg"] == "epoch 3 done"
        logging.getLogger("cgnn_tpu").handlers.clear()
