"""Fleet-layer tests (cgnn_tpu.fleet; ISSUE 14).

Everything here is host-side policy — no jax, no sockets: the router
takes an injectable transport, the breaker an injectable clock, so the
retry/hedge/ejection/shed behavior is pinned deterministically. The
live-process legs (kill -9, restart, rolling promotion) run in
scripts/fleet_smoke.sh against real serve.py replicas.

The load-bearing guarantees, pinned:

- breaker: K consecutive failures eject; cooldown -> ONE half-open
  trial; trial success (or a ready health probe) re-admits, trial
  failure re-ejects with a doubled cooldown;
- router: transport errors and 5xx retry on a DIFFERENT replica
  (bounded, backoff), 4xx request errors pass through unretried,
  nothing-admittable sheds 503 with a Retry-After, a slow attempt is
  hedged and the first success wins;
- exactly once: every attempt of a request carries the SAME trace id
  (the idempotency key) and the client gets exactly one answer — a
  straggler's success is counted as waste, never delivered.
"""

import threading
import time

import pytest

from cgnn_tpu.fleet.breaker import CircuitBreaker
from cgnn_tpu.fleet.replica import FleetTransportError, ReplicaState
from cgnn_tpu.fleet.router import FleetRouter
from cgnn_tpu.resilience import faultinject


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ------------------------------------------------------------- breaker


class TestCircuitBreaker:
    def test_ejects_after_k_consecutive_failures(self):
        clk = FakeClock()
        b = CircuitBreaker(k=3, cooldown_s=2.0, clock=clk)
        assert b.state == "closed" and b.would_admit()
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"  # streak below K
        b.record_success()          # success RESETS the streak
        b.record_failure()
        b.record_failure()
        assert b.state == "closed"
        b.record_failure()
        assert b.state == "open" and not b.would_admit()
        assert b.opens == 1
        assert 0.0 < b.retry_after_s() <= 2.0

    def test_half_open_single_trial_then_close(self):
        clk = FakeClock()
        b = CircuitBreaker(k=1, cooldown_s=2.0, clock=clk)
        b.record_failure()
        assert b.state == "open" and not b.admit()
        clk.advance(2.5)
        assert b.state == "half_open"
        assert b.admit()            # the ONE trial
        assert not b.admit()        # concurrent caller refused
        b.record_success()
        assert b.state == "closed" and b.admit()

    def test_failed_trial_reopens_with_doubled_cooldown(self):
        clk = FakeClock()
        b = CircuitBreaker(k=1, cooldown_s=2.0, max_cooldown_s=30.0,
                           clock=clk)
        b.record_failure()
        clk.advance(2.5)
        assert b.admit()
        b.record_failure()          # trial failed
        assert b.state == "open" and b.opens == 2
        clk.advance(2.5)            # old cooldown is NOT enough now
        assert b.state == "open"
        clk.advance(2.0)            # doubled: 4 s total
        assert b.state == "half_open"

    def test_probe_readmission_from_half_open_only(self):
        clk = FakeClock()
        b = CircuitBreaker(k=1, cooldown_s=2.0, clock=clk)
        b.record_failure()
        b.record_probe_success()    # cooldown still running: stays open
        assert b.state == "open"
        clk.advance(2.5)
        b.record_probe_success()    # half-open: the probe re-admits
        assert b.state == "closed" and b.closes == 1


# ----------------------------------------------------- replica scoring


def _ready_replica(rid: int, **probe) -> ReplicaState:
    r = ReplicaState(rid, f"http://127.0.0.1:{9000 + rid}")
    r.note_probe(ready=True, **probe)
    return r


class TestReplicaState:
    def test_unprobed_replica_is_not_pickable(self):
        r = ReplicaState(0, "http://127.0.0.1:9000")
        assert not r.pickable()
        r.note_probe(ready=True)
        assert r.pickable()

    def test_score_prefers_idle_then_fast(self):
        a = _ready_replica(0, queue_depth=4.0, p99_ms=10.0)
        b = _ready_replica(1, queue_depth=0.0, p99_ms=10.0)
        c = _ready_replica(2, queue_depth=0.0, p99_ms=50.0)
        order = sorted([a, b, c], key=lambda r: r.score())
        assert [r.rid for r in order] == [1, 2, 0]

    def test_transport_error_marks_unready_and_feeds_breaker(self):
        r = _ready_replica(0)
        r.note_sent()
        r.note_result("transport_errors")
        assert not r.ready          # faster than the next poll round
        assert r.breaker.stats()["consecutive_failures"] == 1
        assert r.inflight == 0

    def test_draining_replica_not_ready(self):
        r = _ready_replica(0)
        assert r.ready
        r.note_probe(ready=True, draining=True)
        assert not r.ready


# -------------------------------------------------------------- router


def _ok_payload(version="v1"):
    return {"param_version": version, "prediction": [0.0],
            "latency_ms": 1.0}


def _router(replicas, transport, **kw):
    kw.setdefault("backoff_ms", 1.0)
    kw.setdefault("default_timeout_ms", 10000.0)
    kw.setdefault("log_fn", lambda *a: None)
    return FleetRouter(replicas, transport=transport, **kw)


class TestFleetRouter:
    def test_answers_first_try_on_best_replica(self):
        seen = []

        def transport(replica, body, timeout_s):
            seen.append((replica.rid, body["trace_id"]))
            return 200, _ok_payload()

        r0, r1 = _ready_replica(0), _ready_replica(1, queue_depth=9.0)
        router = _router([r0, r1], transport)
        status, payload, meta = router.dispatch({"graph": {}})
        assert status == 200
        assert meta["attempts"] == 1 and meta["retries"] == 0
        assert meta["replica"] == 0  # the idle one
        assert seen[0][0] == 0
        assert router.counts["fleet_answered"] == 1

    def test_transport_error_retries_on_sibling_exactly_once_answer(self):
        tried = []

        def transport(replica, body, timeout_s):
            tried.append((replica.rid, body["trace_id"]))
            if replica.rid == 0:
                raise FleetTransportError("connection refused")
            return 200, _ok_payload()

        r0, r1 = _ready_replica(0), _ready_replica(1)
        router = _router([r0, r1], transport)
        status, payload, meta = router.dispatch({"graph": {}},
                                                trace_id="probe-7")
        assert status == 200 and meta["replica"] == 1
        assert meta["attempts"] == 2 and meta["retries"] == 1
        # the idempotency key: every attempt carried the SAME trace id
        assert [t for _, t in tried] == ["probe-7", "probe-7"]
        assert router.counts["fleet_transport_errors"] == 1
        assert router.counts["fleet_answered"] == 1
        assert router.counts["fleet_duplicate_answers"] == 0
        assert not r0.ready  # marked down ahead of the next probe round

    def test_500_retries_and_breaker_counts_it(self):
        def transport(replica, body, timeout_s):
            if replica.rid == 0:
                return 500, {"error": "boom", "reason": "dispatch_failed"}
            return 200, _ok_payload()

        r0, r1 = _ready_replica(0), _ready_replica(1)
        router = _router([r0, r1], transport)
        status, _, meta = router.dispatch({"graph": {}})
        assert status == 200 and meta["retries"] == 1
        assert r0.breaker.stats()["consecutive_failures"] == 1
        assert r0.ready  # a 500 is a failure, not proof of death

    def test_request_errors_pass_through_unretried(self):
        calls = []

        def transport(replica, body, timeout_s):
            calls.append(replica.rid)
            return 400, {"error": "malformed", "reason": "malformed"}

        router = _router([_ready_replica(0), _ready_replica(1)],
                         transport)
        status, payload, meta = router.dispatch({"graph": {}})
        assert status == 400 and payload["reason"] == "malformed"
        assert len(calls) == 1 and meta["retries"] == 0
        assert router.counts["fleet_passthrough_rejects"] == 1

    def test_sheds_503_with_retry_after_when_nothing_admittable(self):
        def transport(replica, body, timeout_s):  # noqa: ARG001
            raise AssertionError("nothing should be dispatched")

        r0 = ReplicaState(0, "http://127.0.0.1:9000")  # never probed
        router = _router([r0], transport)
        status, payload, meta = router.dispatch({"graph": {}})
        assert status == 503 and payload["reason"] == "no_replicas"
        assert meta["retry_after_s"] >= 1.0
        assert router.counts["fleet_shed"] == 1

    def test_repeated_failures_eject_then_shed(self):
        def transport(replica, body, timeout_s):  # noqa: ARG001
            return 500, {"error": "boom"}

        reps = [_ready_replica(i, queue_depth=0.0) for i in range(2)]
        for r in reps:
            r.breaker.k = 2
        router = _router(reps, transport, max_attempts=2)
        s1, p1, _ = router.dispatch({"graph": {}})
        assert s1 == 502 and p1["reason"] == "upstream_exhausted"
        s2, _, _ = router.dispatch({"graph": {}})
        assert s2 == 502
        # two consecutive failures each: both breakers are now open
        assert all(r.breaker.state == "open" for r in reps)
        s3, p3, _ = router.dispatch({"graph": {}})
        assert s3 == 503 and p3["reason"] == "no_replicas"

    def test_hedge_races_slow_replica_first_success_wins(self):
        release = threading.Event()
        seen = []

        def transport(replica, body, timeout_s):
            seen.append((replica.rid, body["trace_id"]))
            if replica.rid == 0:
                release.wait(5.0)  # the slow primary
                return 200, _ok_payload("v-slow")
            return 200, _ok_payload("v-fast")

        # rid 0 scores better (idle) so it is picked first
        r0 = _ready_replica(0, queue_depth=0.0)
        r1 = _ready_replica(1, queue_depth=1.0)
        router = _router([r0, r1], transport, hedge_ms=40.0,
                         max_attempts=3)
        status, payload, meta = router.dispatch({"graph": {}})
        assert status == 200
        assert payload["param_version"] == "v-fast"
        assert meta["replica"] == 1 and meta["hedges"] == 1
        assert router.counts["fleet_hedges"] == 1
        assert router.counts["fleet_hedge_wins"] == 1
        # same idempotency key on both attempts
        assert len({t for _, t in seen}) == 1
        # let the straggler finish: its success is WASTE, never a
        # second answer
        release.set()
        deadline = time.monotonic() + 5.0
        while (router.counts.get("fleet_hedge_waste", 0) == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert router.counts["fleet_hedge_waste"] == 1
        assert router.counts["fleet_answered"] == 1
        assert router.counts["fleet_duplicate_answers"] == 0

    def test_deadline_exceeded_returns_typed_504(self):
        def transport(replica, body, timeout_s):  # noqa: ARG001
            time.sleep(0.2)
            return 200, _ok_payload()

        router = _router([_ready_replica(0)], transport,
                         default_timeout_ms=50.0, hedge_ms=0.0)
        status, payload, _ = router.dispatch({"graph": {}})
        assert status == 504 and payload["reason"] == "timeout"
        assert router.counts["fleet_deadline_exceeded"] == 1

    def test_probe_readmits_restarted_replica(self):
        alive = {"up": False}

        def transport(replica, body, timeout_s):  # noqa: ARG001
            if not alive["up"]:
                raise FleetTransportError("connection refused")
            return 200, _ok_payload("v2")

        clk_real = time.monotonic
        r0 = ReplicaState(0, "http://127.0.0.1:9000",
                          breaker_k=1, breaker_cooldown_s=0.05,
                          clock=clk_real)
        r0.note_probe(ready=True)
        router = _router([r0], transport, max_attempts=1)
        s1, _, _ = router.dispatch({"graph": {}})
        assert s1 == 502  # the dead replica failed its only attempt
        assert r0.breaker.state == "open" and not r0.ready
        # ... replica restarts, cooldown passes, a health probe lands
        alive["up"] = True
        time.sleep(0.08)
        r0.note_probe(ready=True, version="v2")
        assert r0.breaker.state == "closed" and r0.pickable()
        s2, payload, _ = router.dispatch({"graph": {}})
        assert s2 == 200 and payload["param_version"] == "v2"

    def test_versions_view_and_registry_families(self):
        def transport(replica, body, timeout_s):  # noqa: ARG001
            # answered responses refresh the version view too — return
            # each replica's own probed version so both paths agree
            return 200, _ok_payload(f"ckpt-0000000{replica.rid + 1}")

        reps = [_ready_replica(0), _ready_replica(1)]
        reps[0].note_probe(ready=True, version="ckpt-00000001")
        reps[1].note_probe(ready=True, version="ckpt-00000002")
        router = _router(reps, transport)
        router.dispatch({"graph": {}})
        assert router.versions() == {0: "ckpt-00000001",
                                     1: "ckpt-00000002"}
        from cgnn_tpu.observe.export import parse_prometheus_text

        fams = parse_prometheus_text(router.registry.prometheus_text())
        assert "cgnn_fleet_requests_total" in fams
        # per-replica gauges fold into ONE labeled family per metric
        assert "cgnn_replica_inflight" in fams
        labels = [s for s, _ in fams["cgnn_replica_inflight"]["samples"]]
        assert any('replica="0"' in s for s in labels)
        assert any('replica="1"' in s for s in labels)
        stats = router.stats()
        assert stats["counts"]["fleet_answered"] == 1
        assert set(stats["replicas"]) == {"0", "1"}


# -------------------------- cross-process trace propagation (ISSUE 15)


class TestRouterTracePropagation:
    def test_attempts_carry_distinct_parents_same_trace(self):
        """Every attempt of one request propagates the SAME trace id
        but its OWN attempt span id in trace_parent — the replica-side
        serve.request spans then nest under the right attempt in the
        joined trace (a hedge's two subtrees stay distinguishable)."""
        seen = []

        def transport(replica, body, timeout_s):
            seen.append((replica.rid, body["trace_id"],
                         body.get("trace_parent", "")))
            if replica.rid == 0:
                raise FleetTransportError("connection refused")
            return 200, _ok_payload()

        router = _router([_ready_replica(0), _ready_replica(1)],
                         transport)
        status, _, meta = router.dispatch({"graph": {}},
                                          trace_id="probe-9")
        assert status == 200 and meta["attempts"] == 2
        assert meta["span_id"].startswith("req-")
        from cgnn_tpu.observe.tracectx import parse_parent

        parents = [parse_parent(tp) for _, _, tp in seen]
        # same trace id on every attempt, a DISTINCT span id per attempt
        assert [t for t, _ in parents] == ["probe-9", "probe-9"]
        sids = [s for _, s in parents]
        assert len(set(sids)) == 2 and all(s.startswith("att-")
                                           for s in sids)

    def test_router_ring_holds_request_and_attempt_spans(self):
        def transport(replica, body, timeout_s):
            if replica.rid == 0:
                raise FleetTransportError("refused")
            return 200, _ok_payload()

        router = _router([_ready_replica(0), _ready_replica(1)],
                         transport)
        router.dispatch({"graph": {}}, trace_id="probe-10")
        events = router.tracer.events
        reqs = [e for e in events if e["name"] == "fleet.request"]
        atts = [e for e in events if e["name"] == "fleet.attempt"]
        assert len(reqs) == 1 and len(atts) == 2
        root = reqs[0]["args"]
        assert root["trace_id"] == "probe-10" and root["status"] == 200
        # both attempts parent to the root span; outcomes name the
        # failure AND the win
        assert {a["args"]["parent"] for a in atts} == {root["span_id"]}
        assert {a["args"]["outcome"] for a in atts} == {
            "transport_errors", "answered"}
        # the router's window is a joinable /trace payload
        w = router.trace_window()
        assert w["role"] == "router" and w["dropped"] == 0

    def test_trace_ring_off_disables_cleanly(self):
        def transport(replica, body, timeout_s):
            assert "trace_parent" not in body  # nothing propagates
            return 200, _ok_payload()

        router = _router([_ready_replica(0)], transport, trace_ring=0)
        status, _, meta = router.dispatch({"graph": {}})
        assert status == 200 and meta["span_id"] == ""
        assert router.tracer is None and router.trace_window() is None

    def test_breaker_trip_fires_flight_recorder(self, tmp_path):
        from cgnn_tpu.observe import FlightRecorder

        def transport(replica, body, timeout_s):
            # typed 500s: the replica stays READY (it answered), so the
            # retry loop keeps feeding the same breaker until it trips
            return 500, {"error": "boom", "reason": "dispatch_failed"}

        r0 = _ready_replica(0)
        router = _router([r0], transport, max_attempts=4)
        recorder = FlightRecorder(str(tmp_path / "fr"), role="router",
                                  min_interval_s=0.0,
                                  tracer=router.tracer,
                                  log_fn=lambda *a, **k: None)
        router.attach_flight_recorder(recorder)
        status, _, _ = router.dispatch({"graph": {}})
        assert status in (502, 503)
        recorder.wait_idle()
        s = recorder.stats()
        # K=3 consecutive 500s tripped the breaker -> one bundle; the
        # dispatch outcome also landed in the recent-request ring
        assert s["triggers"].get("breaker_trip", 0) >= 1
        assert s["bundles"] >= 1
        import os as _os

        assert _os.path.isdir(s["last_bundle"])
        assert recorder.recent_requests()[-1]["status"] in (502, 503)

    def test_vanished_replica_fires_recorder_on_probe(self, tmp_path):
        """The kill -9 case, made deterministic: the victim's breaker
        may or may not accumulate K in-flight failures before the
        router stops picking it, but the NEXT health-probe round always
        sees reachable -> unreachable and bundles the incident."""
        from cgnn_tpu.observe import FlightRecorder

        r0 = _ready_replica(0)  # nothing listens on its port
        router = _router([r0], lambda *a: (200, _ok_payload()))
        recorder = FlightRecorder(str(tmp_path / "fr"), role="router",
                                  min_interval_s=0.0,
                                  tracer=router.tracer,
                                  log_fn=lambda *a, **k: None)
        router.attach_flight_recorder(recorder)
        assert r0.stats()["probe_ok"]  # the fixture probed it ready
        router.probe_all(timeout_s=0.2)  # real probe: connection refused
        recorder.wait_idle()
        s = recorder.stats()
        assert s["triggers"].get("replica_unreachable", 0) == 1
        # still-unreachable on later rounds: no transition, no re-fire
        router.probe_all(timeout_s=0.2)
        recorder.wait_idle()
        assert recorder.stats()["triggers"]["replica_unreachable"] == 1


# --------------------------------------- serve-side fault-plan parsing


class TestServeFaultPlan:
    def test_parse_round_trip(self):
        p = faultinject.FaultPlan.parse(
            "dispatch_exc=2;wedge_flush=1:0.5;slow_dispatch=50:3;"
            "drop_conn=4"
        )
        assert p.dispatch_exc == 2
        assert p.wedge_flush == 1 and p.wedge_secs == 0.5
        assert p.slow_dispatch_ms == 50.0 and p.slow_every == 3
        assert p.drop_conn == 4
        d = p.describe()
        assert "dispatch exception" in d and "wedge" in d
        assert "drop every 4th" in d

    def test_dispatch_point_fires_at_exact_ordinal(self):
        faultinject.set_plan(faultinject.FaultPlan(dispatch_exc=2))
        try:
            faultinject.dispatch_point()  # flush 0
            faultinject.dispatch_point()  # flush 1
            with pytest.raises(faultinject.InjectedDispatchError):
                faultinject.dispatch_point()  # flush 2
            faultinject.dispatch_point()  # later flushes unaffected
        finally:
            faultinject.set_plan(None)

    def test_drop_connection_every_nth(self):
        faultinject.set_plan(faultinject.FaultPlan(drop_conn=3))
        try:
            hits = [faultinject.drop_connection() for _ in range(6)]
            assert hits == [False, False, True, False, False, True]
        finally:
            faultinject.set_plan(None)

    def test_hooks_are_noops_without_plan(self):
        faultinject.set_plan(None)
        assert not faultinject.drop_connection()
        faultinject.dispatch_point()  # must not raise or count
