"""Self-driving fleet tests (cgnn_tpu.fleet.autoscale/remediate;
ISSUE 17).

Everything here is host-side policy on injectable clocks with fake
signal providers — no jax, no sockets. The live legs (load ramp with
scale-up-before-shed, wedge with remediator replace-and-drain) run in
scripts/fleet_smoke.sh against real serve.py replicas.

The load-bearing guarantees, pinned:

- autoscaler decision core: hysteresis (up threshold above down
  threshold; the band holds), cooldowns between actions (shed bypasses
  the up-cooldown — capacity was REFUSED), min/max bounds, scale-down
  only after a sustained-calm window, warm-pool accounting bounded by
  headroom, victim selection = least loaded and never a draining one;
- scale-event vs incident: a draining replica's disappearance is
  removed as a scale event (no flight-recorder trigger, breaker
  untouched); an un-flagged disappearance counts an incident, fires
  the recorder, and STAYS routed for re-admission;
- crash-loop guard: exponential restart backoff with a give-up cap;
- health-poller backoff: the probe interval for an unreachable replica
  doubles to a bound and resets on first success;
- remediator: the wedge signature (health plane answers, dispatch
  plane tripped) maps to replace-and-drain, rate limits hold against
  respawn storms, and every action names its evidence bundle.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from cgnn_tpu.fleet.autoscale import (
    AutoscalePolicy,
    Autoscaler,
    ScaleSignals,
    signals_from_router,
)
from cgnn_tpu.fleet.remediate import (
    RemediationPolicy,
    Remediator,
    rid_from_detail,
)
from cgnn_tpu.fleet.replica import ReplicaState
from cgnn_tpu.fleet.router import FleetRouter
from cgnn_tpu.fleet.spawn import RestartBackoff, boot_with_retries


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _policy(**kw) -> AutoscalePolicy:
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 8)
    kw.setdefault("up_queue_per_replica", 2.0)
    kw.setdefault("down_queue_per_replica", 0.5)
    kw.setdefault("cooldown_up_s", 5.0)
    kw.setdefault("cooldown_down_s", 10.0)
    kw.setdefault("down_sustain_s", 10.0)
    return AutoscalePolicy(**kw)


def _sig(**kw) -> ScaleSignals:
    kw.setdefault("replicas", 2)
    kw.setdefault("ready", 2)
    return ScaleSignals(**kw)


# ------------------------------------------------- the decision core


class TestAutoscalePolicy:
    def test_queue_above_up_threshold_scales_up(self):
        p = _policy()
        d = p.poll(0.0, _sig(queue_depth=5.0))  # 2.5/replica >= 2.0
        assert d is not None and d.action == "up"
        assert "queue" in d.reason

    def test_hysteresis_band_holds(self):
        # between down (0.5) and up (2.0) per replica: no decision in
        # EITHER direction, no matter how long it sits there
        p = _policy()
        clk = 0.0
        for _ in range(50):
            assert p.poll(clk, _sig(queue_depth=2.0)) is None  # 1.0/rep
            clk += 1.0

    def test_equal_thresholds_rejected(self):
        with pytest.raises(ValueError):
            _policy(up_queue_per_replica=1.0, down_queue_per_replica=1.0)

    def test_up_cooldown_blocks_back_to_back_ups(self):
        p = _policy(cooldown_up_s=5.0)
        assert p.poll(0.0, _sig(queue_depth=10.0)).action == "up"
        assert p.poll(1.0, _sig(replicas=3, ready=3,
                                queue_depth=10.0)) is None
        d = p.poll(6.0, _sig(replicas=3, ready=3, queue_depth=10.0))
        assert d is not None and d.action == "up"

    def test_shed_bypasses_up_cooldown(self):
        # a shed means capacity was REFUSED: the urgent path must not
        # sit out a cooldown while requests bounce
        p = _policy(cooldown_up_s=60.0)
        assert p.poll(0.0, _sig(queue_depth=10.0, shed=0)).action == "up"
        d = p.poll(1.0, _sig(replicas=3, ready=3, shed=4))
        assert d is not None and d.action == "up" and d.urgent
        assert "shed" in d.reason

    def test_shed_delta_not_cumulative(self):
        # the cumulative fleet_shed counter must not re-trigger forever
        # on one old incident
        p = _policy()
        # queue_depth in the hysteresis band: only a shed could trigger
        assert p.poll(0.0, _sig(queue_depth=2.0, shed=7)) is None
        assert p.poll(20.0, _sig(queue_depth=2.0, shed=7)) is None

    def test_max_bound_holds_even_urgent(self):
        p = _policy(max_replicas=2)
        assert p.poll(0.0, _sig(replicas=2, ready=2, queue_depth=50.0,
                                shed=9)) is None

    def test_below_min_repairs_immediately(self):
        p = _policy(min_replicas=2)
        d = p.poll(0.0, _sig(replicas=1, ready=1))
        assert d is not None and d.action == "up" and d.urgent
        assert d.reason == "below_min_replicas"

    def test_p99_and_burn_triggers(self):
        p = _policy(up_p99_ms=500.0)
        d = p.poll(0.0, _sig(p99_ms=900.0))
        assert d is not None and "p99" in d.reason
        p2 = _policy(up_burn=6.0)
        # both windows must burn (the multi-window rule): fast alone no
        assert p2.poll(0.0, _sig(burn_fast=10.0, burn_slow=1.0)) is None
        d2 = p2.poll(0.0, _sig(burn_fast=10.0, burn_slow=8.0))
        assert d2 is not None and "burn" in d2.reason

    def test_scale_down_needs_sustained_calm(self):
        p = _policy(down_sustain_s=10.0, cooldown_down_s=0.0)
        calm = _sig(replicas=3, ready=3, queue_depth=0.0)
        assert p.poll(0.0, calm) is None     # calm starts counting
        assert p.poll(5.0, calm) is None     # not sustained yet
        # a busy blip RESETS the calm window
        assert p.poll(6.0, _sig(replicas=3, ready=3,
                                queue_depth=3.0)) is None
        assert p.poll(7.0, calm) is None
        assert p.poll(12.0, calm) is None    # only 5 s calm again
        d = p.poll(17.5, calm)
        assert d is not None and d.action == "down"

    def test_scale_down_never_below_min(self):
        p = _policy(min_replicas=2, down_sustain_s=0.5,
                    cooldown_down_s=0.0)
        calm = _sig(replicas=2, ready=2, queue_depth=0.0)
        p.poll(0.0, calm)
        assert p.poll(10.0, calm) is None

    def test_draining_counts_against_down_headroom(self):
        # 3 routed but 2 already draining: one more down would land
        # below min — hold
        p = _policy(min_replicas=1, down_sustain_s=0.5,
                    cooldown_down_s=0.0)
        calm = _sig(replicas=3, ready=1, draining=2, queue_depth=0.0)
        p.poll(0.0, calm)
        assert p.poll(10.0, calm) is None

    def test_down_cooldown(self):
        p = _policy(down_sustain_s=1.0, cooldown_down_s=30.0)
        calm = _sig(replicas=4, ready=4, queue_depth=0.0)
        p.poll(0.0, calm)
        assert p.poll(2.0, calm).action == "down"
        p.poll(3.0, calm)
        assert p.poll(10.0, calm) is None    # cooldown holds
        assert p.poll(40.0, calm).action == "down"

    def test_pool_deficit_bounded_by_headroom(self):
        p = _policy(max_replicas=4, warm_target=2)
        assert p.pool_deficit(_sig(replicas=1, warm_pool=0)) == 2
        assert p.pool_deficit(_sig(replicas=1, warm_pool=1)) == 1
        assert p.pool_deficit(_sig(replicas=1, warm_pool=2)) == 0
        # at the bound, a spare could never be routed: don't warm it
        assert p.pool_deficit(_sig(replicas=4, warm_pool=0)) == 0
        assert p.pool_deficit(_sig(replicas=3, warm_pool=0)) == 1

    def test_pick_victim_least_loaded_never_draining(self):
        a = ReplicaState(0, "http://127.0.0.1:9000")
        a.note_probe(ready=True, queue_depth=5.0)
        b = ReplicaState(1, "http://127.0.0.1:9001")
        b.note_probe(ready=True, queue_depth=0.0)
        c = ReplicaState(2, "http://127.0.0.1:9002")
        c.note_probe(ready=True, queue_depth=0.0)
        c.note_draining()
        # b and c are equally idle, but c is already going
        assert AutoscalePolicy.pick_victim([a, b, c]) == 1
        b.note_draining()
        assert AutoscalePolicy.pick_victim([a, b, c]) == 0
        a.note_draining()
        assert AutoscalePolicy.pick_victim([a, b, c]) is None


# -------------------------------------------------- crash-loop guard


class FakeProc:
    """A ReplicaProcess-shaped fake: scripted boot outcomes."""

    def __init__(self, rid=0, outcomes=()):
        self.rid = rid
        self.base_url = f"http://127.0.0.1:{9100 + rid}"
        self.outcomes = list(outcomes)  # True = boot ok, False = crash
        self.starts = 0
        self.kills = 0
        self.terminated = False
        self.exit_code = 0
        self._ok = False

    def start(self):
        self._ok = self.outcomes.pop(0) if self.outcomes else True
        self.starts += 1
        return self

    def alive(self):
        return self._ok and not self.terminated

    def wait_ready(self, timeout_s=300.0, poll_s=0.25):
        return self._ok

    def kill9(self):
        self.kills += 1
        self._ok = False

    def terminate(self, timeout_s=60.0):
        self.terminated = True
        self._ok = False
        return self.exit_code

    def probe(self, timeout_s=2.0):
        return True


class TestRestartBackoff:
    def test_exponential_delays_then_give_up(self):
        clk = FakeClock()
        b = RestartBackoff(base_s=0.5, mult=2.0, max_s=3.0, give_up=5,
                           clock=clk)
        assert b.next_delay() == 0.5
        assert b.next_delay() == 1.0
        assert b.next_delay() == 2.0
        assert b.next_delay() == 3.0   # capped at max_s
        assert b.next_delay() is None  # 5th failure: budget spent
        assert b.failures == 5

    def test_reset_restores_budget(self):
        b = RestartBackoff(base_s=0.5, give_up=2, clock=FakeClock())
        assert b.next_delay() == 0.5
        b.reset()
        assert b.failures == 0
        assert b.next_delay() == 0.5   # full budget again

    def test_boot_with_retries_outlasts_boot_crash(self):
        # the boot_crash=N pin shape: N boots die, the N+1st succeeds
        proc = FakeProc(outcomes=[False, False, True])
        slept = []
        ok = boot_with_retries(
            proc, backoff=RestartBackoff(base_s=0.25, give_up=5,
                                         clock=FakeClock()),
            log_fn=lambda *a: None, sleep=slept.append)
        assert ok and proc.starts == 3
        assert slept == [0.25, 0.5]    # exponential between attempts

    def test_boot_with_retries_gives_up_and_reaps(self):
        proc = FakeProc(outcomes=[False] * 10)
        ok = boot_with_retries(
            proc, backoff=RestartBackoff(base_s=0.1, give_up=3,
                                         clock=FakeClock()),
            log_fn=lambda *a: None, sleep=lambda s: None)
        assert not ok
        assert proc.starts == 3        # give_up bounds the respawns
        assert proc.terminated

    def test_boot_crash_fault_point_across_real_processes(self, tmp_path):
        # the boot_crash=N pin against the REAL fault point: state
        # survives each crashed process (the crash takes its in-memory
        # counters with it), so the first N boots die with os._exit(7)
        # and the N+1st proceeds — exactly what the crash-loop guard
        # retries through
        state = tmp_path / "boots"
        env = dict(os.environ)
        env["CGNN_TPU_FAULTS"] = "boot_crash=2"
        env["CGNN_TPU_FAULT_STATE"] = str(state)
        code = ("from cgnn_tpu.resilience import faultinject; "
                "faultinject.boot_point(); print('SURVIVED')")
        runs = [subprocess.run([sys.executable, "-c", code], env=env,
                               capture_output=True, text=True,
                               timeout=120)
                for _ in range(3)]
        assert [r.returncode for r in runs] == [7, 7, 0]
        assert "SURVIVED" in runs[2].stdout
        assert state.stat().st_size == 3  # one byte per boot attempt


# ------------------------------------------- health-poller backoff


class TestProbeBackoff:
    def test_interval_doubles_to_bound_and_resets(self):
        clk = FakeClock()
        r = ReplicaState(0, "http://127.0.0.1:9000", clock=clk,
                         probe_backoff_base_s=1.0,
                         probe_backoff_max_s=4.0)
        assert r.probe_due()           # reachable: always due
        r.note_unreachable()
        assert not r.probe_due()       # 1 s backoff armed
        clk.advance(1.1)
        assert r.probe_due()
        r.note_unreachable()           # still dead: doubles to 2 s
        clk.advance(1.1)
        assert not r.probe_due()
        clk.advance(1.0)
        assert r.probe_due()
        r.note_unreachable()           # 4 s
        r.note_unreachable()           # capped at 4 s
        assert r.stats()["probe_backoff_s"] == 4.0
        clk.advance(4.1)
        assert r.probe_due()
        r.note_probe(ready=True)       # first success resets fully
        assert r.stats()["probe_backoff_s"] == 0.0
        assert r.probe_due()


# ------------------------- scale events vs incidents (the ledger)


class ScriptedReplica(ReplicaState):
    """probe() plays back a script of states instead of hitting a
    socket: True = healthy probe, 'draining' = healthy-but-draining,
    False = unreachable."""

    def __init__(self, rid, script, **kw):
        super().__init__(rid, f"http://127.0.0.1:{9200 + rid}", **kw)
        self.script = list(script)

    def probe(self, timeout_s=2.0):
        step = self.script.pop(0) if self.script else False
        if step is False:
            self.note_unreachable()
            return False
        self.note_probe(ready=step is True, draining=step == "draining")
        return step is True


class FakeRecorder:
    def __init__(self):
        self.trigger_calls = []
        self.last_bundle = "/tmp/bundle-last"
        self.on_trigger = None

    def trigger(self, reason, detail="", **kw):
        self.trigger_calls.append((reason, detail))
        if self.on_trigger is not None:
            self.on_trigger(reason, detail, f"/tmp/bundle-{reason}")
        return f"/tmp/bundle-{reason}"


def _router(replicas, **kw):
    kw.setdefault("slo_layer", False)
    kw.setdefault("trace_ring", 0)
    kw.setdefault("log_fn", lambda *a: None)
    return FleetRouter(replicas, transport=lambda *a: (200, {}), **kw)


class TestScaleEventClassification:
    def test_draining_disappearance_is_scale_event(self):
        clk = FakeClock()
        victim = ScriptedReplica(0, [True, "draining", False], clock=clk)
        other = ScriptedReplica(1, [True] * 10, clock=clk)
        router = _router([victim, other], clock=clk)
        rec = FakeRecorder()
        router.flightrec = rec
        router.probe_all()             # both healthy
        router.probe_all()             # victim advertises draining
        router.probe_all()             # victim gone
        counts = router.stats()["counts"]
        assert counts["fleet_scale_events"] == 1
        assert counts["fleet_incidents"] == 0
        # removed from routing, NO incident bundle, breaker untripped
        assert [r.rid for r in router.replica_list()] == [1]
        assert rec.trigger_calls == []
        assert victim.breaker.stats()["state"] == "closed"

    def test_unflagged_disappearance_is_incident_and_stays_routed(self):
        clk = FakeClock()
        victim = ScriptedReplica(0, [True, False], clock=clk)
        other = ScriptedReplica(1, [True] * 10, clock=clk)
        router = _router([victim, other], clock=clk)
        rec = FakeRecorder()
        router.flightrec = rec
        router.probe_all()
        router.probe_all()             # victim vanishes un-flagged
        counts = router.stats()["counts"]
        assert counts["fleet_incidents"] == 1
        assert counts["fleet_scale_events"] == 0
        # stays routed: a kill -9'd replica may restart and re-admit
        assert [r.rid for r in router.replica_list()] == [0, 1]
        assert [c[0] for c in rec.trigger_calls] == ["replica_unreachable"]

    def test_begin_drain_makes_fast_exit_a_scale_event(self):
        # the race the sticky router-side mark closes: SIGTERM lands
        # and the replica dies before ANY probe saw it draining
        clk = FakeClock()
        victim = ScriptedReplica(0, [True, True, False], clock=clk)
        router = _router([victim, ScriptedReplica(1, [True] * 9,
                                                  clock=clk)], clock=clk)
        router.probe_all()
        router.begin_drain(0)
        router.probe_all()             # probe overwrites nothing:
        assert victim.stats()["draining"]  # intent is sticky
        router.probe_all()
        counts = router.stats()["counts"]
        assert counts["fleet_scale_events"] == 1
        assert counts["fleet_incidents"] == 0

    def test_probe_backoff_skips_dead_replica_rounds(self):
        clk = FakeClock()
        dead = ScriptedReplica(0, [False] * 10, clock=clk,
                               probe_backoff_base_s=2.0)
        router = _router([dead, ScriptedReplica(1, [True] * 10,
                                                clock=clk)], clock=clk)
        router.probe_all()             # probes it (due), backs off 2 s
        router.probe_all()             # NOT due: skipped
        router.probe_all()
        assert dead.stats()["probes"] == 1
        clk.advance(2.1)
        router.probe_all()             # due again
        assert dead.stats()["probes"] == 2


class TestRouterMembership:
    def test_add_and_remove(self):
        router = _router([ScriptedReplica(0, [True])])
        n = ReplicaState(5, "http://127.0.0.1:9905")
        router.add_replica(n)
        assert [r.rid for r in router.replica_list()] == [0, 5]
        with pytest.raises(ValueError):
            router.add_replica(ReplicaState(5, "http://127.0.0.1:9906"))
        assert router.remove_replica(5, reason="scale_down") is n
        # idempotent: the poller and the drain thread can both notice
        assert router.remove_replica(5, reason="scale_down") is None
        assert router.count("fleet_scale_events") == 1
        events = router.lifecycle_events()
        assert [e["event"] for e in events] == ["add", "remove"]

    def test_remediation_removal_counts_incident(self):
        router = _router([ScriptedReplica(0, [True]),
                          ScriptedReplica(1, [True])])
        router.remove_replica(0, reason="remediation")
        assert router.count("fleet_incidents") == 1
        assert router.count("fleet_scale_events") == 0


# ------------------------------------------------ autoscaler runtime


def _runtime(clk=None, n=2, **pol_kw):
    clk = clk or FakeClock()
    replicas = [ScriptedReplica(i, [True] * 50, clock=clk)
                for i in range(n)]
    router = _router(replicas, clock=clk)
    router.probe_all()
    procs = {i: FakeProc(i) for i in range(n)}
    made = []

    def factory(rid):
        p = FakeProc(rid)
        made.append(p)
        return p

    def state_factory(rid, base_url):
        r = ReplicaState(rid, base_url, clock=clk)
        r.note_probe(ready=True)
        return r

    pol_kw.setdefault("min_replicas", 1)
    pol_kw.setdefault("max_replicas", 6)
    asc = Autoscaler(router, _policy(**pol_kw), factory, state_factory,
                     procs=procs, next_rid=n, drain_timeout_s=1.0,
                     clock=clk, log_fn=lambda *a: None)
    return router, asc, made


class TestAutoscalerRuntime:
    def test_scale_up_prefers_warm_pool(self):
        router, asc, made = _runtime()
        asc._refill_one()              # warm one spare synchronously
        assert [rid for rid, _ in asc.pool] == [2]
        rid = asc.scale_up("test")
        assert rid == 2
        assert asc.pool == []          # popped from the pool
        assert 2 in [r.rid for r in router.replica_list()]
        assert asc.stats()["counts"]["scale_ups"] == 1
        actions = [e["action"] for e in asc.stats()["events"]]
        assert actions == ["pool_add", "scale_up"]

    def test_scale_up_cold_boots_when_pool_empty(self):
        router, asc, made = _runtime()
        rid = asc.scale_up("test")
        assert rid == 2 and len(made) == 1
        assert 2 in [r.rid for r in router.replica_list()]

    def test_scale_down_drains_least_loaded_and_records(self):
        router, asc, _ = _runtime()
        router._replica(0).note_probe(ready=True, queue_depth=9.0)
        victim = asc.scale_down("test")
        assert victim == 1             # the idle one
        for t in asc._down_threads:
            t.join(timeout=5.0)
        assert asc.proc_for(1).terminated
        assert [r.rid for r in router.replica_list()] == [0]
        assert router.count("fleet_scale_events") == 1
        assert router.count("fleet_incidents") == 0
        assert asc.stats()["counts"]["scale_downs"] == 1

    def test_tick_replenishes_pool_when_calm(self):
        router, asc, made = _runtime(warm_target=1)
        d = asc.tick()                 # calm fleet: no scale decision
        assert d is None
        with asc._lock:
            refill = asc._refill_thread
        assert refill is not None
        refill.join(timeout=5.0)
        assert len(asc.pool) == 1      # ...but the pool got warmed
        assert asc.stats()["counts"]["pool_refills"] == 1

    def test_tick_acts_on_overload(self):
        router, asc, made = _runtime(warm_target=0)
        router._replica(0).note_probe(ready=True, queue_depth=10.0)
        d = asc.tick()
        assert d is not None and d.action == "up"
        assert len(router.replica_list()) == 3

    def test_signals_from_router_snapshot(self):
        clk = FakeClock()
        replicas = [ScriptedReplica(i, [True] * 5, clock=clk)
                    for i in range(2)]
        router = _router(replicas, clock=clk)
        router.probe_all()
        replicas[0].note_probe(ready=True, queue_depth=3.0)
        replicas[1].note_probe(ready=True, draining=True)
        s = signals_from_router(router, warm_pool=2)
        assert s.replicas == 2 and s.ready == 1 and s.draining == 1
        assert s.queue_depth == 3.0 and s.warm_pool == 2


# -------------------------------------------------------- remediator


def _wedged_stats(**kw):
    # the wedge signature: health plane answers, dispatch plane dead.
    # ready=False is the REALISTIC trip-time state — the k-th timeout
    # clears the dispatch-path ready flag in the same breath that
    # trips the breaker — which is exactly why the signature must key
    # on probe_ready (the health plane's own word), never on ready
    kw.setdefault("probe_ok", True)
    kw.setdefault("probe_ready", True)
    kw.setdefault("ready", False)
    kw.setdefault("draining", False)
    return kw


class TestRemediationPolicy:
    def test_rid_extraction(self):
        assert rid_from_detail(
            "breaker_trip",
            "fleet.breaker.3: open after 3 consecutive failures") == 3
        assert rid_from_detail(
            "replica_unreachable",
            "replica12 (http://h:1) stopped answering health probes",
        ) == 12
        assert rid_from_detail("breaker_trip", "garbage") is None

    def test_wedge_signature_triggers_replace(self):
        p = RemediationPolicy(min_interval_s=0.0)
        a = p.consider(0.0, "breaker_trip",
                       "fleet.breaker.1: open after 3 consecutive "
                       "failures", _wedged_stats())
        assert a == {"action": "replace_and_drain", "replica": 1,
                     "why": a["why"]}
        assert "wedged" in a["why"]

    def test_loaded_or_dead_replica_not_replaced_on_trip(self):
        p = RemediationPolicy(min_interval_s=0.0)
        # dead replica: probe plane down too — the breaker did its job,
        # the restart/re-admission path owns this, not the remediator
        assert p.consider(0.0, "breaker_trip", "fleet.breaker.1: open",
                          _wedged_stats(probe_ok=False)) is None
        assert p.consider(0.0, "breaker_trip", "fleet.breaker.1: open",
                          _wedged_stats(probe_ready=False)) is None

    def test_unreachable_acts_unless_draining(self):
        p = RemediationPolicy(min_interval_s=0.0,
                              per_replica_interval_s=0.0)
        detail = "replica2 (http://h) stopped answering health probes"
        assert p.consider(0.0, "replica_unreachable", detail,
                          _wedged_stats(draining=True)) is None
        a = p.consider(0.0, "replica_unreachable", detail,
                       _wedged_stats(probe_ok=False, probe_ready=False))
        assert a is not None and a["replica"] == 2

    def test_rate_limits_hold_against_respawn_storm(self):
        p = RemediationPolicy(min_interval_s=10.0,
                              per_replica_interval_s=60.0,
                              max_actions=3)
        detail = "fleet.breaker.1: open after 3 consecutive failures"
        assert p.consider(0.0, "breaker_trip", detail,
                          _wedged_stats()) is not None
        # global interval
        assert p.consider(5.0, "breaker_trip",
                          "fleet.breaker.2: open", _wedged_stats()) is None
        # per-replica interval outlives the global one
        assert p.consider(15.0, "breaker_trip", detail,
                          _wedged_stats()) is None
        assert p.consider(15.0, "breaker_trip",
                          "fleet.breaker.2: open",
                          _wedged_stats()) is not None
        # hard cap
        assert p.consider(30.0, "breaker_trip",
                          "fleet.breaker.3: open",
                          _wedged_stats()) is not None
        assert p.consider(60.0, "breaker_trip",
                          "fleet.breaker.4: open", _wedged_stats()) is None
        assert p.stats()["actions_taken"] == 3
        assert p.stats()["suppressed"] == 3

    def test_non_actionable_reasons_ignored(self):
        p = RemediationPolicy(min_interval_s=0.0)
        assert p.consider(0.0, "5xx_burst", "20+ server errors",
                          _wedged_stats()) is None
        assert p.consider(0.0, "slo_burn_fleet_availability", "x",
                          _wedged_stats()) is None


class TestRemediator:
    def _make(self, tmp_path, clk=None):
        clk = clk or FakeClock()
        router, asc, made = _runtime(clk=clk)
        rem = Remediator(router, asc,
                         RemediationPolicy(min_interval_s=0.0,
                                           per_replica_interval_s=0.0),
                         out_dir=str(tmp_path), drain_timeout_s=1.0,
                         clock=clk, log_fn=lambda *a: None)
        return router, asc, rem, made

    def test_replace_and_drain_chain(self, tmp_path):
        router, asc, rem, made = self._make(tmp_path)
        # wedge replica 1: health plane fine, breaker tripped
        record = rem.handle(
            "breaker_trip",
            "fleet.breaker.1: open after 3 consecutive failures",
            "/tmp/bundle-breaker_trip")
        assert record is not None
        # replacement routed, victim unrouted + reaped
        rids = [r.rid for r in router.replica_list()]
        assert 1 not in rids and 2 in rids
        assert record["replacement"] == 2
        assert asc.proc_for(1).terminated
        # the removal was an INCIDENT response, not elastic sizing
        assert router.count("fleet_incidents") == 1
        # the action chain names its evidence
        assert record["bundle"] == "/tmp/bundle-breaker_trip"
        path = os.path.join(str(tmp_path), "remediation.jsonl")
        with open(path) as f:
            lines = [json.loads(x) for x in f]
        assert len(lines) == 1
        assert lines[0]["replica"] == 1
        assert lines[0]["bundle"] == "/tmp/bundle-breaker_trip"

    def test_suppressed_bundle_falls_back_to_last(self, tmp_path):
        router, asc, rem, _ = self._make(tmp_path)
        rec = FakeRecorder()
        rem._recorder = rec
        record = rem.handle(
            "breaker_trip",
            "fleet.breaker.0: open after 3 consecutive failures", None)
        assert record is not None
        assert record["bundle"] == rec.last_bundle

    def test_attach_subscribes_and_worker_consumes(self, tmp_path):
        router, asc, rem, _ = self._make(tmp_path)
        rec = FakeRecorder()
        rem.attach(rec)
        assert rec.on_trigger is not None
        try:
            rec.trigger(
                "breaker_trip",
                "fleet.breaker.1: open after 3 consecutive failures")
            deadline = 50
            while not rem.stats()["actions"] and deadline:
                threading.Event().wait(0.05)
                deadline -= 1
            actions = rem.stats()["actions"]
            assert len(actions) == 1
            assert actions[0]["replica"] == 1
            assert actions[0]["bundle"] == "/tmp/bundle-breaker_trip"
        finally:
            rem.stop()

    def test_policy_veto_means_no_action(self, tmp_path):
        router, asc, rem, made = self._make(tmp_path)
        # mark the implicated replica draining: unreachable-on-draining
        # is the planned-exit path, not a remediation case
        router.begin_drain(1)
        record = rem.handle(
            "replica_unreachable",
            "replica1 (http://h) stopped answering health probes",
            "/tmp/b")
        assert record is None
        assert not os.path.exists(
            os.path.join(str(tmp_path), "remediation.jsonl"))
