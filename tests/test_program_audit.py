"""graftaudit tests (ISSUE 8): IR-level invariants + the roofline ledger.

Three layers, mirroring test_analysis.py:

- broken-program fixtures: toy programs with donation deliberately
  broken, an f64 sneaked in, or a ``pure_callback`` added — each must
  trip EXACTLY its check and stay quiet on the others;
- the live-repo pin: the real entry-program registry lowers and audits
  CLEAN (the IR-level twin of graftcheck's live-repo test), and the
  committed AUDIT_LEDGER.json carries a roofline row for every
  (rung, staging form) predict program plus the train step;
- the budget gate: ``diff_ledgers`` fails on a dropped program/key or a
  >threshold regression of a lower-is-better key, shrugs at
  improvements, and downgrades numeric drift to a warning under jax
  version skew — demonstrated end-to-end through the
  ``bench_regress.py --ledger`` CLI on a seeded regression.
"""

import copy
import json
import os
import subprocess
import sys
import warnings

import jax
import numpy as np
import pytest

from cgnn_tpu.analysis.program_audit import (
    CHECKS,
    LEDGER_GATE_KEYS,
    Program,
    check_donation,
    check_f64,
    check_hostcalls,
    check_identity,
    diff_ledgers,
    fingerprint,
    near_duplicates,
    run_audit,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEDGER_PATH = os.path.join(REPO, "AUDIT_LEDGER.json")

F32 = jax.ShapeDtypeStruct((8,), np.float32)


def _lowered_text(jitted, *avals) -> str:
    with warnings.catch_warnings():
        # the broken-donation fixture provokes jax's own donation
        # warning on purpose
        warnings.simplefilter("ignore")
        return jitted.lower(*avals).as_text()


def _program(name, text, donated=0, callbacks=0) -> Program:
    p = Program(name=name, donated_leaves=donated, callbacks=callbacks)
    p.text = text
    p.lowered = object()  # marks it as successfully lowered
    return p


def _other_checks_quiet(p: Program, tripped: str):
    """The fixture trips EXACTLY its check: every other per-program
    check stays quiet."""
    by_check = {
        "GA-DONATION": check_donation,
        "GA-F64": check_f64,
        "GA-HOSTCALL": check_hostcalls,
    }
    for check_id, fn in by_check.items():
        if check_id == tripped:
            continue
        assert fn(p) == [], f"{check_id} fired on the {tripped} fixture"


class TestBrokenProgramFixtures:
    def test_broken_donation_is_flagged(self):
        # the donated input's shape matches no output, so XLA cannot
        # alias it: jax drops the donation with a warning and the
        # program silently pays a copy — the exact failure mode
        step = jax.jit(lambda x: x[:1].sum(), donate_argnums=0)
        p = _program("toy/broken-donation", _lowered_text(step, F32),
                     donated=1)
        findings = check_donation(p)
        assert [f.check for f in findings] == ["GA-DONATION"]
        assert "donation silently not applied" in findings[0].message
        _other_checks_quiet(p, "GA-DONATION")

    def test_applied_donation_is_clean(self):
        step = jax.jit(lambda x: x + 1, donate_argnums=0)
        p = _program("toy/good-donation", _lowered_text(step, F32),
                     donated=1)
        assert p.text.count("tf.aliasing_output") == 1
        assert check_donation(p) == []

    def test_f64_sneak_is_flagged(self):
        from jax.experimental import enable_x64

        with enable_x64():
            f64_aval = jax.ShapeDtypeStruct((4,), np.float64)
            step = jax.jit(lambda x: x * 2.0)
            p = _program("toy/f64", _lowered_text(step, f64_aval))
        findings = check_f64(p)
        assert [f.check for f in findings] == ["GA-F64"]
        _other_checks_quiet(p, "GA-F64")

    def test_f32_program_passes_f64_check(self):
        step = jax.jit(lambda x: x * 2.0)
        p = _program("toy/f32", _lowered_text(step, F32))
        assert check_f64(p) == []

    def test_pure_callback_is_flagged(self):
        step = jax.jit(lambda x: jax.pure_callback(
            np.asarray, jax.ShapeDtypeStruct((8,), np.float32), x))
        p = _program("toy/callback", _lowered_text(step, F32))
        findings = check_hostcalls(p)
        assert [f.check for f in findings] == ["GA-HOSTCALL"]
        assert "callback" in findings[0].message
        _other_checks_quiet(p, "GA-HOSTCALL")

    def test_sanctioned_callback_count_passes(self):
        step = jax.jit(lambda x: jax.pure_callback(
            np.asarray, jax.ShapeDtypeStruct((8,), np.float32), x))
        p = _program("toy/tap", _lowered_text(step, F32), callbacks=1)
        assert check_hostcalls(p) == []

    def test_unknown_custom_call_is_flagged(self):
        p = _program("toy/weird", 'stablehlo.custom_call @weird_target(%0)')
        findings = check_hostcalls(p)
        assert [f.check for f in findings] == ["GA-HOSTCALL"]
        assert "weird_target" in findings[0].message

    def test_constant_only_twins_are_near_duplicates(self):
        # the Python-scalar-leakage shape: two programs identical except
        # for a burned-in constant
        a = _lowered_text(jax.jit(lambda x: x + np.float32(1.0)), F32)
        b = _lowered_text(jax.jit(lambda x: x + np.float32(2.0)), F32)
        assert fingerprint(a) != fingerprint(b)
        pairs = near_duplicates([("prog/a", a), ("prog/b", b)])
        assert pairs == [("prog/a", "prog/b")]
        findings = check_identity(
            [_program("prog/a", a), _program("prog/b", b)],
            predict_expected=0)
        assert "GA-IDENT" in [f.check for f in findings]

    def test_near_duplicate_pair_names_the_constant_variant(self):
        # byte-identical twins in the group are the duplicate finding's
        # job; the near-duplicate pair must name programs with DISTINCT
        # exact fingerprints so the report points at the real variant
        a = _lowered_text(jax.jit(lambda x: x + np.float32(1.0)), F32)
        b = _lowered_text(jax.jit(lambda x: x + np.float32(2.0)), F32)
        pairs = near_duplicates([("p/a1", a), ("p/a2", a), ("p/b", b)])
        assert len(pairs) == 1
        assert "p/b" in pairs[0], pairs

    def test_structurally_distinct_programs_are_not_duplicates(self):
        a = _lowered_text(jax.jit(lambda x: x + np.float32(1.0)), F32)
        b = _lowered_text(jax.jit(lambda x: x * x), F32)
        assert near_duplicates([("prog/a", a), ("prog/b", b)]) == []
        assert check_identity(
            [_program("prog/a", a), _program("prog/b", b)],
            predict_expected=0) == []

    def test_identical_programs_are_flagged(self):
        a = _lowered_text(jax.jit(lambda x: x + 1), F32)
        findings = check_identity(
            [_program("predict/a", a), _program("predict/b", a)],
            predict_expected=2)
        assert [f.check for f in findings] == ["GA-IDENT"]
        assert "IDENTICAL" in findings[0].message

    def test_predict_count_mismatch_is_flagged(self):
        findings = check_identity(
            [_program("predict/rung0/full",
                      _lowered_text(jax.jit(lambda x: x + 1), F32))],
            predict_expected=6)
        assert [f.check for f in findings] == ["GA-IDENT"]
        assert "expected" in findings[0].message


class TestShardBudgetFixtures:
    """GA-SHARD (ISSUE 10): the replicated-batch mistake must trip the
    gate; the correctly batch-sharded twin must pass it."""

    def _mesh_fixtures(self):
        from jax.sharding import PartitionSpec as P

        from cgnn_tpu.parallel import compat
        from cgnn_tpu.parallel.executor import MeshExecutor

        ex = MeshExecutor(jax.devices())
        n = len(ex)

        def body(w, b):
            return (b @ w).sum(axis=-1)

        good = jax.jit(compat.shard_map(
            body, mesh=ex.mesh, in_specs=(P(), P("data")),
            out_specs=P("data"), check_vma=False))
        # the classic mistake: the batch staged WITHOUT its sharding —
        # every device holds (and reads) the full stack
        bad = jax.jit(compat.shard_map(
            lambda w, b: body(w, b)[:1], mesh=ex.mesh,
            in_specs=(P(), P()), out_specs=P("data"), check_vma=False))
        w_av = jax.ShapeDtypeStruct((64, 64), np.float32)
        b_av = jax.ShapeDtypeStruct((n, 128, 64), np.float32)
        budget = 64 * 64 * 4 + (n * 128 * 64 * 4) // n
        return good, bad, (w_av, b_av), budget

    def test_replicated_batch_is_flagged(self):
        from cgnn_tpu.analysis.program_audit import check_shard_budget

        good, bad, avals, budget = self._mesh_fixtures()
        mem = bad.lower(*avals).compile().memory_analysis()
        p = Program(name="fixture/replicated", arg_byte_budget=budget)
        findings = check_shard_budget(p, mem)
        assert len(findings) == 1
        assert findings[0].check == "GA-SHARD"
        assert "REPLICATED" in findings[0].message

    def test_sharded_batch_passes(self):
        from cgnn_tpu.analysis.program_audit import check_shard_budget

        good, bad, avals, budget = self._mesh_fixtures()
        mem = good.lower(*avals).compile().memory_analysis()
        p = Program(name="fixture/sharded", arg_byte_budget=budget)
        assert check_shard_budget(p, mem) == []

    def test_unbudgeted_program_is_ungated(self):
        from cgnn_tpu.analysis.program_audit import check_shard_budget

        _, bad, avals, _ = self._mesh_fixtures()
        mem = bad.lower(*avals).compile().memory_analysis()
        assert check_shard_budget(Program(name="x"), mem) == []

    def test_unmeasurable_args_is_itself_a_finding(self):
        from cgnn_tpu.analysis.program_audit import check_shard_budget

        class _NoArgs:
            argument_size_in_bytes = 0

        findings = check_shard_budget(
            Program(name="x", arg_byte_budget=100), _NoArgs())
        assert len(findings) == 1 and findings[0].check == "GA-SHARD"


class TestLowerTrainProgram:
    def test_one_lowering_path_for_train_programs(self):
        """`lower_train_program` is the ONE jit/lower plumbing for
        train steps (used by the audit registry via jit_train_step and
        by scripts/hlo_dump.py): it lowers on abstract avals, with the
        donation applied."""
        from cgnn_tpu.analysis.program_audit import lower_train_program
        from cgnn_tpu.data.dataset import (
            FeaturizeConfig,
            load_synthetic_mp,
        )
        from cgnn_tpu.data.graph import batch_iterator, capacities_for
        from cgnn_tpu.models import CrystalGraphConvNet
        from cgnn_tpu.train import (
            Normalizer,
            create_train_state,
            make_optimizer,
        )

        graphs = load_synthetic_mp(8, FeaturizeConfig(radius=6.0,
                                                      max_num_nbr=8),
                                   seed=0)
        nc, ec = capacities_for(graphs, 4, snug=True)
        batch = next(batch_iterator(graphs, 4, nc, ec, snug=True))
        model = CrystalGraphConvNet(atom_fea_len=8, n_conv=1,
                                    h_fea_len=16)
        state = create_train_state(
            model, batch, make_optimizer(),
            Normalizer.fit(np.stack([g.target for g in graphs])),
        )
        text = lower_train_program(state, batch).as_text()
        n_leaves = len(jax.tree_util.tree_leaves(state))
        assert text.count("tf.aliasing_output") >= n_leaves
        # guard-wrapped variant lowers through the same path
        guarded = lower_train_program(state, batch, guard=True).as_text()
        assert guarded.count("tf.aliasing_output") >= n_leaves


@pytest.fixture(scope="module")
def live_audit():
    """One no-compile audit of the real entry-program registry, shared
    by every live-repo test (lowering ~10 programs is the slow part)."""
    return run_audit(compile=False)


class TestLiveRepo:
    def test_live_repo_audit_is_clean(self, live_audit):
        """THE pin: the real train/predict/expander programs obey the
        IR-level catalog. A finding here means fix the program — never
        weaken the check (INVARIANTS.md policy)."""
        findings, _, _ = live_audit
        assert not findings, (
            "graftaudit findings on the live repo:\n"
            + "\n".join(f.format() for f in findings)
        )

    def test_every_ladder_program_lowers(self, live_audit):
        _, ledger, programs = live_audit
        lowered = {p.name for p in programs if p.lowered is not None}
        expected = ledger["meta"]["predict_programs_expected"]
        rungs = len(ledger["meta"]["ladder"]["shapes"])
        # the engine dimension (ISSUE 10) x the staging-form dimension
        # (ISSUE 11): compact + full + raw per rung for the
        # single-device ladder AND the mesh-sharded twin (the conftest
        # mesh has 8 devices, so the mesh engine registers)
        assert ledger["meta"]["mesh_devices"] >= 2
        assert expected == 3 * rungs * 2
        predict = {n for n in lowered if n.startswith("predict/")}
        assert len(predict) == expected, sorted(predict)
        mesh = {n for n in predict if n.startswith("predict/mesh/")}
        assert len(mesh) == 3 * rungs, sorted(mesh)
        assert "train/coo" in lowered
        assert "train/coo+guard" in lowered
        assert "train/coo+tap@step" in lowered
        assert "expander/rung0" in lowered
        assert "ops/neighbor_search/rung0" in lowered

    def test_mesh_programs_carry_shard_budgets(self, live_audit):
        """Every mesh-sharded predict program is GA-SHARD-budgeted —
        an unbudgeted one would make the replication gate vacuous."""
        _, _, programs = live_audit
        mesh = [p for p in programs if p.name.startswith("predict/mesh/")]
        assert mesh
        for p in mesh:
            assert p.arg_byte_budget > 0, p.name

    def test_skips_are_known_backend_gaps_only(self, live_audit):
        _, ledger, _ = live_audit
        # conv/fused_pallas_fwd: Mosaic lowers only on a tpu backend
        # (its structured twin conv/fused_xla_fwd is audited everywhere)
        known = {"train/dense", "train/dp", "train/edge",
                 "conv/fused_pallas_fwd", "predict/mesh"}
        assert set(ledger["meta"]["skipped"]) <= known, (
            "unexpected skip — a program stopped lowering: "
            f"{ledger['meta']['skipped']}"
        )


class TestCommittedLedger:
    """The committed AUDIT_LEDGER.json is the CI budget baseline."""

    @pytest.fixture(scope="class")
    def ledger(self):
        with open(LEDGER_PATH) as f:
            return json.load(f)

    def test_every_program_has_roofline_keys(self, ledger):
        assert ledger["programs"], "empty ledger"
        for name, entry in ledger["programs"].items():
            for key in ("flops", "bytes", "intensity_flops_per_byte",
                        "bytes_per_flop", "peak_temp_bytes"):
                assert key in entry, f"{name} missing {key}"
            assert entry["flops"] > 0, name
            assert entry["bytes"] > 0, name

    def test_ladder_coverage(self, ledger):
        names = set(ledger["programs"])
        rungs = len(ledger["meta"]["ladder"]["shapes"])
        for rung in range(rungs):
            for form in ("compact", "full", "raw"):
                assert f"predict/rung{rung}/{form}" in names
        assert "train/coo" in names
        # the ISSUE-11 neighbor-search program rides its GA-ROOFLINE
        # budget in the baseline: dropping either diffs red
        entry = ledger["programs"].get("ops/neighbor_search/rung0")
        assert entry is not None and entry.get("byte_budget", 0) > 0
        assert entry["bytes"] <= entry["byte_budget"] * 2.0
        assert ledger["meta"]["gate_keys"] == list(LEDGER_GATE_KEYS)

    def test_mesh_engine_coverage(self, ledger):
        """The committed baseline carries the mesh-sharded predict rows
        with their GA-SHARD budgets: a future session dropping them (or
        their budgets) diffs red, not silent."""
        rungs = len(ledger["meta"]["ladder"]["shapes"])
        for rung in range(rungs):
            for form in ("compact", "full", "raw"):
                entry = ledger["programs"].get(
                    f"predict/mesh/rung{rung}/{form}")
                assert entry is not None, (rung, form)
                assert entry.get("arg_byte_budget", 0) > 0
                assert 0 < entry.get("arg_bytes", 0) <= (
                    entry["arg_byte_budget"] * 1.5)

    def test_train_step_donation_survived_compilation(self, ledger):
        # alias_bytes > 0 is the compiled-side proof donation applied
        for name, entry in ledger["programs"].items():
            if name.startswith("train/"):
                assert entry["alias_bytes"] > 0, (
                    f"{name}: no aliased bytes in the compiled "
                    "executable — donation not applied"
                )


def _ledger_payload(**programs) -> dict:
    return {"meta": {"jax": jax.__version__}, "programs": programs}


ROW = {"flops": 100.0, "bytes": 1000.0, "bytes_per_flop": 10.0,
       "peak_temp_bytes": 512}


class TestDiffLedgers:
    def test_clean_roundtrip(self):
        old = _ledger_payload(a=dict(ROW))
        assert diff_ledgers(old, copy.deepcopy(old))["regressions"] == []

    def test_improvement_passes(self):
        old = _ledger_payload(a=dict(ROW))
        new = _ledger_payload(a={**ROW, "bytes": 500.0})
        assert diff_ledgers(old, new)["regressions"] == []

    def test_small_drift_within_threshold_passes(self):
        old = _ledger_payload(a=dict(ROW))
        new = _ledger_payload(a={**ROW, "bytes": 1100.0})
        assert diff_ledgers(old, new)["regressions"] == []

    def test_regression_beyond_threshold_fails(self):
        old = _ledger_payload(a=dict(ROW))
        new = _ledger_payload(a={**ROW, "bytes": 1250.0})
        regs = diff_ledgers(old, new)["regressions"]
        assert len(regs) == 1 and regs[0]["key"] == "a.bytes"
        assert "REGRESSION" in regs[0]["note"]

    def test_zero_baseline_to_nonzero_is_a_regression(self):
        # a zero budget has no ratio — the expander's peak_temp_bytes=0
        # must not be a free pass to start materializing temps
        old = _ledger_payload(a={**ROW, "peak_temp_bytes": 0})
        new = _ledger_payload(a={**ROW, "peak_temp_bytes": 4096})
        regs = diff_ledgers(old, new)["regressions"]
        assert [r["key"] for r in regs] == ["a.peak_temp_bytes"]
        assert "budget was 0" in regs[0]["note"]

    def test_zero_to_zero_passes(self):
        old = _ledger_payload(a={**ROW, "peak_temp_bytes": 0})
        assert diff_ledgers(old, copy.deepcopy(old))["regressions"] == []

    def test_dropped_program_is_a_regression(self):
        old = _ledger_payload(a=dict(ROW), b=dict(ROW))
        new = _ledger_payload(a=dict(ROW))
        regs = diff_ledgers(old, new)["regressions"]
        assert [r["key"] for r in regs] == ["b"]
        assert "DROPPED" in regs[0]["note"]

    def test_dropped_gate_key_is_a_regression(self):
        old = _ledger_payload(a=dict(ROW))
        entry = dict(ROW)
        del entry["peak_temp_bytes"]
        regs = diff_ledgers(old, _ledger_payload(a=entry))["regressions"]
        assert [r["key"] for r in regs] == ["a.peak_temp_bytes"]

    def test_version_skew_downgrades_numeric_drift_to_warning(self):
        old = _ledger_payload(a=dict(ROW))
        old["meta"]["jax"] = "0.0.1-other"
        new = _ledger_payload(a={**ROW, "bytes": 2000.0})
        diff = diff_ledgers(old, new)
        assert diff["version_skew"]
        assert diff["regressions"] == []
        assert [w["key"] for w in diff["warnings"]] == ["a.bytes"]

    def test_version_skew_keeps_structural_drops_hard(self):
        old = _ledger_payload(a=dict(ROW), b=dict(ROW))
        old["meta"]["jax"] = "0.0.1-other"
        new = _ledger_payload(a=dict(ROW))
        assert [r["key"] for r in
                diff_ledgers(old, new)["regressions"]] == ["b"]


class TestCLI:
    def test_list_checks(self):
        res = subprocess.run(
            [sys.executable, os.path.join(REPO, "graftaudit.py"),
             "--list-checks"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert res.returncode == 0, res.stdout + res.stderr
        for check in CHECKS:
            assert check in res.stdout

    def _bench_regress(self, tmp_path, baseline, fresh):
        base = tmp_path / "baseline.json"
        new = tmp_path / "new.json"
        base.write_text(json.dumps(baseline))
        new.write_text(json.dumps(fresh))
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "bench_regress.py"),
             "--dir", str(tmp_path), "--github",
             "--ledger", str(base), str(new)],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )

    def test_budget_gate_fails_on_seeded_regression(self, tmp_path):
        """The acceptance pin: seed a regression against the committed
        ledger (baseline bytes halved => today's real bytes are 2x the
        budget) and the gate must go red with an ::error annotation."""
        with open(LEDGER_PATH) as f:
            baseline = json.load(f)
        seeded = copy.deepcopy(baseline)
        victim = sorted(seeded["programs"])[0]
        seeded["programs"][victim]["bytes"] *= 0.5
        res = self._bench_regress(tmp_path, seeded, baseline)
        assert res.returncode == 1, res.stdout + res.stderr
        assert "::error title=audit budget::" in res.stdout
        assert f"{victim}.bytes" in res.stdout

    def test_budget_gate_passes_on_identity(self, tmp_path):
        with open(LEDGER_PATH) as f:
            baseline = json.load(f)
        res = self._bench_regress(tmp_path, baseline, baseline)
        assert res.returncode == 0, res.stdout + res.stderr
        assert "audit budgets ok" in res.stdout
