"""GraphBatch invariant layer (VERDICT r2 #8, SURVEY.md §5 sanitizers).

Every deliberate corruption below must fail LOUDLY under check_batch;
conftest enables the global flag so every iterator-produced batch in the
whole suite is validated as a side effect.
"""

import numpy as np
import pytest

from cgnn_tpu.data import invariants
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.data.graph import batch_iterator, capacities_for


@pytest.fixture(scope="module")
def dense_batch():
    graphs = load_synthetic(24, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=9, max_atoms=6)
    nc, ec = capacities_for(graphs, 8, dense_m=8, snug=True)
    return next(batch_iterator(graphs, 8, nc, ec, dense_m=8, snug=True))


def test_clean_batches_validate(dense_batch):
    assert invariants.check_batch(dense_batch, dense_m=8) is dense_batch


@pytest.mark.parametrize(
    "corrupt,match",
    [
        (lambda b: b.replace(
            centers=np.flip(np.asarray(b.centers).copy())),
         "non-decreasing|ownership"),
        (lambda b: b.replace(
            neighbors=np.full_like(np.asarray(b.neighbors),
                                   b.node_capacity + 3)),
         "out of node-slot range"),
        (lambda b: b.replace(
            edge_mask=1.0 - np.asarray(b.edge_mask)),
         "padding|prefix|features"),
        (lambda b: b.replace(
            node_mask=np.concatenate(
                [[0.0], np.asarray(b.node_mask)[1:]])),
         "prefix|padding node"),
        (lambda b: b.replace(
            graph_mask=np.asarray(b.graph_mask) * 0.5),
         "outside"),
        (lambda b: b.replace(
            in_slots=np.zeros_like(np.asarray(b.in_slots))),
         "transpose|twice"),
    ],
)
def test_corruptions_fail_loudly(dense_batch, corrupt, match):
    with pytest.raises(invariants.BatchInvariantError, match=match):
        invariants.check_batch(corrupt(dense_batch), dense_m=8)


def test_dense_ownership_checked(dense_batch):
    c = np.asarray(dense_batch.centers).copy()
    c[10] = (10 // 8) + 1  # wrong owner, still sorted-ish
    with pytest.raises(invariants.BatchInvariantError):
        invariants.check_batch(dense_batch.replace(centers=c), dense_m=8)


def test_stacked_batch_rows_checked(dense_batch):
    """DP-stacked batches validate per device row, and a corrupted row is
    localized in the error (VERDICT r3 next-step #7)."""
    from cgnn_tpu.parallel.data_parallel import stack_batches

    stacked = stack_batches([dense_batch, dense_batch])
    assert invariants.check_any(stacked, train=True) is stacked
    bad_row = dense_batch.replace(
        centers=np.flip(np.asarray(dense_batch.centers).copy())
    )
    with pytest.raises(invariants.BatchInvariantError):
        invariants.check_any(stack_batches([dense_batch, bad_row]))


def test_empty_row_rejected_for_training(dense_batch):
    """empty_batch_like rows are eval-only; a training-stacked batch with
    one must fail loudly (the enforced never-train contract)."""
    from cgnn_tpu.parallel.data_parallel import (
        empty_batch_like,
        stack_batches,
    )

    stacked = stack_batches([dense_batch, empty_batch_like(dense_batch)])
    # eval accepts the padding row...
    assert invariants.check_any(stacked) is stacked
    # ...training does not
    with pytest.raises(invariants.BatchInvariantError, match="eval-only"):
        invariants.check_any(stacked, train=True)


def test_parallel_train_step_guards_empty_rows(dense_batch):
    """The jitted DP train step itself rejects a host-side stacked batch
    with an all-padding row under --check-invariants (last line of
    defense for direct callers that bypass the iterators)."""
    import jax

    from cgnn_tpu.parallel.data_parallel import (
        empty_batch_like,
        make_parallel_train_step,
        stack_batches,
    )
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    step = make_parallel_train_step(mesh)
    stacked = stack_batches([dense_batch, empty_batch_like(dense_batch)])
    with pytest.raises(invariants.BatchInvariantError, match="eval-only"):
        step(object(), stacked)  # rejected before state is even touched


def test_scan_driver_validates_input_batches(dense_batch):
    """ScanEpochDriver checks every input batch before staging stacks."""
    from cgnn_tpu.train.loop import ScanEpochDriver

    bad = dense_batch.replace(
        neighbors=np.full_like(np.asarray(dense_batch.neighbors),
                               dense_batch.node_capacity + 3)
    )
    with pytest.raises(invariants.BatchInvariantError):
        ScanEpochDriver(
            lambda s, b: (s, {}), lambda s, b: {},
            [dense_batch, bad], [], np.random.default_rng(0),
            stage=lambda t: t,
        )


def test_cache_spot_check_catches_corruption(tmp_path):
    """A cache whose arrays were corrupted on disk fails loudly on reload
    under --check-invariants (sample-based, so corrupt a sampled graph)."""
    from cgnn_tpu.data.cache import load_graph_cache, save_graph_cache

    graphs = load_synthetic(6, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=3, max_atoms=6)
    path = str(tmp_path / "cache.npz")
    save_graph_cache(graphs, path)
    assert len(load_graph_cache(path)) == 6  # clean cache passes

    # corrupt: neighbors of the FIRST graph point out of range (the spot
    # check always samples index 0)
    with np.load(path) as z:
        payload = {k: np.asarray(z[k]).copy() for k in z.files}
    payload["neighbors"][: int(payload["edge_counts"][0])] = 10**6
    with open(path, "wb") as f:
        np.savez(f, **payload)
    with pytest.raises(invariants.BatchInvariantError, match="out of range"):
        load_graph_cache(path)


def test_flag_gates_iterator_validation():
    graphs = load_synthetic(8, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=9, max_atoms=6)
    nc, ec = capacities_for(graphs, 4, snug=True)
    was = invariants.enabled()
    try:
        invariants.enable(False)
        assert len(list(batch_iterator(graphs, 4, nc, ec, snug=True))) >= 1
        invariants.enable(True)
        assert len(list(batch_iterator(graphs, 4, nc, ec, snug=True))) >= 1
    finally:
        invariants.enable(was)
