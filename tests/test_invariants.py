"""GraphBatch invariant layer (VERDICT r2 #8, SURVEY.md §5 sanitizers).

Every deliberate corruption below must fail LOUDLY under check_batch;
conftest enables the global flag so every iterator-produced batch in the
whole suite is validated as a side effect.
"""

import numpy as np
import pytest

from cgnn_tpu.data import invariants
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.data.graph import batch_iterator, capacities_for


@pytest.fixture(scope="module")
def dense_batch():
    graphs = load_synthetic(24, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=9, max_atoms=6)
    nc, ec = capacities_for(graphs, 8, dense_m=8, snug=True)
    return next(batch_iterator(graphs, 8, nc, ec, dense_m=8, snug=True))


def test_clean_batches_validate(dense_batch):
    assert invariants.check_batch(dense_batch, dense_m=8) is dense_batch


@pytest.mark.parametrize(
    "corrupt,match",
    [
        (lambda b: b.replace(
            centers=np.flip(np.asarray(b.centers).copy())),
         "non-decreasing|ownership"),
        (lambda b: b.replace(
            neighbors=np.full_like(np.asarray(b.neighbors),
                                   b.node_capacity + 3)),
         "out of node-slot range"),
        (lambda b: b.replace(
            edge_mask=1.0 - np.asarray(b.edge_mask)),
         "padding|prefix|features"),
        (lambda b: b.replace(
            node_mask=np.concatenate(
                [[0.0], np.asarray(b.node_mask)[1:]])),
         "prefix|padding node"),
        (lambda b: b.replace(
            graph_mask=np.asarray(b.graph_mask) * 0.5),
         "outside"),
        (lambda b: b.replace(
            in_slots=np.zeros_like(np.asarray(b.in_slots))),
         "transpose|twice"),
    ],
)
def test_corruptions_fail_loudly(dense_batch, corrupt, match):
    with pytest.raises(invariants.BatchInvariantError, match=match):
        invariants.check_batch(corrupt(dense_batch), dense_m=8)


def test_dense_ownership_checked(dense_batch):
    c = np.asarray(dense_batch.centers).copy()
    c[10] = (10 // 8) + 1  # wrong owner, still sorted-ish
    with pytest.raises(invariants.BatchInvariantError):
        invariants.check_batch(dense_batch.replace(centers=c), dense_m=8)


def test_flag_gates_iterator_validation():
    graphs = load_synthetic(8, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=9, max_atoms=6)
    nc, ec = capacities_for(graphs, 4, snug=True)
    was = invariants.enabled()
    try:
        invariants.enable(False)
        assert len(list(batch_iterator(graphs, 4, nc, ec, snug=True))) >= 1
        invariants.enable(True)
        assert len(list(batch_iterator(graphs, 4, nc, ec, snug=True))) >= 1
    finally:
        invariants.enable(was)
