"""MeshExecutor + multi-host readiness tests (ISSUE 10).

The load-bearing guarantees of the one-mesh execution layer, pinned:

- mesh-vs-DeviceSet BIT-exact parity over identical batches
  (run_fast_inference: ladder + compact + ragged 157-graph tail, and
  the legacy bucket path);
- the compile pin: traced programs = rungs x staging forms x tiers,
  INDEPENDENT of the device count, and — unlike the threads engine —
  executables = programs too (one multi-device program each), with a
  second full pass adding nothing;
- serving through the mesh engine: every shard answers, predictions
  match the offline reference, zero post-warmup recompiles, and a hot
  swap under concurrent sharded dispatch stays atomic (every
  response's numbers match the version it reports);
- the one-sharded-tree ParamStore mode (placer): swap publishes one
  tree under one version;
- per-host loader slicing (parallel/dist.host_shard): disjoint and
  complete for every (index, count) partition.
"""

import threading
import time

import numpy as np
import pytest

import jax

from cgnn_tpu.config import DataConfig, ModelConfig, build_model
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic, \
    load_synthetic_mp
from cgnn_tpu.parallel import dist
from cgnn_tpu.parallel.executor import MeshExecutor
from cgnn_tpu.serve.reload import ParamStore
from cgnn_tpu.serve.server import InferenceServer
from cgnn_tpu.serve.shapes import plan_shape_set
from cgnn_tpu.train import (
    CheckpointManager,
    Normalizer,
    create_train_state,
    make_optimizer,
)
from cgnn_tpu.train.infer import run_fast_inference
from cgnn_tpu.train.step import make_predict_step

CFG = FeaturizeConfig(radius=6.0, max_num_nbr=12)
SERVE_CFG = FeaturizeConfig(radius=5.0, max_num_nbr=8)


@pytest.fixture(scope="module")
def mp_graphs():
    return load_synthetic_mp(157, CFG, seed=9)


@pytest.fixture(scope="module")
def mp_state(mp_graphs):
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.data.graph import batch_iterator, capacities_for

    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=32,
                                dense_m=12)
    nc, ec = capacities_for(mp_graphs, 32, dense_m=12, snug=True)
    example = next(batch_iterator(mp_graphs, 32, nc, ec, dense_m=12,
                                  in_cap=0, snug=True))
    return create_train_state(
        model, example, make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in mp_graphs])),
        rng=jax.random.key(3),
    )


@pytest.fixture(scope="module")
def serve_graphs():
    return load_synthetic(48, SERVE_CFG, seed=11, max_atoms=8)


@pytest.fixture(scope="module")
def serve_state(serve_graphs):
    model_cfg = ModelConfig(atom_fea_len=8, n_conv=1, h_fea_len=16)
    model = build_model(model_cfg, DataConfig(radius=5.0, max_num_nbr=8))
    ss = plan_shape_set(serve_graphs, 8, rungs=2)
    state = create_train_state(
        model, ss.pack([serve_graphs[0]]), make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in serve_graphs])),
        rng=jax.random.key(7),
    )
    return model_cfg, ss, state


# --------------------------------------------------- offline inference


class TestMeshInference:
    def test_mesh_vs_threads_bit_exact_ladder_compact(self, mp_graphs,
                                                      mp_state):
        """THE parity pin: identical packing plan, identical per-shard
        program — the mesh engine's outputs must be BIT-equal to both
        the threads engine's and the single-device loop's, across the
        compact ladder with the ragged 157-graph tail."""
        from cgnn_tpu.data.compact import CompactSpec, make_expander

        spec = CompactSpec.build(mp_graphs, CFG.gdf(), dense_m=12)
        ladder = plan_shape_set(mp_graphs, 32, rungs=2, dense_m=12,
                                compact=spec)
        pstep = jax.jit(make_predict_step(make_expander(spec)))
        single, _ = run_fast_inference(mp_state, mp_graphs, 32,
                                       shape_set=ladder,
                                       predict_step=pstep, pack_workers=0)
        mesh, _ = run_fast_inference(mp_state, mp_graphs, 32,
                                     shape_set=ladder, pack_workers=3,
                                     devices=jax.devices(), engine="mesh")
        threads, _ = run_fast_inference(mp_state, mp_graphs, 32,
                                        shape_set=ladder,
                                        predict_step=pstep,
                                        pack_workers=3,
                                        devices=jax.devices(),
                                        engine="threads")
        np.testing.assert_array_equal(mesh, single)
        np.testing.assert_array_equal(threads, single)

    def test_mesh_bit_exact_legacy_buckets(self, mp_graphs, mp_state):
        pstep = jax.jit(make_predict_step())
        single, _ = run_fast_inference(mp_state, mp_graphs, 32, buckets=3,
                                       dense_m=12, snug=True,
                                       predict_step=pstep)
        mesh, _ = run_fast_inference(mp_state, mp_graphs, 32, buckets=3,
                                     dense_m=12, snug=True,
                                     predict_step=pstep,
                                     devices=jax.devices(), engine="mesh")
        np.testing.assert_array_equal(mesh, single)

    def test_auto_engine_is_mesh_for_multidevice(self, mp_graphs,
                                                 mp_state):
        """engine='auto' with > 1 device takes the mesh path (the
        default flip this ISSUE ships) — proven by the compile
        signature: one cache entry per shape, never per device."""
        ladder = plan_shape_set(mp_graphs, 32, rungs=2, dense_m=12)
        body = make_predict_step()
        traces = [0]

        def counting(state, batch):
            traces[0] += 1
            return body(state, batch)

        run_fast_inference(mp_state, mp_graphs, 32, shape_set=ladder,
                           predict_step=counting,
                           devices=jax.devices())  # engine defaults auto
        # the counting body is traced inside the ONE sharded program per
        # dispatched shape; the threads engine would trace the same
        # count but build 8x the executables — distinguishing them needs
        # the jit cache, covered below; here the trace count pins that
        # the auto path ran the mesh grouping (<= one trace per rung)
        assert 1 <= traces[0] <= len(ladder)

    def test_mesh_compile_count_independent_of_devices(self, mp_graphs,
                                                       mp_state):
        """Traced programs AND executables = one per (shape, form) under
        the mesh engine, independent of the device count; a second full
        pass adds neither."""
        from cgnn_tpu.data.compact import CompactSpec, make_expander

        spec = CompactSpec.build(mp_graphs, CFG.gdf(), dense_m=12)
        ladder = plan_shape_set(mp_graphs, 32, rungs=2, dense_m=12,
                                compact=spec)
        body = make_predict_step(make_expander(spec))
        for devices in (jax.devices()[:2], jax.devices()):
            executor = MeshExecutor(devices)
            mesh_predict = executor.shard_predict(body)
            placed = executor.place_params(mp_state)
            # drive the executor directly the way run_fast_inference
            # does: every rung's stacked program traced/compiled once
            for shape in ladder:
                sub = ladder.pack([mp_graphs[0]], shape=shape)
                staged = executor.stage(
                    executor.stack([sub] * len(executor)))
                np.asarray(mesh_predict(placed, staged))
            assert mesh_predict._cache_size() == len(ladder)
            # second pass: zero growth (the ISSUE acceptance pin:
            # compile count = programs, not programs x N)
            for shape in ladder:
                sub = ladder.pack([mp_graphs[0]], shape=shape)
                staged = executor.stage(
                    executor.stack([sub] * len(executor)))
                np.asarray(mesh_predict(placed, staged))
            assert mesh_predict._cache_size() == len(ladder)

    def test_plan_flush_common_rung_and_counts(self, mp_graphs):
        ladder = plan_shape_set(mp_graphs, 32, rungs=3, dense_m=12)
        executor = MeshExecutor(jax.devices())
        n = len(executor)
        groups, shape, counts = executor.plan_flush(mp_graphs[:11], ladder)
        assert len(groups) == n and len(counts) == n
        assert sum(counts) == 11
        assert max(counts) - min(counts) <= 1
        # every group (incl. filler-packed empties) fits the chosen rung
        for g in groups:
            tot_n = sum(x.num_nodes for x in g)
            tot_e = sum(ladder.graph_counts(x)[1] for x in g)
            assert shape.fits(len(g), tot_n, tot_e)
        # a 1-graph flush still plans: filler shards, counts record 0
        groups1, _, counts1 = executor.plan_flush(mp_graphs[:1], ladder)
        assert counts1[0] == 1 and sum(counts1) == 1
        assert all(len(g) >= 1 for g in groups1)  # filler, never empty


# --------------------------------------------------------- mesh serving


def _mesh_server(serve_state, **kw):
    _, ss, state = serve_state
    kw.setdefault("log_fn", lambda *a, **k: None)
    kw.setdefault("max_wait_ms", 5.0)
    return InferenceServer(state, ss, devices=jax.devices()[:4],
                           engine="mesh", **kw)


class TestMeshServing:
    def test_warm_compile_pin_and_distribution(self, serve_graphs,
                                               serve_state):
        _, ss, state = serve_state
        server = _mesh_server(serve_state, cache_size=0, pack_workers=1)
        server.warm(serve_graphs[0])
        # THE pin: programs, not programs x N (threads would read 2*4=8)
        assert server.engine == "mesh"
        assert server._jit_cache_size() == len(ss)
        server.start()
        futs = [server.submit(g, timeout_ms=30000)
                for _ in range(4) for g in serve_graphs[:24]]
        res = [f.result(30.0) for f in futs]
        assert server.drain(timeout_s=30.0)
        assert len(res) == 96
        assert server.stats()["recompiles_after_warm"] == 0
        assert server._jit_cache_size() == len(ss)
        assert server.stats()["engine"] == "mesh"
        # shard-level distribution: every mesh shard computed responses
        assert {r.device_id for r in res} == set(range(4))
        dev_stats = server.stats()["devices"]
        assert all(d["dispatches"] >= 1 for d in dev_stats)
        # parity with the offline single-device reference
        pstep = jax.jit(make_predict_step())
        for g, r in zip([g for _ in range(4) for g in serve_graphs[:24]],
                        res):
            ref = np.asarray(pstep(state, ss.pack([g])))[0]
            np.testing.assert_allclose(r.prediction, ref, rtol=1e-5,
                                       atol=1e-5)

    def test_hot_swap_atomic_under_concurrent_sharded_dispatch(
            self, serve_graphs, serve_state, tmp_path):
        model_cfg, ss, state = serve_state
        mgr = CheckpointManager(str(tmp_path / "meshckpt"),
                                log_fn=lambda m: None)

        def save(nudge=0.0):
            s = state
            if nudge:
                s = state.replace(params=jax.tree_util.tree_map(
                    lambda x: (np.asarray(x) + nudge).astype(
                        np.asarray(x).dtype)
                    if np.issubdtype(np.asarray(x).dtype, np.floating)
                    else x, state.params))
            mgr.save(s, {"model": model_cfg.to_meta(),
                         "data": DataConfig(radius=5.0,
                                            max_num_nbr=8).to_meta(),
                         "task": "regression", "epoch": 0})
            mgr.wait()
            return mgr.newest_committed(), s

        v1, _ = save()
        server = _mesh_server(serve_state, cache_size=0, pack_workers=1,
                              version=v1, default_timeout_ms=60000.0,
                              max_queue=4096)
        server.warm(serve_graphs[0])
        watcher = server.attach_watcher(mgr, poll_interval_s=3600)
        v2, nudged = save(nudge=0.5)
        server.start()

        results, lock, stop = [], threading.Lock(), threading.Event()

        def client(ci):
            rng = np.random.default_rng(ci)
            while not stop.is_set():
                g = serve_graphs[int(rng.integers(24))]
                r = server.predict(g, timeout_ms=60000)
                with lock:
                    results.append((id(g), r))

        threads = [threading.Thread(target=client, args=(i,), daemon=True)
                   for i in range(8)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= 40:
                    break
            time.sleep(0.01)
        assert watcher.poll_once()  # ONE sharded tree swaps mid-load
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            with lock:
                if len(results) >= 120:
                    break
            time.sleep(0.01)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert server.drain(timeout_s=60.0)
        assert server.stats()["recompiles_after_warm"] == 0

        pstep = jax.jit(make_predict_step())
        refs = {}
        for g in serve_graphs[:24]:
            refs[(id(g), v1)] = np.asarray(pstep(state, ss.pack([g])))[0]
            refs[(id(g), v2)] = np.asarray(pstep(nudged, ss.pack([g])))[0]
        seen = set()
        for gid, r in results:
            assert r.param_version in (v1, v2)
            seen.add(r.param_version)
            # THE atomicity pin: numbers match the version label, on
            # whatever shard computed them
            np.testing.assert_allclose(
                r.prediction, refs[(gid, r.param_version)],
                rtol=1e-4, atol=1e-4,
                err_msg=f"response labeled {r.param_version} (shard "
                        f"{r.device_id}) disagrees with those params")
        assert seen == {v1, v2}
        mgr.close()


# ----------------------------------------------- ParamStore placer mode


class TestParamStorePlacer:
    def test_one_tree_per_tier_and_atomic_swap(self, serve_state):
        _, _, state = serve_state
        executor = MeshExecutor(jax.devices()[:4])
        store = ParamStore(state, "v1", placer=executor.place_params)
        placed, version = store.get()
        assert version == "v1"
        # ONE sharded tree: its leaves are mesh-replicated jax Arrays
        leaf = jax.tree_util.tree_leaves(placed.params)[0]
        assert len(leaf.sharding.device_set) == 4
        store.swap(state, "v2")
        _, version = store.get()
        assert version == "v2"

    def test_placer_and_devices_are_exclusive(self, serve_state):
        _, _, state = serve_state
        with pytest.raises(ValueError):
            ParamStore(state, "v", devices=jax.devices()[:2],
                       placer=lambda s: s)


# ------------------------------------------------- per-host data slicing


class TestHostShard:
    def test_disjoint_and_complete(self):
        items = list(range(103))
        for count in (1, 2, 3, 5, 8):
            shards = [dist.host_shard(items, index=i, count=count)
                      for i in range(count)]
            flat = [x for s in shards for x in s]
            assert sorted(flat) == items  # complete
            assert len(flat) == len(set(flat))  # disjoint
            sizes = [len(s) for s in shards]
            assert max(sizes) - min(sizes) <= 1  # balanced

    def test_single_process_is_identity(self):
        items = ["a", "b", "c"]
        assert dist.host_shard(items) == items

    def test_bad_index_rejected(self):
        with pytest.raises(ValueError):
            dist.host_shard([1, 2], index=2, count=2)

    def test_inactive_helpers_degrade(self):
        # single-process semantics: no-op barrier, identity broadcast,
        # local min — the same entrypoints run unchanged on one host
        assert not dist.active()
        dist.barrier("noop")
        assert dist.broadcast_str("ckpt-00000007") == "ckpt-00000007"
        assert dist.min_over_hosts(5) == 5
        assert dist.is_coordinator()
