"""In-tree torch-CPU CGCNN oracle (SURVEY.md §4.3).

The reference tree is unavailable (SURVEY.md §0), so this ~150-LoC PyTorch
model — written fresh from the publicly-known CGCNN architecture spec
(SURVEY.md §2 components 6-7, §3.3) — serves as the numerical ground truth
for the JAX implementation: identical weights must produce identical
forwards/gradients. Dense [N, M] neighbor layout, exactly as the lineage
computes it. Test-only; never imported by the framework.
"""

from __future__ import annotations

import torch
import torch.nn as nn


class ConvLayer(nn.Module):
    """Edge-gated crystal-graph convolution, dense [N, M] layout."""

    def __init__(self, atom_fea_len: int, nbr_fea_len: int):
        super().__init__()
        self.atom_fea_len = atom_fea_len
        self.fc_full = nn.Linear(2 * atom_fea_len + nbr_fea_len, 2 * atom_fea_len)
        self.bn1 = nn.BatchNorm1d(2 * atom_fea_len)
        self.bn2 = nn.BatchNorm1d(atom_fea_len)

    def _masked_bn1(self, flat, mask_flat):
        """BatchNorm1d over only the rows with mask 1 — the semantics of
        the framework's MaskedBatchNorm (biased batch var for
        normalization, unbiased for the running update, momentum 0.1), so
        under-coordinated structures compare EXACTLY: a dense [N, M]
        padding slot must not pollute the batch statistics."""
        bn = self.bn1
        if self.training:
            rows = flat[mask_flat > 0]
            mean = rows.mean(dim=0)
            var = rows.var(dim=0, unbiased=False)
            with torch.no_grad():
                cnt = rows.shape[0]
                unbiased = var * cnt / max(cnt - 1, 1)
                bn.running_mean.mul_(1 - bn.momentum).add_(
                    bn.momentum * mean.detach())
                bn.running_var.mul_(1 - bn.momentum).add_(
                    bn.momentum * unbiased.detach())
        else:
            mean, var = bn.running_mean, bn.running_var
        y = (flat - mean) * torch.rsqrt(var + bn.eps)
        return y * bn.weight + bn.bias

    def forward(self, atom_in_fea, nbr_fea, nbr_fea_idx, nbr_mask=None):
        n, m = nbr_fea_idx.shape
        atom_nbr_fea = atom_in_fea[nbr_fea_idx, :]  # [N, M, F] gather
        total_fea = torch.cat(
            [
                atom_in_fea.unsqueeze(1).expand(n, m, self.atom_fea_len),
                atom_nbr_fea,
                nbr_fea,
            ],
            dim=2,
        )
        gated = self.fc_full(total_fea)
        flat = gated.view(-1, 2 * self.atom_fea_len)
        if nbr_mask is None:
            flat = self.bn1(flat)
        else:
            flat = self._masked_bn1(flat, nbr_mask.reshape(-1))
        gated = flat.view(n, m, 2 * self.atom_fea_len)
        nbr_filter, nbr_core = gated.chunk(2, dim=2)
        msg = torch.sigmoid(nbr_filter) * nn.functional.softplus(nbr_core)
        if nbr_mask is not None:
            msg = msg * nbr_mask.unsqueeze(-1)
        nbr_sumed = torch.sum(msg, dim=1)
        nbr_sumed = self.bn2(nbr_sumed)
        return nn.functional.softplus(atom_in_fea + nbr_sumed)


class TorchCGCNN(nn.Module):
    """Full oracle model: embedding, n_conv ConvLayers, pooling, MLP head."""

    def __init__(
        self,
        orig_atom_fea_len: int,
        nbr_fea_len: int,
        atom_fea_len: int = 64,
        n_conv: int = 3,
        h_fea_len: int = 128,
        n_h: int = 1,
        num_targets: int = 1,
        classification: bool = False,
        num_classes: int = 2,
    ):
        super().__init__()
        self.classification = classification
        self.embedding = nn.Linear(orig_atom_fea_len, atom_fea_len)
        self.convs = nn.ModuleList(
            ConvLayer(atom_fea_len, nbr_fea_len) for _ in range(n_conv)
        )
        self.conv_to_fc = nn.Linear(atom_fea_len, h_fea_len)
        self.fcs = nn.ModuleList(
            nn.Linear(h_fea_len, h_fea_len) for _ in range(n_h - 1)
        )
        # lineage classification head: fc_out -> LogSoftmax (trained with
        # NLLLoss), mirroring models/cgcnn.py's log_softmax output
        self.fc_out = nn.Linear(
            h_fea_len, num_classes if classification else num_targets
        )

    def forward(self, atom_fea, nbr_fea, nbr_fea_idx, crystal_atom_idx,
                nbr_mask=None):
        atom_fea = self.embedding(atom_fea)
        for conv in self.convs:
            atom_fea = conv(atom_fea, nbr_fea, nbr_fea_idx, nbr_mask)
        crys_fea = torch.stack(
            [atom_fea[idx].mean(dim=0) for idx in crystal_atom_idx]
        )
        crys_fea = self.conv_to_fc(nn.functional.softplus(crys_fea))
        crys_fea = nn.functional.softplus(crys_fea)
        for fc in self.fcs:
            crys_fea = nn.functional.softplus(fc(crys_fea))
        out = self.fc_out(crys_fea)
        if self.classification:
            out = nn.functional.log_softmax(out, dim=-1)
        return out


def variables_from_torch(oracle: "TorchCGCNN", template):
    """Transplant oracle weights into the flax variable tree.

    jnp.array (copy), never jnp.asarray: on CPU, asarray of tensor.numpy()
    is zero-copy, so torch's in-place running-stat updates during the
    oracle forward would silently mutate the transplanted JAX arrays too.

    Shared by the parity tests AND the MAE harness (which uses it with an
    UNTRAINED oracle so both frameworks start from the same torch-default
    init distribution — flax lecun_normal vs torch kaiming_uniform is an
    init-lottery confound, not a framework difference).
    """
    import jax
    import jax.numpy as jnp

    def w(linear):  # torch [out, in] -> flax kernel [in, out]
        return jnp.array(linear.weight.detach().numpy().T)

    def b(linear):
        return jnp.array(linear.bias.detach().numpy())

    params = jax.tree_util.tree_map(lambda x: x, template["params"])
    stats = jax.tree_util.tree_map(lambda x: x, template["batch_stats"])
    params["embedding"] = {"kernel": w(oracle.embedding),
                           "bias": b(oracle.embedding)}
    for i, conv in enumerate(oracle.convs):
        params[f"conv_{i}"]["fc_full"] = {"kernel": w(conv.fc_full),
                                          "bias": b(conv.fc_full)}
        for bn_name, bn in (("bn1", conv.bn1), ("bn2", conv.bn2)):
            params[f"conv_{i}"][bn_name] = {
                "scale": jnp.array(bn.weight.detach().numpy()),
                "bias": jnp.array(bn.bias.detach().numpy()),
            }
            stats[f"conv_{i}"][bn_name] = {
                "mean": jnp.array(bn.running_mean.detach().numpy()),
                "var": jnp.array(bn.running_var.detach().numpy()),
            }
    params["conv_to_fc"] = {"kernel": w(oracle.conv_to_fc),
                            "bias": b(oracle.conv_to_fc)}
    for i, fc in enumerate(oracle.fcs):
        params[f"fc_{i}"] = {"kernel": w(fc), "bias": b(fc)}
    params["fc_out"] = {"kernel": w(oracle.fc_out), "bias": b(oracle.fc_out)}
    return {"params": params, "batch_stats": stats}
