"""Edge-sharded graph parallelism (SP analog; SURVEY.md §5 long-context).

All tests run on the 8 virtual CPU devices from conftest. The bar is exact
agreement with the unsharded step — sharding is a layout change, not a
numerics change.
"""

import jax
import jax.numpy as jnp
import jax.tree_util as jtu
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.data.graph import batch_iterator, capacities_for
from cgnn_tpu.models import CrystalGraphConvNet
from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
from cgnn_tpu.train.step import make_train_step
from cgnn_tpu.parallel.data_parallel import (
    make_parallel_train_step,
    shard_leading_axis,
    stack_batches,
)
from cgnn_tpu.parallel.edge_parallel import (
    batch_specs,
    make_dp_edge_parallel_train_step,
    make_edge_parallel_eval_step,
    make_edge_parallel_train_step,
    pad_edges_divisible,
    prepare_dense_sharded,
    shard_batch,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

# Graph-sharded TRAINING gradient parity needs the vma-typed shard_map
# transpose (pcast-to-varying inserting the completing psums at the
# right interior points). The parallel/compat.py shim runs these bodies
# on jax 0.4.37's experimental shard_map, but the old transpose leaves
# cross-shard cotangent terms incomplete (~1e-4 relative — measured,
# see compat.pcast), so the exact-parity pins hold only on a jax with
# native jax.shard_map (CI). Forward/eval sharding and in-body-reduced
# DP training (test_parallel.py) are exact everywhere.
needs_vma_transpose = pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="graph-sharded backward is approximate on pre-vma shard_map "
           "(parallel/compat.py); exact parity pinned in CI",
)


def _setup(batch_size=16, n_graphs=16):
    graphs = load_synthetic(
        n_graphs, FeaturizeConfig(radius=5.0, max_num_nbr=8), seed=0
    )
    nc, ec = capacities_for(graphs, batch_size)
    batch = next(batch_iterator(graphs, batch_size, nc, ec))
    targets = np.stack([g.target for g in graphs])
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[100])
    return graphs, batch, targets, tx


def _states(model_ref, model_gp, batch, targets, tx):
    """Two identically-initialized states (no shared buffers — donation on
    CPU aliases device_put, so shared leaves would be deleted)."""
    a = create_train_state(
        model_ref, batch, tx, Normalizer.fit(targets), rng=jax.random.key(0)
    )
    b = create_train_state(
        model_ref, batch, tx, Normalizer.fit(targets), rng=jax.random.key(0)
    ).replace(apply_fn=model_gp.apply)
    return a, b


def test_pad_edges_divisible_preserves_semantics():
    _, batch, _, _ = _setup()
    padded = pad_edges_divisible(batch, 8)
    assert padded.edge_capacity % 8 == 0
    e = batch.edge_capacity
    np.testing.assert_array_equal(padded.edges[:e], batch.edges)
    assert (np.asarray(padded.edge_mask[e:]) == 0).all()
    assert (np.asarray(padded.centers[e:]) == batch.node_capacity - 1).all()
    # sortedness invariant survives
    assert (np.diff(np.asarray(padded.centers)) >= 0).all()


@needs_vma_transpose
def test_edge_parallel_train_step_matches_single_device():
    _, batch, targets, tx = _setup()
    batch = pad_edges_divisible(batch, 8)
    model_ref = CrystalGraphConvNet(atom_fea_len=32, n_conv=2, h_fea_len=32)
    model_gp = CrystalGraphConvNet(
        atom_fea_len=32, n_conv=2, h_fea_len=32, edge_axis_name="graph"
    )
    state_ref, state_gp = _states(model_ref, model_gp, batch, targets, tx)

    s1, m1 = jax.jit(make_train_step())(state_ref, batch)

    mesh = Mesh(np.array(jax.devices()), ("graph",))
    s2, m2 = make_edge_parallel_train_step(mesh)(
        state_gp, shard_batch(batch, mesh)
    )
    assert float(m1["loss_sum"]) == pytest.approx(float(m2["loss_sum"]), abs=1e-4)
    for a, b in zip(
        jtu.tree_leaves(jax.device_get(s1.params)),
        jtu.tree_leaves(jax.device_get(s2.params)),
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)
    for a, b in zip(
        jtu.tree_leaves(jax.device_get(s1.batch_stats)),
        jtu.tree_leaves(jax.device_get(s2.batch_stats)),
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_edge_parallel_eval_matches_single_device():
    _, batch, targets, tx = _setup()
    batch = pad_edges_divisible(batch, 8)
    model_ref = CrystalGraphConvNet(atom_fea_len=32, n_conv=2, h_fea_len=32)
    model_gp = CrystalGraphConvNet(
        atom_fea_len=32, n_conv=2, h_fea_len=32, edge_axis_name="graph"
    )
    state_ref, state_gp = _states(model_ref, model_gp, batch, targets, tx)
    from cgnn_tpu.train.step import make_eval_step

    m1 = jax.jit(make_eval_step())(state_ref, batch)
    mesh = Mesh(np.array(jax.devices()), ("graph",))
    m2 = make_edge_parallel_eval_step(mesh)(state_gp, shard_batch(batch, mesh))
    assert float(m1["mae_sum"]) == pytest.approx(float(m2["mae_sum"]), rel=1e-5)


@needs_vma_transpose
def test_fit_data_parallel_2d_mesh_matches_plain_dp():
    """Full fit loop through a ('data','graph') mesh == plain-DP fit:
    same seed -> same batch order -> identical training trajectory."""
    from cgnn_tpu.parallel.data_parallel import fit_data_parallel
    from cgnn_tpu.parallel.mesh import make_2d_mesh, make_mesh

    graphs = load_synthetic(
        48, FeaturizeConfig(radius=5.0, max_num_nbr=8), seed=0
    )
    train_g, val_g = graphs[:32], graphs[32:]
    targets = np.stack([g.target for g in train_g])
    nc, ec = capacities_for(train_g, 4)
    batch = next(batch_iterator(train_g, 4, nc, ec))
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[100])
    model_ref = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16)
    model_gp = CrystalGraphConvNet(
        atom_fea_len=16, n_conv=2, h_fea_len=16, edge_axis_name="graph"
    )
    state_a, state_b = _states(model_ref, model_gp, batch, targets, tx)

    quiet = lambda *a, **k: None  # noqa: E731
    s1, r1 = fit_data_parallel(
        state_a, train_g, val_g, epochs=2, batch_size=4, node_cap=nc,
        edge_cap=ec, seed=7, mesh=make_mesh(4), log_fn=quiet,
    )
    s2, r2 = fit_data_parallel(
        state_b, train_g, val_g, epochs=2, batch_size=4, node_cap=nc,
        edge_cap=ec, seed=7, mesh=make_2d_mesh(2, data_shards=4),
        log_fn=quiet,
    )
    for e1, e2 in zip(r1["history"], r2["history"]):
        assert e1["train_loss"] == pytest.approx(e2["train_loss"], rel=1e-4)
        assert e1["val"]["mae"] == pytest.approx(e2["val"]["mae"], rel=1e-4)
    for a, b in zip(
        jtu.tree_leaves(jax.device_get(s1.params)),
        jtu.tree_leaves(jax.device_get(s2.params)),
    ):
        np.testing.assert_allclose(a, b, atol=1e-4)


def _dense_setup(n_graphs=16, batch_size=16, n_shards=4):
    """Dense-layout batch with shard-divisible node capacity + two models."""
    graphs = load_synthetic(
        n_graphs, FeaturizeConfig(radius=5.0, max_num_nbr=8), seed=0
    )
    nc, ec = capacities_for(graphs, batch_size, dense_m=8,
                            node_multiple=8 * n_shards)
    batch = next(batch_iterator(graphs, batch_size, nc, ec, dense_m=8))
    targets = np.stack([g.target for g in graphs])
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[100])
    model_ref = CrystalGraphConvNet(
        atom_fea_len=32, n_conv=2, h_fea_len=32, dense_m=8
    )
    model_gp = CrystalGraphConvNet(
        atom_fea_len=32, n_conv=2, h_fea_len=32, dense_m=8,
        edge_axis_name="graph",
    )
    return graphs, batch, targets, tx, model_ref, model_gp


def test_shard_transpose_slots_checks_node_cap_divisibility():
    """The raise fires at the REAL precondition (node_cap % n_shards) with
    a message that matches it — not only when the edge capacity happens to
    be indivisible too (ADVICE r5: node_cap=6, dense_m=8, n_shards=4 has
    e_cap=48 divisible by 4, yet strips would cut mid node-row and die
    later as an opaque shard_map error)."""
    from cgnn_tpu.data.graph import shard_transpose_slots

    node_cap, dense_m, n_shards = 6, 8, 4
    e_cap = node_cap * dense_m
    assert e_cap % n_shards == 0  # the case the old check let through
    neighbors = np.zeros(e_cap, np.int32)
    edge_real = np.zeros(e_cap, bool)
    with pytest.raises(ValueError, match="node_cap 6 not divisible"):
        shard_transpose_slots(neighbors, edge_real, node_cap, dense_m,
                              n_shards, over_cap=8)


def test_shard_transpose_mapping_is_complete():
    """Per-shard mappings pass the same completeness invariant as the flat
    mapping (invariants._check_transpose_mapping understands both), and a
    corrupted shard mapping fails it."""
    from cgnn_tpu.data import invariants

    _, batch, *_ = _dense_setup()
    prepped = prepare_dense_sharded(batch, 4, train=True)
    assert prepped.in_mask.ndim == 3 and prepped.in_mask.shape[0] == 4
    invariants.check_batch(prepped)  # raises on any broken invariant

    import dataclasses

    bad_slots = np.array(prepped.in_slots)
    first = tuple(np.argwhere(np.asarray(prepped.in_mask).reshape(
        4, -1) > 0)[0])
    bad_slots[first[0], first[1]] += 1  # duplicate/missing edge slot
    with pytest.raises(invariants.BatchInvariantError):
        invariants.check_batch(
            dataclasses.replace(prepped, in_slots=bad_slots))


@needs_vma_transpose
def test_dense_sharded_train_step_matches_single_device():
    """The dense fast path composed with graph sharding: one training step
    on a 4-shard mesh == the unsharded dense step (params, stats, loss)."""
    _, batch, targets, tx, model_ref, model_gp = _dense_setup()
    state_ref, state_gp = _states(model_ref, model_gp, batch, targets, tx)

    s1, m1 = jax.jit(make_train_step())(state_ref, batch)

    mesh = Mesh(np.array(jax.devices()[:4]), ("graph",))
    prepped = prepare_dense_sharded(batch, 4, train=True)
    s2, m2 = make_edge_parallel_train_step(mesh, dense=True)(
        state_gp, shard_batch(prepped, mesh)
    )
    assert float(m1["loss_sum"]) == pytest.approx(
        float(m2["loss_sum"]), abs=1e-4)
    for a, b in zip(
        jtu.tree_leaves(jax.device_get(s1.params)),
        jtu.tree_leaves(jax.device_get(s2.params)),
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)
    for a, b in zip(
        jtu.tree_leaves(jax.device_get(s1.batch_stats)),
        jtu.tree_leaves(jax.device_get(s2.batch_stats)),
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)


def test_dense_sharded_eval_matches_single_device():
    from cgnn_tpu.train.step import make_eval_step

    _, batch, targets, tx, model_ref, model_gp = _dense_setup()
    state_ref, state_gp = _states(model_ref, model_gp, batch, targets, tx)
    m1 = jax.jit(make_eval_step())(state_ref, batch)
    mesh = Mesh(np.array(jax.devices()[:4]), ("graph",))
    prepped = prepare_dense_sharded(batch, 4, train=False)
    assert prepped.in_slots is None  # eval batches carry no mapping
    m2 = make_edge_parallel_eval_step(mesh, dense=True)(
        state_gp, shard_batch(prepped, mesh)
    )
    assert float(m1["mae_sum"]) == pytest.approx(float(m2["mae_sum"]),
                                                 rel=1e-5)


@needs_vma_transpose
def test_fit_dense_graph_sharded_matches_plain_dp():
    """Full fit through ('data','graph') with the DENSE layout == plain-DP
    dense fit: same capacities -> same batches -> identical trajectory.
    This is the VERDICT r4 #3 acceptance: the fast path composes with
    graph sharding instead of falling back to COO."""
    from cgnn_tpu.parallel.data_parallel import fit_data_parallel
    from cgnn_tpu.parallel.mesh import make_2d_mesh, make_mesh

    graphs = load_synthetic(
        96, FeaturizeConfig(radius=5.0, max_num_nbr=8), seed=0
    )
    train_g, val_g = graphs[:80], graphs[80:]
    targets = np.stack([g.target for g in train_g])
    tx = make_optimizer(optim="sgd", lr=0.02, lr_milestones=[100])
    nc, ec = capacities_for(train_g, 4, dense_m=8, snug=True,
                            node_multiple=16)
    batch = next(batch_iterator(train_g, 4, nc, ec, dense_m=8, snug=True))
    model_ref = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                    dense_m=8)
    model_gp = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                   dense_m=8, edge_axis_name="graph")
    state_a, state_b = _states(model_ref, model_gp, batch, targets, tx)

    quiet = lambda *a, **k: None  # noqa: E731
    s1, r1 = fit_data_parallel(
        state_a, train_g, val_g, epochs=3, batch_size=4, node_cap=nc,
        edge_cap=ec, seed=5, mesh=make_mesh(4), log_fn=quiet, snug=True,
        dense_m=8,
    )
    s2, r2 = fit_data_parallel(
        state_b, train_g, val_g, epochs=3, batch_size=4, node_cap=nc,
        edge_cap=ec, seed=5, mesh=make_2d_mesh(2, data_shards=4),
        log_fn=quiet, snug=True, dense_m=8,
    )
    for e1, e2 in zip(r1["history"], r2["history"]):
        assert e1["train_loss"] == pytest.approx(e2["train_loss"], rel=1e-4)
        assert e1["val"]["mae"] == pytest.approx(e2["val"]["mae"], rel=1e-4)
    for a, b in zip(
        jtu.tree_leaves(jax.device_get(s1.params)),
        jtu.tree_leaves(jax.device_get(s2.params)),
    ):
        np.testing.assert_allclose(a, b, atol=1e-4)


def test_fit_dense_graph_sharded_buckets_snug_trains():
    """The FULL fast-path composition — dense + snug + 2 size-class buckets
    + DP x graph shards — trains with decreasing loss (capacities differ
    from plain DP by the strip rounding, so the bar is convergence, not
    trajectory identity)."""
    from cgnn_tpu.parallel.data_parallel import fit_data_parallel
    from cgnn_tpu.parallel.mesh import make_2d_mesh

    graphs = load_synthetic(
        96, FeaturizeConfig(radius=5.0, max_num_nbr=8), seed=0
    )
    train_g, val_g = graphs[:80], graphs[80:]
    targets = np.stack([g.target for g in train_g])
    tx = make_optimizer(optim="sgd", lr=0.05, lr_milestones=[100])
    nc, ec = capacities_for(train_g, 4, dense_m=8, snug=True,
                            node_multiple=16)
    batch = next(batch_iterator(train_g, 4, nc, ec, dense_m=8, snug=True))
    model_ref = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                    dense_m=8)
    model_gp = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                   dense_m=8, edge_axis_name="graph")
    state = create_train_state(
        model_ref, batch, tx, Normalizer.fit(targets), rng=jax.random.key(0)
    ).replace(apply_fn=model_gp.apply)

    quiet = lambda *a, **k: None  # noqa: E731
    _, result = fit_data_parallel(
        state, train_g, val_g, epochs=6, batch_size=4, node_cap=0,
        edge_cap=0, seed=5, mesh=make_2d_mesh(2, data_shards=4),
        log_fn=quiet, buckets=2, snug=True, dense_m=8,
    )
    h = result["history"]
    assert np.isfinite(h[-1]["train_loss"])
    assert h[-1]["train_loss"] < h[0]["train_loss"]


def test_fit_dense_graph_sharded_scan_matches_per_step():
    """ScanEpochDriver composes with graph sharding (r5): on the same
    ('data','graph') mesh, the scan path reproduces the per-step
    device-resident path exactly (single shape group, same seed)."""
    from cgnn_tpu.parallel.data_parallel import fit_data_parallel
    from cgnn_tpu.parallel.mesh import make_2d_mesh

    graphs = load_synthetic(
        96, FeaturizeConfig(radius=5.0, max_num_nbr=8), seed=0
    )
    train_g, val_g = graphs[:80], graphs[80:]
    targets = np.stack([g.target for g in train_g])
    tx = make_optimizer(optim="sgd", lr=0.02, lr_milestones=[100])
    nc, ec = capacities_for(train_g, 4, dense_m=8, snug=True,
                            node_multiple=16)
    batch = next(batch_iterator(train_g, 4, nc, ec, dense_m=8, snug=True))
    model_ref = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                    dense_m=8)
    model_gp = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                   dense_m=8, edge_axis_name="graph")

    def fresh():
        return create_train_state(
            model_ref, batch, tx, Normalizer.fit(targets),
            rng=jax.random.key(0),
        ).replace(apply_fn=model_gp.apply)

    quiet = lambda *a, **k: None  # noqa: E731
    mesh = make_2d_mesh(2, data_shards=4)
    _, r1 = fit_data_parallel(
        fresh(), train_g, val_g, epochs=2, batch_size=4, node_cap=nc,
        edge_cap=ec, seed=5, mesh=mesh, log_fn=quiet, snug=True,
        dense_m=8, device_resident=True,
    )
    _, r2 = fit_data_parallel(
        fresh(), train_g, val_g, epochs=2, batch_size=4, node_cap=nc,
        edge_cap=ec, seed=5, mesh=mesh, log_fn=quiet, snug=True,
        dense_m=8, scan_epochs=True,
    )
    for e1, e2 in zip(r1["history"], r2["history"]):
        assert e1["train_loss"] == pytest.approx(e2["train_loss"], rel=1e-5)
        assert e1["val"]["mae"] == pytest.approx(e2["val"]["mae"], rel=1e-5)


def test_fit_coo_graph_sharded_scan_matches_per_step():
    """The COO layout's graph-sharded runs also take the scan path now
    (train.py's device-resident scan default applies to --layout coo
    too): scan == per-step on the same 2-D mesh."""
    from cgnn_tpu.parallel.data_parallel import fit_data_parallel
    from cgnn_tpu.parallel.mesh import make_2d_mesh

    graphs = load_synthetic(
        64, FeaturizeConfig(radius=5.0, max_num_nbr=8), seed=0
    )
    train_g, val_g = graphs[:48], graphs[48:]
    targets = np.stack([g.target for g in train_g])
    tx = make_optimizer(optim="sgd", lr=0.02, lr_milestones=[100])
    nc, ec = capacities_for(train_g, 4)
    batch = next(batch_iterator(train_g, 4, nc, ec))
    model_ref = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16)
    model_gp = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                   edge_axis_name="graph")

    def fresh():
        return create_train_state(
            model_ref, batch, tx, Normalizer.fit(targets),
            rng=jax.random.key(0),
        ).replace(apply_fn=model_gp.apply)

    quiet = lambda *a, **k: None  # noqa: E731
    mesh = make_2d_mesh(2, data_shards=4)
    _, r1 = fit_data_parallel(
        fresh(), train_g, val_g, epochs=2, batch_size=4, node_cap=nc,
        edge_cap=ec, seed=5, mesh=mesh, log_fn=quiet,
        device_resident=True,
    )
    _, r2 = fit_data_parallel(
        fresh(), train_g, val_g, epochs=2, batch_size=4, node_cap=nc,
        edge_cap=ec, seed=5, mesh=mesh, log_fn=quiet, scan_epochs=True,
    )
    for e1, e2 in zip(r1["history"], r2["history"]):
        assert e1["train_loss"] == pytest.approx(e2["train_loss"], rel=1e-5)
        assert e1["val"]["mae"] == pytest.approx(e2["val"]["mae"], rel=1e-5)


def test_fit_dense_graph_sharded_scan_buckets_trains():
    """The full flagship composition on a sharded mesh: scan driver + 2
    size-class buckets + snug dense node-strip sharding trains with
    decreasing loss across epoch boundaries."""
    from cgnn_tpu.parallel.data_parallel import fit_data_parallel
    from cgnn_tpu.parallel.mesh import make_2d_mesh

    graphs = load_synthetic(
        96, FeaturizeConfig(radius=5.0, max_num_nbr=8), seed=0
    )
    train_g, val_g = graphs[:80], graphs[80:]
    targets = np.stack([g.target for g in train_g])
    tx = make_optimizer(optim="sgd", lr=0.05, lr_milestones=[100])
    nc, ec = capacities_for(train_g, 4, dense_m=8, snug=True,
                            node_multiple=16)
    batch = next(batch_iterator(train_g, 4, nc, ec, dense_m=8, snug=True))
    model_ref = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                    dense_m=8)
    model_gp = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=16,
                                   dense_m=8, edge_axis_name="graph")
    state = create_train_state(
        model_ref, batch, tx, Normalizer.fit(targets), rng=jax.random.key(0)
    ).replace(apply_fn=model_gp.apply)

    quiet = lambda *a, **k: None  # noqa: E731
    _, result = fit_data_parallel(
        state, train_g, val_g, epochs=6, batch_size=4, node_cap=0,
        edge_cap=0, seed=5, mesh=make_2d_mesh(2, data_shards=4),
        log_fn=quiet, buckets=2, snug=True, dense_m=8, scan_epochs=True,
    )
    h = result["history"]
    assert np.isfinite(h[-1]["train_loss"])
    assert h[-1]["train_loss"] < h[0]["train_loss"]


@needs_vma_transpose
def test_2d_data_x_graph_mesh_matches_plain_dp():
    graphs, _, targets, tx = _setup(batch_size=8, n_graphs=32)
    nc, ec = capacities_for(graphs, 8)
    batches = [
        pad_edges_divisible(b, 2)
        for b in list(batch_iterator(graphs, 8, nc, ec))[:4]
    ]
    stacked = stack_batches(batches)
    model_ref = CrystalGraphConvNet(atom_fea_len=32, n_conv=2, h_fea_len=32)
    model_gp = CrystalGraphConvNet(
        atom_fea_len=32, n_conv=2, h_fea_len=32, edge_axis_name="graph"
    )
    state_a, state_b = _states(model_ref, model_gp, batches[0], targets, tx)

    mesh_dp = Mesh(np.array(jax.devices()[:4]), ("data",))
    mesh2d = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "graph"))
    state_a = jtu.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh_dp, P())), state_a
    )
    state_b = jtu.tree_map(
        lambda x: jax.device_put(x, NamedSharding(mesh2d, P())), state_b
    )

    s1, m1 = make_parallel_train_step(mesh_dp)(
        state_a, shard_leading_axis(stacked, mesh_dp)
    )
    specs = batch_specs(graph_axis="graph", data_axis="data")
    sb = jtu.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh2d, s)),
        stacked, specs, is_leaf=lambda x: isinstance(x, P),
    )
    s2, m2 = make_dp_edge_parallel_train_step(mesh2d)(state_b, sb)
    assert float(m1["loss_sum"]) == pytest.approx(float(m2["loss_sum"]), abs=1e-3)
    for a, b in zip(
        jtu.tree_leaves(jax.device_get(s1.params)),
        jtu.tree_leaves(jax.device_get(s2.params)),
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)
