"""Force-path correctness: LJ ground truth + stored-geometry consistency.

Covers the two silent-corruption bugs ADVICE.md (round 1) identified:
sign-flipped LJ forces and unwrapped stored geometry.
"""

import numpy as np
import pytest

from cgnn_tpu.data.dataset import FeaturizeConfig, featurize_structure
from cgnn_tpu.data.structure import Structure, lattice_from_parameters
from cgnn_tpu.data.synthetic import (
    lj_energy_forces,
    random_structure,
    synthetic_trajectory,
)


def test_lj_forces_match_finite_differences():
    """F must equal -dE/dx of the same energy function (central diff)."""
    rng = np.random.default_rng(7)
    s = random_structure(rng, 6, 6, a_range=(5.5, 7.0))
    energy, forces = lj_energy_forces(s)
    assert np.isfinite(energy)
    inv_lat = np.linalg.inv(s.lattice)
    h = 1e-5
    cart = s.cart_coords
    for atom in range(s.num_atoms):
        for axis in range(3):
            for sign, store in ((+1, "p"), (-1, "m")):
                c = cart.copy()
                c[atom, axis] += sign * h
                e = lj_energy_forces(Structure(s.lattice, c @ inv_lat, s.numbers))[0]
                if store == "p":
                    ep = e
                else:
                    em = e
            fd_force = -(ep - em) / (2 * h)
            assert forces[atom, axis] == pytest.approx(fd_force, rel=1e-3, abs=1e-5)


def test_lj_forces_sum_to_zero():
    """Newton's third law: net force on a periodic cell is zero."""
    rng = np.random.default_rng(3)
    s = random_structure(rng, 8, 8, a_range=(5.5, 7.0))
    _, forces = lj_energy_forces(s)
    np.testing.assert_allclose(forces.sum(axis=0), 0.0, atol=1e-4)


def test_trajectory_labels_are_consistent():
    frames = synthetic_trajectory(3, seed=1, num_atoms=6)
    for _, s, e, f in frames:
        e2, f2 = lj_energy_forces(s)
        assert e == pytest.approx(e2)
        np.testing.assert_allclose(f, f2, atol=1e-6)


def test_keep_geometry_stores_wrapped_positions():
    """Stored positions + offsets must reproduce the neighbor-list distances
    even when input fractional coordinates fall outside [0, 1)."""
    lattice = lattice_from_parameters(5.5, 6.0, 6.5, 88.0, 92.0, 95.0)
    # deliberately out-of-cell fracs (synthetic_trajectory jitter regime)
    fracs = np.array(
        [
            [0.1, 0.2, 0.3],
            [-0.35, 0.6, 1.42],
            [0.7, 1.15, -0.2],
            [2.3, 0.4, 0.55],
        ]
    )
    s = Structure(lattice, fracs, np.array([8, 14, 26, 29], np.int32))
    g = featurize_structure(
        s, 0.0, FeaturizeConfig(radius=6.0, max_num_nbr=12), keep_geometry=True
    )
    shift = g.offsets.astype(np.float64) @ g.lattice.astype(np.float64)
    rel = g.positions[g.neighbors].astype(np.float64) + shift - g.positions[g.centers].astype(np.float64)
    recomputed = np.linalg.norm(rel, axis=1)
    np.testing.assert_allclose(recomputed, g.distances, rtol=1e-5, atol=1e-5)
