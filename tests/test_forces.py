"""Force-path correctness: LJ ground truth + stored-geometry consistency.

Covers the two silent-corruption bugs ADVICE.md (round 1) identified:
sign-flipped LJ forces and unwrapped stored geometry.
"""

import numpy as np
import pytest

from cgnn_tpu.data.dataset import FeaturizeConfig, featurize_structure
from cgnn_tpu.data.structure import Structure, lattice_from_parameters
from cgnn_tpu.data.synthetic import (
    lj_energy_forces,
    random_structure,
    synthetic_trajectory,
)


def test_lj_forces_match_finite_differences():
    """F must equal -dE/dx of the same energy function (central diff)."""
    rng = np.random.default_rng(7)
    s = random_structure(rng, 6, 6, a_range=(5.5, 7.0))
    energy, forces = lj_energy_forces(s)
    assert np.isfinite(energy)
    inv_lat = np.linalg.inv(s.lattice)
    h = 1e-5
    cart = s.cart_coords
    for atom in range(s.num_atoms):
        for axis in range(3):
            for sign, store in ((+1, "p"), (-1, "m")):
                c = cart.copy()
                c[atom, axis] += sign * h
                e = lj_energy_forces(Structure(s.lattice, c @ inv_lat, s.numbers))[0]
                if store == "p":
                    ep = e
                else:
                    em = e
            fd_force = -(ep - em) / (2 * h)
            assert forces[atom, axis] == pytest.approx(fd_force, rel=1e-3, abs=1e-5)


def test_lj_forces_sum_to_zero():
    """Newton's third law: net force on a periodic cell is zero."""
    rng = np.random.default_rng(3)
    s = random_structure(rng, 8, 8, a_range=(5.5, 7.0))
    _, forces = lj_energy_forces(s)
    np.testing.assert_allclose(forces.sum(axis=0), 0.0, atol=1e-4)


def test_trajectory_labels_are_consistent():
    frames = synthetic_trajectory(3, seed=1, num_atoms=6)
    for _, s, e, f in frames:
        e2, f2 = lj_energy_forces(s)
        assert e == pytest.approx(e2)
        np.testing.assert_allclose(f, f2, atol=1e-6)


def test_force_training_fits_lj_ground_truth():
    """End-to-end config #5: composite energy+force loss on LJ trajectory
    frames; force MAE vs the analytic forces must drop far below the
    untrained model and below an absolute bound (measured ~0.15 at 60
    epochs; bound leaves 2x margin). BASELINE config #5, SURVEY.md §7 ph. 7."""
    import jax

    from cgnn_tpu.data.dataset import load_trajectory
    from cgnn_tpu.data.graph import pack_graphs
    from cgnn_tpu.models.forcefield import ForceFieldCGCNN
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.force_step import (
        make_force_eval_step,
        make_force_train_step,
    )
    from cgnn_tpu.train.loop import capacities_for, evaluate, fit

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_trajectory(320, cfg, seed=0, num_atoms=6)
    train_g, val_g = graphs[:280], graphs[280:]
    norm = Normalizer.fit(np.stack([g.target for g in train_g]))
    model = ForceFieldCGCNN(atom_fea_len=64, n_conv=3, h_fea_len=64, dmax=6.0)
    node_cap, edge_cap = capacities_for(graphs, 32)
    example = pack_graphs(train_g[:32], node_cap, edge_cap, 32)
    state = create_train_state(
        model, example, make_optimizer(optim="adam", lr=2e-3), norm,
        rng=jax.random.key(0),
    )
    ev = make_force_eval_step()
    m0 = evaluate(state, val_g, 32, node_cap, edge_cap, eval_step_fn=ev)
    state, _ = fit(
        state, train_g, val_g, epochs=60, batch_size=32,
        node_cap=node_cap, edge_cap=edge_cap, print_freq=0,
        train_step_fn=make_force_train_step(),
        eval_step_fn=ev, best_metric="force_mae", log_fn=lambda *_: None,
    )
    m1 = evaluate(state, val_g, 32, node_cap, edge_cap, eval_step_fn=ev)
    assert float(m1["force_mae"]) < 0.25 * float(m0["force_mae"])
    assert float(m1["force_mae"]) < 0.30
    assert float(m1["mae"]) < float(m0["mae"])  # energy improves too


def test_dense_force_layout_matches_coo():
    """--task force --layout dense (VERDICT r3 next-step #4): the dense
    edge-slot layout must reproduce the flat-COO force model exactly —
    energies, forces, AND one composite-loss training step's gradients
    (the second-order path through linear_call's gather transpose)."""
    import jax
    import jax.numpy as jnp

    from cgnn_tpu.data.dataset import load_trajectory
    from cgnn_tpu.data.graph import batch_iterator
    from cgnn_tpu.models.forcefield import ForceFieldCGCNN, energy_and_forces
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.force_step import make_force_train_step
    from cgnn_tpu.train.loop import capacities_for

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_trajectory(24, cfg, seed=5, num_atoms=6)
    norm = Normalizer.fit(np.stack([g.target for g in graphs]))

    nc_c, ec_c = capacities_for(graphs, 8)
    coo = next(batch_iterator(graphs, 8, nc_c, ec_c))
    nc_d, ec_d = capacities_for(graphs, 8, dense_m=12)
    dense = next(batch_iterator(graphs, 8, nc_d, ec_d, dense_m=12))
    assert dense.in_slots is not None  # two-tier transpose is packed

    m_coo = ForceFieldCGCNN(atom_fea_len=32, n_conv=2, h_fea_len=32, dmax=6.0)
    m_dense = ForceFieldCGCNN(
        atom_fea_len=32, n_conv=2, h_fea_len=32, dmax=6.0, dense_m=12
    )
    variables = m_coo.init(jax.random.key(0), coo)
    # same params apply to both layouts (layout is batching, not identity)
    e_c, f_c, _ = energy_and_forces(m_coo, variables, coo)
    e_d, f_d, _ = energy_and_forces(m_dense, variables, dense)

    gm_c, gm_d = np.asarray(coo.graph_mask) > 0, np.asarray(dense.graph_mask) > 0
    np.testing.assert_allclose(
        np.asarray(e_c)[gm_c], np.asarray(e_d)[gm_d], rtol=1e-5, atol=1e-5
    )
    nm_c, nm_d = np.asarray(coo.node_mask) > 0, np.asarray(dense.node_mask) > 0
    np.testing.assert_allclose(
        np.asarray(f_c)[nm_c], np.asarray(f_d)[nm_d], rtol=1e-4, atol=1e-5
    )

    # one training step: params gradients must agree through the nested
    # (positions-then-params) differentiation on both layouts
    step = make_force_train_step()
    tx = make_optimizer(optim="adam", lr=1e-3)
    s_c = create_train_state(m_coo, coo, tx, norm, rng=jax.random.key(1))
    s_d = create_train_state(m_dense, dense, tx, norm, rng=jax.random.key(1))
    s_c2, met_c = step(s_c, coo)
    s_d2, met_d = step(s_d, dense)
    assert float(met_c["loss_sum"]) == pytest.approx(
        float(met_d["loss_sum"]), rel=1e-4
    )
    flat_c = jax.tree_util.tree_leaves(s_c2.params)
    flat_d = jax.tree_util.tree_leaves(s_d2.params)
    for a, b in zip(flat_c, flat_d):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )


def test_keep_geometry_stores_wrapped_positions():
    """Stored positions + offsets must reproduce the neighbor-list distances
    even when input fractional coordinates fall outside [0, 1)."""
    lattice = lattice_from_parameters(5.5, 6.0, 6.5, 88.0, 92.0, 95.0)
    # deliberately out-of-cell fracs (synthetic_trajectory jitter regime)
    fracs = np.array(
        [
            [0.1, 0.2, 0.3],
            [-0.35, 0.6, 1.42],
            [0.7, 1.15, -0.2],
            [2.3, 0.4, 0.55],
        ]
    )
    s = Structure(lattice, fracs, np.array([8, 14, 26, 29], np.int32))
    g = featurize_structure(
        s, 0.0, FeaturizeConfig(radius=6.0, max_num_nbr=12), keep_geometry=True
    )
    shift = g.offsets.astype(np.float64) @ g.lattice.astype(np.float64)
    rel = g.positions[g.neighbors].astype(np.float64) + shift - g.positions[g.centers].astype(np.float64)
    recomputed = np.linalg.norm(rel, axis=1)
    np.testing.assert_allclose(recomputed, g.distances, rtol=1e-5, atol=1e-5)
