"""Raw wire format + in-program neighbor search (ISSUE 11).

The acceptance pins:

- in-program graph CONSTRUCTION is bit-exact vs the host featurizer
  over identical structures: identical edge sets, neighbor indices,
  canonical edge order (center, distance, source atom, lexicographic
  image), masks, and atom feature rows — with distances/features at f32
  roundoff (the host search runs f64; XLA contracts FMAs);
- the Pallas variant is bit-exact vs the XLA variant (selection keys
  are distinct (d, c) pairs, so sort-based and argmin-round selection
  must agree EXACTLY);
- cap overflow never silently truncates: the in-program flag fires for
  a lattice needing more periodic images than the rung provides, and
  serving routes the flagged request to the host-featurized fallback;
- zero post-warmup recompiles under mixed raw/featurized (+ mixed
  tier) load — the form boundary is a batch cut, not a retrace;
- wire-form structures that cannot stage raw are featurized on the
  PACK POOL, never on the admission thread (the ISSUE-11 bugfix).
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from cgnn_tpu.data.dataset import FeaturizeConfig, featurize_structure
from cgnn_tpu.data.elements import atom_features
from cgnn_tpu.data.featurize import gaussian_expand
from cgnn_tpu.data.neighbors import knn_neighbor_list
from cgnn_tpu.data.rawbatch import (
    RawSpec,
    RawStructure,
    pack_raw,
    plan_raw_spec,
    raw_fingerprint,
    raw_from_graph,
    raw_neighbor_graph_host,
)
from cgnn_tpu.data.structure import Structure
from cgnn_tpu.data.synthetic import synthetic_dataset
from cgnn_tpu.ops.neighbor_search import make_raw_expander, neighbor_search
from cgnn_tpu.serve.shapes import plan_shape_set

CFG = FeaturizeConfig(radius=6.0, max_num_nbr=12)


def _spec(items, m=12, coverage=1.0):
    graphs = [featurize_structure(s, t, CFG, sid, keep_geometry=True)
              for sid, s, t in items]
    return graphs, plan_raw_spec(graphs, CFG.gdf(), CFG.radius, m,
                                 coverage=coverage)


def _search(rb, spec, impl="xla"):
    out = jax.jit(
        lambda rb: neighbor_search(rb.frac, rb.lattices, rb.atom_mask,
                                   spec, impl=impl)
    )(rb)
    return tuple(np.asarray(x) for x in out)


class TestInProgramSearch:
    def test_bitexact_graph_construction_vs_host_featurizer(self):
        """THE parity pin: per structure, the device search selects the
        SAME edges in the SAME canonical order as knn_neighbor_list —
        neighbor indices and masks integer-exact, distances at f32
        roundoff."""
        items = synthetic_dataset(16, seed=3)
        graphs, spec = _spec(items)
        raws = [RawStructure.from_structure(s, t, sid)
                for sid, s, t in items]
        rb = pack_raw(raws, len(raws), spec)
        nbr, dist, em, ne, ovf = _search(rb, spec)
        assert not ovf.any()
        for gi, (sid, s, _t) in enumerate(items):
            nl = knn_neighbor_list(s, CFG.radius, spec.dense_m,
                                   warn_under_coordinated=False)
            n = s.num_atoms
            counts = np.bincount(nl.centers, minlength=n)
            assert int(ne[gi]) == int(np.minimum(counts,
                                                 spec.dense_m).sum())
            for i in range(n):
                sel = nl.centers == i  # knn output is center-sorted,
                #                        distance-ordered within center
                want_nbr = nl.neighbors[sel]
                cnt = len(want_nbr)
                np.testing.assert_array_equal(nbr[gi, i, :cnt], want_nbr)
                np.testing.assert_allclose(dist[gi, i, :cnt],
                                           nl.distances[sel], atol=2e-5)
                assert em[gi, i, :cnt].min() == 1
                assert cnt == spec.dense_m or em[gi, i, cnt:].max() == 0

    def test_exact_tie_canonical_order(self):
        """Simple cubic: all 6 first neighbors at EXACTLY equal
        distance — ties must order by (source atom, lexicographic
        image), the host featurizer's stable-sort order."""
        s = Structure(np.eye(3) * 3.0, [[0, 0, 0]], [29])
        spec = RawSpec(snode_cap=8, images=(2, 2, 2), radius=6.0,
                       dense_m=12,
                       gauss_filter=CFG.gdf().filter,
                       gauss_var=CFG.gdf().var)
        rb = pack_raw([RawStructure.from_structure(s)], 1, spec)
        nbr, dist, em, ne, ovf = _search(rb, spec)
        nl = knn_neighbor_list(s, 6.0, 12, warn_under_coordinated=False)
        cnt = len(nl.centers)
        np.testing.assert_array_equal(nbr[0, 0, :cnt], nl.neighbors)
        np.testing.assert_allclose(dist[0, 0, :cnt], nl.distances,
                                   atol=2e-5)
        # the tie-broken order itself: image offsets sort
        # lexicographically within each distance shell on the host; the
        # device tie-break (candidate index = atom-major, image-minor)
        # must reproduce it exactly
        host_d = np.round(nl.distances, 5)
        assert (np.diff(host_d) >= 0).all()

    def test_numpy_twin_structural_parity(self):
        items = synthetic_dataset(8, seed=11)
        _graphs, spec = _spec(items)
        raws = [RawStructure.from_structure(s, t, sid)
                for sid, s, t in items]
        rb = pack_raw(raws, 12, spec)
        nbr, dist, em, ne, ovf = _search(rb, spec)
        for gi in range(12):
            hn, hd, hm, hne, hovf = raw_neighbor_graph_host(
                rb.frac[gi], rb.lattices[gi], rb.atom_mask[gi], spec)
            np.testing.assert_array_equal(hn, nbr[gi])
            np.testing.assert_array_equal(hm, em[gi].astype(np.uint8))
            np.testing.assert_allclose(hd, dist[gi], atol=2e-5)
            assert hne == int(ne[gi])
            assert (gi < len(raws)) == bool(rb.graph_mask[gi])

    def test_pallas_variant_bitexact_vs_xla(self):
        """Selection keys are distinct (d, c) pairs, so the Pallas
        argmin rounds and the XLA sort must agree BITWISE — including
        distances (both variants share the candidate arithmetic)."""
        items = synthetic_dataset(10, seed=7)
        _graphs, spec = _spec(items)
        raws = [RawStructure.from_structure(s, t, sid)
                for sid, s, t in items]
        rb = pack_raw(raws, 12, spec)
        x = _search(rb, spec, impl="xla")
        p = _search(rb, spec, impl="pallas")
        for a, b in zip(x, p):
            np.testing.assert_array_equal(a, b)

    def test_overflow_flag_fires_in_program(self):
        """A tiny cell needing more images than the caps MUST flag —
        and a comfortably-fitting one must not (the flag is per
        structure, computed from the STAGED lattice)."""
        spec = RawSpec(snode_cap=8, images=(1, 1, 1), radius=6.0,
                       dense_m=12, gauss_filter=CFG.gdf().filter,
                       gauss_var=CFG.gdf().var)
        ok = RawStructure(np.zeros((1, 3)), np.eye(3) * 7.0,
                          np.array([6], np.int32))
        tiny = RawStructure(np.zeros((1, 3)), np.eye(3) * 2.0,
                            np.array([6], np.int32))
        rb = pack_raw([ok, tiny], 4, spec)
        _nbr, _d, _em, _ne, ovf = _search(rb, spec)
        assert not ovf[0]
        assert ovf[1]
        assert not ovf[2:].any()  # padding slots never flag

    def test_skewed_lattice_overflow_axis(self):
        """High-aspect skew: one SHORT axis needs many images while the
        others need one — the per-axis caps must catch exactly that."""
        lat = np.diag([20.0, 20.0, 2.2])
        spec = RawSpec(snode_cap=8, images=(1, 1, 1), radius=6.0,
                       dense_m=12, gauss_filter=CFG.gdf().filter,
                       gauss_var=CFG.gdf().var)
        rs = RawStructure(np.array([[0.5, 0.5, 0.5]]), lat,
                          np.array([14], np.int32))
        assert not spec.admits(rs)
        spec_ok = RawSpec(snode_cap=8, images=(1, 1, 3), radius=6.0,
                          dense_m=12, gauss_filter=CFG.gdf().filter,
                          gauss_var=CFG.gdf().var)
        assert spec_ok.admits(rs)
        rb = pack_raw([rs], 1, spec_ok)
        nbr, dist, em, ne, ovf = _search(rb, spec_ok)
        assert not ovf[0]
        # parity on the self-image neighbors along the short axis
        s = Structure(lat, rs.frac_coords, rs.numbers)
        nl = knn_neighbor_list(s, 6.0, 12, warn_under_coordinated=False)
        np.testing.assert_array_equal(nbr[0, 0, : len(nl.centers)],
                                      nl.neighbors)


class TestRawExpander:
    def test_graphbatch_contract_and_feature_parity(self):
        items = synthetic_dataset(6, seed=5)
        _graphs, spec = _spec(items)
        raws = [RawStructure.from_structure(s, t, sid)
                for sid, s, t in items]
        rb = pack_raw(raws, 8, spec)
        gb, ovf, ne = jax.jit(make_raw_expander(spec))(rb)
        s_cap, m = spec.snode_cap, spec.dense_m
        g_cap = 8
        nodes = np.asarray(gb.nodes)
        centers = np.asarray(gb.centers)
        # dense-layout invariants: centers = slot // M (non-decreasing),
        # padding edge slots self-loop, masks zero on padding
        np.testing.assert_array_equal(
            centers, np.arange(g_cap * s_cap * m) // m)
        emask = np.asarray(gb.edge_mask)
        nbr = np.asarray(gb.neighbors)
        own = np.arange(g_cap * s_cap * m) // m
        assert (nbr[emask == 0] == own[emask == 0]).all()
        for gi, (sid, s, _t) in enumerate(items):
            n = s.num_atoms
            # atom rows: BIT-exact vs the host featurizer's table
            np.testing.assert_array_equal(
                nodes[gi * s_cap: gi * s_cap + n],
                atom_features(s.numbers))
            # neighbors point inside the owning structure's block
            blk = nbr[gi * s_cap * m: (gi + 1) * s_cap * m]
            assert blk.min() >= gi * s_cap
            assert blk.max() < (gi + 1) * s_cap
        # padding structures: all masks zero
        assert np.asarray(gb.node_mask)[len(items) * s_cap:].max() == 0
        assert np.asarray(gb.graph_mask)[len(items):].max() == 0
        # edge features = gaussian_expand of the search distances
        # (<= 1-ulp jnp.exp contract, like the compact expander)
        _nbr2, dist, em, _ne2, _ovf2 = _search(rb, spec)
        want = gaussian_expand(dist, CFG.gdf().filter, CFG.gdf().var)
        want = want * em[..., None]
        got = np.asarray(gb.edges).reshape(g_cap, s_cap, m, -1)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_end_to_end_prediction_parity(self):
        from cgnn_tpu.models import CrystalGraphConvNet
        from cgnn_tpu.train import (
            Normalizer,
            create_train_state,
            make_optimizer,
        )
        from cgnn_tpu.train.infer import run_fast_inference, \
            run_raw_inference
        from cgnn_tpu.train.step import make_predict_step

        items = synthetic_dataset(24, seed=2)
        graphs, spec = _spec(items)
        ladder = plan_shape_set(graphs, 8, rungs=2, dense_m=12, raw=spec)
        model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2,
                                    h_fea_len=32, dense_m=12)
        state = create_train_state(
            model, ladder.pack_full([graphs[0]]), make_optimizer(),
            Normalizer.fit(np.stack([g.target for g in graphs])),
            rng=jax.random.key(0),
        )
        pstep = jax.jit(make_predict_step(
            raw_expander=ladder.raw_expander()))
        raws = [raw_from_graph(g) for g in graphs]
        assert all(r is not None and ladder.admits_raw(r) for r in raws)
        fp, _ = run_fast_inference(state, graphs, 8, shape_set=ladder,
                                   predict_step=pstep)
        rp, _ = run_raw_inference(state, raws, ladder,
                                  predict_step=pstep)
        np.testing.assert_allclose(rp, fp, atol=1e-4, rtol=1e-4)


class TestRawSpecPlanning:
    def test_coverage_quantile_caps(self):
        items = synthetic_dataset(40, seed=13)
        graphs, spec_full = _spec(items, coverage=1.0)
        _g2, spec_95 = _spec(items, coverage=0.9)
        assert spec_95.snode_cap <= spec_full.snode_cap
        assert all(a <= b for a, b in zip(spec_95.images,
                                          spec_full.images))
        raws = [raw_from_graph(g) for g in graphs]
        # full coverage admits everything; quantile coverage admits at
        # least its quantile share
        assert all(spec_full.admits(r) for r in raws)
        share = sum(spec_95.admits(r) for r in raws) / len(raws)
        assert share >= 0.85

    def test_plan_refuses_without_lattices(self):
        from cgnn_tpu.data.rawbatch import RawUnsupported

        items = synthetic_dataset(4, seed=0)
        graphs = [featurize_structure(s, t, CFG, sid)
                  for sid, s, t in items]  # no keep_geometry
        with pytest.raises(RawUnsupported):
            plan_raw_spec(graphs, CFG.gdf(), CFG.radius, 12)

    def test_fingerprint_form_isolated(self):
        items = synthetic_dataset(2, seed=1)
        r0 = RawStructure.from_structure(items[0][1])
        r1 = RawStructure.from_structure(items[1][1])
        assert raw_fingerprint(r0).startswith("raw:")
        assert raw_fingerprint(r0) != raw_fingerprint(r1)
        assert raw_fingerprint(r0) == raw_fingerprint(
            RawStructure.from_structure(items[0][1]))


def _tiny_server(tmp_path, **kw):
    from scripts.serve_loadgen import make_synth_ckpt

    from cgnn_tpu.serve.server import load_server

    ckpt = str(tmp_path / "ckpt")
    make_synth_ckpt(ckpt)
    server, parts = load_server(
        ckpt, batch_size=8, rungs=2, wire="raw", watch=False,
        cache_size=kw.pop("cache_size", 0), max_wait_ms=2.0, **kw,
    )
    server.start()
    return server, parts


class TestRawServing:
    def test_mixed_wire_zero_recompiles(self, tmp_path):
        """Raw + featurized + deferred requests interleaved: every
        answer lands, forms cut flush boundaries, and the compile count
        is PINNED at warmup."""
        server, parts = _tiny_server(tmp_path)
        try:
            assert server.shape_set.raw is not None
            cfg = parts["data_cfg"].featurize_config()
            items = synthetic_dataset(16, seed=21)
            futs = []
            for i, (sid, s, t) in enumerate(items):
                if i % 2 == 0:
                    futs.append(("raw", server.submit(
                        RawStructure.from_structure(s, cif_id=sid),
                        timeout_ms=30000)))
                else:
                    g = featurize_structure(s, t, cfg, sid)
                    futs.append(("featurized", server.submit(
                        g, timeout_ms=30000)))
            wires = {}
            for want, f in futs:
                res = f.result(60)
                assert res.wire == want
                wires[res.wire] = wires.get(res.wire, 0) + 1
            assert wires["raw"] == 8 and wires["featurized"] == 8
            assert server.stats()["recompiles_after_warm"] == 0
            occ = server.stats()["ingest"]["rung_edge_occupancy"]
            assert occ and all(0 < v <= 1 for v in occ.values())
        finally:
            server.drain()

    def test_overflow_flag_routes_to_fallback(self, tmp_path):
        """Pre-check disabled: the tiny cell reaches the device, the
        IN-PROGRAM flag fires, the featurized fallback answers — never
        the truncated graph (prediction equals the precheck-on path's
        bit for bit: same fallback featurizer, same program)."""
        server, _ = _tiny_server(tmp_path, raw_precheck=False)
        try:
            tiny = RawStructure(
                np.array([[0.2, 0.2, 0.2], [0.7, 0.6, 0.5]]),
                np.eye(3) * 1.8, np.array([6, 8], np.int32))
            res = server.predict(tiny, timeout_ms=30000)
            assert res.wire == "featurized"
            st = server.stats()["ingest"]
            assert st["cap_overflows"] == 1
            assert server.stats()["recompiles_after_warm"] == 0
        finally:
            server.drain()
        server2, _ = _tiny_server(tmp_path)
        try:
            res2 = server2.predict(tiny, timeout_ms=30000)
            assert res2.wire == "featurized"
            assert server2.stats()["ingest"]["cap_overflows"] == 0
            np.testing.assert_array_equal(res.prediction,
                                          res2.prediction)
        finally:
            server2.drain()

    def test_deferred_featurize_on_pack_pool(self, tmp_path):
        """A structure too big for the raw caps is admitted instantly
        and featurized at pack time (the ISSUE-11 bugfix: admission
        never featurizes); a malformed one fails ALONE at admission."""
        server, _ = _tiny_server(tmp_path, pack_workers=1)
        try:
            big_n = server.shape_set.raw.snode_cap + 4
            rng = np.random.default_rng(0)
            big = RawStructure(rng.random((big_n, 3)), np.eye(3) * 14.0,
                               np.full(big_n, 14, np.int32))
            res = server.predict(big, timeout_ms=30000)
            assert res.wire == "featurized"
            from cgnn_tpu.serve.batcher import ServeRejection

            with pytest.raises(ServeRejection):
                server.predict(RawStructure(
                    np.zeros((1, 3)), np.eye(3) * 4.0,
                    np.array([150], np.int32)), timeout_ms=3000)
            assert server.stats()["recompiles_after_warm"] == 0
        finally:
            server.drain()

    def test_raw_cache_isolated_from_featurized(self, tmp_path):
        """A row cached by the raw program must never answer the same
        structure's featurized-fallback request (form-qualified keys:
        the two programs agree only to f32 roundoff)."""
        server, parts = _tiny_server(tmp_path, cache_size=64)
        try:
            sid, s, t = synthetic_dataset(1, seed=33)[0]
            rs = RawStructure.from_structure(s, cif_id=sid)
            r1 = server.predict(rs, timeout_ms=30000)
            r2 = server.predict(rs, timeout_ms=30000)
            assert r1.wire == "raw" and r2.cached and r2.wire == "raw"
            cfg = parts["data_cfg"].featurize_config()
            g = featurize_structure(s, t, cfg, sid)
            r3 = server.predict(g, timeout_ms=30000)
            assert not r3.cached  # different wire, different key
        finally:
            server.drain()
