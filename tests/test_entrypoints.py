"""Entrypoint regression tests (SURVEY.md §4.4, VERDICT.md next-step #9).

These run the driver-facing and user-facing entrypoints the way their real
callers do — in subprocesses with realistic (sometimes hostile) environments
— to catch the platform/env bug class that unit tests cannot see.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, env_overrides=None, timeout=600):
    env = dict(os.environ)
    # simulate the driver env: no pytest-conftest CPU pinning
    env.pop("_CGNN_DRYRUN_CHILD", None)
    if env_overrides:
        env.update(env_overrides)
    return subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout,
    )


def test_dryrun_multichip_survives_pinned_axon_platform():
    """The driver pins JAX_PLATFORMS to the real-TPU tunnel; the dry run
    must self-provision a virtual CPU mesh anyway (round-1 red check)."""
    code = "import __graft_entry__ as g; g.dryrun_multichip(2)"
    proc = _run(
        [sys.executable, "-c", code],
        env_overrides={"JAX_PLATFORMS": "axon", "XLA_FLAGS": ""},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "step ok" in proc.stdout, proc.stdout


def test_train_resume_predict_cycle(tmp_path):
    """The reference workflow end to end, as subprocesses with a clean env:
    train 2 epochs -> --resume 1 more -> predict.py -> CSV rows match.
    Catches the platform/env regression class (VERDICT round 1 weak #1)."""
    ckpt = str(tmp_path / "ckpt")
    env = {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""}
    base = [
        sys.executable, "train.py", "--synthetic", "64", "--device", "cpu",
        "--epochs", "2", "--optim", "Adam", "-b", "16", "--radius", "5",
        "--ckpt-dir", ckpt, "--print-freq", "0",
    ]
    p1 = _run(base, env_overrides=env)
    assert p1.returncode == 0, p1.stderr[-2000:]
    assert "Epoch 1:" in p1.stdout and "** test mae:" in p1.stdout

    # machine-readable metrics were produced (SURVEY.md §5)
    metrics_file = os.path.join(ckpt, "logs", "metrics.jsonl")
    assert os.path.exists(metrics_file)
    lines = open(metrics_file).read().strip().splitlines()
    assert len(lines) >= 4  # train+val per epoch (+ test)
    import json

    rec = json.loads(lines[0])
    assert "train/loss" in rec and rec["step"] == 0

    assert base[6] == "--epochs"
    p2 = _run(
        base[:7] + ["3"] + base[8:] + ["--resume", ckpt],
        env_overrides=env,
    )
    assert p2.returncode == 0, p2.stderr[-2000:]
    assert "resumed from" in p2.stdout and "at epoch 2" in p2.stdout
    assert "Epoch 2:" in p2.stdout
    assert "Epoch 0:" not in p2.stdout  # numbering continued, not restarted

    out_csv = str(tmp_path / "preds.csv")
    p3 = _run(
        [sys.executable, "predict.py", ckpt, "unused", "--device", "cpu",
         "--synthetic", "16", "-b", "16", "--out", out_csv],
        env_overrides=env,
    )
    assert p3.returncode == 0, p3.stderr[-2000:]
    rows = open(out_csv).read().strip().splitlines()
    assert len(rows) == 16
    cid, target, pred = rows[0].split(",")
    float(target), float(pred)  # numeric columns
    assert cid.startswith("synth-")


def test_train_cli_graph_shards(tmp_path):
    """--graph-shards 2 --data-parallel over 8 virtual devices: the 2-D
    ('data','graph') mesh trains end to end from the CLI."""
    proc = _run(
        [sys.executable, "train.py", "--synthetic", "48", "--device", "cpu",
         "--epochs", "1", "-b", "8", "--radius", "5",
         "--data-parallel", "--graph-shards", "2",
         "--ckpt-dir", str(tmp_path / "ckpt"), "--print-freq", "0"],
        env_overrides={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dp x4 * graph x2" in proc.stdout, proc.stdout
    assert "** test mae:" in proc.stdout

    # a checkpoint saved from the 8-device 2-D mesh must restore in a
    # plain single-device predict process (topology-independent saves)
    out_csv = str(tmp_path / "preds.csv")
    p2 = _run(
        [sys.executable, "predict.py", str(tmp_path / "ckpt"), "unused",
         "--device", "cpu", "--synthetic", "8", "-b", "8", "--out", out_csv],
        env_overrides={"JAX_PLATFORMS": "cpu", "XLA_FLAGS": ""},
    )
    assert p2.returncode == 0, p2.stderr[-2000:]
    assert len(open(out_csv).read().strip().splitlines()) == 8


def test_dryrun_multichip_child_guard_runs_inline():
    """With the child guard set, dryrun must execute inline (no recursion)."""
    code = (
        "import __graft_entry__ as g; g.dryrun_multichip(2); "
        "import sys; print('CHILDMODE-DONE')"
    )
    proc = _run(
        [sys.executable, "-c", code],
        env_overrides={
            "_CGNN_DRYRUN_CHILD": "1",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CHILDMODE-DONE" in proc.stdout
