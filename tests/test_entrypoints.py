"""Entrypoint regression tests (SURVEY.md §4.4, VERDICT.md next-step #9).

These run the driver-facing and user-facing entrypoints the way their real
callers do — in subprocesses with realistic (sometimes hostile) environments
— to catch the platform/env bug class that unit tests cannot see.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, env_overrides=None, timeout=600):
    env = dict(os.environ)
    # simulate the driver env: no pytest-conftest CPU pinning
    env.pop("_CGNN_DRYRUN_CHILD", None)
    if env_overrides:
        env.update(env_overrides)
    return subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout,
    )


def test_dryrun_multichip_survives_pinned_axon_platform():
    """The driver pins JAX_PLATFORMS to the real-TPU tunnel; the dry run
    must self-provision a virtual CPU mesh anyway (round-1 red check)."""
    code = "import __graft_entry__ as g; g.dryrun_multichip(2)"
    proc = _run(
        [sys.executable, "-c", code],
        env_overrides={"JAX_PLATFORMS": "axon", "XLA_FLAGS": ""},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "step ok" in proc.stdout, proc.stdout


def test_dryrun_multichip_child_guard_runs_inline():
    """With the child guard set, dryrun must execute inline (no recursion)."""
    code = (
        "import __graft_entry__ as g; g.dryrun_multichip(2); "
        "import sys; print('CHILDMODE-DONE')"
    )
    proc = _run(
        [sys.executable, "-c", code],
        env_overrides={
            "_CGNN_DRYRUN_CHILD": "1",
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "CHILDMODE-DONE" in proc.stdout
