"""Hostile-CIF corpus (VERDICT r2 #6, SURVEY.md §7 hard parts #6).

The in-tree parser's pre-round-3 validation was a self-consistent loop
(files written by write_cif_file). These fixtures are hand-authored in
FOREIGN conventions — pymatgen/VESTA/ICSD/mmCIF-style headers, esd
suffixes, oxidation states, reordered and interleaved loops, multi-block
files — plus corrupt/unsupported files that must fail LOUDLY AND
SPECIFICALLY, never silently mis-parse (the HM-symbol-only case would
otherwise silently drop every atom outside the asymmetric unit).
"""

import os

import numpy as np
import pytest

from cgnn_tpu.data.cif import CIFError, parse_cif_file

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "cif")


def fx(name: str) -> str:
    return os.path.join(FIXTURES, name)


class TestForeignConventionsParse:
    def test_pymatgen_style(self):
        s = parse_cif_file(fx("pymatgen_style.cif"))
        assert len(s.numbers) == 8
        assert sorted(np.bincount(s.numbers).nonzero()[0]) == [11, 17]
        assert s.lattice_parameters()[0] == pytest.approx(5.691698)

    def test_icsd_esds_and_label_only_sites(self):
        s = parse_cif_file(fx("icsd_esd_label_only.cif"))
        assert len(s.numbers) == 4
        assert set(s.numbers) == {13}  # AL1 -> Al, not A-l confusion
        assert s.lattice_parameters()[0] == pytest.approx(4.0521)

    def test_mmcif_dotted_tags(self):
        s = parse_cif_file(fx("mmcif_dotted_tags.cif"))
        assert len(s.numbers) == 5  # SrTiO3 perovskite cell
        assert sorted(set(s.numbers)) == [8, 22, 38]

    def test_vesta_oxidation_states_reordered_columns(self):
        s = parse_cif_file(fx("vesta_oxidation_reordered.cif"))
        assert len(s.numbers) == 6  # rutile TiO2
        assert sorted(np.bincount(s.numbers).nonzero()[0]) == [8, 22]

    def test_symop_expansion_with_fraction_translations(self):
        s = parse_cif_file(fx("symop_fractions_reordered.cif"))
        # 1 site x {identity, (1/2,1/2,1/2)} -> bcc: 2 atoms
        assert len(s.numbers) == 2
        assert set(s.numbers) == {26}

    def test_multiblock_and_text_field(self):
        s = parse_cif_file(fx("multiblock_textfield.cif"))
        # first block only: 2 Si sites; '?' occupancy treated as unknown=full
        assert len(s.numbers) == 2
        assert set(s.numbers) == {14}
        assert s.lattice_parameters()[0] == pytest.approx(5.43)


class TestHostileFilesRefuseLoudly:
    def test_hm_symbol_only_refused(self):
        """A non-P1 HM symbol without operators must NOT silently parse as
        P1 — that reads 2 asymmetric-unit atoms where Fm-3m implies 8."""
        with pytest.raises(CIFError, match="F m -3 m.*Hermann-Mauguin"):
            parse_cif_file(fx("hm_symbol_only.cif"))

    def test_it_number_only_refused(self):
        with pytest.raises(CIFError, match="IT number 227"):
            parse_cif_file(fx("it_number_only.cif"))

    def test_mmcif_cartesian_only_refused(self):
        with pytest.raises(CIFError, match="Cartn.*fractional"):
            parse_cif_file(fx("mmcif_cartesian_only.cif"))

    def test_partial_occupancy_refused(self):
        with pytest.raises(CIFError, match="partial occupancy 0.5"):
            parse_cif_file(fx("partial_occupancy.cif"))

    def test_ragged_loop_refused(self):
        with pytest.raises(CIFError, match="4 columns has 7 values"):
            parse_cif_file(fx("ragged_loop.cif"))

    def test_unknown_cell_value_refused(self):
        with pytest.raises(CIFError, match="expected a number, got '\\?'"):
            parse_cif_file(fx("unknown_cell_value.cif"))


class TestRound4Corpus:
    """VERDICT r3 next-step #9 fixtures: CRLF, isotopes, esd-on-angles,
    multi-block selection, oxidation-suffix symbols, Hall-only refusal."""

    def test_crlf_windows_line_endings(self):
        s = parse_cif_file(fx("crlf_windows.cif"))
        assert len(s.numbers) == 4
        assert set(s.numbers) == {13}
        assert s.lattice_parameters()[0] == pytest.approx(4.05)

    def test_deuterium_tritium_sites_map_to_hydrogen(self):
        s = parse_cif_file(fx("deuterium_ice.cif"))
        assert len(s.numbers) == 4
        assert sorted(np.bincount(s.numbers).nonzero()[0]) == [1, 8]
        assert int((s.numbers == 1).sum()) == 3  # D1, D2, T1

    def test_esd_on_angles_and_negative_coords(self):
        s = parse_cif_file(fx("esd_angles_negative_coords.cif"))
        assert len(s.numbers) == 3
        a, b, c, al, be, ga = s.lattice_parameters()
        assert al == pytest.approx(89.95)
        assert ga == pytest.approx(90.03)
        # negative/out-of-cell fracs wrap into [0, 1)
        w = s.wrapped().frac_coords
        assert (w >= 0).all() and (w < 1).all()

    def test_metadata_first_block_skipped(self):
        """Selection policy: the first block WITH fractional atom sites is
        the structure — a leading metadata-only block must not make the
        parse fail (or worse, return zero atoms)."""
        s = parse_cif_file(fx("metadata_block_first.cif"))
        assert len(s.numbers) == 2
        assert sorted(s.numbers) == [11, 17]
        assert s.lattice_parameters()[0] == pytest.approx(5.64)

    def test_oxidation_suffix_symbols(self):
        s = parse_cif_file(fx("oxidation_edge_labels.cif"))
        assert len(s.numbers) == 5
        counts = np.bincount(s.numbers)
        assert counts[25] == 2 and counts[29] == 1 and counts[8] == 2

    def test_hall_symbol_only_refused(self):
        """A Hall-only non-P1 group without operators must refuse like the
        H-M/IT-number cases (advisor r3: it used to parse silently as P1,
        dropping 6 of Fm-3m gold's 8 atoms)."""
        with pytest.raises(CIFError, match="Hall symbol.*-F 4 2 3"):
            parse_cif_file(fx("hall_symbol_only.cif"))


def test_dirty_directory_featurization_and_training(tmp_path):
    """featurize_directory_parallel over a directory where ~20% of files
    are corrupt: the failure report must name every corrupt file with its
    reason, and the survivors must train (VERDICT r3 next-step #9)."""
    import shutil

    from cgnn_tpu.data.cache import featurize_directory_parallel
    from cgnn_tpu.data.dataset import FeaturizeConfig

    good = ["pymatgen_style.cif", "icsd_esd_label_only.cif",
            "mmcif_dotted_tags.cif", "vesta_oxidation_reordered.cif",
            "crlf_windows.cif", "deuterium_ice.cif",
            "esd_angles_negative_coords.cif", "metadata_block_first.cif",
            "oxidation_edge_labels.cif", "symop_fractions_reordered.cif",
            "multiblock_textfield.cif", "pymatgen_style.cif"]
    bad = ["hm_symbol_only.cif", "hall_symbol_only.cif",
           "partial_occupancy.cif"]
    rows = []
    for i, name in enumerate(good):
        shutil.copy(fx(name), tmp_path / f"g{i:02d}.cif")
        rows.append(f"g{i:02d},{0.1 * i:.3f}")
    for i, name in enumerate(bad):
        shutil.copy(fx(name), tmp_path / f"b{i:02d}.cif")
        rows.append(f"b{i:02d},0.0")
    rows.append("missing,1.0")  # listed in id_prop.csv, no file on disk
    (tmp_path / "id_prop.csv").write_text("\n".join(rows) + "\n")

    graphs, failures = featurize_directory_parallel(
        str(tmp_path), FeaturizeConfig(radius=6.0, max_num_nbr=8), workers=2,
    )
    assert len(graphs) == len(good)
    failed_ids = {cid for cid, _ in failures}
    assert failed_ids == {"b00", "b01", "b02", "missing"}
    reasons = dict(failures)
    assert "Hermann-Mauguin" in reasons["b00"]
    assert "Hall symbol" in reasons["b01"]
    assert "partial occupancy" in reasons["b02"]

    # the survivors train: loss decreases over a few epochs
    import jax

    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import fit

    nc, ec = capacities_for(graphs, 4)
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=1, h_fea_len=16)
    state = create_train_state(
        model, next(batch_iterator(graphs, 4, nc, ec)),
        make_optimizer(optim="adam", lr=0.01),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(0),
    )
    state, result = fit(state, graphs, graphs, epochs=6, batch_size=4,
                        node_cap=nc, edge_cap=ec, print_freq=0,
                        log_fn=lambda *a: None)
    losses = [h["train"]["loss"] for h in result["history"]]
    assert losses[-1] < losses[0]


def test_p1_hm_symbol_still_parses():
    """'P 1' HM symbols (pymatgen always writes one) must not trip the
    refusal — only non-P1 symbols without operators do."""
    s = parse_cif_file(fx("pymatgen_style.cif"))
    assert len(s.numbers) == 8


def test_hm_placeholder_values_parse_as_p1():
    """'?' / '.' H-M values are CIF placeholders, not declared space
    groups — they must not trip the no-operator refusal."""
    from cgnn_tpu.data.cif import parse_cif

    text = open(fx("icsd_esd_label_only.cif")).read()
    for placeholder in ("?", "."):
        s = parse_cif(
            text.replace(
                "data_12345-ICSD",
                f"data_x\n_symmetry_space_group_name_H-M {placeholder}",
            )
        )
        assert len(s.numbers) == 4


def test_placeholder_hm_does_not_bypass_it_number_refusal():
    """'?' in the H-M tag must fall through to the IT-number check — a
    file declaring IT 227 with a placeholder symbol would otherwise be
    silently read as P1, dropping every atom outside the asymmetric
    unit."""
    from cgnn_tpu.data.cif import parse_cif

    text = open(fx("it_number_only.cif")).read()
    with pytest.raises(CIFError, match="IT number 227"):
        parse_cif(text.replace(
            "data_spinel_unit",
            "data_x\n_symmetry_space_group_name_H-M ?",
        ))


def test_p1_hm_does_not_bypass_it_number_refusal():
    """A (mislabeled) 'P 1' H-M value must not suppress the IT-number
    check: IT 227 with no operators means asymmetric-unit sites either
    way."""
    from cgnn_tpu.data.cif import parse_cif

    text = open(fx("it_number_only.cif")).read()
    with pytest.raises(CIFError, match="IT number 227"):
        parse_cif(text.replace(
            "data_spinel_unit",
            "data_x\n_symmetry_space_group_name_H-M 'P 1'",
        ))
