"""Hostile-CIF corpus (VERDICT r2 #6, SURVEY.md §7 hard parts #6).

The in-tree parser's pre-round-3 validation was a self-consistent loop
(files written by write_cif_file). These fixtures are hand-authored in
FOREIGN conventions — pymatgen/VESTA/ICSD/mmCIF-style headers, esd
suffixes, oxidation states, reordered and interleaved loops, multi-block
files — plus corrupt/unsupported files that must fail LOUDLY AND
SPECIFICALLY, never silently mis-parse (the HM-symbol-only case would
otherwise silently drop every atom outside the asymmetric unit).
"""

import os

import numpy as np
import pytest

from cgnn_tpu.data.cif import CIFError, parse_cif_file

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "cif")


def fx(name: str) -> str:
    return os.path.join(FIXTURES, name)


class TestForeignConventionsParse:
    def test_pymatgen_style(self):
        s = parse_cif_file(fx("pymatgen_style.cif"))
        assert len(s.numbers) == 8
        assert sorted(np.bincount(s.numbers).nonzero()[0]) == [11, 17]
        assert s.lattice_parameters()[0] == pytest.approx(5.691698)

    def test_icsd_esds_and_label_only_sites(self):
        s = parse_cif_file(fx("icsd_esd_label_only.cif"))
        assert len(s.numbers) == 4
        assert set(s.numbers) == {13}  # AL1 -> Al, not A-l confusion
        assert s.lattice_parameters()[0] == pytest.approx(4.0521)

    def test_mmcif_dotted_tags(self):
        s = parse_cif_file(fx("mmcif_dotted_tags.cif"))
        assert len(s.numbers) == 5  # SrTiO3 perovskite cell
        assert sorted(set(s.numbers)) == [8, 22, 38]

    def test_vesta_oxidation_states_reordered_columns(self):
        s = parse_cif_file(fx("vesta_oxidation_reordered.cif"))
        assert len(s.numbers) == 6  # rutile TiO2
        assert sorted(np.bincount(s.numbers).nonzero()[0]) == [8, 22]

    def test_symop_expansion_with_fraction_translations(self):
        s = parse_cif_file(fx("symop_fractions_reordered.cif"))
        # 1 site x {identity, (1/2,1/2,1/2)} -> bcc: 2 atoms
        assert len(s.numbers) == 2
        assert set(s.numbers) == {26}

    def test_multiblock_and_text_field(self):
        s = parse_cif_file(fx("multiblock_textfield.cif"))
        # first block only: 2 Si sites; '?' occupancy treated as unknown=full
        assert len(s.numbers) == 2
        assert set(s.numbers) == {14}
        assert s.lattice_parameters()[0] == pytest.approx(5.43)


class TestHostileFilesRefuseLoudly:
    def test_hm_symbol_only_refused(self):
        """A non-P1 HM symbol without operators must NOT silently parse as
        P1 — that reads 2 asymmetric-unit atoms where Fm-3m implies 8."""
        with pytest.raises(CIFError, match="F m -3 m.*Hermann-Mauguin"):
            parse_cif_file(fx("hm_symbol_only.cif"))

    def test_it_number_only_refused(self):
        with pytest.raises(CIFError, match="IT number 227"):
            parse_cif_file(fx("it_number_only.cif"))

    def test_mmcif_cartesian_only_refused(self):
        with pytest.raises(CIFError, match="Cartn.*fractional"):
            parse_cif_file(fx("mmcif_cartesian_only.cif"))

    def test_partial_occupancy_refused(self):
        with pytest.raises(CIFError, match="partial occupancy 0.5"):
            parse_cif_file(fx("partial_occupancy.cif"))

    def test_ragged_loop_refused(self):
        with pytest.raises(CIFError, match="4 columns has 7 values"):
            parse_cif_file(fx("ragged_loop.cif"))

    def test_unknown_cell_value_refused(self):
        with pytest.raises(CIFError, match="expected a number, got '\\?'"):
            parse_cif_file(fx("unknown_cell_value.cif"))


def test_p1_hm_symbol_still_parses():
    """'P 1' HM symbols (pymatgen always writes one) must not trip the
    refusal — only non-P1 symbols without operators do."""
    s = parse_cif_file(fx("pymatgen_style.cif"))
    assert len(s.numbers) == 8


def test_hm_placeholder_values_parse_as_p1():
    """'?' / '.' H-M values are CIF placeholders, not declared space
    groups — they must not trip the no-operator refusal."""
    from cgnn_tpu.data.cif import parse_cif

    text = open(fx("icsd_esd_label_only.cif")).read()
    for placeholder in ("?", "."):
        s = parse_cif(
            text.replace(
                "data_12345-ICSD",
                f"data_x\n_symmetry_space_group_name_H-M {placeholder}",
            )
        )
        assert len(s.numbers) == 4


def test_placeholder_hm_does_not_bypass_it_number_refusal():
    """'?' in the H-M tag must fall through to the IT-number check — a
    file declaring IT 227 with a placeholder symbol would otherwise be
    silently read as P1, dropping every atom outside the asymmetric
    unit."""
    from cgnn_tpu.data.cif import parse_cif

    text = open(fx("it_number_only.cif")).read()
    with pytest.raises(CIFError, match="IT number 227"):
        parse_cif(text.replace(
            "data_spinel_unit",
            "data_x\n_symmetry_space_group_name_H-M ?",
        ))


def test_p1_hm_does_not_bypass_it_number_refusal():
    """A (mislabeled) 'P 1' H-M value must not suppress the IT-number
    check: IT 227 with no operators means asymmetric-unit sites either
    way."""
    from cgnn_tpu.data.cif import parse_cif

    text = open(fx("it_number_only.cif")).read()
    with pytest.raises(CIFError, match="IT number 227"):
        parse_cif(text.replace(
            "data_spinel_unit",
            "data_x\n_symmetry_space_group_name_H-M 'P 1'",
        ))
