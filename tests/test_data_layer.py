"""Data-layer unit tests: elements, featurization, CIF parsing, containers.

Covers SURVEY.md §4.2 (golden values, round-trips) for the host-side pieces.
"""

import numpy as np
import pytest

from cgnn_tpu.data.elements import (
    ATOM_FEA_DIM,
    ELEMENTS,
    atom_features,
    full_embedding_table,
)
from cgnn_tpu.data.featurize import GaussianDistance
from cgnn_tpu.data.cif import CIFError, parse_cif, parse_symmetry_op
from cgnn_tpu.data.structure import Structure, lattice_from_parameters
from cgnn_tpu.data.graph import (
    CrystalGraph,
    pack_graphs,
    batch_iterator,
    round_to_bucket,
)
from cgnn_tpu.data.dataset import FeaturizeConfig, featurize_structure
from cgnn_tpu.data.synthetic import random_structure, synthetic_dataset


class TestElements:
    def test_dim_and_dtype(self):
        fea = atom_features([1, 8, 26, 92])
        assert fea.shape == (4, ATOM_FEA_DIM)
        assert fea.dtype == np.float32
        assert set(np.unique(fea)) <= {0.0, 1.0}

    def test_table_complete(self):
        table = full_embedding_table()
        assert table.shape == (101, 92)
        assert np.all(table[0] == 0)
        # every real element must have group/period/block one-hots set
        for z in range(1, 101):
            assert table[z, :18].sum() == 1.0, f"group missing for Z={z}"
            assert table[z, 18:26].sum() == 1.0, f"period missing for Z={z}"

    def test_distinct_elements_distinct_features(self):
        table = full_embedding_table()
        # common elements should be pairwise distinguishable
        common = [1, 3, 6, 7, 8, 9, 11, 14, 16, 26, 29, 79]
        for i, a in enumerate(common):
            for b in common[i + 1 :]:
                assert not np.array_equal(table[a], table[b]), (a, b)

    def test_unknown_z_raises(self):
        with pytest.raises(KeyError):
            atom_features([150])

    def test_nan_properties_give_zero_segment(self):
        he = atom_features([2])[0]
        # electronegativity bins are dims 26..36 — He has no Pauling EN
        assert he[26:36].sum() == 0.0


class TestGaussianDistance:
    def test_golden(self):
        gdf = GaussianDistance(dmin=0.0, dmax=8.0, step=0.2)
        assert gdf.num_features == 41
        out = gdf.expand(np.array([1.0]))
        assert out.shape == (1, 41)
        # peak at mu=1.0 (bin 5), value exp(0)=1
        assert out[0, 5] == pytest.approx(1.0, abs=1e-6)
        # neighbor bin: exp(-(0.2^2)/0.2^2) = e^-1
        assert out[0, 4] == pytest.approx(np.exp(-1.0), rel=1e-5)

    def test_shapes(self):
        gdf = GaussianDistance()
        assert gdf.expand(np.zeros((7, 3))).shape == (7, 3, 41)


class TestLattice:
    def test_cubic(self):
        lat = lattice_from_parameters(4, 4, 4, 90, 90, 90)
        np.testing.assert_allclose(lat, np.eye(3) * 4, atol=1e-12)

    def test_volume_triclinic(self):
        lat = lattice_from_parameters(3, 4, 5, 80, 95, 103)
        s = Structure(lat, [[0, 0, 0]], [6])
        assert 0 < s.volume < 60

    def test_cart_roundtrip(self):
        lat = lattice_from_parameters(3.1, 4.2, 5.3, 82, 94, 101)
        frac = np.array([[0.1, 0.7, 0.3]])
        s = Structure(lat, frac, [14])
        back = s.cart_coords @ np.linalg.inv(lat)
        np.testing.assert_allclose(back, frac, atol=1e-12)


NACL_CIF = """
data_NaCl
_cell_length_a 5.64
_cell_length_b 5.64
_cell_length_c 5.64
_cell_angle_alpha 90
_cell_angle_beta 90
_cell_angle_gamma 90
loop_
_atom_site_label
_atom_site_type_symbol
_atom_site_fract_x
_atom_site_fract_y
_atom_site_fract_z
Na1 Na 0.0 0.0 0.0
Na2 Na 0.5 0.5 0.0
Na3 Na 0.5 0.0 0.5
Na4 Na 0.0 0.5 0.5
Cl1 Cl 0.5 0.0 0.0
Cl2 Cl 0.0 0.5 0.0
Cl3 Cl 0.0 0.0 0.5
Cl4 Cl 0.5 0.5 0.5
"""

SYMMETRY_CIF = """
data_bcc_Fe
_cell_length_a 2.87
_cell_length_b 2.87
_cell_length_c 2.87
_cell_angle_alpha 90.0
_cell_angle_beta 90.0
_cell_angle_gamma 90.0
loop_
_symmetry_equiv_pos_as_xyz
'x, y, z'
'1/2+x, 1/2+y, 1/2+z'
loop_
_atom_site_label
_atom_site_fract_x
_atom_site_fract_y
_atom_site_fract_z
_atom_site_occupancy
Fe1 0.0 0.0 0.0 1.0
"""


class TestCIF:
    def test_p1(self):
        s = parse_cif(NACL_CIF)
        assert s.num_atoms == 8
        assert sorted(s.numbers.tolist()) == [11] * 4 + [17] * 4

    def test_symmetry_expansion(self):
        s = parse_cif(SYMMETRY_CIF)
        assert s.num_atoms == 2  # bcc: corner + body center
        assert set(s.numbers.tolist()) == {26}
        fracs = sorted(s.frac_coords.tolist())
        np.testing.assert_allclose(fracs[1], [0.5, 0.5, 0.5], atol=1e-9)

    def test_symmetry_op_parser(self):
        rot, trans = parse_symmetry_op("-x, 1/2+y, x-z")
        np.testing.assert_allclose(rot[0], [-1, 0, 0])
        np.testing.assert_allclose(rot[1], [0, 1, 0])
        np.testing.assert_allclose(rot[2], [1, 0, -1])
        np.testing.assert_allclose(trans, [0, 0.5, 0])

    def test_partial_occupancy_rejected(self):
        bad = SYMMETRY_CIF.replace("Fe1 0.0 0.0 0.0 1.0", "Fe1 0.0 0.0 0.0 0.5")
        with pytest.raises(CIFError, match="occupancy"):
            parse_cif(bad)

    def test_esd_numbers(self):
        cif = NACL_CIF.replace("_cell_length_a 5.64", "_cell_length_a 5.64(2)")
        assert parse_cif(cif).num_atoms == 8


def _toy_graph(n_nodes, n_edges, target=1.0, seed=0):
    rng = np.random.default_rng(seed)
    return CrystalGraph(
        atom_fea=rng.normal(size=(n_nodes, 92)).astype(np.float32),
        edge_fea=rng.normal(size=(n_edges, 41)).astype(np.float32),
        centers=rng.integers(0, n_nodes, n_edges).astype(np.int32),
        neighbors=rng.integers(0, n_nodes, n_edges).astype(np.int32),
        target=np.array([target], np.float32),
        cif_id=f"toy-{seed}",
    )


class TestGraphBatch:
    def test_pack_offsets_and_masks(self):
        g1, g2 = _toy_graph(3, 10, 1.0, 1), _toy_graph(5, 20, 2.0, 2)
        b = pack_graphs([g1, g2], node_cap=16, edge_cap=64, graph_cap=4)
        assert b.nodes.shape == (16, 92)
        assert b.node_mask.sum() == 8
        assert b.edge_mask.sum() == 30
        assert b.graph_mask.sum() == 2
        # second graph's edges index into offset node slots
        assert b.centers[10:30].min() >= 3
        assert b.centers[10:30].max() < 8
        np.testing.assert_array_equal(b.node_graph[:8], [0] * 3 + [1] * 5)
        np.testing.assert_allclose(b.targets[:2, 0], [1.0, 2.0])

    def test_capacity_overflow_raises(self):
        with pytest.raises(ValueError, match="exceeds capacity"):
            pack_graphs([_toy_graph(10, 5)], node_cap=4, edge_cap=64, graph_cap=2)

    def test_bucket_ladder(self):
        assert round_to_bucket(10, minimum=64) == 64
        assert round_to_bucket(64, minimum=64) == 64
        v1, v2 = round_to_bucket(65, minimum=64), round_to_bucket(1000, minimum=64)
        assert v1 >= 65 and v2 >= 1000
        # ladder is deterministic: same n -> same cap
        assert round_to_bucket(999, minimum=64) == round_to_bucket(999, minimum=64)

    def test_batch_iterator_fixed_shapes(self):
        graphs = [_toy_graph(3 + i % 4, 10 + i % 7, seed=i) for i in range(20)]
        batches = list(batch_iterator(graphs, batch_size=4, node_cap=64, edge_cap=256))
        assert all(b.nodes.shape == (64, 92) for b in batches)
        assert sum(int(b.graph_mask.sum()) for b in batches) == 20

    def test_batch_iterator_respects_caps(self):
        graphs = [_toy_graph(30, 100, seed=i) for i in range(4)]
        batches = list(batch_iterator(graphs, batch_size=4, node_cap=64, edge_cap=512))
        assert len(batches) == 2  # 2 graphs of 30 nodes fit per 64-node batch


class TestSyntheticAndFeaturize:
    def test_deterministic(self):
        a = synthetic_dataset(3, seed=7)
        b = synthetic_dataset(3, seed=7)
        for (ida, sa, ta), (idb, sb, tb) in zip(a, b):
            assert ida == idb and ta == tb
            np.testing.assert_array_equal(sa.numbers, sb.numbers)

    def test_featurize_structure(self):
        rng = np.random.default_rng(0)
        s = random_structure(rng)
        g = featurize_structure(s, 1.5, FeaturizeConfig(radius=6.0, max_num_nbr=8),
                                keep_geometry=True)
        assert g.atom_fea.shape == (s.num_atoms, 92)
        assert g.edge_fea.shape[1] == 31  # radius 6, step 0.2 -> 31 bins
        assert g.centers.max() < s.num_atoms
        # knn truncation: no atom exceeds max_num_nbr
        assert np.bincount(g.centers).max() <= 8
        assert g.positions.shape == (s.num_atoms, 3)


class TestReviewRegressions:
    """Regressions from the round-1 code review."""

    def test_all_caps_labels(self):
        from cgnn_tpu.data.cif import _symbol_from_label
        assert _symbol_from_label("FE1") == "Fe"
        assert _symbol_from_label("CA2") == "Ca"
        assert _symbol_from_label("Fe2+") == "Fe"
        assert _symbol_from_label("O1") == "O"
        assert _symbol_from_label("OW") == "O"  # water oxygen label
        assert _symbol_from_label("NB3") == "Nb"

    def test_trailing_dot_numbers(self):
        cif = NACL_CIF.replace("_cell_angle_alpha 90", "_cell_angle_alpha 90.")
        assert parse_cif(cif).num_atoms == 8

    def test_wrapped_halfopen(self):
        s = Structure(np.eye(3) * 3.0, [[-1e-20, 0.5, 0.999999999]], [6])
        w = s.wrapped()
        assert np.all(w.frac_coords < 1.0)
        assert np.all(w.frac_coords >= 0.0)

    def test_drop_last_keeps_full_final_batch(self):
        graphs = [_toy_graph(3, 10, seed=i) for i in range(8)]
        batches = list(
            batch_iterator(graphs, batch_size=4, node_cap=64, edge_cap=256,
                           drop_last=True)
        )
        assert sum(int(b.graph_mask.sum()) for b in batches) == 8
        # 9 graphs -> tail of 1 dropped
        graphs9 = graphs + [_toy_graph(3, 10, seed=99)]
        batches9 = list(
            batch_iterator(graphs9, batch_size=4, node_cap=64, edge_cap=256,
                           drop_last=True)
        )
        assert sum(int(b.graph_mask.sum()) for b in batches9) == 8
