"""Periodic neighbor list: vectorized vs explicit-loop brute force.

SURVEY.md §4.1: highest-risk in-house component (pymatgen unavailable) —
property-test over random triclinic cells that both implementations return
identical neighbor multisets and distances.
"""

import numpy as np
import pytest

from cgnn_tpu.data.neighbors import (
    knn_neighbor_list,
    neighbor_list,
    neighbor_list_brute,
)
from cgnn_tpu.data.structure import Structure, lattice_from_parameters


def _edge_set(nl):
    return sorted(
        zip(
            nl.centers.tolist(),
            nl.neighbors.tolist(),
            map(tuple, nl.offsets.tolist()),
            np.round(nl.distances, 5).tolist(),
        )
    )


def _random_structure(rng, n_atoms):
    abc = rng.uniform(2.5, 6.0, size=3)
    angles = rng.uniform(60.0, 120.0, size=3)
    while True:
        try:
            lat = lattice_from_parameters(*abc, *angles)
            break
        except ValueError:
            angles = rng.uniform(70.0, 110.0, size=3)
    fracs = rng.uniform(0, 1, size=(n_atoms, 3))
    numbers = rng.integers(1, 80, size=n_atoms)
    return Structure(lat, fracs, numbers)


@pytest.mark.parametrize("seed", range(8))
def test_vectorized_matches_brute(seed):
    rng = np.random.default_rng(seed)
    s = _random_structure(rng, int(rng.integers(1, 6)))
    radius = float(rng.uniform(2.0, 5.0))
    fast = neighbor_list(s, radius)
    slow = neighbor_list_brute(s, radius)
    assert _edge_set(fast) == _edge_set(slow)


def test_chunked_matches_unchunked():
    rng = np.random.default_rng(42)
    s = _random_structure(rng, 12)
    full = neighbor_list(s, 4.0)
    tiny_chunks = neighbor_list(s, 4.0, chunk_elems=10)
    assert _edge_set(full) == _edge_set(tiny_chunks)


def test_simple_cubic_coordination():
    # simple cubic, a=3: 6 first neighbors at 3.0, 12 second at 3*sqrt(2)
    s = Structure(np.eye(3) * 3.0, [[0, 0, 0]], [29])
    nl = neighbor_list(s, 3.05)
    assert len(nl) == 6
    np.testing.assert_allclose(nl.distances, 3.0, atol=1e-5)
    nl2 = neighbor_list(s, 3.0 * np.sqrt(2) + 0.01)
    assert len(nl2) == 18


def test_self_image_neighbors_included():
    # one atom: neighbors are its own periodic copies only
    s = Structure(np.eye(3) * 2.0, [[0.5, 0.5, 0.5]], [6])
    nl = neighbor_list(s, 2.1)
    assert len(nl) == 6
    assert np.all(nl.centers == 0) and np.all(nl.neighbors == 0)
    assert not any((o == (0, 0, 0)).all() for o in nl.offsets)


def test_knn_truncation_orders_by_distance():
    rng = np.random.default_rng(3)
    s = _random_structure(rng, 5)
    full = neighbor_list(s, 5.0)
    m = 4
    knn = knn_neighbor_list(s, 5.0, m, warn_under_coordinated=False)
    counts = np.bincount(knn.centers, minlength=s.num_atoms)
    assert counts.max() <= m
    # kept edges per center must be the m smallest distances
    for i in range(s.num_atoms):
        all_d = np.sort(full.distances[full.centers == i])
        kept = np.sort(knn.distances[knn.centers == i])
        np.testing.assert_allclose(kept, all_d[: len(kept)], rtol=1e-6)


def test_under_coordination_warns():
    s = Structure(np.eye(3) * 4.0, [[0, 0, 0]], [29])
    with pytest.warns(UserWarning, match="fewer than"):
        knn_neighbor_list(s, 4.1, 12)


def test_radius_symmetry():
    # every edge (i -> j, off) has a mirror (j -> i, -off)
    rng = np.random.default_rng(11)
    s = _random_structure(rng, 4)
    nl = neighbor_list(s, 4.0)
    edges = set(zip(nl.centers.tolist(), nl.neighbors.tolist(),
                    map(tuple, nl.offsets.tolist())))
    for i, j, off in edges:
        assert (j, i, tuple(-o for o in off)) in edges


def _adversarial_structures(rng):
    """The lattices that break naive periodic searches (ISSUE 11):
    tiny cells (many images), high-aspect-ratio skew (one short axis),
    and a lone atom neighboring only its own periodic copies."""
    cases = []
    # tiny cell: every atom within radius of many images of everything
    cases.append((Structure(np.eye(3) * 1.9,
                            [[0.1, 0.2, 0.3], [0.6, 0.55, 0.8]],
                            [6, 8]), 4.5))
    # high-aspect skew: long a/b, short c, sheared
    lat = lattice_from_parameters(18.0, 16.0, 2.1, 90.0, 95.0, 112.0)
    cases.append((Structure(lat, rng.uniform(0, 1, (4, 3)),
                            rng.integers(1, 80, 4)), 5.0))
    # extreme shear angles on a small cell
    lat2 = lattice_from_parameters(3.2, 3.4, 3.1, 62.0, 118.0, 65.0)
    cases.append((Structure(lat2, rng.uniform(0, 1, (3, 3)),
                            rng.integers(1, 80, 3)), 6.0))
    # self-image-only neighbors
    cases.append((Structure(np.diag([2.3, 2.9, 2.5]),
                            [[0.4, 0.4, 0.4]], [26]), 5.5))
    return cases


@pytest.mark.parametrize("case", range(4))
def test_vectorized_matches_brute_on_adversarial_lattices(case):
    """ISSUE-11 property pin: the production host search agrees with
    the explicit-loop reference on the lattices that stress the
    image-count bound (tiny cells, skew, self-images)."""
    rng = np.random.default_rng(100 + case)
    s, radius = _adversarial_structures(rng)[case]
    fast = neighbor_list(s, radius, backend="numpy")
    slow = neighbor_list_brute(s, radius)
    assert _edge_set(fast) == _edge_set(slow)


@pytest.mark.parametrize("case", range(4))
def test_in_program_search_matches_host_on_adversarial_lattices(case):
    """The in-program search (ops/neighbor_search.py) selects the SAME
    edges in the SAME canonical order as the host knn featurizer on the
    adversarial lattices — with image caps sized to fit, so no
    overflow flag fires and the comparison is apples-to-apples."""
    jax = pytest.importorskip("jax")

    from cgnn_tpu.data.rawbatch import (
        RawSpec,
        RawStructure,
        host_image_counts,
        pack_raw,
    )
    from cgnn_tpu.ops.neighbor_search import neighbor_search

    rng = np.random.default_rng(100 + case)
    s, radius = _adversarial_structures(rng)[case]
    m = 12
    spec = RawSpec(
        snode_cap=8,
        images=host_image_counts(s.lattice, radius),
        radius=radius,
        dense_m=m,
        gauss_filter=np.arange(0, radius, 0.2, dtype=np.float32),
        gauss_var=0.2,
    )
    rb = pack_raw([RawStructure.from_structure(s)], 1, spec)
    nbr, dist, em, ne, ovf = (
        np.asarray(x) for x in jax.jit(
            lambda rb: neighbor_search(rb.frac, rb.lattices,
                                       rb.atom_mask, spec))(rb)
    )
    assert not ovf.any()
    nl = knn_neighbor_list(s, radius, m, warn_under_coordinated=False)
    counts = np.bincount(nl.centers, minlength=s.num_atoms)
    assert int(ne[0]) == int(np.minimum(counts, m).sum())
    for i in range(s.num_atoms):
        sel = nl.centers == i
        cnt = len(nl.neighbors[sel])
        np.testing.assert_array_equal(nbr[0, i, :cnt], nl.neighbors[sel])
        np.testing.assert_allclose(dist[0, i, :cnt], nl.distances[sel],
                                   atol=2e-5)
        assert em[0, i, :cnt].min() == 1
        assert cnt == m or em[0, i, cnt:].max() == 0


def test_native_cell_list_matches_brute_force_at_slab_scale():
    """The C++ cell list must agree with the brute-force reference in the
    large-graph regime (OC20 slabs, vacuum gap) and in multi-image tiny
    cells (SURVEY.md §7 hard parts #2)."""
    from cgnn_tpu.data.synthetic import synthetic_slab
    from cgnn_tpu.native import native_available, neighbor_search_native

    if not native_available():
        pytest.skip("no C++ toolchain in this environment")

    def canon(c, nb, d, off):
        key = np.lexsort((off[:, 2], off[:, 1], off[:, 0], nb, c))
        return c[key], nb[key], d[key], off[key]

    rng = np.random.default_rng(5)
    cases = [
        (synthetic_slab(rng, nx=4, ny=4, layers=5, adsorbate_atoms=2), 6.0),
        (Structure(np.diag([2.1, 2.3, 2.0]),
                   [[0.1, 0.2, 0.3], [0.6, 0.7, 0.8]], [6, 8]), 7.0),
        (_random_structure(rng, 10), 8.0),
    ]
    for s, r in cases:
        res = neighbor_search_native(s.lattice, s.frac_coords, r)
        assert res is not None
        ref = neighbor_list(s, r, backend="numpy")
        cn, nn, dn, on = canon(*res)
        cr, nr, dr, orr = canon(ref.centers, ref.neighbors, ref.distances,
                                ref.offsets)
        assert len(cn) == len(cr)
        assert (cn == cr).all() and (nn == nr).all() and (on == orr).all()
        np.testing.assert_allclose(dn, dr, atol=1e-5)


def test_native_cell_list_is_fast_at_slab_scale():
    """>=10x over numpy on a 200+ atom slab (it measures ~100x+; the bound
    leaves headroom for slow CI hosts)."""
    import time

    from cgnn_tpu.data.synthetic import synthetic_slab
    from cgnn_tpu.native import native_available, neighbor_search_native

    if not native_available():
        pytest.skip("no C++ toolchain in this environment")
    rng = np.random.default_rng(7)
    s = synthetic_slab(rng, nx=6, ny=6, layers=6, adsorbate_atoms=3)
    assert s.num_atoms >= 200
    neighbor_search_native(s.lattice, s.frac_coords, 6.0)  # warm/build
    t0 = time.perf_counter()
    for _ in range(10):
        neighbor_search_native(s.lattice, s.frac_coords, 6.0)
    t_native = (time.perf_counter() - t0) / 10
    t0 = time.perf_counter()
    neighbor_list(s, 6.0, backend="numpy")
    t_numpy = time.perf_counter() - t0
    assert t_numpy / t_native > 10.0
