"""Live observability plane tests (observe/export.py, observe/profile.py,
the telemetry/spans extensions).

The load-bearing guarantees, pinned:

- RollingSeries retention is bounded by BOTH the sample cap and the time
  window, with explicit eviction — pushing far more than a window's
  worth of samples cannot grow memory (the days-long-server invariant);
- Telemetry value series ride the same windowed retention, and the live
  sub-window quantiles (the /metrics view) differ from the full-window
  view exactly when old traffic ages out;
- the MetricsRegistry snapshot merges telemetry + providers live, its
  Prometheus rendering parses under the sibling validator with
  counter/gauge/summary families and per-device labels, and a broken
  provider cannot take down the scrape;
- LiveMetricsWriter appends schema-stable snapshots;
- ProfileCapture is gated (concurrent captures rejected, never
  stacked), bounded, and writes a non-empty artifact on this backend;
- SpanTracer's event buffer is bounded with an explicit drop counter,
  and retro-stamped complete() spans land on the shared timeline.
"""

import json
import threading

import pytest

from cgnn_tpu.observe import (
    LiveMetricsWriter,
    MetricsRegistry,
    ProfileBusy,
    ProfileCapture,
    RollingSeries,
    SpanTracer,
    Telemetry,
    parse_prometheus_text,
)


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestRollingSeries:
    def test_window_eviction_is_explicit_and_bounded(self):
        clock = _FakeClock()
        s = RollingSeries(window_s=10.0, max_samples=10_000, clock=clock)
        # push WAY more than a window's worth: 50 windows of samples
        for i in range(5000):
            clock.t = i * 0.1
            s.add(float(i))
        # only the last window survives (10s / 0.1s = ~100 samples)
        assert len(s) <= 101
        assert s.evicted >= 4890
        assert s.total_count == 5000  # lifetime accounting intact
        vals = s.values()
        assert min(vals) >= 4899.0  # everything old is GONE, not hidden
        # quantiles describe the window, not the run
        q = s.quantiles()
        assert q["count"] == len(vals)
        assert q["p50"] >= 4899.0

    def test_count_bound_still_applies(self):
        clock = _FakeClock()
        s = RollingSeries(window_s=1e9, max_samples=16, clock=clock)
        for i in range(100):
            s.add(float(i))
        assert len(s) == 16
        assert s.values() == [float(i) for i in range(84, 100)]

    def test_lifetime_totals_are_cumulative_past_eviction(self):
        # the Prometheus _count/_sum contract: they may NEVER decrease,
        # even after the window evicts every sample that produced them
        clock = _FakeClock()
        s = RollingSeries(window_s=10.0, clock=clock)
        for i in range(100):
            clock.t = float(i)
            s.add(2.0)
        q = s.quantiles()
        assert q["count"] < 100  # window shrank...
        assert q["count_total"] == 100  # ...totals did not
        assert q["sum_total"] == 200.0
        clock.t = 1000.0  # everything evicts -> quantiles empty, but a
        s.evict()         # later sample still reports full totals
        s.add(5.0)
        q2 = s.quantiles()
        assert q2["count"] == 1
        assert q2["count_total"] == 101 and q2["sum_total"] == 205.0

    def test_time_passes_with_no_appends(self):
        clock = _FakeClock()
        s = RollingSeries(window_s=5.0, clock=clock)
        s.add(1.0)
        s.add(2.0)
        clock.t = 100.0
        s.evict()
        assert len(s) == 0 and s.quantiles() == {}

    def test_sub_window_narrows(self):
        clock = _FakeClock()
        s = RollingSeries(window_s=100.0, clock=clock)
        s.add(1.0)
        clock.t = 90.0
        s.add(9.0)
        assert sorted(s.values()) == [1.0, 9.0]
        assert s.values(window_s=20.0) == [9.0]
        assert s.quantiles(window_s=20.0)["count"] == 1


class TestTelemetryWindowedSeries:
    def test_series_memory_bounded_past_window(self, tmp_path):
        """The satellite pin: push >window samples through the telemetry
        facade and the retained series stays bounded, with quantiles
        covering the window only."""
        t = Telemetry("epoch", str(tmp_path), use_clu=False,
                      series_window_s=30.0)
        clock = _FakeClock()
        # drive the underlying series with a fake clock (the facade
        # builds it on first observe_value)
        t.observe_value("lat", 0.0, keep=100_000)
        series = t._series["lat"]
        series._clock = clock
        series._samples.clear()  # drop the real-clock bootstrap sample
        for i in range(20_000):
            clock.t = i * 0.01  # 200s of traffic vs a 30s window
            t.observe_value("lat", float(i), keep=100_000)
        assert len(series) <= 3001  # 30s / 0.01s (+1 for the first add)
        q = t.series_quantiles("lat")
        assert q["count"] == len(series)
        assert q["p50"] >= 16_998  # only the recent window
        # the live sub-window narrows further
        q5 = t.series_quantiles("lat", window_s=5.0)
        assert q5["count"] <= 501
        assert q5["p50"] > q["p50"]
        t.close()

    def test_run_summary_series_unchanged_for_short_runs(self, tmp_path):
        from cgnn_tpu.observe import read_jsonl

        t = Telemetry("epoch", str(tmp_path), use_clu=False)
        for v in (1.0, 2.0, 3.0, 4.0):
            t.observe_value("serve_latency_ms", v)
        t.close()
        recs = read_jsonl(str(tmp_path / "metrics.jsonl"))
        summary = [r for r in recs if r.get("event") == "run_summary"]
        assert summary[0]["gauges"]["serve_latency_ms_count"] == 4
        assert summary[0]["gauges"]["serve_latency_ms_p50"] == 2.5


class TestMetricsRegistry:
    def _registry(self, tmp_path):
        t = Telemetry("epoch", str(tmp_path), use_clu=False)
        t.counter_add("serve_requests", 5)
        t.set_gauge("pipeline_workers", 2.0)
        t.set_gauge("device0_inflight", 1.0)
        t.set_gauge("device1_inflight", 3.0)
        t.observe_value("serve_latency_ms", 10.0)
        t.observe_value("serve_latency_ms", 30.0)
        r = MetricsRegistry().attach_telemetry(t)
        return t, r

    def test_snapshot_merges_live(self, tmp_path):
        t, r = self._registry(tmp_path)
        r.add_provider("extra", lambda: {
            "counters": {"pipeline_jobs": 7},
            "gauges": {"serve_queue_depth": 4.0},
        })
        snap = r.snapshot()
        assert snap["counters"]["serve_requests"] == 5
        assert snap["counters"]["pipeline_jobs"] == 7
        assert snap["gauges"]["serve_queue_depth"] == 4.0
        assert snap["series"]["serve_latency_ms"]["count"] == 2
        # live: a counter bump is visible on the NEXT snapshot without
        # any flush/close
        t.counter_add("serve_requests", 1)
        assert r.snapshot()["counters"]["serve_requests"] == 6
        t.close()

    def test_prometheus_round_trip_and_families(self, tmp_path):
        t, r = self._registry(tmp_path)
        text = r.prometheus_text()
        fams = parse_prometheus_text(text)
        assert fams["cgnn_serve_requests_total"]["type"] == "counter"
        assert fams["cgnn_serve_requests_total"]["samples"][0][1] == 5.0
        # device gauges fold into ONE labeled family
        dev = fams["cgnn_device_inflight"]
        assert dev["type"] == "gauge"
        assert sorted(dev["samples"]) == [
            ('cgnn_device_inflight{device="0"}', 1.0),
            ('cgnn_device_inflight{device="1"}', 3.0),
        ]
        # series render as summaries with quantile labels + sum/count
        lat = fams["cgnn_serve_latency_ms"]
        assert lat["type"] == "summary"
        names = [n for n, _ in lat["samples"]]
        assert any('quantile="0.99"' in n for n in names)
        assert "cgnn_serve_latency_ms_count" in names
        t.close()

    def test_replica_family_round_trip(self):
        """The PR-12 ``replica{i}_*`` gauge folding, parsed back: one
        labeled family per metric, every per-replica value recoverable
        from the exposition text by the SAME parser the fleet poller
        uses — emitter and validator cannot drift apart (ISSUE 15
        satellite; fleet/replica.py scrapes exactly this way)."""
        r = MetricsRegistry()
        r.add_provider("fleet", lambda: {
            "counters": {"fleet_requests": 12},
            "gauges": {
                "replica0_inflight": 2.0,
                "replica0_queue_depth": 5.0,
                "replica1_inflight": 0.0,
                "replica1_queue_depth": 1.5,
                "replica10_inflight": 7.0,  # multi-digit rid
                "fleet_replicas_ready": 3.0,
            },
            "series": {
                "replica0_latency_ms": {"p50": 4.0, "p95": 9.0,
                                        "p99": 12.5, "mean": 5.0,
                                        "count": 8},
            },
        })
        fams = parse_prometheus_text(r.prometheus_text())
        inflight = fams["cgnn_replica_inflight"]
        assert inflight["type"] == "gauge"
        assert sorted(inflight["samples"]) == [
            ('cgnn_replica_inflight{replica="0"}', 2.0),
            ('cgnn_replica_inflight{replica="1"}', 0.0),
            ('cgnn_replica_inflight{replica="10"}', 7.0),
        ]
        depth = dict(fams["cgnn_replica_queue_depth"]["samples"])
        assert depth['cgnn_replica_queue_depth{replica="1"}'] == 1.5
        # the un-indexed fleet gauge stays a plain family
        assert fams["cgnn_fleet_replicas_ready"]["samples"] == [
            ("cgnn_fleet_replicas_ready", 3.0)]
        # per-replica latency summaries keep their quantile labels AND
        # the provider-series count fallback (no lifetime totals)
        lat = fams["cgnn_replica0_latency_ms"]
        assert lat["type"] == "summary"
        samples = dict(lat["samples"])
        assert samples[
            'cgnn_replica0_latency_ms{quantile="0.99"}'] == 12.5
        assert samples["cgnn_replica0_latency_ms_count"] == 8.0

    def test_broken_provider_cannot_kill_scrape(self, tmp_path):
        t, r = self._registry(tmp_path)
        r.add_provider("broken", lambda: 1 / 0)
        snap = r.snapshot()  # no raise
        assert snap["counters"]["serve_requests"] == 5
        assert "broken" in r.last_provider_errors
        parse_prometheus_text(r.prometheus_text())
        t.close()

    def test_telemetry_off_contributes_nothing(self):
        r = MetricsRegistry().attach_telemetry(Telemetry.disabled())
        r.add_provider("serve", lambda: {"counters": {"serve_requests": 1}})
        snap = r.snapshot()
        assert snap["counters"] == {"serve_requests": 1}


class TestLiveMetricsWriter:
    def test_appends_snapshots(self, tmp_path):
        r = MetricsRegistry()
        ticks = [0]

        def provider():
            ticks[0] += 1
            return {"gauges": {"tick": float(ticks[0])}}

        r.add_provider("t", provider)
        w = LiveMetricsWriter(r, str(tmp_path / "metrics_live.jsonl"),
                              interval_s=0.05)
        w.write_once()
        w.start()
        import time

        deadline = time.monotonic() + 5.0
        while w.writes < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        w.stop()
        lines = [json.loads(ln) for ln in
                 open(tmp_path / "metrics_live.jsonl")]
        assert len(lines) >= 3
        for rec in lines:
            assert {"time", "counters", "gauges", "series"} <= set(rec)
        # monotone ticks prove each line is a FRESH snapshot
        assert lines[1]["gauges"]["tick"] > lines[0]["gauges"]["tick"]


class TestProfileCapture:
    def test_capture_writes_nonempty_artifact(self, tmp_path):
        spans = SpanTracer()
        with spans.span("pre_capture"):
            pass
        cap = ProfileCapture(str(tmp_path), spans=spans,
                             log_fn=lambda *a: None)
        # give the profiler something to see
        import jax.numpy as jnp

        def work():
            x = jnp.ones((32, 32))
            for _ in range(50):
                x = (x @ x) / 32.0
            x.block_until_ready()

        t = threading.Thread(target=work)
        t.start()
        rec = cap.capture(0.3)
        t.join()
        assert rec["bytes"] > 0 and rec["files"] > 0
        assert cap.captures == 1
        # the host span window landed next to the device trace
        doc = json.load(open(rec["host_trace"]))
        assert any(e["name"] == "pre_capture" for e in doc["traceEvents"])

    def test_concurrent_capture_rejected_not_stacked(self, tmp_path):
        cap = ProfileCapture(str(tmp_path), log_fn=lambda *a: None)
        # hold the gate as a running capture would (two real overlapping
        # jax profiler sessions would crash the process, which is
        # exactly why the gate exists)
        assert cap._gate.acquire(blocking=False)
        try:
            assert cap.busy
            with pytest.raises(ProfileBusy):
                cap.capture(0.05)
        finally:
            cap._gate.release()
        assert cap.rejected == 1 and cap.captures == 0
        assert not cap.busy

    def test_wait_idle_blocks_until_capture_done(self, tmp_path):
        # shutdown paths wait out an in-flight capture: tearing the
        # process down mid-trace segfaults in the profiler backend
        cap = ProfileCapture(str(tmp_path), log_fn=lambda *a: None)
        assert cap.wait_idle(timeout_s=0.1)  # idle: returns immediately
        assert cap._gate.acquire(blocking=False)
        try:
            assert not cap.wait_idle(timeout_s=0.05)  # busy: times out
            timer = threading.Timer(0.2, cap._gate.release)
            timer.start()
            assert cap.wait_idle(timeout_s=5.0)  # released: unblocks
        finally:
            timer.cancel()
            if cap._gate.acquire(blocking=False):
                cap._gate.release()

    def test_duration_is_bounded(self, tmp_path):
        cap = ProfileCapture(str(tmp_path), max_duration_s=0.2,
                             log_fn=lambda *a: None)
        import time

        t0 = time.perf_counter()
        rec = cap.capture(60.0)  # an operator typo, clamped
        # generous bound: the sleep is 0.2s; trace write adds overhead
        assert time.perf_counter() - t0 < 30.0
        assert rec["duration_s"] >= 0.2


class TestSpanTracerBounds:
    def test_event_cap_counts_drops(self, tmp_path):
        tr = SpanTracer(max_events=10)
        for i in range(25):
            tr.instant("e", i=i)
        assert len(tr.events) == 10
        assert tr.dropped == 15
        # ring semantics: the NEWEST events survive (a live trace must
        # show recent requests, not the startup era)
        assert [e["args"]["i"] for e in tr.events] == list(range(15, 25))
        doc = json.load(open(tr.export(str(tmp_path / "t.json"))))
        meta = [e for e in doc["traceEvents"]
                if e.get("name") == "events_dropped"]
        assert meta and meta[0]["args"]["dropped"] == 15

    def test_complete_retro_stamps_on_shared_timeline(self, tmp_path):
        tr = SpanTracer()
        t0 = tr.now_s()
        with tr.span("live"):
            pass
        t1 = tr.now_s()
        tr.complete("retro", t0, t1, trace_id="req-1")
        doc = json.load(open(tr.export(str(tmp_path / "t.json"))))
        retro = [e for e in doc["traceEvents"] if e["name"] == "retro"][0]
        live = [e for e in doc["traceEvents"] if e["name"] == "live"][0]
        assert retro["args"]["trace_id"] == "req-1"
        # the retro span covers the live one on the same clock
        assert retro["ts"] <= live["ts"]
        assert retro["ts"] + retro["dur"] >= live["ts"] + live["dur"]


class TestBenchRegress:
    def test_regression_detected_and_annotated(self, tmp_path, capsys):
        import sys

        sys.path.insert(0, "scripts")
        import bench_regress

        old = {"parsed": {"value": 100.0, "mfu": 0.03,
                          "oc20": {"oc20_structs_per_sec": 50.0}}}
        new_ok = {"parsed": {"value": 95.0, "mfu": 0.03,
                             "oc20": {"oc20_structs_per_sec": 55.0}}}
        new_bad = {"parsed": {"value": 70.0, "mfu": 0.03,
                              "oc20": {"oc20_structs_per_sec": 55.0}}}
        json.dump(old, open(tmp_path / "BENCH_r01.json", "w"))
        json.dump(new_ok, open(tmp_path / "BENCH_r02.json", "w"))
        assert bench_regress.main(["--dir", str(tmp_path)]) == 0
        json.dump(new_bad, open(tmp_path / "BENCH_r03.json", "w"))
        rc = bench_regress.main(["--dir", str(tmp_path), "--github"])
        assert rc == 1
        out = capsys.readouterr().out
        assert "::error" in out and "value" in out

    def test_dropped_key_is_a_regression(self, tmp_path):
        import sys

        sys.path.insert(0, "scripts")
        import bench_regress

        json.dump({"parsed": {"value": 100.0, "mfu": 0.03}},
                  open(tmp_path / "BENCH_r01.json", "w"))
        json.dump({"parsed": {"value": 101.0}},
                  open(tmp_path / "BENCH_r02.json", "w"))
        assert bench_regress.main(["--dir", str(tmp_path)]) == 1

    def test_single_round_is_an_explicit_baseline(self, tmp_path, capsys):
        """One BENCH file is NOT a silent pass: the step must say
        'baseline recorded' (ISSUE 7 — an empty-looking success is how
        a broken glob or wiped artifact dir hides)."""
        import sys

        sys.path.insert(0, "scripts")
        import bench_regress

        json.dump({"parsed": {"mfu": 0.03,
                              "train_structs_per_sec": 100.0}},
                  open(tmp_path / "BENCH_r01.json", "w"))
        rc = bench_regress.main(["--dir", str(tmp_path), "--github"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "baseline recorded" in out
        assert "::notice" in out  # annotated, not invisible, in CI
        assert "r01" in out

    def test_no_rounds_says_nothing_to_do(self, tmp_path, capsys):
        import sys

        sys.path.insert(0, "scripts")
        import bench_regress

        assert bench_regress.main(["--dir", str(tmp_path)]) == 0
        assert "nothing to do" in capsys.readouterr().out
