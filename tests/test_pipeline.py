"""Input-pipeline tests: graph cache round-trip, prefetch loader, the
parallel pack pipeline (data/pipeline.py), native neighbor backend vs
numpy (SURVEY.md §7 phase 4)."""

import threading
import time

import numpy as np
import pytest

from cgnn_tpu.data.cache import load_graph_cache, save_graph_cache
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.data.graph import batch_iterator
from cgnn_tpu.data.loader import prefetch_to_device
from cgnn_tpu.data.neighbors import neighbor_list
from cgnn_tpu.data.pipeline import BufferPool, PackError, parallel_pack
from cgnn_tpu.data.synthetic import random_structure
from cgnn_tpu.native import native_available, neighbor_search_native


@pytest.fixture(scope="module")
def graphs():
    return load_synthetic(12, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                          seed=3, keep_geometry=True)


class TestGraphCache:
    def test_round_trip(self, graphs, tmp_path):
        path = str(tmp_path / "cache.npz")
        save_graph_cache(graphs, path)
        loaded = load_graph_cache(path)
        assert len(loaded) == len(graphs)
        for a, b in zip(graphs, loaded):
            np.testing.assert_array_equal(a.atom_fea, b.atom_fea)
            np.testing.assert_array_equal(a.edge_fea, b.edge_fea)
            np.testing.assert_array_equal(a.centers, b.centers)
            np.testing.assert_array_equal(a.neighbors, b.neighbors)
            np.testing.assert_allclose(
                np.atleast_1d(a.target), b.target[: len(np.atleast_1d(a.target))]
            )
            assert a.cif_id == b.cif_id
            np.testing.assert_allclose(a.positions, b.positions)
            np.testing.assert_allclose(a.lattice, b.lattice)
            np.testing.assert_array_equal(a.offsets, b.offsets)

    def test_cached_graphs_batch_identically(self, graphs, tmp_path):
        path = str(tmp_path / "cache.npz")
        save_graph_cache(graphs, path)
        loaded = load_graph_cache(path)
        b1 = next(batch_iterator(graphs, 4, 128, 1024))
        b2 = next(batch_iterator(loaded, 4, 128, 1024))
        np.testing.assert_array_equal(b1.nodes, b2.nodes)
        np.testing.assert_array_equal(b1.centers, b2.centers)
        np.testing.assert_array_equal(b1.edges, b2.edges)


class TestPrefetch:
    def test_yields_all_batches_in_order(self, graphs):
        batches = list(batch_iterator(graphs, 4, 128, 1024))
        fetched = list(prefetch_to_device(batch_iterator(graphs, 4, 128, 1024)))
        assert len(fetched) == len(batches)
        for a, b in zip(batches, fetched):
            np.testing.assert_allclose(a.nodes, np.asarray(b.nodes))

    def test_propagates_producer_errors(self):
        def boom():
            yield from ()
            raise RuntimeError("producer failed")

        def gen():
            raise RuntimeError("producer failed")
            yield  # noqa

        with pytest.raises(RuntimeError, match="producer failed"):
            list(prefetch_to_device(gen()))


def _pack_threads():
    # the stable pool thread names (graftcheck GC-THREADNAME satellite):
    # workers are '<prefix>-worker-{i}', the feeder '<prefix>-feeder',
    # both keyed by the pool prefix so concurrent pools stay distinct
    # in the racecheck beats registry (default prefix: 'cgnn-pack')
    return [t for t in threading.enumerate()
            if t.name.startswith("cgnn-pack") and t.is_alive()]


class TestParallelPack:
    def test_order_restored_under_skew(self):
        """Workers finishing out of order must not reorder results: slow
        every third job and check the stream still matches input order."""
        def job(i):
            if i % 3 == 0:
                time.sleep(0.01)
            return i * i

        got = list(parallel_pack(range(40), job, workers=4))
        assert got == [i * i for i in range(40)]

    def test_matches_serial_map(self):
        jobs = [np.arange(i + 1) for i in range(25)]
        want = [a.sum() for a in jobs]
        got = list(parallel_pack(iter(jobs), lambda a: a.sum(), workers=3))
        assert got == want

    def test_consumer_abandonment_stops_workers(self):
        """The prefetch stop-event contract, generalized to the pool: a
        consumer that leaves mid-stream (exception/early return) must
        release the feeder and every packer thread promptly — nothing
        may block forever holding packed batches alive."""
        it = parallel_pack(range(10_000), lambda i: np.zeros(1024) + i,
                           workers=3, depth=4)
        for _, _ in zip(range(3), it):
            pass
        it.close()  # what an exception in the consumer loop triggers
        deadline = time.monotonic() + 6.0
        while _pack_threads() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not _pack_threads(), (
            "pack pipeline threads still alive after the consumer left"
        )

    def test_pack_error_delivered_in_order_and_raises(self):
        def job(i):
            if i == 5:
                raise RuntimeError("bad job 5")
            return i

        out = []
        with pytest.raises(RuntimeError, match="bad job 5"):
            for r in parallel_pack(range(10), job, workers=2):
                out.append(r)
        assert out == [0, 1, 2, 3, 4]  # everything before the poison slot

    def test_pack_error_yielded_when_not_raising(self):
        def job(i):
            if i % 4 == 2:
                raise ValueError(f"poison {i}")
            return i

        got = list(parallel_pack(range(8), job, workers=3,
                                 raise_on_error=False))
        assert [r for r in got if not isinstance(r, PackError)] == [
            0, 1, 3, 4, 5, 7]
        errs = [r for r in got if isinstance(r, PackError)]
        assert [str(e.error) for e in errs] == ["poison 2", "poison 6"]
        assert got.index(errs[0]) == 2  # in-order delivery

    def test_jobs_iterable_error_propagates(self):
        """The loader's producer-error contract: an exception raised by
        the JOBS iterable surfaces at the consumer."""
        def jobs():
            yield 1
            yield 2
            raise RuntimeError("producer failed")

        with pytest.raises(RuntimeError, match="producer failed"):
            list(parallel_pack(jobs(), lambda i: i, workers=2))

    def test_depth_bounds_in_flight(self):
        """At most ``depth`` jobs may be past the feeder at once: a
        stalled consumer must not let the packers run ahead unboundedly
        (packed batches are the memory the bound protects)."""
        started = []
        lock = threading.Lock()

        def job(i):
            with lock:
                started.append(i)
            return i

        it = parallel_pack(range(100), job, workers=2, depth=3)
        next(it)
        time.sleep(0.3)  # consumer stalls; feeder+workers run free
        with lock:
            n_started = len(started)
        # 1 consumed + at most `depth` in flight behind it
        assert n_started <= 1 + 3 + 1  # +1: release happens before yield
        it.close()

    def test_buffer_pool_reuses(self):
        pool = BufferPool()
        a = pool.acquire("k", lambda: np.zeros(4))
        pool.release("k", a)
        b = pool.acquire("k", lambda: np.ones(4))  # factory NOT called
        assert b is a
        c = pool.acquire("k", lambda: np.ones(4))  # empty again -> fresh
        assert c is not a
        assert pool.allocated == 2 and pool.reused == 1


class TestNativeNeighbors:
    def test_native_builds(self):
        # g++ is part of this image (SURVEY.md §7); the build must succeed
        assert native_available(), "native neighbor kernel failed to build"

    def test_native_matches_numpy(self):
        rng = np.random.default_rng(0)
        for trial in range(8):
            s = random_structure(rng, min_atoms=2, max_atoms=10)
            radius = float(rng.uniform(3.0, 7.0))
            ref = neighbor_list(s, radius, backend="numpy")
            got = neighbor_search_native(s.lattice, s.frac_coords, radius)
            assert got is not None
            c, nb, d, off = got
            assert len(c) == len(ref), f"trial {trial}: {len(c)} vs {len(ref)}"
            # compare as sets of (i, j, image) -> distance
            def key(cs, ns, offs):
                return {
                    (int(a), int(b), tuple(int(x) for x in o))
                    for a, b, o in zip(cs, ns, offs)
                }

            assert key(c, nb, off) == key(ref.centers, ref.neighbors, ref.offsets)
            ref_map = {
                (int(a), int(b), tuple(map(int, o))): float(dd)
                for a, b, o, dd in zip(
                    ref.centers, ref.neighbors, ref.offsets, ref.distances
                )
            }
            for a, b, o, dd in zip(c, nb, off, d):
                np.testing.assert_allclose(
                    dd, ref_map[(int(a), int(b), tuple(map(int, o)))],
                    rtol=1e-5, atol=1e-5,
                )

    def test_auto_backend_used_in_featurization(self):
        rng = np.random.default_rng(1)
        s = random_structure(rng)
        auto = neighbor_list(s, 5.0, backend="auto")
        ref = neighbor_list(s, 5.0, backend="numpy")
        assert len(auto) == len(ref)
