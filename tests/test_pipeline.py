"""Input-pipeline tests: graph cache round-trip, prefetch loader, native
neighbor backend vs numpy (SURVEY.md §7 phase 4)."""

import numpy as np
import pytest

from cgnn_tpu.data.cache import load_graph_cache, save_graph_cache
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.data.graph import batch_iterator
from cgnn_tpu.data.loader import prefetch_to_device
from cgnn_tpu.data.neighbors import neighbor_list
from cgnn_tpu.data.synthetic import random_structure
from cgnn_tpu.native import native_available, neighbor_search_native


@pytest.fixture(scope="module")
def graphs():
    return load_synthetic(12, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                          seed=3, keep_geometry=True)


class TestGraphCache:
    def test_round_trip(self, graphs, tmp_path):
        path = str(tmp_path / "cache.npz")
        save_graph_cache(graphs, path)
        loaded = load_graph_cache(path)
        assert len(loaded) == len(graphs)
        for a, b in zip(graphs, loaded):
            np.testing.assert_array_equal(a.atom_fea, b.atom_fea)
            np.testing.assert_array_equal(a.edge_fea, b.edge_fea)
            np.testing.assert_array_equal(a.centers, b.centers)
            np.testing.assert_array_equal(a.neighbors, b.neighbors)
            np.testing.assert_allclose(
                np.atleast_1d(a.target), b.target[: len(np.atleast_1d(a.target))]
            )
            assert a.cif_id == b.cif_id
            np.testing.assert_allclose(a.positions, b.positions)
            np.testing.assert_allclose(a.lattice, b.lattice)
            np.testing.assert_array_equal(a.offsets, b.offsets)

    def test_cached_graphs_batch_identically(self, graphs, tmp_path):
        path = str(tmp_path / "cache.npz")
        save_graph_cache(graphs, path)
        loaded = load_graph_cache(path)
        b1 = next(batch_iterator(graphs, 4, 128, 1024))
        b2 = next(batch_iterator(loaded, 4, 128, 1024))
        np.testing.assert_array_equal(b1.nodes, b2.nodes)
        np.testing.assert_array_equal(b1.centers, b2.centers)
        np.testing.assert_array_equal(b1.edges, b2.edges)


class TestPrefetch:
    def test_yields_all_batches_in_order(self, graphs):
        batches = list(batch_iterator(graphs, 4, 128, 1024))
        fetched = list(prefetch_to_device(batch_iterator(graphs, 4, 128, 1024)))
        assert len(fetched) == len(batches)
        for a, b in zip(batches, fetched):
            np.testing.assert_allclose(a.nodes, np.asarray(b.nodes))

    def test_propagates_producer_errors(self):
        def boom():
            yield from ()
            raise RuntimeError("producer failed")

        def gen():
            raise RuntimeError("producer failed")
            yield  # noqa

        with pytest.raises(RuntimeError, match="producer failed"):
            list(prefetch_to_device(gen()))


class TestNativeNeighbors:
    def test_native_builds(self):
        # g++ is part of this image (SURVEY.md §7); the build must succeed
        assert native_available(), "native neighbor kernel failed to build"

    def test_native_matches_numpy(self):
        rng = np.random.default_rng(0)
        for trial in range(8):
            s = random_structure(rng, min_atoms=2, max_atoms=10)
            radius = float(rng.uniform(3.0, 7.0))
            ref = neighbor_list(s, radius, backend="numpy")
            got = neighbor_search_native(s.lattice, s.frac_coords, radius)
            assert got is not None
            c, nb, d, off = got
            assert len(c) == len(ref), f"trial {trial}: {len(c)} vs {len(ref)}"
            # compare as sets of (i, j, image) -> distance
            def key(cs, ns, offs):
                return {
                    (int(a), int(b), tuple(int(x) for x in o))
                    for a, b, o in zip(cs, ns, offs)
                }

            assert key(c, nb, off) == key(ref.centers, ref.neighbors, ref.offsets)
            ref_map = {
                (int(a), int(b), tuple(map(int, o))): float(dd)
                for a, b, o, dd in zip(
                    ref.centers, ref.neighbors, ref.offsets, ref.distances
                )
            }
            for a, b, o, dd in zip(c, nb, off, d):
                np.testing.assert_allclose(
                    dd, ref_map[(int(a), int(b), tuple(map(int, o)))],
                    rtol=1e-5, atol=1e-5,
                )

    def test_auto_backend_used_in_featurization(self):
        rng = np.random.default_rng(1)
        s = random_structure(rng)
        auto = neighbor_list(s, 5.0, backend="auto")
        ref = neighbor_list(s, 5.0, backend="numpy")
        assert len(auto) == len(ref)
