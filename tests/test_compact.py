"""Compact staging (data/compact.py): raw-form packing + on-device
expansion must reproduce pack_graphs exactly (indices/masks) or to f32
roundoff (features), and compose with the scan-epoch training path."""

import numpy as np
import jax
import pytest

from cgnn_tpu.data import invariants
from cgnn_tpu.data.compact import (
    AtomVocab,
    CompactSpec,
    CompactUnsupported,
    compact_pack_fn,
    make_expander,
    pack_compact,
)
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
from cgnn_tpu.data.featurize import GaussianDistance
from cgnn_tpu.data.graph import (
    batch_shape_key,
    bucketed_batch_iterator,
    capacities_for,
    overflow_cap,
    pack_graphs,
)

CFG = FeaturizeConfig(radius=6.0, max_num_nbr=12)


@pytest.fixture(scope="module")
def graphs():
    return load_synthetic_mp(96, CFG, seed=11)


@pytest.fixture(scope="module")
def spec(graphs):
    return CompactSpec.build(graphs, CFG.gdf(), dense_m=CFG.max_num_nbr)


def _pack_pair(graphs, spec, in_cap=None, over_cap=None, edge_dtype=np.float32):
    nc, ec = capacities_for(graphs, len(graphs), dense_m=12, snug=True)
    full = pack_graphs(graphs, nc, ec, len(graphs), dense_m=12,
                       in_cap=in_cap, over_cap=over_cap,
                       edge_dtype=edge_dtype)
    comp = pack_compact(graphs, nc, ec, len(graphs), spec,
                        in_cap=in_cap, over_cap=over_cap)
    return full, comp


def test_expand_reproduces_pack_graphs(graphs, spec):
    oc = overflow_cap(graphs, len(graphs), 12)
    full, comp = _pack_pair(graphs, spec, over_cap=oc)
    got = jax.jit(make_expander(spec))(comp)
    # exact: everything except the exp()-computed edge features
    np.testing.assert_array_equal(np.asarray(got.nodes), full.nodes)
    np.testing.assert_array_equal(np.asarray(got.centers), full.centers)
    np.testing.assert_array_equal(np.asarray(got.neighbors), full.neighbors)
    np.testing.assert_array_equal(np.asarray(got.node_graph), full.node_graph)
    np.testing.assert_array_equal(np.asarray(got.node_mask), full.node_mask)
    np.testing.assert_array_equal(np.asarray(got.edge_mask), full.edge_mask)
    np.testing.assert_array_equal(np.asarray(got.graph_mask), full.graph_mask)
    np.testing.assert_array_equal(np.asarray(got.targets), full.targets)
    np.testing.assert_array_equal(np.asarray(got.target_mask),
                                  full.target_mask)
    np.testing.assert_array_equal(np.asarray(got.in_slots), full.in_slots)
    np.testing.assert_array_equal(np.asarray(got.in_mask), full.in_mask)
    np.testing.assert_array_equal(np.asarray(got.over_slots), full.over_slots)
    np.testing.assert_array_equal(np.asarray(got.over_nodes), full.over_nodes)
    np.testing.assert_array_equal(np.asarray(got.over_mask), full.over_mask)
    np.testing.assert_allclose(np.asarray(got.edges), full.edges, atol=2e-6)
    # geometry comes back None (energy models never read it)
    assert got.positions is None and got.lattices is None


def test_expand_eval_batches_no_transpose(graphs, spec):
    # (batch_iterator normalizes eval's in_cap=0 to None before packing)
    full, comp = _pack_pair(graphs, spec, in_cap=None)
    assert comp.in_slots is None
    got = jax.jit(make_expander(spec))(comp)
    assert got.in_slots is None
    np.testing.assert_allclose(np.asarray(got.edges), full.edges, atol=2e-6)


def test_compact_batch_is_small(graphs, spec):
    oc = overflow_cap(graphs, len(graphs), 12)
    full, comp = _pack_pair(graphs, spec, over_cap=oc)
    nbytes = lambda b: sum(  # noqa: E731
        x.nbytes for x in jax.tree_util.tree_leaves(b)
    )
    assert nbytes(comp) < nbytes(full) / 8


def test_vocab_unsupported_on_continuous_features(graphs):
    import dataclasses

    rng = np.random.default_rng(0)
    cont = [
        dataclasses.replace(
            g, atom_fea=rng.standard_normal(g.atom_fea.shape).astype(
                np.float32
            )
        )
        for g in graphs
    ]
    with pytest.raises(CompactUnsupported):
        AtomVocab.build(cont, max_size=64)


def test_spec_rejects_wrong_gaussian(graphs):
    with pytest.raises(CompactUnsupported):
        CompactSpec.build(graphs, GaussianDistance(0.0, 4.0, 0.5),
                          dense_m=12)


def test_invariants_cover_compact(graphs, spec):
    oc = overflow_cap(graphs, len(graphs), 12)
    _, comp = _pack_pair(graphs, spec, over_cap=oc)
    invariants.check_compact_batch(comp)
    bad = comp.replace(neighbors=comp.neighbors.copy())
    bad.neighbors[0] = comp.node_capacity + 5
    with pytest.raises(invariants.BatchInvariantError):
        invariants.check_compact_batch(bad)
    bad2 = comp.replace(distances=comp.distances.copy())
    bad2.distances[comp.edge_mask == 0] = 1.0
    if (comp.edge_mask == 0).any():
        with pytest.raises(invariants.BatchInvariantError):
            invariants.check_compact_batch(bad2)


def test_iterator_with_compact_pack_fn(graphs, spec):
    stats_batches = list(
        bucketed_batch_iterator(
            graphs, 32, 2, dense_m=12, snug=True,
            pack_fn=compact_pack_fn(spec),
        )
    )
    assert all(hasattr(b, "atom_idx") for b in stats_batches)
    keys = {batch_shape_key(b) for b in stats_batches}
    assert all(k[0] == "compact" for k in keys)


def test_pack_compact_buffer_reuse_bit_identical(graphs, spec):
    """pack_compact(out=) must be indistinguishable from a fresh pack —
    including stale state from a PREVIOUS batch in the recycled buffer
    (the padding-tail zeroing is what this pins)."""
    from cgnn_tpu.data.compact import alloc_compact_buffers

    nc, ec = capacities_for(graphs, len(graphs), dense_m=12, snug=True)
    tdim = 1
    buf = alloc_compact_buffers(nc, 12, len(graphs), tdim)
    # dirty the buffer with a big batch, then pack a SMALLER one into it
    pack_compact(graphs, nc, ec, len(graphs), spec, num_targets=tdim,
                 out=buf)
    small = graphs[:5]
    fresh = pack_compact(small, nc, ec, len(graphs), spec,
                         num_targets=tdim)
    reused = pack_compact(small, nc, ec, len(graphs), spec,
                          num_targets=tdim, out=buf)
    import jax

    for leaf_fresh, leaf_reused in zip(
        jax.tree_util.tree_leaves(fresh), jax.tree_util.tree_leaves(reused)
    ):
        np.testing.assert_array_equal(leaf_fresh, leaf_reused)
    assert reused.atom_idx is buf.atom_idx  # actually reused, not copied


def test_pack_compact_out_rejects_mismatch_and_transpose(graphs, spec):
    from cgnn_tpu.data.compact import alloc_compact_buffers

    nc, ec = capacities_for(graphs, len(graphs), dense_m=12, snug=True)
    wrong = alloc_compact_buffers(nc + 8, 12, len(graphs), 1)
    with pytest.raises(ValueError, match="geometry"):
        pack_compact(graphs, nc, ec, len(graphs), spec, num_targets=1,
                     out=wrong)
    ok = alloc_compact_buffers(nc, 12, len(graphs), 1)
    with pytest.raises(ValueError, match="forward-only"):
        pack_compact(graphs, nc, ec, len(graphs), spec, num_targets=1,
                     over_cap=overflow_cap(graphs, len(graphs), 12), out=ok)


def test_graph_compactable_probe(graphs, spec):
    import dataclasses

    g = graphs[0]
    assert spec.graph_compactable(g)
    # no raw distances (the wire-format request case) -> full fidelity
    bare = dataclasses.replace(g, distances=None)
    assert not spec.graph_compactable(bare)
    # edge features inconsistent with distances -> full fidelity (the
    # exactness contract: compact staging must never change the answer)
    lying = dataclasses.replace(g, edge_fea=g.edge_fea + 0.25)
    assert not spec.graph_compactable(lying)
    # atom rows outside the vocabulary -> full fidelity
    alien = dataclasses.replace(
        g, atom_fea=np.full_like(g.atom_fea, 0.123456)
    )
    assert not spec.graph_compactable(alien)
    # the verdict is cached on the graph, keyed to THIS spec's identity
    # (a different spec in the same process must re-probe, not reuse)
    assert g._compact_ok == (spec._probe_token, True)
    assert alien._compact_ok == (spec._probe_token, False)
    spec2 = CompactSpec.build(graphs, CFG.gdf(), dense_m=12)
    assert spec2.graph_compactable(g)  # re-probed under spec2, not stale
    assert g._compact_ok[0] is spec2._probe_token


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="the 2% val-MAE drift pin is calibrated against current jax "
           "numerics (CI): training is chaotically sensitive to the "
           "expander's <=1-ulp jnp.exp-vs-np.exp edge difference, and "
           "on jax 0.4.37 the 3-epoch trajectory lands at ~3.2% (train "
           "losses still agree to 4 digits; exact pack/geometry parity "
           "is pinned by the tests above, which run everywhere)",
)
def test_fit_compact_matches_full(graphs):
    """Single-bucket scan training: compact staging must produce the same
    trajectory as full staging up to edge-feature roundoff."""
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import (
        Normalizer,
        create_train_state,
        make_optimizer,
    )
    from cgnn_tpu.train.loop import fit

    train_g, val_g = graphs[:64], graphs[64:]
    spec = CompactSpec.build(train_g + val_g, CFG.gdf(), dense_m=12)
    results = {}
    for mode in ("full", "compact"):
        model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=32,
                                    dense_m=12)
        tx = make_optimizer(optim="adam", lr=0.01, lr_milestones=[10**9])
        norm = Normalizer.fit(np.stack([g.target for g in train_g]))
        nc, ec = capacities_for(train_g, 16, dense_m=12, snug=True)
        example = pack_graphs(train_g[:4], nc, ec, 16, dense_m=12)
        state = create_train_state(model, example, tx, norm,
                                   rng=jax.random.key(0))
        _, res = fit(
            state, train_g, val_g, epochs=3, batch_size=16,
            node_cap=nc, edge_cap=ec, seed=0, print_freq=0,
            scan_epochs=True, snug=True, dense_m=12,
            compact=spec if mode == "compact" else None,
        )
        results[mode] = [h["val"]["mae"] for h in res["history"]]
    # the ~1-ulp jnp.exp/np.exp edge-feature difference is amplified by
    # training dynamics across epochs; trajectories track within ~1%
    np.testing.assert_allclose(results["compact"], results["full"],
                               rtol=2e-2)
