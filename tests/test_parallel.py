"""Data-parallel tests on the 8-virtual-device CPU mesh (SURVEY.md §4.5).

The fake-NCCL analog: assert the shard_map DP step reproduces the
single-device step exactly when every device sees the same batch, and that
eval padding batches contribute nothing.
"""

import numpy as np
import pytest

import jax

from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.data.graph import pack_graphs
from cgnn_tpu.models import CrystalGraphConvNet
from cgnn_tpu.parallel import (
    empty_batch_like,
    make_parallel_eval_step,
    make_parallel_train_step,
    parallel_batches,
    replicate_state,
    shard_leading_axis,
    stack_batches,
)
from cgnn_tpu.parallel.mesh import make_mesh
from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
from cgnn_tpu.train.step import make_eval_step, make_train_step

N_DEV = 8


# function scope: the DP train step donates its (replicated) state, and
# replication aliases the device-0 shard — a module-scoped state would be
# deleted for later tests
@pytest.fixture()
def setup():
    assert len(jax.devices()) >= N_DEV, "conftest must provide 8 CPU devices"
    graphs = load_synthetic(16, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=9, max_atoms=6)
    node_cap, edge_cap = 96, 768
    batch = pack_graphs(graphs[:4], node_cap, edge_cap, 4)
    model = CrystalGraphConvNet(atom_fea_len=12, n_conv=2, h_fea_len=16)
    tx = make_optimizer(optim="sgd", lr=0.05)
    normalizer = Normalizer.fit(np.stack([g.target for g in graphs]))
    state = create_train_state(model, batch, tx, normalizer)
    return graphs, batch, model, state, (node_cap, edge_cap)


class TestDataParallel:
    def test_replicated_batch_matches_single_device(self, setup):
        """Same batch on all 8 devices -> pmean(grads)==grads, so the DP
        step must equal the single-device step; metric sums are 8x."""
        graphs, batch, model, state, _ = setup
        mesh = make_mesh(N_DEV)

        single_step = jax.jit(make_train_step())  # no donation: reuse state
        s_single, m_single = single_step(state, batch)

        dp_step = make_parallel_train_step(mesh)
        stacked = stack_batches([batch] * N_DEV)
        s_dp, m_dp = dp_step(
            replicate_state(state, mesh), shard_leading_axis(stacked, mesh)
        )

        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
            jax.device_get(s_dp.params), jax.device_get(s_single.params),
        )
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7),
            jax.device_get(s_dp.batch_stats), jax.device_get(s_single.batch_stats),
        )
        np.testing.assert_allclose(
            float(m_dp["loss_sum"]), N_DEV * float(m_single["loss_sum"]),
            rtol=1e-6,
        )
        np.testing.assert_allclose(float(m_dp["count"]), N_DEV * 4.0)

    def test_eval_padding_contributes_zero(self, setup):
        graphs, batch, model, state, _ = setup
        mesh = make_mesh(N_DEV)
        eval_single = jax.jit(make_eval_step())
        m_single = jax.device_get(eval_single(state, batch))

        # one real batch + 7 empty padding batches
        stacked = stack_batches([batch] + [empty_batch_like(batch)] * (N_DEV - 1))
        dp_eval = make_parallel_eval_step(mesh)
        m_dp = jax.device_get(
            dp_eval(replicate_state(state, mesh), shard_leading_axis(stacked, mesh))
        )
        for k in m_single:
            np.testing.assert_allclose(
                float(m_dp[k]), float(m_single[k]), rtol=1e-6, atol=1e-8
            )

    def test_parallel_batches_grouping(self, setup):
        graphs, _, _, _, (node_cap, edge_cap) = setup
        stacked_list = list(
            parallel_batches(graphs, 4, 2, node_cap, edge_cap, pad_incomplete=True)
        )
        assert all(s.nodes.shape[0] == 4 for s in stacked_list)
        total_real = sum(float(np.sum(s.graph_mask)) for s in stacked_list)
        assert total_real == len(graphs)
        # without padding, incomplete trailing groups are dropped
        stacked_drop = list(parallel_batches(graphs, 5, 2, node_cap, edge_cap))
        assert all(s.nodes.shape[0] == 5 for s in stacked_drop)

    def test_hierarchical_dcn_mesh_matches_flat_dp(self, setup):
        """A multi-host-style ('dcn', 'data') 2x4 mesh must produce exactly
        the same step as a flat 8-device ('data',) mesh: the reductions span
        both axes, XLA just routes them over different fabrics."""
        import jax.tree_util as jtu
        from jax.sharding import Mesh

        graphs, batch, model, state, (node_cap, edge_cap) = setup
        state2 = create_train_state(
            model, batch, state.tx,
            Normalizer.fit(np.stack([g.target for g in graphs])),
        )
        stacked = next(
            parallel_batches(graphs, 8, 2, node_cap, edge_cap)
        )

        mesh_flat = make_mesh(N_DEV)
        s1, m1 = make_parallel_train_step(mesh_flat)(
            replicate_state(state, mesh_flat),
            shard_leading_axis(stacked, mesh_flat),
        )

        mesh_dcn = Mesh(
            np.array(jax.devices()[:8]).reshape(2, 4), ("dcn", "data")
        )
        s2, m2 = make_parallel_train_step(mesh_dcn)(
            replicate_state(state2, mesh_dcn),
            shard_leading_axis(stacked, mesh_dcn),
        )
        m1, m2 = jax.device_get((m1, m2))
        assert float(m1["loss_sum"]) == pytest.approx(
            float(m2["loss_sum"]), rel=1e-6)
        for a, b in zip(
            jtu.tree_leaves(jax.device_get(s1.params)),
            jtu.tree_leaves(jax.device_get(s2.params)),
        ):
            np.testing.assert_allclose(a, b, atol=1e-6)

    def test_fit_dp_device_resident_matches_streaming(self, setup):
        """DP fit with pack_once/device_resident: first epoch identical to
        the streaming path (same seed), later epochs keep training."""
        from cgnn_tpu.parallel import fit_data_parallel

        graphs, batch, model, state, (node_cap, edge_cap) = setup
        quiet = lambda *a, **k: None  # noqa: E731

        def run(**kw):
            s = create_train_state(
                model, batch, state.tx,
                Normalizer.fit(np.stack([g.target for g in graphs])),
            )
            _, result = fit_data_parallel(
                s, graphs, graphs[:8], epochs=2, batch_size=2,
                node_cap=node_cap, edge_cap=edge_cap, seed=5,
                mesh=make_mesh(4), log_fn=quiet, **kw,
            )
            return result["history"]

        h_stream = run()
        h_dr = run(device_resident=True)
        assert h_dr[0]["train_loss"] == pytest.approx(
            h_stream[0]["train_loss"], rel=1e-6)
        assert h_dr[0]["val"]["mae"] == pytest.approx(
            h_stream[0]["val"]["mae"], rel=1e-6)
        assert np.isfinite(h_dr[1]["train_loss"])

    def test_sharded_train_progresses(self, setup):
        """Distinct per-device batches: loss goes down over DP steps."""
        graphs, batch, model, state, (node_cap, edge_cap) = setup
        mesh = make_mesh(N_DEV)
        dp_step = make_parallel_train_step(mesh)
        state = replicate_state(state, mesh)
        losses = []
        for _ in range(6):
            for stacked in parallel_batches(
                graphs, N_DEV, 2, node_cap, edge_cap, pad_incomplete=False,
                shuffle=True, rng=np.random.default_rng(0),
            ):
                state, m = dp_step(state, shard_leading_axis(stacked, mesh))
                m = jax.device_get(m)
                losses.append(float(m["loss_sum"]) / max(float(m["count"]), 1))
        assert losses[-1] < losses[0]


class TestDPFeatureParity:
    """VERDICT r2 #3: buckets / snug / scan_epochs inside the DP loop."""

    def _dense_setup(self, graphs):
        from cgnn_tpu.data.graph import bucketed_batch_iterator

        dense_model = CrystalGraphConvNet(
            atom_fea_len=12, n_conv=2, h_fea_len=16, dense_m=8
        )
        eb = next(iter(bucketed_batch_iterator(
            graphs, 2, 2, dense_m=8, snug=True
        )))
        tx = make_optimizer(optim="sgd", lr=0.05)

        def fresh():
            return create_train_state(
                dense_model, eb, tx,
                Normalizer.fit(np.stack([g.target for g in graphs])),
            )

        return fresh

    def test_fit_dp_bucketed_snug_trains(self, setup):
        from cgnn_tpu.parallel import fit_data_parallel

        graphs, *_ = setup
        fresh = self._dense_setup(graphs)
        quiet = lambda *a, **k: None  # noqa: E731
        _, result = fit_data_parallel(
            fresh(), graphs, graphs[:8], epochs=6, batch_size=2,
            node_cap=0, edge_cap=0, seed=5, mesh=make_mesh(4), log_fn=quiet,
            buckets=2, snug=True, dense_m=8,
        )
        h = result["history"]
        assert np.isfinite(h[-1]["train_loss"])
        assert h[-1]["train_loss"] < h[0]["train_loss"]

    def test_fit_dp_scan_epochs_matches_per_step(self, setup):
        """First epoch of DP scan_epochs == per-step DP (same seed/batches,
        single shape group so the orders coincide): the scan folds
        dispatches, not math. Multi-bucket scan ordering is chunk-granular
        by design (ScanEpochDriver docstring), so exact parity is a
        single-shape property."""
        from cgnn_tpu.data.graph import capacities_for
        from cgnn_tpu.parallel import fit_data_parallel

        graphs, *_ = setup
        fresh = self._dense_setup(graphs)
        quiet = lambda *a, **k: None  # noqa: E731
        nc, ec = capacities_for(graphs, 2, dense_m=8, snug=True)

        def run(**kw):
            _, result = fit_data_parallel(
                fresh(), graphs, graphs[:8], epochs=2, batch_size=2,
                node_cap=nc, edge_cap=ec, seed=5, mesh=make_mesh(4),
                log_fn=quiet, snug=True, dense_m=8, **kw,
            )
            return result["history"]

        h_step = run(device_resident=True)
        h_scan = run(scan_epochs=True)
        assert h_scan[0]["train_loss"] == pytest.approx(
            h_step[0]["train_loss"], rel=1e-5)
        assert h_scan[0]["val"]["mae"] == pytest.approx(
            h_step[0]["val"]["mae"], rel=1e-5)
        assert np.isfinite(h_scan[1]["train_loss"])

    def test_graph_shards_reject_unsupported_flags(self, setup):
        """Scan-epochs composes with graph shards since r5; per-step
        profiling remains the one composition the scan cannot provide."""
        from cgnn_tpu.parallel import fit_data_parallel
        from cgnn_tpu.parallel.mesh import make_2d_mesh

        graphs, batch, model, state, (node_cap, edge_cap) = setup
        with pytest.raises(NotImplementedError, match="profile"):
            fit_data_parallel(
                state, graphs, graphs[:8], epochs=1, batch_size=2,
                node_cap=node_cap, edge_cap=edge_cap,
                mesh=make_2d_mesh(2, data_shards=2), profile_steps=4,
            )
