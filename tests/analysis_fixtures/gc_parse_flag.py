# MUST-FLAG: GC-PARSE — an unparseable file is a finding, never a
# silent skip (graftcheck cannot vouch for invariants it cannot see).
def broken(:
    pass
