"""MUST-FLAG GC-ALIAS: unaudited device_get + device_put(x, x.sharding)."""
import jax


def save_state(state, path):
    host = jax.device_get(state)  # aliases device buffers on CPU
    write(path, host)


def warm(x):
    return jax.device_put(x, x.sharding)
