"""MUST-FLAG GC-LOCKSHARE: the PR-6 scrape-bug shape."""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def add(self, n):
        with self._lock:
            self.count += n

    def snapshot(self):
        return {"count": self.count}
