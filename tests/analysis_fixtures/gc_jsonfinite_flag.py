"""MUST-FLAG GC-JSONFINITE: float payload with no non-finite guard."""
import json


def write_metrics(path, payload):
    with open(path, "w") as f:
        json.dump(payload, f)
