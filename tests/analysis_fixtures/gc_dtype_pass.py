"""MUST-PASS GC-DTYPE: explicit f32 in jit; dtype-less numpy on host."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step(x):
    bias = np.zeros(4, dtype=np.float32)
    scale = np.ones(4, np.float32)  # positional dtype counts too
    return jnp.ones(x.shape) + x + bias * scale


def host_setup():
    # host-side staging: dtype-less numpy never reaches traced code here
    return np.zeros(8)
