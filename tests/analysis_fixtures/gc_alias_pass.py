"""MUST-PASS GC-ALIAS: copy barriers, bare fences, copy-then-place."""
import jax
import jax.numpy as jnp
import numpy as np


def save_state(state, path):
    host = jax.tree_util.tree_map(np.array, jax.device_get(state))
    write(path, host)


def fetch_scalar(x):
    return float(jax.device_get(x))


def fence(x):
    jax.device_get(x)


def warm(x):
    return jax.device_put(jnp.array(x), x.sharding)
