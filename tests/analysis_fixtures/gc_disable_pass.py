"""MUST-PASS GC-DISABLE: a justified disable silences its rule."""
import jax


def snapshot(state):
    # graftcheck: disable=GC-ALIAS -- audited: consumed read-only and
    # fully drained before the next donated dispatch can touch buffers
    return jax.device_get(state)
