"""MUST-FLAG GC-THREADNAME: anonymous Thread-5 is undebuggable."""
import threading


def start(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()
    return t
