"""MUST-FLAG GC-DISABLE: escape hatches without the required why."""
import jax
import numpy as np


def snapshot(state):
    return jax.device_get(state)  # graftcheck: disable=GC-ALIAS


def other(state):
    # graftcheck: disable=GC-BOGUS -- names a rule that does not exist
    return np.array(jax.device_get(state))
