"""MUST-PASS GC-HOSTCALL: host prints outside traced code are fine."""
import jax


@jax.jit
def train_step(x):
    return x * 2


def host_loop(xs):
    for x in xs:
        print(train_step(x))
