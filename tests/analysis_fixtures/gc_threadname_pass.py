"""MUST-PASS GC-THREADNAME: stable attributable thread names."""
import threading


def start(fn, i):
    t = threading.Thread(target=fn, daemon=True,
                         name=f"serve-dispatch-{i}")
    t.start()
    return t
