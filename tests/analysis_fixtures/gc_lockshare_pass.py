"""MUST-PASS GC-LOCKSHARE: every access under the lock (or *_locked)."""
import threading


class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def add(self, n):
        with self._lock:
            self.count += n

    def snapshot(self):
        with self._lock:
            return {"count": self.count}

    def merge_locked(self, other):
        self.count += other
