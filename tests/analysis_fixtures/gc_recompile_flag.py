"""MUST-FLAG GC-RECOMPILE: data-dependent shape + scalar traced arg."""
import jax
import jax.numpy as jnp


@jax.jit
def gather_active(mask):
    return jnp.nonzero(mask)


@jax.jit
def scale(x, k):
    return x * k


def caller(x):
    return scale(x, 0.5)
