"""MUST-PASS GC-THREAD: the loader contract — stop-event bounded loop."""
import queue
import threading


def worker(q, stop):
    while True:
        if stop.is_set():
            return
        try:
            item = q.get(timeout=0.1)
        except queue.Empty:
            continue
        handle(item)


def start(q, stop):
    t = threading.Thread(target=worker, args=(q, stop), daemon=True,
                         name="pool-worker-0")
    t.start()
    return t
