"""MUST-PASS GC-BLOCKING: block outside, publish under the lock."""
import threading


class Fetcher:
    def __init__(self, q):
        self._lock = threading.Lock()
        self._q = q
        self.last = None

    def fetch(self):
        item = self._q.get(timeout=1.0)
        with self._lock:
            self.last = item
        return item
