"""MUST-PASS GC-JSONFINITE: jsonfinite() wrap or allow_nan=False."""
import json

from cgnn_tpu.observe.metrics_io import jsonfinite


def write_metrics(path, payload):
    with open(path, "w") as f:
        json.dump(jsonfinite(payload), f)


def write_strict(path, payload):
    with open(path, "w") as f:
        json.dump(payload, f, allow_nan=False)
