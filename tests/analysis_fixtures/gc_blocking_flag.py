"""MUST-FLAG GC-BLOCKING: a zero-timeout queue.get under the lock."""
import threading


class Fetcher:
    def __init__(self, q):
        self._lock = threading.Lock()
        self._q = q
        self.last = None

    def fetch(self):
        with self._lock:
            item = self._q.get()
            self.last = item
        return item
