"""MUST-FLAG GC-THREAD: worker loop with no stop-event/sentinel exit."""
import threading


def worker(q):
    while True:
        item = q.get(timeout=0.1)
        handle(item)


def start(q):
    t = threading.Thread(target=worker, args=(q,), daemon=True,
                         name="pool-worker-0")
    t.start()
    return t
