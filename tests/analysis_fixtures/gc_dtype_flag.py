"""MUST-FLAG GC-DTYPE: f64 creep inside jitted bodies, three shapes."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def step_explicit(x):
    return x.astype(np.float64)


@jax.jit
def step_string(x):
    return jnp.zeros(x.shape, dtype="float64") + x


@jax.jit
def step_default(x):
    return x + np.ones(4)
