"""MUST-FLAG GC-HOSTCALL: callback outside the tap + print in a jit."""
import jax


def step(state, batch):
    jax.debug.callback(emit, batch)
    return state


@jax.jit
def train_step(x):
    print("tracing", x)
    return x * 2
