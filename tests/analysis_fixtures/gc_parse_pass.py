# MUST-PASS: GC-PARSE — a file that parses produces no parse finding.
def fine():
    return 1
