"""MUST-PASS GC-RECOMPILE: fixed shapes; scalars declared static."""
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit
def masked_sum(x, mask):
    return jnp.where(mask, x, 0.0).sum()


@partial(jax.jit, static_argnums=(1,))
def scale(x, k):
    return x * k


def caller(x):
    return scale(x, 2)
