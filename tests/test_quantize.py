"""Quantized serving programs (serve/quantize.py, ISSUE 9 tentpole B).

The contract under test: int8-weight / bf16-activation serving programs
are a PRECISION dial, not an accuracy cliff — prediction MAE on the
cached synthetic set may drift at most 0.5% relative vs the f32 program
(the MAE_PARITY posture, applied to serving tiers), tier states share
the native checkpoint (no retraining, hot-swap safe), and every tier is
a warm program (zero post-warmup recompiles — pinned on the serving side
in tests/test_serve.py TestPrecisionServing).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cgnn_tpu.config import DataConfig, ModelConfig, build_model
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.serve.quantize import (
    TIERS,
    QuantizedKernel,
    build_tier_specs,
    dequantize_params,
    quantize_kernel,
    quantize_params,
)
from cgnn_tpu.serve.shapes import plan_shape_set
from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
from cgnn_tpu.train.step import make_predict_step

CFG = FeaturizeConfig(radius=5.0, max_num_nbr=8)


@pytest.fixture(scope="module")
def graphs():
    return load_synthetic(96, CFG, seed=3, max_atoms=8)


@pytest.fixture(scope="module")
def trained(graphs):
    """A briefly-TRAINED model (not a random init: quantization error on
    random weights says nothing about the served operating point)."""
    from cgnn_tpu.data.graph import capacities_for
    from cgnn_tpu.train.loop import fit

    model_cfg = ModelConfig(atom_fea_len=16, n_conv=2, h_fea_len=24)
    model = build_model(model_cfg, DataConfig(radius=5.0, max_num_nbr=8))
    train_g = graphs[:64]
    nc, ec = capacities_for(train_g, 16)
    from cgnn_tpu.data.graph import batch_iterator

    example = next(batch_iterator(train_g, 16, nc, ec))
    state = create_train_state(
        model, example, make_optimizer(optim="adam", lr=0.01),
        Normalizer.fit(np.stack([g.target for g in train_g])),
        rng=jax.random.key(0),
    )
    state, _ = fit(state, train_g, graphs[64:80], epochs=4, batch_size=16,
                   node_cap=nc, edge_cap=ec, seed=0, print_freq=0,
                   log_fn=lambda *a, **k: None)
    return model, state


class TestQuantizeCore:
    @staticmethod
    def _deq(qk):
        return np.asarray(dequantize_params({"x": {"kernel": qk}})["x"]
                          ["kernel"])

    def test_kernel_roundtrip_error_bounded(self):
        rng = np.random.default_rng(0)
        w = rng.normal(0, 0.2, size=(70, 48)).astype(np.float32)  # ragged
        qk = quantize_kernel(w)
        assert np.asarray(qk.q).dtype == np.int8
        deq = self._deq(qk)
        assert deq.shape == w.shape  # block padding undone
        # blocked symmetric: per-element error bounded by its block's
        # scale/2
        scale = np.asarray(qk.scale)
        blocks = np.repeat(scale, 32, axis=0)[: w.shape[0]]
        assert (np.abs(deq - w) <= blocks / 2 + 1e-7).all()

    def test_zero_column_kernel_safe(self):
        w = np.zeros((8, 4), np.float32)
        qk = quantize_kernel(w)
        np.testing.assert_array_equal(self._deq(qk), w)

    def test_quantize_params_targets_kernels_only(self, trained):
        _, state = trained
        q = quantize_params(state.params)
        leaves = jax.tree_util.tree_leaves_with_path(
            q, is_leaf=lambda x: isinstance(x, QuantizedKernel)
        )
        n_q = sum(isinstance(v, QuantizedKernel) for _, v in leaves)
        # the conv fc_full kernels (the HBM payload) quantize; the
        # embedding and output head stay full precision by policy
        n_expected = sum(
            1 for p, v in jax.tree_util.tree_leaves_with_path(state.params)
            if getattr(p[-1], "key", None) == "kernel"
            and np.ndim(v) == 2 and np.shape(v)[1] > 8
            and not any(getattr(k, "key", None) in ("embedding", "fc_out")
                        for k in p)
        )
        assert n_q == n_expected and n_q > 0
        q_names = {jax.tree_util.keystr(p) for p, v in leaves
                   if isinstance(v, QuantizedKernel)}
        assert not any("embedding" in n or "fc_out" in n for n in q_names)
        assert any("fc_full" in n for n in q_names)
        # every non-kernel leaf is untouched (bit-identical)
        for path, v in leaves:
            if not isinstance(v, QuantizedKernel):
                ref = state.params
                for k in path:
                    ref = ref[k.key]
                np.testing.assert_array_equal(np.asarray(v),
                                              np.asarray(ref))

    def test_dequantize_restores_structure(self, trained):
        _, state = trained
        deq = dequantize_params(quantize_params(state.params), jnp.bfloat16)
        ref_paths = [jax.tree_util.keystr(p) for p, _ in
                     jax.tree_util.tree_leaves_with_path(state.params)]
        got_paths = [jax.tree_util.keystr(p) for p, _ in
                     jax.tree_util.tree_leaves_with_path(deq)]
        assert sorted(ref_paths) == sorted(got_paths)

    def test_unknown_tier_rejected(self, trained):
        model, _ = trained
        with pytest.raises(ValueError, match="unknown precision"):
            build_tier_specs(model, ("f32", "fp4"))


class TestTierPrograms:
    """One ladder rung, all three programs: the satellite-4 tier-1 gate
    — prediction MAE ratio vs f32 <= 1.005 on the cached synthetic set."""

    @pytest.fixture(scope="class")
    def tier_maes(self, graphs, trained):
        model, state = trained
        eval_g = graphs[80:]
        ladder = plan_shape_set(graphs, 16, rungs=1)
        specs = build_tier_specs(model, TIERS)
        pstep = jax.jit(make_predict_step())
        batch = ladder.pack(eval_g[:16])
        targets = np.stack([np.atleast_1d(g.target) for g in eval_g[:16]])
        maes = {}
        preds = {}
        for tier in TIERS:
            st = specs[tier].state_for(state)
            out = np.array(jax.device_get(pstep(st, batch)))[:16]
            preds[tier] = out
            maes[tier] = float(np.abs(out - targets).mean())
        return maes, preds

    def test_mae_ratio_within_half_percent(self, tier_maes):
        maes, _ = tier_maes
        assert maes["f32"] > 0
        for tier in ("bf16", "int8"):
            ratio = maes[tier] / maes["f32"]
            assert ratio <= 1.005, (
                f"{tier} prediction MAE ratio {ratio:.4f} exceeds the "
                f"0.5% drift gate (maes={maes})"
            )

    def test_tiers_actually_differ_from_f32(self, tier_maes):
        """Guard against a silently-ignored tier (a transform that
        returns the native program would pass the ratio gate vacuously)."""
        _, preds = tier_maes
        assert np.abs(preds["bf16"] - preds["f32"]).max() > 0
        assert np.abs(preds["int8"] - preds["bf16"]).max() > 0

    def test_specs_stable_identity(self, trained):
        """The apply_fn handed to the jit cache must be the SAME object
        for repeated state derivations (hot reload must not retrace)."""
        model, state = trained
        specs = build_tier_specs(model, TIERS)
        for tier in TIERS:
            a = specs[tier].state_for(state)
            b = specs[tier].state_for(state)
            assert a.apply_fn is b.apply_fn

    def test_int8_state_drops_opt_state(self, trained):
        model, state = trained
        specs = build_tier_specs(model, ("f32", "int8"))
        st = specs["int8"].state_for(state)
        assert st.opt_state == ()
        # int8 kernels really are int8 on the wire
        n_int8 = sum(
            np.asarray(v.q).dtype == np.int8
            for _, v in jax.tree_util.tree_leaves_with_path(
                st.params,
                is_leaf=lambda x: isinstance(x, QuantizedKernel))
            if isinstance(v, QuantizedKernel)
        )
        assert n_int8 > 0
