"""Closed-loop continual learning tests (cgnn_tpu.continual; ISSUE 18).

The load-bearing guarantees, pinned:

- the label journal joins late ground truth EXACTLY ONCE — per trace id
  (hedged/retried requests share one), across duplicate POSTs, and
  across a process restart replaying the same stream;
- the canary gate is a pure decision core: promote / hold / rollback
  are deterministic functions of injected clock + samples, latency
  breaches out-rank MAE, and an undecided window is never promotable;
- the reload watcher's gate holds fleet replicas at the approved
  version while a trainer commits candidates into the SAME directory,
  and a pin overrides everything (including downgrades — the rollback
  path);
- a canary rollback dumps a flight-recorder bundle NAMING the
  regressing version, and the rejected candidate is never re-evaluated;
- per-version labeled histogram families render under one family
  declaration and merge label-set by label-set;
- training while serving holds the lock discipline (racecheck clean).
"""

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.continual import (
    CanaryController,
    CanaryGate,
    ContinualTrainer,
    GateConfig,
    GateStats,
    JournalTail,
    LabelJournal,
)
from cgnn_tpu.continual.journal import iter_labeled_graphs
from cgnn_tpu.observe import flightrec
from cgnn_tpu.observe.export import MetricsRegistry, parse_prometheus_text
from cgnn_tpu.observe.hist import merge_snapshot_maps


# ---------------------------------------------------------------- journal


def _serve(j, tid, pred=1.0, fp=None, payload=None, version="ckpt-00000001"):
    j.note_served(trace_id=tid, payload=payload, prediction=pred,
                  param_version=version, fingerprint=fp, ts=123.0)


class TestLabelJournal:
    def test_round_trip_and_exactly_once(self):
        j = LabelJournal()
        _serve(j, "t1", pred=2.0)
        assert j.join(2.5, trace_id="t1") == "joined"
        recs = j.labeled_records()
        assert len(recs) == 1
        rec = recs[0]
        assert rec["trace_id"] == "t1"
        assert rec["prediction"] == 2.0 and rec["label"] == 2.5
        assert rec["param_version"] == "ckpt-00000001"
        assert rec["join_seq"] == 1 == j.join_seq
        # a retransmitted label is acknowledged, never applied: the
        # stored value is immutable and the duplicate is counted
        assert j.join(9.9, trace_id="t1") == "already"
        assert j.labeled_records()[0]["label"] == 2.5
        s = j.stats()
        assert s["joined"] == 1 and s["duplicate_joins"] == 1

    def test_hedged_retry_shares_one_record(self):
        # hedged/retried attempts re-report under the SAME trace id:
        # the journal keeps one record, so one label joins exactly once
        j = LabelJournal()
        _serve(j, "t1", pred=1.0)
        _serve(j, "t1", pred=1.0)  # the hedge's duplicate report
        assert j.stats()["served"] == 1
        assert j.join(1.5, trace_id="t1") == "joined"
        assert j.join(1.5, trace_id="t1") == "already"
        assert j.stats()["joined"] == 1

    def test_fingerprint_join_lands_oldest_unlabeled(self):
        j = LabelJournal()
        _serve(j, "t1", fp="fp-a")
        _serve(j, "t2", fp="fp-a")
        assert j.join(1.0, fingerprint="fp-a") == "joined"
        assert j.labeled_records()[0]["trace_id"] == "t1"
        assert j.join(2.0, fingerprint="fp-a") == "joined"
        assert {r["trace_id"] for r in j.labeled_records()} == {"t1", "t2"}
        # all records for the print labeled: the next one is a duplicate
        assert j.join(3.0, fingerprint="fp-a") == "already"

    def test_unmatched_label(self):
        j = LabelJournal()
        assert j.join(1.0, trace_id="nope") == "unmatched"
        assert j.stats()["unmatched_labels"] == 1
        with pytest.raises(ValueError):
            j.join(1.0)

    def test_capacity_eviction(self):
        j = LabelJournal(capacity=2)
        for i in range(3):
            _serve(j, f"t{i}", fp=f"fp{i}")
        s = j.stats()
        assert s["evicted"] == 1 and s["resident"] == 2
        # the evicted record (and its fingerprint index entry) is gone
        assert j.join(1.0, trace_id="t0") == "unmatched"
        assert j.join(1.0, fingerprint="fp0") == "unmatched"
        assert j.join(1.0, trace_id="t2") == "joined"

    def test_labeled_records_after_seq(self):
        j = LabelJournal()
        for i in range(4):
            _serve(j, f"t{i}")
        for i in range(3):
            j.join(float(i), trace_id=f"t{i}")
        assert [r["trace_id"] for r in j.labeled_records(after_seq=1)] == [
            "t1", "t2"]

    def test_replay_preserves_exactly_once(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        j = LabelJournal(path)
        _serve(j, "t1", pred=1.0)
        _serve(j, "t2", pred=2.0)
        assert j.join(1.5, trace_id="t1") == "joined"
        assert j.join(1.5, trace_id="t1") == "already"
        j.close()
        # restart: rebuild from the stream through the SAME apply path
        j2 = LabelJournal.replay(path)
        assert j2.stats()["served"] == 2 and j2.stats()["joined"] == 1
        assert j2.labeled_records()[0]["label"] == 1.5
        # the replayed duplicate did not double-apply, and a NEW
        # retransmission still answers 'already'
        assert j2.join(9.0, trace_id="t1") == "already"
        assert j2.join(2.5, trace_id="t2") == "joined"

    def test_tail_survives_rotation(self, tmp_path):
        # writer rotates mid-stream (several times); a tail polling
        # faster than the rotation cadence must deliver every line
        # exactly once across each os.replace
        path = str(tmp_path / "rot.jsonl")
        writer = LabelJournal(path, max_bytes=2048)
        tail = JournalTail(path)
        follower = LabelJournal()
        n = 40
        for k in range(n):
            _serve(writer, f"t{k}", pred=float(k))
            writer.join(float(k) + 0.5, trace_id=f"t{k}")
            tail.follow_into(follower)
        tail.follow_into(follower)
        assert os.path.exists(path + ".1")  # rotation actually happened
        ws, fs = writer.stats(), follower.stats()
        assert fs["served"] == ws["served"] == n
        assert fs["joined"] == ws["joined"] == n
        assert fs["duplicate_joins"] == 0
        writer.close()
        tail.close()

    def test_iter_labeled_graphs_round_trip(self):
        from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic

        g = load_synthetic(1, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                           seed=3, max_atoms=8)[0]
        payload = {"graph": {
            "atom_fea": np.asarray(g.atom_fea).tolist(),
            "edge_fea": np.asarray(g.edge_fea).tolist(),
            "centers": np.asarray(g.centers).tolist(),
            "neighbors": np.asarray(g.neighbors).tolist(),
            "id": g.cif_id,
        }}
        j = LabelJournal()
        _serve(j, "t1", payload=payload)
        _serve(j, "t2", payload=None)        # accounting-only: skipped
        _serve(j, "t3", payload={"structure": {}})  # raw wire: skipped
        for t in ("t1", "t2", "t3"):
            j.join(7.25, trace_id=t)
        out = list(iter_labeled_graphs(j.labeled_records()))
        assert len(out) == 1
        g2, rec = out[0]
        assert rec["trace_id"] == "t1"
        # the replayed graph carries the TRUE target, not the prediction
        np.testing.assert_allclose(g2.target, [7.25])
        np.testing.assert_allclose(g2.atom_fea, g.atom_fea)
        np.testing.assert_array_equal(g2.neighbors, g.neighbors)


# ------------------------------------------------------------------ gate


def _stats(cand_n=100, cand_mae=1.0, cand_p99=10.0, base_n=100,
           base_mae=1.0):
    return GateStats(candidate_count=cand_n, candidate_mae=cand_mae,
                     candidate_p99_ms=cand_p99, baseline_count=base_n,
                     baseline_mae=base_mae)


class TestCanaryGate:
    CFG = GateConfig(min_samples=10, min_baseline=10, max_mae_ratio=1.05,
                     rollback_mae_ratio=1.25, p99_budget_ms=100.0,
                     min_window_s=2.0, max_window_s=60.0)

    def test_config_validation(self):
        with pytest.raises(ValueError):
            GateConfig(max_mae_ratio=1.3, rollback_mae_ratio=1.2)
        with pytest.raises(ValueError):
            GateConfig(min_samples=0)
        with pytest.raises(ValueError):
            GateConfig(min_window_s=10.0, max_window_s=5.0)

    def test_promote_within_ratio(self):
        g = CanaryGate(self.CFG)
        g.begin("ckpt-00000002", now=0.0)
        assert g.active == "ckpt-00000002"
        d = g.poll(3.0, _stats(cand_mae=1.02, base_mae=1.0))
        assert d.action == "promote" and d.reason == "ok"
        assert d.version == "ckpt-00000002"
        assert d.mae_ratio == pytest.approx(1.02)
        assert g.active is None  # one decision per window

    def test_holds_before_min_samples_and_min_window(self):
        g = CanaryGate(self.CFG)
        g.begin("v", now=0.0)
        # starved of shadow samples: hold
        assert g.poll(3.0, _stats(cand_n=5)) is None
        # starved of baseline: hold
        assert g.poll(3.0, _stats(base_n=5)) is None
        # inside min_window even with samples: hold (no verdict faster
        # than the floor, however good it looks)
        assert g.poll(1.0, _stats(cand_mae=0.5)) is None
        assert g.active == "v"

    def test_rollback_on_mae_ratio(self):
        g = CanaryGate(self.CFG)
        g.begin("v", now=0.0)
        d = g.poll(3.0, _stats(cand_mae=1.5, base_mae=1.0))
        assert d.action == "rollback" and d.reason == "mae"

    def test_latency_outranks_good_mae(self):
        g = CanaryGate(self.CFG)
        g.begin("v", now=0.0)
        d = g.poll(3.0, _stats(cand_mae=0.5, cand_p99=250.0))
        assert d.action == "rollback" and d.reason == "latency"

    def test_inconclusive_band_holds_then_window_expires(self):
        g = CanaryGate(self.CFG)
        g.begin("v", now=0.0)
        mid = _stats(cand_mae=1.15, base_mae=1.0)  # between 1.05 and 1.25
        assert g.poll(3.0, mid) is None
        assert g.poll(30.0, mid) is None
        d = g.poll(60.0, mid)
        assert d.action == "rollback" and d.reason == "window_expired"

    def test_starved_window_expires_to_rollback(self):
        # undecided is NOT promotable: no samples ever -> rollback
        g = CanaryGate(self.CFG)
        g.begin("v", now=0.0)
        d = g.poll(61.0, _stats(cand_n=0, base_n=0,
                                cand_mae=float("nan"),
                                base_mae=float("nan")))
        assert d.action == "rollback" and d.reason == "window_expired"

    def test_one_candidate_at_a_time(self):
        g = CanaryGate(self.CFG)
        g.begin("v1", now=0.0)
        with pytest.raises(RuntimeError):
            g.begin("v2", now=0.0)


# ---------------------------------------------------- watcher pin / gate
# (tiny real checkpoint dir + ParamStore: the satellite-b regression —
# a gated watcher must NOT auto-swap to an unevaluated trainer commit)


@pytest.fixture(scope="module")
def watch_parts():
    import jax

    from cgnn_tpu.config import DataConfig, ModelConfig, build_model
    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
    from cgnn_tpu.serve import plan_shape_set
    from cgnn_tpu.train import (
        Normalizer,
        create_train_state,
        make_optimizer,
    )

    graphs = load_synthetic(16, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=11, max_atoms=8)
    ss = plan_shape_set(graphs, 8, rungs=1)
    model_cfg = ModelConfig(atom_fea_len=8, n_conv=1, h_fea_len=16)
    model = build_model(model_cfg, DataConfig(radius=5.0, max_num_nbr=8))
    state = create_train_state(
        model, ss.pack([graphs[0]]), make_optimizer(),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(7),
    )
    return model_cfg, state


def _commit(mgr, state, model_cfg, nudge=0.0):
    import jax

    from cgnn_tpu.config import DataConfig

    params = state.params
    if nudge:
        params = jax.tree_util.tree_map(
            lambda x: (np.asarray(x) + nudge).astype(np.asarray(x).dtype)
            if np.issubdtype(np.asarray(x).dtype, np.floating) else x,
            params,
        )
    mgr.save(state.replace(params=params),
             {"model": model_cfg.to_meta(),
              "data": DataConfig(radius=5.0, max_num_nbr=8).to_meta(),
              "task": "regression", "epoch": 0})
    mgr.wait()
    return mgr.newest_committed()


class TestWatcherPromotionGuard:
    def test_gate_holds_ungated_candidate(self, watch_parts, tmp_path):
        from cgnn_tpu.serve.reload import CheckpointWatcher, ParamStore
        from cgnn_tpu.train import CheckpointManager

        model_cfg, state = watch_parts
        mgr = CheckpointManager(str(tmp_path / "ckpt"),
                                log_fn=lambda m: None)
        v1 = _commit(mgr, state, model_cfg)
        store = ParamStore(state, v1)
        w = CheckpointWatcher(mgr, store, state, gate=v1,
                              log_fn=lambda m: None)
        # a continual trainer commits a CANDIDATE into the same dir:
        # the gated watcher must hold the line, not chase newest
        v2 = _commit(mgr, state, model_cfg, nudge=0.25)
        assert not w.poll_once()
        assert store.version == v1 and w.gate_holds == 1
        # the promotion broadcast raises the gate -> the swap happens
        w.set_gate(v2)
        assert w.poll_once()
        assert store.version == v2 and w.swaps == 1
        mgr.close()

    def test_gate_newer_than_current_converges_on_gate(self, watch_parts,
                                                       tmp_path):
        from cgnn_tpu.serve.reload import CheckpointWatcher, ParamStore
        from cgnn_tpu.train import CheckpointManager

        model_cfg, state = watch_parts
        mgr = CheckpointManager(str(tmp_path / "ckptg"),
                                log_fn=lambda m: None)
        v1 = _commit(mgr, state, model_cfg)
        v2 = _commit(mgr, state, model_cfg, nudge=0.25)
        v3 = _commit(mgr, state, model_cfg, nudge=0.5)
        store = ParamStore(state, v1)
        w = CheckpointWatcher(mgr, store, state, gate=v2,
                              log_fn=lambda m: None)
        # newest is v3 but the gate says v2: converge on the GATE —
        # the rolling-promotion step, never past the approved version
        assert w.poll_once()
        assert store.version == v2
        assert not w.poll_once()  # v3 still held
        assert store.version == v2 and w.gate_holds == 1
        assert mgr.newest_committed() == v3
        mgr.close()

    def test_pin_overrides_and_allows_downgrade(self, watch_parts,
                                                tmp_path):
        from cgnn_tpu.serve.reload import CheckpointWatcher, ParamStore
        from cgnn_tpu.train import CheckpointManager

        model_cfg, state = watch_parts
        mgr = CheckpointManager(str(tmp_path / "ckptp"),
                                log_fn=lambda m: None)
        v1 = _commit(mgr, state, model_cfg)
        v2 = _commit(mgr, state, model_cfg, nudge=0.25)
        store = ParamStore(state, v1)
        w = CheckpointWatcher(mgr, store, state, gate=v1,
                              log_fn=lambda m: None)
        # canary path: pin PAST the gate to the candidate
        w.set_pin(v2)
        assert w.poll_once() and store.version == v2
        # rollback path: pin DOWN to the fleet version
        w.set_pin(v1)
        assert w.poll_once() and store.version == v1
        # an uncommitted pin just retries (mid-commit candidate)
        w.set_pin("ckpt-99999999")
        assert not w.poll_once() and store.version == v1
        # clearing the pin resumes gate behaviour (gate v1 holds v2)
        w.set_pin(None)
        assert not w.poll_once() and store.version == v1
        ctl = w.control()
        assert ctl["pin"] is None and ctl["gate"] == v1
        assert ctl["version"] == v1
        mgr.close()


# ------------------------------------------------------------ controller


class FakeFleet:
    """Duck-typed fleet adapter: instant pin convergence, scripted
    shadow answers."""

    def __init__(self, fleet_v="ckpt-00000001", shadow_fn=None):
        self.fleet_v = fleet_v
        self.pinned = None          # what the canary replica serves
        self.shadow_fn = shadow_fn or (lambda payload: 1.1)
        self.shadow_latency_ms = 5.0
        self.calls = []

    def fleet_version(self):
        return self.fleet_v

    def begin_canary(self, version):
        self.calls.append(("begin", version))
        self.pinned = version
        return "r-canary"

    def canary_version(self, rid):
        return self.pinned

    def shadow_predict(self, rid, payload, timeout_s):
        self.calls.append(("shadow", rid))
        return self.shadow_fn(payload), self.shadow_latency_ms

    def promote(self, rid, version):
        self.calls.append(("promote", version))
        self.fleet_v = version
        self.pinned = None

    def abort_canary(self, rid, to_version):
        self.calls.append(("abort", to_version))
        self.pinned = to_version

    def end_canary(self, rid):
        self.calls.append(("end", rid))
        self.pinned = None


def _controller(journal, fleet, newest, tmp_path=None, **kw):
    gate = CanaryGate(GateConfig(
        min_samples=4, min_baseline=4, max_mae_ratio=1.05,
        rollback_mae_ratio=1.25, p99_budget_ms=1000.0,
        min_window_s=0.0, max_window_s=60.0))
    rec = None
    if tmp_path is not None:
        rec = flightrec.FlightRecorder(str(tmp_path / "flightrec"),
                                       role="test", log_fn=lambda m: None)
    return CanaryController(
        gate=gate, journal=journal, fleet=fleet, newest_fn=lambda: newest,
        flightrec=rec, log_fn=lambda m: None, **kw), rec


def _feed_labels(journal, n, *, pred=1.0, label=1.1, version=None,
                 start=0):
    for i in range(start, start + n):
        journal.note_served(trace_id=f"t{i}", payload={"graph": {"i": i}},
                            prediction=pred, param_version=version,
                            fingerprint=None, ts=None)
        journal.join(label, trace_id=f"t{i}")


class TestCanaryController:
    CAND = "ckpt-00000002"
    FLEET = "ckpt-00000001"

    def test_promote_flow(self):
        j = LabelJournal()
        fleet = FakeFleet(self.FLEET, shadow_fn=lambda p: 1.1)  # == label
        ctl, _ = _controller(j, fleet, self.CAND)
        ctl.tick(now=0.0)    # idle -> pinning (one replica pulled)
        assert ("begin", self.CAND) in fleet.calls
        ctl.tick(now=0.1)    # pin converged -> evaluating, gate opens
        assert ctl.gate.active == self.CAND
        # labeled live traffic arrives: live err 0.1, shadow err 0.0
        _feed_labels(j, 6, pred=1.0, label=1.1, version=self.FLEET)
        ctl.tick(now=0.5)
        # decision landed THIS tick: ratio 0 <= 1.05 -> fleet-wide gate
        assert ("promote", self.CAND) in fleet.calls
        assert fleet.fleet_v == self.CAND
        s = ctl.stats()
        assert s["state"] == "idle" and s["candidate"] is None
        assert s["shadow_sent"] == 6 and s["live_observed"] == 6
        kinds = [e["kind"] for e in s["events"]]
        assert kinds == ["canary_begin", "canary_pinned", "promoted"]

    def test_mirror_fraction_subsamples(self):
        j = LabelJournal()
        fleet = FakeFleet(self.FLEET)
        ctl, _ = _controller(j, fleet, self.CAND, mirror_fraction=0.5)
        ctl.tick(now=0.0)
        ctl.tick(now=0.1)
        _feed_labels(j, 8, version=self.FLEET)
        ctl.tick(now=0.2)
        # deterministic accumulator: exactly half the eligible records
        # mirrored; every label still counts toward the live baseline
        assert ctl.shadow_sent == 4 and ctl.live_observed == 8

    def test_rollback_names_version_in_bundle(self, tmp_path):
        j = LabelJournal()
        # the regressing candidate: shadow answers are far off truth
        fleet = FakeFleet(self.FLEET, shadow_fn=lambda p: 11.0)
        ctl, rec = _controller(j, fleet, self.CAND, tmp_path=tmp_path)
        ctl.tick(now=0.0)
        ctl.tick(now=0.1)
        _feed_labels(j, 6, pred=1.0, label=1.1, version=self.FLEET)
        ctl.tick(now=0.5)    # ratio ~99 >= 1.25 -> rollback begins
        assert ("abort", self.FLEET) in fleet.calls
        assert self.CAND in ctl.rejected
        ctl.tick(now=0.6)    # canary converged back -> returned to pool
        assert ("end", "r-canary") in fleet.calls
        assert ctl.stats()["state"] == "idle"
        assert fleet.fleet_v == self.FLEET  # fleet never moved
        # a rejected candidate is never re-evaluated
        begins = [c for c in fleet.calls if c[0] == "begin"]
        ctl.tick(now=1.0)
        assert [c for c in fleet.calls if c[0] == "begin"] == begins
        # the accountability pin: the bundle dir NAMES the version
        deadline = time.monotonic() + 10.0
        pat = os.path.join(str(tmp_path / "flightrec"),
                           f"bundle-*canary_rollback_{self.CAND}",
                           "manifest.json")
        while not glob.glob(pat) and time.monotonic() < deadline:
            time.sleep(0.05)
        manifests = glob.glob(pat)
        assert manifests, f"no rollback bundle matching {pat}"
        with open(manifests[0]) as f:
            manifest = json.load(f)
        assert self.CAND in json.dumps(manifest)

    def test_pin_timeout_rejects_candidate(self):
        j = LabelJournal()
        fleet = FakeFleet(self.FLEET)
        ctl, _ = _controller(j, fleet, self.CAND)
        # the pin never converges (dead replica / corrupt save)
        fleet.canary_version = lambda rid: None
        ctl.tick(now=0.0)           # -> pinning
        ctl.tick(now=30.0)          # inside the deadline: still waiting
        assert ctl.stats()["state"] == "pinning"
        ctl.tick(now=61.0)          # past max_window_s: reject
        assert self.CAND in ctl.rejected
        assert ("abort", self.FLEET) in fleet.calls

    def test_idle_when_no_new_candidate(self):
        j = LabelJournal()
        fleet = FakeFleet(self.FLEET)
        # newest == fleet version: nothing to evaluate
        ctl, _ = _controller(j, fleet, self.FLEET)
        ctl.tick(now=0.0)
        assert ctl.stats()["state"] == "idle"
        assert not fleet.calls


# ----------------------------------------- per-version labeled metrics


class TestPerVersionMetrics:
    def test_labeled_families_render_and_merge(self):
        j = LabelJournal()
        fleet = FakeFleet("ckpt-00000001")
        ctl, _ = _controller(j, fleet, "ckpt-00000002")
        ctl._observe_live("ckpt-00000001", 0.1)
        ctl._observe_live("ckpt-00000001", 0.2)
        ctl._observe_shadow("ckpt-00000002", 0.15, 5.0)
        reg = MetricsRegistry(namespace="fleet")
        reg.add_provider("canary",
                         lambda: {"histograms": ctl.metrics_histograms()})
        text = reg.prometheus_text()
        # ONE family declaration, labels riding every sample
        assert text.count("# TYPE fleet_fleet_label_mae_hist histogram") == 1
        assert 'param_version="ckpt-00000001"' in text
        assert 'param_version="ckpt-00000002"' in text
        fams = parse_prometheus_text(text)
        mae = fams["fleet_fleet_label_mae_hist"]["histogram"]
        assert len(mae) == 2  # one snapshot per label set
        counts = sorted(int(s["count"]) for s in mae.values())
        assert counts == [1, 2]
        # the fleet merge is label-set-aware: two replicas' expositions
        # pool per version, never across versions
        merged = merge_snapshot_maps([mae, mae])
        assert sorted(int(s["count"]) for s in merged.values()) == [2, 4]


# ------------------------------------- trainer + concurrent racecheck


@pytest.fixture
def rc_enabled():
    was = racecheck.enabled()
    racecheck.enable(True)
    racecheck.reset()
    yield racecheck
    racecheck.reset()
    racecheck.enable(was)


def _graph_payload(g):
    return {"graph": {
        "atom_fea": np.asarray(g.atom_fea).tolist(),
        "edge_fea": np.asarray(g.edge_fea).tolist(),
        "centers": np.asarray(g.centers).tolist(),
        "neighbors": np.asarray(g.neighbors).tolist(),
        "id": g.cif_id,
    }}


class TestContinualTrainer:
    def test_requires_exactly_one_journal(self):
        with pytest.raises(ValueError):
            ContinualTrainer("/tmp/x")
        with pytest.raises(ValueError):
            ContinualTrainer("/tmp/x", journal=LabelJournal(),
                             journal_path="/tmp/y")

    def test_gates_hold_without_labels_or_interval(self, tmp_path):
        j = LabelJournal()
        t = ContinualTrainer(str(tmp_path / "ckpt"), journal=j,
                             min_new_labels=4, min_interval_s=100.0,
                             clock=lambda: 0.0, log_fn=lambda m: None)
        # no labels: the cadence gate holds before any train-side boot
        assert t.poll_once(now=1000.0) is None
        assert t.rounds == 0 and t.stats()["commits"] == []

    def test_train_while_serving_racecheck_clean(self, rc_enabled,
                                                 tmp_path):
        """The first workload that trains WHILE the same process
        serves: journal appends + label joins + canary ticks race a
        real fine-tune round under the instrumented locks; the run
        must finish with zero inversions and zero shared-field
        violations, and the round must actually COMMIT a candidate."""
        from cgnn_tpu.config import DataConfig
        from cgnn_tpu.data.dataset import load_synthetic
        from cgnn_tpu.train import CheckpointManager
        from scripts.serve_loadgen import make_synth_ckpt

        ckpt = str(tmp_path / "ckpt")
        make_synth_ckpt(ckpt)
        mgr = CheckpointManager(ckpt)
        v1 = mgr.newest_committed()
        graphs = load_synthetic(
            32, DataConfig(radius=6.0, max_num_nbr=12).featurize_config(),
            seed=5)
        journal = LabelJournal()
        trainer = ContinualTrainer(
            ckpt, journal=journal, min_new_labels=24, min_interval_s=0.0,
            batch_size=8, epochs_per_round=1, max_rounds=1,
            log_fn=lambda m: None)
        fleet = FakeFleet(v1)
        ctl, _ = _controller(journal, fleet, None)
        stop = threading.Event()

        def serve_side():
            # the serving hook's exact append path: note_served on every
            # answer, a late join per trace — while training runs
            for i, g in enumerate(graphs):
                journal.note_served(
                    trace_id=f"s{i}", payload=_graph_payload(g),
                    prediction=float(np.asarray(g.target).reshape(-1)[0]),
                    param_version=v1, fingerprint=None, ts=None)
                journal.join(float(np.asarray(g.target).reshape(-1)[0]),
                             trace_id=f"s{i}")
                time.sleep(0.002)

        def canary_side():
            while not stop.wait(0.01):
                racecheck.heartbeat()
                ctl.tick()

        threads = [threading.Thread(target=serve_side, name="serve-feed"),
                   threading.Thread(target=canary_side, name="canary-tick")]
        for t in threads:
            t.start()
        try:
            deadline = time.monotonic() + 120.0
            name = None
            while name is None and time.monotonic() < deadline:
                name = trainer.poll_once()
                time.sleep(0.05)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert name is not None, "trainer never committed a candidate"
        assert name != v1 and mgr.is_committed(name)
        assert trainer.stats()["rounds"] == 1
        # the committed meta records its continual provenance
        meta = mgr.read_meta(name)
        assert meta.get("continual_round") == 1
        assert meta.get("replay_labels", 0) >= 24
        trainer.close()
        mgr.close()
        rep = racecheck.report()
        assert rep["inversions"] == [], rep["inversions"]
        assert rep["violations"] == [], rep["violations"]
