"""Training-runtime tests: normalizer, optimizer, loop convergence,
checkpoint round-trip, metrics (SURVEY.md §4.4)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic, train_val_test_split
from cgnn_tpu.data.graph import pack_graphs
from cgnn_tpu.models import CrystalGraphConvNet
from cgnn_tpu.train import (
    CheckpointManager,
    Normalizer,
    class_eval,
    create_train_state,
    make_optimizer,
)
from cgnn_tpu.train.loop import capacities_for, fit
from cgnn_tpu.train.state import multistep_lr


class TestNormalizer:
    def test_round_trip(self):
        rng = np.random.default_rng(0)
        t = rng.normal(3.0, 2.5, size=(100, 1))
        n = Normalizer.fit(t)
        normed = n.norm(jnp.asarray(t))
        np.testing.assert_allclose(np.mean(normed), 0.0, atol=1e-5)
        np.testing.assert_allclose(np.std(normed), 1.0, atol=1e-4)
        np.testing.assert_allclose(n.denorm(normed), t, rtol=1e-5)

    def test_masked_fit_ignores_missing(self):
        t = np.array([[1.0, 99.0], [3.0, 99.0], [5.0, 99.0]])
        m = np.array([[1.0, 0.0], [1.0, 0.0], [1.0, 0.0]])
        n = Normalizer.fit(t, m)
        np.testing.assert_allclose(n.mean[0], 3.0, atol=1e-6)
        # fully-masked task falls back to harmless defaults (no NaN)
        assert np.isfinite(n.mean[1]) and float(n.std[1]) > 0

    def test_state_dict_round_trip(self):
        n = Normalizer.fit(np.array([[1.0], [2.0], [3.0]]))
        n2 = Normalizer.from_state_dict(n.state_dict())
        np.testing.assert_allclose(n2.mean, n.mean)
        np.testing.assert_allclose(n2.std, n.std)


class TestOptimizer:
    def test_multistep_schedule(self):
        sched = multistep_lr(0.1, [10, 20], gamma=0.1)
        np.testing.assert_allclose(sched(0), 0.1)
        np.testing.assert_allclose(sched(10), 0.01, rtol=1e-6)
        np.testing.assert_allclose(sched(25), 0.001, rtol=1e-6)

    @pytest.mark.parametrize("optim", ["sgd", "adam", "adamw"])
    def test_optimizers_build_and_step(self, optim):
        tx = make_optimizer(optim=optim, lr=0.01, weight_decay=1e-4)
        params = {"w": jnp.ones(3)}
        os_ = tx.init(params)
        upd, _ = tx.update({"w": jnp.ones(3)}, os_, params)
        assert np.all(np.isfinite(upd["w"]))


class TestMetrics:
    def test_class_eval_perfect(self):
        lp = np.log(np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3], [0.1, 0.9]]))
        labels = np.array([0, 1, 0, 1])
        m = class_eval(lp, labels)
        assert m["accuracy"] == 1.0 and m["f1"] == 1.0 and m["auc"] == 1.0

    def test_class_eval_auc_random(self):
        rng = np.random.default_rng(0)
        scores = rng.uniform(size=2000)
        lp = np.stack([np.log1p(-scores), np.log(scores)], axis=1)
        labels = rng.integers(0, 2, size=2000)
        m = class_eval(lp, labels)
        assert 0.45 < m["auc"] < 0.55  # uninformative scores -> AUC ~ 0.5


@pytest.fixture(scope="module")
def tiny_dataset():
    graphs = load_synthetic(80, FeaturizeConfig(radius=5.0, max_num_nbr=8),
                            seed=5, max_atoms=6)
    return train_val_test_split(graphs, 0.7, 0.15, seed=0)


class TestFit:
    def test_loss_decreases_and_beats_mean(self, tiny_dataset):
        """SURVEY.md §4.4: integration — loss decreases, MAE < mean predictor."""
        train_g, val_g, _ = tiny_dataset
        model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=24)
        tx = make_optimizer(optim="adam", lr=0.01)
        normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
        node_cap, edge_cap = capacities_for(train_g, 16)
        example = pack_graphs(train_g[:16], node_cap, edge_cap, 16)
        state = create_train_state(model, example, tx, normalizer)
        state, result = fit(
            state, train_g, val_g, epochs=6, batch_size=16,
            node_cap=node_cap, edge_cap=edge_cap, print_freq=0,
            log_fn=lambda *a: None,
        )
        hist = result["history"]
        assert hist[-1]["train"]["loss"] < hist[0]["train"]["loss"]
        # mean predictor MAE on val
        mean_t = float(np.mean([g.target for g in train_g]))
        mean_mae = float(np.mean([abs(float(g.target[0]) - mean_t) for g in val_g]))
        assert result["best"] < mean_mae

    def test_pack_once_first_epoch_identical_then_trains(self, tiny_dataset):
        """pack_once: epoch 0 is bit-identical to per-epoch packing (same
        seed, same packing order); later epochs reshuffle batch order and
        keep training on every structure."""
        train_g, val_g, _ = tiny_dataset
        node_cap, edge_cap = capacities_for(train_g, 16)

        def run(pack_once, device_resident=False, scan_epochs=False):
            model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=24)
            tx = make_optimizer(optim="adam", lr=0.01)
            normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
            example = pack_graphs(train_g[:16], node_cap, edge_cap, 16)
            state = create_train_state(model, example, tx, normalizer,
                                       rng=jax.random.key(1))
            _, result = fit(
                state, train_g, val_g, epochs=3, batch_size=16,
                node_cap=node_cap, edge_cap=edge_cap, print_freq=0,
                seed=4, pack_once=pack_once,
                device_resident=device_resident, scan_epochs=scan_epochs,
                log_fn=lambda *a: None,
            )
            return result["history"]

        h_ref, h_po = run(False), run(True)
        # device_resident implies pack_once and reuses HBM buffers; the
        # trajectory must be identical to host-side pack_once
        h_dr = run(False, device_resident=True)
        # single bucket -> one scan group in packing/permutation order: the
        # whole-epoch-scan trajectory must match the loop exactly too
        h_scan = run(False, scan_epochs=True)
        for h, hs in zip(h_po, h_scan):
            assert hs["train"]["loss"] == pytest.approx(
                h["train"]["loss"], rel=1e-5)
            assert hs["val"]["mae"] == pytest.approx(
                h["val"]["mae"], rel=1e-5)
        assert h_po[0]["train"]["loss"] == pytest.approx(
            h_ref[0]["train"]["loss"], rel=1e-6)
        assert h_po[0]["val"]["mae"] == pytest.approx(
            h_ref[0]["val"]["mae"], rel=1e-6)
        for h, hd in zip(h_po, h_dr):
            # every epoch still visits every training structure once
            assert h["train"]["count"] == h_ref[0]["train"]["count"]
            assert np.isfinite(h["train"]["loss"])
            assert hd["train"]["loss"] == pytest.approx(
                h["train"]["loss"], rel=1e-6)

    def test_scan_epochs_multibucket(self, tiny_dataset):
        """scan_epochs + buckets>1: one scan per bucket shape still
        visits every structure every epoch and trains to finite losses."""
        train_g, val_g, _ = tiny_dataset
        model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=24)
        tx = make_optimizer(optim="adam", lr=0.01)
        normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
        node_cap, edge_cap = capacities_for(train_g, 8)
        example = pack_graphs(train_g[:8], node_cap, edge_cap, 8)
        state = create_train_state(model, example, tx, normalizer)
        _, result = fit(
            state, train_g, val_g, epochs=2, batch_size=8, buckets=2,
            print_freq=0, scan_epochs=True, log_fn=lambda *a: None,
        )
        for h in result["history"]:
            assert h["train"]["count"] == len(train_g)
            assert np.isfinite(h["train"]["loss"])
            assert np.isfinite(h["val"]["mae"])

    def test_scan_driver_mechanics(self, tiny_dataset):
        """r4 driver internals: run_epoch_pair == train_epoch+eval_epoch
        metrics, warm() stabilizes the compiled-program set, the eval
        schedule is cached (and survives reuse — its chunk lists are
        consumed per epoch), and the mixed tail scales with group size."""
        from cgnn_tpu.data.graph import bucketed_batch_iterator
        from cgnn_tpu.train.loop import ScanEpochDriver
        from cgnn_tpu.train.step import make_eval_step, make_train_step

        train_g, val_g, _ = tiny_dataset
        batches = list(bucketed_batch_iterator(
            train_g, 8, 2, shuffle=True, rng=np.random.default_rng(0),
        ))
        vbatches = list(bucketed_batch_iterator(val_g, 8, 2, in_cap=0))

        def fresh():
            model = CrystalGraphConvNet(atom_fea_len=16, n_conv=1,
                                        h_fea_len=16)
            tx = make_optimizer(optim="sgd", lr=0.01)
            state = create_train_state(
                model, batches[0], tx,
                Normalizer.fit(np.stack([g.target for g in train_g])),
                rng=jax.random.key(0),
            )
            drv = ScanEpochDriver(make_train_step(), make_eval_step(),
                                  batches, vbatches,
                                  np.random.default_rng(7))
            return state, drv

        # pair == separate drives, epoch by epoch (same rng consumption:
        # eval makes no draws, so interleaving order is identical)
        s1, d1 = fresh()
        s2, d2 = fresh()
        for epoch in range(3):
            first = epoch == 0
            s1, tm1, vm1 = d1.run_epoch_pair(s1, first=first)
            s2, tm2 = d2.train_epoch(s2, first=first)
            vm2 = d2.eval_epoch(s2)
            assert tm1["loss"] == pytest.approx(tm2["loss"], rel=1e-6)
            assert vm1["mae"] == pytest.approx(vm2["mae"], rel=1e-6)
            assert tm1["count"] == len(train_g)
            assert vm1["count"] == len(val_g)

        # eval schedule is cached once and reused without decay
        eval_keys = [k for k in d1._sched_cache if not k[1]]
        assert len(eval_keys) == 1

        # warm(): the program set stabilizes and further epochs add none;
        # it compiles via a disposable state copy, so the caller's state
        # comes back bit-identical (warm must not train — advisor r4)
        s3, d3 = fresh()
        before = jax.tree_util.tree_map(np.asarray, s3.params)
        s3 = d3.warm(s3)
        after = jax.tree_util.tree_map(np.asarray, s3.params)
        assert all(
            np.array_equal(a, b) for a, b in zip(
                jax.tree_util.tree_leaves(before),
                jax.tree_util.tree_leaves(after))
        )
        n_programs = len(d3._train_scans)
        for _ in range(3):
            s3, _, _ = d3.run_epoch_pair(s3, first=False)
        assert len(d3._train_scans) == n_programs

        # proportional tail: small groups no longer dispatch mostly
        # single-step scans
        assert d3._tail_for(6) == 1
        assert d3._tail_for(40) == 8   # capped at mixed_tail
        assert d3._tail_for(1) == 1    # never zero for a real group

    def test_async_pair_fetch_bit_identical(self, tiny_dataset):
        """ISSUE 5 satellite: the background-thread epoch-pair fetch is
        a pure scheduling change — metrics AND the training trajectory
        are bit-identical to the synchronous path (same fetch, same rng
        draw order: the deferred prebuild slots after eval, which draws
        nothing), at the driver level and through fit()'s deferred
        one-epoch-deep overlap."""
        from cgnn_tpu.data.graph import bucketed_batch_iterator
        from cgnn_tpu.train.loop import ScanEpochDriver
        from cgnn_tpu.train.step import make_eval_step, make_train_step

        train_g, val_g, _ = tiny_dataset
        # single bucket keeps the compiled scan-program count down; the
        # rng-order property at stake (the deferred prebuild draws after
        # eval instead of before) is bucket-count independent, and the
        # multi-bucket weighted draws happen inside _drive, untouched by
        # the async restructure
        batches = list(bucketed_batch_iterator(
            train_g, 8, 1, shuffle=True, rng=np.random.default_rng(0),
        ))
        vbatches = list(bucketed_batch_iterator(val_g, 8, 1, in_cap=0))

        def fresh():
            model = CrystalGraphConvNet(atom_fea_len=16, n_conv=1,
                                        h_fea_len=16)
            state = create_train_state(
                model, batches[0], make_optimizer(optim="sgd", lr=0.01),
                Normalizer.fit(np.stack([g.target for g in train_g])),
                rng=jax.random.key(0),
            )
            drv = ScanEpochDriver(make_train_step(), make_eval_step(),
                                  batches, vbatches,
                                  np.random.default_rng(7))
            return state, drv

        s1, d1 = fresh()
        s2, d2 = fresh()
        for epoch in range(2):
            first = epoch == 0
            s1, tm1, vm1 = d1.run_epoch_pair(s1, first=first)
            s2, pending = d2.run_epoch_pair(s2, first=first,
                                            async_fetch=True)
            tm2, vm2 = pending.result()
            assert tm1 == tm2  # bit-identical means, every key
            assert vm1 == vm2
        for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                        jax.tree_util.tree_leaves(s2.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # fit-level: the deferred path (no epoch-end consumer -> the
        # fetch overlaps the next epoch's dispatches) vs the immediate
        # join an epoch-end consumer forces — identical history/params
        def run_fit(**kw):
            model = CrystalGraphConvNet(atom_fea_len=16, n_conv=1,
                                        h_fea_len=16)
            nc, ec = capacities_for(train_g, 8)
            state = create_train_state(
                model, pack_graphs(train_g[:8], nc, ec, 8),
                make_optimizer(optim="sgd", lr=0.01),
                Normalizer.fit(np.stack([g.target for g in train_g])),
                rng=jax.random.key(1),
            )
            # buckets=1 keeps the compiled scan-program count down: the
            # multi-bucket rng-order parity is already pinned by the
            # driver-level comparison above
            return fit(state, train_g, val_g, epochs=2, batch_size=8,
                       print_freq=0, scan_epochs=True,
                       log_fn=lambda *a: None, **kw)
        sa, ra = run_fit()  # deferred overlap engaged
        saves = []
        sb, rb = run_fit(on_epoch_end=lambda s, e, m, b:
                         saves.append(e))  # immediate join
        assert len(saves) == 2  # the consumer still fired every epoch
        assert ra["history"] == rb["history"]
        assert ra["best"] == rb["best"]
        for a, b in zip(jax.tree_util.tree_leaves(sa.params),
                        jax.tree_util.tree_leaves(sb.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_checkpoint_round_trip(self, tiny_dataset, tmp_path):
        train_g, _, _ = tiny_dataset
        model = CrystalGraphConvNet(atom_fea_len=8, n_conv=1, h_fea_len=16)
        tx = make_optimizer(optim="sgd", lr=0.01)
        normalizer = Normalizer.fit(np.stack([g.target for g in train_g]))
        node_cap, edge_cap = capacities_for(train_g, 8)
        example = pack_graphs(train_g[:8], node_cap, edge_cap, 8)
        state = create_train_state(model, example, tx, normalizer)

        mgr = CheckpointManager(str(tmp_path / "ckpt"))
        meta = {"model": {"atom_fea_len": 8}, "epoch": 4, "task": "regression"}
        mgr.save(state, meta, is_best=True)
        mgr.wait()
        assert mgr.exists("latest") and mgr.exists("best")

        # restore into a freshly-initialized state: must match the saved one
        state2 = create_train_state(
            model, example, tx, normalizer, rng=jax.random.key(99)
        )
        restored, meta2 = mgr.restore(state2)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-7),
            restored.params, state.params,
        )
        assert meta2["epoch"] == 4 and meta2["task"] == "regression"
        # inference restore path
        inf = mgr.restore_for_inference(state2, "best")
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-7),
            inf.params, state.params,
        )
        mgr.close()
