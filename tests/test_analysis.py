"""graftcheck + racecheck tests (ISSUE 7).

Three layers:

- the fixture corpus (tests/analysis_fixtures): one minimal must-flag
  and one must-pass snippet per rule — the rule catalog's unit tests;
- the live-repo pin: ``graftcheck`` runs CLEAN over the real tree, so
  every invariant the rules encode is enforced forever (a new finding
  is a CI failure, not a note);
- racecheck: lock-order inversion detection, the shared-field tripwire,
  the deadlock watchdog (with the attributable thread names the
  GC-THREADNAME rule exists for), and the zero-overhead-off contract.
"""

import io
import os
import subprocess
import sys
import threading
import time

import pytest

from cgnn_tpu.analysis import (
    RULES,
    check_file,
    check_paths,
    default_targets,
)
from cgnn_tpu.analysis import racecheck
from cgnn_tpu.analysis.engine import check_file as engine_check_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "analysis_fixtures")


def _slug(rule: str) -> str:
    return rule.lower().replace("-", "_")


class TestFixtureCorpus:
    @pytest.mark.parametrize("rule", sorted(RULES))
    def test_flag_fixture_is_caught(self, rule):
        path = os.path.join(FIXTURES, f"{_slug(rule)}_flag.py")
        findings = check_file(path)
        hits = [f for f in findings if f.rule == rule]
        assert hits, (
            f"{rule}: must-flag fixture produced no {rule} finding "
            f"(got {[f.rule for f in findings]})"
        )

    @pytest.mark.parametrize("rule", sorted(RULES))
    def test_pass_fixture_is_clean(self, rule):
        path = os.path.join(FIXTURES, f"{_slug(rule)}_pass.py")
        findings = check_file(path)
        assert not findings, (
            f"{rule}: must-pass fixture flagged: "
            + "; ".join(f.format() for f in findings)
        )

    def test_corpus_covers_every_rule(self):
        """The seeded corpus trips every rule at least once — the CI
        static-analysis job's 'linter still has teeth' check."""
        findings = check_paths([FIXTURES], rel_to=REPO)
        seen = {f.rule for f in findings}
        missing = set(RULES) - seen
        assert not missing, f"no corpus violation for rule(s) {missing}"

    def test_messages_cite_the_motivating_incident(self):
        """Findings explain WHY via the CHANGES.md incident — the fix-it
        message is the point of the tool."""
        findings = check_paths([FIXTURES], rel_to=REPO)
        for f in findings:
            if f.rule in ("GC-DISABLE", "GC-PARSE"):
                continue  # policy/parse findings have no PR incident
            assert "CHANGES.md" in f.message or "PR" in f.message, (
                f"{f.rule} message cites no incident: {f.message}"
            )


class TestRepoClean:
    def test_graftcheck_clean_on_live_repo(self):
        """THE pin: the tree obeys its own invariant catalog. A finding
        here means either fix the code or add an audited disable —
        never weaken the rule."""
        findings = check_paths(default_targets(REPO), rel_to=REPO)
        assert not findings, (
            "graftcheck findings on the live repo:\n"
            + "\n".join(f.format() for f in findings)
        )

    def test_scan_set_covers_the_package(self):
        targets = default_targets(REPO)
        rel = {os.path.relpath(t, REPO) for t in targets}
        for expected in (
            "cgnn_tpu/serve/server.py",
            "cgnn_tpu/fleet/router.py",
            "cgnn_tpu/train/checkpoint.py",
            "cgnn_tpu/data/pipeline.py",
            "scripts/serve_loadgen.py",
            "train.py",
            "serve.py",
            "fleet.py",
        ):
            assert expected in rel, f"{expected} not in the scan set"
        assert "__graft_entry__.py" not in rel
        assert not any(p.startswith("tests") for p in rel)


class TestCLI:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "graftcheck.py"), *args],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )

    def test_ci_exit_zero_on_repo(self):
        res = self._run("--ci")
        assert res.returncode == 0, res.stdout + res.stderr
        assert "clean" in res.stdout

    def test_ci_exit_nonzero_on_corpus_with_every_rule(self):
        res = self._run("--ci", os.path.join("tests", "analysis_fixtures"))
        assert res.returncode == 1, res.stdout + res.stderr
        for rule in RULES:
            assert rule in res.stdout, f"{rule} missing from corpus output"
        # --ci emits GitHub error annotations for the blocking job
        assert "::error file=" in res.stdout

    def test_list_rules(self):
        res = self._run("--list-rules")
        assert res.returncode == 0
        for rule in RULES:
            assert rule in res.stdout


class TestDisableComments:
    def _check(self, source, tmp_path, name="snippet.py"):
        path = tmp_path / name
        path.write_text(source)
        return engine_check_file(str(path))

    def test_justified_trailing_disable_silences(self, tmp_path):
        findings = self._check(
            "import jax\n"
            "def f(s):\n"
            "    return jax.device_get(s)"
            "  # graftcheck: disable=GC-ALIAS -- audited: read-only\n",
            tmp_path,
        )
        assert not findings

    def test_standalone_disable_covers_next_code_line(self, tmp_path):
        findings = self._check(
            "import jax\n"
            "def f(s):\n"
            "    # graftcheck: disable=GC-ALIAS -- audited: read-only\n"
            "    return jax.device_get(s)\n",
            tmp_path,
        )
        assert not findings

    def test_unjustified_disable_is_a_finding_and_does_not_cover(
            self, tmp_path):
        findings = self._check(
            "import jax\n"
            "def f(s):\n"
            "    return jax.device_get(s)  # graftcheck: disable=GC-ALIAS\n",
            tmp_path,
        )
        rules = sorted(f.rule for f in findings)
        assert rules == ["GC-ALIAS", "GC-DISABLE"], rules

    def test_unknown_rule_is_a_finding(self, tmp_path):
        findings = self._check(
            "x = 1  # graftcheck: disable=GC-NOPE -- because\n", tmp_path)
        assert [f.rule for f in findings] == ["GC-DISABLE"]
        assert "unknown rule" in findings[0].message

    def test_disable_covers_only_named_rule(self, tmp_path):
        findings = self._check(
            "import jax\n"
            "def f(s):\n"
            "    return jax.device_get(s)"
            "  # graftcheck: disable=GC-THREAD -- wrong rule named\n",
            tmp_path,
        )
        assert [f.rule for f in findings] == ["GC-ALIAS"]


@pytest.fixture
def rc_enabled():
    """Racecheck on, state isolated; always restored to off (the suite
    runs with the env gate off)."""
    was = racecheck.enabled()
    racecheck.enable(True)
    racecheck.reset()
    yield racecheck
    racecheck.reset()
    racecheck.enable(was)


class TestRacecheckLocks:
    def test_lock_order_inversion_detected(self, rc_enabled):
        a = racecheck.make_lock("lock-a")
        b = racecheck.make_lock("lock-b")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        # sequential threads: the ORDER is the hazard, not a live race
        for name, fn in (("serve-dispatch-0", ab), ("pack-worker-0", ba)):
            t = threading.Thread(target=fn, name=name)
            t.start()
            t.join()
        rep = racecheck.report()
        assert len(rep["inversions"]) == 1, rep
        inv = rep["inversions"][0]
        assert inv["locks"] == ["lock-a", "lock-b"]
        # attributable: the report names the threads, not Thread-5
        joined = inv["order_a"] + inv["order_b"]
        assert "serve-dispatch-0" in joined and "pack-worker-0" in joined
        assert not rep["clean"]

    def test_consistent_order_is_clean(self, rc_enabled):
        a = racecheck.make_lock("lock-a")
        b = racecheck.make_lock("lock-b")
        for _ in range(3):
            with a:
                with b:
                    pass
        rep = racecheck.report()
        assert rep["inversions"] == [] and rep["clean"]

    def test_reentrant_acquire_not_an_inversion(self, rc_enabled):
        c = racecheck.make_condition("cond-x")
        with c:
            with c:
                pass
        assert racecheck.report()["clean"]

    def test_condition_wait_notify_roundtrip(self, rc_enabled):
        """The Condition protocol shims (_is_owned/_release_save/
        _acquire_restore) must survive a real wait/notify cycle."""
        c = racecheck.make_condition("cond-y")
        ready = []

        def consumer():
            with c:
                while not ready:
                    c.wait(timeout=2.0)

        t = threading.Thread(target=consumer, name="cond-consumer")
        t.start()
        time.sleep(0.05)
        with c:
            ready.append(1)
            c.notify_all()
        t.join(timeout=5)
        assert not t.is_alive()
        assert racecheck.report()["clean"]


class TestRacecheckWatchFields:
    def test_cross_thread_unlocked_touch_is_a_violation(self, rc_enabled):
        class Counters:
            def __init__(self):
                self.responses = 0

        lock = racecheck.make_lock("serve.server")
        obj = Counters()
        racecheck.watch_fields(obj, lock, ("responses",))

        def locked_touch():
            with lock:
                obj.responses += 1

        def unlocked_touch():
            obj.responses += 1

        t = threading.Thread(target=locked_touch, name="serve-dispatch-1")
        t.start(); t.join()
        assert racecheck.report()["violations"] == []
        t = threading.Thread(target=unlocked_touch, name="rogue-scraper")
        t.start(); t.join()
        rep = racecheck.report()
        assert rep["violations"], "unlocked cross-thread touch not caught"
        v = rep["violations"][0]
        assert v["field"] == "responses" and v["thread"] == "rogue-scraper"
        assert v["lock"] == "serve.server"

    def test_registering_thread_exempt(self, rc_enabled):
        class Counters:
            def __init__(self):
                self.responses = 0

        lock = racecheck.make_lock("serve.server")
        obj = Counters()
        racecheck.watch_fields(obj, lock, ("responses",))
        obj.responses += 1  # same thread that registered: allowed
        assert racecheck.report()["violations"] == []


class TestRacecheckWatchdog:
    def test_watchdog_names_the_stalled_thread(self, rc_enabled):
        """The satellite pin: dumps are attributable BY NAME — the
        stable serve-dispatch-{i}/pack-worker-{i} names graftcheck's
        GC-THREADNAME rule mandates show up in the stall report and the
        ident map."""
        release = threading.Event()

        def wedge():
            racecheck.heartbeat()
            release.wait(10)

        names = ["serve-dispatch-0", "pack-worker-1"]
        threads = [threading.Thread(target=wedge, name=n, daemon=True)
                   for n in names]
        for t in threads:
            t.start()
        time.sleep(0.05)
        sink = io.StringIO()
        dog = racecheck.Watchdog(bound_s=0.2, interval_s=0.05, sink=sink,
                                 log_fn=lambda m: None)
        assert dog.check_once() == []  # beats fresh: not stalled yet
        time.sleep(0.35)
        stalled = dog.check_once()
        assert sorted(stalled) == sorted(names), stalled
        dog.dump(stalled)
        out = sink.getvalue()
        for n in names:
            assert n in out, f"dump not attributable: {n} missing\n{out}"
        assert "racecheck deadlock watchdog" in out
        release.set()
        for t in threads:
            t.join(timeout=5)

    def test_cleanly_exited_thread_is_pruned_not_reported(self, rc_enabled):
        def beat_and_exit():
            racecheck.heartbeat()

        t = threading.Thread(target=beat_and_exit, name="pack-worker-9")
        t.start(); t.join()
        dog = racecheck.Watchdog(bound_s=0.0, interval_s=10,
                                 log_fn=lambda m: None)
        assert dog.check_once(now=time.monotonic() + 60) == []
        rep = racecheck.report()
        assert "pack-worker-9" not in rep["heartbeating_threads"]
        # ...but heartbeats_seen survives the prune: the smoke leg's
        # "the watchdog watched SOMETHING" assertion must not race a
        # clean post-drain exit
        assert "pack-worker-9" in rep["heartbeats_seen"]

    def test_ident_reuse_does_not_fake_a_deadlock(self, rc_enabled):
        """A dead thread's beat must be pruned even when an unrelated
        live thread holds the (reused) ident — keying liveness on the
        bare ident would dump a spurious deadlock for a clean exit."""
        def beat_and_exit():
            racecheck.heartbeat()

        t = threading.Thread(target=beat_and_exit, name="pack-worker-8")
        t.start(); t.join()
        # simulate CPython ident reuse: point the stale beat at a LIVE
        # thread (this one) that has a different name
        with racecheck._state_lock:
            last, _ = racecheck._beats["pack-worker-8"]
            racecheck._beats["pack-worker-8"] = (
                last, threading.get_ident())
        dog = racecheck.Watchdog(bound_s=0.0, interval_s=10,
                                 log_fn=lambda m: None)
        assert dog.check_once(now=time.monotonic() + 60) == []

    def test_start_watchdog_rearms_the_singleton(self, rc_enabled):
        """A second server in the same process must re-point the
        watchdog's bound and logger, not be silently ignored (stall
        logs wired to a drained predecessor)."""
        logs_a, logs_b = [], []
        dog = racecheck.start_watchdog(bound_s=40.0, log_fn=logs_a.append)
        try:
            again = racecheck.start_watchdog(bound_s=5.0,
                                             log_fn=logs_b.append)
            assert again is dog  # still the singleton
            assert dog.bound_s == 5.0
            dog._log("stall")
            assert logs_b == ["stall"] and logs_a == []
        finally:
            dog.stop()


class TestRacecheckOff:
    def test_zero_overhead_when_gated_off(self):
        racecheck.enable(False)
        racecheck.reset()
        lk = racecheck.make_lock("anything")
        assert isinstance(lk, type(threading.Lock())), (
            "make_lock must return a PLAIN threading.Lock when off "
            "(the PERF.md zero-overhead contract)"
        )
        cond = racecheck.make_condition("anything")
        assert isinstance(cond, threading.Condition)
        assert not isinstance(getattr(cond, "_lock", None),
                              racecheck.InstrumentedLock)
        racecheck.heartbeat()  # no-op: registers nothing
        assert racecheck.start_watchdog() is None

        class Obj:
            pass

        obj = Obj()
        racecheck.watch_fields(obj, lk, ("x",))
        assert type(obj) is Obj  # class NOT swapped when off
        rep = racecheck.report()
        assert rep["clean"] and not rep["enabled"]
        assert rep["heartbeating_threads"] == []
