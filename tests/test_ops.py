"""Unit tests for segment ops and masked BatchNorm (SURVEY.md §4.2)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch

from cgnn_tpu.ops.norm import MaskedBatchNorm
from cgnn_tpu.ops.segment import (
    aggregate_edge_messages,
    segment_mean,
    segment_sum,
)


class TestSegmentOps:
    def test_segment_sum_matches_loop(self):
        rng = np.random.default_rng(0)
        data = rng.normal(size=(40, 5)).astype(np.float32)
        ids = rng.integers(0, 7, size=40)
        expected = np.zeros((7, 5), np.float32)
        for row, i in zip(data, ids):
            expected[i] += row
        got = segment_sum(jnp.asarray(data), jnp.asarray(ids), 7)
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)

    def test_segment_mean_masked(self):
        data = jnp.array([[2.0], [4.0], [100.0], [6.0]])
        ids = jnp.array([0, 0, 0, 1])
        w = jnp.array([1.0, 1.0, 0.0, 1.0])  # row 2 is padding
        got = segment_mean(data, ids, 3, weights=w)
        np.testing.assert_allclose(got, [[3.0], [6.0], [0.0]], atol=1e-6)

    @pytest.mark.parametrize("impl", ["xla", "sort"])
    def test_aggregate_impls_agree(self, impl):
        rng = np.random.default_rng(1)
        msgs = rng.normal(size=(64, 8)).astype(np.float32)
        centers = np.sort(rng.integers(0, 16, size=64)).astype(np.int32)
        base = segment_sum(jnp.asarray(msgs), jnp.asarray(centers), 16)
        got = aggregate_edge_messages(
            jnp.asarray(msgs), jnp.asarray(centers), 16, impl=impl
        )
        np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-5)


class TestPallasSegmentSum:
    """Interpreter-mode checks (real-chip compile is exercised by bench.py
    and the TPU smoke script; the CPU suite can only interpret)."""

    def _case(self, e, n, f, seed):
        rng = np.random.default_rng(seed)
        msgs = rng.normal(size=(e, f)).astype(np.float32)
        centers = np.sort(rng.integers(0, n, size=e)).astype(np.int32)
        return jnp.asarray(msgs), jnp.asarray(centers)

    @pytest.mark.parametrize("e,n,f", [(64, 16, 8), (1000, 300, 32), (2048, 513, 16)])
    def test_matches_xla(self, e, n, f):
        from jax.experimental.pallas import tpu as pltpu

        from cgnn_tpu.ops.pallas_scatter import segment_sum_pallas

        msgs, centers = self._case(e, n, f, seed=e)
        expected = segment_sum(msgs, centers, n)
        with pltpu.force_tpu_interpret_mode():
            got = segment_sum_pallas(msgs, centers, n)
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)

    def test_gradient_is_gather(self):
        from jax.experimental.pallas import tpu as pltpu

        from cgnn_tpu.ops.pallas_scatter import segment_sum_pallas

        msgs, centers = self._case(200, 40, 8, seed=0)

        with pltpu.force_tpu_interpret_mode():
            g_pallas = jax.grad(
                lambda m: jnp.sum(segment_sum_pallas(m, centers, 40) ** 2)
            )(msgs)
        g_xla = jax.grad(lambda m: jnp.sum(segment_sum(m, centers, 40) ** 2))(msgs)
        np.testing.assert_allclose(g_pallas, g_xla, rtol=1e-5, atol=1e-5)

    def test_empty_segments_and_skew(self):
        """Gaps (empty nodes) and one hub node with huge degree."""
        from jax.experimental.pallas import tpu as pltpu

        from cgnn_tpu.ops.pallas_scatter import segment_sum_pallas

        rng = np.random.default_rng(1)
        n = 260
        centers = np.sort(
            np.concatenate([
                np.full(700, 5),          # hub: degree 700 > chunk size
                rng.integers(100, 120, 50),  # sparse middle, gaps elsewhere
                np.full(30, n - 1),       # tail node
            ])
        ).astype(np.int32)
        msgs = jnp.asarray(rng.normal(size=(len(centers), 8)).astype(np.float32))
        expected = segment_sum(msgs, jnp.asarray(centers), n)
        with pltpu.force_tpu_interpret_mode():
            got = segment_sum_pallas(msgs, jnp.asarray(centers), n)
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-4)


class TestMaskedBatchNorm:
    """Parity with torch.nn.BatchNorm1d — the oracle's normalizer."""

    def _torch_bn_reference(self, x, train, steps=1):
        bn = torch.nn.BatchNorm1d(x.shape[-1], momentum=0.1, eps=1e-5)
        bn.train(train)
        with torch.no_grad():
            for _ in range(steps):
                out = bn(torch.from_numpy(x))
        return out.numpy(), bn.running_mean.numpy(), bn.running_var.numpy()

    def test_train_mode_matches_torch(self):
        rng = np.random.default_rng(2)
        x = rng.normal(2.0, 3.0, size=(32, 6)).astype(np.float32)
        mod = MaskedBatchNorm()
        variables = mod.init(jax.random.key(0), jnp.asarray(x))
        y, updated = mod.apply(
            variables, jnp.asarray(x), mutable=["batch_stats"],
            use_running_average=False,
        )
        ref_y, ref_mean, ref_var = self._torch_bn_reference(x, train=True)
        np.testing.assert_allclose(y, ref_y, rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            updated["batch_stats"]["mean"], ref_mean, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            updated["batch_stats"]["var"], ref_var, rtol=1e-4, atol=1e-5
        )

    def test_eval_mode_uses_running_stats(self):
        rng = np.random.default_rng(3)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        mod = MaskedBatchNorm()
        variables = mod.init(jax.random.key(0), jnp.asarray(x))
        # running stats are (0, 1) at init -> eval output is x (scale=1, bias=0)
        y = mod.apply(variables, jnp.asarray(x), use_running_average=True)
        np.testing.assert_allclose(y, x / np.sqrt(1 + 1e-5), rtol=1e-5, atol=1e-5)

    def test_fully_masked_batch_preserves_running_stats(self):
        """An all-padding batch (empty DP shard) must not decay stats."""
        x = np.zeros((8, 3), np.float32)
        mask = np.zeros(8, np.float32)
        mod = MaskedBatchNorm()
        v = mod.init(jax.random.key(0), jnp.asarray(x))
        before = jax.device_get(v["batch_stats"])
        _, upd = mod.apply(
            v, jnp.asarray(x), mask=jnp.asarray(mask),
            mutable=["batch_stats"], use_running_average=False,
        )
        after = jax.device_get(upd["batch_stats"])
        np.testing.assert_array_equal(after["mean"], before["mean"])
        np.testing.assert_array_equal(after["var"], before["var"])

    def test_masked_equals_unmasked_on_real_rows(self):
        """SURVEY.md §4.2: masked BN over padded data == BN over unpadded."""
        rng = np.random.default_rng(4)
        real = rng.normal(1.0, 2.0, size=(20, 5)).astype(np.float32)
        padded = np.concatenate([real, np.zeros((12, 5), np.float32)])
        mask = np.concatenate([np.ones(20), np.zeros(12)]).astype(np.float32)

        mod = MaskedBatchNorm()
        v1 = mod.init(jax.random.key(0), jnp.asarray(real))
        y_real, s_real = mod.apply(
            v1, jnp.asarray(real), mutable=["batch_stats"],
            use_running_average=False,
        )
        y_pad, s_pad = mod.apply(
            v1, jnp.asarray(padded), mask=jnp.asarray(mask),
            mutable=["batch_stats"], use_running_average=False,
        )
        np.testing.assert_allclose(y_pad[:20], y_real, rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(
            s_pad["batch_stats"]["mean"], s_real["batch_stats"]["mean"],
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            s_pad["batch_stats"]["var"], s_real["batch_stats"]["var"],
            rtol=1e-5, atol=1e-6,
        )


def test_one_pass_bn_matches_two_pass_reference():
    """The f32 one-pass (E[x^2]-E[x]^2) masked moments must match a numpy
    two-pass centered reference at f32-roundoff tolerance — the f64 parity
    suite deliberately routes to the two-pass branch and would not catch a
    one-pass regression (dropped mask in s2, broken psum tuple)."""
    import jax

    from cgnn_tpu.ops.norm import MaskedBatchNorm

    rng = np.random.default_rng(0)
    x = rng.normal(2.0, 3.0, size=(257, 6)).astype(np.float32)
    mask = (rng.random(257) > 0.3).astype(np.float32)

    bn = MaskedBatchNorm()
    variables = bn.init(jax.random.key(0), x, mask=mask)
    y, mutated = bn.apply(
        variables, x, mask=mask, use_running_average=False,
        mutable=["batch_stats"],
    )

    rows = x[mask > 0]
    mean = rows.mean(axis=0)
    var = rows.var(axis=0)  # biased, two-pass centered
    ref = (x - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-4)
    # running stats: unbiased variance update at momentum 0.1
    n = rows.shape[0]
    np.testing.assert_allclose(
        np.asarray(mutated["batch_stats"]["mean"]), 0.1 * mean, rtol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(mutated["batch_stats"]["var"]),
        0.9 * 1.0 + 0.1 * var * n / (n - 1), rtol=2e-4,
    )


class TestFusedEpilogue:
    """ops/fused_epilogue.py vs the unfused MaskedBatchNorm+gate+mask+sum
    chain (PERF.md §4b, VERDICT r3 next-step #1): values, gradients, and
    running-stat updates must agree to f32 roundoff, both impls."""

    def _setup(self, seed=0, n=67, m=12, f=32):
        import jax

        rng = np.random.default_rng(seed)
        z = rng.normal(0.5, 1.5, size=(n, m, 2 * f)).astype(np.float32)
        mask = np.zeros((n, m), np.float32)
        # ragged realistic mask: leading rows real, random slot counts
        for i in range(n - 7):  # last 7 node slots are padding
            mask[i, : rng.integers(3, m + 1)] = 1.0
        scale = rng.normal(1.0, 0.1, 2 * f).astype(np.float32)
        bias = rng.normal(0.0, 0.1, 2 * f).astype(np.float32)
        return jax.numpy.asarray(z), jax.numpy.asarray(mask), \
            jax.numpy.asarray(scale), jax.numpy.asarray(bias)

    @staticmethod
    def _reference(z, mask, scale, bias):
        """The unfused chain, as CGConv computes it (one-pass f32 BN)."""
        import jax
        import jax.numpy as jnp

        from cgnn_tpu.ops.norm import MaskedBatchNorm

        bn = MaskedBatchNorm()
        variables = {
            "params": {"scale": scale, "bias": bias},
            "batch_stats": {"mean": jnp.zeros_like(scale),
                            "var": jnp.ones_like(scale)},
        }
        y, mutated = bn.apply(variables, z, mask=mask,
                              use_running_average=False,
                              mutable=["batch_stats"])
        f = y.shape[-1] // 2
        msg = jax.nn.sigmoid(y[..., :f]) * jax.nn.softplus(y[..., f:])
        msg = msg * mask[..., None]
        return msg.sum(axis=1), mutated["batch_stats"]

    def _check_impl(self, impl):
        import jax
        import jax.numpy as jnp

        from cgnn_tpu.ops.fused_epilogue import fused_epilogue

        z, mask, scale, bias = self._setup()

        def fused_loss(z, scale, bias):
            agg, mean, var, n_real = fused_epilogue(
                z, mask, scale, bias, 1e-5, impl)
            return (agg ** 2).sum(), (agg, mean, var, n_real)

        def ref_loss(z, scale, bias):
            agg, stats = self._reference(z, mask, scale, bias)
            return (agg ** 2).sum(), (agg, stats)

        (l1, (agg_f, mean, var, n_real)), g_f = jax.value_and_grad(
            fused_loss, argnums=(0, 1, 2), has_aux=True)(z, scale, bias)
        (l2, (agg_r, stats)), g_r = jax.value_and_grad(
            ref_loss, argnums=(0, 1, 2), has_aux=True)(z, scale, bias)

        np.testing.assert_allclose(np.asarray(agg_f), np.asarray(agg_r),
                                   rtol=2e-5, atol=2e-5)
        # padding node rows aggregate to zero... (mask rows are all zero)
        assert float(np.abs(np.asarray(agg_f)[-7:]).max()) < 1e-5
        # stats consistent with the unfused module's EMA update at step 1:
        # running = 0.9*init + 0.1*batch  =>  batch mean = 10*(run - 0.9*0)
        np.testing.assert_allclose(
            np.asarray(mean), np.asarray(stats["mean"]) / 0.1,
            rtol=1e-4, atol=1e-5,
        )
        c = float(n_real)
        unb = np.asarray(var) * c / (c - 1.0)
        np.testing.assert_allclose(
            unb, (np.asarray(stats["var"]) - 0.9) / 0.1, rtol=1e-4,
            atol=1e-4,
        )
        for a, b, name in zip(g_f, g_r, ("dz", "dscale", "dbias")):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
                err_msg=f"fused[{impl}] {name} mismatch",
            )

    def test_xla_impl_matches_unfused(self):
        self._check_impl("xla")

    def test_pallas_impl_matches_unfused(self):
        from jax.experimental.pallas import tpu as pltpu

        with pltpu.force_tpu_interpret_mode():
            self._check_impl("pallas")

    def test_eval_mode_matches_unfused(self):
        import jax
        import jax.numpy as jnp

        from cgnn_tpu.ops.fused_epilogue import fused_epilogue_eval
        from cgnn_tpu.ops.norm import MaskedBatchNorm

        z, mask, scale, bias = self._setup(seed=3)
        rng = np.random.default_rng(9)
        rmean = jnp.asarray(rng.normal(0, 1, z.shape[-1]).astype(np.float32))
        rvar = jnp.asarray(
            rng.uniform(0.5, 2.0, z.shape[-1]).astype(np.float32))
        got = fused_epilogue_eval(z, mask, scale, bias, rmean, rvar, 1e-5)
        bn = MaskedBatchNorm()
        variables = {"params": {"scale": scale, "bias": bias},
                     "batch_stats": {"mean": rmean, "var": rvar}}
        y = bn.apply(variables, z, mask=mask, use_running_average=True)
        f = y.shape[-1] // 2
        ref = (jax.nn.sigmoid(y[..., :f]) * jax.nn.softplus(y[..., f:])
               * mask[..., None]).sum(axis=1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_cgconv_fused_matches_unfused_end_to_end(self):
        """Whole-model check: CrystalGraphConvNet with fused_epilogue='xla'
        reproduces the unfused model's outputs and parameter gradients on a
        real packed dense batch (same variable tree — drop-in)."""
        import jax
        import jax.numpy as jnp

        from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
        from cgnn_tpu.data.graph import batch_iterator, capacities_for
        from cgnn_tpu.models import CrystalGraphConvNet

        cfg = FeaturizeConfig(radius=5.0, max_num_nbr=8)
        graphs = load_synthetic(12, cfg, seed=2, max_atoms=6)
        nc, ec = capacities_for(graphs, 12, dense_m=8)
        batch = next(batch_iterator(graphs, 12, nc, ec, dense_m=8))
        base = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=24,
                                   dense_m=8)
        fused = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=24,
                                    dense_m=8, fused_epilogue="xla")
        variables = base.init(jax.random.key(0), batch)

        def loss(model, params):
            out, mut = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                batch, train=True, mutable=["batch_stats"])
            return (out ** 2).sum(), mut["batch_stats"]

        (l_b, s_b), g_b = jax.value_and_grad(
            lambda p: loss(base, p), has_aux=True)(variables["params"])
        (l_f, s_f), g_f = jax.value_and_grad(
            lambda p: loss(fused, p), has_aux=True)(variables["params"])
        assert float(l_f) == pytest.approx(float(l_b), rel=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_b),
                        jax.tree_util.tree_leaves(g_f)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(s_b),
                        jax.tree_util.tree_leaves(s_f)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


def test_one_pass_bn_high_mean_no_cancellation():
    """|mean| >> std regime: unshifted f32 E[x^2]-E[x]^2 loses all variance
    bits (var clamps to 0 and rsqrt(eps) AMPLIFIES by ~300x); the
    shift-invariant accumulation must keep the output unit-variance.
    Advisor finding r3 (ops/norm.py one-pass cancellation)."""
    import jax

    from cgnn_tpu.ops.norm import MaskedBatchNorm

    rng = np.random.default_rng(1)
    # mean 1e4, std 1: mean^2/var = 1e8 > 2^24 — guaranteed f32
    # cancellation without a shift
    x = (1e4 + rng.normal(0.0, 1.0, size=(1024, 4))).astype(np.float32)
    mask = np.ones(1024, np.float32)
    mask[900:] = 0.0

    bn = MaskedBatchNorm()
    variables = bn.init(jax.random.key(0), x, mask=mask)
    y, _ = bn.apply(
        variables, x, mask=mask, use_running_average=False,
        mutable=["batch_stats"],
    )
    rows = x[mask > 0].astype(np.float64)
    ref = (x.astype(np.float64) - rows.mean(0)) / np.sqrt(rows.var(0) + 1e-5)
    got = np.asarray(y)[:900]
    # unit-scale output, not a 300x blowup; tolerance is loose because the
    # data itself carries only ~3 significant fractional digits in f32
    np.testing.assert_allclose(got, ref[:900], atol=5e-2)
    assert float(np.abs(got).max()) < 10.0


class TestFusedCGConv:
    """ops/pallas_cgconv.py (the WHOLE-conv fused kernel, ROADMAP item 2)
    vs the unfused dense CGConv branch: values, parameter gradients,
    running-stat updates, and eval mode must agree to f32 roundoff for
    both impls — mirroring TestFusedEpilogue's contract one level up."""

    def _models(self, impl, dense_m=8, window=0):
        from cgnn_tpu.models import CrystalGraphConvNet

        kw = dict(atom_fea_len=16, n_conv=2, h_fea_len=24, dense_m=dense_m)
        base = CrystalGraphConvNet(**kw)
        fused = CrystalGraphConvNet(**kw, cgconv_impl=impl,
                                    cgconv_window=window)
        return base, fused

    def _batch(self, n=14, max_atoms=6, dense_m=8, in_cap=None):
        from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
        from cgnn_tpu.data.graph import batch_iterator, capacities_for

        cfg = FeaturizeConfig(radius=5.0, max_num_nbr=dense_m)
        graphs = load_synthetic(n, cfg, seed=2, max_atoms=max_atoms)
        nc, ec = capacities_for(graphs, n, dense_m=dense_m)
        return next(batch_iterator(graphs, n, nc, ec, dense_m=dense_m,
                                   in_cap=in_cap)), graphs

    @staticmethod
    def _flat(tree):
        return sorted(
            ((jax.tree_util.keystr(k), np.asarray(v))
             for k, v in jax.tree_util.tree_leaves_with_path(tree)),
            key=lambda kv: kv[0],
        )

    def _check(self, impl, window=0, in_cap=None):
        batch, _ = self._batch(in_cap=in_cap)
        base, fused = self._models(impl, window=window)
        variables = base.init(jax.random.key(0), batch)
        vf = fused.init(jax.random.key(0), batch)
        # identical parameter TREE and identical init VALUES: the fused
        # path declares the same fc_full/bn1 scopes, so checkpoints
        # restore across impls
        for (ka, a), (kb, b) in zip(self._flat(variables["params"]),
                                    self._flat(vf["params"])):
            assert ka == kb
            np.testing.assert_array_equal(a, b, err_msg=ka)

        def loss(model, params):
            out, mut = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                batch, train=True, mutable=["batch_stats"])
            return (out ** 2).sum(), mut["batch_stats"]

        (l_b, s_b), g_b = jax.value_and_grad(
            lambda p: loss(base, p), has_aux=True)(variables["params"])
        (l_f, s_f), g_f = jax.value_and_grad(
            lambda p: loss(fused, p), has_aux=True)(variables["params"])
        assert float(l_f) == pytest.approx(float(l_b), rel=1e-4)
        for (ka, a), (kb, b) in zip(self._flat(g_b), self._flat(g_f)):
            np.testing.assert_allclose(
                a, b, rtol=2e-3, atol=1e-4,
                err_msg=f"fused-cgconv[{impl}] grad {ka}")
        for (ka, a), (kb, b) in zip(self._flat(s_b), self._flat(s_f)):
            np.testing.assert_allclose(
                a, b, rtol=1e-4, atol=1e-5,
                err_msg=f"fused-cgconv[{impl}] stats {ka}")
        # eval (running stats — the serving path, one apply pass)
        out_b = base.apply(variables, batch, train=False)
        out_f = fused.apply(variables, batch, train=False)
        np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_b),
                                   rtol=1e-4, atol=1e-5)

    def test_xla_impl_matches_unfused(self):
        self._check("xla")

    def test_pallas_impl_matches_unfused(self):
        from cgnn_tpu.ops.pallas_cgconv import interpret_mode

        with interpret_mode():
            self._check("pallas")

    def test_pallas_bounded_window_matches_unfused(self):
        """The caller-bounded neighbor window (the perf configuration):
        window_width(max graph nodes) must reproduce the full-range
        gather exactly — an undersized bound would silently zero
        out-of-window neighbors, so coverage is pinned here."""
        from cgnn_tpu.ops.pallas_cgconv import interpret_mode, window_width

        with interpret_mode():
            self._check("pallas", window=window_width(6))

    def test_pallas_no_transpose_slots(self):
        """Forward-only batches (in_cap=0, the serving ladder) take the
        plain-gather backward; values must not care."""
        from cgnn_tpu.ops.pallas_cgconv import interpret_mode

        with interpret_mode():
            self._check("pallas", in_cap=0)

    def test_window_starts_cover_every_graph_span(self):
        """_win_starts x window_width coverage proof over adversarial
        node counts: every block's possible neighbor span (its rows'
        graph-mates) lies inside [ws[b], ws[b] + W)."""
        from cgnn_tpu.ops.pallas_cgconv import (
            _TN,
            _win_starts,
            window_width,
        )

        for maxg in (1, 5, 64, 129, 300):
            w = window_width(maxg)
            for n in (8, 120, 128, 136, 1000, 2048):
                nb = -(-n // _TN)
                n_pad = nb * _TN
                win = min(w, n_pad)
                ws = np.asarray(_win_starts(nb, n_pad, win))
                for b in range(nb):
                    lo = max(0, b * _TN - (maxg - 1))
                    hi = min(n, b * _TN + _TN + maxg - 1)
                    if hi - lo > win:
                        continue  # window itself smaller than span:
                        # excluded by the window>=window_width contract
                    assert ws[b] <= lo and hi <= ws[b] + win, (
                        maxg, n, b, ws[b], lo, hi, win)

    def test_fused_conv_byte_model_shape(self):
        """The graftaudit roofline budget helper stays self-consistent:
        model_bytes == 2 reads + 1 write (the one-round-trip claim the
        audit gates against)."""
        from cgnn_tpu.ops.pallas_cgconv import fused_conv_hbm_bytes

        m = fused_conv_hbm_bytes(1024, 12, 41, 64)
        assert m["model_bytes"] == 2 * m["reads_per_pass"] + m["write_bytes"]
        assert m["passes"] == 2


def test_windowed_gather_kernel_matches_take():
    """Pallas windowed one-hot gather (interpret mode on CPU): bit-exact
    vs jnp.take, including out-of-window padding self-loops -> zeros.
    (The kernel is a measured negative result for perf — see its module
    docstring — but stays correct and tested as a scaffold.)"""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    from cgnn_tpu.ops import pallas_gather

    nc, w = 256, 256
    rng = np.random.default_rng(0)
    nodes = jnp.asarray(rng.normal(size=(nc, 8)).astype(np.float32))
    # neighbors within a window starting at 0 for block 0, 128 for block 1
    nbr = jnp.asarray(
        np.concatenate([
            rng.integers(0, 128, size=128 * 4),
            rng.integers(128, 256, size=128 * 4),
        ]).astype(np.int32)
    )
    ws = jnp.asarray(np.array([0, 128], np.int32))
    with pltpu.force_tpu_interpret_mode():
        got = pallas_gather.windowed_gather(nodes, nbr, ws, w)
    ref = jnp.take(nodes, nbr, axis=0).reshape(nc, 4, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
