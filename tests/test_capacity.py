"""Device-resident capacity precheck (VERDICT r4 missing #3): a dataset
that cannot fit HBM must fall back LOUDLY to host-side pack-once staging
and still train — never an opaque XLA OOM mid-staging."""

import jax
import numpy as np
import pytest

from cgnn_tpu.data.compact import CompactSpec
from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
from cgnn_tpu.data.graph import capacities_for, pack_graphs
from cgnn_tpu.models import CrystalGraphConvNet
from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
from cgnn_tpu.train import loop as loop_mod
from cgnn_tpu.train.loop import check_device_resident_fit, fit

CFG = FeaturizeConfig(radius=6.0, max_num_nbr=12)


def _fit_scan(graphs, compact=None, epochs=2):
    train_g, val_g = graphs[:64], graphs[64:]
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=32,
                                dense_m=12)
    nc, ec = capacities_for(train_g, 16, dense_m=12, snug=True)
    state = create_train_state(
        model, pack_graphs(train_g[:4], nc, ec, 16, dense_m=12),
        make_optimizer(optim="adam", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([g.target for g in train_g])),
        rng=jax.random.key(0),
    )
    logs = []
    state, res = fit(
        state, train_g, val_g, epochs=epochs, batch_size=16,
        node_cap=nc, edge_cap=ec, seed=0, print_freq=0,
        scan_epochs=True, snug=True, dense_m=12, compact=compact,
        log_fn=lambda m: logs.append(str(m)),
    )
    return res, logs


def test_check_passes_when_budget_unknown(monkeypatch):
    monkeypatch.setattr(loop_mod, "device_hbm_budget", lambda *a: None)
    assert check_device_resident_fit(10**15)


def test_check_math(monkeypatch):
    monkeypatch.setattr(loop_mod, "device_hbm_budget", lambda *a: 1000)
    assert check_device_resident_fit(1000)
    assert not check_device_resident_fit(1001, log_fn=lambda m: None)
    # per-device share: 8 devices carry 1/8 each
    assert check_device_resident_fit(8000, n_devices=8,
                                     log_fn=lambda m: None)


def test_oversize_dataset_falls_back_and_trains(monkeypatch):
    graphs = load_synthetic_mp(96, CFG, seed=21)
    monkeypatch.setattr(loop_mod, "device_hbm_budget", lambda *a: 1024)
    res, logs = _fit_scan(graphs)
    assert res["staging"]["fallback"] == "host_pack_once"
    assert any("FALLING BACK" in m for m in logs)
    assert len(res["history"]) == 2
    assert np.isfinite(res["best"])


def test_oversize_compact_falls_back_with_expanded_steps(monkeypatch):
    graphs = load_synthetic_mp(96, CFG, seed=21)
    spec = CompactSpec.build(graphs, CFG.gdf(), dense_m=12)
    monkeypatch.setattr(loop_mod, "device_hbm_budget", lambda *a: 1024)
    res, logs = _fit_scan(graphs, compact=spec)
    assert res["staging"]["fallback"] == "host_pack_once"
    assert len(res["history"]) == 2
    assert np.isfinite(res["best"])


def test_fitting_dataset_keeps_scan_driver(monkeypatch):
    graphs = load_synthetic_mp(96, CFG, seed=21)
    monkeypatch.setattr(loop_mod, "device_hbm_budget",
                        lambda *a: 64 << 30)
    res, logs = _fit_scan(graphs)
    assert "fallback" not in res["staging"]
    assert "stack_stage_dispatch_s" in res["staging"]


def test_dp_oversize_falls_back_and_trains(monkeypatch):
    from cgnn_tpu.parallel import fit_data_parallel
    from cgnn_tpu.parallel.mesh import make_mesh

    graphs = load_synthetic_mp(64, CFG, seed=22)
    monkeypatch.setattr(loop_mod, "device_hbm_budget", lambda *a: 1024)
    model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2, h_fea_len=32,
                                dense_m=12)
    nc, ec = capacities_for(graphs, 4, dense_m=12, snug=True)
    state = create_train_state(
        model, pack_graphs(graphs[:4], nc, ec, 8, dense_m=12),
        make_optimizer(optim="adam", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([g.target for g in graphs])),
        rng=jax.random.key(0),
    )
    logs = []
    _, res = fit_data_parallel(
        state, graphs, graphs[:8], epochs=2, batch_size=4,
        node_cap=nc, edge_cap=ec, seed=0, mesh=make_mesh(4),
        snug=True, dense_m=12, scan_epochs=True,
        log_fn=lambda m: logs.append(str(m)),
    )
    assert any("FALLING BACK" in m for m in logs)
    assert len(res["history"]) == 2
    assert np.isfinite(res["best"])
