"""Model-layer tests: shapes, masking/padding invariance, gradients, forces."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
from cgnn_tpu.data.graph import pack_graphs
from cgnn_tpu.models import (
    CrystalGraphConvNet,
    ForceFieldCGCNN,
    MultiTaskHead,
    energy_and_forces,
)


@pytest.fixture(scope="module")
def graphs():
    return load_synthetic(8, FeaturizeConfig(radius=6.0), seed=7, keep_geometry=True)


def _make_batch(graphs, node_cap, edge_cap, graph_cap):
    return pack_graphs(graphs, node_cap, edge_cap, graph_cap)


class TestCrystalGraphConvNet:
    def test_forward_shapes_and_finite(self, graphs):
        batch = _make_batch(graphs, 128, 2048, 10)
        model = CrystalGraphConvNet(atom_fea_len=32, n_conv=2, h_fea_len=48)
        variables = model.init(jax.random.key(0), batch)
        out = model.apply(variables, batch)
        assert out.shape == (10, 1)
        assert np.all(np.isfinite(out))
        # padding graph slots are zeroed
        np.testing.assert_allclose(out[len(graphs):], 0.0)

    def test_padding_invariance(self, graphs):
        """More padding must not change real outputs (train & eval)."""
        small = _make_batch(graphs, 128, 2048, 10)
        big = _make_batch(graphs, 256, 4096, 16)
        model = CrystalGraphConvNet(atom_fea_len=32, n_conv=2, h_fea_len=48)
        variables = model.init(jax.random.key(0), small)
        for train in (False, True):
            kw = dict(train=train)
            if train:
                a, _ = model.apply(variables, small, mutable=["batch_stats"], **kw)
                b, _ = model.apply(variables, big, mutable=["batch_stats"], **kw)
            else:
                a = model.apply(variables, small, **kw)
                b = model.apply(variables, big, **kw)
            np.testing.assert_allclose(
                a[: len(graphs)], b[: len(graphs)], rtol=2e-4, atol=2e-5,
            )

    def test_batch_stats_padding_invariance(self, graphs):
        small = _make_batch(graphs, 128, 2048, 10)
        big = _make_batch(graphs, 256, 4096, 16)
        model = CrystalGraphConvNet(atom_fea_len=16, n_conv=1)
        variables = model.init(jax.random.key(0), small)
        _, sa = model.apply(variables, small, mutable=["batch_stats"], train=True)
        _, sb = model.apply(variables, big, mutable=["batch_stats"], train=True)
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(x, y, rtol=2e-4, atol=1e-5),
            sa, sb,
        )

    def test_gradients_finite(self, graphs):
        batch = _make_batch(graphs, 128, 2048, 10)
        model = CrystalGraphConvNet(atom_fea_len=16, n_conv=2)
        variables = model.init(jax.random.key(0), batch)

        def loss_fn(params):
            out, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                batch, train=True, mutable=["batch_stats"],
            )
            err = (out[:, 0] - batch.targets[:, 0]) * batch.graph_mask
            return jnp.sum(err**2) / jnp.sum(batch.graph_mask)

        grads = jax.grad(loss_fn)(variables["params"])
        leaves = jax.tree_util.tree_leaves(grads)
        assert leaves and all(np.all(np.isfinite(g)) for g in leaves)
        # gradients actually reach the embedding (graph structure is used)
        assert any(float(jnp.abs(g).max()) > 0 for g in leaves)

    def test_classification_log_probs(self, graphs):
        batch = _make_batch(graphs, 128, 2048, 10)
        model = CrystalGraphConvNet(
            atom_fea_len=16, n_conv=1, classification=True, num_classes=3,
            dropout_rate=0.1,
        )
        variables = model.init(jax.random.key(0), batch)
        out = model.apply(variables, batch)
        assert out.shape == (10, 3)
        # real rows are log-probs summing to 1
        probs = np.exp(out[: len(graphs)])
        np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-3)

    def test_multitask_head(self, graphs):
        batch = _make_batch(graphs, 128, 2048, 10)
        model = CrystalGraphConvNet(
            atom_fea_len=16, n_conv=1, head=MultiTaskHead(num_tasks=4, n_h=2)
        )
        variables = model.init(jax.random.key(0), batch)
        out = model.apply(variables, batch)
        assert out.shape == (10, 4)
        assert np.all(np.isfinite(out))

    def test_bfloat16_compute(self, graphs):
        batch = _make_batch(graphs, 128, 2048, 10)
        model = CrystalGraphConvNet(atom_fea_len=16, n_conv=1, dtype=jnp.bfloat16)
        variables = model.init(jax.random.key(0), batch)
        out = model.apply(variables, batch)
        assert out.dtype == jnp.float32  # outputs promoted back
        assert np.all(np.isfinite(out))


class TestForceField:
    def test_energy_and_forces(self, graphs):
        batch = _make_batch(graphs, 128, 2048, 10)
        model = ForceFieldCGCNN(atom_fea_len=16, n_conv=2, dmax=6.0)
        variables = model.init(jax.random.key(0), batch, batch.positions)
        energies, forces, stats = energy_and_forces(model, variables, batch)
        assert energies.shape == (10,)
        assert forces.shape == (128, 3)
        assert stats is None  # eval mode
        assert np.all(np.isfinite(energies)) and np.all(np.isfinite(forces))
        np.testing.assert_allclose(energies[len(graphs):], 0.0)
        # the force trunk is BatchNorm-free by design (train/eval force
        # consistency — see CGConv.use_batchnorm), so train mode returns an
        # empty stats collection and train == eval energies
        e_train, f_train, new_stats = energy_and_forces(
            model, variables, batch, train=True
        )
        assert jax.tree_util.tree_leaves(new_stats) == []
        np.testing.assert_allclose(e_train, energies, rtol=1e-5)
        np.testing.assert_allclose(f_train, forces, rtol=1e-5, atol=1e-6)

    def test_translation_invariance(self, graphs):
        """Rigid translation changes no distances -> forces sum to ~0."""
        batch = _make_batch(graphs, 128, 2048, 10)
        model = ForceFieldCGCNN(atom_fea_len=16, n_conv=1, dmax=6.0)
        variables = model.init(jax.random.key(0), batch, batch.positions)
        e0 = model.apply(variables, batch, batch.positions)
        shifted = batch.positions + jnp.array([1.7, -0.4, 2.2])
        e1 = model.apply(variables, batch, shifted)
        np.testing.assert_allclose(e0, e1, rtol=1e-4, atol=1e-4)
        _, forces, _ = energy_and_forces(model, variables, batch)
        # net force on each crystal vanishes by translation symmetry
        net = jax.ops.segment_sum(forces, batch.node_graph, 10)
        np.testing.assert_allclose(net, 0.0, atol=1e-3)
