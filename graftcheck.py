#!/usr/bin/env python
"""graftcheck: the repo's invariant linter (cgnn_tpu/analysis).

Every rule encodes an invariant a previous PR paid for in debugging
time — donation/aliasing safety (PR 1/2), the thread-shutdown contract
(PR 2/4), the zero-post-warmup-recompile pin (PR 3), counts-under-lock
scrapes (PR 6) — so the next refactor can't silently reintroduce the
incident. INVARIANTS.md is the catalog; ``--list-rules`` the summary.

Usage::

    python graftcheck.py                  # scan the repo, human output
    python graftcheck.py --ci             # concise; exit 1 on findings
    python graftcheck.py path/ other.py   # scan specific targets
    python graftcheck.py --list-rules

Exit status: 0 when clean, 1 when any finding survives its disables,
2 on usage errors. The CI ``static-analysis`` job runs ``--ci`` as a
BLOCKING step (tier1.yml) — intentional exceptions get
``# graftcheck: disable=RULE -- justification`` at the site, never a
weaker rule.

Scans ``cgnn_tpu/``, ``scripts/``, and the root entrypoints by
default. ``tests/`` is excluded (test code fakes locks and threads on
purpose; the fixture corpus under tests/analysis_fixtures is exercised
by tests/test_analysis.py, which also pins that THIS scan stays clean);
``__graft_entry__.py`` is the frozen seed harness.

Stdlib-only: runs without jax installed.
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _ROOT)

from cgnn_tpu.analysis.engine import (  # noqa: E402
    check_paths,
    default_targets,
)
from cgnn_tpu.analysis.rules import RULES  # noqa: E402


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: the repo scan set)")
    p.add_argument("--ci", action="store_true",
                   help="concise one-line-per-finding output + GitHub "
                        "error annotations; exit 1 on any finding")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES):
            print(f"{rule}\n    {RULES[rule]}\n")
        return 0

    if args.paths:
        findings = check_paths(args.paths, rel_to=os.getcwd())
        scanned = args.paths
    else:
        targets = default_targets(_ROOT)
        findings = check_paths(targets, rel_to=_ROOT)
        scanned = targets

    for f in findings:
        if args.ci:
            print(f"::error file={f.path},line={f.line},"
                  f"title={f.rule}::{f.message}")
        print(f.format(verbose=not args.ci))
        if args.ci:
            # one explanatory line even in concise mode: the fix-it
            # message is the point of the tool
            print(f"    {f.message}")

    n_files = len(scanned)
    if findings:
        print(f"\ngraftcheck: {len(findings)} finding(s) "
              f"({len({f.path for f in findings})} file(s)); see "
              f"INVARIANTS.md for the rule catalog and the disable "
              f"policy", file=sys.stderr)
        return 1
    print(f"graftcheck: clean ({n_files} target(s), "
          f"{len(RULES)} rules)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
