#!/usr/bin/env python
"""graftaudit: the compiled-program auditor (cgnn_tpu/analysis/program_audit).

graftcheck lints what the SOURCE says; graftaudit verifies what XLA
actually COMPILES. It lowers the repo's real entry programs — the train
step (plain / guard / telemetry-tapped / dense / DP / edge-sharded
where the backend allows), every (rung, staging form) predict program
in the warm shape ladder, and the compact expander — on abstract args,
then audits the artifacts: donation applied (GA-DONATION), no f64
anywhere (GA-F64), no host calls beyond the sanctioned telemetry tap
(GA-HOSTCALL), exact program identity across the ladder (GA-IDENT),
and a per-program FLOP/byte/temp-memory roofline ledger written to
AUDIT_LEDGER.json and gated as a budget: a key that disappears or a
lower-is-better key (bytes, peak temp memory, bytes/FLOP) regressing
>20% fails the run, mirroring scripts/bench_regress.py.

Usage::

    python graftaudit.py                  # audit + ledger, human output
    python graftaudit.py --ci             # concise; exit 1 on findings
    python graftaudit.py --no-compile     # StableHLO checks only (fast)
    python graftaudit.py --list-checks

Exit status: 0 clean, 1 findings or budget regressions, 2 usage
errors. The CI ``program-audit`` job runs ``--ci`` BLOCKING under
JAX_PLATFORMS=cpu (lowering needs no accelerator) and uploads the
fresh ledger as an artifact. The committed AUDIT_LEDGER.json is the
budget baseline: regenerate it deliberately (rerun this script in the
repo root and commit the diff), never to make CI green. Numeric
budget drift under a DIFFERENT jax version than the baseline's is
reported as a warning (XLA's cost model moves between releases);
structural drops fail regardless.
"""

from __future__ import annotations

import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _ROOT)

from cgnn_tpu.analysis.program_audit import (  # noqa: E402
    CHECKS,
    diff_ledgers,
    load_ledger,
    run_audit,
    write_ledger,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("--ci", action="store_true",
                   help="concise output + GitHub annotations; exit 1 on "
                        "any finding or budget regression")
    p.add_argument("--list-checks", action="store_true",
                   help="print the check catalog and exit")
    p.add_argument("--no-compile", action="store_true",
                   help="StableHLO-level checks only: skip XLA "
                        "compilation, the compiled-donation check, the "
                        "ledger, and the budget gate")
    p.add_argument("--ledger-out",
                   default=os.path.join(_ROOT, "AUDIT_LEDGER.json"),
                   help="where to write the fresh roofline ledger "
                        "(default: the repo baseline; deterministic "
                        "shapes make a clean re-run a no-op diff)")
    p.add_argument("--baseline",
                   default=os.path.join(_ROOT, "AUDIT_LEDGER.json"),
                   help="budget baseline to diff against (loaded BEFORE "
                        "--ledger-out is written)")
    p.add_argument("--threshold", type=float, default=0.2,
                   help="fractional increase of a lower-is-better key "
                        "that counts as a budget regression")
    args = p.parse_args(argv)

    if args.list_checks:
        for check in sorted(CHECKS):
            print(f"{check}\n    {CHECKS[check]}\n")
        return 0

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # deterministic device inventory for CPU audits: the mesh-sharded
    # predict programs (ISSUE 10) need >= 2 devices to lower, and the
    # committed ledger carries their GA-SHARD-budgeted rows — a
    # 1-device run would report them as DROPPED (a budget regression).
    # 8 virtual host devices matches CI's program-audit job and the
    # test suite's conftest; a user-provided XLA_FLAGS wins.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    baseline = None
    if not args.no_compile and os.path.exists(args.baseline):
        baseline = load_ledger(args.baseline)

    findings, ledger, programs = run_audit(compile=not args.no_compile)

    lowered = [p for p in programs if p.lowered is not None]
    skipped = {p.name: p.skip for p in programs if p.skip is not None}
    for name, reason in sorted(skipped.items()):
        print(f"graftaudit: SKIP {name}: {reason}")

    for f in findings:
        if args.ci:
            print(f"::error title={f.check}::{f.program}: {f.message}")
        print(f.format())

    rc = 1 if findings else 0
    if not args.no_compile:
        write_ledger(ledger, args.ledger_out)
        n_prog = len(ledger["programs"])
        print(f"graftaudit: ledger {args.ledger_out} "
              f"({n_prog} programs)")
        if baseline is not None:
            diff = diff_ledgers(baseline, ledger,
                                threshold=args.threshold)
            for row in diff["regressions"]:
                msg = (f"budget {row['key']}: {row.get('note', '')} "
                       f"(baseline {row['old']}, now {row['new']})")
                if args.ci:
                    print(f"::error title=audit budget::{msg}")
                print(f"graftaudit: {msg}", file=sys.stderr)
                rc = 1
            for row in diff["warnings"]:
                msg = (f"budget {row['key']} drifted under a different "
                       f"jax than the baseline's: {row.get('note', '')} "
                       f"(baseline {row['old']}, now {row['new']})")
                if args.ci:
                    print(f"::warning title=audit budget skew::{msg}")
                print(f"graftaudit: {msg}")
            if not diff["regressions"]:
                print(f"graftaudit: budgets ok "
                      f"({len(diff['rows'])} keys vs {args.baseline}"
                      f"{', version skew' if diff['version_skew'] else ''})")

    if rc:
        print(f"\ngraftaudit: {len(findings)} finding(s); see "
              f"INVARIANTS.md 'IR-level invariants' for the catalog",
              file=sys.stderr)
    else:
        print(f"graftaudit: clean ({len(lowered)} programs lowered, "
              f"{len(skipped)} backend skips, {len(CHECKS)} checks)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
