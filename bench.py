#!/usr/bin/env python
"""Benchmark: training throughput in structures/sec/chip (BASELINE.md).

Measures steady-state jitted train-step throughput of the flagship CGCNN
config (64-dim, 3 conv layers — BASELINE.json config #2 shape), with
``jax.block_until_ready`` fencing and compile excluded (SURVEY.md §6).

The PRIMARY metric uses an MP-like size distribution (lognormal, ~30 atoms
mean — Materials Project's actual regime), not tiny toy crystals; secondary
numbers cover the OC20 slab distribution (config #4) and the legacy
tiny-graph figure for cross-round comparability. Each workload reports
padding efficiency and an analytic-FLOP MFU estimate (matmul FLOPs /
measured time / chip peak).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}
where vs_baseline is value / 10_000 (BASELINE.json:5 north star).
"""

from __future__ import annotations

import json
import time

# bf16 matmul peak by device kind; conservative public numbers.
_PEAK_FLOPS = {
    "TPU v5 lite": 394e12,  # v5e
    "TPU v5": 459e12,       # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,  # trillium
}
_DEFAULT_PEAK = 394e12


def _flops_per_batch(batch, atom_dim, gauss_dim, f, h, n_conv, n_h) -> float:
    """Analytic matmul FLOPs for one fwd+bwd train step on real elements.

    Counts the MXU work only (dense layers; fwd 2mnk, bwd ~2x fwd). Segment
    ops / BN / elementwise are bandwidth-bound and excluded, as is padding
    (so MFU reflects useful work, discounted by padding efficiency).
    """
    import numpy as np

    n = float(np.asarray(batch.node_mask).sum())
    e = float(np.asarray(batch.edge_mask).sum())
    g = float(np.asarray(batch.graph_mask).sum())
    fwd = (
        2.0 * n * atom_dim * f                      # embedding
        + n_conv * 2.0 * e * (2 * f + gauss_dim) * (2 * f)  # fc_full per conv
        + 2.0 * g * f * h                           # conv_to_fc
        + (n_h - 1) * 2.0 * g * h * h               # hidden fcs
        + 2.0 * g * h                               # fc_out
    )
    return 3.0 * fwd  # fwd + ~2x bwd


def _bench_workload(graphs, batch_size, *, buckets=1, n_timed=30, label=""):
    """-> dict(structs_per_sec, mfu, node_eff, edge_eff, shapes)."""
    import jax
    import numpy as np

    from cgnn_tpu.data.graph import (
        PaddingStats,
        batch_iterator,
        bucketed_batch_iterator,
        capacities_for,
    )
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_train_step

    atom_dim = graphs[0].atom_fea.shape[1]
    gauss_dim = graphs[0].edge_fea.shape[1]
    f, h, n_conv, n_h = 64, 128, 3, 1

    stats = PaddingStats()
    if buckets > 1:
        batches = list(
            bucketed_batch_iterator(
                graphs, batch_size, buckets, stats=stats,
                rng=np.random.default_rng(0),
            )
        )
    else:
        node_cap, edge_cap = capacities_for(graphs, batch_size)
        batches = list(
            stats.wrap(batch_iterator(graphs, batch_size, node_cap, edge_cap))
        )
    real_per_batch = [float(np.asarray(b.graph_mask).sum()) for b in batches]
    flops_per_batch = [
        _flops_per_batch(b, atom_dim, gauss_dim, f, h, n_conv, n_h)
        for b in batches
    ]

    model = CrystalGraphConvNet(
        atom_fea_len=f, n_conv=n_conv, h_fea_len=h, dtype=jax.numpy.bfloat16
    )
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10_000])
    normalizer = Normalizer.fit(np.stack([g.target for g in graphs]))
    state = create_train_state(model, batches[0], tx, normalizer)

    train_step = jax.jit(make_train_step(), donate_argnums=0)
    device_batches = [jax.device_put(b) for b in batches]

    # warmup: one step per distinct shape (compiles), then one more
    seen = set()
    for i, b in enumerate(device_batches):
        shape = (b.node_capacity, b.edge_capacity)
        if shape not in seen:
            seen.add(shape)
            state, _ = train_step(state, b)
    state, _ = train_step(state, device_batches[0])
    jax.block_until_ready(state.params)

    # timed steady state: best of 3 rounds (the tunnel to the chip has
    # transient degraded phases; the best round reflects device capability)
    best_rate, best_mfu = 0.0, 0.0
    peak = _PEAK_FLOPS.get(jax.devices()[0].device_kind, _DEFAULT_PEAK)
    for _round in range(3):
        structures = flops = 0.0
        t0 = time.perf_counter()
        for i in range(n_timed):
            k = i % len(device_batches)
            state, _ = train_step(state, device_batches[k])
            structures += real_per_batch[k]
            flops += flops_per_batch[k]
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
        if structures / dt > best_rate:
            best_rate = structures / dt
            best_mfu = flops / dt / peak
    return {
        f"{label}structs_per_sec": round(best_rate, 1),
        f"{label}mfu": round(best_mfu, 4),
        f"{label}node_eff": round(stats.node_efficiency, 3),
        f"{label}edge_eff": round(stats.edge_efficiency, 3),
        f"{label}shapes": len(stats.shapes),
    }


def main() -> None:
    from cgnn_tpu.data.dataset import (
        FeaturizeConfig,
        load_synthetic,
        load_synthetic_mp,
        load_synthetic_oc20,
    )

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)

    # PRIMARY: MP-like size distribution (~30-atom lognormal), bucketed.
    # Configs picked by measured sweep (batch 256/512, buckets 2/3): b512
    # fills the MXU (50% MFU vs 32% at b256) and 6k structures amortize the
    # per-bucket tail batches that dominated padding at 2k.
    mp = _bench_workload(
        load_synthetic_mp(6144, cfg, seed=0), batch_size=512, buckets=3,
        n_timed=24,
    )
    # SECONDARY: OC20 slab distribution (config #4 large-graph regime)
    oc20 = _bench_workload(
        load_synthetic_oc20(512, cfg, seed=0), batch_size=128, buckets=2,
        n_timed=16, label="oc20_",
    )
    # SECONDARY: legacy tiny-graph figure (round-1 comparability)
    tiny = _bench_workload(
        load_synthetic(2048, cfg, seed=0), batch_size=512, n_timed=20,
        label="tiny_",
    )

    value = mp["structs_per_sec"]
    print(
        json.dumps(
            {
                "metric": "train_structures_per_sec_per_chip_mp_distribution",
                "value": value,
                "unit": "structures/sec/chip",
                "vs_baseline": round(value / 10_000.0, 4),
                "mfu": mp["mfu"],
                "padding_eff_nodes": mp["node_eff"],
                "padding_eff_edges": mp["edge_eff"],
                "compiled_shapes": mp["shapes"],
                "oc20": oc20,
                "tiny": tiny,
            }
        )
    )


if __name__ == "__main__":
    main()
