#!/usr/bin/env python
"""Benchmark: training throughput in structures/sec/chip (BASELINE.md).

Measures steady-state jitted train-step throughput of the flagship CGCNN
config (64-dim, 3 conv layers — BASELINE.json config #2 shape) with the
dense edge-slot layout (scatter-free aggregation, data/graph.py) and
honest fencing.

FENCING (important): timing rounds end with a ``float(metrics[...])``
VALUE FETCH — a true data dependency through the whole donated-state step
chain. ``jax.block_until_ready`` is NOT sufficient on this machine: under
the tunneled TPU runtime it returns before execution completes, which
overstated round-1/2 numbers by ~100x. Numbers from this file before
round 3 are not comparable.

The PRIMARY metric uses an MP-like size distribution (lognormal, ~30 atoms
mean — Materials Project's actual regime). Secondary numbers cover the
OC20 slab distribution (config #4) and the tiny-graph figure for
cross-round comparability. Each workload reports padding efficiency and an
analytic-FLOP MFU estimate against the v5e bf16 peak.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}
where vs_baseline is value / 10_000 (BASELINE.json:5 north star).
"""

from __future__ import annotations

import argparse
import json
import time

from cgnn_tpu.observe.metrics_io import jsonfinite

# bf16 matmul peak by device kind (dense bf16, not the int8 headline).
_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5": 459e12,       # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,  # trillium
}
_DEFAULT_PEAK = 197e12


def _flops_per_batch(batch, atom_dim, gauss_dim, f, h, n_conv, n_h) -> float:
    """Analytic matmul FLOPs for one fwd+bwd train step on real elements.

    Counts the MXU work only (dense layers; fwd 2mnk, bwd ~2x fwd). Segment
    ops / BN / elementwise are bandwidth-bound and excluded, as is padding
    (so MFU reflects useful work, discounted by padding efficiency).
    """
    import numpy as np

    n = float(np.asarray(batch.node_mask).sum())
    e = float(np.asarray(batch.edge_mask).sum())
    g = float(np.asarray(batch.graph_mask).sum())
    fwd = (
        2.0 * n * atom_dim * f                      # embedding
        + n_conv * 2.0 * e * (2 * f + gauss_dim) * (2 * f)  # fc_full per conv
        + 2.0 * g * f * h                           # conv_to_fc
        + (n_h - 1) * 2.0 * g * h * h               # hidden fcs
        + 2.0 * g * h                               # fc_out
    )
    return 3.0 * fwd  # fwd + ~2x bwd


def _bench_workload(
    graphs, batch_size, *, buckets=1, n_timed=40, label="", dense_m=None,
    snug=True, fused=None,
):
    """-> dict(structs_per_sec, mfu, node_eff, edge_eff, shapes, rounds_s)."""
    import jax
    import numpy as np

    from cgnn_tpu.data.graph import (
        PaddingStats,
        batch_iterator,
        bucketed_batch_iterator,
        capacities_for,
    )
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_train_step

    atom_dim = graphs[0].atom_fea.shape[1]
    gauss_dim = graphs[0].edge_fea.shape[1]
    f, h, n_conv, n_h = 64, 128, 3, 1

    stats = PaddingStats()
    edge_dtype = jax.numpy.bfloat16  # model computes bf16; store bf16
    if buckets > 1:
        batches = list(
            bucketed_batch_iterator(
                graphs, batch_size, buckets, stats=stats,
                rng=np.random.default_rng(0), dense_m=dense_m, snug=snug,
                edge_dtype=edge_dtype,
            )
        )
    else:
        node_cap, edge_cap = capacities_for(
            graphs, batch_size, dense_m=dense_m, snug=snug
        )
        batches = list(
            stats.wrap(
                batch_iterator(
                    graphs, batch_size, node_cap, edge_cap, dense_m=dense_m,
                    snug=snug, edge_dtype=edge_dtype,
                )
            )
        )
    real_per_batch = [float(np.asarray(b.graph_mask).sum()) for b in batches]
    atoms_per_batch = [float(np.asarray(b.node_mask).sum()) for b in batches]
    flops_per_batch = [
        _flops_per_batch(b, atom_dim, gauss_dim, f, h, n_conv, n_h)
        for b in batches
    ]

    model = CrystalGraphConvNet(
        atom_fea_len=f, n_conv=n_conv, h_fea_len=h,
        dtype=jax.numpy.bfloat16, dense_m=dense_m, fused_epilogue=fused,
    )
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10_000])
    normalizer = Normalizer.fit(np.stack([g.target for g in graphs]))
    state = create_train_state(model, batches[0], tx, normalizer)

    train_step = jax.jit(make_train_step(), donate_argnums=0)
    device_batches = [jax.device_put(b) for b in batches]

    # warmup: one step per distinct shape (compiles), fenced by value fetch
    seen = set()
    metrics = None
    for b in device_batches:
        shape = (b.node_capacity, b.edge_capacity)
        if shape not in seen:
            seen.add(shape)
            state, metrics = train_step(state, b)
    state, metrics = train_step(state, device_batches[0])
    float(metrics["loss_sum"])

    # timed steady state: best of 3 rounds, each fenced by a VALUE FETCH of
    # the final step's metrics (depends on the whole donated-state chain).
    # All three round times are reported (rounds_s) so cross-round BENCH
    # comparisons can see the tunnel's run-to-run variance, not just the
    # best (VERDICT r2 weak #7).
    best_rate, best_mfu, best_atoms = 0.0, 0.0, 0.0
    rounds_s = []
    peak = _PEAK_FLOPS.get(jax.devices()[0].device_kind, _DEFAULT_PEAK)
    for _round in range(3):
        structures = flops = atoms = 0.0
        t0 = time.perf_counter()
        for i in range(n_timed):
            k = i % len(device_batches)
            state, metrics = train_step(state, device_batches[k])
            structures += real_per_batch[k]
            atoms += atoms_per_batch[k]
            flops += flops_per_batch[k]
        float(metrics["loss_sum"])
        dt = time.perf_counter() - t0
        rounds_s.append(round(dt, 4))
        if structures / dt > best_rate:
            best_rate = structures / dt
            best_mfu = flops / dt / peak
            best_atoms = atoms / dt
    return {
        f"{label}structs_per_sec": round(best_rate, 1),
        # atoms/s is the cross-distribution invariant: a 113-atom OC20
        # slab is ~3.8x an MP structure's work, so structs/s alone makes
        # the OC20 number look artificially low vs the 10k MP north star
        f"{label}atoms_per_sec": round(best_atoms, 1),
        f"{label}mfu": round(best_mfu, 4),
        f"{label}node_eff": round(stats.node_efficiency, 3),
        f"{label}edge_eff": round(stats.edge_efficiency, 3),
        f"{label}shapes": len(stats.shapes),
        f"{label}rounds_s": rounds_s,
    }


def _bench_force_workload(graphs, batch_size, *, dense_m=None, n_timed=16,
                          label="force_"):
    """Force-task train-step throughput (config #5): frames/sec/chip.

    The step differentiates twice (positions inside, params outside);
    dense vs COO isolates the layout win on this workload
    (VERDICT r3 next-step #4)."""
    import jax
    import numpy as np

    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models.forcefield import ForceFieldCGCNN
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.force_step import make_force_train_step

    node_cap, edge_cap = capacities_for(graphs, batch_size, dense_m=dense_m,
                                        snug=True)
    batches = list(batch_iterator(graphs, batch_size, node_cap, edge_cap,
                                  dense_m=dense_m, snug=True))
    real = [float(np.asarray(b.graph_mask).sum()) for b in batches]
    model = ForceFieldCGCNN(atom_fea_len=64, n_conv=3, h_fea_len=64,
                            dmax=6.0, dense_m=dense_m)
    tx = make_optimizer(optim="sgd", lr=0.001, lr_milestones=[10**9])
    normalizer = Normalizer.fit(np.stack([g.target for g in graphs]))
    state = create_train_state(model, batches[0], tx, normalizer)
    step = jax.jit(make_force_train_step(), donate_argnums=0)
    device_batches = [jax.device_put(b) for b in batches]
    state, metrics = step(state, device_batches[0])
    float(metrics["loss_sum"])
    best = 0.0
    rounds_s = []
    for _ in range(3):
        t0 = time.perf_counter()
        s = 0.0
        for i in range(n_timed):
            k = i % len(device_batches)
            state, metrics = step(state, device_batches[k])
            s += real[k]
        float(metrics["loss_sum"])
        dt = time.perf_counter() - t0
        rounds_s.append(round(dt, 4))
        best = max(best, s / dt)
    return {f"{label}structs_per_sec": round(best, 1),
            f"{label}rounds_s": rounds_s}


# ---------------------------------------------------------------------------
# --ab: first-class interleaved A/B (the §6b/§8 protocol in ONE flag)
# ---------------------------------------------------------------------------

# flag -> how to build the train-step variants. Cross-session BENCH
# levels drift with the link (PERF.md §8), so the ONLY trustworthy
# comparison is alternating rounds in one process: one unrecorded
# burn-in round, then recorded rounds with the variant order rotated so
# monotonic drift within a round biases each variant equally; the
# artifact reports PAIRED per-round ratios, which is what kills the
# bench-link noise that muddied the r3->r5 trajectory.
AB_FLAGS = ("cgconv", "fused-epilogue", "transpose", "compact", "precision",
            "engine", "wire", "observe", "slo", "backfill", "cachepart")


def _ab_train_variants(flag: str, graphs, batch_size, buckets):
    """{name: dict(step, state, dev, structs)} for a train-step A/B."""
    import jax
    import numpy as np

    from cgnn_tpu.data.compact import (
        CompactSpec,
        compact_pack_fn,
        make_expander,
    )
    from cgnn_tpu.data.dataset import FeaturizeConfig
    from cgnn_tpu.data.graph import bucketed_batch_iterator
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.ops.pallas_cgconv import window_width
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_train_step

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    edge_dtype = jax.numpy.bfloat16
    on_tpu = jax.default_backend() == "tpu"

    def batches(pack_fn=None):
        return list(bucketed_batch_iterator(
            graphs, batch_size, buckets, rng=np.random.default_rng(0),
            dense_m=12, snug=True, edge_dtype=edge_dtype, pack_fn=pack_fn,
        ))

    full = batches()
    structs = [float(np.asarray(b.graph_mask).sum()) for b in full]
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9])
    targets = np.stack([np.array(g.target) for g in graphs])

    def model_for(**kw):
        return CrystalGraphConvNet(
            atom_fea_len=64, n_conv=3, h_fea_len=128,
            dtype=jax.numpy.bfloat16, dense_m=12, **kw,
        )

    def variant(model, dev, step_body=None, transpose=None):
        state = create_train_state(
            model, full[0], tx,
            Normalizer.fit(np.copy(targets)), rng=jax.random.key(0),
        )
        body = step_body or make_train_step()
        return {
            "dev": dev,
            "state": state,
            "step": jax.jit(body, donate_argnums=0),
            "transpose": transpose,
            "structs": structs,
        }

    dev_full = [jax.device_put(b) for b in full]
    base = model_for()
    if flag == "cgconv":
        # the whole-conv fused kernel (ops/pallas_cgconv.py): 'pallas'
        # on a TPU backend, the structured 'xla' twin elsewhere (the
        # kernels lower only on TPU — config.py backend rule)
        impl = "pallas" if on_tpu else "xla"
        fused = model_for(cgconv_impl=impl,
                          cgconv_window=window_width(
                              max(g.num_nodes for g in graphs)))
        return {
            "unfused": variant(base, dev_full),
            f"cgconv-{impl}": variant(fused, dev_full),
        }
    if flag == "fused-epilogue":
        impl = "pallas" if on_tpu else "xla"
        fused = model_for(fused_epilogue=impl)
        return {
            "unfused": variant(base, dev_full),
            f"epilogue-{impl}": variant(fused, dev_full),
        }
    if flag == "transpose":
        return {
            "linear_call": variant(base, dev_full,
                                   transpose="linear_call"),
            "custom_vjp": variant(base, dev_full,
                                  transpose="custom_vjp"),
        }
    if flag == "compact":
        spec = CompactSpec.build(graphs, cfg.gdf(), dense_m=12,
                                 edge_dtype=edge_dtype)
        compact = batches(compact_pack_fn(spec))
        expander = make_expander(spec)
        base_step = make_train_step()
        return {
            "full": variant(base, dev_full),
            "compact": variant(
                base, [jax.device_put(b) for b in compact],
                step_body=lambda s, b: base_step(s, expander(b)),
            ),
        }
    raise ValueError(f"--ab {flag}: unknown (valid: {AB_FLAGS})")


def _run_ab(flag: str, *, n: int, batch_size: int, buckets: int,
            rounds: int, steps: int) -> dict:
    import jax
    import numpy as np

    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic_mp
    from cgnn_tpu.ops import segment

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)
    graphs = load_synthetic_mp(n, cfg, seed=0, keep_geometry=flag == "wire")
    if flag == "precision":
        return _run_ab_precision(graphs, batch_size, rounds)
    if flag == "engine":
        return _run_ab_engine(graphs, batch_size, rounds)
    if flag == "wire":
        return _run_ab_wire(graphs, batch_size, rounds, cfg)
    if flag == "observe":
        return _run_ab_observe(graphs, batch_size, rounds)
    if flag == "slo":
        return _run_ab_slo(graphs, batch_size, rounds)
    if flag == "backfill":
        return _run_ab_backfill(graphs, batch_size, rounds)
    if flag == "cachepart":
        return _run_ab_cachepart(graphs, batch_size, rounds)
    variants = _ab_train_variants(flag, graphs, batch_size, buckets)

    def set_transpose(v):
        segment.set_transpose_impl(v.get("transpose") or "linear_call")

    # compile every variant first (per-shape warmup, value-fetch fenced)
    for name, v in variants.items():
        set_transpose(v)
        seen = set()
        metrics = None
        for b in v["dev"]:
            k = (b.node_capacity, b.edge_capacity)
            if k not in seen:
                seen.add(k)
                v["state"], metrics = v["step"](v["state"], b)
        v["state"], metrics = v["step"](v["state"], v["dev"][0])
        float(metrics["loss_sum"])

    names = list(variants)
    rows: list[dict] = []
    for r in range(-1, rounds):  # round -1 = discarded burn-in
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            v = variants[name]
            set_transpose(v)
            t0 = time.perf_counter()
            done = 0.0
            metrics = None
            for i in range(steps):
                k = i % len(v["dev"])
                v["state"], metrics = v["step"](v["state"], v["dev"][k])
                done += v["structs"][k]
            float(metrics["loss_sum"])  # value-fetch fence
            dt = time.perf_counter() - t0
            if r >= 0:
                rows.append({"round": r, "variant": name,
                             "structs_per_sec": round(done / dt, 1)})
    segment.set_transpose_impl("linear_call")
    return _ab_report(flag, names, rows, extra={
        "workload": f"MP-like n={n} batch={batch_size} buckets={buckets} "
                    f"dense two-tier bf16 train step",
        "device": str(jax.devices()[0].device_kind),
    })


def _run_ab_precision(graphs, batch_size, rounds) -> dict:
    """Inference-side A/B: the serving precision tiers' e2e forward rate
    (run_fast_inference over the ladder), interleaved per round."""
    import jax
    import numpy as np

    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.quantize import TIERS, build_tier_specs
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.infer import run_fast_inference
    from cgnn_tpu.train.step import make_predict_step

    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=12)
    ladder = plan_shape_set(graphs, batch_size, rungs=3, dense_m=12)
    state = create_train_state(
        model, ladder.pack_full([graphs[0]]),
        make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([np.array(g.target) for g in graphs])),
    )
    specs = build_tier_specs(model, TIERS)
    pstep = jax.jit(make_predict_step())
    states = {t: specs[t].state_for(state) for t in TIERS}
    kw = dict(shape_set=ladder, predict_step=pstep, pack_workers=0)
    for st in states.values():  # compile pass per tier
        run_fast_inference(st, graphs, batch_size, **kw)
    names = list(TIERS)
    rows = []
    for r in range(-1, rounds):
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            _, rate = run_fast_inference(states[name], graphs, batch_size,
                                         **kw)
            if r >= 0:
                rows.append({"round": r, "variant": name,
                             "structs_per_sec": round(rate, 1)})
    return _ab_report("precision", names, rows, extra={
        "workload": f"MP-like n={len(graphs)} ladder inference e2e "
                    f"(serve/quantize.py tiers)",
        "device": str(jax.devices()[0].device_kind),
    })


def _run_ab_engine(graphs, batch_size, rounds) -> dict:
    """Inference-side A/B of the two multi-device execution layers
    (ISSUE 10): the mesh single-dispatch engine vs the ISSUE-5
    thread-per-device DeviceSet round-robin, e2e over the serving
    ladder across ALL local devices, interleaved per round (the §6b/§8
    paired-ratio protocol). On a 1-device backend both engines
    degenerate to the single-device loop and the ratio honestly reads
    ~1 — run under ``--xla_force_host_platform_device_count=N`` (the
    dryrun pattern) or on a real multi-chip host for the verdict."""
    import jax
    import numpy as np

    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.infer import run_fast_inference
    from cgnn_tpu.train.step import make_predict_step

    devices = list(jax.local_devices())
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=12)
    ladder = plan_shape_set(graphs, batch_size, rungs=3, dense_m=12)
    state = create_train_state(
        model, ladder.pack_full([graphs[0]]),
        make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([np.array(g.target) for g in graphs])),
    )
    pstep = jax.jit(make_predict_step())
    variants = {
        "deviceset": dict(shape_set=ladder, predict_step=pstep,
                          pack_workers=0, devices=devices,
                          engine="threads"),
        "mesh": dict(shape_set=ladder, predict_step=pstep,
                     pack_workers=0, devices=devices, engine="mesh"),
    }
    for kw in variants.values():  # compile pass per engine
        run_fast_inference(state, graphs, batch_size, **kw)
    names = list(variants)
    rows = []
    for r in range(-1, rounds):  # round -1 = discarded burn-in
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            _, rate = run_fast_inference(state, graphs, batch_size,
                                         **variants[name])
            if r >= 0:
                rows.append({"round": r, "variant": name,
                             "structs_per_sec": round(rate, 1)})
    return _ab_report("engine", names, rows, extra={
        "workload": f"MP-like n={len(graphs)} ladder inference e2e, "
                    f"{len(devices)} device(s) "
                    f"(mesh single-dispatch vs DeviceSet threads)",
        "devices": len(devices),
        "device": str(jax.devices()[0].device_kind),
    })


def _run_ab_wire(graphs, batch_size, rounds, cfg) -> dict:
    """Inference-side A/B of the two wire formats (ISSUE 11): the
    in-program neighbor search over raw (positions, lattice, species)
    vs the host featurizer's packed ladder, e2e, interleaved per round
    (the §6b/§8 paired-ratio protocol). The raw leg covers the
    coverage-calibrated admitted subset (plan_raw_spec) and BOTH legs
    run the same structures so the ratio is apples-to-apples. This is
    the standing chip-side verdict for the raw default ('auto' keeps
    raw off on CPU, where the host IS the device and the verdict
    honestly reads < 1)."""
    import jax
    import numpy as np

    from cgnn_tpu.data.rawbatch import plan_raw_spec, raw_from_graph
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.infer import run_fast_inference, run_raw_inference
    from cgnn_tpu.train.step import make_predict_step

    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=12)
    raw_spec = plan_raw_spec(graphs, cfg.gdf(), cfg.radius, 12)
    ladder = plan_shape_set(graphs, batch_size, rungs=3, dense_m=12,
                            raw=raw_spec)
    pairs = [(g, raw_from_graph(g)) for g in graphs]
    pairs = [(g, r) for g, r in pairs
             if r is not None and ladder.admits_raw(r)]
    sub_graphs = [g for g, _ in pairs]
    sub_raws = [r for _, r in pairs]
    state = create_train_state(
        model, ladder.pack_full([graphs[0]]),
        make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([np.array(g.target) for g in graphs])),
    )
    pstep = jax.jit(make_predict_step(raw_expander=ladder.raw_expander()))

    def run_featurized():
        return run_fast_inference(state, sub_graphs, batch_size,
                                  shape_set=ladder, predict_step=pstep,
                                  pack_workers=0)[1]

    def run_raw():
        return run_raw_inference(state, sub_raws, ladder,
                                 predict_step=pstep)[1]

    variants = {"featurized": run_featurized, "raw": run_raw}
    for fn in variants.values():  # compile pass per wire
        fn()
    names = list(variants)
    rows = []
    for r in range(-1, rounds):  # round -1 = discarded burn-in
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            rate = variants[name]()
            if r >= 0:
                rows.append({"round": r, "variant": name,
                             "structs_per_sec": round(rate, 1)})
    return _ab_report("wire", names, rows, extra={
        "workload": f"MP-like n={len(sub_raws)} admitted of "
                    f"{len(graphs)} (coverage caps "
                    f"{raw_spec.to_meta()}), ladder inference e2e",
        "device": str(jax.devices()[0].device_kind),
    })


def _run_ab_observe(graphs, batch_size, rounds) -> dict:
    """Serving-path A/B of the cross-process observability layer
    (ISSUE 15): span ring + trace-parent propagation + flight recorder
    ON vs fully OFF, e2e rps/p99 through the in-process
    InferenceServer — the PERF.md §13 plane-cost methodology as
    interleaved same-process rounds (§6b/§8). Both variants serve the
    SAME requests through the same warmed programs; the delta is pure
    host bookkeeping (ring appends + recorder deque + one extra body
    key per request)."""
    import tempfile
    import threading

    import jax
    import numpy as np

    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.observe import FlightRecorder
    from cgnn_tpu.serve.server import InferenceServer
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_predict_step

    batch_size = min(batch_size, 64)
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=12)
    ladder = plan_shape_set(graphs, batch_size, rungs=3, dense_m=12)
    state = create_train_state(
        model, ladder.pack_full([graphs[0]]),
        make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([np.array(g.target) for g in graphs])),
    )
    pstep = jax.jit(make_predict_step())
    pool = [g for g in graphs if ladder.admits(g)][:512]

    def build(on: bool) -> InferenceServer:
        server = InferenceServer(
            state, ladder, predict_step=pstep, cache_size=0,
            max_queue=8192, pack_workers=0,
            trace_ring=65536 if on else 0,
            log_fn=lambda *a, **k: None,
        )
        server.warm(pool[0])
        server.start()
        if on:
            server.attach_flight_recorder(FlightRecorder(
                tempfile.mkdtemp(prefix="ab-observe-"), role="replica",
                registry=server.registry, tracer=server.tracer,
                log_fn=lambda *a, **k: None))
        return server

    servers = {"off": build(False), "observe-on": build(True)}
    n_req, n_threads = 2048, 8

    def drive(server: InferenceServer, on: bool):
        lat: list = []
        lock = threading.Lock()

        def client(ci: int) -> None:
            vals = []
            for i in range(n_req // n_threads):
                g = pool[(ci * 997 + i) % len(pool)]
                res = server.predict(
                    g, timeout_ms=120000.0,
                    trace_parent="att-ab-000001" if on else None)
                vals.append(res.latency_ms)
            with lock:
                lat.extend(vals)

        threads = [threading.Thread(target=client, args=(i,),
                                    name=f"ab-observe-client-{i}")
                   for i in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        return len(lat) / dt, float(np.percentile(np.asarray(lat), 99))

    names = list(servers)
    rows: list = []
    p99s: dict = {n: [] for n in names}
    for r in range(-1, rounds):  # round -1 = discarded burn-in
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            rate, p99 = drive(servers[name], name != "off")
            if r >= 0:
                rows.append({"round": r, "variant": name,
                             "structs_per_sec": round(rate, 1),
                             "p99_ms": round(p99, 3)})
                p99s[name].append(p99)
    for s in servers.values():
        s.drain(timeout_s=30.0)
    return _ab_report("observe", names, rows, extra={
        "workload": f"closed-loop serving, {n_req} requests x "
                    f"{n_threads} client threads per round, in-process "
                    f"InferenceServer batch={batch_size} (span ring + "
                    f"recorder + parent propagation on vs off)",
        "median_p99_ms": {n: round(float(np.median(v)), 3)
                          for n, v in p99s.items() if v},
        "device": str(jax.devices()[0].device_kind),
    })


def _run_ab_slo(graphs, batch_size, rounds) -> dict:
    """Serving-path A/B of the metrics-truth layer (ISSUE 16):
    mergeable histograms + SLO engine + embedded tsdb collector ON vs
    fully OFF, e2e rps/p99 through the in-process InferenceServer —
    the same interleaved same-process protocol as the observe A/B
    (§6b/§8). Both variants serve the SAME requests through the same
    warmed programs; the delta is pure host bookkeeping (three
    histogram observes + one SLO window record per request, plus one
    registry-snapshot heartbeat thread). The trace ring is OFF in both
    so the delta isolates this layer alone."""
    import threading

    import jax
    import numpy as np

    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.server import InferenceServer
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_predict_step

    batch_size = min(batch_size, 64)
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=12)
    ladder = plan_shape_set(graphs, batch_size, rungs=3, dense_m=12)
    state = create_train_state(
        model, ladder.pack_full([graphs[0]]),
        make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([np.array(g.target) for g in graphs])),
    )
    pstep = jax.jit(make_predict_step())
    pool = [g for g in graphs if ladder.admits(g)][:512]

    def build(on: bool) -> InferenceServer:
        server = InferenceServer(
            state, ladder, predict_step=pstep, cache_size=0,
            max_queue=8192, pack_workers=0, trace_ring=0,
            slo_layer=on, tsdb_interval_s=1.0,
            log_fn=lambda *a, **k: None,
        )
        server.warm(pool[0])
        server.start()
        return server

    servers = {"off": build(False), "slo-on": build(True)}
    n_req, n_threads = 2048, 8

    def drive(server: InferenceServer):
        lat: list = []
        lock = threading.Lock()

        def client(ci: int) -> None:
            vals = []
            for i in range(n_req // n_threads):
                g = pool[(ci * 997 + i) % len(pool)]
                res = server.predict(g, timeout_ms=120000.0)
                vals.append(res.latency_ms)
            with lock:
                lat.extend(vals)

        threads = [threading.Thread(target=client, args=(i,),
                                    name=f"ab-slo-client-{i}")
                   for i in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        return len(lat) / dt, float(np.percentile(np.asarray(lat), 99))

    names = list(servers)
    rows: list = []
    p99s: dict = {n: [] for n in names}
    for r in range(-1, rounds):  # round -1 = discarded burn-in
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            rate, p99 = drive(servers[name])
            if r >= 0:
                rows.append({"round": r, "variant": name,
                             "structs_per_sec": round(rate, 1),
                             "p99_ms": round(p99, 3)})
                p99s[name].append(p99)
    hist_count = int(servers["slo-on"].hists[
        "serve_latency_ms_hist"].count)
    for s in servers.values():
        s.drain(timeout_s=30.0)
    return _ab_report("slo", names, rows, extra={
        "workload": f"closed-loop serving, {n_req} requests x "
                    f"{n_threads} client threads per round, in-process "
                    f"InferenceServer batch={batch_size} (histograms + "
                    f"SLO engine + tsdb heartbeat on vs off; trace "
                    f"ring off in both)",
        "median_p99_ms": {n: round(float(np.median(v)), 3)
                          for n, v in p99s.items() if v},
        "slo_on_hist_count": hist_count,
        "device": str(jax.devices()[0].device_kind),
    })


def _run_ab_backfill(graphs, batch_size, rounds) -> dict:
    """Serving-path A/B of padding-slack backfill (ISSUE 19): the
    priority batcher with backfill ON vs OFF, e2e goodput through the
    in-process InferenceServer — the same interleaved same-process
    protocol as the observe/slo A/Bs (§6b/§8). The workload is the
    regime backfill exists for: a closed-loop interactive trickle keeps
    the head class pending (so its small flushes fire on the 10 ms wait
    budget, mostly padding), while a fixed scavenger backlog drains
    however the policy lets it. OFF, that backlog moves only through
    16x-aged scavenger flushes squeezed between interactive cuts; ON,
    it rides the interactive flushes' padded slots. Per round the clock
    runs until the WHOLE backlog is answered, so structs_per_sec is
    aggregate goodput for identical work, and the interactive p99 is
    recorded to show the head class paid nothing for it (backfill never
    delays or reshapes a head flush)."""
    import threading

    import jax
    import numpy as np

    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.server import InferenceServer
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_predict_step

    batch_size = min(batch_size, 64)
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=12)
    ladder = plan_shape_set(graphs, batch_size, rungs=3, dense_m=12)
    state = create_train_state(
        model, ladder.pack_full([graphs[0]]),
        make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([np.array(g.target) for g in graphs])),
    )
    pstep = jax.jit(make_predict_step())
    pool = [g for g in graphs if ladder.admits(g)][:512]

    def build(on: bool) -> InferenceServer:
        server = InferenceServer(
            state, ladder, predict_step=pstep, cache_size=0,
            max_queue=8192, pack_workers=0, trace_ring=0,
            max_wait_ms=10.0, backfill=on,
            log_fn=lambda *a, **k: None,
        )
        server.warm(pool[0])
        server.start()
        return server

    servers = {"no-backfill": build(False), "backfill": build(True)}
    n_scav, n_threads = 384, 4

    def drive(server: InferenceServer):
        futs = [server.submit(pool[(7 * i) % len(pool)],
                              timeout_ms=600000.0, klass="scavenger")
                for i in range(n_scav)]
        stop = threading.Event()
        lat: list = []
        lock = threading.Lock()

        def client(ci: int) -> None:
            vals = []
            while not stop.is_set():
                g = pool[(ci * 997 + len(vals)) % len(pool)]
                res = server.submit(
                    g, timeout_ms=600000.0,
                    klass="interactive").result(timeout=600.0)
                vals.append(res.latency_ms)
            with lock:
                lat.extend(vals)

        threads = [threading.Thread(target=client, args=(i,),
                                    name=f"ab-backfill-client-{i}")
                   for i in range(n_threads)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for f in futs:
            f.result(timeout=600.0)
        dt = time.perf_counter() - t0  # backlog-drained fence
        stop.set()
        for t in threads:
            t.join()
        return ((n_scav + len(lat)) / dt,
                float(np.percentile(np.asarray(lat), 99)))

    names = list(servers)
    rows: list = []
    p99s: dict = {n: [] for n in names}
    for r in range(-1, rounds):  # round -1 = discarded burn-in
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            rate, p99 = drive(servers[name])
            if r >= 0:
                rows.append({"round": r, "variant": name,
                             "structs_per_sec": round(rate, 1),
                             "interactive_p99_ms": round(p99, 3)})
                p99s[name].append(p99)
    stats_on = servers["backfill"].stats()
    stats_off = servers["no-backfill"].stats()
    for s in servers.values():
        s.drain(timeout_s=60.0)
    return _ab_report("backfill", names, rows, extra={
        "workload": f"open scavenger backlog of {n_scav} under a "
                    f"{n_threads}-thread closed-loop interactive "
                    f"trickle, in-process InferenceServer "
                    f"batch={batch_size} max_wait=10ms; per-round clock "
                    f"stops when the whole backlog is answered",
        "median_interactive_p99_ms": {
            n: round(float(np.median(v)), 3) for n, v in p99s.items() if v},
        "serve_padding_fill_share": stats_on["priority"][
            "padding_fill_share"],
        "backfilled_responses": stats_on["priority"][
            "backfilled_responses"],
        "recompiles_after_warm": {
            "backfill": stats_on["recompiles_after_warm"],
            "no-backfill": stats_off["recompiles_after_warm"]},
        "device": str(jax.devices()[0].device_kind),
    })


def _run_ab_cachepart(graphs, batch_size, rounds) -> dict:
    """Serving-path A/B of the one-fleet-cache layer (ISSUE 20):
    consistent-hash cache partitioning + single-flight coalescing vs
    the replicated baseline, over a 3-replica fleet of in-process
    InferenceServers with per-replica cache capacity FIXED.

    The workload is the regime partitioning exists for: a Zipf-drawn
    hot keyset WIDER than any one replica's cache (so the replicated
    fleet thrashes its three identical LRUs while the partitioned
    fleet's union holds everything), punctuated by cold-key stampede
    BURSTS (many concurrent requests for one never-seen structure —
    the thundering herd that coalescing collapses to one compute).
    Routing is the only difference: 'replicated' round-robins with
    per-replica single-flight OFF (the pre-ISSUE-20 fleet), 'cachepart'
    sends each fingerprint to its CacheRing owner with single-flight
    ON. The headline is the fleet-wide EFFECTIVE hit ratio — answers
    served without a fresh model compute, (cache_hits + coalesced) /
    requests — and the bench hard-asserts zero duplicate in-flight
    misses under the partitioned stampede and bit-identical prediction
    bytes per key across both variants."""
    import hashlib
    import threading

    import jax
    import numpy as np

    from cgnn_tpu.fleet.cachering import CacheRing
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.serve.cache import structure_fingerprint
    from cgnn_tpu.serve.server import InferenceServer
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_predict_step

    batch_size = min(batch_size, 64)
    model = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                dense_m=12)
    ladder = plan_shape_set(graphs, batch_size, rungs=3, dense_m=12)
    state = create_train_state(
        model, ladder.pack_full([graphs[0]]),
        make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10**9]),
        Normalizer.fit(np.stack([np.array(g.target) for g in graphs])),
    )
    pstep = jax.jit(make_predict_step())
    pool = [g for g in graphs if ladder.admits(g)][:512]

    # the keyspace: a hot Zipf set wider than one replica's cache but
    # narrower than the fleet's union, plus a disjoint cold-key stream
    # for the stampede bursts (each burst key is seen exactly once per
    # variant — a guaranteed herd on a guaranteed miss)
    n_fleet, cache_cap, hot_n = 3, 64, 96
    hot = pool[:hot_n]
    cold = pool[hot_n:]
    hot_fps = [structure_fingerprint(g) for g in hot]
    cold_fps = [structure_fingerprint(g) for g in cold]
    zipf_p = np.array([1.0 / (i + 1) ** 1.1 for i in range(hot_n)])
    zipf_p /= zipf_p.sum()
    n_bursts, burst_fan, n_singles = 8, 24, 128

    def build_fleet(single_flight: bool) -> list:
        fleet = []
        for _ in range(n_fleet):
            s = InferenceServer(
                state, ladder, predict_step=pstep, cache_size=cache_cap,
                max_queue=8192, pack_workers=0, trace_ring=0,
                max_wait_ms=5.0, single_flight=single_flight,
                log_fn=lambda *a, **k: None,
            )
            s.warm(pool[0])
            s.start()
            fleet.append(s)
        return fleet

    ring = CacheRing(range(n_fleet))
    fleets = {"replicated": build_fleet(False),
              "cachepart": build_fleet(True)}
    rr = {"n": 0}

    def route(name: str, g, fp: str):
        # the ONLY difference between the variants: who gets the key.
        # The fingerprint is hashed once here at the 'edge' and rides
        # the submit (satellite: hash once per request)
        if name == "cachepart":
            server = fleets[name][ring.owner(fp)]
        else:
            server = fleets[name][rr["n"] % n_fleet]
            rr["n"] += 1
        return server.submit(g, timeout_ms=600000.0, fingerprint=fp)

    def fleet_counts(name: str) -> dict:
        tot: dict = {}
        for s in fleets[name]:
            for k, v in s.stats()["counts"].items():
                tot[k] = tot.get(k, 0) + v
        return tot

    preds: dict = {n: {} for n in fleets}

    def note(name, fp, fut):
        row = np.asarray(fut.result(timeout=600.0).prediction)
        preds[name].setdefault(fp, row)

    def drive(name: str, r: int, zipf_draws, burst_ids) -> tuple:
        c0 = fleet_counts(name)
        t0 = time.perf_counter()
        for b in burst_ids:
            g, fp = cold[b], cold_fps[b]
            futs = [route(name, g, fp) for _ in range(burst_fan)]
            for f in futs:
                note(name, fp, f)
        for k in zipf_draws:
            note(name, hot_fps[k], route(name, hot[k], hot_fps[k]))
        dt = time.perf_counter() - t0
        c1 = fleet_counts(name)
        d = {k: c1.get(k, 0) - c0.get(k, 0) for k in c1}
        served = n_bursts * burst_fan + len(zipf_draws)
        eff = (d.get("cache_hits", 0)
               + d.get("cache_coalesced", 0)) / max(d["requests"], 1)
        return served / dt, eff

    names = list(fleets)
    rows: list = []
    effs: dict = {n: [] for n in names}
    rng = np.random.default_rng(0)
    for r in range(-1, rounds):  # round -1 = discarded burn-in
        # one draw per round, shared by both variants (paired rounds)
        zipf_draws = rng.choice(hot_n, size=n_singles, p=zipf_p)
        lo = (r + 1) * n_bursts
        burst_ids = [b % len(cold) for b in range(lo, lo + n_bursts)]
        order = names[r % len(names):] + names[: r % len(names)]
        for name in order:
            rate, eff = drive(name, r, zipf_draws, burst_ids)
            if r >= 0:
                rows.append({"round": r, "variant": name,
                             "structs_per_sec": round(rate, 1),
                             "effective_hit_ratio": round(eff, 4)})
                effs[name].append(eff)
    # ---- acceptance gates (ISSUE 20) ----
    cp, repl = fleet_counts("cachepart"), fleet_counts("replicated")
    # single-flight ON: ZERO duplicate in-flight misses under stampede
    assert cp.get("cache_dup_misses", 0) == 0, cp
    # and the baseline PROVES the stampede was real (herds did overlap)
    assert repl.get("cache_dup_misses", 0) > 0, repl
    # owner-affinity answers are bit-exact vs the baseline, key by key
    diffs = [float(np.max(np.abs(preds["cachepart"][fp]
                                 - preds["replicated"][fp])))
             for fp in preds["cachepart"]]
    assert max(diffs) == 0.0, f"responses not bit-exact: {max(diffs)}"
    # hashing micro-bench (satellite: the sha1 -> blake2b swap)
    hash_us = {}
    for label, hasher in (("sha1", hashlib.sha1),
                          ("blake2b", lambda: hashlib.blake2b(
                              digest_size=20))):
        t0 = time.perf_counter()
        for g in pool:
            h = hasher()
            for arr in (g.atom_fea, g.edge_fea, g.centers, g.neighbors):
                a = np.ascontiguousarray(arr)
                h.update(str(a.shape).encode())
                h.update(str(a.dtype).encode())
                h.update(a.tobytes())
            h.hexdigest()
        hash_us[label] = round(
            (time.perf_counter() - t0) / len(pool) * 1e6, 2)
    med_eff = {n: float(np.median(v)) for n, v in effs.items()}
    for fleet in fleets.values():
        for s in fleet:
            s.drain(timeout_s=60.0)
    return _ab_report("cachepart", names, rows, extra={
        "workload": f"{n_fleet}-replica fleet, per-replica cache "
                    f"capacity {cache_cap}; per round {n_bursts} "
                    f"cold-key stampede bursts x{burst_fan} concurrent "
                    f"+ {n_singles} Zipf(1.1) singles over a "
                    f"{hot_n}-key hot set; routing is the only "
                    f"difference (round-robin+no-single-flight vs "
                    f"ring-owner+single-flight)",
        "median_effective_hit_ratio": {
            n: round(v, 4) for n, v in med_eff.items()},
        "effective_hit_ratio_gain": round(
            med_eff["cachepart"] / max(med_eff["replicated"], 1e-9), 2),
        "dup_misses": {"replicated": repl.get("cache_dup_misses", 0),
                       "cachepart": cp.get("cache_dup_misses", 0)},
        "coalesced": {"replicated": repl.get("cache_coalesced", 0),
                      "cachepart": cp.get("cache_coalesced", 0)},
        "bitexact_keys_checked": len(diffs),
        "max_abs_pred_diff": max(diffs),
        "fingerprint_hash_us": hash_us,
        "fingerprint_blake2b_speedup": round(
            hash_us["sha1"] / max(hash_us["blake2b"], 1e-9), 2),
        "cache_ring": ring.stats(),
        "device": str(jax.devices()[0].device_kind),
    })


def _ab_report(flag, names, rows, extra) -> dict:
    import numpy as np

    def rates(name):
        return [e["structs_per_sec"] for e in rows if e["variant"] == name]

    base = names[0]
    med = {n: float(np.median(rates(n))) for n in names}
    # PAIRED per-round deltas vs the first variant: each round's tunnel
    # conditions hit all variants, so the ratio is noise-robust where
    # the absolute levels are not (§8)
    paired = {
        n: [round(b / a, 4) for a, b in zip(rates(base), rates(n))]
        for n in names[1:]
    }
    return {
        "metric": f"bench_ab_{flag.replace('-', '_')}",
        "variants": names,
        "rounds": rows,
        "median_structs_per_sec": med,
        "paired_round_ratios_vs_" + base: paired,
        "median_ratio_vs_" + base: {
            n: round(float(np.median(p)), 4) for n, p in paired.items()
        },
        "fencing": "value-fetch per round; burn-in discarded; order "
                   "rotated per round",
        **extra,
    }


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--ab", choices=AB_FLAGS, default=None,
                   help="interleaved same-process A/B of one flag's "
                        "variants (alternating rounds, burn-in "
                        "discarded, paired per-round deltas — the "
                        "PERF.md §6b/§8 protocol as one command); "
                        "prints the A/B JSON line INSTEAD of the bench")
    p.add_argument("--ab-rounds", type=int, default=4)
    p.add_argument("--ab-steps", type=int, default=40)
    p.add_argument("--ab-n", type=int, default=8192)
    p.add_argument("--ab-batch-size", type=int, default=512)
    p.add_argument("--ab-buckets", type=int, default=3)
    args = p.parse_args(argv)
    if args.ab is not None:
        out = _run_ab(args.ab, n=args.ab_n, batch_size=args.ab_batch_size,
                      buckets=args.ab_buckets, rounds=args.ab_rounds,
                      steps=args.ab_steps)
        print(json.dumps(jsonfinite(out)))
        return

    from cgnn_tpu.data.dataset import (
        FeaturizeConfig,
        load_synthetic,
        load_synthetic_mp,
        load_synthetic_oc20,
    )

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)

    # PRIMARY: MP-like size distribution (~30-atom lognormal), dense
    # layout, bucketed. Batch/bucket re-swept under snug packing (r3:
    # 512/3b 47.5k, 768/3b 41.6k, 1024/3b 40.1k structs/s — per-slot
    # cost dominates, so tighter buckets beat bigger batches).
    # keep_geometry: the ISSUE-11 raw-wire leg converts these back to
    # wire form (packed shapes unchanged; the extra host fields are
    # never staged by the other legs)
    mp_graphs = load_synthetic_mp(8192, cfg, seed=0, keep_geometry=True)
    mp = _bench_workload(
        mp_graphs, batch_size=512, buckets=3, n_timed=40, dense_m=12,
    )
    # SECONDARY: OC20 slab distribution (config #4 large-graph regime)
    oc20 = _bench_workload(
        load_synthetic_oc20(768, cfg, seed=0), batch_size=128, buckets=2,
        n_timed=24, label="oc20_", dense_m=12,
    )
    # SECONDARY: tiny-graph figure (round-1 comparability; honest fencing)
    tiny = _bench_workload(
        load_synthetic(4096, cfg, seed=0), batch_size=1024, n_timed=30,
        label="tiny_", dense_m=12,
    )
    # SECONDARY: flat-COO layout at the same MP workload (the layout win)
    flat = _bench_workload(
        mp_graphs, batch_size=512, buckets=3, n_timed=20, label="coo_",
    )
    # NOTE: the fused BN1->gate->mask->sum epilogue (--fused-epilogue,
    # ops/fused_epilogue.py) measured 5-20% SLOWER than the unfused chain
    # in same-process interleaved rounds (PERF.md 6b) and is NOT benched
    # here; reproduce with scripts/scan_cost.py --fused-epilogue xla|pallas
    # SECONDARY: force task (config #5) — COO vs dense layout
    from cgnn_tpu.data.dataset import load_trajectory

    md_graphs = load_trajectory(1024, cfg, seed=0, num_atoms=16,
                                jitter=0.05)
    force_coo = _bench_force_workload(md_graphs, 256, label="force_coo_")
    force_dense = _bench_force_workload(md_graphs, 256, dense_m=12,
                                        label="force_dense_")

    # production epoch-driver mode (VERDICT r3 #5): the ScanEpochDriver at
    # bench scale, per-epoch metric semantics (one link sync per epoch —
    # SCAN_COST.json has the full breakdown incl. the per-step production
    # driver, which the scan driver beats ~4x on this tunneled link)
    import time as _time

    import jax
    import numpy as np

    from cgnn_tpu.data.graph import bucketed_batch_iterator
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import ScanEpochDriver
    from cgnn_tpu.train.step import make_eval_step, make_train_step

    eb = list(bucketed_batch_iterator(
        mp_graphs, 512, 3, shuffle=True, rng=np.random.default_rng(0),
        dense_m=12, snug=True, edge_dtype=jax.numpy.bfloat16,
    ))
    estructs = sum(float(np.asarray(b.graph_mask).sum()) for b in eb)
    emodel = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                 dtype=jax.numpy.bfloat16, dense_m=12)
    estate = create_train_state(
        emodel, eb[0], make_optimizer(optim="sgd", lr=0.01,
                                      lr_milestones=[10**9]),
        Normalizer.fit(np.stack([g.target for g in mp_graphs])),
    )
    edrv = ScanEpochDriver(make_train_step(), make_eval_step(), eb, [],
                           np.random.default_rng(0))
    estate = edrv.warm(estate)  # keeps first-compiles out of timed epochs
    et0 = _time.perf_counter()
    for _ in range(4):
        estate, _, _ = edrv.run_epoch_pair(estate, first=False)
    epoch_rate = estructs * 4 / (_time.perf_counter() - et0)

    # inference throughput (predict.py fast path, VERDICT r4 weak #5),
    # two numbers with different denominators:
    # - device rate: forward steps over pre-staged batches (the train
    #   bench's own convention — packing excluded), value-fetch fenced
    # - end-to-end rate: run_fast_inference including host packing and
    #   the stacked fetch (what a cold `predict.py` run sees; host
    #   packing dominated it at scale until ISSUE 4 — the breakdown is
    #   PERF.md §7, the fix §11). Measured over predict.py's DEFAULT
    #   path FOR THIS BACKEND: on an accelerator that is the serving
    #   shape ladder, compact-staged, packed by the parallel ingest
    #   pipeline (data/pipeline.py); on a CPU backend predict.py's
    #   `--compact auto` keeps both off (the device IS the host — §11
    #   measured compact e2e SLOWER there), so the bench mirrors that
    #   and the headline never reports a config predict.py wouldn't run.
    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train.infer import run_fast_inference
    from cgnn_tpu.train.step import make_predict_step

    istate = create_train_state(
        emodel, eb[0], make_optimizer(optim="sgd", lr=0.01,
                                      lr_milestones=[10**9]),
        Normalizer.fit(np.stack([g.target for g in mp_graphs])),
    )
    on_accel = jax.default_backend() != "cpu"
    ispec = (CompactSpec.build(mp_graphs, cfg.gdf(), dense_m=12,
                               edge_dtype=jax.numpy.bfloat16)
             if on_accel else None)
    # ONE jitted step for all passes: the expander makes it accept BOTH
    # staging forms (compact e2e batches AND the device-rate GraphBatches)
    pstep = jax.jit(make_predict_step(
        make_expander(ispec) if ispec is not None else None))
    ladder = plan_shape_set(mp_graphs, 512, rungs=3, dense_m=12,
                            edge_dtype=jax.numpy.bfloat16, compact=ispec)
    infer_kw = dict(shape_set=ladder, predict_step=pstep,
                    pack_workers=4 if on_accel else 0)
    run_fast_inference(istate, mp_graphs, 512, **infer_kw)  # compile pass
    _, infer_e2e = run_fast_inference(istate, mp_graphs, 512, **infer_kw)
    # device-parallel leg (ISSUE 5): the SAME ladder/step/pack config
    # round-robined across resolve_devices('auto') — measured in the same
    # session as the single-device number (§8's in-session-ratio rule:
    # cross-session levels drift with the link; the ratio is the result).
    # On a CPU backend 'auto' is one device by design, so the two legs
    # coincide and the ratio honestly reads ~1.
    from cgnn_tpu.serve.devices import resolve_devices

    inf_devices = resolve_devices("auto")
    # engine="threads" pins the ISSUE-5 DeviceSet layer this key has
    # always measured; the mesh engine (ISSUE 10, the new default for
    # multi-device sets) gets its own leg + in-session ratio below
    mdev_kw = dict(infer_kw, devices=inf_devices, engine="threads")
    run_fast_inference(istate, mp_graphs, 512, **mdev_kw)  # per-dev compile
    _, infer_e2e_mdev = run_fast_inference(istate, mp_graphs, 512, **mdev_kw)
    # mesh single-dispatch engine over the SAME devices/ladder/session:
    # one batch-sharded jitted dispatch covers the whole set (§8's
    # in-session-ratio rule; on CPU 'auto' is one device, the engines
    # coincide, and the ratio honestly reads ~1)
    mesh_kw = dict(infer_kw, devices=inf_devices, engine="mesh")
    run_fast_inference(istate, mp_graphs, 512, **mesh_kw)  # compile pass
    _, infer_e2e_mesh = run_fast_inference(istate, mp_graphs, 512, **mesh_kw)
    # the pre-ISSUE-4 serial full-fidelity path, for the same-session
    # before/after (cross-session BENCH levels drift with the link, §8)
    serial_kw = dict(buckets=3, dense_m=12, snug=True,
                     edge_dtype=jax.numpy.bfloat16, predict_step=pstep)
    run_fast_inference(istate, mp_graphs, 512, **serial_kw)  # compile pass
    _, infer_e2e_serial = run_fast_inference(istate, mp_graphs, 512,
                                             **serial_kw)

    # quantized serving tiers (ISSUE 9, serve/quantize.py): the SAME
    # params through the bf16-activation and int8-weight programs, e2e
    # over the same ladder in the same session (§8's in-session-ratio
    # rule). The flagship bench model already computes bf16, so the
    # bf16 tier isolates the activation dtype and the int8 tier adds
    # the 4x weight-byte cut; on a CPU backend the low-precision tiers
    # run EMULATED (slower — honest numbers, the HBM/MXU win needs the
    # accelerator; MAE parity is gated by scripts/quant_parity.py).
    from cgnn_tpu.serve.quantize import build_tier_specs

    tier_specs = build_tier_specs(emodel, ("bf16", "int8"))
    infer_tier = {}
    for tier in ("bf16", "int8"):
        tstate = tier_specs[tier].state_for(istate)
        run_fast_inference(tstate, mp_graphs, 512, **infer_kw)  # compile
        _, rate = run_fast_inference(tstate, mp_graphs, 512, **infer_kw)
        infer_tier[tier] = rate

    # raw wire (ISSUE 11): the in-program neighbor search over
    # (positions, lattice, species), same session as the featurized e2e
    # legs (§8's in-session-ratio rule). Coverage-calibrated caps
    # (plan_raw_spec): the admitted share rides raw, the tail the
    # featurized path — both reported. On CPU the ratio honestly reads
    # << 1 (the host IS the device and pays the padded candidate
    # matrix); the chip verdict is `bench.py --ab wire`.
    from cgnn_tpu.data.rawbatch import plan_raw_spec, raw_from_graph
    from cgnn_tpu.train.infer import run_raw_inference

    raw_spec_b = plan_raw_spec(mp_graphs, cfg.gdf(), cfg.radius, 12)
    ladder_raw = plan_shape_set(mp_graphs, 512, rungs=3, dense_m=12,
                                edge_dtype=jax.numpy.bfloat16,
                                raw=raw_spec_b)
    raw_pairs = [(g, raw_from_graph(g)) for g in mp_graphs]
    raw_pairs = [(g, r) for g, r in raw_pairs
                 if r is not None and ladder_raw.admits_raw(r)]
    raw_items = [r for _, r in raw_pairs]
    rstep = jax.jit(make_predict_step(
        raw_expander=ladder_raw.raw_expander()))
    run_raw_inference(istate, raw_items, ladder_raw,
                      predict_step=rstep)  # compile pass
    _, infer_e2e_raw = run_raw_inference(istate, raw_items, ladder_raw,
                                         predict_step=rstep)
    wire_raw_bytes = sum(r.wire_nbytes for r in raw_items)
    wire_feat_bytes = sum(
        g.atom_fea.nbytes + g.edge_fea.nbytes + g.centers.nbytes
        + g.neighbors.nbytes for g, _ in raw_pairs
    )

    ib = list(bucketed_batch_iterator(
        mp_graphs, 512, 3, rng=np.random.default_rng(0), dense_m=12,
        in_cap=0, snug=True, edge_dtype=jax.numpy.bfloat16,
    ))
    ireal = [float(np.asarray(b.graph_mask).sum()) for b in ib]
    idev = [jax.device_put(b) for b in ib]
    out = None
    for b in idev:  # compile per shape
        out = pstep(istate, b)
    float(out[0, 0])
    infer_dev = 0.0
    for _ in range(3):
        it0 = _time.perf_counter()
        done = 0.0
        for _rep in range(3):
            for k, b in enumerate(idev):
                out = pstep(istate, b)
                done += ireal[k]
        float(out[0, 0])
        infer_dev = max(infer_dev, done / (_time.perf_counter() - it0))

    value = mp["structs_per_sec"]
    print(
        json.dumps(jsonfinite(
            {
                "metric": "train_structures_per_sec_per_chip_mp_distribution",
                "value": value,
                "unit": "structures/sec/chip",
                "vs_baseline": round(value / 10_000.0, 4),
                "atoms_per_sec": mp["atoms_per_sec"],
                "mfu": mp["mfu"],
                # production ScanEpochDriver at bench scale, per-epoch
                # metric semantics. The ratio's denominator is THIS
                # bench's best-of-3 step rate — a different (stricter)
                # baseline than SCAN_COST.json's sync-free in-process
                # loop, which is why the two artifacts' ratios differ by
                # construction (r4 weak #4); the key now names its
                # denominator so the same-named-quantity ambiguity is
                # gone. The physical residual is one link round trip per
                # epoch either way (SCAN_COST.json breakdown).
                "epoch_driver_structs_per_sec": round(epoch_rate, 1),
                "epoch_driver_vs_best_step_bench": round(
                    epoch_rate / max(value, 1.0), 3),
                # forward-only inference (predict.py fast path): device
                # rate over staged batches (train-bench convention) and
                # the end-to-end rate incl. host packing
                "inference_structs_per_sec": round(infer_dev, 1),
                "inference_e2e_structs_per_sec": round(infer_e2e, 1),
                # device-parallel forward path (ISSUE 5): same config
                # dispatched across all 'auto' devices, same session as
                # the single-device e2e above (§8 in-session-ratio rule)
                "inference_devices": len(inf_devices),
                "inference_e2e_multidev_structs_per_sec": round(
                    infer_e2e_mdev, 1),
                "inference_multidev_vs_single": round(
                    infer_e2e_mdev / max(infer_e2e, 1.0), 3),
                # mesh single-dispatch engine (ISSUE 10): same devices,
                # same session — the in-session engine ratio is the
                # result (>= 1.0 expected on accelerator backends;
                # report-only on CPU where 'auto' is one device)
                "inference_e2e_mesh_structs_per_sec": round(
                    infer_e2e_mesh, 1),
                "inference_mesh_vs_deviceset": round(
                    infer_e2e_mesh / max(infer_e2e_mdev, 1.0), 3),
                # the pre-ISSUE-4 serial full-fidelity ingest, same
                # session (the honest before/after; PERF.md §11)
                "inference_e2e_serial_structs_per_sec": round(
                    infer_e2e_serial, 1),
                # quantized serving tiers (ISSUE 9): same-session e2e
                # rates next to the native leg + the paired ratios
                "inference_e2e_bf16_structs_per_sec": round(
                    infer_tier["bf16"], 1),
                "inference_e2e_int8_structs_per_sec": round(
                    infer_tier["int8"], 1),
                "inference_bf16_vs_native": round(
                    infer_tier["bf16"] / max(infer_e2e, 1.0), 3),
                "inference_int8_vs_native": round(
                    infer_tier["int8"] / max(infer_e2e, 1.0), 3),
                # raw wire (ISSUE 11): in-program neighbor search e2e
                # over the coverage-admitted subset, same session; the
                # wire-bytes ratio is the structural win the wire
                # format exists for (the chip-side throughput verdict
                # is the standing `--ab wire` protocol)
                "inference_e2e_raw_structs_per_sec": round(
                    infer_e2e_raw, 1),
                "inference_raw_vs_featurized": round(
                    infer_e2e_raw / max(infer_e2e, 1.0), 3),
                "ingest_raw_admit_share": round(
                    len(raw_items) / len(mp_graphs), 3),
                "ingest_wire_bytes_ratio": round(
                    wire_feat_bytes / max(wire_raw_bytes, 1), 1),
                "inference_ingest": ("ladder+compact+4workers" if on_accel
                                     else "ladder serial full (cpu "
                                          "backend: compact auto-off)"),
                "padding_eff_nodes": mp["node_eff"],
                "padding_eff_edges": mp["edge_eff"],
                "compiled_shapes": mp["shapes"],
                "rounds_s": mp["rounds_s"],
                "fencing": "value-fetch (block_until_ready unreliable here; "
                           "pre-round-3 numbers overstated)",
                "oc20": oc20,
                "tiny": tiny,
                "coo_layout": flat,
                "force_task": {**force_coo, **force_dense},
            })
        )
    )


if __name__ == "__main__":
    main()
