#!/usr/bin/env python
"""Benchmark: training throughput in structures/sec/chip (BASELINE.md).

Measures steady-state jitted train-step throughput on the flagship CGCNN
config (64-dim, 3 conv layers — BASELINE.json config #2 shape) over
synthetic MP-like crystals, with ``jax.block_until_ready`` fencing and
compile excluded (SURVEY.md §6 measurement protocol).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where
vs_baseline is value / 10_000 (the driver's north-star target,
BASELINE.json:5).
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import numpy as np

    from cgnn_tpu.data.dataset import FeaturizeConfig, load_synthetic
    from cgnn_tpu.data.graph import batch_iterator
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import capacities_for
    from cgnn_tpu.train.step import make_train_step

    batch_size = 512
    n_structures = 4096
    graphs = load_synthetic(
        n_structures, FeaturizeConfig(radius=6.0, max_num_nbr=12), seed=0
    )
    node_cap, edge_cap = capacities_for(graphs, batch_size)

    batches = list(batch_iterator(graphs, batch_size, node_cap, edge_cap))
    real_per_batch = [float(np.asarray(b.graph_mask).sum()) for b in batches]

    model = CrystalGraphConvNet(
        atom_fea_len=64, n_conv=3, h_fea_len=128, dtype=jax.numpy.bfloat16
    )
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10_000])
    normalizer = Normalizer.fit(np.stack([g.target for g in graphs]))
    state = create_train_state(model, batches[0], tx, normalizer)

    train_step = jax.jit(make_train_step(), donate_argnums=0)
    device_batches = [jax.device_put(b) for b in batches]

    # warmup: compile + 2 steps
    state, _ = train_step(state, device_batches[0])
    state, _ = train_step(state, device_batches[1 % len(device_batches)])
    jax.block_until_ready(state.params)

    # timed steady state: best of 3 rounds (the tunnel to the chip has
    # transient degraded phases; the best round reflects device capability)
    n_timed = 30
    value = 0.0
    for _round in range(3):
        structures = 0.0
        t0 = time.perf_counter()
        for i in range(n_timed):
            k = i % len(device_batches)
            state, _ = train_step(state, device_batches[k])
            structures += real_per_batch[k]
        jax.block_until_ready(state.params)
        value = max(value, structures / (time.perf_counter() - t0))
    print(
        json.dumps(
            {
                "metric": "train_structures_per_sec_per_chip",
                "value": round(value, 1),
                "unit": "structures/sec/chip",
                "vs_baseline": round(value / 10_000.0, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
