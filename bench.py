#!/usr/bin/env python
"""Benchmark: training throughput in structures/sec/chip (BASELINE.md).

Measures steady-state jitted train-step throughput of the flagship CGCNN
config (64-dim, 3 conv layers — BASELINE.json config #2 shape) with the
dense edge-slot layout (scatter-free aggregation, data/graph.py) and
honest fencing.

FENCING (important): timing rounds end with a ``float(metrics[...])``
VALUE FETCH — a true data dependency through the whole donated-state step
chain. ``jax.block_until_ready`` is NOT sufficient on this machine: under
the tunneled TPU runtime it returns before execution completes, which
overstated round-1/2 numbers by ~100x. Numbers from this file before
round 3 are not comparable.

The PRIMARY metric uses an MP-like size distribution (lognormal, ~30 atoms
mean — Materials Project's actual regime). Secondary numbers cover the
OC20 slab distribution (config #4) and the tiny-graph figure for
cross-round comparability. Each workload reports padding efficiency and an
analytic-FLOP MFU estimate against the v5e bf16 peak.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}
where vs_baseline is value / 10_000 (BASELINE.json:5 north star).
"""

from __future__ import annotations

import json
import time

from cgnn_tpu.observe.metrics_io import jsonfinite

# bf16 matmul peak by device kind (dense bf16, not the int8 headline).
_PEAK_FLOPS = {
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5": 459e12,       # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,  # trillium
}
_DEFAULT_PEAK = 197e12


def _flops_per_batch(batch, atom_dim, gauss_dim, f, h, n_conv, n_h) -> float:
    """Analytic matmul FLOPs for one fwd+bwd train step on real elements.

    Counts the MXU work only (dense layers; fwd 2mnk, bwd ~2x fwd). Segment
    ops / BN / elementwise are bandwidth-bound and excluded, as is padding
    (so MFU reflects useful work, discounted by padding efficiency).
    """
    import numpy as np

    n = float(np.asarray(batch.node_mask).sum())
    e = float(np.asarray(batch.edge_mask).sum())
    g = float(np.asarray(batch.graph_mask).sum())
    fwd = (
        2.0 * n * atom_dim * f                      # embedding
        + n_conv * 2.0 * e * (2 * f + gauss_dim) * (2 * f)  # fc_full per conv
        + 2.0 * g * f * h                           # conv_to_fc
        + (n_h - 1) * 2.0 * g * h * h               # hidden fcs
        + 2.0 * g * h                               # fc_out
    )
    return 3.0 * fwd  # fwd + ~2x bwd


def _bench_workload(
    graphs, batch_size, *, buckets=1, n_timed=40, label="", dense_m=None,
    snug=True, fused=None,
):
    """-> dict(structs_per_sec, mfu, node_eff, edge_eff, shapes, rounds_s)."""
    import jax
    import numpy as np

    from cgnn_tpu.data.graph import (
        PaddingStats,
        batch_iterator,
        bucketed_batch_iterator,
        capacities_for,
    )
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.step import make_train_step

    atom_dim = graphs[0].atom_fea.shape[1]
    gauss_dim = graphs[0].edge_fea.shape[1]
    f, h, n_conv, n_h = 64, 128, 3, 1

    stats = PaddingStats()
    edge_dtype = jax.numpy.bfloat16  # model computes bf16; store bf16
    if buckets > 1:
        batches = list(
            bucketed_batch_iterator(
                graphs, batch_size, buckets, stats=stats,
                rng=np.random.default_rng(0), dense_m=dense_m, snug=snug,
                edge_dtype=edge_dtype,
            )
        )
    else:
        node_cap, edge_cap = capacities_for(
            graphs, batch_size, dense_m=dense_m, snug=snug
        )
        batches = list(
            stats.wrap(
                batch_iterator(
                    graphs, batch_size, node_cap, edge_cap, dense_m=dense_m,
                    snug=snug, edge_dtype=edge_dtype,
                )
            )
        )
    real_per_batch = [float(np.asarray(b.graph_mask).sum()) for b in batches]
    atoms_per_batch = [float(np.asarray(b.node_mask).sum()) for b in batches]
    flops_per_batch = [
        _flops_per_batch(b, atom_dim, gauss_dim, f, h, n_conv, n_h)
        for b in batches
    ]

    model = CrystalGraphConvNet(
        atom_fea_len=f, n_conv=n_conv, h_fea_len=h,
        dtype=jax.numpy.bfloat16, dense_m=dense_m, fused_epilogue=fused,
    )
    tx = make_optimizer(optim="sgd", lr=0.01, lr_milestones=[10_000])
    normalizer = Normalizer.fit(np.stack([g.target for g in graphs]))
    state = create_train_state(model, batches[0], tx, normalizer)

    train_step = jax.jit(make_train_step(), donate_argnums=0)
    device_batches = [jax.device_put(b) for b in batches]

    # warmup: one step per distinct shape (compiles), fenced by value fetch
    seen = set()
    metrics = None
    for b in device_batches:
        shape = (b.node_capacity, b.edge_capacity)
        if shape not in seen:
            seen.add(shape)
            state, metrics = train_step(state, b)
    state, metrics = train_step(state, device_batches[0])
    float(metrics["loss_sum"])

    # timed steady state: best of 3 rounds, each fenced by a VALUE FETCH of
    # the final step's metrics (depends on the whole donated-state chain).
    # All three round times are reported (rounds_s) so cross-round BENCH
    # comparisons can see the tunnel's run-to-run variance, not just the
    # best (VERDICT r2 weak #7).
    best_rate, best_mfu, best_atoms = 0.0, 0.0, 0.0
    rounds_s = []
    peak = _PEAK_FLOPS.get(jax.devices()[0].device_kind, _DEFAULT_PEAK)
    for _round in range(3):
        structures = flops = atoms = 0.0
        t0 = time.perf_counter()
        for i in range(n_timed):
            k = i % len(device_batches)
            state, metrics = train_step(state, device_batches[k])
            structures += real_per_batch[k]
            atoms += atoms_per_batch[k]
            flops += flops_per_batch[k]
        float(metrics["loss_sum"])
        dt = time.perf_counter() - t0
        rounds_s.append(round(dt, 4))
        if structures / dt > best_rate:
            best_rate = structures / dt
            best_mfu = flops / dt / peak
            best_atoms = atoms / dt
    return {
        f"{label}structs_per_sec": round(best_rate, 1),
        # atoms/s is the cross-distribution invariant: a 113-atom OC20
        # slab is ~3.8x an MP structure's work, so structs/s alone makes
        # the OC20 number look artificially low vs the 10k MP north star
        f"{label}atoms_per_sec": round(best_atoms, 1),
        f"{label}mfu": round(best_mfu, 4),
        f"{label}node_eff": round(stats.node_efficiency, 3),
        f"{label}edge_eff": round(stats.edge_efficiency, 3),
        f"{label}shapes": len(stats.shapes),
        f"{label}rounds_s": rounds_s,
    }


def _bench_force_workload(graphs, batch_size, *, dense_m=None, n_timed=16,
                          label="force_"):
    """Force-task train-step throughput (config #5): frames/sec/chip.

    The step differentiates twice (positions inside, params outside);
    dense vs COO isolates the layout win on this workload
    (VERDICT r3 next-step #4)."""
    import jax
    import numpy as np

    from cgnn_tpu.data.graph import batch_iterator, capacities_for
    from cgnn_tpu.models.forcefield import ForceFieldCGCNN
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.force_step import make_force_train_step

    node_cap, edge_cap = capacities_for(graphs, batch_size, dense_m=dense_m,
                                        snug=True)
    batches = list(batch_iterator(graphs, batch_size, node_cap, edge_cap,
                                  dense_m=dense_m, snug=True))
    real = [float(np.asarray(b.graph_mask).sum()) for b in batches]
    model = ForceFieldCGCNN(atom_fea_len=64, n_conv=3, h_fea_len=64,
                            dmax=6.0, dense_m=dense_m)
    tx = make_optimizer(optim="sgd", lr=0.001, lr_milestones=[10**9])
    normalizer = Normalizer.fit(np.stack([g.target for g in graphs]))
    state = create_train_state(model, batches[0], tx, normalizer)
    step = jax.jit(make_force_train_step(), donate_argnums=0)
    device_batches = [jax.device_put(b) for b in batches]
    state, metrics = step(state, device_batches[0])
    float(metrics["loss_sum"])
    best = 0.0
    rounds_s = []
    for _ in range(3):
        t0 = time.perf_counter()
        s = 0.0
        for i in range(n_timed):
            k = i % len(device_batches)
            state, metrics = step(state, device_batches[k])
            s += real[k]
        float(metrics["loss_sum"])
        dt = time.perf_counter() - t0
        rounds_s.append(round(dt, 4))
        best = max(best, s / dt)
    return {f"{label}structs_per_sec": round(best, 1),
            f"{label}rounds_s": rounds_s}


def main() -> None:
    from cgnn_tpu.data.dataset import (
        FeaturizeConfig,
        load_synthetic,
        load_synthetic_mp,
        load_synthetic_oc20,
    )

    cfg = FeaturizeConfig(radius=6.0, max_num_nbr=12)

    # PRIMARY: MP-like size distribution (~30-atom lognormal), dense
    # layout, bucketed. Batch/bucket re-swept under snug packing (r3:
    # 512/3b 47.5k, 768/3b 41.6k, 1024/3b 40.1k structs/s — per-slot
    # cost dominates, so tighter buckets beat bigger batches).
    mp_graphs = load_synthetic_mp(8192, cfg, seed=0)
    mp = _bench_workload(
        mp_graphs, batch_size=512, buckets=3, n_timed=40, dense_m=12,
    )
    # SECONDARY: OC20 slab distribution (config #4 large-graph regime)
    oc20 = _bench_workload(
        load_synthetic_oc20(768, cfg, seed=0), batch_size=128, buckets=2,
        n_timed=24, label="oc20_", dense_m=12,
    )
    # SECONDARY: tiny-graph figure (round-1 comparability; honest fencing)
    tiny = _bench_workload(
        load_synthetic(4096, cfg, seed=0), batch_size=1024, n_timed=30,
        label="tiny_", dense_m=12,
    )
    # SECONDARY: flat-COO layout at the same MP workload (the layout win)
    flat = _bench_workload(
        mp_graphs, batch_size=512, buckets=3, n_timed=20, label="coo_",
    )
    # NOTE: the fused BN1->gate->mask->sum epilogue (--fused-epilogue,
    # ops/fused_epilogue.py) measured 5-20% SLOWER than the unfused chain
    # in same-process interleaved rounds (PERF.md 6b) and is NOT benched
    # here; reproduce with scripts/scan_cost.py --fused-epilogue xla|pallas
    # SECONDARY: force task (config #5) — COO vs dense layout
    from cgnn_tpu.data.dataset import load_trajectory

    md_graphs = load_trajectory(1024, cfg, seed=0, num_atoms=16,
                                jitter=0.05)
    force_coo = _bench_force_workload(md_graphs, 256, label="force_coo_")
    force_dense = _bench_force_workload(md_graphs, 256, dense_m=12,
                                        label="force_dense_")

    # production epoch-driver mode (VERDICT r3 #5): the ScanEpochDriver at
    # bench scale, per-epoch metric semantics (one link sync per epoch —
    # SCAN_COST.json has the full breakdown incl. the per-step production
    # driver, which the scan driver beats ~4x on this tunneled link)
    import time as _time

    import jax
    import numpy as np

    from cgnn_tpu.data.graph import bucketed_batch_iterator
    from cgnn_tpu.models import CrystalGraphConvNet
    from cgnn_tpu.train import Normalizer, create_train_state, make_optimizer
    from cgnn_tpu.train.loop import ScanEpochDriver
    from cgnn_tpu.train.step import make_eval_step, make_train_step

    eb = list(bucketed_batch_iterator(
        mp_graphs, 512, 3, shuffle=True, rng=np.random.default_rng(0),
        dense_m=12, snug=True, edge_dtype=jax.numpy.bfloat16,
    ))
    estructs = sum(float(np.asarray(b.graph_mask).sum()) for b in eb)
    emodel = CrystalGraphConvNet(atom_fea_len=64, n_conv=3, h_fea_len=128,
                                 dtype=jax.numpy.bfloat16, dense_m=12)
    estate = create_train_state(
        emodel, eb[0], make_optimizer(optim="sgd", lr=0.01,
                                      lr_milestones=[10**9]),
        Normalizer.fit(np.stack([g.target for g in mp_graphs])),
    )
    edrv = ScanEpochDriver(make_train_step(), make_eval_step(), eb, [],
                           np.random.default_rng(0))
    estate = edrv.warm(estate)  # keeps first-compiles out of timed epochs
    et0 = _time.perf_counter()
    for _ in range(4):
        estate, _, _ = edrv.run_epoch_pair(estate, first=False)
    epoch_rate = estructs * 4 / (_time.perf_counter() - et0)

    # inference throughput (predict.py fast path, VERDICT r4 weak #5),
    # two numbers with different denominators:
    # - device rate: forward steps over pre-staged batches (the train
    #   bench's own convention — packing excluded), value-fetch fenced
    # - end-to-end rate: run_fast_inference including host packing and
    #   the stacked fetch (what a cold `predict.py` run sees; host
    #   packing dominated it at scale until ISSUE 4 — the breakdown is
    #   PERF.md §7, the fix §11). Measured over predict.py's DEFAULT
    #   path FOR THIS BACKEND: on an accelerator that is the serving
    #   shape ladder, compact-staged, packed by the parallel ingest
    #   pipeline (data/pipeline.py); on a CPU backend predict.py's
    #   `--compact auto` keeps both off (the device IS the host — §11
    #   measured compact e2e SLOWER there), so the bench mirrors that
    #   and the headline never reports a config predict.py wouldn't run.
    from cgnn_tpu.data.compact import CompactSpec, make_expander
    from cgnn_tpu.serve.shapes import plan_shape_set
    from cgnn_tpu.train.infer import run_fast_inference
    from cgnn_tpu.train.step import make_predict_step

    istate = create_train_state(
        emodel, eb[0], make_optimizer(optim="sgd", lr=0.01,
                                      lr_milestones=[10**9]),
        Normalizer.fit(np.stack([g.target for g in mp_graphs])),
    )
    on_accel = jax.default_backend() != "cpu"
    ispec = (CompactSpec.build(mp_graphs, cfg.gdf(), dense_m=12,
                               edge_dtype=jax.numpy.bfloat16)
             if on_accel else None)
    # ONE jitted step for all passes: the expander makes it accept BOTH
    # staging forms (compact e2e batches AND the device-rate GraphBatches)
    pstep = jax.jit(make_predict_step(
        make_expander(ispec) if ispec is not None else None))
    ladder = plan_shape_set(mp_graphs, 512, rungs=3, dense_m=12,
                            edge_dtype=jax.numpy.bfloat16, compact=ispec)
    infer_kw = dict(shape_set=ladder, predict_step=pstep,
                    pack_workers=4 if on_accel else 0)
    run_fast_inference(istate, mp_graphs, 512, **infer_kw)  # compile pass
    _, infer_e2e = run_fast_inference(istate, mp_graphs, 512, **infer_kw)
    # device-parallel leg (ISSUE 5): the SAME ladder/step/pack config
    # round-robined across resolve_devices('auto') — measured in the same
    # session as the single-device number (§8's in-session-ratio rule:
    # cross-session levels drift with the link; the ratio is the result).
    # On a CPU backend 'auto' is one device by design, so the two legs
    # coincide and the ratio honestly reads ~1.
    from cgnn_tpu.serve.devices import resolve_devices

    inf_devices = resolve_devices("auto")
    mdev_kw = dict(infer_kw, devices=inf_devices)
    run_fast_inference(istate, mp_graphs, 512, **mdev_kw)  # per-dev compile
    _, infer_e2e_mdev = run_fast_inference(istate, mp_graphs, 512, **mdev_kw)
    # the pre-ISSUE-4 serial full-fidelity path, for the same-session
    # before/after (cross-session BENCH levels drift with the link, §8)
    serial_kw = dict(buckets=3, dense_m=12, snug=True,
                     edge_dtype=jax.numpy.bfloat16, predict_step=pstep)
    run_fast_inference(istate, mp_graphs, 512, **serial_kw)  # compile pass
    _, infer_e2e_serial = run_fast_inference(istate, mp_graphs, 512,
                                             **serial_kw)

    ib = list(bucketed_batch_iterator(
        mp_graphs, 512, 3, rng=np.random.default_rng(0), dense_m=12,
        in_cap=0, snug=True, edge_dtype=jax.numpy.bfloat16,
    ))
    ireal = [float(np.asarray(b.graph_mask).sum()) for b in ib]
    idev = [jax.device_put(b) for b in ib]
    out = None
    for b in idev:  # compile per shape
        out = pstep(istate, b)
    float(out[0, 0])
    infer_dev = 0.0
    for _ in range(3):
        it0 = _time.perf_counter()
        done = 0.0
        for _rep in range(3):
            for k, b in enumerate(idev):
                out = pstep(istate, b)
                done += ireal[k]
        float(out[0, 0])
        infer_dev = max(infer_dev, done / (_time.perf_counter() - it0))

    value = mp["structs_per_sec"]
    print(
        json.dumps(jsonfinite(
            {
                "metric": "train_structures_per_sec_per_chip_mp_distribution",
                "value": value,
                "unit": "structures/sec/chip",
                "vs_baseline": round(value / 10_000.0, 4),
                "atoms_per_sec": mp["atoms_per_sec"],
                "mfu": mp["mfu"],
                # production ScanEpochDriver at bench scale, per-epoch
                # metric semantics. The ratio's denominator is THIS
                # bench's best-of-3 step rate — a different (stricter)
                # baseline than SCAN_COST.json's sync-free in-process
                # loop, which is why the two artifacts' ratios differ by
                # construction (r4 weak #4); the key now names its
                # denominator so the same-named-quantity ambiguity is
                # gone. The physical residual is one link round trip per
                # epoch either way (SCAN_COST.json breakdown).
                "epoch_driver_structs_per_sec": round(epoch_rate, 1),
                "epoch_driver_vs_best_step_bench": round(
                    epoch_rate / max(value, 1.0), 3),
                # forward-only inference (predict.py fast path): device
                # rate over staged batches (train-bench convention) and
                # the end-to-end rate incl. host packing
                "inference_structs_per_sec": round(infer_dev, 1),
                "inference_e2e_structs_per_sec": round(infer_e2e, 1),
                # device-parallel forward path (ISSUE 5): same config
                # dispatched across all 'auto' devices, same session as
                # the single-device e2e above (§8 in-session-ratio rule)
                "inference_devices": len(inf_devices),
                "inference_e2e_multidev_structs_per_sec": round(
                    infer_e2e_mdev, 1),
                "inference_multidev_vs_single": round(
                    infer_e2e_mdev / max(infer_e2e, 1.0), 3),
                # the pre-ISSUE-4 serial full-fidelity ingest, same
                # session (the honest before/after; PERF.md §11)
                "inference_e2e_serial_structs_per_sec": round(
                    infer_e2e_serial, 1),
                "inference_ingest": ("ladder+compact+4workers" if on_accel
                                     else "ladder serial full (cpu "
                                          "backend: compact auto-off)"),
                "padding_eff_nodes": mp["node_eff"],
                "padding_eff_edges": mp["edge_eff"],
                "compiled_shapes": mp["shapes"],
                "rounds_s": mp["rounds_s"],
                "fencing": "value-fetch (block_until_ready unreliable here; "
                           "pre-round-3 numbers overstated)",
                "oc20": oc20,
                "tiny": tiny,
                "coo_layout": flat,
                "force_task": {**force_coo, **force_dense},
            })
        )
    )


if __name__ == "__main__":
    main()
