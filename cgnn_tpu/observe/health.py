"""In-graph gradient-health metrics (grad/update norms, NaN/Inf counts).

Computed INSIDE the jitted train step (train/step.py, train/force_step.py
call this when built with ``grad_health=True``) so they ride the existing
metric plumbing: device-side accumulation across steps, the packed
one-fetch epoch aggregate, and — at ``--telemetry step`` — the in-scan
stream. Everything is derived from values the step already has (grads,
old/new params, loss); nothing here feeds back into the update, so the
training trajectory is bit-identical with or without it.

Keys follow the (sum, count) metric convention: ``*_sum`` with a
matching ``*_count`` of 1 per step, so epoch aggregation yields per-step
means and the stream's single-step derivation yields the raw values.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def global_norm(tree):
    """sqrt(sum of squares) over every leaf, accumulated in f32."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves)
    )


def nonfinite_count(tree):
    """Total NaN/Inf elements over every leaf (f32 scalar)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return sum(
        (~jnp.isfinite(x)).sum() for x in leaves
    ).astype(jnp.float32)


def grad_health_metrics(grads, old_params, new_params, loss=None) -> dict:
    """The step's health metric dict (merge into the step's metrics)."""
    one = jnp.float32(1.0)
    out = {
        "grad_norm_sum": global_norm(grads),
        "grad_norm_count": one,
        "update_norm_sum": global_norm(
            jax.tree_util.tree_map(
                lambda a, b: a.astype(jnp.float32) - b.astype(jnp.float32),
                new_params, old_params,
            )
        ),
        "update_norm_count": one,
        "nonfinite_grads_sum": nonfinite_count(grads),
        "nonfinite_grads_count": one,
    }
    if loss is not None:
        out["nonfinite_loss_sum"] = (
            ~jnp.isfinite(jnp.asarray(loss, jnp.float32))
        ).astype(jnp.float32)
        out["nonfinite_loss_count"] = one
    return out
