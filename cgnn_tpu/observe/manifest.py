"""Run manifest: config + environment fingerprint, written once per run.

Answers "what exactly was this run?" without scraping stdout: the full
flag/config dict, device inventory and mesh shape, package versions, and
the git SHA (+dirty bit) of the working tree. One JSON file
(``manifest.json``) next to ``metrics.jsonl``/``trace.json``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time


def _git_info() -> dict:
    """Best-effort {sha, dirty} of the repo this package lives in."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, capture_output=True,
            text=True, timeout=10,
        ).stdout.strip()
        if not sha:
            return {}
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=root, capture_output=True,
            text=True, timeout=10,
        ).stdout.strip()
        return {"git_sha": sha, "git_dirty": bool(dirty)}
    except Exception:  # noqa: BLE001 — no git in the image / not a repo
        return {}


def build_manifest(config: dict | None = None, **extra) -> dict:
    """The manifest dict (separated from the write for testability)."""
    import jax

    devices = jax.devices()
    manifest = {
        "time": time.time(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "jax_version": jax.__version__,
        "backend": devices[0].platform if devices else "none",
        "device_count": len(devices),
        "devices": [
            {
                "id": d.id,
                "kind": getattr(d, "device_kind", ""),
                "platform": getattr(d, "platform", ""),
            }
            for d in devices
        ],
        **_git_info(),
    }
    if config is not None:
        manifest["config"] = {
            k: v for k, v in config.items()
            if isinstance(v, (int, float, str, bool, list, tuple, type(None)))
        }
    manifest.update(extra)
    return manifest


def write_manifest(log_dir: str, config: dict | None = None, **extra) -> str:
    """Write manifest.json under ``log_dir``; returns the path."""
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, "manifest.json")
    with open(path, "w") as f:
        # config/versions/inventory are finite by construction:
        # allow_nan=False makes a violation loud instead of emitting an
        # invalid bare-NaN token (graftcheck GC-JSONFINITE)
        json.dump(build_manifest(config, **extra), f, indent=1,
                  allow_nan=False)
    return path
