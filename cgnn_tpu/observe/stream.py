"""In-scan metric streaming: per-step scalars ring out of jitted bodies.

The flagship dispatch mode folds whole epochs into ``lax.scan`` over
device-resident stacks (``train.loop.ScanEpochDriver``) — the fastest
path, but it hides every per-step signal from the host: loss spikes,
grad-norm blowups, and NaN onset are only visible as epoch aggregates.
``StepStream.tap`` is the fix: called at TRACE time inside a step/scan
body, it packs that step's scalar metrics into one f32 vector and stages
a ``jax.debug.callback`` — an asynchronous host callback that the runtime
invokes with the concrete values at each executed step, WITHOUT a
host<->device fetch on the training-critical path and without touching
the donated-buffer scan carry (the tap only reads freshly computed metric
scalars, so trajectory parity with the untapped program is exact).

Host side, each arrival becomes one ``{"event": "step"}`` record in
``metrics.jsonl`` (per-step means derived from the step's (sum, count)
pairs, plus an arrival-rate ``steps_per_s``) and lands in a bounded ring
buffer for cheap in-process inspection. Callbacks may arrive from
runtime threads and — with ``ordered=False`` — out of submission order;
records carry the in-graph optimizer step (or an arrival sequence number
for eval) so ordering is recoverable downstream.

Nothing here stages a callback unless ``tap``/``wrap_*`` is actually
called: with telemetry off or at epoch level the compiled HLO is
byte-identical to an unstreamed build (the ``--telemetry off`` no-op
guarantee, pinned by tests/test_observe.py).
"""

from __future__ import annotations

import collections
import contextlib
import threading
import time
from typing import Callable, Iterator

import numpy as np


def _derive_means(sums: dict) -> dict:
    """Per-step means from one step's '<name>_sum' totals (each divided
    by its matching '<name>_count' when present, else the global
    'count') — the single-step analog of train.metrics.means_from_sums,
    duplicated here so cgnn_tpu.observe never imports cgnn_tpu.train."""
    count = max(sums.get("count", 1.0), 1.0)
    out = {
        k[: -len("_sum")]: v
        / max(sums.get(k[: -len("_sum")] + "_count", count), 1.0)
        for k, v in sums.items()
        if k.endswith("_sum")
    }
    out["count"] = sums.get("count", 0.0)
    return out


class StepStream:
    """Per-step metric tap: jitted bodies -> ring buffer + metrics.jsonl."""

    def __init__(self, logger=None, ring_size: int = 4096,
                 rate_window: int = 32):
        self._logger = logger
        self.ring: collections.deque = collections.deque(maxlen=ring_size)
        self._lock = threading.Lock()
        self._callbacks: dict = {}
        self._seq: dict[str, int] = {}
        self._arrivals: dict[str, collections.deque] = {}
        self._rate_window = rate_window
        self._muted = 0
        self.dropped = 0  # records lost to host-side callback errors

    # ---- trace-time API (called inside jit/scan tracing) ----

    def tap(self, metrics: dict, phase: str, step=None) -> None:
        """Stage the async host callback carrying this step's scalars.

        ``metrics`` is the step's (sum, count) dict; non-scalar entries
        are skipped. ``step`` is the in-graph optimizer step (traced
        int) for training taps; eval taps pass None and records fall
        back to an arrival sequence number.
        """
        import jax
        import jax.numpy as jnp

        scalars = {k: v for k, v in metrics.items() if jnp.ndim(v) == 0}
        if not scalars:
            return
        keys = tuple(sorted(scalars))
        packed = jnp.stack(
            [jnp.asarray(scalars[k], jnp.float32) for k in keys]
        )
        step_no = jnp.asarray(-1 if step is None else step, jnp.int32)
        # unordered: the callback must not serialize scan iterations —
        # records are tagged with the step number instead
        jax.debug.callback(
            self._callback_for(phase, keys), step_no, packed, ordered=False
        )

    def wrap_train(self, body: Callable, phase: str = "train") -> Callable:
        """(state, batch) -> (state, metrics) body with the tap staged."""

        def wrapped(state, batch):
            new_state, metrics = body(state, batch)
            self.tap(metrics, phase, step=new_state.step)
            return new_state, metrics

        return wrapped

    def wrap_eval(self, body: Callable, phase: str = "eval") -> Callable:
        """(state, batch) -> metrics body with the tap staged."""

        def wrapped(state, batch):
            metrics = body(state, batch)
            self.tap(metrics, phase)
            return metrics

        return wrapped

    # ---- host side ----

    def _callback_for(self, phase: str, keys: tuple) -> Callable:
        # one host function per (phase, metric-key layout); cached so
        # scan re-traces reuse the same callable
        ck = (phase, keys)
        with self._lock:
            cb = self._callbacks.get(ck)
            if cb is None:

                def cb(step_no, packed, _phase=phase, _keys=keys):
                    try:
                        self._record(_phase, _keys, step_no, packed)
                    except Exception:  # noqa: BLE001 — never kill training
                        with self._lock:
                            self.dropped += 1

                self._callbacks[ck] = cb
        return cb

    def _record(self, phase: str, keys: tuple, step_no, packed) -> None:
        vals = np.asarray(packed, dtype=np.float64)
        step_no = int(np.asarray(step_no))
        now = time.perf_counter()
        with self._lock:
            if self._muted:
                return
            seq = self._seq.get(phase, 0)
            self._seq[phase] = seq + 1
            arr = self._arrivals.setdefault(
                phase, collections.deque(maxlen=self._rate_window)
            )
            arr.append(now)
            rate = (
                (len(arr) - 1) / (arr[-1] - arr[0])
                if len(arr) > 1 and arr[-1] > arr[0]
                else float("nan")
            )
        rec = {
            "phase": phase,
            "step": step_no if step_no >= 0 else seq,
            **_derive_means(dict(zip(keys, map(float, vals)))),
        }
        if rate == rate:
            rec["steps_per_s"] = rate
        with self._lock:
            self.ring.append(rec)
        if self._logger is not None:
            self._logger.event("step", rec)

    @contextlib.contextmanager
    def muted(self) -> Iterator[None]:
        """Drop arrivals inside the context (warmup/compile dispatches
        run the same compiled programs; their records are not training
        signal). Unmuting drains in-flight callbacks first
        (``jax.effects_barrier``): they run on runtime threads, so
        without the barrier a late warmup arrival could land after the
        mute lifts and masquerade as a real step record."""
        with self._lock:
            self._muted += 1
        try:
            yield
        finally:
            try:
                import jax

                jax.effects_barrier()
            except Exception:  # noqa: BLE001 — jax may be torn down
                pass
            with self._lock:
                self._muted -= 1

    def records(self, phase: str | None = None) -> list[dict]:
        with self._lock:
            recs = list(self.ring)
        return recs if phase is None else [
            r for r in recs if r["phase"] == phase
        ]
