"""Incident flight recorder: bounded always-on state, dumped on trigger.

The PR-12 fleet can kill, eject, hedge, and shed — but when it does,
the evidence is scattered across N processes' stdouts and whatever
/metrics happened to be scraped. The flight recorder is the black box:
every process keeps a small, always-cheap ring of recent per-request
records (trace id, stages, status, param version, tier/wire) plus its
live metrics registry and span ring, and a TRIGGER — breaker trip,
watchdog dump, 5xx burst, drain force-exit, divergence rollback —
dumps one correlated bundle directory for the postmortem:

    bundle-<utc>-<reason>/
      manifest.json    who dumped, why, when, argv, config manifest
      requests.jsonl   the recent-request ring (grep by trace id)
      metrics.json     the registry snapshot at dump time
      trace.json       the span window — JOINED across every reachable
                       peer process when ``peers`` is configured (the
                       router's bundle shows the whole fleet's tree)
      peers.json       each peer's own /flightrec ring + metrics

Triggers are rate-limited (``min_interval_s``) and bounded
(``max_bundles``): an incident storm produces a few bundles, not a full
disk. The hot-path cost is one lock + deque append per request; all IO
happens on a one-shot named dump thread, never on the request path.
Host-side only — nothing here is staged into jitted code, so served
numbers are bit-exact with the recorder on or off.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Callable

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.observe.metrics_io import jsonfinite


def _write_json(path: str, payload) -> None:
    try:
        body = json.dumps(payload, allow_nan=False, indent=1)
    except ValueError:
        body = json.dumps(jsonfinite(payload), indent=1)
    with open(path, "w") as f:
        f.write(body)


class FlightRecorder:
    """One process's black box; see the module docstring.

    ``registry`` (observe/export.py MetricsRegistry), ``tracer``
    (observe/spans.py SpanTracer), and ``peers`` (base urls whose
    ``/trace`` + ``/flightrec`` a dump pulls) are all optional — the
    recorder degrades to whatever surfaces its process actually has.
    """

    def __init__(
        self,
        out_dir: str,
        *,
        role: str = "process",
        name: str = "",
        ring: int = 512,
        burst_threshold: int = 20,
        burst_window_s: float = 10.0,
        min_interval_s: float = 30.0,
        max_bundles: int = 16,
        registry=None,
        tracer=None,
        peers=(),
        manifest: dict | None = None,
        log_fn: Callable = print,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.out_dir = out_dir
        self.role = str(role)
        self.name = str(name) or f"{role}-{os.getpid()}"
        self.registry = registry
        self.tracer = tracer
        self.peers = list(peers)
        self.manifest = dict(manifest or {})
        self.burst_threshold = int(burst_threshold)
        self.burst_window_s = float(burst_window_s)
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = int(max_bundles)
        self._log = log_fn
        self._clock = clock
        self._lock = racecheck.make_lock(f"observe.flightrec.{self.name}")
        # all below mutated under self._lock (graftcheck GC-LOCKSHARE)
        self._ring: collections.deque = collections.deque(maxlen=int(ring))
        self._errors: collections.deque = collections.deque(maxlen=4096)
        self._last_dump = -1e18
        self._burst_fired = False
        self.bundles = 0
        self.suppressed = 0
        self.triggers: dict[str, int] = {}
        self.last_bundle = ""
        self._dump_thread: threading.Thread | None = None
        # trigger subscription (ISSUE 17): called as
        # ``on_trigger(reason, detail, bundle_dir_or_None)`` after
        # every trigger — INCLUDING rate-limited ones (bundle None), so
        # an auto-remediator never misses an incident just because its
        # evidence bundle was suppressed. Called outside the lock;
        # exceptions are swallowed (a broken subscriber must not take
        # the serving process down)
        self.on_trigger: Callable | None = None

    # ---- the always-on cheap path ----

    def note_request(self, record: dict) -> None:
        """Remember one finished request (answered OR failed): the
        caller supplies whatever it knows — trace_id, status, stamps,
        param_version, precision/wire/rung, latency_ms, replica/device.
        One lock + append; the hot-path whole cost."""
        record = dict(record)
        record.setdefault("t_unix", time.time())
        with self._lock:
            self._ring.append(record)

    def note_status(self, status: int) -> None:
        """Feed the 5xx burst detector with one response status. A
        burst (``burst_threshold`` server errors inside
        ``burst_window_s``) fires the ``5xx_burst`` trigger ONCE per
        quiet period — it re-arms only after the window drains below
        half the threshold, so a sustained error plateau produces one
        bundle, not one per request."""
        if status < 500:
            return
        now = self._clock()
        fire = False
        with self._lock:
            self._errors.append(now)
            cutoff = now - self.burst_window_s
            while self._errors and self._errors[0] < cutoff:
                self._errors.popleft()
            n = len(self._errors)
            if n >= self.burst_threshold and not self._burst_fired:
                self._burst_fired = True
                fire = True
            elif n <= self.burst_threshold // 2:
                self._burst_fired = False
        if fire:
            self.trigger("5xx_burst",
                         f"{self.burst_threshold}+ server errors in "
                         f"{self.burst_window_s:.0f} s")

    def recent_requests(self) -> list:
        with self._lock:
            return list(self._ring)

    def snapshot(self) -> dict:
        """The ``GET /flightrec`` body: ring + live metrics + identity
        (what a PEER's dump pulls to correlate this process)."""
        with self._lock:
            bundles = self.bundles
            triggers = dict(self.triggers)
            requests = list(self._ring)
        snap = {
            "role": self.role,
            "name": self.name,
            "pid": os.getpid(),
            "time_unix": time.time(),
            "bundles": bundles,
            "triggers": triggers,
            "requests": requests,
            "manifest": self.manifest,
        }
        if self.registry is not None:
            try:
                snap["metrics"] = self.registry.snapshot()
            except Exception as e:  # noqa: BLE001 — a broken gauge must
                snap["metrics_error"] = repr(e)  # not kill the bundle
        return snap

    # ---- triggers ----

    def trigger(self, reason: str, detail: str = "",
                wait: bool = False, force: bool = False) -> str | None:
        """Fire one incident dump; returns the bundle dir (None when
        rate-limited/bounded away). The dump's IO runs on a one-shot
        named daemon thread so a trigger on the request path costs a
        thread spawn, not a fleet-wide /trace pull — ``wait=True``
        blocks for it. ``force=True`` bypasses the rate limit and the
        bundle cap, first waiting out any in-flight dump — the
        drain-force-exit path, where the process is about to ``os._exit``
        and the promised final bundle must not be suppressed because a
        5xx burst happened to dump 10 s earlier."""
        now = self._clock()
        with self._lock:
            self.triggers[reason] = self.triggers.get(reason, 0) + 1
            t_busy = self._dump_thread
        busy = t_busy is not None and t_busy.is_alive()
        if force and busy:
            t_busy.join(timeout=60.0)
            busy = t_busy.is_alive()  # still alive = wedged dump
        with self._lock:
            limited = (now - self._last_dump < self.min_interval_s
                       or self.bundles >= self.max_bundles)
            if busy or (limited and not force):
                self.suppressed += 1
                bundle = t = None
            else:
                self._last_dump = now
                self.bundles += 1
                # pid in the name: replicas sharing one --flightrec-dir
                # (the serve.py 'auto' default under a shared ckpt dir)
                # firing in the same second must land in DISTINCT dirs,
                # never interleave files inside one
                stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
                bundle = os.path.join(
                    self.out_dir,
                    f"bundle-{stamp}-p{os.getpid()}"
                    f"-{self.bundles:02d}-{reason}")
                self.last_bundle = bundle
                t = threading.Thread(
                    target=self._dump, args=(bundle, reason, detail),
                    daemon=True, name=f"flightrec-dump-{self.bundles}",
                )
                self._dump_thread = t
        if t is None:
            self._notify(reason, detail, None)
            return None
        t.start()
        self._notify(reason, detail, bundle)
        if wait:
            t.join(timeout=60.0)
        return bundle

    def _notify(self, reason: str, detail: str,
                bundle: str | None) -> None:
        cb = self.on_trigger
        if cb is None:
            return
        try:
            cb(reason, detail, bundle)
        except Exception as e:  # noqa: BLE001 — see on_trigger contract
            self._log(f"flightrec: on_trigger subscriber failed: {e!r}")

    def wait_idle(self, timeout_s: float = 60.0) -> None:
        with self._lock:
            t = self._dump_thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)

    # ---- the dump (one-shot thread; all IO lives here) ----

    def _dump(self, bundle: str, reason: str, detail: str) -> None:
        try:
            os.makedirs(bundle, exist_ok=True)
            with self._lock:
                requests = list(self._ring)
                triggers = dict(self.triggers)
            _write_json(os.path.join(bundle, "manifest.json"), {
                "reason": reason,
                "detail": detail,
                "role": self.role,
                "name": self.name,
                "pid": os.getpid(),
                "time_unix": time.time(),
                "argv": list(sys.argv),
                "triggers": triggers,
                "peers": self.peers,
                **self.manifest,
            })
            with open(os.path.join(bundle, "requests.jsonl"), "w") as f:
                for r in requests:
                    try:
                        f.write(json.dumps(r, allow_nan=False) + "\n")
                    except ValueError:
                        f.write(json.dumps(jsonfinite(r)) + "\n")
            if self.registry is not None:
                try:
                    _write_json(os.path.join(bundle, "metrics.json"),
                                self.registry.snapshot())
                except Exception as e:  # noqa: BLE001 — partial bundle
                    _write_json(os.path.join(bundle, "metrics.json"),
                                {"error": repr(e)})
            self._dump_trace(bundle)
            self._dump_peers(bundle)
            self._log(f"flightrec: {reason} -> {bundle} "
                      f"({len(requests)} recent requests, "
                      f"{len(self.peers)} peers)")
        except Exception as e:  # noqa: BLE001 — a failing dump must not
            # take the serving process with it; the trigger count
            # already recorded that the incident happened
            self._log(f"flightrec: dump for {reason!r} failed: {e!r}")

    def _dump_trace(self, bundle: str) -> None:
        from cgnn_tpu.observe import trace_join

        windows = []
        if self.tracer is not None:
            w = self.tracer.window()
            w["role"] = self.role
            windows.append(w)
        errors = {}
        if self.peers:
            peer_windows, errors = trace_join.collect_windows(self.peers)
            windows.extend(peer_windows)
        if windows:
            doc = trace_join.write_joined(
                os.path.join(bundle, "trace.json"), windows)
            if errors:
                _write_json(os.path.join(bundle, "trace_errors.json"),
                            errors)
            n_cross = len(trace_join.cross_process_traces(doc))
            self._log(f"flightrec: joined trace over "
                      f"{len(windows)} window(s), {n_cross} "
                      f"cross-process request(s)")

    def _dump_peers(self, bundle: str) -> None:
        if not self.peers:
            return
        import urllib.request

        out = {}
        for url in self.peers:
            try:
                with urllib.request.urlopen(
                        url.rstrip("/") + "/flightrec",
                        timeout=5.0) as resp:
                    out[url] = json.loads(resp.read())
            except Exception as e:  # noqa: BLE001 — a dead peer is
                out[url] = {"error": repr(e)}  # itself evidence
        _write_json(os.path.join(bundle, "peers.json"), out)

    def stats(self) -> dict:
        with self._lock:
            return {
                "bundles": self.bundles,
                "suppressed": self.suppressed,
                "triggers": dict(self.triggers),
                "last_bundle": self.last_bundle,
                "ring": len(self._ring),
            }
