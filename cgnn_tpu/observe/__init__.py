"""Telemetry subsystem (first-class observability for the training stack).

Grown from the 76-line ``train/observe.py`` into four pillars:

- **in-scan metric streaming** (``stream.StepStream``): a
  ``jax.debug.callback``-based tap staged INSIDE jitted step/scan bodies
  that rings per-step scalars (loss, grad-norm, update-norm, NaN/Inf
  counts, steps/s) out to the host without fetching — the whole-epoch
  ``lax.scan`` dispatch path (``ScanEpochDriver``) stays donated and
  trajectory-identical, but per-step signals land in ``metrics.jsonl``
  as they happen instead of vanishing into epoch aggregates.
- **host span tracing** (``spans.SpanTracer``): nested wall-clock spans
  (staging, compile, device_put, warmup, epoch, eval, checkpoint)
  exported as Chrome-trace/Perfetto JSON (``trace.json``).
- **gauges/counters** (``gauges``): per-bucket padding efficiency and
  occupancy from ``PaddingStats``, per-device HBM via
  ``device.memory_stats()`` with a device-kind table fallback, loader
  wait time, and scan-vs-per-step dispatch share.
- **run manifest** (``manifest``): config, mesh/device inventory, git
  SHA, versions — written once per run (``manifest.json``).

Everything hangs off one ``Telemetry`` facade behind the train.py
``--telemetry {off,epoch,step}`` flag; the default (``epoch``) matches
the pre-existing behavior (epoch records in ``metrics.jsonl``) and
stages NO callbacks into any compiled program — only ``step`` does.
"""

from cgnn_tpu.observe.export import (
    LiveMetricsWriter,
    MetricsRegistry,
    RollingSeries,
    parse_prometheus_text,
)
from cgnn_tpu.observe.flightrec import FlightRecorder
from cgnn_tpu.observe.hist import (
    LATENCY_MS_BOUNDS,
    OCCUPANCY_BOUNDS,
    QUEUE_WAIT_MS_BOUNDS,
    Histogram,
    log_bounds,
    merge_snapshot_maps,
    quantile_from_snapshot,
    snapshots_from_family,
)
from cgnn_tpu.observe.gauges import (
    device_hbm_table_bytes,
    hbm_gauges,
    padding_gauges,
)
from cgnn_tpu.observe.manifest import write_manifest
from cgnn_tpu.observe.metrics_io import (
    MetricsLogger,
    enable_debug_nans,
    jsonfinite,
    profile_trace,
    read_jsonl,
)
from cgnn_tpu.observe.log import (
    bind_trace,
    current_trace_id,
    json_log_fn,
    setup_json_logging,
)
from cgnn_tpu.observe.profile import ProfileBusy, ProfileCapture, install_sigusr2
from cgnn_tpu.observe.slo import (
    BurnRateRule,
    SLOEngine,
    SLOObjective,
    default_rules,
)
from cgnn_tpu.observe.spans import SpanTracer
from cgnn_tpu.observe.stream import StepStream
from cgnn_tpu.observe.telemetry import Telemetry
from cgnn_tpu.observe.tsdb import TimeSeriesStore, TsdbCollector
from cgnn_tpu.observe.tracectx import (
    TRACE_PARENT_HEADER,
    format_parent,
    mint_span_id,
    parse_parent,
)

__all__ = [
    "BurnRateRule",
    "FlightRecorder",
    "Histogram",
    "LATENCY_MS_BOUNDS",
    "OCCUPANCY_BOUNDS",
    "QUEUE_WAIT_MS_BOUNDS",
    "TRACE_PARENT_HEADER",
    "LiveMetricsWriter",
    "MetricsLogger",
    "MetricsRegistry",
    "ProfileBusy",
    "ProfileCapture",
    "RollingSeries",
    "SLOEngine",
    "SLOObjective",
    "SpanTracer",
    "StepStream",
    "Telemetry",
    "TimeSeriesStore",
    "TsdbCollector",
    "bind_trace",
    "current_trace_id",
    "default_rules",
    "format_parent",
    "log_bounds",
    "merge_snapshot_maps",
    "quantile_from_snapshot",
    "snapshots_from_family",
    "install_sigusr2",
    "json_log_fn",
    "mint_span_id",
    "parse_parent",
    "parse_prometheus_text",
    "setup_json_logging",
    "device_hbm_table_bytes",
    "enable_debug_nans",
    "hbm_gauges",
    "jsonfinite",
    "padding_gauges",
    "profile_trace",
    "read_jsonl",
    "write_manifest",
]
