"""On-demand device profiling: capture a bounded ``jax.profiler`` trace
from a LIVE process — no restart, no always-on overhead.

The pre-existing profiling story (``train.py --profile N``,
``observe.metrics_io.profile_trace``) decides at LAUNCH whether to
trace; a production server that starts misbehaving on Tuesday cannot be
relaunched with a flag. :class:`ProfileCapture` turns profiling into a
runtime request:

- ``POST /profile`` (serve/http.py) and ``SIGUSR2`` (both entrypoints)
  trigger ``capture()``: start a ``jax.profiler`` trace into a fresh
  timestamped directory under the run dir, hold it for a bounded window
  (capped at ``max_duration_s`` — an operator typo must not leave the
  profiler running for an hour), stop it, and — when a span tracer is
  attached — export the CURRENT host span buffer alongside it, so the
  device trace and the host orchestration window land together.
- The gate is a non-blocking lock: a capture that arrives while one is
  running is REJECTED (:class:`ProfileBusy`) rather than stacked —
  ``jax.profiler`` supports one trace at a time, and queueing captures
  would turn a monitoring poke into a profiling marathon.

Host-side only: starting/stopping the profiler never retraces any jitted
program, so the serving zero-recompile pin and trajectory bit-exactness
are untouched (pinned by tests).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable


class ProfileBusy(RuntimeError):
    """A capture was requested while another is still running."""


def _dir_stats(root: str) -> tuple[int, int]:
    """(file count, total bytes) under ``root``."""
    files = 0
    total = 0
    for dirpath, _, names in os.walk(root):
        for name in names:
            files += 1
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                pass
    return files, total


class ProfileCapture:
    """One-at-a-time bounded device-trace captures into ``out_dir``."""

    def __init__(self, out_dir: str, *, spans=None,
                 default_duration_s: float = 1.0,
                 max_duration_s: float = 10.0,
                 log_fn: Callable = print):
        self.out_dir = out_dir
        self.spans = spans  # an observe.spans.SpanTracer, or None
        self.default_duration_s = float(default_duration_s)
        self.max_duration_s = float(max_duration_s)
        self._log = log_fn
        self._gate = threading.Lock()
        self.captures = 0
        self.rejected = 0
        self.last: dict | None = None

    @property
    def busy(self) -> bool:
        if self._gate.acquire(blocking=False):
            self._gate.release()
            return False
        return True

    def wait_idle(self, timeout_s: float = 60.0) -> bool:
        """Block until no capture is running (or the timeout passes).

        Shutdown paths call this before process exit: tearing the
        process down while ``jax.profiler`` holds an active trace
        segfaults in the profiler backend, so a drain must wait out an
        in-flight capture. Returns True when idle was reached.
        """
        if self._gate.acquire(timeout=timeout_s):
            self._gate.release()
            return True
        return False

    def capture(self, duration_s: float | None = None) -> dict:
        """Run one bounded capture; returns the artifact record
        ``{"dir", "duration_s", "files", "bytes", "host_trace"}``.

        Raises :class:`ProfileBusy` when a capture is already running
        (the non-stacking gate) and re-raises profiler start failures
        after releasing the gate.
        """
        duration = self.default_duration_s if duration_s is None \
            else float(duration_s)
        duration = max(0.05, min(duration, self.max_duration_s))
        if not self._gate.acquire(blocking=False):
            self.rejected += 1
            raise ProfileBusy(
                "a profile capture is already running; retry when it "
                "finishes (captures are rejected, never stacked)"
            )
        try:
            import jax

            stamp = time.strftime("%Y%m%d-%H%M%S")
            target = os.path.join(self.out_dir,
                                  f"profile-{stamp}-{self.captures:03d}")
            os.makedirs(target, exist_ok=True)
            t0 = time.perf_counter()
            jax.profiler.start_trace(target)
            try:
                # the capture window: whatever the process is doing runs
                # under the profiler for this long — dispatches from the
                # serving workers / the train loop, not synthetic work
                time.sleep(duration)
            finally:
                jax.profiler.stop_trace()
            record = {
                "dir": target,
                "duration_s": round(time.perf_counter() - t0, 3),
            }
            files, total = _dir_stats(target)
            record["files"], record["bytes"] = files, total
            if self.spans is not None:
                # the matching host window: the span buffer as of now,
                # exported NEXT TO the device trace (the Chrome-trace
                # stream keeps accumulating in the main trace.json)
                record["host_trace"] = self.spans.export(
                    os.path.join(target, "host_trace.json")
                )
            self.captures += 1
            self.last = record
            self._log(
                f"profile: captured {record['duration_s']:.2f}s device "
                f"trace -> {target} ({files} files, {total} bytes)"
            )
            return record
        finally:
            self._gate.release()


def install_sigusr2(capture: ProfileCapture,
                    log_fn: Callable = print) -> bool:
    """SIGUSR2 -> one default-duration capture on a background thread.

    The handler itself only spawns the thread (signal context must stay
    quick); a signal landing mid-capture is logged and dropped by the
    gate. Returns False (and installs nothing) off the main thread or on
    platforms without SIGUSR2 — callers treat profiling-by-signal as
    best-effort.
    """
    import signal

    if not hasattr(signal, "SIGUSR2"):
        return False

    def _run() -> None:
        try:
            capture.capture()
        except ProfileBusy as e:
            log_fn(f"profile: SIGUSR2 ignored ({e})")
        except Exception as e:  # noqa: BLE001 — a failed capture must
            log_fn(f"profile: capture failed: {e!r}")  # not kill the run

    def _handler(signum, frame):  # noqa: ARG001 — signal API
        threading.Thread(target=_run, daemon=True,
                         name="cgnn-profile-sigusr2").start()

    try:
        signal.signal(signal.SIGUSR2, _handler)
    except ValueError:  # not the main thread
        return False
    return True
