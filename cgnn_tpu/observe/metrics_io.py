"""metrics.jsonl writer + misc observability helpers.

``MetricsLogger`` is the one sink every telemetry record flows through:
epoch aggregates (``write``), arbitrary tagged events — step streams,
gauges, counters (``event``) — one JSON object per line, thread-safe
(the in-scan stream's host callbacks fire from runtime threads).
TensorBoard mirroring via ``clu.metric_writers`` stays best-effort, as
before.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Iterator


def jsonfinite(obj):
    """Non-finite floats -> None, recursively: ``json.dumps`` would emit
    bare ``NaN``/``Infinity`` tokens — invalid strict JSON that breaks
    jq/pandas/non-Python consumers. The shared guard every telemetry/
    report serialization routes through (graftcheck GC-JSONFINITE; the
    PR-6 metrics_live.jsonl incident)."""
    if isinstance(obj, dict):
        return {k: jsonfinite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [jsonfinite(v) for v in obj]
    if isinstance(obj, float) and (obj != obj or obj in
                                   (float("inf"), float("-inf"))):
        return None
    return obj


class MetricsLogger:
    """Epoch/event metrics -> metrics.jsonl (+ TensorBoard when available)."""

    def __init__(self, log_dir: str, use_clu: bool = True):
        self.log_dir = log_dir = log_dir or "."
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(log_dir, "metrics.jsonl")
        self._jsonl = open(self.path, "a", buffering=1)
        self._lock = threading.Lock()
        self._writer = None
        if use_clu:
            try:
                from clu import metric_writers

                self._writer = metric_writers.SummaryWriter(log_dir)
            except Exception:  # noqa: BLE001 — TF backing may be absent
                self._writer = None

    def write(self, step: int, values: dict, prefix: str = "") -> None:
        """One epoch-level record: {"step", "time", "<prefix>/<k>": v}."""
        scalars = {
            (f"{prefix}/{k}" if prefix else k): float(v)
            for k, v in values.items()
            if isinstance(v, (int, float)) and v == v  # drop NaNs
        }
        rec = {"step": int(step), "time": time.time(), **scalars}
        with self._lock:
            self._jsonl.write(json.dumps(jsonfinite(rec)) + "\n")
        if self._writer is not None:
            self._writer.write_scalars(int(step), scalars)

    def event(self, event: str, record: dict) -> None:
        """One tagged record: {"event": <tag>, "time", **record}.

        The tap between the in-scan stream / gauge emitters and the file;
        callable from any thread (host callbacks run off-thread).
        """
        rec = {"event": event, "time": time.time(), **record}
        with self._lock:
            self._jsonl.write(json.dumps(jsonfinite(rec)) + "\n")

    def flush(self) -> None:
        with self._lock:
            self._jsonl.flush()

    def close(self) -> None:
        with self._lock:
            self._jsonl.close()
        if self._writer is not None:
            self._writer.close()


def read_jsonl(path: str) -> list[dict]:
    """Load every record of a metrics.jsonl (schema round-trip helper)."""
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


@contextlib.contextmanager
def profile_trace(log_dir: str, enabled: bool = True) -> Iterator[None]:
    """jax.profiler.trace context (xprof/perfetto trace under log_dir).

    Tolerant of a profiler session already being active: jax.profiler
    supports ONE trace at a time, and an on-demand SIGUSR2/POST-profile
    capture (observe.profile.ProfileCapture) may hold it when the
    ``--profile N`` window opens — a lost launch-time trace must not
    kill the training run, so the window is skipped with a log line
    instead of propagating."""
    if not enabled:
        yield
        return
    import jax

    os.makedirs(log_dir, exist_ok=True)
    try:
        ctx = jax.profiler.trace(log_dir)
        ctx.__enter__()
    except Exception as e:  # noqa: BLE001 — profiler busy/unavailable
        print(f"profile_trace: skipped ({e!r}); is another capture "
              f"holding the profiler?")
        yield
        return
    try:
        yield
    finally:
        ctx.__exit__(None, None, None)


def enable_debug_nans() -> None:
    """Fail fast with a traceback at the first NaN any jitted op produces."""
    import jax

    jax.config.update("jax_debug_nans", True)
