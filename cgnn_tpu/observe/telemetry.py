"""The ``Telemetry`` facade: one object the training stack threads through.

Levels (the train.py ``--telemetry`` flag):

- ``off``   — true no-op: no files, no spans, no callbacks anywhere.
- ``epoch`` — the pre-existing default: epoch records in
  ``metrics.jsonl``, plus host span tracing (``trace.json``), the run
  manifest, and end-of-run gauges. Zero per-step overhead: no callback
  is staged into any compiled program.
- ``step``  — everything above plus the in-scan per-step stream
  (``StepStream``) and in-graph grad-health metrics.

Gauge/counter summaries are buffered and flushed at ``close()`` so the
FIRST records in ``metrics.jsonl`` remain the epoch-0 aggregates —
downstream consumers (and tests/test_entrypoints.py) key on that.
"""

from __future__ import annotations

import contextlib
import os
from typing import Callable, Iterator

from cgnn_tpu.observe.gauges import (
    cache_gauges,
    device_gauges,
    hbm_gauges,
    ingest_gauges,
    padding_gauges,
    pipeline_gauges,
    priority_gauges,
)
from cgnn_tpu.analysis import racecheck
from cgnn_tpu.observe.metrics_io import MetricsLogger
from cgnn_tpu.observe.spans import SpanTracer
from cgnn_tpu.observe.stream import StepStream

LEVELS = ("off", "epoch", "step")


class Telemetry:
    """Metric sink + span tracer + step stream + gauges, behind one level
    switch. Every method is safe (a no-op) at ``off``, so call sites never
    branch — except where staging a CALLBACK into compiled code is the
    difference, which is exactly what ``stream is None`` gates."""

    def __init__(self, level: str = "epoch", log_dir: str = "",
                 use_clu: bool = True, series_window_s: float = 900.0):
        if level not in LEVELS:
            raise ValueError(f"telemetry level {level!r} not in {LEVELS}")
        self.level = level
        # value-series retention window (observe_value docstring); the
        # run-summary quantiles at close cover at most this much history
        self.series_window_s = float(series_window_s)
        self.enabled = level != "off"
        self.step_level = level == "step"
        self.log_dir = log_dir
        self.logger: MetricsLogger | None = None
        self.spans: SpanTracer | None = None
        self.stream: StepStream | None = None
        if self.enabled:
            self.logger = MetricsLogger(log_dir, use_clu=use_clu)
            self.spans = SpanTracer()
        if self.step_level:
            self.stream = StepStream(self.logger)
        # instrumented under CGNN_TPU_RACECHECK=1: this lock is taken
        # from serve workers, scrape threads, and host callbacks — the
        # exact cross-thread surface lock-order inversions hide in
        self._lock = racecheck.make_lock("observe.telemetry")
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._series: dict = {}
        self._pending_events: list[tuple[str, dict]] = []
        self._padding_stats = None
        self._warmups = 0
        self._summary_written = False
        self._closed = False
        if self.enabled:
            # a run that crashes mid-training is exactly the run whose
            # telemetry matters: flush the summary and export the span
            # trace at interpreter exit if close() was never reached
            # (close() unregisters; double close is a no-op regardless)
            import atexit

            atexit.register(self.close)

    @classmethod
    def disabled(cls) -> "Telemetry":
        return cls(level="off")

    # ---- spans ----

    def span(self, name: str, **args) -> contextlib.AbstractContextManager:
        if self.spans is None:
            return contextlib.nullcontext()
        return self.spans.span(name, **args)

    # ---- epoch records (the pre-existing metrics.jsonl schema) ----

    def write_scalars(self, step: int, values: dict, prefix: str = "") -> None:
        if self.logger is not None:
            self.logger.write(step, values, prefix=prefix)

    def write_epoch(self, epoch: int, train_m: dict, val_m: dict) -> None:
        self.write_scalars(epoch, train_m, prefix="train")
        self.write_scalars(epoch, val_m, prefix="val")

    # ---- manifest ----

    def write_manifest(self, config: dict | None = None, **extra) -> None:
        if not self.enabled:
            return
        from cgnn_tpu.observe.manifest import write_manifest

        write_manifest(self.log_dir, config, **extra)

    # ---- gauges / counters (buffered; flushed at close) ----

    def counter_add(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            if self._warmups:
                return  # warmup/compile dispatches are not run work
            self._counters[name] = self._counters.get(name, 0.0) + float(value)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def set_gauge(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = float(value)

    def gauges(self) -> dict:
        """Live gauge view (the export registry scrapes this)."""
        with self._lock:
            return dict(self._gauges)

    def observe_value(self, name: str, value: float,
                      keep: int = 8192) -> None:
        """Append one sample to a windowed value series (latencies, batch
        occupancies). At close the series flushes as p50/p95/p99 + mean +
        count gauges in the run summary — the serving SLO numbers.

        Retention (observe.export.RollingSeries) is bounded BOTH ways:
        at most ``keep`` samples AND nothing older than
        ``series_window_s`` (default 15 min), with explicit eviction on
        every append/read — a days-long server's series memory stays
        flat and its quantiles describe recent traffic, not week-old
        history. The export registry reads narrower sub-windows (60 s)
        for live scrapes via ``series_quantiles(window_s=...)``."""
        if not self.enabled:
            return
        from cgnn_tpu.observe.export import RollingSeries

        with self._lock:
            series = self._series.get(name)
            if series is None or series.max_samples != keep:
                old = series
                series = RollingSeries(window_s=self.series_window_s,
                                       max_samples=keep)
                if old is not None:
                    series.reseed_from(old)
                self._series[name] = series
        series.add(float(value))

    def series_names(self) -> list[str]:
        with self._lock:
            return list(self._series)

    def series_quantiles(self, name: str,
                         window_s: float | None = None) -> dict:
        """{p50, p95, p99, mean, count} for one series ({} if empty).

        Default: everything retained (the run-summary view). Pass
        ``window_s`` for a live sub-window — the /metrics scrape."""
        with self._lock:
            series = self._series.get(name)
        if series is None:
            return {}
        return series.quantiles(window_s=window_s)

    def observe_padding(self, stats) -> None:
        """Remember the run's PaddingStats; per-bucket gauges are derived
        at close (the stats object keeps accumulating until then)."""
        if self.enabled:
            self._padding_stats = stats

    def sample_hbm(self, tag: str) -> None:
        """Sample per-device HBM now; the records flush at close."""
        if not self.enabled:
            return
        recs = [dict(r, tag=tag) for r in hbm_gauges()]
        with self._lock:
            self._pending_events.extend(("hbm", r) for r in recs)

    # ---- step-stream passthroughs (no-ops below step level) ----

    def tap_metrics(self, metrics: dict, phase: str, step=None) -> None:
        if self.stream is not None:
            self.stream.tap(metrics, phase, step=step)

    def wrap_train_body(self, body: Callable, phase: str = "train") -> Callable:
        return body if self.stream is None else self.stream.wrap_train(
            body, phase)

    def wrap_eval_body(self, body: Callable, phase: str = "eval") -> Callable:
        return body if self.stream is None else self.stream.wrap_eval(
            body, phase)

    @contextlib.contextmanager
    def warmup(self) -> Iterator[None]:
        """Mute the step stream AND the dispatch counters for
        warmup/compile dispatches (they run the real compiled programs
        but are not run work)."""
        with self._lock:
            self._warmups += 1
        try:
            if self.stream is None:
                yield
            else:
                with self.stream.muted():
                    yield
        finally:
            with self._lock:
                self._warmups -= 1

    # ---- teardown ----

    def flush_summary(self) -> None:
        """Write buffered gauges/counters/HBM/padding/dispatch-share
        events to metrics.jsonl. Emitted ONCE per run — close() calls
        it; a second call is a no-op so metrics.jsonl carries exactly
        one run_summary/padding set."""
        if not self.enabled or self.logger is None:
            return
        with self._lock:
            if self._summary_written:
                return
            self._summary_written = True
            pending, self._pending_events = self._pending_events, []
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            series_names = list(self._series)
        for name in series_names:
            q = self.series_quantiles(name)
            for stat, v in q.items():
                gauges[f"{name}_{stat}"] = v
        for name, rec in pending:
            self.logger.event(name, rec)
        if self._padding_stats is not None:
            for rec in padding_gauges(self._padding_stats):
                self.logger.event("padding", rec)
        scan = counters.get("scan_steps", 0.0)
        per_step = counters.get("per_step_steps", 0.0)
        if scan + per_step > 0:
            gauges["scan_dispatch_share"] = scan / (scan + per_step)
        gauges.update(pipeline_gauges(counters, gauges))
        gauges.update(device_gauges(counters, gauges))
        gauges.update(ingest_gauges(counters, gauges))
        gauges.update(priority_gauges(counters, gauges))
        gauges.update(cache_gauges(counters, gauges))
        if counters or gauges:
            self.logger.event("run_summary", {
                "counters": counters, "gauges": gauges,
            })

    def close(self) -> None:
        if self._closed or not self.enabled:
            self._closed = True
            return
        if self.stream is not None:
            # step callbacks are async; make sure every in-flight record
            # lands in metrics.jsonl before the summary/close
            try:
                import jax

                jax.effects_barrier()
            except Exception:  # noqa: BLE001 — jax may be torn down
                pass
        self.flush_summary()
        if self.spans is not None:
            self.spans.export(os.path.join(self.log_dir, "trace.json"))
        if self.logger is not None:
            self.logger.close()
        self._closed = True
        import atexit

        atexit.unregister(self.close)
