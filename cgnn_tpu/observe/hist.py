"""Mergeable fixed-bucket histograms — the cross-process metrics truth.

The live plane's :class:`~cgnn_tpu.observe.export.RollingSeries`
quantiles are *per-process* statistics: a p99 computed from one
replica's sample window cannot be combined with another replica's p99
into anything meaningful (quantiles do not add). That makes every
fleet-level question — "what is the fleet p99?", "are we inside the
SLO?", "how much error budget is left?" — unanswerable from summaries
alone. Histograms over a FIXED, shared bucket layout fix this by
construction: per-bucket counts are integers, integer addition is
associative and commutative, so

    merge(h_replica_0, ..., h_replica_N)
        == histogram(all raw observations pooled)

bit-exactly for the counts, regardless of which process observed what
in which order. That identity is the contract the fleet merge
(``GET /metrics/fleet``) and the SLO engine stand on, and it is pinned
by test (tests/test_slo.py).

Bucket layouts are log-spaced (:func:`log_bounds`) and FROZEN per
metric family (module constants below): every process must bucket a
family identically or the merge is meaningless — :meth:`Histogram.merge`
refuses mismatched bounds loudly. Rendering follows the Prometheus
histogram convention: cumulative ``_bucket`` samples labeled with their
inclusive upper bound ``le``, a ``+Inf`` bucket equal to ``_count``,
plus ``_sum``. Bounds and sums render via ``repr`` (shortest
round-trip float), so parse(render(h)) reconstructs the exact snapshot
— the loadgen/CI/fleet-merge shared-parser satellite.

Everything here is host-side integer bookkeeping: nothing is staged
into jitted code, so served numbers stay bit-exact and the
zero-post-warmup-recompile pin is untouched with the layer fully on.
"""

from __future__ import annotations

import bisect
import math
import re
import threading

_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_labels(labels: str) -> dict:
    """``{a="1",le="0.5"}`` -> {"a": "1", "le": "0.5"} ("" -> {})."""
    return dict(_LABEL_RE.findall(labels or ""))


def format_labels(labels: dict) -> str:
    """The inverse of :func:`parse_labels` (sorted, stable)."""
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _fmt(value: float) -> str:
    """Shortest exact round-trip rendering (float(_fmt(v)) == v)."""
    return repr(float(value))


def log_bounds(lo: float, hi: float, per_decade: int = 6) -> tuple:
    """Log-spaced inclusive upper bounds from ``lo`` up past ``hi``.

    Deterministic given the arguments — every process computing the same
    ``log_bounds(...)`` call gets bit-identical floats, which is what
    makes the bounds a cross-process contract rather than a local
    choice. The last bound is the first grid point >= ``hi``.
    """
    if lo <= 0 or hi <= lo or per_decade < 1:
        raise ValueError(f"bad log_bounds({lo}, {hi}, {per_decade})")
    start = round(math.log10(lo) * per_decade)
    bounds = []
    i = start
    while True:
        b = 10.0 ** (i / per_decade)
        bounds.append(b)
        if b >= hi:
            return tuple(bounds)
        i += 1


# the frozen per-family layouts: latency and queue-wait share one grid
# (both are milliseconds of request time; sharing lets dashboards and
# the loadgen compare them bucket-for-bucket), flush occupancy is a
# fraction in (0, 1]
LATENCY_MS_BOUNDS = log_bounds(0.1, 60_000.0, per_decade=6)
QUEUE_WAIT_MS_BOUNDS = LATENCY_MS_BOUNDS
OCCUPANCY_BOUNDS = log_bounds(0.01, 1.0, per_decade=8)
# absolute prediction error (the shadow-vs-live MAE plane, ISSUE 18):
# wide because the unit is the task's — eV/atom-scale errors and the
# deliberately-corrupted regression candidates must both land on-grid
MAE_BOUNDS = log_bounds(1e-4, 1e4, per_decade=6)


class Histogram:
    """Fixed-bucket histogram with associative, bit-exact count merge.

    ``bounds`` are strictly increasing inclusive upper bounds; values
    above the last bound land in the implicit ``+Inf`` bucket. Counts
    are integers (merge is exact); ``sum`` is a float accumulated in
    observation order (exact whenever the observed values are exactly
    representable and their running sum stays exact — the pooled-equals-
    merged test uses dyadic values for precisely this reason; real
    traffic compares sums within bucket resolution instead).

    Thread-safe; observation is O(log buckets) (bisect).
    """

    def __init__(self, bounds=LATENCY_MS_BOUNDS):
        bounds = tuple(float(b) for b in bounds)
        if not bounds:
            raise ValueError("histogram needs at least one bound")
        for a, b in zip(bounds, bounds[1:]):
            if not a < b:
                raise ValueError(f"bounds not increasing: {a} !< {b}")
        if any(not math.isfinite(b) for b in bounds):
            raise ValueError("bounds must be finite (+Inf is implicit)")
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # [+Inf] last
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    # ---- observation ----

    def observe(self, value: float) -> None:
        value = float(value)
        if value != value:  # NaN: a poisoned sample is noise, not signal
            return
        i = self._bucket_index(value)
        with self._lock:
            self._counts[i] += 1
            self._count += 1
            self._sum += value

    def _bucket_index(self, value: float) -> int:
        # first bound >= value (le is INCLUSIVE: v == bound stays in it)
        return bisect.bisect_left(self.bounds, value)

    # ---- views ----

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict:
        """One consistent ``{"bounds", "counts", "count", "sum"}`` view
        (``counts`` per-bucket, NOT cumulative; +Inf bucket last)."""
        with self._lock:
            return {
                "bounds": list(self.bounds),
                "counts": list(self._counts),
                "count": self._count,
                "sum": self._sum,
            }

    def cumulative(self) -> list:
        """Cumulative counts per bound + the +Inf total (len bounds+1)."""
        snap = self.snapshot()
        out, running = [], 0
        for c in snap["counts"]:
            running += c
            out.append(running)
        return out

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile estimate (linear within bucket).

        This is a DERIVED convenience (fleet p99 display, tsdb feed) —
        its precision is one bucket; the bucket counts are the truth.
        Returns nan when empty.
        """
        return quantile_from_snapshot(self.snapshot(), q)

    # ---- merge (the whole point) ----

    @classmethod
    def from_snapshot(cls, snap: dict) -> "Histogram":
        h = cls(snap["bounds"])
        counts = [int(c) for c in snap["counts"]]
        if len(counts) != len(h._counts):
            raise ValueError(
                f"snapshot has {len(counts)} buckets for "
                f"{len(h._counts)} bounds(+Inf)"
            )
        if any(c < 0 for c in counts):
            raise ValueError("negative bucket count in snapshot")
        h._counts = counts
        h._count = int(snap["count"])
        h._sum = float(snap["sum"])
        if h._count != sum(counts):
            raise ValueError(
                f"snapshot count {h._count} != bucket total {sum(counts)}"
            )
        return h

    def merge(self, other: "Histogram") -> "Histogram":
        """A NEW histogram = self + other (inputs untouched).

        Refuses mismatched bucket layouts: merging differently-bucketed
        families silently would produce numbers that look valid and mean
        nothing — the exact failure mode this module exists to prevent.
        """
        if self.bounds != other.bounds:
            raise ValueError(
                f"cannot merge histograms with different bounds "
                f"({len(self.bounds)} vs {len(other.bounds)} buckets; "
                f"first diff at "
                f"{next((i for i, (a, b) in enumerate(zip(self.bounds, other.bounds)) if a != b), 'length')})"
            )
        a, b = self.snapshot(), other.snapshot()
        out = Histogram(self.bounds)
        out._counts = [x + y for x, y in zip(a["counts"], b["counts"])]
        out._count = a["count"] + b["count"]
        out._sum = a["sum"] + b["sum"]
        return out

    @classmethod
    def merge_all(cls, hists) -> "Histogram":
        hists = list(hists)
        if not hists:
            raise ValueError("merge_all of no histograms")
        out = hists[0]
        for h in hists[1:]:
            out = out.merge(h)
        return out

    # ---- Prometheus exposition ----

    def exposition_lines(self, fullname: str, labels: dict | None = None
                         ) -> list:
        """The family body (no # TYPE line — the registry emits that):
        cumulative ``_bucket`` samples, ``+Inf``, ``_sum``, ``_count``.
        Extra ``labels`` (e.g. a preserved replica label) ride every
        sample beside ``le``."""
        return snapshot_exposition_lines(fullname, self.snapshot(),
                                         labels=labels)


def snapshot_exposition_lines(fullname: str, snap: dict,
                              labels: dict | None = None) -> list:
    """Render a histogram snapshot as Prometheus sample lines.

    Bounds and sums render via ``repr`` so the sibling parser
    reconstructs the exact floats — the round-trip contract.
    """
    labels = dict(labels or {})
    lines = []
    running = 0
    for b, c in zip(snap["bounds"], snap["counts"]):
        running += c
        lbl = format_labels({**labels, "le": _fmt(b)})
        lines.append(f"{fullname}_bucket{lbl} {running}")
    running += snap["counts"][-1]
    lbl = format_labels({**labels, "le": "+Inf"})
    lines.append(f"{fullname}_bucket{lbl} {running}")
    base = format_labels(labels)
    lines.append(f"{fullname}_sum{base} {_fmt(snap['sum'])}")
    lines.append(f"{fullname}_count{base} {int(snap['count'])}")
    return lines


def quantile_from_snapshot(snap: dict, q: float) -> float:
    """Bucket-resolution quantile from a histogram snapshot (nan when
    empty). Linear interpolation inside the landing bucket; the first
    bucket interpolates from 0, the +Inf bucket reports the last finite
    bound (there is no upper edge to interpolate toward)."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile must be in [0, 1], got {q}")
    total = int(snap["count"])
    if total <= 0:
        return float("nan")
    rank = q * total
    running = 0
    bounds = snap["bounds"]
    for i, c in enumerate(snap["counts"]):
        prev_running = running
        running += c
        if running >= rank and c > 0:
            if i >= len(bounds):  # +Inf bucket
                return float(bounds[-1])
            lo = 0.0 if i == 0 else float(bounds[i - 1])
            hi = float(bounds[i])
            frac = (rank - prev_running) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
    return float(bounds[-1])


def snapshots_from_family(family: dict) -> dict:
    """Reconstruct histogram snapshots from ONE parsed exposition family
    (:func:`~cgnn_tpu.observe.export.parse_prometheus_text` output for a
    ``# TYPE ... histogram`` family).

    Returns ``{label_key: snapshot}`` where ``label_key`` is the
    non-``le`` label set rendered via :func:`format_labels` ("" for an
    unlabeled family) — labels are PRESERVED through a fleet merge, so
    e.g. per-rung histograms merge per rung, never across rungs.

    Validates the Prometheus histogram invariants and raises ValueError
    on violation: every ``_bucket`` carries ``le``, cumulative counts
    are monotone non-decreasing in le order, and the ``+Inf`` bucket
    equals ``_count``.
    """
    by_key: dict = {}
    for name_labels, value in family["samples"]:
        brace = name_labels.find("{")
        name = name_labels if brace < 0 else name_labels[:brace]
        labels = parse_labels("" if brace < 0 else name_labels[brace:])
        if name.endswith("_bucket"):
            le = labels.pop("le", None)
            if le is None:
                raise ValueError(
                    f"histogram bucket sample without le label: "
                    f"{name_labels!r}"
                )
            key = format_labels(labels)
            entry = by_key.setdefault(
                key, {"buckets": [], "sum": None, "count": None})
            ub = float("inf") if le == "+Inf" else float(le)
            entry["buckets"].append((ub, value))
        elif name.endswith("_sum"):
            by_key.setdefault(format_labels(labels),
                              {"buckets": [], "sum": None, "count": None}
                              )["sum"] = value
        elif name.endswith("_count"):
            by_key.setdefault(format_labels(labels),
                              {"buckets": [], "sum": None, "count": None}
                              )["count"] = value
    out = {}
    for key, entry in by_key.items():
        buckets = sorted(entry["buckets"])
        if not buckets or not math.isinf(buckets[-1][0]):
            raise ValueError(f"histogram series {key!r} has no +Inf bucket")
        cum = [c for _, c in buckets]
        for a, b in zip(cum, cum[1:]):
            if b < a:
                raise ValueError(
                    f"histogram series {key!r} cumulative counts "
                    f"decrease ({a} -> {b}) — not a valid histogram"
                )
        if entry["count"] is not None and cum[-1] != entry["count"]:
            raise ValueError(
                f"histogram series {key!r}: +Inf bucket {cum[-1]} != "
                f"_count {entry['count']}"
            )
        counts = [int(cum[0])] + [int(b - a)
                                  for a, b in zip(cum, cum[1:])]
        out[key] = {
            "bounds": [ub for ub, _ in buckets[:-1]],
            "counts": counts,
            "count": int(cum[-1]),
            "sum": float(entry["sum"] if entry["sum"] is not None
                         else 0.0),
        }
    return out


def merge_snapshot_maps(maps) -> dict:
    """Merge N ``{label_key: snapshot}`` maps (one per scraped process)
    into one, label-set by label-set — the fleet-merge core. A label
    set present in only some processes merges what exists (a replica
    that never saw rung-2 traffic contributes nothing to rung 2)."""
    merged: dict = {}
    for m in maps:
        for key, snap in m.items():
            if key in merged:
                merged[key] = merged[key].merge(
                    Histogram.from_snapshot(snap))
            else:
                merged[key] = Histogram.from_snapshot(snap)
    return {k: h.snapshot() for k, h in merged.items()}
