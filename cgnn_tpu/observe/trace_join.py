"""Fleet trace joining: N per-process span rings -> ONE Perfetto file.

Every process in the serving fleet keeps a bounded span ring
(observe/spans.py) and serves it as a self-describing window over
``GET /trace``. This module is the other half: pull the windows, rebase
each process's relative-microsecond timestamps onto one shared
wall-clock anchor (``SpanTracer.t0_unix``), and emit a single
Chrome-trace/Perfetto document in which a hedged request reads as one
tree — the router's ``fleet.request`` root, its ``fleet.attempt`` spans
(winner and straggler both visible), and under each attempt the target
replica's ``serve.request``/``serve.pack``/``serve.dispatch`` stage
spans, connected by flow arrows keyed on the propagated span ids
(observe/tracectx.py).

Honesty rules (the truncation satellite): every source window carries
its ring's ``dropped`` count and retained bounds, and the joiner folds
them into the output — ``incomplete_processes`` lists rings that
evicted events, and the per-trace index marks any chain that cannot
prove its root survived, so a truncated join is never mistaken for a
complete one.

Clock caveat, stated rather than hidden: cross-process alignment rides
``time.time()`` sampled once per tracer, so spans from different
processes line up to NTP/wall-clock skew (sub-ms on one host, the only
deployment the fleet layer currently has) — within one process the
ordering is exact ``perf_counter``.
"""

from __future__ import annotations

import json
import os
import urllib.error
import urllib.parse
import urllib.request
import zlib

from cgnn_tpu.observe.metrics_io import jsonfinite

# joined-trace span names that root a request's tree in their process
_ROOT_NAMES = ("fleet.request", "serve.request")


def parse_since_query(path: str) -> tuple[float | None, str]:
    """``/trace?since=...`` request path -> ``(since_s, "")``, or
    ``(None, error_message)`` on a malformed value; ``(None, "")``
    when the parameter is absent. Shared by the serve and fleet HTTP
    handlers so the query contract cannot drift between them."""
    query = urllib.parse.parse_qs(urllib.parse.urlsplit(path).query)
    if "since" not in query:
        return None, ""
    try:
        return float(query["since"][0]), ""
    except ValueError:
        return None, "since must be a unix timestamp in seconds"


def fetch_window(base_url: str, since_s: float | None = None,
                 timeout_s: float = 5.0) -> dict:
    """GET one process's ``/trace`` window; raises on wire failure or a
    non-JSON body (the caller decides whether a missing process fails
    the join or just shrinks it)."""
    url = base_url.rstrip("/") + "/trace"
    if since_s is not None:
        url += "?" + urllib.parse.urlencode({"since": f"{since_s:.6f}"})
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def collect_windows(base_urls, since_s: float | None = None,
                    timeout_s: float = 5.0) -> tuple[list, dict]:
    """Pull ``/trace`` from every url; -> (windows, {url: error}).

    Unreachable processes shrink the join instead of failing it — an
    incident bundle wants whatever the survivors still hold (the dead
    replica's window died with it; that absence IS the finding)."""
    windows, errors = [], {}
    for url in base_urls:
        try:
            windows.append(fetch_window(url, since_s=since_s,
                                        timeout_s=timeout_s))
        except Exception as e:  # noqa: BLE001 — collector must survive
            errors[url] = repr(e)
    return windows, errors


def _flow_id(span_id: str) -> int:
    # Chrome-trace flow events want an integer id; crc32 of the
    # process-unique span id is stable and collision-tolerant at ring
    # scale (a colliding arrow draws wrong, it cannot corrupt spans)
    return zlib.crc32(span_id.encode())


def join_windows(windows: list) -> dict:
    """N ``SpanTracer.window()`` dicts -> one Chrome-trace document.

    Each window becomes one pid (its real OS pid + role in the process
    name metadata); timestamps rebase onto the earliest window's
    ``t0_unix``. Span-id/parent args become flow arrows so Perfetto
    draws the cross-process tree. The document additionally carries a
    ``traces`` index (trace id -> pids/spans/rooted/complete) — the
    machine-checkable join the loadgen asserts on."""
    windows = [w for w in windows if w and w.get("events") is not None]
    if not windows:
        return {"traceEvents": [], "traces": {},
                "incomplete_processes": []}
    anchor = min(float(w.get("t0_unix", 0.0)) for w in windows)
    events: list[dict] = []
    incomplete: list[str] = []
    span_ends: dict[str, tuple[int, int, float]] = {}  # sid -> (pid,tid,ts)
    children: list[tuple[str, dict]] = []              # (parent sid, event)
    traces: dict[str, dict] = {}
    for i, w in enumerate(windows):
        pid = int(w.get("pid", i))
        name = str(w.get("process", f"process-{i}"))
        role = str(w.get("role", ""))
        label = f"{role}:{name}" if role else name
        offset_us = (float(w.get("t0_unix", anchor)) - anchor) * 1e6
        dropped = int(w.get("dropped", 0))
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": label}})
        events.append({
            "name": "process_labels", "ph": "M", "pid": pid,
            "args": {"labels": f"dropped={dropped} "
                               f"window_us=[{w.get('begin_us', 0):.0f},"
                               f"{w.get('end_us', 0):.0f}]"},
        })
        if dropped:
            incomplete.append(label)
        for e in w["events"]:
            ev = dict(e)
            ev["pid"] = pid
            ev["ts"] = float(ev.get("ts", 0.0)) + offset_us
            events.append(ev)
            args = ev.get("args") or {}
            tid = args.get("trace_id")
            if tid:
                t = traces.setdefault(tid, {
                    "pids": set(), "spans": [], "rooted": False,
                    "from_truncated_ring": False,
                })
                t["pids"].add(pid)
                t["spans"].append(ev.get("name", ""))
                if ev.get("name") in _ROOT_NAMES and not args.get("parent"):
                    t["rooted"] = True
                if dropped:
                    t["from_truncated_ring"] = True
            sid = args.get("span_id")
            if sid:
                span_ends[sid] = (pid, ev.get("tid", 0),
                                  ev["ts"] + float(ev.get("dur", 0.0)))
            parent = args.get("parent")
            if parent:
                children.append((parent, ev))
    # flow arrows: parent span end -> child span start, one id per edge
    for parent, ev in children:
        src = span_ends.get(parent)
        if src is None:
            continue  # the parent's ring evicted it — the incomplete
            #           marking above already says so
        fid = _flow_id(parent + "->" + str(ev.get("args", {})
                                           .get("span_id", ev["ts"])))
        spid, stid, sts = src
        events.append({"name": "trace_parent", "cat": "trace", "ph": "s",
                       "id": fid, "pid": spid, "tid": stid,
                       "ts": max(sts - 1.0, 0.0)})
        events.append({"name": "trace_parent", "cat": "trace", "ph": "f",
                       "bp": "e", "id": fid, "pid": ev["pid"],
                       "tid": ev.get("tid", 0), "ts": ev["ts"]})
    index = {
        tid: {
            "pids": sorted(t["pids"]),
            "spans": sorted(set(t["spans"])),
            "span_count": len(t["spans"]),
            "rooted": t["rooted"],
            # complete = we saw its root AND no contributing ring had
            # evicted events; anything else renders, but marked
            "complete": t["rooted"] and not t["from_truncated_ring"],
        }
        for tid, t in traces.items()
    }
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "t0_unix": anchor,
        "incomplete_processes": incomplete,
        "traces": index,
    }


def cross_process_traces(doc: dict, min_pids: int = 2,
                         span_name: str = "fleet.attempt",
                         min_spans: int = 2) -> list:
    """Trace ids whose joined tree spans >= ``min_pids`` processes and
    carries >= ``min_spans`` ``span_name`` spans — the retried/hedged
    requests the chaos leg hard-asserts exist."""
    out = []
    counts: dict[str, int] = {}
    for e in doc.get("traceEvents", []):
        if e.get("name") == span_name:
            tid = (e.get("args") or {}).get("trace_id")
            if tid:
                counts[tid] = counts.get(tid, 0) + 1
    for tid, t in doc.get("traces", {}).items():
        if len(t["pids"]) >= min_pids and counts.get(tid, 0) >= min_spans:
            out.append(tid)
    return sorted(out)


def write_joined(path: str, windows: list) -> dict:
    """Join + write; returns the document (``traces`` index included).

    The ``traces``/``incomplete_processes`` keys ride inside the same
    JSON — Perfetto ignores unknown top-level keys, so one file serves
    both the human (open it) and the assertion (parse it)."""
    doc = join_windows(windows)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    try:
        body = json.dumps(doc, allow_nan=False)
    except ValueError:
        body = json.dumps(jsonfinite(doc))
    with open(path, "w") as f:
        f.write(body)
    return doc
