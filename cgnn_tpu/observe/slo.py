"""Declarative SLOs: error budgets, multi-window burn rates, and an
alert state machine whose firing action is a flight-recorder trigger.

The contract in one paragraph: an :class:`SLOObjective` declares what
"good" means (availability — the event succeeded; or latency — it
succeeded within a threshold) and the target fraction of good events
over an accounting window. The error BUDGET is the allowed bad
fraction (1 - target). The BURN RATE over a window is

    burn = (bad / total over the window) / (1 - target)

i.e. how many times faster than "exactly on budget" we are spending —
burn 1 spends the budget exactly at the accounting window's length,
burn 14.4 spends a 30-day budget in 2 days. A :class:`BurnRateRule`
pairs a FAST window (catches the spike quickly) with a SLOW window
(refuses to page on a blip): the alert condition holds only while BOTH
windows burn above ``factor`` — the standard multi-window construction,
here with injectable windows so a 30-second smoke test and a 30-day
production objective run the same code.

Per (objective, rule) the engine runs a state machine
``inactive -> pending -> firing -> resolved`` (``for_s`` is the
pending hold; a resolved alert RE-ARMS: a later burst walks
resolved -> pending -> firing again, pinned by test). The firing
transition invokes ``on_fire`` — the serving layers wire this to
``FlightRecorder.trigger("slo_burn_<objective>", ...)`` so an SLO page
arrives as a correlated evidence bundle (requests + metrics + joined
trace), not a log line; the bundle manifest names the alert as its
trigger reason, which fleet_smoke hard-asserts end to end.

Events aggregate into per-second buckets per objective (bounded by the
longest window, NOT by traffic volume), so recording is O(1) and a
days-long server holds minutes of state. Host-side only; injectable
clock; thread-safe.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import Callable

# the classic fast/slow pairs (Google SRE workbook ch. 5), scaled to a
# 1h budget window by default; smoke tests inject seconds-scale rules
DEFAULT_RULES = None  # sentinel: SLOEngine builds from the objectives


@dataclasses.dataclass(frozen=True)
class SLOObjective:
    """What "good" means and how much of it we promise.

    ``latency_threshold_ms`` None -> availability objective (good =
    the event succeeded); set -> latency objective (good = succeeded
    AND answered within the threshold). ``window_s`` is the error-
    budget accounting window. ``klass`` scopes the objective to one
    priority class (ISSUE 19): only events recorded with a matching
    ``klass`` feed its windows — None keeps the legacy behavior (the
    objective sees every event, whatever its class).
    """

    name: str
    target: float
    latency_threshold_ms: float | None = None
    window_s: float = 3600.0
    klass: str | None = None

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"target must be in (0, 1), got {self.target} "
                f"(a target of 1.0 has zero budget: any error is an "
                f"instant page, which is not an SLO, it is an alarm)"
            )
        if self.window_s <= 0:
            raise ValueError(f"window_s must be > 0: {self.window_s}")
        if (self.latency_threshold_ms is not None
                and self.latency_threshold_ms <= 0):
            raise ValueError(
                f"latency_threshold_ms must be > 0: "
                f"{self.latency_threshold_ms}"
            )

    def good(self, ok: bool, latency_ms: float | None) -> bool:
        if self.latency_threshold_ms is None:
            return bool(ok)
        return bool(ok) and (latency_ms is not None
                             and latency_ms <= self.latency_threshold_ms)


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """Fire while burn(fast) > factor AND burn(slow) > factor, held
    for ``for_s``."""

    fast_s: float
    slow_s: float
    factor: float
    for_s: float = 0.0

    def __post_init__(self):
        if not 0 < self.fast_s <= self.slow_s:
            raise ValueError(
                f"need 0 < fast_s <= slow_s, got {self.fast_s}/"
                f"{self.slow_s}"
            )
        if self.factor <= 0 or self.for_s < 0:
            raise ValueError(
                f"bad rule: factor={self.factor}, for_s={self.for_s}"
            )

    @property
    def key(self) -> str:
        return f"{self.fast_s:g}s_{self.slow_s:g}s_x{self.factor:g}"


def default_rules(window_s: float) -> tuple:
    """The two standard pairs scaled to the accounting window: a page
    rule (fast spend, 5%-of-window fast window) and a warn rule (slower
    spend, longer windows)."""
    return (
        BurnRateRule(fast_s=max(window_s / 12.0, 1.0),
                     slow_s=window_s, factor=14.4,
                     for_s=max(window_s / 60.0, 0.0)),
        BurnRateRule(fast_s=max(window_s / 4.0, 1.0),
                     slow_s=window_s, factor=6.0,
                     for_s=max(window_s / 24.0, 0.0)),
    )


class _Window:
    """Per-second (good, total) buckets, bounded by the horizon."""

    def __init__(self, horizon_s: float):
        self.horizon = int(math.ceil(horizon_s)) + 1
        self._buckets: collections.deque = collections.deque()
        # (t_sec, good, total); newest last

    def record(self, t: float, good: bool) -> None:
        sec = int(t)
        if self._buckets and self._buckets[-1][0] == sec:
            ts, g, n = self._buckets[-1]
            self._buckets[-1] = (ts, g + int(good), n + 1)
        else:
            self._buckets.append((sec, int(good), 1))
        cutoff = sec - self.horizon
        while self._buckets and self._buckets[0][0] < cutoff:
            self._buckets.popleft()

    def totals(self, now: float, window_s: float) -> tuple:
        """(good, total) over the trailing window at ``now``."""
        cutoff = now - window_s
        good = total = 0
        for ts, g, n in reversed(self._buckets):
            if ts < cutoff:
                break
            good += g
            total += n
        return good, total


class SLOEngine:
    """Feed it events, evaluate periodically, read alerts/budgets.

    ``record(ok, latency_ms)`` is the per-event feed (attempt-level at
    the router — retries hide errors from clients, they must NOT hide
    them from the budget; response-level on a replica).
    ``evaluate()`` advances every (objective, rule) state machine and
    returns the transitions it made. ``on_fire``/``on_resolve`` run
    OUTSIDE the engine lock (a flight-recorder dump must never block
    recording).
    """

    def __init__(self, objectives, rules=DEFAULT_RULES,
                 clock: Callable[[], float] = time.monotonic,
                 on_fire: Callable[[dict], None] | None = None,
                 on_resolve: Callable[[dict], None] | None = None,
                 max_transitions: int = 256):
        self.objectives = tuple(objectives)
        if not self.objectives:
            raise ValueError("SLOEngine needs at least one objective")
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self._rules = {}
        for obj in self.objectives:
            obj_rules = (default_rules(obj.window_s) if rules is None
                         else tuple(rules))
            self._rules[obj.name] = obj_rules
        self._clock = clock
        self.on_fire = on_fire
        self.on_resolve = on_resolve
        self._lock = threading.Lock()
        horizon = {
            o.name: max([o.window_s]
                        + [r.slow_s for r in self._rules[o.name]])
            for o in self.objectives
        }
        self._windows = {o.name: _Window(horizon[o.name])
                         for o in self.objectives}
        # (objective, rule.key) -> {"state", "since", ...}
        self._alerts = {
            (o.name, r.key): {"state": "inactive", "since": None,
                              "fired_at": None, "resolved_at": None,
                              "fire_count": 0}
            for o in self.objectives for r in self._rules[o.name]
        }
        self.transitions: collections.deque = collections.deque(
            maxlen=max_transitions)
        self.events = 0

    # ---- feed ----

    def record(self, ok: bool, latency_ms: float | None = None,
               now: float | None = None,
               klass: str | None = None) -> None:
        """One event into every objective it scopes to: class-agnostic
        objectives (``obj.klass`` None) see all events; class-scoped
        ones (ISSUE 19) see only their class. An event with no class
        feeds the class-agnostic objectives alone."""
        now = self._clock() if now is None else now
        with self._lock:
            self.events += 1
            for obj in self.objectives:
                if obj.klass is not None and obj.klass != klass:
                    continue
                self._windows[obj.name].record(
                    now, obj.good(ok, latency_ms))

    def note_status(self, status: int, latency_ms: float | None = None,
                    now: float | None = None) -> None:
        """HTTP feed: 5xx burns budget, everything else is good — a
        429/400 is the server protecting itself or the client's fault,
        not an availability failure."""
        self.record(int(status) < 500, latency_ms, now)

    # ---- evaluation ----

    def burn_rate(self, objective: str, window_s: float,
                  now: float | None = None) -> float:
        now = self._clock() if now is None else now
        obj = self._objective(objective)
        with self._lock:
            good, total = self._windows[objective].totals(now, window_s)
        if total == 0:
            return 0.0
        bad_rate = (total - good) / total
        return bad_rate / (1.0 - obj.target)

    def budget(self, objective: str, now: float | None = None) -> dict:
        """Error-budget accounting over the objective's window."""
        now = self._clock() if now is None else now
        obj = self._objective(objective)
        with self._lock:
            good, total = self._windows[objective].totals(
                now, obj.window_s)
        bad = total - good
        allowed = (1.0 - obj.target) * total
        return {
            "window_s": obj.window_s,
            "total": total,
            "bad": bad,
            "allowed": allowed,
            "remaining_frac": (1.0 - bad / allowed) if allowed > 0
            else 1.0,
        }

    def evaluate(self, now: float | None = None) -> list:
        """Advance every state machine; returns the transitions made,
        each ``{"t", "objective", "rule", "from", "to", ...}``. Fire/
        resolve hooks run after the lock is released."""
        now = self._clock() if now is None else now
        made: list = []
        hooks: list = []
        with self._lock:
            for obj in self.objectives:
                for rule in self._rules[obj.name]:
                    a = self._alerts[(obj.name, rule.key)]
                    fast = self._burn_locked(obj, rule.fast_s, now)
                    slow = self._burn_locked(obj, rule.slow_s, now)
                    cond = fast > rule.factor and slow > rule.factor
                    state = a["state"]
                    if state in ("inactive", "resolved") and cond:
                        self._move(a, obj, rule, "pending", now, made,
                                   fast, slow)
                        a["since"] = now
                        state = "pending"
                    if state == "pending":
                        if not cond:
                            self._move(a, obj, rule, "inactive", now,
                                       made, fast, slow)
                        elif now - a["since"] >= rule.for_s:
                            self._move(a, obj, rule, "firing", now,
                                       made, fast, slow)
                            a["fired_at"] = now
                            a["fire_count"] += 1
                            hooks.append(("fire", made[-1]))
                    elif state == "firing" and not cond:
                        self._move(a, obj, rule, "resolved", now, made,
                                   fast, slow)
                        a["resolved_at"] = now
                        hooks.append(("resolve", made[-1]))
        for kind, transition in hooks:
            cb = self.on_fire if kind == "fire" else self.on_resolve
            if cb is not None:
                try:
                    cb(transition)
                except Exception:  # noqa: BLE001 — a broken hook must
                    pass           # not stop alert evaluation

        return made

    def _burn_locked(self, obj, window_s: float, now: float) -> float:
        good, total = self._windows[obj.name].totals(now, window_s)
        if total == 0:
            return 0.0
        return ((total - good) / total) / (1.0 - obj.target)

    def _move(self, a, obj, rule, to: str, now: float, made: list,
              fast: float, slow: float) -> None:
        made.append({
            "t": now, "objective": obj.name, "rule": rule.key,
            "from": a["state"], "to": to,
            "burn_fast": round(fast, 4), "burn_slow": round(slow, 4),
            "factor": rule.factor,
        })
        a["state"] = to
        self.transitions.append(made[-1])

    # ---- views ----

    def _objective(self, name: str) -> SLOObjective:
        for o in self.objectives:
            if o.name == name:
                return o
        raise KeyError(f"unknown objective {name!r} "
                       f"(have: {[o.name for o in self.objectives]})")

    def alerts(self) -> dict:
        """{objective: {rule_key: alert-state dict}} (copies)."""
        with self._lock:
            out: dict = {}
            for (obj, key), a in self._alerts.items():
                out.setdefault(obj, {})[key] = dict(a)
            return out

    def firing(self) -> list:
        with self._lock:
            return [{"objective": obj, "rule": key, **a}
                    for (obj, key), a in self._alerts.items()
                    if a["state"] == "firing"]

    def state(self, now: float | None = None) -> dict:
        """The /stats view: per objective, budget + burn per rule +
        alert states; plus the transition history tail."""
        now = self._clock() if now is None else now
        with self._lock:
            out = {"objectives": {}, "events": self.events,
                   "transitions": list(self.transitions)}
        for obj in self.objectives:
            rules = {}
            for rule in self._rules[obj.name]:
                a = self.alerts()[obj.name][rule.key]
                rules[rule.key] = {
                    "fast_s": rule.fast_s, "slow_s": rule.slow_s,
                    "factor": rule.factor, "for_s": rule.for_s,
                    "burn_fast": self.burn_rate(obj.name, rule.fast_s,
                                                now),
                    "burn_slow": self.burn_rate(obj.name, rule.slow_s,
                                                now),
                    **a,
                }
            out["objectives"][obj.name] = {
                "target": obj.target,
                "latency_threshold_ms": obj.latency_threshold_ms,
                "budget": self.budget(obj.name, now),
                "rules": rules,
            }
        return out

    def gauges(self) -> dict:
        """Registry-provider gauges: budget remaining + worst burn per
        objective + the count of alerts currently firing."""
        out = {"slo_alerts_firing": float(len(self.firing()))}
        for obj in self.objectives:
            b = self.budget(obj.name)
            out[f"slo_{obj.name}_budget_remaining"] = b["remaining_frac"]
            burns = [self.burn_rate(obj.name, r.fast_s)
                     for r in self._rules[obj.name]]
            out[f"slo_{obj.name}_burn_fast"] = max(burns) if burns else 0.0
        return out
