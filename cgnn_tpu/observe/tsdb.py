"""Embedded multi-resolution time-series store — bounded history for
the live plane.

``GET /metrics`` answers "what is the p99 NOW"; nothing in the repo
answers "what was it ten minutes ago" without an external scraper. This
module is that historical substrate, embedded: a
:class:`TimeSeriesStore` holds, per metric name and per resolution
tier (default 10s / 1m / 10m), a RING of time-aligned aggregate
buckets ``{t, count, sum, min, max, last}``. Dashboards and the
planned autoscaler (ROADMAP items 3/5) read it over
``GET /timeseries?name=&res=`` on every replica and the router.

Memory is bounded by construction, never by luck: ``points_per_tier``
bounds each ring (deque maxlen — appending past the window EVICTS the
oldest bucket), ``max_series`` bounds the name space (novel names past
the cap are DROPPED and counted in ``dropped_series``, because an
unbounded label explosion must degrade the history, not the process).
Every tier aggregates independently from the same appends, so a 1m
bucket is exactly the fold of its 10s buckets — pinned by test.

:class:`TsdbCollector` is the feeder: a daemon thread appending
flattened :class:`~cgnn_tpu.observe.export.MetricsRegistry` snapshots
every ``interval_s`` (the LiveMetricsWriter pattern), with optional
``on_tick`` callbacks — the serving layers hang their periodic SLO
evaluation off the same heartbeat. Injectable clock throughout; pure
host-side bookkeeping (nothing staged into jitted code).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from cgnn_tpu.observe.hist import quantile_from_snapshot

DEFAULT_RESOLUTIONS = (("10s", 10.0), ("1m", 60.0), ("10m", 600.0))


class TimeSeriesStore:
    """Per-name, per-resolution rings of time-aligned aggregate buckets."""

    def __init__(self, resolutions=DEFAULT_RESOLUTIONS,
                 points_per_tier: int = 360, max_series: int = 512,
                 clock: Callable[[], float] = time.time):
        if points_per_tier < 1 or max_series < 1:
            raise ValueError(
                f"bad bounds: points_per_tier={points_per_tier}, "
                f"max_series={max_series}"
            )
        res = [(str(n), float(s)) for n, s in resolutions]
        if not res or any(s <= 0 for _, s in res):
            raise ValueError(f"bad resolutions: {resolutions!r}")
        if len({n for n, _ in res}) != len(res):
            raise ValueError(f"duplicate resolution names: {resolutions!r}")
        self._resolutions = dict(res)
        self.points_per_tier = int(points_per_tier)
        self.max_series = int(max_series)
        self._clock = clock
        self._lock = threading.Lock()
        # name -> res_name -> list of bucket dicts (ring, newest last)
        self._series: dict = {}
        self.dropped_series = 0
        self.appends = 0

    # ---- write ----

    def observe(self, name: str, value: float, now: float | None = None
                ) -> None:
        """Fold one scalar point into every resolution tier."""
        value = float(value)
        if value != value:  # NaN: history must stay aggregatable
            return
        now = self._clock() if now is None else float(now)
        with self._lock:
            tiers = self._series.get(name)
            if tiers is None:
                if len(self._series) >= self.max_series:
                    self.dropped_series += 1
                    return
                tiers = {res: [] for res in self._resolutions}
                self._series[name] = tiers
            self.appends += 1
            for res, step in self._resolutions.items():
                ring = tiers[res]
                t0 = (now // step) * step
                if ring and t0 <= ring[-1]["t"]:
                    # same bucket (or clock skew: fold rather than
                    # rewrite history)
                    b = ring[-1]
                    b["count"] += 1
                    b["sum"] += value
                    b["min"] = min(b["min"], value)
                    b["max"] = max(b["max"], value)
                    b["last"] = value
                else:
                    ring.append({"t": t0, "count": 1, "sum": value,
                                 "min": value, "max": value,
                                 "last": value})
                    if len(ring) > self.points_per_tier:
                        del ring[0]  # the ring bound: oldest evicted

    def append_snapshot(self, snap: dict, now: float | None = None) -> int:
        """Flatten one MetricsRegistry snapshot into scalar series.

        Counters keep their cumulative value (rate() is the reader's
        job), gauges their level, series quantiles fan out to
        ``<name>_p50/p95/p99``, histograms contribute their cumulative
        ``<name>_count``/``_sum`` plus a bucket-resolution ``_p99``
        estimate. Returns the number of points folded.
        """
        now = self._clock() if now is None else float(now)
        n = 0
        for name, value in snap.get("counters", {}).items():
            self.observe(name, float(value), now)
            n += 1
        for name, value in snap.get("gauges", {}).items():
            self.observe(name, float(value), now)
            n += 1
        for name, q in snap.get("series", {}).items():
            for key in ("p50", "p95", "p99"):
                if key in q:
                    self.observe(f"{name}_{key}", float(q[key]), now)
                    n += 1
        for name, hsnap in snap.get("histograms", {}).items():
            self.observe(f"{name}_count", float(hsnap["count"]), now)
            self.observe(f"{name}_sum", float(hsnap["sum"]), now)
            n += 2
            if hsnap["count"]:
                self.observe(f"{name}_p99",
                             quantile_from_snapshot(hsnap, 0.99), now)
                n += 1
        return n

    # ---- read ----

    def resolutions(self) -> dict:
        return dict(self._resolutions)

    def names(self) -> list:
        with self._lock:
            return sorted(self._series)

    def query(self, name: str, res: str) -> list:
        """The ring for (name, res), oldest first, each bucket
        ``{t, count, sum, min, max, last, mean}``. Unknown resolution
        raises (a typo must 400, not silently return []); an unknown
        name returns [] (the series may simply not have traffic yet).
        """
        if res not in self._resolutions:
            raise KeyError(
                f"unknown resolution {res!r} "
                f"(have: {sorted(self._resolutions)})"
            )
        with self._lock:
            ring = self._series.get(name, {}).get(res, [])
            out = []
            for b in ring:
                d = dict(b)
                d["mean"] = d["sum"] / d["count"] if d["count"] else 0.0
                out.append(d)
            return out

    def stats(self) -> dict:
        with self._lock:
            points = sum(len(ring) for tiers in self._series.values()
                         for ring in tiers.values())
            return {
                "series": len(self._series),
                "points": points,
                "appends": self.appends,
                "dropped_series": self.dropped_series,
                "resolutions": dict(self._resolutions),
                "points_per_tier": self.points_per_tier,
                "max_series": self.max_series,
            }


class TsdbCollector:
    """Daemon heartbeat: registry snapshot -> store, every interval.

    ``on_tick`` callbacks run after each append on the same thread —
    the serving layers use this for periodic SLO evaluation so the
    whole quantitative plane shares ONE timer. A callback that raises
    is swallowed per-tick (the collector must outlive a broken hook on
    a days-long server), like LiveMetricsWriter's appender.
    """

    def __init__(self, registry, store: TimeSeriesStore,
                 interval_s: float = 2.0):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.registry = registry
        self.store = store
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._ticks_cbs: list = []
        self.ticks = 0

    def add_on_tick(self, fn: Callable[[], None]) -> None:
        self._ticks_cbs.append(fn)

    def tick_once(self) -> int:
        """One collect cycle now (the testable core); returns points."""
        n = self.store.append_snapshot(self.registry.snapshot())
        for fn in list(self._ticks_cbs):
            try:
                fn()
            except Exception:  # noqa: BLE001 — heartbeat must survive
                pass
        self.ticks += 1
        return n

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick_once()
            except Exception:  # noqa: BLE001 — outlive transient hiccups
                pass

    def start(self) -> "TsdbCollector":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="cgnn-tsdb-collect"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
