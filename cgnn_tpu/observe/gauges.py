"""Gauges: padding efficiency, HBM occupancy, device inventory.

PERF.md's dominant systemic cost was padding efficiency (0.685 before
snug packing) — yet no run-time counter tracked it. ``padding_gauges``
turns a ``data.graph.PaddingStats`` into per-bucket efficiency/occupancy
records; ``hbm_gauges`` samples ``device.memory_stats()`` per device
with the device-kind table fallback (this repo's tunneled runtime
returns None from memory_stats — train/loop.py's HBM precheck shares
the same table via ``device_hbm_table_bytes``).
"""

from __future__ import annotations

# HBM per chip by device kind, for runtimes whose memory_stats() returns
# None (the table train/loop.py's device-resident capacity precheck uses)
_HBM_BYTES = {
    "TPU v5 lite": 16 << 30,  # v5e
    "TPU v5": 95 << 30,       # v5p
    "TPU v4": 32 << 30,
    "TPU v6 lite": 32 << 30,  # trillium
}


def device_hbm_table_bytes(device_kind: str) -> int | None:
    """Total HBM bytes for a device kind, or None when unknown."""
    return _HBM_BYTES.get(device_kind)


def hbm_gauges(devices=None) -> list[dict]:
    """One record per device: bytes in use / limit and the source.

    ``source`` is ``"memory_stats"`` when the backend reports live
    occupancy, ``"table"`` when only the device-kind capacity is known
    (occupancy fields absent), ``"unknown"`` when neither is available
    (CPU test meshes).
    """
    import jax

    out = []
    for d in devices if devices is not None else jax.devices():
        rec = {
            "device": str(d),
            "kind": getattr(d, "device_kind", ""),
            "platform": getattr(d, "platform", ""),
        }
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — backend-dependent, best-effort
            stats = None
        if stats and "bytes_limit" in stats:
            rec["source"] = "memory_stats"
            rec["bytes_limit"] = int(stats["bytes_limit"])
            rec["bytes_in_use"] = int(stats.get("bytes_in_use", 0))
            rec["occupancy"] = rec["bytes_in_use"] / max(
                rec["bytes_limit"], 1
            )
        else:
            total = device_hbm_table_bytes(rec["kind"])
            if total is not None:
                rec["source"] = "table"
                rec["bytes_limit"] = total
            else:
                rec["source"] = "unknown"
        out.append(rec)
    return out


def padding_gauges(stats) -> list[dict]:
    """Per-bucket padding efficiency/occupancy records from a
    ``PaddingStats`` (one record per compiled (node_cap, edge_cap)
    shape, plus an ``"overall"`` rollup)."""
    out = []
    for shape, acc in sorted(getattr(stats, "per_shape", {}).items()):
        real_n, real_e, slot_n, slot_e, batches = acc
        out.append({
            "bucket": f"{shape[0]}n/{shape[1]}e",
            "node_cap": int(shape[0]),
            "edge_cap": int(shape[1]),
            "batches": int(batches),
            "node_efficiency": real_n / max(slot_n, 1),
            "edge_efficiency": real_e / max(slot_e, 1),
        })
    out.append({
        "bucket": "overall",
        "batches": int(stats.batches),
        "node_efficiency": stats.node_efficiency,
        "edge_efficiency": stats.edge_efficiency,
        "shapes": len(stats.shapes),
    })
    return out


def device_gauges(counters: dict, gauges: dict) -> dict:
    """Derived health figures for the device-parallel dispatch layer
    (serve/devices.py, ISSUE 5), from a run's counters/gauges — the
    ``pipeline_gauges`` analog for the device dimension.

    ``DeviceSet.flush_gauges`` writes the raw per-device names
    (``device{i}_dispatches`` / ``device{i}_occupancy`` /
    ``device{i}_window_depth`` plus ``device_count``); this rollup adds:

    - ``devices_active``: devices that dispatched at least one flush —
      the 8-host-device dryrun's distribution invariant keys on this;
    - ``device_dispatch_min_share`` / ``device_dispatch_max_share``:
      each device's share of total dispatches — min near 1/N means the
      least-loaded router balanced, max near 1 means one chip served
      everything (the pre-ISSUE-5 shape).
    """
    n = int(gauges.get("device_count", 0))
    if n <= 0:
        return {}
    dispatches = [float(gauges.get(f"device{i}_dispatches", 0.0))
                  for i in range(n)]
    total = sum(dispatches)
    out = {"devices_active": float(sum(1 for d in dispatches if d > 0))}
    if total > 0:
        shares = [d / total for d in dispatches]
        out["device_dispatch_min_share"] = min(shares)
        out["device_dispatch_max_share"] = max(shares)
    return out


def ingest_gauges(counters: dict, gauges: dict) -> dict:
    """Derived health figures for the on-device ingest path (ISSUE 11),
    from a run's counters/gauges — the raw-wire analog of
    ``pipeline_gauges``.

    - ``ingest_cap_overflow_total``: structures the IN-PROGRAM
      neighbor search flagged (lattice needed more periodic images than
      the rung provides) and re-served host-featurized. Non-zero on a
      calibrated ladder means the image caps are mis-planned for live
      traffic — loadgen asserts zero;
    - ``ingest_rung{i}_edge_occupancy``: true in-program edge count
      over allocated edge slots per rung, the signal for re-calibrating
      ``snode_cap``/``dense_m`` (occupancy near 0 = caps too generous,
      padded search work; near 1 = truncation pressure).
    """
    out = {}
    if "ingest_cap_overflow" in counters:
        out["ingest_cap_overflow_total"] = float(
            counters["ingest_cap_overflow"])
    occ = {k: float(v) for k, v in gauges.items()
           if k.startswith("ingest_rung") and k.endswith("_edge_occupancy")}
    if occ:
        out.update(sorted(occ.items()))
        out["ingest_edge_occupancy_min"] = min(occ.values())
        out["ingest_edge_occupancy_max"] = max(occ.values())
    if "ingest_raw_wire" in gauges:
        out["ingest_raw_wire"] = float(gauges["ingest_raw_wire"])
    return out


def priority_gauges(counters: dict, gauges: dict) -> dict:
    """Derived health figures for priority-class serving (ISSUE 19),
    from a run's counters/gauges — the ``ingest_gauges`` analog for the
    continuous batcher's front door.

    - ``serve_padding_fill_share``: of the graph slots higher-class
      flushes would have PADDED, the fraction lower-class backfill
      actually filled — the padding→goodput conversion rate (0 with
      backfill off or under single-class load);
    - ``serve_class_{c}_responses``: answers per priority class, the
      share view WFQ/aging fairness assertions read;
    - ``serve_backfilled_total``: responses that rode another class's
      flush slack rather than waiting for their own cut.
    """
    out = {}
    if "serve_padding_fill_share" in gauges:
        out["serve_padding_fill_share"] = float(
            gauges["serve_padding_fill_share"])
    if "serve_backfill_enabled" in gauges:
        out["serve_backfill_enabled"] = float(
            gauges["serve_backfill_enabled"])
    if "serve_responses_backfilled" in counters:
        out["serve_backfilled_total"] = float(
            counters["serve_responses_backfilled"])
    classes = {k: float(v) for k, v in counters.items()
               if k.startswith("serve_responses_class_")}
    for k, v in sorted(classes.items()):
        out[k.replace("serve_responses_class_", "serve_class_")
            + "_responses"] = v
    if classes and sum(classes.values()) > 0:
        total = sum(classes.values())
        out["serve_class_max_share"] = max(classes.values()) / total
    return out


def cache_gauges(counters: dict, gauges: dict) -> dict:
    """Derived health figures for the fleet-partitioned result cache
    (ISSUE 20), from a run's counters/gauges — the ``priority_gauges``
    analog for the cache plane.

    - ``serve_cache_hit_ratio``: raw LRU hits over lookups, from the
      cache's CONSISTENT snapshot counters (one lock acquisition — the
      pre-snapshot scrape could pair counts from different instants);
    - ``serve_cache_fill_ratio``: occupied over capacity;
    - ``serve_cache_effective_hit_ratio``: answers that needed no
      forward pass on THIS replica — version-valid hits plus coalesced
      followers — over requests. The bench A/B's headline figure;
    - ``serve_cache_coalesced_share`` / ``serve_cache_dup_miss_total``:
      single-flight conversion rate and the duplicate in-flight misses
      the stampede assertion pins to 0 when coalescing is on;
    - ``fleet_owner_routed_share``: of owner-routable dispatches, the
      fraction the healthy owner actually answered (router-side).
    """
    out = {}
    hits = float(counters.get("serve_cache_lookup_hits", 0.0))
    misses = float(counters.get("serve_cache_lookup_misses", 0.0))
    if hits + misses > 0:
        out["serve_cache_hit_ratio"] = hits / (hits + misses)
    cap = float(gauges.get("serve_cache_capacity", 0.0))
    if cap > 0:
        out["serve_cache_fill_ratio"] = (
            float(gauges.get("serve_cache_size", 0.0)) / cap)
    requests = float(counters.get("serve_requests", 0.0))
    valid_hits = float(counters.get("serve_cache_hits", 0.0))
    coalesced = float(counters.get("serve_cache_coalesced", 0.0))
    if requests > 0:
        out["serve_cache_effective_hit_ratio"] = (
            (valid_hits + coalesced) / requests)
        out["serve_cache_coalesced_share"] = coalesced / requests
    if "serve_cache_dup_misses" in counters:
        out["serve_cache_dup_miss_total"] = float(
            counters["serve_cache_dup_misses"])
    if "serve_cache_fills" in counters:
        out["serve_cache_fill_total"] = float(counters["serve_cache_fills"])
    routed = float(counters.get("fleet_owner_routed", 0.0))
    fallback = float(counters.get("fleet_owner_fallback", 0.0))
    if routed + fallback > 0:
        out["fleet_owner_routed_share"] = routed / (routed + fallback)
    return out


def pipeline_gauges(counters: dict, gauges: dict) -> dict:
    """Derived health figures for the parallel ingest pipeline
    (data/pipeline.py), from a run's counters/gauges — the
    ``loader_wait_s`` analog for the forward path.

    - ``pipeline_wait_share``: consumer wait over (wait + pack) — near 0
      means the packers kept the dispatch loop fed; near 1 means the
      device idled on the host (add workers / enable compact staging);
    - ``pipeline_pack_s_per_job``: mean worker seconds per packed batch.

    The raw series (``pipeline_wait_s`` p50/p95/p99 via
    ``Telemetry.observe_value``) and the ``pipeline_occupancy`` gauge the
    pipeline sets directly complement these rollups.
    """
    wait = float(counters.get("pipeline_wait_s", 0.0))
    pack = float(counters.get("pipeline_pack_s", 0.0))
    jobs = float(counters.get("pipeline_jobs", 0.0))
    out = {}
    if wait + pack > 0:
        out["pipeline_wait_share"] = wait / (wait + pack)
    if jobs > 0:
        out["pipeline_pack_s_per_job"] = pack / jobs
    if "pipeline_occupancy" in gauges:
        out["pipeline_occupancy"] = float(gauges["pipeline_occupancy"])
    return out
