"""Host-side span tracing -> Chrome-trace/Perfetto JSON.

Lightweight nested wall-clock spans for the host orchestration phases the
device profiler cannot see (featurize, pack, stage, compile+warmup, epoch
dispatch, checkpoint writes). ``SpanTracer.span`` is a context manager;
nesting is tracked per thread and exported as complete events (``"ph":
"X"``) in the Chrome trace event format, which Perfetto and
``chrome://tracing`` open directly.

Timestamps are ``time.perf_counter`` microseconds relative to tracer
construction (Chrome traces only need a consistent monotonic base).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Iterator


class SpanTracer:
    """Nested host spans; ``export()`` writes trace.json (Chrome format)."""

    def __init__(self, process_name: str = "cgnn-tpu host"):
        self._t0 = time.perf_counter()
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._depth = threading.local()
        self._tids: dict[int, int] = {}
        self._process_name = process_name

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _tid(self) -> int:
        # stable small ints per thread (raw thread idents overflow the
        # int32 tid some trace viewers assume)
        ident = threading.get_ident()
        with self._lock:
            return self._tids.setdefault(ident, len(self._tids))

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        """Time a block; ``args`` become the event's args dict (viewable
        in the Perfetto detail pane)."""
        depth = getattr(self._depth, "value", 0)
        self._depth.value = depth + 1
        start = self._now_us()
        try:
            yield
        finally:
            self._depth.value = depth
            event = {
                "name": name,
                "ph": "X",
                "ts": start,
                "dur": self._now_us() - start,
                "pid": 0,
                "tid": self._tid(),
                "args": {k: v for k, v in args.items()} | {"depth": depth},
            }
            with self._lock:
                self._events.append(event)

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker event."""
        event = {
            "name": name,
            "ph": "i",
            "ts": self._now_us(),
            "s": "t",
            "pid": 0,
            "tid": self._tid(),
            "args": dict(args),
        }
        with self._lock:
            self._events.append(event)

    @property
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def export(self, path: str) -> str:
        """Write the Chrome trace JSON; returns the path."""
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 0,
                "args": {"name": self._process_name},
            }
        ]
        doc = {"traceEvents": meta + self.events, "displayTimeUnit": "ms"}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return path
