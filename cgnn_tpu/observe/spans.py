"""Host-side span tracing -> Chrome-trace/Perfetto JSON.

Lightweight nested wall-clock spans for the host orchestration phases the
device profiler cannot see (featurize, pack, stage, compile+warmup, epoch
dispatch, checkpoint writes). ``SpanTracer.span`` is a context manager;
nesting is tracked per thread and exported as complete events (``"ph":
"X"``) in the Chrome trace event format, which Perfetto and
``chrome://tracing`` open directly.

Timestamps are ``time.perf_counter`` microseconds relative to tracer
construction (Chrome traces only need a consistent monotonic base).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Iterator

from cgnn_tpu.observe.metrics_io import jsonfinite


class SpanTracer:
    """Nested host spans; ``export()`` writes trace.json (Chrome format).

    The event buffer is a BOUNDED RING (``max_events``): per-request
    serving spans at thousands of rps would otherwise grow a days-long
    server's trace without limit. Once full, the OLDEST events are
    evicted (and counted in ``dropped``) — the live-tracing consumers
    (reconstructing a recent slow request, a profile capture's host
    window) need the most recent spans, not the startup era — and
    ``export`` stamps the drop count into the trace metadata so a
    truncated trace is never mistaken for a complete one.
    """

    def __init__(self, process_name: str = "cgnn-tpu host",
                 max_events: int = 200_000):
        import collections

        self._t0 = time.perf_counter()
        # the wall-clock epoch of _t0: how a fleet joiner rebases this
        # process's relative-µs timestamps onto a timeline SHARED with
        # other processes' rings (observe/trace_join.py). Sampled at
        # the same instant as _t0, so abs(event) = t0_unix + ts/1e6.
        self.t0_unix = time.time()
        self._events: collections.deque = collections.deque(
            maxlen=int(max_events))
        self._lock = threading.Lock()
        self._depth = threading.local()
        self._tids: dict[int, int] = {}
        self._process_name = process_name
        self.max_events = int(max_events)
        self.dropped = 0

    @staticmethod
    def now_s() -> float:
        """The stamp clock (``time.perf_counter`` seconds). Callers that
        record per-stage timestamps for later ``complete()`` calls must
        use THIS clock so retro-stamped spans line up with live ones."""
        return time.perf_counter()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _append(self, event: dict) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1  # the deque evicts its oldest entry
            self._events.append(event)

    def _tid(self) -> int:
        # stable small ints per thread (raw thread idents overflow the
        # int32 tid some trace viewers assume)
        ident = threading.get_ident()
        with self._lock:
            return self._tids.setdefault(ident, len(self._tids))

    @contextlib.contextmanager
    def span(self, name: str, **args) -> Iterator[None]:
        """Time a block; ``args`` become the event's args dict (viewable
        in the Perfetto detail pane)."""
        depth = getattr(self._depth, "value", 0)
        self._depth.value = depth + 1
        start = self._now_us()
        try:
            yield
        finally:
            self._depth.value = depth
            event = {
                "name": name,
                "ph": "X",
                "ts": start,
                "dur": self._now_us() - start,
                "pid": 0,
                "tid": self._tid(),
                "args": {k: v for k, v in args.items()} | {"depth": depth},
            }
            self._append(event)

    def complete(self, name: str, start_s: float, end_s: float,
                 **args) -> None:
        """Record a span from explicit ``now_s()`` stamps taken earlier
        — the request-tracing path, where a stage's start was stamped on
        one thread and its end observed on another. Emitted on the
        calling thread's track."""
        if end_s < start_s:
            start_s, end_s = end_s, start_s
        self._append({
            "name": name,
            "ph": "X",
            "ts": (start_s - self._t0) * 1e6,
            "dur": (end_s - start_s) * 1e6,
            "pid": 0,
            "tid": self._tid(),
            "args": dict(args),
        })

    def instant(self, name: str, **args) -> None:
        """Zero-duration marker event."""
        self._append({
            "name": name,
            "ph": "i",
            "ts": self._now_us(),
            "s": "t",
            "pid": 0,
            "tid": self._tid(),
            "args": dict(args),
        })

    @property
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def window(self, since_s: float | None = None) -> dict:
        """The ring as one self-describing dict — what ``GET /trace``
        serves (the fleet-join wire format, observe/trace_join.py).

        Carries everything a joiner needs to NOT silently render a
        partial tree: ``dropped`` (ring evictions so far) plus the
        retained window's bounds (``begin_us``/``end_us``, relative µs
        like the event timestamps) — a chain whose root predates
        ``begin_us`` is provably incomplete, not merely sparse.
        ``since_s`` (unix seconds) filters to events ending at or after
        that wall-clock instant (incremental pulls)."""
        with self._lock:
            events = list(self._events)
            dropped = self.dropped
        end_us = self._now_us()
        begin_us = events[0]["ts"] if events else end_us
        if since_s is not None:
            cut_us = (float(since_s) - self.t0_unix) * 1e6
            events = [e for e in events
                      if e["ts"] + e.get("dur", 0.0) >= cut_us]
        return {
            "process": self._process_name,
            "pid": os.getpid(),
            "t0_unix": self.t0_unix,
            "dropped": dropped,
            "max_events": self.max_events,
            "begin_us": begin_us,
            "end_us": end_us,
            "events": events,
        }

    def export(self, path: str) -> str:
        """Write the Chrome trace JSON; returns the path."""
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 0,
                "args": {"name": self._process_name},
            }
        ]
        with self._lock:
            dropped = self.dropped
        if dropped:
            meta.append({
                "name": "events_dropped",
                "ph": "M",
                "pid": 0,
                "args": {"dropped": dropped,
                         "max_events": self.max_events},
            })
        doc = {"traceEvents": meta + self.events, "displayTimeUnit": "ms"}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # span args can carry request-derived floats; non-finite -> null
        # keeps trace.json loadable by Perfetto's strict parser
        # (graftcheck GC-JSONFINITE). Serialize BEFORE opening so the
        # all-finite common case never deep-copies a 200k-event ring and
        # a non-finite fallback can't leave a truncated file behind.
        try:
            body = json.dumps(doc, allow_nan=False)
        except ValueError:
            body = json.dumps(jsonfinite(doc))
        with open(path, "w") as f:
            f.write(body)
        return path
