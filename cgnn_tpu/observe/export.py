"""Streaming metrics export: scrape a live run instead of its post-mortem.

PR 1's telemetry buffers counters/gauges/series and flushes ONE
``run_summary`` record at ``close()`` — perfect for a bench round,
useless for a serving fleet that needs to know its p99 NOW. This module
is the live half of the plane:

- :class:`RollingSeries` — a time-windowed value series (latencies,
  occupancies) with EXPLICIT eviction: samples older than ``window_s``
  (and beyond ``max_samples``) are dropped on every append and on every
  read, so a days-long server holds a bounded, recent window instead of
  a run-lifetime list. Quantiles therefore describe *the last minute*,
  which is what an SLO dashboard wants.
- :class:`MetricsRegistry` — one scrape point aggregating the
  ``Telemetry`` buffers (counters/gauges/series, read LIVE, not at
  close) plus any number of provider callbacks (the serving core
  registers one exposing its request counts, rolling latency, and
  per-device in-flight depth). ``snapshot()`` returns the merged dict;
  ``prometheus_text()`` renders the Prometheus exposition format the
  ``GET /metrics`` endpoint serves (counters -> ``*_total`` counter
  families, series -> summary families with quantile labels,
  ``device{i}_*`` gauges -> one ``device`` label per chip).
- :class:`LiveMetricsWriter` — a periodic appender writing registry
  snapshots to ``metrics_live.jsonl``, so training runs and headless
  fleets are observable mid-flight with no HTTP endpoint at all
  (``train.py --live-metrics N`` / ``serve.py --live-metrics N``).

Everything here is host-side bookkeeping: nothing is staged into jitted
code, so trajectories and served numbers are bit-identical with the
plane on or off, and the zero-post-warmup-recompile pin is untouched.
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import Callable

from cgnn_tpu.observe import hist as _hist
from cgnn_tpu.observe.metrics_io import jsonfinite

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")
# device{i}_metric / replica{i}_metric gauges become one labeled family
# per metric (the per-chip and per-fleet-replica series in /metrics)
_DEVICE_GAUGE = re.compile(r"^device(\d+)_(\w+)$")
_REPLICA_GAUGE = re.compile(r"^replica(\d+)_(\w+)$")


class RollingSeries:
    """Bounded, time-windowed samples with on-demand quantiles.

    Retention is the AND of two bounds — ``max_samples`` (a hard memory
    cap, like the old deque) and ``window_s`` (age) — and eviction is
    explicit: ``evict()`` runs on every ``add`` and every read, so the
    structure never holds samples it would not report. ``clock`` is
    injectable for deterministic eviction tests.
    """

    def __init__(self, window_s: float = 900.0, max_samples: int = 8192,
                 clock: Callable[[], float] = time.monotonic):
        if window_s <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._clock = clock
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque(
            maxlen=self.max_samples
        )  # (monotonic t, value)
        self.total_count = 0   # lifetime appends (the _count a scraper sums)
        self.total_sum = 0.0
        self.evicted = 0

    def add(self, value: float, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            if (self._samples
                    and len(self._samples) == self._samples.maxlen):
                self.evicted += 1  # deque drop (count bound)
            self._samples.append((now, float(value)))
            self.total_count += 1
            self.total_sum += float(value)
            self._evict_locked(now)

    def _evict_locked(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._samples and self._samples[0][0] < cutoff:
            self._samples.popleft()
            self.evicted += 1

    def evict(self, now: float | None = None) -> None:
        """Drop samples older than the window (also runs on add/read)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._evict_locked(now)

    def values(self, now: float | None = None,
               window_s: float | None = None) -> list:
        """Samples inside the window (optionally a narrower one)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._evict_locked(now)
            if window_s is None:
                return [v for _, v in self._samples]
            cutoff = now - min(window_s, self.window_s)
            return [v for t, v in self._samples if t >= cutoff]

    def __len__(self) -> int:
        with self._lock:
            self._evict_locked(self._clock())
            return len(self._samples)

    def reseed_from(self, old: "RollingSeries") -> "RollingSeries":
        """Carry another series' samples AND lifetime totals into this
        one (the keep-change migration path in Telemetry.observe_value)
        — totals must survive, they are the cumulative _count/_sum a
        Prometheus scraper rates over."""
        with old._lock:
            samples = list(old._samples)
            count, total, evicted = (old.total_count, old.total_sum,
                                     old.evicted)
        with self._lock:
            self._samples.extend(samples)
            self.total_count += count
            self.total_sum += total
            self.evicted += evicted
        return self

    def quantiles(self, now: float | None = None,
                  window_s: float | None = None) -> dict:
        """{p50, p95, p99, mean, count, count_total, sum_total} over the
        (sub-)window; {} when empty. ``count``/``mean`` describe the
        window; ``count_total``/``sum_total`` are LIFETIME cumulative
        (what a Prometheus summary's _count/_sum must be — they may
        never decrease, while a windowed count shrinks as samples age
        out)."""
        vals = self.values(now, window_s=window_s)
        if not vals:
            return {}
        import numpy as np

        arr = np.asarray(vals, np.float64)
        p50, p95, p99 = np.percentile(arr, [50, 95, 99])
        with self._lock:
            count_total, sum_total = self.total_count, self.total_sum
        return {
            "p50": float(p50), "p95": float(p95), "p99": float(p99),
            "mean": float(arr.mean()), "count": len(vals),
            "count_total": count_total, "sum_total": sum_total,
        }


def sanitize_metric_name(name: str) -> str:
    """A Prometheus-legal metric name (invalid chars -> '_')."""
    name = _NAME_FIX.sub("_", str(name))
    if not name or not _NAME_OK.match(name):
        name = "_" + name
    return name


class MetricsRegistry:
    """The scrape point: telemetry buffers + provider callbacks, merged.

    Providers are zero-arg callables returning any of
    ``{"counters": {...}, "gauges": {...}, "series": {name: quantiles},
    "histograms": {name: snapshot}}`` — evaluated at snapshot time, so
    every scrape sees live values. Histogram snapshots are
    ``observe.hist.Histogram.snapshot()`` dicts and render as Prometheus
    histogram families (cumulative ``_bucket``/``le`` + ``_sum`` +
    ``_count``) — the MERGEABLE cross-process complement to the
    per-process summary quantiles. A provider that raises is skipped for
    that scrape (a broken gauge must not take down ``/metrics``); the
    error is remembered in ``last_provider_errors``.
    """

    def __init__(self, namespace: str = "cgnn",
                 window_s: float = 60.0):
        self.namespace = sanitize_metric_name(namespace)
        self.window_s = float(window_s)
        self._telemetry = None
        self._providers: list[tuple[str, Callable[[], dict]]] = []
        self._lock = threading.Lock()
        self.last_provider_errors: dict[str, str] = {}

    def attach_telemetry(self, telemetry) -> "MetricsRegistry":
        """Expose a ``Telemetry``'s live counters/gauges/series (no-op
        buffers at level 'off' simply contribute nothing)."""
        self._telemetry = telemetry
        return self

    def add_provider(self, name: str, fn: Callable[[], dict]) -> None:
        with self._lock:
            self._providers.append((name, fn))

    # ---- snapshot ----

    def snapshot(self, window_s: float | None = None) -> dict:
        """Merged live view: {"time", "counters", "gauges", "series"}.

        Series quantiles cover the rolling window (``window_s`` defaults
        to the registry's, 60 s) — NOT the run lifetime; that is the
        whole point of the live plane.
        """
        window_s = self.window_s if window_s is None else window_s
        out = {"time": time.time(), "counters": {}, "gauges": {},
               "series": {}, "histograms": {}}
        t = self._telemetry
        if t is not None and getattr(t, "enabled", False):
            out["counters"].update(t.counters())
            out["gauges"].update(t.gauges())
            for name in t.series_names():
                q = t.series_quantiles(name, window_s=window_s)
                if q:
                    out["series"][name] = q
        with self._lock:
            providers = list(self._providers)
        for name, fn in providers:
            try:
                part = fn() or {}
            except Exception as e:  # noqa: BLE001 — scrape must survive
                self.last_provider_errors[name] = repr(e)
                continue
            self.last_provider_errors.pop(name, None)
            out["counters"].update(part.get("counters", {}))
            out["gauges"].update(part.get("gauges", {}))
            out["series"].update(part.get("series", {}))
            out["histograms"].update(part.get("histograms", {}))
        return out

    # ---- Prometheus exposition ----

    def prometheus_text(self, window_s: float | None = None) -> str:
        """The ``GET /metrics`` body (text exposition format 0.0.4)."""
        snap = self.snapshot(window_s=window_s)
        ns = self.namespace
        lines: list[str] = []

        def emit(name: str, kind: str, samples: list[tuple[str, float]],
                 help_text: str = "") -> None:
            full = f"{ns}_{sanitize_metric_name(name)}"
            if help_text:
                lines.append(f"# HELP {full} {help_text}")
            lines.append(f"# TYPE {full} {kind}")
            for labels, value in samples:
                if value != value:  # NaN: Prometheus accepts it, but a
                    continue        # poisoned gauge is noise, not signal
                lines.append(f"{full}{labels} {value:g}")

        for name, value in sorted(snap["counters"].items()):
            cname = name if name.endswith("_total") else f"{name}_total"
            emit(cname, "counter", [("", float(value))])

        # fold device{i}_* / replica{i}_* gauges into labeled families
        labeled_fams: dict[str, list[tuple[str, float]]] = {}
        plain: list[tuple[str, float]] = []
        for name, value in sorted(snap["gauges"].items()):
            for pattern, label in ((_DEVICE_GAUGE, "device"),
                                   (_REPLICA_GAUGE, "replica")):
                m = pattern.match(name)
                if m:
                    labeled_fams.setdefault(
                        f"{label}_{m.group(2)}", []).append(
                        (f'{{{label}="{m.group(1)}"}}', float(value))
                    )
                    break
            else:
                plain.append((name, float(value)))
        for name, value in plain:
            emit(name, "gauge", [("", value)])
        for fam, samples in sorted(labeled_fams.items()):
            emit(fam, "gauge", samples)

        for name, q in sorted(snap["series"].items()):
            samples = [(f'{{quantile="{lbl}"}}', q[key])
                       for lbl, key in (("0.5", "p50"), ("0.95", "p95"),
                                        ("0.99", "p99"))
                       if key in q]
            emit(name, "summary", samples)
            full = f"{ns}_{sanitize_metric_name(name)}"
            # _count/_sum MUST be cumulative (a windowed count shrinks
            # as samples age out, which rate()/increase() reads as a
            # counter reset); fall back to the window only for provider
            # series that carry no lifetime totals
            if "count_total" in q:
                lines.append(f"{full}_count {int(q['count_total'])}")
                lines.append(f"{full}_sum {q['sum_total']:g}")
            else:
                if "count" in q:
                    lines.append(f"{full}_count {int(q['count'])}")
                if "mean" in q and "count" in q:
                    lines.append(f"{full}_sum {q['mean'] * q['count']:g}")

        # mergeable histogram families (observe/hist.py): cumulative
        # _bucket/le + _sum/_count, bounds and sums rendered at full
        # round-trip precision — the cross-process truth the fleet
        # merge and the SLO engine consume. A provider key may carry a
        # label set (`name{param_version="..."}`, ISSUE 18): labeled
        # members group under ONE family declaration, labels riding
        # every sample — the per-version serve latency families.
        hist_fams: dict[str, list[tuple[dict | None, dict]]] = {}
        for key, hsnap in sorted(snap["histograms"].items()):
            name, labels = key, None
            if "{" in key:
                name, _, rest = key.partition("{")
                labels = _hist.parse_labels("{" + rest)
            full = f"{ns}_{sanitize_metric_name(name)}"
            hist_fams.setdefault(full, []).append((labels, hsnap))
        for full, members in sorted(hist_fams.items()):
            body: list[str] = []
            ok = True
            for labels, hsnap in members:
                try:
                    body.extend(_hist.snapshot_exposition_lines(
                        full, hsnap, labels=labels))
                except Exception as e:  # noqa: BLE001 — a malformed
                    # provider snapshot must not take down the scrape
                    self.last_provider_errors[f"histogram:{full}"] = repr(e)
                    ok = False
            if body or ok:
                lines.append(f"# TYPE {full} histogram")
                lines.extend(body)
        return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> dict:
    """Strict-enough parser for the exposition format (shared by the
    loadgen assertion and the CI metrics-scrape step — the validator
    must live WITH the emitter so they cannot drift).

    Returns {family: {"type": str, "samples": [(labels, value), ...]}}.
    Raises ValueError on a line that is neither a comment, blank, nor a
    ``name[{labels}] value`` sample, or on an unparseable value.

    Histogram families round-trip STRUCTURALLY: every declared-histogram
    family is validated on parse (each ``_bucket`` carries ``le``,
    cumulative counts are monotone non-decreasing in le order, ``+Inf``
    equals ``_count``) and its reconstructed per-label-set snapshots —
    ``observe.hist.Histogram.from_snapshot``-ready — land under the
    family's ``"histogram"`` key. The fleet merge, the loadgen
    distribution assert, and CI all consume THIS parser, so emitter and
    validators cannot drift.
    """
    fams: dict[str, dict] = {}
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)(\s+\d+)?$"
    )
    declared_type: dict[str, str] = {}
    for i, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                declared_type[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        m = sample_re.match(line)
        if not m:
            raise ValueError(f"line {i} is not a valid sample: {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        try:
            fval = float(value)
        except ValueError:
            raise ValueError(
                f"line {i}: unparseable value {value!r} for {name}"
            ) from None
        # summary _sum/_count samples belong to their base family
        base = name
        for suffix in ("_sum", "_count", "_bucket"):
            if name.endswith(suffix) and name[: -len(suffix)] in declared_type:
                base = name[: -len(suffix)]
                break
        fam = fams.setdefault(
            base, {"type": declared_type.get(base, "untyped"), "samples": []}
        )
        fam["samples"].append((name + labels, fval))
    for fname, fam in fams.items():
        if fam["type"] == "histogram":
            try:
                fam["histogram"] = _hist.snapshots_from_family(fam)
            except ValueError as e:
                raise ValueError(
                    f"invalid histogram family {fname!r}: {e}"
                ) from None
    return fams


class LiveMetricsWriter:
    """Periodic registry snapshots -> ``metrics_live.jsonl``.

    One JSON object per line (``{"time", "counters", "gauges",
    "series"}``), appended every ``interval_s`` by a daemon thread —
    the scrape path for runs with no HTTP surface (training). The file
    is opened lazily and append-mode, so a restarted run extends it.
    """

    def __init__(self, registry: MetricsRegistry, path: str,
                 interval_s: float = 10.0):
        if interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.registry = registry
        self.path = path
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self.writes = 0

    def write_once(self) -> dict:
        """Append one snapshot now; returns it (the testable core)."""
        snap = self.registry.snapshot()
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with self._lock:
            with open(self.path, "a") as f:
                # non-finite floats -> null: a diverging run's NaN val
                # gauge must not make the line unparseable to strict
                # consumers (graftcheck GC-JSONFINITE)
                f.write(json.dumps(jsonfinite(snap)) + "\n")
            self.writes += 1
        return snap

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.write_once()
            except Exception:  # noqa: BLE001 — the appender must outlive
                pass           # transient fs hiccups on a days-long run

    def start(self) -> "LiveMetricsWriter":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="cgnn-metrics-live"
            )
            self._thread.start()
        return self

    def stop(self, final_write: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if final_write:
            try:
                self.write_once()
            except Exception:  # noqa: BLE001 — best-effort at teardown
                pass
