"""Structured JSON logging: one line, one event, grep-able by trace id.

The serve/fleet/resilience layers log through injectable ``log_fn``
callables that default to ``print`` — fine for a laptop, useless for an
incident bundle holding five processes' interleaved stdouts. This
module is the one formatter they all route through when ``--log-json``
is on:

    {"t": 1754300000.12, "role": "replica", "pid": 4242,
     "trace_id": "flt-ab12-000003", "msg": "serve: batch failed ..."}

- :func:`bind_trace` sets the CURRENT trace id (a contextvar, so
  concurrent request threads don't stomp each other); the router binds
  it around ``dispatch`` and the replica HTTP handler binds it around
  ``predict``, so lines logged ON THOSE THREADS while a request is
  being worked carry its id. Scope honesty: logs from OTHER threads
  (a flush failure on the dispatch worker, the reload watcher) carry
  the id only where the message itself includes it — the
  flight-recorder request ring, keyed by trace id, is the surface that
  covers those.
- :func:`json_log_fn` returns a drop-in ``log_fn`` (same call shape as
  ``print``) for the existing injection points — no call site changes,
  just a different sink.
- :func:`setup_json_logging` additionally routes a stdlib
  ``logging.Logger`` through the same formatter for code that prefers
  the logging API.

Host-side and allocation-light; the JSON body rides the same
non-finite-safe serialization discipline as every other telemetry file
(graftcheck GC-JSONFINITE).
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import logging
import sys
import time
from typing import Callable, Iterator

from cgnn_tpu.observe.metrics_io import jsonfinite

# the current request's trace id, per execution context: bound by the
# layer that knows it (router dispatch, HTTP handler), read by every
# log line emitted underneath
_current_trace: contextvars.ContextVar = contextvars.ContextVar(
    "cgnn_trace_id", default="")


def current_trace_id() -> str:
    return _current_trace.get()


@contextlib.contextmanager
def bind_trace(trace_id: str) -> Iterator[None]:
    """Scope ``trace_id`` as the current trace for this context."""
    token = _current_trace.set(str(trace_id))
    try:
        yield
    finally:
        _current_trace.reset(token)


def format_record(msg: str, role: str, pid: int,
                  trace_id: str | None = None, **extra) -> str:
    rec = {
        "t": round(time.time(), 3),
        "role": role,
        "pid": pid,
        "trace_id": (current_trace_id() if trace_id is None
                     else str(trace_id)),
        "msg": str(msg),
    }
    rec.update(extra)
    try:
        return json.dumps(rec, allow_nan=False)
    except ValueError:
        return json.dumps(jsonfinite(rec))


def json_log_fn(role: str, stream=None) -> Callable:
    """A ``print``-compatible ``log_fn`` emitting one JSON line per
    call — the drop-in for every ``log_fn=print`` injection point in
    serve/fleet/resilience. Multiple positional args join like print's
    would; ``file=`` is accepted and ignored (the sink is fixed)."""
    import os

    pid = os.getpid()

    def log(*args, **kw) -> None:  # noqa: ARG001 — print-compatible
        out = stream or sys.stderr
        msg = " ".join(str(a) for a in args)
        out.write(format_record(msg, role, pid) + "\n")
        out.flush()

    return log


class JsonLineFormatter(logging.Formatter):
    """Stdlib-logging twin of :func:`json_log_fn` (same line schema)."""

    def __init__(self, role: str):
        super().__init__()
        self.role = role

    def format(self, record: logging.LogRecord) -> str:
        return format_record(record.getMessage(), self.role,
                             record.process or 0,
                             level=record.levelname.lower())


def setup_json_logging(role: str, stream=None,
                       level: int = logging.INFO) -> logging.Logger:
    """Route the ``cgnn_tpu`` stdlib logger through the JSON formatter;
    returns it. Idempotent: re-setup replaces the handler rather than
    stacking a second one (every line would otherwise print twice)."""
    logger = logging.getLogger("cgnn_tpu")
    for h in list(logger.handlers):
        if getattr(h, "_cgnn_json", False):
            logger.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JsonLineFormatter(role))
    handler._cgnn_json = True
    logger.addHandler(handler)
    logger.setLevel(level)
    logger.propagate = False
    return logger
