"""Cross-process trace context: span ids and the ``X-Trace-Parent`` wire.

The PR-6/PR-12 plane already shares ONE id across a request's attempts
(the trace id / idempotency key), but a joined fleet trace needs more:
each hop must know which upstream span it hangs under, or a hedged
request's two attempts render as four unrelated rows instead of one
tree. This module is the whole contract, deliberately tiny:

- a **span id** names one span instance within one process
  (``mint_span_id``: process-unique prefix + counter — cheap enough for
  the per-attempt hot path, no randomness per call);
- a **trace parent** is the pair ``"<trace_id>/<span_id>"`` carried to
  the next process as the ``X-Trace-Parent`` header (and the
  ``trace_parent`` body field for transports that cannot set headers).
  The receiver adopts the trace id and records ``parent=<span_id>`` on
  its own root span, which is all the joiner (observe/trace_join.py)
  needs to nest the replica's stage spans under the router's attempt.

Host-side bookkeeping only: nothing here touches jax, and a process
that never parses the header simply roots its own spans (the joiner
renders them as an orphan tree rather than guessing).
"""

from __future__ import annotations

import itertools
import os
import threading

TRACE_PARENT_HEADER = "X-Trace-Parent"

# process-unique span-id prefix + a lock-free counter: ids must be
# distinct across the processes whose rings one joiner merges, and the
# pid alone recycles — fold in 2 random bytes minted once per process
_SPAN_PREFIX = f"{os.getpid():x}-{os.urandom(2).hex()}"
_SPAN_SEQ = itertools.count(1)
_SEQ_LOCK = threading.Lock()


def mint_span_id(kind: str = "span") -> str:
    """A process-unique span id, e.g. ``att-1f03-9a2c-000007``."""
    with _SEQ_LOCK:
        n = next(_SPAN_SEQ)
    return f"{kind}-{_SPAN_PREFIX}-{n:06x}"


def format_parent(trace_id: str, span_id: str) -> str:
    """The ``X-Trace-Parent`` header value for a downstream hop."""
    return f"{trace_id}/{span_id}"


def parse_parent(value: str | None) -> tuple[str, str]:
    """Header/body value -> ``(trace_id, parent_span_id)``; a missing
    or malformed value parses to ``("", "")`` — the receiver then roots
    its own spans instead of inventing a parent."""
    if not value or not isinstance(value, str):
        return "", ""
    value = value.strip()
    # the span id never contains '/', so split from the RIGHT: trace
    # ids are client-controlled (X-Request-Id) and may contain '/'
    trace_id, sep, span_id = value.rpartition("/")
    if not sep or not trace_id or not span_id:
        return "", ""
    return trace_id[:128], span_id[:128]
