"""cgnn-tpu: a TPU-native crystal-graph neural network framework.

A ground-up JAX/XLA/Pallas re-design of the capability surface of the reference
PyTorch/CUDA stack ``CaoAo/CGNN`` (see SURVEY.md — note §0: the reference mount
was empty at survey time, so parity targets come from BASELINE.json and the
reconstructed architecture in SURVEY.md §1-§3).

Layout:
    cgnn_tpu.data      — CIF parsing, periodic neighbor lists, featurization,
                         graph containers, bucketed/padded batching.
    cgnn_tpu.models    — Flax CGCNN model (edge-gated CGConv over flat COO
                         edges via segment ops), heads.
    cgnn_tpu.ops       — segment ops + Pallas TPU kernels for the
                         gather-scatter hot loop.
    cgnn_tpu.parallel  — device mesh, data-parallel training over ICI
                         (shard_map + psum), edge-sharded message passing.
    cgnn_tpu.train     — training runtime: train state, normalizer,
                         checkpointing (orbax), metrics, loops.
"""

__version__ = "0.1.0"
