// Native periodic neighbor search — the host-side hot loop of the offline
// preprocessor (SURVEY.md §2 native table: "pymatgen/spglib periodic
// neighbor search" -> in-tree host kernel; §7 "hard parts" #2).
//
// Periodic CELL LIST in fractional space, O(n · density · r³) instead of
// the O(n² · images) brute force: each axis is split into M_k bins
// (M_k ≈ min(1/frac_range_k, ~cbrt(4n)) so bins stay populated); per center
// atom only bins within the fractional search range are scanned. Scanned
// bin indices may run past [0, M_k): the floor-division quotient IS the
// periodic image offset of the atoms in that bin, so small cells (bin span
// > one period) degrade gracefully into an image loop, matching the brute
// force exactly.
//
// Same semantics as cgnn_tpu/data/neighbors.py::neighbor_list (the numpy
// reference used in tests): fractional coords are wrapped into [0,1);
// self-pairs are excluded only in the home image. Emits flat COO sorted by
// (center, order of discovery) — the Python wrapper re-sorts by distance
// for knn anyway.
//
// C ABI only (ctypes binding, no pybind11 in this image). Returns the pair
// count, or -(needed_hint) when `cap` is too small so the caller can retry.

#include <cmath>
#include <cstdint>
#include <vector>

namespace {

// inverse of a row-major 3x3 matrix; returns false if singular
bool invert3(const double* m, double* inv) {
  const double a = m[0], b = m[1], c = m[2];
  const double d = m[3], e = m[4], f = m[5];
  const double g = m[6], h = m[7], i = m[8];
  const double det =
      a * (e * i - f * h) - b * (d * i - f * g) + c * (d * h - e * g);
  if (std::fabs(det) < 1e-300) return false;
  const double s = 1.0 / det;
  inv[0] = (e * i - f * h) * s;
  inv[1] = (c * h - b * i) * s;
  inv[2] = (b * f - c * e) * s;
  inv[3] = (f * g - d * i) * s;
  inv[4] = (a * i - c * g) * s;
  inv[5] = (c * d - a * f) * s;
  inv[6] = (d * h - e * g) * s;
  inv[7] = (b * g - a * h) * s;
  inv[8] = (a * e - b * d) * s;
  return true;
}

}  // namespace

extern "C" {

// lattice: [9] row-major (rows are lattice vectors, row-vector convention)
// frac:    [n*3] fractional coordinates (any range; wrapped internally)
// outputs: centers/neighbors [cap], dists [cap], offsets [cap*3]
// returns pair count, or -needed when cap is insufficient, -1 on bad input
long long cgnn_neighbor_search(const double* lattice, const double* frac,
                               long long n, double radius, long long cap,
                               int32_t* centers, int32_t* neighbors,
                               float* dists, int32_t* offsets) {
  if (n <= 0 || radius <= 0.0) return -1;
  double inv[9];
  if (!invert3(lattice, inv)) return -1;

  // fractional search range per axis: any |v| <= radius has
  // |frac_k| = |v . inv[:,k]| <= radius * ||inv column k||
  double frange[3];
  for (int k = 0; k < 3; ++k) {
    const double norm = std::sqrt(inv[k] * inv[k] + inv[k + 3] * inv[k + 3] +
                                  inv[k + 6] * inv[k + 6]);
    frange[k] = radius * norm;
  }

  // wrapped fractional + cartesian coordinates
  std::vector<double> w(static_cast<size_t>(n) * 3);
  std::vector<double> cart(static_cast<size_t>(n) * 3);
  for (long long i = 0; i < n; ++i) {
    for (int k = 0; k < 3; ++k) {
      double fk = std::fmod(frac[i * 3 + k], 1.0);
      if (fk < 0) fk += 1.0;
      if (fk >= 1.0) fk = 0.0;  // tiny negatives wrap to exactly 1.0
      w[i * 3 + k] = fk;
    }
    for (int k = 0; k < 3; ++k) {
      cart[i * 3 + k] = w[i * 3] * lattice[0 + k] +
                        w[i * 3 + 1] * lattice[3 + k] +
                        w[i * 3 + 2] * lattice[6 + k];
    }
  }

  // bins per axis: at most one bin per frange (so the scan stencil stays
  // +-R with R small), capped near cbrt(4n) so bins stay populated
  const int mcap =
      std::max(1, static_cast<int>(std::cbrt(4.0 * static_cast<double>(n))) + 1);
  int M[3], R[3];
  for (int k = 0; k < 3; ++k) {
    int m = frange[k] > 0 ? static_cast<int>(std::floor(1.0 / frange[k])) : mcap;
    M[k] = std::max(1, std::min(m, mcap));
    // stencil half-width: bin distance <= M*frange + 1 (floor rounding)
    R[k] = static_cast<int>(std::floor(frange[k] * M[k])) + 1;
  }
  const long long nbins =
      static_cast<long long>(M[0]) * M[1] * M[2];

  // linked-list cell bins over wrapped fracs
  std::vector<int32_t> head(static_cast<size_t>(nbins), -1);
  std::vector<int32_t> nxt(static_cast<size_t>(n), -1);
  std::vector<int32_t> bin_of(static_cast<size_t>(n) * 3);
  for (long long i = 0; i < n; ++i) {
    int b[3];
    for (int k = 0; k < 3; ++k) {
      b[k] = static_cast<int>(w[i * 3 + k] * M[k]);
      if (b[k] >= M[k]) b[k] = M[k] - 1;  // w == 1.0-eps rounding guard
      bin_of[i * 3 + k] = b[k];
    }
    const long long flat =
        (static_cast<long long>(b[0]) * M[1] + b[1]) * M[2] + b[2];
    nxt[i] = head[flat];
    head[flat] = static_cast<int32_t>(i);
  }

  // Euclidean floor division: quotient -> image offset, remainder -> bin
  const auto floordiv = [](int a, int m, int* rem) {
    int q = a / m, r = a % m;
    if (r < 0) {
      r += m;
      --q;
    }
    *rem = r;
    return q;
  };

  const double r2 = radius * radius;
  long long count = 0;
  for (long long i = 0; i < n; ++i) {
    const double xi = cart[i * 3], yi = cart[i * 3 + 1], zi = cart[i * 3 + 2];
    const int bi0 = bin_of[i * 3], bi1 = bin_of[i * 3 + 1],
              bi2 = bin_of[i * 3 + 2];
    for (int da = -R[0]; da <= R[0]; ++da) {
      int ba;
      const int ma = floordiv(bi0 + da, M[0], &ba);
      for (int db = -R[1]; db <= R[1]; ++db) {
        int bb;
        const int mb = floordiv(bi1 + db, M[1], &bb);
        for (int dc = -R[2]; dc <= R[2]; ++dc) {
          int bc;
          const int mc = floordiv(bi2 + dc, M[2], &bc);
          const double sx = ma * lattice[0] + mb * lattice[3] + mc * lattice[6];
          const double sy = ma * lattice[1] + mb * lattice[4] + mc * lattice[7];
          const double sz = ma * lattice[2] + mb * lattice[5] + mc * lattice[8];
          const bool home = ma == 0 && mb == 0 && mc == 0;
          const long long flat =
              (static_cast<long long>(ba) * M[1] + bb) * M[2] + bc;
          for (int32_t j = head[flat]; j >= 0; j = nxt[j]) {
            if (home && j == i) continue;
            const double dx = cart[j * 3] + sx - xi;
            const double dy = cart[j * 3 + 1] + sy - yi;
            const double dz = cart[j * 3 + 2] + sz - zi;
            const double d2 = dx * dx + dy * dy + dz * dz;
            if (d2 <= r2) {
              if (count < cap) {
                centers[count] = static_cast<int32_t>(i);
                neighbors[count] = j;
                dists[count] = static_cast<float>(std::sqrt(d2));
                offsets[count * 3] = ma;
                offsets[count * 3 + 1] = mb;
                offsets[count * 3 + 2] = mc;
              }
              ++count;
            }
          }
        }
      }
    }
  }
  if (count > cap) return -count;  // caller retries with `count` capacity
  return count;
}

}  // extern "C"
