// Native periodic neighbor search — the host-side hot loop of the offline
// preprocessor (SURVEY.md §2 native table: "pymatgen/spglib periodic
// neighbor search" -> in-tree host kernel; §7 "hard parts" #2).
//
// Same semantics as cgnn_tpu/data/neighbors.py::neighbor_list (the numpy
// reference used in tests): fractional coords are wrapped into [0,1); the
// image range per axis is ceil(radius / plane_spacing); self-pairs are
// excluded only in the home image. Emits flat COO sorted by (center, order
// of discovery) — the Python wrapper re-sorts by distance for knn anyway.
//
// C ABI only (ctypes binding, no pybind11 in this image). Returns the pair
// count, or -(needed_hint) when `cap` is too small so the caller can retry.

#include <cmath>
#include <cstdint>
#include <vector>

namespace {

// inverse of a row-major 3x3 matrix; returns false if singular
bool invert3(const double* m, double* inv) {
  const double a = m[0], b = m[1], c = m[2];
  const double d = m[3], e = m[4], f = m[5];
  const double g = m[6], h = m[7], i = m[8];
  const double det =
      a * (e * i - f * h) - b * (d * i - f * g) + c * (d * h - e * g);
  if (std::fabs(det) < 1e-300) return false;
  const double s = 1.0 / det;
  inv[0] = (e * i - f * h) * s;
  inv[1] = (c * h - b * i) * s;
  inv[2] = (b * f - c * e) * s;
  inv[3] = (f * g - d * i) * s;
  inv[4] = (a * i - c * g) * s;
  inv[5] = (c * d - a * f) * s;
  inv[6] = (d * h - e * g) * s;
  inv[7] = (b * g - a * h) * s;
  inv[8] = (a * e - b * d) * s;
  return true;
}

}  // namespace

extern "C" {

// lattice: [9] row-major (rows are lattice vectors, row-vector convention)
// frac:    [n*3] fractional coordinates (any range; wrapped internally)
// outputs: centers/neighbors [cap], dists [cap], offsets [cap*3]
// returns pair count, or -needed when cap is insufficient, -1 on bad input
long long cgnn_neighbor_search(const double* lattice, const double* frac,
                               long long n, double radius, long long cap,
                               int32_t* centers, int32_t* neighbors,
                               float* dists, int32_t* offsets) {
  if (n <= 0 || radius <= 0.0) return -1;
  double inv[9];
  if (!invert3(lattice, inv)) return -1;

  // images per axis: ceil(radius * ||inv column k|| - eps)
  int na[3];
  for (int k = 0; k < 3; ++k) {
    const double norm = std::sqrt(inv[k] * inv[k] + inv[k + 3] * inv[k + 3] +
                                  inv[k + 6] * inv[k + 6]);
    na[k] = static_cast<int>(std::ceil(radius * norm - 1e-12));
    if (na[k] < 0) na[k] = 0;
  }

  // wrapped cartesian coordinates
  std::vector<double> cart(static_cast<size_t>(n) * 3);
  for (long long i = 0; i < n; ++i) {
    double w[3];
    for (int k = 0; k < 3; ++k) {
      double fk = std::fmod(frac[i * 3 + k], 1.0);
      if (fk < 0) fk += 1.0;
      w[k] = fk;
    }
    for (int k = 0; k < 3; ++k) {
      cart[i * 3 + k] =
          w[0] * lattice[0 + k] + w[1] * lattice[3 + k] + w[2] * lattice[6 + k];
    }
  }

  // precompute image shift vectors
  struct Shift {
    double v[3];
    int img[3];
  };
  std::vector<Shift> shifts;
  shifts.reserve(static_cast<size_t>(2 * na[0] + 1) * (2 * na[1] + 1) *
                 (2 * na[2] + 1));
  for (int ia = -na[0]; ia <= na[0]; ++ia)
    for (int ib = -na[1]; ib <= na[1]; ++ib)
      for (int ic = -na[2]; ic <= na[2]; ++ic) {
        Shift s;
        for (int k = 0; k < 3; ++k)
          s.v[k] = ia * lattice[0 + k] + ib * lattice[3 + k] + ic * lattice[6 + k];
        s.img[0] = ia;
        s.img[1] = ib;
        s.img[2] = ic;
        shifts.push_back(s);
      }

  const double r2 = radius * radius;
  long long count = 0;
  for (long long i = 0; i < n; ++i) {
    const double xi = cart[i * 3], yi = cart[i * 3 + 1], zi = cart[i * 3 + 2];
    for (long long j = 0; j < n; ++j) {
      const double dx0 = cart[j * 3] - xi;
      const double dy0 = cart[j * 3 + 1] - yi;
      const double dz0 = cart[j * 3 + 2] - zi;
      for (const Shift& s : shifts) {
        const bool home = s.img[0] == 0 && s.img[1] == 0 && s.img[2] == 0;
        if (home && i == j) continue;
        const double dx = dx0 + s.v[0];
        const double dy = dy0 + s.v[1];
        const double dz = dz0 + s.v[2];
        const double d2 = dx * dx + dy * dy + dz * dz;
        if (d2 <= r2) {
          if (count < cap) {
            centers[count] = static_cast<int32_t>(i);
            neighbors[count] = static_cast<int32_t>(j);
            dists[count] = static_cast<float>(std::sqrt(d2));
            offsets[count * 3] = s.img[0];
            offsets[count * 3 + 1] = s.img[1];
            offsets[count * 3 + 2] = s.img[2];
          }
          ++count;
        }
      }
    }
  }
  if (count > cap) return -count;  // caller retries with `count` capacity
  return count;
}

}  // extern "C"
