"""Native (C++) host kernels with lazy in-tree builds (ctypes, no pybind11).

The TPU compute path is XLA/Pallas; these kernels cover the *host-side*
runtime hot loops the reference delegates to C-backed libraries
(SURVEY.md §2 native table). Each binding degrades gracefully: if no
compiler is available the numpy implementation is used instead.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "_cgnn_native.so")
_SRC = os.path.join(_DIR, "neighbors.cpp")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_failed = False


def _build() -> str | None:
    """Compile the shared library if missing/stale; None on failure."""
    try:
        if os.path.exists(_LIB_PATH) and os.path.getmtime(
            _LIB_PATH
        ) >= os.path.getmtime(_SRC):
            return _LIB_PATH
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            _SRC, "-o", _LIB_PATH + ".tmp",
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_LIB_PATH + ".tmp", _LIB_PATH)
        return _LIB_PATH
    except Exception:  # noqa: BLE001 — any failure means "no native backend"
        return None


def get_native_lib() -> ctypes.CDLL | None:
    """The loaded native library, building it on first use; None if absent."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _build()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        fn = lib.cgnn_neighbor_search
        fn.restype = ctypes.c_longlong
        fn.argtypes = [
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),  # lattice
            np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS"),  # frac
            ctypes.c_longlong,
            ctypes.c_double,
            ctypes.c_longlong,
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return get_native_lib() is not None


def neighbor_search_native(
    lattice: np.ndarray, frac: np.ndarray, radius: float
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray] | None:
    """(centers, neighbors, distances, offsets) or None if no native lib."""
    lib = get_native_lib()
    if lib is None:
        return None
    lattice = np.ascontiguousarray(lattice, np.float64)
    frac = np.ascontiguousarray(frac, np.float64)
    n = len(frac)
    cap = max(1024, n * 64)
    for _ in range(4):
        centers = np.empty(cap, np.int32)
        neighbors = np.empty(cap, np.int32)
        dists = np.empty(cap, np.float32)
        offsets = np.empty(cap * 3, np.int32)
        got = lib.cgnn_neighbor_search(
            lattice, frac, n, float(radius), cap, centers, neighbors, dists,
            offsets,
        )
        if got >= 0:
            return (
                centers[:got],
                neighbors[:got],
                dists[:got],
                offsets[: got * 3].reshape(-1, 3),
            )
        if got == -1:
            raise ValueError("native neighbor search: bad input (singular cell?)")
        cap = int(-got) + 16
    raise RuntimeError("native neighbor search: capacity negotiation failed")
