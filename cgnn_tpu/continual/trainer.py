"""The colocated continual trainer: journal -> fine-tune -> commit.

Consumes the label journal's replay set through the EXISTING data
machinery (``iter_labeled_graphs`` -> ``graph_from_json`` ->
``capacities_for``/``batch_iterator`` via ``train.loop.fit``), fine-
tunes from the newest committed checkpoint, and commits versioned
candidates into the fleet's shared checkpoint directory with the PR-2
``CheckpointManager`` protocol — the same manifest-as-commit-marker
saves the serving watchers poll. Nothing here promotes anything: a
commit only makes a CANDIDATE visible; the canary gate (canary.py)
decides whether the fleet ever serves it, and the reload-watcher gate
(serve/reload.py) holds every fleet replica until it does.

Commit cadence is doubly gated — at least ``min_new_labels`` newly
joined labels AND at least ``min_interval_s`` since the last commit —
so a label burst cannot thrash the checkpoint directory and a trickle
cannot starve the loop. Training is guard/divergence-protected exactly
like ``train.py``: the in-graph guard skips non-finite updates and a
``DivergenceMonitor`` rolls back to the last committed save with an LR
cut on sustained divergence.

This loop is the first workload training WHILE the same host serves
(the fleet smoke runs it beside N serving replicas); keep all its
bookkeeping under the racecheck-instrumented lock discipline.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.continual.journal import (
    JournalTail,
    LabelJournal,
    iter_labeled_graphs,
)
from cgnn_tpu.resilience import faultinject


class ContinualTrainer:
    """Fine-tune-on-served-traffic loop over a shared checkpoint dir.

    ``journal`` is an in-process :class:`LabelJournal` (tests, and the
    single-process serve path) OR ``journal_path`` names a JSONL stream
    another process appends (the router's journal in the fleet) which
    is tailed into a private replay journal — both go through the same
    exactly-once join logic.

    ``poll_once`` is the synchronous, testable unit: it drains new
    journal lines, checks the cadence gates, and runs at most one
    fine-tune round -> committed save name (or None). ``run`` loops it.
    """

    def __init__(self, ckpt_dir: str, *, journal: LabelJournal | None = None,
                 journal_path: str | None = None,
                 min_new_labels: int = 64, min_interval_s: float = 5.0,
                 batch_size: int = 16, epochs_per_round: int = 2,
                 lr: float = 0.01, max_replay: int = 4096,
                 max_rounds: int = 0, seed: int = 0,
                 clock: Callable[[], float] = time.monotonic,
                 log_fn: Callable | None = None):
        if (journal is None) == (journal_path is None):
            raise ValueError("pass exactly one of journal / journal_path")
        if min_new_labels <= 0:
            raise ValueError(
                f"min_new_labels must be > 0, got {min_new_labels}")
        self.ckpt_dir = ckpt_dir
        self._tail = None
        if journal is not None:
            self.journal = journal
        else:
            self.journal = LabelJournal(path=None, capacity=max_replay)
            self._tail = JournalTail(journal_path)
        self.min_new_labels = int(min_new_labels)
        self.min_interval_s = float(min_interval_s)
        self.batch_size = int(batch_size)
        self.epochs_per_round = int(epochs_per_round)
        self.lr = float(lr)
        self.max_replay = int(max_replay)
        self.max_rounds = int(max_rounds)  # 0 = unbounded
        self.seed = int(seed)
        self._clock = clock
        self._log = log_fn or (lambda m: print(m, file=sys.stderr))
        self._lock = racecheck.make_lock("continual.trainer")
        # train-side lazies (built on the first round, once the replay
        # set exists): manager, model, state, monitor, fixed capacities
        self._mgr = None
        self._state = None
        self._model_cfg = None
        self._meta = None
        self._monitor = None
        self._caps = None
        self._trained_seq = 0   # join_seq consumed by the last commit
        self._last_commit_t = float("-inf")
        self.rounds = 0
        self.commits: list[str] = []
        self.labels_trained = 0
        self.divergence_rollbacks = 0

    # ---- lazy train-side boot ----

    def _ensure_mgr(self):
        if self._mgr is None:
            from cgnn_tpu.train import CheckpointManager

            self._mgr = CheckpointManager(self.ckpt_dir)
        return self._mgr

    def _ensure_state(self, graphs):
        """Build model/state from the checkpoint's own meta and restore
        the newest committed save INTO it (params + optimizer +
        normalizer) — the fine-tune starting point."""
        if self._state is not None:
            return
        import jax
        import numpy as np

        from cgnn_tpu.config import DataConfig, ModelConfig, build_model
        from cgnn_tpu.data.graph import batch_iterator, capacities_for
        from cgnn_tpu.resilience import DivergenceMonitor
        from cgnn_tpu.train import (
            Normalizer,
            create_train_state,
            make_optimizer,
        )

        mgr = self._ensure_mgr()
        meta = mgr.read_meta("latest")
        if not meta.get("model"):
            raise RuntimeError(
                f"no committed checkpoint with model meta under "
                f"{self.ckpt_dir}; the continual trainer fine-tunes, it "
                "does not bootstrap"
            )
        self._model_cfg = ModelConfig.from_meta(meta["model"])
        data_cfg = DataConfig.from_meta(meta["data"])
        self._meta = {
            "model": meta["model"], "data": meta["data"],
            "task": meta.get("task", "regression"),
        }
        # fixed capacities for the whole loop: sized once with headroom
        # over the first replay set, so every round reuses the same
        # compiled step shapes instead of retracing per replay window
        nc, ec = capacities_for(graphs, self.batch_size,
                                dense_m=self._model_cfg.dense_m)
        self._caps = (nc, ec)
        example = next(batch_iterator(
            graphs[: self.batch_size], self.batch_size, nc, ec,
            dense_m=self._model_cfg.dense_m, in_cap=0))
        model = build_model(self._model_cfg, data_cfg,
                            self._meta["task"])
        state = create_train_state(
            model, example, make_optimizer(lr=self.lr),
            Normalizer.fit(np.stack([g.target for g in graphs])),
            rng=jax.random.key(self.seed),
        )
        state, _ = mgr.restore(state, "latest")
        self._state = state
        self._monitor = DivergenceMonitor(mgr, log_fn=self._log)

    # ---- the synchronous unit ----

    def poll_once(self, now: float | None = None) -> str | None:
        """Drain the journal; run one gated fine-tune round if due.
        Returns the committed save name, or None (gates closed)."""
        now = self._clock() if now is None else now
        if self._tail is not None:
            self._tail.follow_into(self.journal, on_error=self._log)
        with self._lock:
            rounds = self.rounds
        if self.max_rounds and rounds >= self.max_rounds:
            return None
        new_labels = self.journal.join_seq - self._trained_seq
        if new_labels < self.min_new_labels:
            return None
        if now - self._last_commit_t < self.min_interval_s:
            return None
        return self._round(now)

    def _round(self, now: float) -> str | None:
        import numpy as np

        from cgnn_tpu.train.loop import fit

        records = self.journal.labeled_records()
        if len(records) > self.max_replay:
            records = records[-self.max_replay:]
        graphs = [g for g, _rec in iter_labeled_graphs(records)]
        if len(graphs) < self.min_new_labels:
            # labels joined but payloads missing (accounting-only
            # records replay nothing) — hold
            return None
        with self._lock:
            round_idx = self.rounds + 1
        noise = faultinject.label_noise_for_round(round_idx)
        if noise is not None:
            # the injected REGRESSING candidate (fleet_smoke leg 8):
            # shift every label by a constant offset so even a short
            # fine-tune drags predictions off by ~the offset — the
            # committed version is measurably worse on TRUE labels and
            # the canary gate must catch it. (A zero-mean corruption
            # would NOT regress the model: a couple of epochs can't fit
            # unstructured noise, so the candidate would stay near its
            # init and pass the gate honestly.)
            self._log(
                f"continual: FAULT label_noise +{noise:g} shift on round "
                f"{round_idx} — committing a deliberately bad candidate"
            )
            import dataclasses as _dc

            graphs = [
                _dc.replace(
                    g,
                    target=np.asarray(g.target, np.float32)
                    + np.float32(noise),
                )
                for g in graphs
            ]
        self._ensure_state(graphs)
        # replay split: every 4th graph validates (the divergence
        # monitor and best-tracking need a val signal; the replay set
        # is served traffic, so any slice is distribution-faithful)
        train_g = [g for i, g in enumerate(graphs) if i % 4 != 0]
        val_g = [g for i, g in enumerate(graphs) if i % 4 == 0] or train_g
        nc, ec = self._caps
        self._log(
            f"continual: round {round_idx}: fine-tuning on "
            f"{len(train_g)} replayed labels (val {len(val_g)}, "
            f"{self.journal.join_seq - self._trained_seq} new)"
        )
        before = self._monitor.rollbacks if self._monitor else 0
        state, result = fit(
            self._state, train_g, val_g,
            epochs=self.epochs_per_round,
            batch_size=min(self.batch_size, max(1, len(train_g))),
            node_cap=nc, edge_cap=ec,
            dense_m=self._model_cfg.dense_m,
            print_freq=0, log_fn=self._log,
            seed=self.seed + round_idx,
            guard=True, monitor=self._monitor,
        )
        self._state = state
        if self._monitor is not None:
            with self._lock:
                self.divergence_rollbacks += (
                    self._monitor.rollbacks - before)
        mgr = self._ensure_mgr()
        epoch = 0
        try:
            epoch = int(mgr.read_meta("latest").get("epoch", 0))
        except (TypeError, ValueError):
            pass
        mgr.save(state, dict(
            self._meta, epoch=epoch + 1, continual_round=round_idx,
            replay_labels=len(graphs),
            val_best=float(result.get("best", float("nan"))),
        ))
        mgr.wait()
        name = mgr.newest_committed()
        with self._lock:
            self.rounds = round_idx
            self.commits.append(name)
            self.labels_trained += len(graphs)
        self._trained_seq = self.journal.join_seq
        self._last_commit_t = now
        self._log(f"continual: round {round_idx} committed {name}")
        return name

    # ---- the loop ----

    def run(self, poll_interval_s: float = 1.0,
            stop: threading.Event | None = None) -> None:
        stop = stop or threading.Event()
        while not stop.wait(poll_interval_s):
            racecheck.heartbeat()
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — a failed round must
                # not kill the loop; the journal keeps growing and the
                # next round retries from the restored state
                self._log(f"continual: round failed (will retry): {e!r}")
            with self._lock:
                rounds = self.rounds
            if self.max_rounds and rounds >= self.max_rounds:
                return

    def close(self) -> None:
        if self._tail is not None:
            self._tail.close()
        if self._mgr is not None:
            self._mgr.close()
            self._mgr = None

    def stats(self) -> dict:
        with self._lock:
            return {
                "rounds": self.rounds,
                "commits": list(self.commits),
                "labels_trained": self.labels_trained,
                "divergence_rollbacks": self.divergence_rollbacks,
                "journal": self.journal.stats(),
            }
