"""Shadow evaluation + the canary promotion gate (ISSUE 18).

The trainer commits CANDIDATE versions into the fleet's shared
checkpoint directory; nothing may reach the fleet untested. The plane
here has two halves:

- :class:`CanaryGate` — the PURE decision core, in the
  ``AutoscalePolicy.poll(now, signals)`` idiom: frozen config, all
  state mutated only inside ``poll``, injectable clock and samples, so
  every promote/hold/rollback path is a deterministic unit test. The
  rules are declarative: a candidate promotes when its shadow MAE is
  within ``max_mae_ratio`` of the live fleet's over at least
  ``min_samples`` labeled mirrors AND its shadow p99 fits the budget;
  it rolls back when the MAE ratio crosses ``rollback_mae_ratio``, the
  latency budget breaks, or the observation window expires without a
  verdict (undecided = not promotable — the safe default).
- :class:`CanaryController` — the runtime driving the loop against a
  fleet adapter (the :class:`~cgnn_tpu.fleet.router.FleetRouter` in
  production, a fake in tests): watch for new committed candidates,
  pin ONE canary replica to each (the replica leaves the routing
  rotation but stays addressable), mirror a configurable fraction of
  labeled live traffic to it — the shadow answer NEVER counts toward
  any client response — and turn the gate's verdict into a fleet-wide
  rolling promotion or a rollback whose flight-recorder bundle names
  the regressing version.

Per-version rolling MAE and shadow latency accumulate in the PR-17
mergeable-histogram plane (``fleet_label_mae_hist`` /
``fleet_shadow_latency_ms_hist``, labeled by ``param_version``), so
shadow-vs-live error is scrapeable from ``/metrics``, not loop-internal.
"""

from __future__ import annotations

import dataclasses
import sys
import threading
import time
from typing import Callable

from cgnn_tpu.analysis import racecheck
from cgnn_tpu.observe.hist import (
    LATENCY_MS_BOUNDS,
    MAE_BOUNDS,
    Histogram,
    format_labels,
)


@dataclasses.dataclass(frozen=True)
class GateConfig:
    """Declarative promotion gate. Ratios are candidate/baseline MAE."""

    min_samples: int = 50           # labeled shadow mirrors before verdict
    min_baseline: int = 50          # labeled LIVE answers before verdict
    max_mae_ratio: float = 1.05     # <= this -> promote
    rollback_mae_ratio: float = 1.25  # >= this -> rollback (mae)
    p99_budget_ms: float = 2000.0   # shadow p99 above -> rollback (latency)
    min_window_s: float = 2.0       # never decide faster than this
    max_window_s: float = 300.0     # undecided past this -> rollback

    def __post_init__(self):
        if self.max_mae_ratio >= self.rollback_mae_ratio:
            raise ValueError(
                f"max_mae_ratio ({self.max_mae_ratio}) must be < "
                f"rollback_mae_ratio ({self.rollback_mae_ratio}) — an "
                "overlapping band would promote and roll back the same "
                "candidate"
            )
        if self.min_samples <= 0 or self.min_window_s < 0:
            raise ValueError("min_samples must be > 0, min_window_s >= 0")
        if self.max_window_s <= self.min_window_s:
            raise ValueError(
                f"max_window_s ({self.max_window_s}) must exceed "
                f"min_window_s ({self.min_window_s})"
            )


@dataclasses.dataclass(frozen=True)
class GateStats:
    """One observation snapshot fed to ``poll`` (all window-scoped:
    accumulated since ``begin``, not lifetime)."""

    candidate_count: int = 0
    candidate_mae: float = float("nan")
    candidate_p99_ms: float = float("nan")
    baseline_count: int = 0
    baseline_mae: float = float("nan")


@dataclasses.dataclass(frozen=True)
class GateDecision:
    action: str       # 'promote' | 'rollback'
    version: str
    reason: str       # 'mae' | 'latency' | 'window_expired' | 'ok'
    mae_ratio: float
    stats: GateStats


class CanaryGate:
    """Pure verdict state machine for ONE candidate at a time.

    ``begin(version, now)`` opens an evaluation window; ``poll(now,
    stats)`` returns a :class:`GateDecision` exactly once per window
    (then deactivates) or None to hold. No clocks, no threads, no IO —
    callers serialize access.
    """

    def __init__(self, config: GateConfig | None = None):
        self.config = config or GateConfig()
        self._version: str | None = None
        self._started: float = 0.0
        self.decisions: list[GateDecision] = []

    @property
    def active(self) -> str | None:
        """The candidate under evaluation (None between windows)."""
        return self._version

    def begin(self, version: str, now: float) -> None:
        if self._version is not None:
            raise RuntimeError(
                f"gate already evaluating {self._version}; one candidate "
                "at a time"
            )
        self._version = version
        self._started = float(now)

    def _decide(self, action: str, reason: str, ratio: float,
                stats: GateStats) -> GateDecision:
        d = GateDecision(action=action, version=self._version,
                         reason=reason, mae_ratio=ratio, stats=stats)
        self.decisions.append(d)
        self._version = None
        return d

    def poll(self, now: float, stats: GateStats) -> GateDecision | None:
        if self._version is None:
            return None
        cfg = self.config
        elapsed = now - self._started
        expired = elapsed >= cfg.max_window_s
        have_samples = (stats.candidate_count >= cfg.min_samples
                        and stats.baseline_count >= cfg.min_baseline)
        ratio = float("nan")
        if (stats.baseline_mae == stats.baseline_mae
                and stats.candidate_mae == stats.candidate_mae):
            ratio = stats.candidate_mae / max(stats.baseline_mae, 1e-12)
        if have_samples and elapsed >= cfg.min_window_s:
            # latency first: a candidate that answers correctly but
            # blows the p99 budget still cannot take the fleet
            if (stats.candidate_p99_ms == stats.candidate_p99_ms
                    and stats.candidate_p99_ms > cfg.p99_budget_ms):
                return self._decide("rollback", "latency", ratio, stats)
            if ratio == ratio and ratio >= cfg.rollback_mae_ratio:
                return self._decide("rollback", "mae", ratio, stats)
            if ratio == ratio and ratio <= cfg.max_mae_ratio:
                return self._decide("promote", "ok", ratio, stats)
            # inconclusive band: keep observing until the window expires
        if expired:
            # undecided is NOT promotable: starved of samples or parked
            # in the inconclusive band, the fleet keeps what it has
            return self._decide("rollback", "window_expired", ratio, stats)
        return None

    def state(self) -> dict:
        return {
            "active": self._version,
            "started": self._started if self._version else None,
            "decisions": len(self.decisions),
        }


class CanaryController:
    """Drives the closed loop against a fleet adapter.

    ``fleet`` is duck-typed (the FleetRouter grows these in ISSUE 18;
    tests pass a fake):

    - ``fleet_version() -> str | None`` — the version the routed fleet
      serves (the promotion baseline);
    - ``begin_canary(version) -> rid | None`` — take one ready replica
      out of rotation and pin its watcher to ``version`` (None = no
      replica to spare this tick; retried);
    - ``canary_version(rid) -> str | None`` — what the pinned replica
      serves right now (the convergence probe);
    - ``shadow_predict(rid, payload, timeout_s) -> (prediction,
      latency_ms)`` — a mirrored request straight to the canary,
      bypassing routing; raises on failure;
    - ``promote(rid, version)`` — broadcast the gate fleet-wide (every
      watcher's ceiling rises to ``version``; the rolling-promotion
      path) and return the canary to rotation;
    - ``abort_canary(rid, to_version)`` — pin the canary back to the
      fleet version (rollback); controller calls ``end_canary(rid)``
      once converged;
    - ``end_canary(rid)`` — clear the pin and return the replica to
      rotation.

    ``newest_fn`` surfaces trainer commits (``CheckpointManager.
    newest_committed`` on the shared directory). ``journal`` supplies
    the labeled live traffic; every newly joined record contributes its
    live |prediction - label| to the per-version MAE plane, and — while
    a candidate is evaluating — a ``mirror_fraction`` subset is
    replayed to the canary for the shadow sample.
    """

    def __init__(self, *, gate: CanaryGate, journal, fleet,
                 newest_fn: Callable[[], str | None],
                 mirror_fraction: float = 1.0,
                 shadow_timeout_s: float = 15.0,
                 flightrec=None,
                 tick_interval_s: float = 0.5,
                 clock: Callable[[], float] = time.monotonic,
                 log_fn: Callable | None = None):
        if not 0.0 < mirror_fraction <= 1.0:
            raise ValueError(
                f"mirror_fraction must be in (0, 1], got {mirror_fraction}"
            )
        self.gate = gate
        self.journal = journal
        self.fleet = fleet
        self._newest = newest_fn
        self.mirror_fraction = float(mirror_fraction)
        self.shadow_timeout_s = float(shadow_timeout_s)
        self.flightrec = flightrec
        self.tick_interval_s = float(tick_interval_s)
        self._clock = clock
        self._log = log_fn or (lambda m: print(m, file=sys.stderr))
        self._lock = racecheck.make_lock("continual.canary")
        # state machine: idle -> pinning -> evaluating -> (promote |
        # rollback: unpinning) -> idle. All mutated on the tick path,
        # read by /stats scrapers — hence the lock.
        self._state = "idle"
        self._candidate: str | None = None
        self._rid = None
        self._consumed_seq = 0
        self._mirror_acc = 0.0
        self._pin_deadline = 0.0
        # lifetime per-version metric plane (scrapeable)
        self._mae_hists: dict[str, Histogram] = {}
        self._shadow_lat_hists: dict[str, Histogram] = {}
        # window accumulators (reset per candidate)
        self._win_cand: Histogram | None = None
        self._win_lat: Histogram | None = None
        self._win_base_count = 0
        self._win_base_sum = 0.0
        self.shadow_sent = 0
        self.shadow_errors = 0
        self.live_observed = 0
        self.rejected: set[str] = set()
        self.events: list[dict] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- metric plane ----

    def _observe_live(self, version: str, abs_err: float) -> None:
        with self._lock:
            h = self._mae_hists.get(version)
            if h is None:
                h = self._mae_hists[version] = Histogram(MAE_BOUNDS)
        h.observe(abs_err)

    def _observe_shadow(self, version: str, abs_err: float,
                        latency_ms: float) -> None:
        with self._lock:
            h = self._mae_hists.get(version)
            if h is None:
                h = self._mae_hists[version] = Histogram(MAE_BOUNDS)
            lh = self._shadow_lat_hists.get(version)
            if lh is None:
                lh = self._shadow_lat_hists[version] = Histogram(
                    LATENCY_MS_BOUNDS)
        h.observe(abs_err)
        lh.observe(latency_ms)

    def metrics_histograms(self) -> dict:
        """``param_version``-labeled snapshot map for the registry
        provider (export.py renders the labeled keys; /metrics/fleet
        merges them label-set by label-set)."""
        with self._lock:
            mae = dict(self._mae_hists)
            lat = dict(self._shadow_lat_hists)
        out = {}
        for v, h in mae.items():
            key = format_labels({"param_version": v})
            out[f"fleet_label_mae_hist{key}"] = h.snapshot()
        for v, h in lat.items():
            key = format_labels({"param_version": v})
            out[f"fleet_shadow_latency_ms_hist{key}"] = h.snapshot()
        return out

    # ---- the tick (synchronous, testable) ----

    def tick(self, now: float | None = None) -> None:
        now = self._clock() if now is None else now
        self._drain_labels(now)
        with self._lock:
            state = self._state
        if state == "idle":
            self._maybe_begin(now)
        elif state == "pinning":
            self._check_pinned(now)
        elif state == "evaluating":
            self._poll_gate(now)
        elif state == "unpinning":
            self._check_unpinned(now)

    def _drain_labels(self, now: float) -> None:
        records = self.journal.labeled_records(after_seq=self._consumed_seq)
        if not records:
            return
        self._consumed_seq = records[-1]["join_seq"]
        with self._lock:
            evaluating = self._state == "evaluating"
            rid, cand = self._rid, self._candidate
        for rec in records:
            pred, label = rec.get("prediction"), rec.get("label")
            version = rec.get("param_version") or "unknown"
            if pred is None or label is None:
                continue
            err = abs(float(pred) - float(label))
            self._observe_live(version, err)
            with self._lock:
                self.live_observed += 1
                if evaluating:
                    self._win_base_count += 1
                    self._win_base_sum += err
            if evaluating:
                self._maybe_mirror(rid, cand, rec)

    def _maybe_mirror(self, rid, cand: str, rec: dict) -> None:
        payload = rec.get("payload")
        if not payload:
            return
        # deterministic fraction sampling: an accumulator, not an RNG —
        # exactly mirror_fraction of eligible records mirror, in order
        with self._lock:
            self._mirror_acc += self.mirror_fraction
            if self._mirror_acc < 1.0:
                return
            self._mirror_acc -= 1.0
        try:
            pred, latency_ms = self.fleet.shadow_predict(
                rid, payload, self.shadow_timeout_s)
        except Exception as e:  # noqa: BLE001 — a failed shadow is a
            # metric, never an outage: the client was answered long ago
            with self._lock:
                self.shadow_errors += 1
            self._log(f"canary: shadow predict failed: {e!r}")
            return
        with self._lock:
            self.shadow_sent += 1
        err = abs(float(pred) - float(rec["label"]))
        self._observe_shadow(cand, err, latency_ms)
        with self._lock:
            if self._win_cand is not None:
                self._win_cand.observe(err)
                self._win_lat.observe(latency_ms)

    def _maybe_begin(self, now: float) -> None:
        newest = self._newest()
        fleet_v = self.fleet.fleet_version()
        if (newest is None or fleet_v is None or newest == fleet_v
                or newest in self.rejected or newest <= fleet_v):
            return
        rid = self.fleet.begin_canary(newest)
        if rid is None:
            return  # no spare replica this tick; retry
        self._log(f"canary: evaluating candidate {newest} on replica "
                  f"{rid} (fleet at {fleet_v})")
        self._pin_deadline = now + self.gate.config.max_window_s
        with self._lock:
            self._mirror_acc = 0.0
            self._state = "pinning"
            self._candidate = newest
            self._rid = rid
            self._win_cand = Histogram(MAE_BOUNDS)
            self._win_lat = Histogram(LATENCY_MS_BOUNDS)
            self._win_base_count = 0
            self._win_base_sum = 0.0
        self._event("canary_begin", version=newest, rid=rid)

    def _check_pinned(self, now: float) -> None:
        with self._lock:
            rid, cand = self._rid, self._candidate
        if self.fleet.canary_version(rid) == cand:
            self.gate.begin(cand, now)
            with self._lock:
                self._state = "evaluating"
            self._event("canary_pinned", version=cand, rid=rid)
        elif now >= self._pin_deadline:
            # the pin never converged (corrupt save, dead replica):
            # treat as a rollback — the candidate is not promotable
            self._log(f"canary: pin to {cand} never converged; rejecting")
            self._begin_rollback(rid, cand, "pin_timeout", None)

    def _poll_gate(self, now: float) -> None:
        with self._lock:
            rid, cand = self._rid, self._candidate
            cw, lw = self._win_cand, self._win_lat
            bc, bs = self._win_base_count, self._win_base_sum
        cs = cw.snapshot()
        stats = GateStats(
            candidate_count=int(cs["count"]),
            candidate_mae=(cs["sum"] / cs["count"] if cs["count"]
                           else float("nan")),
            candidate_p99_ms=lw.quantile(0.99),
            baseline_count=bc,
            baseline_mae=(bs / bc if bc else float("nan")),
        )
        decision = self.gate.poll(now, stats)
        if decision is None:
            return
        if decision.action == "promote":
            self._log(
                f"canary: PROMOTING {cand} fleet-wide (shadow MAE "
                f"{stats.candidate_mae:.4g} vs live "
                f"{stats.baseline_mae:.4g}, ratio "
                f"{decision.mae_ratio:.3f}, {stats.candidate_count} "
                "shadow samples)"
            )
            self.fleet.promote(rid, cand)
            with self._lock:
                self._state = "idle"
                self._candidate = None
                self._rid = None
            self._event("promoted", version=cand, rid=rid,
                        mae_ratio=decision.mae_ratio,
                        shadow_samples=stats.candidate_count)
        else:
            self._begin_rollback(rid, cand, decision.reason, decision)

    def _begin_rollback(self, rid, version: str, reason: str,
                        decision: GateDecision | None) -> None:
        fleet_v = self.fleet.fleet_version()
        ratio = decision.mae_ratio if decision is not None else float("nan")
        self._log(
            f"canary: ROLLING BACK {version} (reason={reason}, mae "
            f"ratio {ratio:.3f}); fleet stays on {fleet_v}"
        )
        self.rejected.add(version)
        # the accountability pin: every rollback dumps a bundle NAMING
        # the regressing version — in the reason (the bundle dir name)
        # and in the manifest detail
        if self.flightrec is not None:
            self.flightrec.trigger(
                f"canary_rollback_{version}",
                detail=(f"candidate {version} rejected: {reason}, "
                        f"mae_ratio={ratio:.4g}, fleet stays {fleet_v}"),
            )
        self.fleet.abort_canary(rid, fleet_v)
        self._pin_deadline = self._clock() + self.gate.config.max_window_s
        with self._lock:
            self._state = "unpinning"
        self._event("rolled_back", version=version, rid=rid,
                    reason=reason, mae_ratio=ratio)

    def _check_unpinned(self, now: float) -> None:
        with self._lock:
            rid = self._rid
        fleet_v = self.fleet.fleet_version()
        if self.fleet.canary_version(rid) == fleet_v:
            self.fleet.end_canary(rid)
            with self._lock:
                self._state = "idle"
                self._candidate = None
                self._rid = None
            self._event("canary_returned", rid=rid, version=fleet_v)
        elif now >= self._pin_deadline:
            # a canary that cannot even restore the fleet version is a
            # sick replica: return it to the router's remediation plane
            # rather than holding the loop hostage
            self._log(f"canary: replica {rid} failed to unpin; releasing")
            self.fleet.end_canary(rid)
            with self._lock:
                self._state = "idle"
                self._candidate = None
                self._rid = None
            self._event("canary_release_forced", rid=rid)

    def _event(self, kind: str, **fields) -> None:
        with self._lock:
            self.events.append(dict(kind=kind, **fields))

    # ---- lifecycle ----

    def start(self) -> "CanaryController":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="fleet-canary"
            )
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.tick_interval_s):
            racecheck.heartbeat()
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the loop must
                # survive a flaky canary; next tick retries
                self._log(f"canary: tick error (will retry): {e!r}")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def stats(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "candidate": self._candidate,
                "canary_rid": self._rid,
                "shadow_sent": self.shadow_sent,
                "shadow_errors": self.shadow_errors,
                "live_observed": self.live_observed,
                "rejected": sorted(self.rejected),
                "gate": self.gate.state(),
                "events": [dict(e) for e in self.events],
            }
