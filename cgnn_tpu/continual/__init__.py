"""Closed-loop continual learning (ISSUE 18): serve -> label -> train
-> shadow-evaluate -> canary-promote, with zero serving downtime.

- :mod:`journal` — the label journal: served requests append
  fingerprint/trace-keyed records; late-arriving ground truth joins
  exactly once, producing the labeled replay set the trainer consumes.
- :mod:`trainer` — the colocated fine-tune loop: journal -> existing
  loader/pack machinery -> guarded train steps -> versioned commits
  into the fleet's shared checkpoint directory on a cadence.
- :mod:`canary` — the shadow-evaluation plane: the pure promotion gate
  (injectable clock, AutoscalePolicy idiom) plus the controller that
  pins one canary replica per candidate, mirrors labeled traffic to it,
  and promotes fleet-wide or rolls back with a flight-recorder bundle
  naming the regressing version.
"""

from cgnn_tpu.continual.canary import (  # noqa: F401
    CanaryController,
    CanaryGate,
    GateConfig,
    GateDecision,
    GateStats,
)
from cgnn_tpu.continual.journal import (  # noqa: F401
    JournalTail,
    LabelJournal,
)
from cgnn_tpu.continual.trainer import ContinualTrainer  # noqa: F401
